"""Fused conv+BN+ReLU Pallas kernels — the custom conv suite the
ResNet-50 MFU plateau calls for (ROADMAP item 5, DESIGN_DECISIONS r17).

BENCH_r05 and the `conv_c2_*`/`conv_c5_*` sweep in bench_ops.py put
numbers on the problem: the stage-1/2 ResNet shapes run at 24-76
TFLOP/s through `lax.conv_general_dilated` against 184 TFLOP/s for a
same-FLOP matmul, and the r5 fusion probe showed even perfect XLA
conv+BN fusion caps at ~0.20 MFU — the early stages are ~90%
bandwidth-bound on activation re-reads between conv, BN and ReLU.
These kernels attack exactly that traffic: ONE HBM read of the
activation, the conv as explicit MXU matmuls with fp32 accumulation,
and the BatchNorm scale/shift + ReLU applied in-register before the
single HBM write-back.

Two kernel families cover the ResNet bottleneck sweep:

- 1x1 convs (`_conv1x1_kernel`): a 1x1 conv IS a matmul — the input is
  viewed as `[N*Ho*Wo, Cin]`, tiled over rows, and each grid program
  runs one `[TM, Cin] x [Cin, Cout]` MXU pass with the epilogue fused.
  This alone targets `conv_c2_1x1_64_256` and `conv_c5_1x1_512_2048`,
  the worst matmul-gap rows of the sweep. Stride-2 1x1 (the downsample
  path) pre-slices the input — exact, and the slice is 1/4 the read.
- 3x3 stride-1/2 convs (`_conv3x3_kernel`): implicit GEMM. One grid
  program per image streams output-row slabs of the (pre-padded) input
  HBM->VMEM through a double-buffered scratch — the next slab's DMA in
  flight behind the current slab's compute, halo rows riding inside
  each slab — and computes the conv as 9 shifted `[TH*Wo, Cin] x
  [Cin, Cout]` tap matmuls accumulated in fp32
  (`preferred_element_type`; tpu-verify TPU103 pins it), epilogue
  fused, one output write.

Padding is materialized once with `jnp.pad` before the 3x3 kernel (a
single fused memset+copy) so every slab DMA is in-bounds with a static
shape; the win this suite claims is eliminating the BN/ReLU activation
round-trips, which dwarf the one-off pad. Both `"SAME"` (the bench
sweep's convention — asymmetric at stride 2) and paddle's explicit
symmetric padding (the ResNet blocks' convention) resolve to the same
VALID-over-padded-input geometry, so one kernel serves both.

Backend seam — the `ops/paged_attention.py` pattern verbatim:
`resolve_conv_backend` maps `auto`/`dense`/`pallas` (env override
`PADDLE_CONV_BACKEND` wins, resolved ONCE at block construction by
`nn/fused.py`); `auto` picks the fused kernel only on TPU at supported
shapes; explicit `pallas` off-TPU runs the interpreter (the CPU CI
path, tested numerically against the dense composition like the
paged-attention kernels); unsupported shapes — the 7x7/s2 stem,
grouped/dilated convs, ragged channel counts — fall back to `dense`
CLEANLY whatever was requested, and `CONV_PATH_STATS` records every
dispatch so a silent fallback is impossible (flash_attention
PATH_STATS precedent).

The suite covers BOTH halves of training. Forward in train mode runs
the same kernels with the BN affine epilogue replaced by a fused
stats epilogue (`_conv1x1_train_kernel`/`_conv3x3_train_kernel`
accumulate per-channel f32 sum/sum-of-squares across the sequential
grid), and the backward runs fused too: **dInput** as a
transposed-filter implicit GEMM (1x1: row-tiled MXU matmuls over the
transposed weight with the whole ReLU+BN backward chain folded
in-register; 3x3: the mirrored shifted-tap walk — the SAME
`_conv3x3_call` machinery over the stride-dilated dOut and the
flipped/transposed filter, halo rows in-slab) and **dWeight** as a
slab-streamed accumulation over the same double-buffered HBM->VMEM
walk (`_conv1x1_dw_kernel`/`_conv3x3_dw_kernel`), every matmul
accumulating fp32 via `preferred_element_type`. `nn/fused.py` wires
the pair through ONE `jax.custom_vjp` per static config
(`fused_conv_bn_relu_train`), so a pallas-resolved `ConvBNReLU`
trains fused while the dense composition remains the fallback and
the bit-exactness foil — unsupported geometries resolve dense
cleanly through `resolve_conv_backend`/`conv_train_geometry_tileable`
and `CONV_PATH_STATS` counts train-mode dispatches separately, never
a silent divergence. See DESIGN_DECISIONS r19 for the BN-stats
placement policy (stats-in-epilogue forward, two-pass backward with
dOut-chain materialized once for the 3x3 family).

TraceContracts for all four kernel families (fwd + bwd) are declared
here, colocated with the builders, and `harvest_programs()` hands
tpu-verify tiny-but-real jitted instances so their lowering is gated
like every other compiled program.
"""
from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp

from paddle_tpu.analysis.trace.contracts import TraceContract, \
    register_contract

__all__ = ["fused_conv_bn_relu", "fused_conv_bn_relu_train",
           "conv_bn_relu_reference", "conv_bn_relu_train_reference",
           "resolve_conv_backend", "conv_shapes_supported",
           "conv_geometry_tileable", "conv_train_geometry_tileable",
           "normalize_conv_padding",
           "CONV_BACKENDS", "CONV_PATH_STATS",
           "reset_conv_path_stats", "harvest_programs",
           "CONV_HARVEST_SHAPES", "CONV_BWD_HARVEST_SHAPES"]

CONV_BACKENDS = ("auto", "dense", "pallas")

# which backend a fused-conv dispatch actually ran, incremented per
# call (per TRACE under jit), with TRAIN-mode dispatches counted
# separately from eval so a training fallback is observable on its
# own. Tests read it to prove the requested kernel engaged / the stem
# fell back — never a silent fallback.
CONV_PATH_STATS = {"dense": 0, "pallas": 0,
                   "dense_train": 0, "pallas_train": 0}


def reset_conv_path_stats():
    for k in CONV_PATH_STATS:
        CONV_PATH_STATS[k] = 0


def _on_tpu():
    try:
        return jax.devices()[0].platform == "tpu" or \
            jax.default_backend() in ("tpu", "axon")
    except Exception:
        return False


def _pair(v=1):
    if isinstance(v, (list, tuple)):
        return tuple(int(x) for x in v)
    return (int(v),) * 2


def normalize_conv_padding(padding=0, kernel=3, stride=1, in_hw=None):
    """Paddle/lax padding spec -> ((top, bottom), (left, right)).

    Accepts an int, a 2-int per-dim pad, 2 (lo, hi) pairs, or the
    "SAME"/"VALID" strings. "SAME" needs `in_hw` because lax pads it
    asymmetrically at stride > 1 (total = (ceil(d/s)-1)*s + k - d, lo =
    total//2) — the bench sweep's convention, distinct from the ResNet
    blocks' symmetric padding=1."""
    kh, kw = _pair(kernel)
    sh, sw = _pair(stride)
    if isinstance(padding, str):
        p = padding.upper()
        if p == "VALID":
            return ((0, 0), (0, 0))
        if p == "SAME":
            if in_hw is None:
                raise ValueError("SAME padding needs the input H/W")
            out = []
            for d, k, s in zip(in_hw, (kh, kw), (sh, sw)):
                total = max((-(-d // s) - 1) * s + k - d, 0)
                out.append((total // 2, total - total // 2))
            return tuple(out)
        raise ValueError(f"unsupported conv padding {padding!r}")
    if isinstance(padding, (list, tuple)):
        if len(padding) == 2 and all(
                isinstance(p, (list, tuple)) for p in padding):
            return tuple((int(lo), int(hi)) for lo, hi in padding)
        if len(padding) == 2:
            return tuple((int(p), int(p)) for p in padding)
        if len(padding) == 4:
            return ((int(padding[0]), int(padding[1])),
                    (int(padding[2]), int(padding[3])))
        raise ValueError(f"unsupported conv padding {padding!r}")
    p = int(padding)
    return ((p, p), (p, p))


def conv_shapes_supported(kernel=3, stride=1, in_channels=8,
                          out_channels=8, dilation=1, groups=1,
                          padding=0):
    """Static-shape gate for the fused kernels: k in {1, 3} square,
    stride in {1, 2} square, no dilation/groups, channel counts in
    multiples of 8 (sublane-friendly tiles), and zero padding for the
    1x1 family (a padded 1x1 conv is not a matmul). Everything else —
    the 7x7/s2 stem above all — runs the dense composition; callers
    resolve ONCE so the answer never flips mid-serving."""
    kh, kw = _pair(kernel)
    sh, sw = _pair(stride)
    dh, dw = _pair(dilation)
    if (kh, kw) not in ((1, 1), (3, 3)) or kh != kw:
        return False
    if sh != sw or sh not in (1, 2):
        return False
    if dh != 1 or dw != 1 or groups != 1:
        return False
    if in_channels % 8 or out_channels % 8:
        return False
    if (kh, kw) == (1, 1) and not isinstance(padding, str):
        pads = normalize_conv_padding(padding, kernel, stride,
                                      in_hw=(8, 8))
        if any(p != (0, 0) for p in pads):
            return False
    return True


def conv_geometry_tileable(kernel=3, stride=1, padding=0, in_hw=None,
                           in_channels=8):
    """Per-call geometry gate for the 3x3 family — the H/W-dependent
    half `conv_shapes_supported` (static, construction-time) cannot
    see: True when the output rows tile within the kernel's unroll
    bound, the double-buffered slab fits the VMEM budget at SOME
    output-width tile (`_pick_w_tile` — wide resolutions W-tile
    instead of falling back dense), and every slab DMA lands in-bounds
    of the padded input. 1x1 geometries always tile (the row-tile pad
    covers any M). `nn/fused.py` checks this per forward and runs the
    dense composition when it fails — the same clean-fallback contract
    as the static gate, just resolved at the first shape-bearing
    call."""
    kh, kw = _pair(kernel)
    if (kh, kw) == (1, 1):
        return True
    sh, _ = _pair(stride)
    pads = normalize_conv_padding(padding, kernel, stride, in_hw=in_hw)
    return _conv3x3_geometry(int(in_hw[0]), int(in_hw[1]),
                             int(in_channels), sh, pads) is not None


def _dx_row_rounding(ho=8):
    """Extra zero ROWS appended to the dInput walk's grid when its
    natural row count cannot tile (e.g. the 58-row grid of a 56x56
    stage-1 conv: no divisor <= 8 keeps it within the 16-tile unroll
    bound): round up to the next multiple of 8 — th=8 tiles any
    multiple up to 128 within the bound, the appended rows are zeros
    the conv ignores, and the `[pt:pt+H]` slice discards the tail.
    Returns 0 when the natural count already tiles, None past the
    128-row ceiling (H ~> 126 trains dense)."""
    th = _pick_h_tile(ho)
    if ho // th <= 16:
        return 0
    target = ((ho + 7) // 8) * 8
    return target - ho if target <= 128 else None


def conv_train_geometry_tileable(kernel=3, stride=1, padding=0,
                                 in_hw=None, in_channels=8,
                                 out_channels=8):
    """Per-call geometry gate for the TRAINING path: the forward walk
    must tile AND the backward dInput conv — a stride-1 3x3 walk over
    the stride-dilated dOut (Cout channels) with full (2, 2) halo
    padding, its row grid rounded up per `_dx_row_rounding` — must
    tile too. The dWeight walk reuses the forward slab geometry, so
    the forward check covers it. 1x1 family: always (both directions
    are row-tiled matmuls)."""
    kh, kw = _pair(kernel)
    if (kh, kw) == (1, 1):
        return True
    if not conv_geometry_tileable(kernel, stride, padding, in_hw=in_hw,
                                  in_channels=in_channels):
        return False
    sh, _ = _pair(stride)
    pads = normalize_conv_padding(padding, kernel, stride, in_hw=in_hw)
    hp = int(in_hw[0]) + sum(pads[0])
    wp = int(in_hw[1]) + sum(pads[1])
    ho = (hp - 3) // sh + 1
    wo = (wp - 3) // sh + 1
    hd = sh * (ho - 1) + 1                    # dilated dOut extent
    wd = sh * (wo - 1) + 1
    eh = _dx_row_rounding(hd + 2)
    if eh is None:
        return False
    return _conv3x3_geometry(hd, wd, int(out_channels), 1,
                             ((2, 2 + eh), (2, 2))) is not None


def resolve_conv_backend(backend=None, *, kernel=(3, 3), stride=(1, 1),
                         in_channels=8, out_channels=8, dilation=1,
                         groups=1, padding=0):
    """Resolve `auto`/`dense`/`pallas` to the backend a fused conv
    block will run — ONCE, at construction (the paged-attention
    `resolve_backend` pattern). The `PADDLE_CONV_BACKEND` env override
    wins over the constructor argument (deploy semantics). Unsupported
    static shapes resolve `dense` whatever was requested — the clean
    fallback the 7x7 stem rides — while a supported shape honours an
    explicit `dense`/`pallas` (off-TPU, `pallas` runs the interpreter:
    the CPU CI path); `auto` picks the fused kernel only on TPU."""
    requested = os.environ.get("PADDLE_CONV_BACKEND") or backend \
        or "auto"
    if requested not in CONV_BACKENDS:
        raise ValueError(f"conv backend must be one of {CONV_BACKENDS}, "
                         f"got {requested!r}")
    if not conv_shapes_supported(kernel, stride, in_channels,
                                 out_channels, dilation, groups,
                                 padding):
        return "dense"
    if requested != "auto":
        return requested
    return "pallas" if _on_tpu() else "dense"


# ---------------------------------------------------------------------------
# dense reference (the exactness foil)
# ---------------------------------------------------------------------------

def conv_bn_relu_reference(x, w, scale, shift, stride=1, padding=0,
                           relu=True):
    """The dense `lax.conv_general_dilated` composition the fused
    kernels are tested and benched against: conv with fp32
    accumulation, BN scale/shift in fp32, optional ReLU, ONE cast back
    to the input dtype. x `[N, H, W, Cin]`, w `[kh, kw, Cin, Cout]`,
    scale/shift `[Cout]` f32 (the folded BatchNorm affine)."""
    sh, sw = _pair(stride)
    pads = normalize_conv_padding(padding, w.shape[:2], stride,
                                  in_hw=x.shape[1:3])
    out = jax.lax.conv_general_dilated(
        x, w, (sh, sw), list(pads),
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
        preferred_element_type=jnp.float32)
    out = out * scale.astype(jnp.float32) + shift.astype(jnp.float32)
    if relu:
        out = jnp.maximum(out, 0.0)
    return out.astype(x.dtype)


# ---------------------------------------------------------------------------
# 1x1 family: the conv IS a matmul
# ---------------------------------------------------------------------------

def _conv1x1_kernel(x_ref, w_ref, scale_ref, shift_ref, o_ref, *, relu):
    """One `[TM, Cin] x [Cin, Cout]` MXU pass, epilogue in-register:
    fp32 accumulation, BN scale/shift, optional ReLU, one cast."""
    acc = jax.lax.dot_general(
        x_ref[...], w_ref[...], (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)
    y = acc * scale_ref[...] + shift_ref[...]      # [TM,Cout]*[1,Cout]
    if relu:
        y = jnp.maximum(y, 0.0)
    o_ref[...] = y.astype(o_ref.dtype)


def _pick_row_tile(m=8):
    """Row-tile for the 1x1 matmul: a power-of-two divisor keeps every
    grid step identical; otherwise the wrapper zero-pads M up to the
    tile (the pad rows are sliced off after — ~one tile of waste)."""
    for tm in (512, 256, 128):
        if m % tm == 0:
            return tm
    return 128 if m >= 128 else 8


def _conv1x1_call(x2, w2, scale, shift, relu, interpret):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    M, Cin = x2.shape
    Cout = w2.shape[1]
    TM = _pick_row_tile(M)
    pad = (-M) % TM
    if pad:
        x2 = jnp.pad(x2, ((0, pad), (0, 0)))
    out = pl.pallas_call(
        functools.partial(_conv1x1_kernel, relu=relu),
        grid=((M + pad) // TM,),
        in_specs=[
            pl.BlockSpec((TM, Cin), lambda i: (i, 0)),
            pl.BlockSpec((Cin, Cout), lambda i: (0, 0)),
            pl.BlockSpec((1, Cout), lambda i: (0, 0)),
            pl.BlockSpec((1, Cout), lambda i: (0, 0)),
        ],
        out_specs=pl.BlockSpec((TM, Cout), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((M + pad, Cout), x2.dtype),
        compiler_params=pltpu.TPUCompilerParams(
            dimension_semantics=("arbitrary",)),
        interpret=interpret,
    )(x2, w2, scale.reshape(1, Cout), shift.reshape(1, Cout))
    return out[:M] if pad else out


# ---------------------------------------------------------------------------
# 3x3 family: implicit GEMM over streamed input slabs
# ---------------------------------------------------------------------------

#: VMEM budget for ONE double-buffered input slab (both buffers,
#: bytes). Conservatively sized against fp32 slabs (`_pick_w_tile`
#: uses a constant itemsize so the geometry gate and the kernel
#: wrapper always agree); ~4 MB of the ~16 MB/core leaves room for
#: the weight block, the fp32 accumulator and the output tile. Tests
#: monkeypatch this down to force W-tiling on small shapes.
_VMEM_SLAB_BYTES = 4 * 1024 * 1024


def _pick_w_tile(wo=8, slab=3, stride=1, cin=8, itemsize=4):
    """Output-width tile for the 3x3 slab walk: the largest divisor of
    Wo whose double-buffered input slab `2 * slab_rows * (stride*(tw-1)
    + 3) * Cin` fits `_VMEM_SLAB_BYTES`. TW=Wo (one tile, today's
    whole-width slab) whenever it fits; None when even TW=1 does not
    (pathological Cin — dense handles it)."""
    for tw in range(int(wo), 0, -1):
        if wo % tw:
            continue
        twp = stride * (tw - 1) + 3
        if 2 * slab * twp * cin * itemsize <= _VMEM_SLAB_BYTES:
            return tw
    return None


def _conv3x3_kernel(xp_ref, w_ref, scale_ref, shift_ref, o_ref,
                    xbuf, copy_sems, *, stride, th, num_tiles, tw,
                    relu):
    """One program per (image, width tile). xp_ref is the PADDED
    `[N, Hp, Wp, Cin]` input left in ANY/HBM; the program walks
    `num_tiles` output-row tiles of height `th` within its width tile,
    streaming each tile's input slab (the `stride*(th-1)+3` rows x
    `stride*(tw-1)+3` columns it reads, halo included both ways) into
    the double-buffered VMEM scratch `xbuf` with the next slab's DMA
    in flight behind the current slab's 9 tap matmuls. The epilogue
    (BN scale/shift + optional ReLU) runs on the fp32 accumulator
    before the single cast + output-tile write."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    n = pl.program_id(0)
    j = pl.program_id(1)
    slab = stride * (th - 1) + 3
    twp, cin = xbuf.shape[2], xbuf.shape[3]
    cout = w_ref.shape[3]

    def slab_copy(t, buf):
        return pltpu.make_async_copy(
            xp_ref.at[n, pl.ds(t * th * stride, slab),
                      pl.ds(j * tw * stride, twp)],
            xbuf.at[buf], copy_sems.at[buf])

    slab_copy(0, 0).start()
    for t in range(num_tiles):                # static unroll (<= 16)
        if t + 1 < num_tiles:
            slab_copy(t + 1, (t + 1) % 2).start()
        slab_copy(t, t % 2).wait()
        x = xbuf[t % 2]                       # [slab, TWp, Cin]
        acc = jnp.zeros((th * tw, cout), jnp.float32)
        for dy in range(3):
            for dx in range(3):
                xs = jax.lax.slice(
                    x, (dy, dx, 0),
                    (dy + stride * (th - 1) + 1,
                     dx + stride * (tw - 1) + 1, cin),
                    (stride, stride, 1))      # [th, TW, Cin]
                acc = acc + jax.lax.dot_general(
                    xs.reshape(th * tw, cin), w_ref[dy, dx],
                    (((1,), (0,)), ((), ())),
                    preferred_element_type=jnp.float32)
        y = acc * scale_ref[...] + shift_ref[...]
        if relu:
            y = jnp.maximum(y, 0.0)
        o_ref[0, t * th:(t + 1) * th] = \
            y.reshape(th, tw, cout).astype(o_ref.dtype)


def _pick_h_tile(ho=8):
    """Output-row tile: the largest divisor of Ho <= 8 (TH=1 always
    divides, so every Ho has a tile); the kernel's unrolled tile walk
    is bounded by the caller via conv_shapes_supported + the <= 16
    check in the wrapper."""
    for th in (8, 7, 6, 5, 4, 3, 2, 1):
        if ho % th == 0:
            return th
    return 1


def _conv3x3_geometry(H=8, W=8, Cin=8, stride=1, pads=None):
    """Shared slab/tile geometry for every 3x3-family walk ->
    (Hp, Wp, Ho, Wo, th, num_tiles, slab, tw, num_wtiles, twp), or
    None when the walk cannot tile (unroll bound, VMEM budget, or a
    slab DMA past the padded input)."""
    pads = pads if pads is not None else ((1, 1), (1, 1))
    s = stride
    (pt, pb), (plft, prgt) = pads
    Hp, Wp = H + pt + pb, W + plft + prgt
    Ho = (Hp - 3) // s + 1
    Wo = (Wp - 3) // s + 1
    if Ho < 1 or Wo < 1:
        return None
    th = _pick_h_tile(Ho)
    num_tiles = Ho // th
    if num_tiles > 16:                        # unroll-depth bound
        return None
    slab = s * (th - 1) + 3
    if s * (num_tiles - 1) * th + slab > Hp:
        # the last slab would read past the padded input (possible
        # when padding under-covers the kernel); dense handles it
        return None
    tw = _pick_w_tile(Wo, slab=slab, stride=s, cin=Cin)
    if tw is None:
        return None
    num_wtiles = Wo // tw
    twp = s * (tw - 1) + 3
    if s * (num_wtiles - 1) * tw + twp > Wp:
        return None
    return Hp, Wp, Ho, Wo, th, num_tiles, slab, tw, num_wtiles, twp


def _conv3x3_call(x, w, scale, shift, stride=1, pads=None, relu=True,
                  interpret=False):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    N, H, W, Cin = x.shape
    Cout = w.shape[3]
    s = stride
    pads = pads if pads is not None else ((1, 1), (1, 1))
    geo = _conv3x3_geometry(H, W, Cin, s, pads)
    if geo is None:
        return None
    Hp, Wp, Ho, Wo, th, num_tiles, slab, tw, num_wtiles, twp = geo
    (pt, pb), (plft, prgt) = pads
    xp = jnp.pad(x, ((0, 0), (pt, pb), (plft, prgt), (0, 0)))
    out = pl.pallas_call(
        functools.partial(_conv3x3_kernel, stride=s, th=th,
                          num_tiles=num_tiles, tw=tw, relu=relu),
        grid=(N, num_wtiles),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.TPUMemorySpace.ANY),
            pl.BlockSpec((3, 3, Cin, Cout), lambda n, j: (0, 0, 0, 0)),
            pl.BlockSpec((1, Cout), lambda n, j: (0, 0)),
            pl.BlockSpec((1, Cout), lambda n, j: (0, 0)),
        ],
        out_specs=pl.BlockSpec((1, Ho, tw, Cout),
                               lambda n, j: (n, 0, j, 0)),
        out_shape=jax.ShapeDtypeStruct((N, Ho, Wo, Cout), x.dtype),
        scratch_shapes=[
            pltpu.VMEM((2, slab, twp, Cin), x.dtype),
            pltpu.SemaphoreType.DMA((2,)),
        ],
        compiler_params=pltpu.TPUCompilerParams(
            dimension_semantics=("arbitrary", "arbitrary")),
        interpret=interpret,
    )(xp, w, scale.reshape(1, Cout), shift.reshape(1, Cout))
    return out


# ---------------------------------------------------------------------------
# training forward: same walks, BN-affine epilogue replaced by a fused
# per-channel stats epilogue (sum / sum-of-squares accumulated in f32
# across the SEQUENTIAL grid — "arbitrary" dimension semantics make
# the revisited stats block a legal accumulator)
# ---------------------------------------------------------------------------

def _conv1x1_train_kernel(x_ref, w_ref, o_ref, s_ref):
    """The 1x1 matmul pass with the stats epilogue: the conv tile is
    written in the compute dtype and the SAME cast value feeds the f32
    sum/sum-sq accumulator (the dense foil computes batch stats from
    the cast conv output — bit-parity demands the kernel do too).
    Zero-padded tail rows contribute zero to both sums."""
    from jax.experimental import pallas as pl

    acc = jax.lax.dot_general(
        x_ref[...], w_ref[...], (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)
    yc = acc.astype(o_ref.dtype)
    o_ref[...] = yc

    @pl.when(pl.program_id(0) == 0)
    def _init():
        s_ref[...] = jnp.zeros_like(s_ref)

    p = yc.astype(jnp.float32)
    s_ref[...] += jnp.concatenate(
        [jnp.sum(p, axis=0, keepdims=True),
         jnp.sum(p * p, axis=0, keepdims=True)], axis=0)


def _conv1x1_train_call(x2, w2, interpret=False):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    M, Cin = x2.shape
    Cout = w2.shape[1]
    TM = _pick_row_tile(M)
    pad = (-M) % TM
    if pad:
        x2 = jnp.pad(x2, ((0, pad), (0, 0)))
    out, sums = pl.pallas_call(
        _conv1x1_train_kernel,
        grid=((M + pad) // TM,),
        in_specs=[
            pl.BlockSpec((TM, Cin), lambda i: (i, 0)),
            pl.BlockSpec((Cin, Cout), lambda i: (0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((TM, Cout), lambda i: (i, 0)),
            pl.BlockSpec((2, Cout), lambda i: (0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((M + pad, Cout), x2.dtype),
            jax.ShapeDtypeStruct((2, Cout), jnp.float32),
        ],
        compiler_params=pltpu.TPUCompilerParams(
            dimension_semantics=("arbitrary",)),
        interpret=interpret,
    )(x2, w2)
    return (out[:M] if pad else out), sums


def _conv3x3_train_kernel(xp_ref, w_ref, o_ref, s_ref, xbuf,
                          copy_sems, *, stride=1, th=8, num_tiles=1,
                          tw=8):
    """The 3x3 slab walk (same double-buffered HBM->VMEM stream as
    `_conv3x3_kernel`) with the stats epilogue of
    `_conv1x1_train_kernel`: per-tile conv write in the compute dtype
    plus f32 sum/sum-sq accumulation into the revisited `s_ref`
    block, initialized at the first grid step."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    n = pl.program_id(0)
    j = pl.program_id(1)
    slab = stride * (th - 1) + 3
    twp, cin = xbuf.shape[2], xbuf.shape[3]
    cout = w_ref.shape[3]

    def slab_copy(t, buf):
        return pltpu.make_async_copy(
            xp_ref.at[n, pl.ds(t * th * stride, slab),
                      pl.ds(j * tw * stride, twp)],
            xbuf.at[buf], copy_sems.at[buf])

    @pl.when((n == 0) & (j == 0))
    def _init():
        s_ref[...] = jnp.zeros_like(s_ref)

    slab_copy(0, 0).start()
    for t in range(num_tiles):                # static unroll (<= 16)
        if t + 1 < num_tiles:
            slab_copy(t + 1, (t + 1) % 2).start()
        slab_copy(t, t % 2).wait()
        x = xbuf[t % 2]                       # [slab, TWp, Cin]
        acc = jnp.zeros((th * tw, cout), jnp.float32)
        for dy in range(3):
            for dx in range(3):
                xs = jax.lax.slice(
                    x, (dy, dx, 0),
                    (dy + stride * (th - 1) + 1,
                     dx + stride * (tw - 1) + 1, cin),
                    (stride, stride, 1))
                acc = acc + jax.lax.dot_general(
                    xs.reshape(th * tw, cin), w_ref[dy, dx],
                    (((1,), (0,)), ((), ())),
                    preferred_element_type=jnp.float32)
        yc = acc.astype(o_ref.dtype)
        o_ref[0, t * th:(t + 1) * th] = yc.reshape(th, tw, cout)
        p = yc.astype(jnp.float32)
        s_ref[...] += jnp.concatenate(
            [jnp.sum(p, axis=0, keepdims=True),
             jnp.sum(p * p, axis=0, keepdims=True)], axis=0)


def _conv3x3_train_call(x, w, stride=1, pads=((1, 1), (1, 1)),
                        interpret=False):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    N, H, W, Cin = x.shape
    Cout = w.shape[3]
    s = stride
    geo = _conv3x3_geometry(H, W, Cin, s, pads)
    if geo is None:
        return None
    Hp, Wp, Ho, Wo, th, num_tiles, slab, tw, num_wtiles, twp = geo
    (pt, pb), (plft, prgt) = pads
    xp = jnp.pad(x, ((0, 0), (pt, pb), (plft, prgt), (0, 0)))
    out, sums = pl.pallas_call(
        functools.partial(_conv3x3_train_kernel, stride=s, th=th,
                          num_tiles=num_tiles, tw=tw),
        grid=(N, num_wtiles),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.TPUMemorySpace.ANY),
            pl.BlockSpec((3, 3, Cin, Cout), lambda n, j: (0, 0, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, Ho, tw, Cout), lambda n, j: (n, 0, j, 0)),
            pl.BlockSpec((2, Cout), lambda n, j: (0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((N, Ho, Wo, Cout), x.dtype),
            jax.ShapeDtypeStruct((2, Cout), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((2, slab, twp, Cin), x.dtype),
            pltpu.SemaphoreType.DMA((2,)),
        ],
        compiler_params=pltpu.TPUCompilerParams(
            dimension_semantics=("arbitrary", "arbitrary")),
        interpret=interpret,
    )(xp, w)
    return out, sums


# ---------------------------------------------------------------------------
# backward: dInput as a transposed-filter implicit GEMM, dWeight as a
# slab-streamed accumulation — fp32 accumulation throughout
# ---------------------------------------------------------------------------

def _conv1x1_bwd_kernel(x_ref, dy_ref, y_ref, rows_ref, wt_ref,
                        dx_ref, dw_ref, *, relu=True):
    """One row tile of the FULL 1x1 backward, the ReLU+BN chain folded
    in-register (no padding in the 1x1 family, so the affine chain is
    exact everywhere): recompute the pre-activation from the saved
    conv tile, mask dy, form dConv = scale*(dz - c1 - xhat*c2), then
    BOTH matmuls — dX = dConv @ W^T against the transposed filter and
    the dW accumulation X^T @ dConv into the revisited f32 output
    block. `rows_ref` is the (8, Cout) f32 channel bundle
    [mean_n, inv_n, gamma, beta, mean32, rstd32, c1, c2] (the *_n rows
    are the dtype-cast normalize-path stats, so the recomputed mask
    matches the forward bit-for-bit in fp32). Zero-padded tail rows:
    dX rows are sliced off by the wrapper and X rows are zero, so the
    nonzero dConv they produce cannot leak into dW."""
    from jax.experimental import pallas as pl

    r = rows_ref[...]
    yv = y_ref[...].astype(jnp.float32)
    dz = dy_ref[...].astype(jnp.float32)
    if relu:
        pre = (yv - r[0:1]) * r[1:2] * r[2:3] + r[3:4]
        dz = jnp.where(pre > 0, dz, 0.0)
    xh = (yv - r[4:5]) * r[5:6]
    dcv = ((r[2:3] * r[5:6]) * (dz - r[6:7] - xh * r[7:8])) \
        .astype(dx_ref.dtype)
    dx_ref[...] = jax.lax.dot_general(
        dcv, wt_ref[...], (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32).astype(dx_ref.dtype)

    @pl.when(pl.program_id(0) == 0)
    def _init():
        dw_ref[...] = jnp.zeros_like(dw_ref)

    dw_ref[...] += jax.lax.dot_general(
        x_ref[...], dcv, (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)


def _conv1x1_bwd_call(x2, dy2, y2, rows, wt, relu=True,
                      interpret=False):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    M, Cin = x2.shape
    Cout = wt.shape[0]
    TM = _pick_row_tile(M)
    pad = (-M) % TM
    if pad:
        x2 = jnp.pad(x2, ((0, pad), (0, 0)))
        dy2 = jnp.pad(dy2, ((0, pad), (0, 0)))
        y2 = jnp.pad(y2, ((0, pad), (0, 0)))
    dx, dw = pl.pallas_call(
        functools.partial(_conv1x1_bwd_kernel, relu=relu),
        grid=((M + pad) // TM,),
        in_specs=[
            pl.BlockSpec((TM, Cin), lambda i: (i, 0)),
            pl.BlockSpec((TM, Cout), lambda i: (i, 0)),
            pl.BlockSpec((TM, Cout), lambda i: (i, 0)),
            pl.BlockSpec((8, Cout), lambda i: (0, 0)),
            pl.BlockSpec((Cout, Cin), lambda i: (0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((TM, Cin), lambda i: (i, 0)),
            pl.BlockSpec((Cin, Cout), lambda i: (0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((M + pad, Cin), x2.dtype),
            jax.ShapeDtypeStruct((Cin, Cout), jnp.float32),
        ],
        compiler_params=pltpu.TPUCompilerParams(
            dimension_semantics=("arbitrary",)),
        interpret=interpret,
    )(x2, dy2, y2, rows, wt)
    return (dx[:M] if pad else dx), dw


def _conv3x3_dw_kernel(xp_ref, g_ref, o_ref, xbuf, copy_sems, *,
                       stride=1, th=8, num_tiles=1, tw=8):
    """dWeight for the 3x3 family: the SAME double-buffered input-slab
    walk as the forward kernel, but each of the 9 taps contracts the
    shifted input slice against the dConv tile over the spatial rows —
    `[TH*TW, Cin]^T @ [TH*TW, Cout]` — accumulating into the revisited
    (3, 3, Cin, Cout) f32 output block across every (image, width
    tile, row tile) grid step, initialized at the first."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    n = pl.program_id(0)
    j = pl.program_id(1)
    slab = stride * (th - 1) + 3
    twp, cin = xbuf.shape[2], xbuf.shape[3]
    cout = g_ref.shape[3]

    def slab_copy(t, buf):
        return pltpu.make_async_copy(
            xp_ref.at[n, pl.ds(t * th * stride, slab),
                      pl.ds(j * tw * stride, twp)],
            xbuf.at[buf], copy_sems.at[buf])

    @pl.when((n == 0) & (j == 0))
    def _init():
        o_ref[...] = jnp.zeros_like(o_ref)

    slab_copy(0, 0).start()
    for t in range(num_tiles):                # static unroll (<= 16)
        if t + 1 < num_tiles:
            slab_copy(t + 1, (t + 1) % 2).start()
        slab_copy(t, t % 2).wait()
        x = xbuf[t % 2]                       # [slab, TWp, Cin]
        g2 = g_ref[0, t * th:(t + 1) * th].reshape(th * tw, cout)
        for dy in range(3):
            for dx in range(3):
                xs = jax.lax.slice(
                    x, (dy, dx, 0),
                    (dy + stride * (th - 1) + 1,
                     dx + stride * (tw - 1) + 1, cin),
                    (stride, stride, 1)).reshape(th * tw, cin)
                o_ref[dy, dx] += jax.lax.dot_general(
                    xs, g2, (((0,), (0,)), ((), ())),
                    preferred_element_type=jnp.float32)


def _conv3x3_dw_call(x, g, stride=1, pads=((1, 1), (1, 1)),
                     interpret=False):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    N, H, W, Cin = x.shape
    Cout = g.shape[3]
    s = stride
    geo = _conv3x3_geometry(H, W, Cin, s, pads)
    if geo is None:
        return None
    Hp, Wp, Ho, Wo, th, num_tiles, slab, tw, num_wtiles, twp = geo
    (pt, pb), (plft, prgt) = pads
    xp = jnp.pad(x, ((0, 0), (pt, pb), (plft, prgt), (0, 0)))
    out = pl.pallas_call(
        functools.partial(_conv3x3_dw_kernel, stride=s, th=th,
                          num_tiles=num_tiles, tw=tw),
        grid=(N, num_wtiles),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.TPUMemorySpace.ANY),
            pl.BlockSpec((1, Ho, tw, Cout), lambda n, j: (n, 0, j, 0)),
        ],
        out_specs=pl.BlockSpec((3, 3, Cin, Cout),
                               lambda n, j: (0, 0, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((3, 3, Cin, Cout), jnp.float32),
        scratch_shapes=[
            pltpu.VMEM((2, slab, twp, Cin), x.dtype),
            pltpu.SemaphoreType.DMA((2,)),
        ],
        compiler_params=pltpu.TPUCompilerParams(
            dimension_semantics=("arbitrary", "arbitrary")),
        interpret=interpret,
    )(xp, g)
    return out


# ---------------------------------------------------------------------------
# the training composition: dense foil + fused fwd/bwd + custom_vjp
# ---------------------------------------------------------------------------

def conv_bn_relu_train_reference(x, w, gamma, beta, stride=1,
                                 padding=0, relu=True, eps=1e-5):
    """The dense TRAINING composition the fused custom_vjp is tested
    and benched against — conv + batch-stat BN + ReLU with exactly the
    `nn_ops.conv2d`/`nn_ops.batch_norm` numerics (no
    preferred_element_type on the conv, single-pass f32 E[x^2]-m^2
    stats clamped at 0, mean/inv cast to the compute dtype before the
    normalize, the f32 gamma/beta promoting the affine tail). Returns
    (y, mean, var) like `batch_norm` training mode; fully
    differentiable, so `jax.grad` of this IS the dense backward the
    fused kernels must match."""
    sh, sw = _pair(stride)
    pads = normalize_conv_padding(padding, w.shape[:2], stride,
                                  in_hw=x.shape[1:3])
    conv = jax.lax.conv_general_dilated(
        x, w, (sh, sw), list(pads),
        dimension_numbers=("NHWC", "HWIO", "NHWC"))
    af = conv.astype(jnp.float32)
    mean32 = af.mean(axis=(0, 1, 2))
    m2 = (af * af).mean(axis=(0, 1, 2))
    var32 = jnp.maximum(m2 - mean32 * mean32, 0.0)
    mean = mean32.astype(conv.dtype)
    var = var32.astype(conv.dtype)
    inv = jax.lax.rsqrt(var32 + eps).astype(conv.dtype)
    out = (conv - mean) * inv
    out = out * gamma + beta
    if relu:
        out = jnp.maximum(out, 0.0)
    return out, mean, var


def _train_fwd_impl(x, w, gamma, beta, kernel=1, stride=1,
                    pads=((0, 0), (0, 0)), relu=True, eps=1e-5,
                    interpret=True):
    """Fused training forward -> (y, mean, var, conv, mean32, var32):
    the conv runs through the train kernels (stats in the epilogue —
    ONE pass over the activation produces both the conv output and the
    f32 channel sums), then the normalize+affine+ReLU tail runs as one
    plain-jnp elementwise pass XLA fuses, with the exact
    `nn_ops.batch_norm` dtype staging so the dense foil is matched
    bit-for-bit in fp32."""
    s = stride
    if kernel == 1:
        N = x.shape[0]
        xs = x[:, ::s, ::s] if s != 1 else x
        Ho, Wo = xs.shape[1], xs.shape[2]
        Cin, Cout = x.shape[3], w.shape[3]
        conv2, sums = _conv1x1_train_call(
            xs.reshape(N * Ho * Wo, Cin), w[0, 0], interpret)
        conv = conv2.reshape(N, Ho, Wo, Cout)
    else:
        r = _conv3x3_train_call(x, w, s, pads, interpret)
        if r is None:
            raise ValueError(
                "fused 3x3 train kernel cannot tile this geometry "
                f"(H={x.shape[1]} pad={pads} stride={s}) — run the "
                "dense composition")
        conv, sums = r
    m = float(conv.shape[0] * conv.shape[1] * conv.shape[2])
    mean32 = sums[0] / m
    var32 = jnp.maximum(sums[1] / m - mean32 * mean32, 0.0)
    mean = mean32.astype(conv.dtype)
    var = var32.astype(conv.dtype)
    inv = jax.lax.rsqrt(var32 + eps).astype(conv.dtype)
    y = (conv - mean) * inv
    y = y * gamma + beta
    if relu:
        y = jnp.maximum(y, 0.0)
    return y, mean, var, conv, mean32, var32


def _train_bwd_impl(kernel=1, stride=1, pads=((0, 0), (0, 0)),
                    relu=True, eps=1e-5, interpret=True, res=None,
                    dy=None):
    """Fused training backward (two-pass stats — see DESIGN_DECISIONS
    r19). Pass 1 is ONE fused elementwise+reduce over (dy, conv):
    recompute the pre-activation with the forward's exact dtype
    staging for the ReLU mask, then the f32 channel reductions
    sum(dz) and sum(dz*xhat) — which ARE dbeta/dgamma and fund the
    per-channel c1/c2 of the BN input gradient
    dConv = gamma*rstd*(dz - c1 - xhat*c2). Pass 2 runs the Pallas
    kernels: the 1x1 family folds the whole chain in-register
    (`_conv1x1_bwd_kernel` — dX and the dW accumulation in one
    pallas_call); the 3x3 family materializes dConv once (the chain is
    AFFINE, not linear — on zero-padded halo rows it is nonzero, so it
    cannot be recomputed inside the transposed-conv walk without a
    validity mask; one write + two reads also beats two fused
    recomputes' 2x2 reads), then dX = the stride-1 `_conv3x3_call`
    walk over the s-dilated dConv against the flipped In/Out-swapped
    filter (the mirrored shifted-tap walk, halo in-slab) and dW = the
    `_conv3x3_dw_kernel` slab-streamed accumulation."""
    x, w, gamma, beta, conv, mean32, var32 = res
    s = stride
    dt = x.dtype
    N, H, W, Cin = x.shape
    Ho, Wo, Cout = conv.shape[1], conv.shape[2], conv.shape[3]
    m = float(N * Ho * Wo)
    rstd32 = jax.lax.rsqrt(var32 + eps)
    g32 = gamma.astype(jnp.float32)
    b32 = beta.astype(jnp.float32)
    mean_dt = mean32.astype(dt)
    inv_dt = rstd32.astype(dt)

    # pass 1: mask + channel reductions (one fused XLA pass)
    dz = dy.astype(jnp.float32)
    if relu:
        xn = (conv - mean_dt) * inv_dt        # fwd normalize, bit-exact
        pre = xn.astype(jnp.float32) * g32 + b32
        dz = jnp.where(pre > 0, dz, 0.0)
    xh = (conv.astype(jnp.float32) - mean32) * rstd32
    dbeta32 = dz.sum(axis=(0, 1, 2))
    dgamma32 = (dz * xh).sum(axis=(0, 1, 2))
    c1 = dbeta32 / m
    c2 = dgamma32 / m

    # pass 2: the Pallas kernels
    if kernel == 1:
        rows = jnp.stack([mean_dt.astype(jnp.float32),
                          inv_dt.astype(jnp.float32),
                          g32, b32, mean32, rstd32, c1, c2])
        M = N * Ho * Wo
        xs = x[:, ::s, ::s] if s != 1 else x
        dx2, dw2 = _conv1x1_bwd_call(
            xs.reshape(M, Cin), dy.reshape(M, Cout),
            conv.reshape(M, Cout), rows,
            jnp.transpose(w[0, 0], (1, 0)), relu, interpret)
        dxs = dx2.reshape(N, Ho, Wo, Cin)
        if s != 1:
            dx = jnp.zeros((N, H, W, Cin), dt) \
                .at[:, ::s, ::s].set(dxs)     # fwd sampled; rest is 0
        else:
            dx = dxs
        dw = dw2.reshape(1, 1, Cin, Cout).astype(w.dtype)
    else:
        dconv = ((g32 * rstd32) * (dz - c1 - xh * c2)).astype(dt)
        if s != 1:
            hd, wd = s * (Ho - 1) + 1, s * (Wo - 1) + 1
            dil = jnp.zeros((N, hd, wd, Cout), dt) \
                .at[:, ::s, ::s].set(dconv)
        else:
            dil = dconv
        wflip = jnp.transpose(w[::-1, ::-1], (0, 1, 3, 2))
        # round the walk's row grid up to a tileable count with zero
        # rows (the conv ignores them; the slice below discards them)
        eh = _dx_row_rounding(dil.shape[1] + 2)
        if eh is None:                         # pre-gated; can't happen
            raise ValueError(
                "fused 3x3 dInput kernel cannot tile this geometry — "
                "run the dense composition")
        dxp = _conv3x3_call(
            dil, wflip, jnp.ones((Cin,), jnp.float32),
            jnp.zeros((Cin,), jnp.float32), stride=1,
            pads=((2, 2 + eh), (2, 2)), relu=False,
            interpret=interpret)
        if dxp is None:                        # pre-gated; can't happen
            raise ValueError(
                "fused 3x3 dInput kernel cannot tile this geometry — "
                "run the dense composition")
        (pt, pb), (plft, prgt) = pads
        hfull, wfull = dxp.shape[1], dxp.shape[2]
        need_h, need_w = pt + H, plft + W
        # padded rows/cols the forward never read get zero grad; the
        # pad amounts are 0 whenever the walk already covers them
        dxp = jnp.pad(dxp, ((0, 0), (0, max(0, need_h - hfull)),
                            (0, max(0, need_w - wfull)), (0, 0)))
        dx = dxp[:, pt:pt + H, plft:plft + W]
        dw = _conv3x3_dw_call(x, dconv, s, pads, interpret)
        if dw is None:                         # pre-gated; can't happen
            raise ValueError(
                "fused 3x3 dWeight kernel cannot tile this geometry — "
                "run the dense composition")
        dw = dw.astype(w.dtype)
    return (dx.astype(dt), dw, dgamma32.astype(gamma.dtype),
            dbeta32.astype(beta.dtype))


@functools.lru_cache(maxsize=None)
def _train_vjp(kernel=1, stride=1, pads=((0, 0), (0, 0)), relu=True,
               eps=1e-5, interpret=True):
    """ONE cached `jax.custom_vjp` per static kernel config — the seam
    `nn/fused.py` dispatches training through. The primal runs the
    fused train forward; the vjp pairs it with the fused backward.
    Caching keeps retracing cheap and gives every ConvBNReLU with the
    same geometry the same program identity."""
    def fwd(x, w, gamma, beta):
        return _train_fwd_impl(x, w, gamma, beta, kernel=kernel,
                               stride=stride, pads=pads, relu=relu,
                               eps=eps, interpret=interpret)

    @jax.custom_vjp
    def f(x, w, gamma, beta):
        y, mean, var, _, _, _ = fwd(x, w, gamma, beta)
        return y, mean, var

    def f_fwd(x, w, gamma, beta):
        y, mean, var, conv, mean32, var32 = fwd(x, w, gamma, beta)
        return (y, mean, var), (x, w, gamma, beta, conv, mean32, var32)

    def f_bwd(res, cts):
        # the mean/var outputs feed only the stop-gradient running-stat
        # updates, so their cotangents are structurally zero — the
        # backward is driven by dy alone
        return _train_bwd_impl(kernel=kernel, stride=stride, pads=pads,
                               relu=relu, eps=eps, interpret=interpret,
                               res=res, dy=cts[0])

    f.defvjp(f_fwd, f_bwd)
    return f


def fused_conv_bn_relu_train(x, w, gamma, beta, stride=1, padding=0,
                             relu=True, eps=1e-5, interpret=None):
    """Fused conv+BN+ReLU TRAINING op, NHWC layout — the differentiable
    counterpart of `fused_conv_bn_relu`: batch-stat BN (gamma/beta are
    the learnable affine; running stats are the caller's side-channel,
    `nn/fused.py` updates them from the returned mean/var exactly like
    `nn_ops.batch_norm`). Returns (y, mean, var); differentiating y
    w.r.t. (x, w, gamma, beta) runs the fused backward kernels through
    the cached `jax.custom_vjp`. Raises ValueError on shapes
    `conv_shapes_supported` rejects or geometries
    `conv_train_geometry_tileable` cannot walk — resolve the backend
    and gate first (the `nn/fused.py` blocks do) for the clean dense
    fallback."""
    if interpret is None:
        interpret = not _on_tpu()
    kh, kw = int(w.shape[0]), int(w.shape[1])
    sh, sw = _pair(stride)
    pads = normalize_conv_padding(padding, (kh, kw), (sh, sw),
                                  in_hw=x.shape[1:3])
    if not conv_shapes_supported((kh, kw), (sh, sw), x.shape[3],
                                 w.shape[3], padding=pads):
        raise ValueError(
            f"fused conv train kernels do not cover k={kh}x{kw} "
            f"s={sh}x{sw} cin={x.shape[3]} cout={w.shape[3]} "
            f"pad={pads} — resolve the backend first and run the "
            "dense composition")
    if not conv_train_geometry_tileable((kh, kw), (sh, sw), pads,
                                        in_hw=x.shape[1:3],
                                        in_channels=x.shape[3],
                                        out_channels=w.shape[3]):
        # reject at call time, not first-grad time: the forward walk
        # or the mirrored dX walk cannot tile this geometry
        raise ValueError(
            f"fused conv train kernels cannot tile hw={x.shape[1:3]} "
            f"k={kh}x{kw} s={sh}x{sw} pad={pads} — run the dense "
            "composition")
    f = _train_vjp(kernel=kh, stride=sh, pads=pads, relu=bool(relu),
                   eps=float(eps), interpret=bool(interpret))
    CONV_PATH_STATS["pallas_train"] += 1
    return f(x, w, gamma, beta)


# ---------------------------------------------------------------------------
# public op
# ---------------------------------------------------------------------------

def fused_conv_bn_relu(x, w, scale, shift, stride=1, padding=0,
                       relu=True, interpret=None):
    """Fused conv+BN+ReLU through the Pallas kernels, NHWC layout.

    x `[N, H, W, Cin]`; w `[kh, kw, Cin, Cout]` (HWIO); scale/shift
    `[Cout]` — the BatchNorm affine folded to `y = conv(x)*scale +
    shift` (scale = gamma*rsqrt(var+eps), shift = beta - mean*scale).
    `padding` accepts ints / pairs / (lo, hi) pairs / "SAME"/"VALID".
    Forward-only (no VJP) — the eval/serving op; training runs
    `fused_conv_bn_relu_train` (batch stats + fused backward) via
    `nn/fused.py`. Off-TPU (or `interpret=True`) the kernels run under
    the Pallas interpreter — the CPU CI path. Raises ValueError on
    shapes `conv_shapes_supported` rejects; resolve the backend first
    (the `nn/fused.py` blocks do) for the clean dense fallback."""
    if interpret is None:
        interpret = not _on_tpu()
    kh, kw = int(w.shape[0]), int(w.shape[1])
    sh, sw = _pair(stride)
    pads = normalize_conv_padding(padding, (kh, kw), (sh, sw),
                                  in_hw=x.shape[1:3])
    if not conv_shapes_supported((kh, kw), (sh, sw), x.shape[3],
                                 w.shape[3], padding=pads):
        raise ValueError(
            f"fused conv kernels do not cover k={kh}x{kw} s={sh}x{sw} "
            f"cin={x.shape[3]} cout={w.shape[3]} pad={pads} — resolve "
            "the backend first and run the dense composition")
    scale = scale.astype(jnp.float32)
    shift = shift.astype(jnp.float32)
    if (kh, kw) == (1, 1):
        N, H, W, Cin = x.shape
        if (sh, sw) != (1, 1):
            x = x[:, ::sh, ::sw]              # exact: SAME k=1 samples
        Ho, Wo = x.shape[1], x.shape[2]
        out2 = _conv1x1_call(x.reshape(N * Ho * Wo, Cin), w[0, 0],
                             scale, shift, relu, interpret)
        out = out2.reshape(N, Ho, Wo, w.shape[3])
    else:
        out = _conv3x3_call(x, w, scale, shift, sh, pads, relu,
                            interpret)
        if out is None:
            raise ValueError(
                "fused 3x3 kernel cannot tile this geometry "
                f"(H={x.shape[1]} pad={pads} stride={sh}) — run the "
                "dense composition")
    CONV_PATH_STATS["pallas"] += 1
    return out


# ---------------------------------------------------------------------------
# tpu-verify: contracts + harvest builders
# ---------------------------------------------------------------------------

# All four kernel families (fwd + bwd) are pure programs: nothing
# donated, no collectives at any mp (TPU104 allows zero by default),
# weights ride as traced arguments (TPU102), and every tap/row matmul
# must accumulate fp32 (TPU103 walks the pallas kernel jaxprs — the
# bf16-input harvest shapes give the rule teeth, and the *_bwd
# programs put the dInput/dWeight matmuls under the same rule).
register_contract(TraceContract(
    name="conv_bn_relu_1x1",
    declared_at="paddle_tpu/ops/pallas/conv.py"))
register_contract(TraceContract(
    name="conv_bn_relu_3x3",
    declared_at="paddle_tpu/ops/pallas/conv.py"))
register_contract(TraceContract(
    name="conv_bn_relu_1x1_bwd",
    declared_at="paddle_tpu/ops/pallas/conv.py"))
register_contract(TraceContract(
    name="conv_bn_relu_3x3_bwd",
    declared_at="paddle_tpu/ops/pallas/conv.py"))

#: (contract name, config, kernel, stride, padding, N, H/W, Cin, Cout)
#: — tiny-but-structurally-real instances of every kernel family x
#: stride the suite ships; the asymmetric "SAME" stride-2 3x3 entry
#: covers the halo/padding geometry the bench sweep runs.
CONV_HARVEST_SHAPES = (
    ("conv_bn_relu_1x1", "1x1,s=1", 1, 1, 0, 2, 8, 16, 32),
    ("conv_bn_relu_1x1", "1x1,s=2", 1, 2, 0, 2, 8, 16, 32),
    ("conv_bn_relu_3x3", "3x3,s=1", 3, 1, 1, 2, 8, 16, 16),
    ("conv_bn_relu_3x3", "3x3,s=2", 3, 2, "SAME", 2, 8, 16, 16),
)

#: the backward suite: same family x stride coverage, each program the
#: FULL custom_vjp pullback (ReLU/BN chain + dInput + dWeight) of the
#: training op over bf16 activations.
CONV_BWD_HARVEST_SHAPES = (
    ("conv_bn_relu_1x1_bwd", "1x1,s=1,bwd", 1, 1, 0, 2, 8, 16, 32),
    ("conv_bn_relu_1x1_bwd", "1x1,s=2,bwd", 1, 2, 0, 2, 8, 16, 32),
    ("conv_bn_relu_3x3_bwd", "3x3,s=1,bwd", 3, 1, 1, 2, 8, 16, 16),
    ("conv_bn_relu_3x3_bwd", "3x3,s=2,bwd", 3, 2, "SAME", 2, 8, 16,
     16),
)


def _out_hw(k=1, s=1, pad=0, hw=8):
    pads = normalize_conv_padding(pad, k, s, in_hw=(hw, hw))
    return (hw + sum(pads[0]) - k) // s + 1


def _bwd_harvest_fn(k=1, s=1, pad=0):
    """The bwd harvest program: vjp of the fused training op — the
    jaxpr tpu-verify walks contains the pass-1 reductions AND both
    backward Pallas kernels."""
    def pure(x, w, gamma, beta, dy):
        def run(a, b, g, c):
            y, _, _ = fused_conv_bn_relu_train(
                a, b, g, c, stride=s, padding=pad, relu=True,
                interpret=True)
            return y
        out, vjp = jax.vjp(run, x, w, gamma, beta)
        return vjp(dy.astype(out.dtype))
    return pure


def harvest_programs():
    """-> [(name, config, pure_fn, jitted, args)] for the tpu-verify
    harvester: one jitted fused-conv program per CONV_HARVEST_SHAPES
    entry plus one full-pullback program per CONV_BWD_HARVEST_SHAPES
    entry, interpret-mode (the CPU path the gate runs), bf16 inputs so
    TPU103's narrow-operand accumulation check actually bites."""
    out = []
    for name, config, k, s, pad, n, hw, cin, cout in \
            CONV_HARVEST_SHAPES:
        pure = functools.partial(fused_conv_bn_relu, stride=s,
                                 padding=pad, relu=True,
                                 interpret=True)
        args = (jnp.zeros((n, hw, hw, cin), jnp.bfloat16),
                jnp.zeros((k, k, cin, cout), jnp.bfloat16),
                jnp.ones((cout,), jnp.float32),
                jnp.zeros((cout,), jnp.float32))
        out.append((name, config, pure, jax.jit(pure), args))
    for name, config, k, s, pad, n, hw, cin, cout in \
            CONV_BWD_HARVEST_SHAPES:
        pure = _bwd_harvest_fn(k=k, s=s, pad=pad)
        oh = _out_hw(k=k, s=s, pad=pad, hw=hw)
        args = (jnp.zeros((n, hw, hw, cin), jnp.bfloat16),
                jnp.zeros((k, k, cin, cout), jnp.bfloat16),
                jnp.ones((cout,), jnp.float32),
                jnp.zeros((cout,), jnp.float32),
                jnp.zeros((n, oh, oh, cout), jnp.float32))
        out.append((name, config, pure, jax.jit(pure), args))
    return out
