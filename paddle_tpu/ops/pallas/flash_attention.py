"""Flash attention for TPU.

Replaces the reference's fused_attention/FMHA CUDA path
(paddle/fluid/operators/fused/fused_attention_op.cu, fmha_ref.h) with a
TPU-native blockwise kernel: the S x S score matrix never leaves VMEM.

Two implementations:
- `pallas_sdpa_forward`: our own Pallas forward kernel (online-softmax,
  one (batch*head, q-block) program per grid step, k-blocks innermost with
  VMEM accumulators) — used for inference and as the reference for tests.
- `flash_attention`: full fwd+bwd path that routes to
  jax.experimental.pallas.ops.tpu.flash_attention (the production-tuned
  kernel shipped with jax) when shapes allow, falling back to plain XLA
  attention otherwise. Training uses this.

Layouts: public API takes paddle layout [B, S, H, D] and returns the same.
"""
from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
import numpy as np

_NEG_INF = -1e30


def _xla_attention(q, k, v, causal, scale):
    """Dense fallback [B,H,S,D] -> [B,H,S,D]."""
    logits = jnp.einsum("bhqd,bhkd->bhqk", q, k,
                        preferred_element_type=jnp.float32) * scale
    if causal:
        S, T = logits.shape[-2], logits.shape[-1]
        mask = jnp.tril(jnp.ones((S, T), bool), T - S)
        logits = jnp.where(mask, logits, _NEG_INF)
    probs = jax.nn.softmax(logits, axis=-1).astype(q.dtype)
    return jnp.einsum("bhqk,bhkd->bhqd", probs, v)


def _xla_attention_bf16(q, k, v, causal, scale):
    """Dense attention with bf16 score matmuls (softmax still fp32).

    Kept as a measured reference point, NOT auto-routed: in isolation
    this beats the pallas kernels at narrow-head short-seq shapes
    (8.1ms vs 10.8ms fwd+bwd at B64 H12 S512 D64 on v5e), but inside
    the full BERT training step the S^2 score materialization raises
    memory pressure enough that the end-to-end step is slower
    (278ms vs 262ms) — the flash path stays the default."""
    logits = jnp.einsum("bhqd,bhkd->bhqk", q, k).astype(jnp.float32) * scale
    if causal:
        S, T = logits.shape[-2], logits.shape[-1]
        mask = jnp.tril(jnp.ones((S, T), bool), T - S)
        logits = jnp.where(mask, logits, _NEG_INF)
    probs = jax.nn.softmax(logits, axis=-1).astype(q.dtype)
    return jnp.einsum("bhqk,bhkd->bhqd", probs, v)


# ---------------------------------------------------------------------------
# our own Pallas forward kernel
# ---------------------------------------------------------------------------

def _sdpa_fwd_kernel(q_ref, k_ref, v_ref, o_ref, acc_ref, m_ref, l_ref, *,
                     scale, causal, block_q, block_k, seq_len):
    """Grid: (BH, num_q_blocks, num_k_blocks); k innermost. VMEM scratch
    (acc, m, l) persists across the k dimension of the grid."""
    from jax.experimental import pallas as pl

    qi = pl.program_id(1)
    ki = pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(ki == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)
        m_ref[:] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)

    q_start = qi * block_q
    k_start = ki * block_k

    if causal:
        # skip k-blocks strictly above the causal diagonal
        run = k_start <= q_start + block_q - 1
    else:
        run = jnp.bool_(True)

    @pl.when(run)
    def _compute():
        q = q_ref[0].astype(jnp.float32)  # [bq, d]
        k = k_ref[0].astype(jnp.float32)  # [bk, d]
        v = v_ref[0].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale  # [bq, bk]
        if causal:
            rows = jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)
            cols = jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1)
            mask = (q_start + rows) >= (k_start + cols)
            s = jnp.where(mask, s, _NEG_INF)
        m_prev = m_ref[:, :1]  # [bq, 1]
        m_cur = jnp.max(s, axis=-1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m_prev - m_new)  # [bq,1]
        l_new = alpha[:, 0] * l_ref[:, 0] + jnp.sum(p, axis=-1)
        acc_ref[:] = acc_ref[:] * alpha + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_ref[:] = jnp.broadcast_to(m_new, m_ref.shape)
        l_ref[:] = jnp.broadcast_to(l_new[:, None], l_ref.shape)

    @pl.when(ki == nk - 1)
    def _finalize():
        denom = jnp.maximum(l_ref[:, :1], 1e-30)
        o_ref[0] = (acc_ref[:] / denom).astype(o_ref.dtype)


def pallas_sdpa_forward(q, k, v, causal: bool = True, scale=None,
                        block_q: int = 256, block_k: int = 256,
                        interpret: bool = False):
    """Our Pallas flash forward. Input/output [B, S, H, D] (paddle layout).
    Requires S % block == 0 (pad upstream)."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    B, S, H, D = q.shape
    scale = scale if scale is not None else 1.0 / math.sqrt(D)
    block_q = min(block_q, S)
    block_k = min(block_k, S)
    assert S % block_q == 0 and S % block_k == 0

    # [B,S,H,D] -> [B*H, S, D]
    def to_bh(x):
        return jnp.swapaxes(x, 1, 2).reshape(B * H, S, D)

    qh, kh, vh = to_bh(q), to_bh(k), to_bh(v)
    grid = (B * H, S // block_q, S // block_k)

    kernel = functools.partial(
        _sdpa_fwd_kernel, scale=scale, causal=causal,
        block_q=block_q, block_k=block_k, seq_len=S)

    out = pl.pallas_call(
        kernel,
        interpret=interpret,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, D), lambda bh, qi, ki: (bh, qi, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, block_k, D), lambda bh, qi, ki: (bh, ki, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, block_k, D), lambda bh, qi, ki: (bh, ki, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((1, block_q, D), lambda bh, qi, ki: (bh, qi, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((B * H, S, D), q.dtype),
        scratch_shapes=[
            pltpu.VMEM((block_q, D), jnp.float32),
            pltpu.VMEM((block_q, 128), jnp.float32),
            pltpu.VMEM((block_q, 128), jnp.float32),
        ],
    )(qh, kh, vh)

    return jnp.swapaxes(out.reshape(B, H, S, D), 1, 2)


# ---------------------------------------------------------------------------
# short-sequence fused kernel (whole-seq per program, batched heads)
# ---------------------------------------------------------------------------
# At encoder shapes (S=512, D=64 — BERT/ERNIE-base) the library flash
# kernel is grid-overhead bound: 768 tiny (batch*head) programs, and its
# two-kernel backward recomputes scores twice (9 GEMM-equivalents per
# layer). Measured on v5e: 8.9 ms/layer fwd+bwd at B64 H12 S512 D64.
# This kernel keeps the WHOLE sequence in VMEM (S<=1024: scores are
# S*S*4B <= 4MB, well under the ~16MB/core budget), batches `hb` heads
# per program to amortize grid overhead, and does the backward in ONE
# pass (recompute scores once from the saved logsumexp, then all of
# dq/dk/dv from the shared probabilities — 5 GEMMs). Measured: 4.15
# ms/layer at the same shape (2.1x) — the difference between 0.37 and
# 0.47 MFU on the BERT-base fine-tune bench. Non-causal, no mask (the
# masked/dropout path falls back to dense XLA upstream in
# scaled_dot_product_attention).


def _shortseq_fwd_core(q_ref, k_ref, v_ref, km_ref, o_ref, lse_ref, *,
                       scale, hb):
    for h in range(hb):
        q = q_ref[h]  # [S, D] bf16 — MXU bf16 passes, f32 accumulate
        k = k_ref[h]
        v = v_ref[h]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        if km_ref is not None:
            # additive key mask (padding): [S] broadcast over query rows
            s = s + km_ref[h, 0][None, :]
        m = jnp.max(s, axis=-1, keepdims=True)
        p = jnp.exp(s - m)
        l = jnp.sum(p, axis=-1, keepdims=True)
        o = jax.lax.dot_general(p.astype(v.dtype), v,
                                (((1,), (0,)), ((), ())),
                                preferred_element_type=jnp.float32)
        o_ref[h] = (o / l).astype(o_ref.dtype)
        # [8, S] broadcast: the minimal TPU-tileable layout for a row
        # vector (last two block dims must be multiples of (8, 128))
        lse_ref[h] = jnp.broadcast_to((m + jnp.log(l))[:, 0][None, :],
                                      (8, q.shape[0]))


def _shortseq_bwd_core(q_ref, k_ref, v_ref, km_ref, o_ref, do_ref,
                       lse_ref, dq_ref, dk_ref, dv_ref, *, scale, hb):
    for h in range(hb):
        q = q_ref[h]
        k = k_ref[h]
        v = v_ref[h]
        do = do_ref[h]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        if km_ref is not None:
            s = s + km_ref[h, 0][None, :]
        p = jnp.exp(s - lse_ref[h, 0][:, None])  # [S,S] f32, softmaxed
        pb = p.astype(v.dtype)
        dv = jax.lax.dot_general(pb, do, (((0,), (0,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        # delta_i = sum_d dO_id * O_id (flash-attention-2 backward)
        delta = jnp.sum(do.astype(jnp.float32) *
                        o_ref[h].astype(jnp.float32), axis=-1,
                        keepdims=True)
        ds = (p * (dp - delta) * scale).astype(q_ref.dtype)
        dq = jax.lax.dot_general(ds, k_ref[h], (((1,), (0,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        dk = jax.lax.dot_general(ds, q_ref[h], (((0,), (0,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        dq_ref[h] = dq.astype(dq_ref.dtype)
        dk_ref[h] = dk.astype(dk_ref.dtype)
        dv_ref[h] = dv.astype(dv_ref.dtype)


def _shortseq_fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, *,
                         scale, hb):
    _shortseq_fwd_core(q_ref, k_ref, v_ref, None, o_ref, lse_ref,
                       scale=scale, hb=hb)


def _shortseq_fwd_kernel_masked(q_ref, k_ref, v_ref, km_ref, o_ref,
                                lse_ref, *, scale, hb):
    _shortseq_fwd_core(q_ref, k_ref, v_ref, km_ref, o_ref, lse_ref,
                       scale=scale, hb=hb)


def _shortseq_bwd_kernel(q_ref, k_ref, v_ref, o_ref, do_ref, lse_ref,
                         dq_ref, dk_ref, dv_ref, *, scale, hb):
    _shortseq_bwd_core(q_ref, k_ref, v_ref, None, o_ref, do_ref,
                       lse_ref, dq_ref, dk_ref, dv_ref, scale=scale,
                       hb=hb)


def _shortseq_bwd_kernel_masked(q_ref, k_ref, v_ref, km_ref, o_ref,
                                do_ref, lse_ref, dq_ref, dk_ref,
                                dv_ref, *, scale, hb):
    _shortseq_bwd_core(q_ref, k_ref, v_ref, km_ref, o_ref, do_ref,
                       lse_ref, dq_ref, dk_ref, dv_ref, scale=scale,
                       hb=hb)


def _shortseq_hb(BH, S=512, D=64, itemsize=2):
    """Heads per program: largest divisor of B*H whose per-program VMEM
    working set fits the ~16MB/core budget. Bwd per program: 8 in/out
    blocks of [hb,S,D] (q/k/v/o/do/dq/dk/dv) at the input itemsize,
    plus ~18*S*S bytes of per-head score-sized intermediates (f32
    s/p/dp + bf16 pb/ds — sequential heads reuse the buffers). 12MB
    target leaves room for Mosaic's double-buffered DMA."""
    budget = 12 * 1024 * 1024 - 18 * S * S
    per_head = 8 * S * D * itemsize
    for h in (6, 4, 3, 2):
        if BH % h == 0 and h * per_head <= max(budget, 0):
            return h
    return 1


def _shortseq_call_fwd(q, k, v, kmask, scale, hb, interpret=False):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    BH, S, D = q.shape
    grid = (BH // hb,)

    def blk():
        return pl.BlockSpec((hb, S, D), lambda i: (i, 0, 0),
                            memory_space=pltpu.VMEM)

    row = pl.BlockSpec((hb, 8, S), lambda i: (i, 0, 0),
                       memory_space=pltpu.VMEM)
    out_shape = [jax.ShapeDtypeStruct((BH, S, D), q.dtype),
                 jax.ShapeDtypeStruct((BH, 8, S), jnp.float32)]
    if kmask is None:  # mask-free hot path: no zero-mask traffic
        return pl.pallas_call(
            functools.partial(_shortseq_fwd_kernel, scale=scale, hb=hb),
            grid=grid,
            interpret=interpret,
            in_specs=[blk(), blk(), blk()],
            out_specs=[blk(), row],
            out_shape=out_shape,
        )(q, k, v)
    return pl.pallas_call(
        functools.partial(_shortseq_fwd_kernel_masked, scale=scale,
                          hb=hb),
        grid=grid,
        interpret=interpret,
        in_specs=[blk(), blk(), blk(), row],
        out_specs=[blk(), row],
        out_shape=out_shape,
    )(q, k, v, kmask)


def _shortseq_call_bwd(q, k, v, kmask, o, do, lse, scale, hb,
                       interpret=False):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    BH, S, D = q.shape
    grid = (BH // hb,)

    def blk():
        return pl.BlockSpec((hb, S, D), lambda i: (i, 0, 0),
                            memory_space=pltpu.VMEM)

    row = pl.BlockSpec((hb, 8, S), lambda i: (i, 0, 0),
                       memory_space=pltpu.VMEM)
    if kmask is None:
        return pl.pallas_call(
            functools.partial(_shortseq_bwd_kernel, scale=scale, hb=hb),
            grid=grid,
            interpret=interpret,
            in_specs=[blk(), blk(), blk(), blk(), blk(), row],
            out_specs=[blk(), blk(), blk()],
            out_shape=[jax.ShapeDtypeStruct((BH, S, D), q.dtype)] * 3,
        )(q, k, v, o, do, lse)
    return pl.pallas_call(
        functools.partial(_shortseq_bwd_kernel_masked, scale=scale,
                          hb=hb),
        grid=grid,
        interpret=interpret,
        in_specs=[blk(), blk(), blk(), row, blk(), blk(), row],
        out_specs=[blk(), blk(), blk()],
        out_shape=[jax.ShapeDtypeStruct((BH, S, D), q.dtype)] * 3,
    )(q, k, v, kmask, o, do, lse)


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5))
def _shortseq_attention(q, k, v, kmask, scale, interpret):
    o, _ = _shortseq_call_fwd(q, k, v, kmask, scale,
                              _shortseq_hb(*q.shape, itemsize=q.dtype.itemsize),
                              interpret=interpret)
    return o


def _shortseq_vjp_fwd(q, k, v, kmask, scale, interpret):
    o, lse = _shortseq_call_fwd(q, k, v, kmask, scale,
                                _shortseq_hb(*q.shape, itemsize=q.dtype.itemsize),
                                interpret=interpret)
    return o, (q, k, v, kmask, o, lse)


def _shortseq_vjp_bwd(scale, interpret, res, do):
    q, k, v, kmask, o, lse = res
    dq, dk, dv = _shortseq_call_bwd(q, k, v, kmask, o, do, lse, scale,
                                    _shortseq_hb(*q.shape, itemsize=q.dtype.itemsize),
                                    interpret=interpret)
    # the additive key mask is data, not a trained quantity
    return (dq, dk, dv,
            None if kmask is None else jnp.zeros_like(kmask))


_shortseq_attention.defvjp(_shortseq_vjp_fwd, _shortseq_vjp_bwd)


def shortseq_attention(q, k, v, scale=None, key_mask=None,
                       interpret=False):
    """Fused short-seq bidirectional attention, [B,S,H,D] -> [B,S,H,D].
    Requirements: S % 128 == 0, S <= 512, D in {64, 128}. key_mask is
    an OPTIONAL additive [B, S] float mask over KEYS (0 for real
    tokens, -1e30/-inf for padding — the encoder attention_mask
    convention). Used by flash_attention/sdpa for encoder shapes."""
    B, S, H, D = q.shape
    scale = scale if scale is not None else 1.0 / math.sqrt(D)

    def to_bh(x):
        return jnp.swapaxes(x, 1, 2).reshape(B * H, S, D)

    if key_mask is None:
        km = None  # mask-free kernels: no zero-mask traffic
    else:
        km = jnp.repeat(jnp.asarray(key_mask, jnp.float32), H, axis=0)
        km = jnp.broadcast_to(km[:, None, :], (B * H, 8, S))
    out = _shortseq_attention(to_bh(q), to_bh(k), to_bh(v), km, scale,
                              interpret)
    return jnp.swapaxes(out.reshape(B, H, S, D), 1, 2)


def _shapes_ok_for_shortseq(Sq, Skv, D):
    # S <= 512: the whole-seq score intermediates (~18*S^2 bytes) must
    # fit VMEM next to the head blocks; S=1024 alone would need ~18MB
    return (Sq == Skv and Sq <= 512 and Sq % 128 == 0 and
            D in (64, 128))


# ---------------------------------------------------------------------------
# chunked exact-softmax CAUSAL kernel (decoder shapes)
# ---------------------------------------------------------------------------
# The library flash kernel pays twice at decoder shapes: online-softmax
# rescaling in the forward, and a two-kernel backward that recomputes
# scores twice (9 GEMM-equivalents). This kernel processes one (b,h)
# whole per program with an UNROLLED q-block loop whose k-prefix slices
# are static — causal FLOP-optimal (no above-diagonal blocks), exact
# softmax per row (the whole prefix row is in VMEM, no rescaling), and
# a single-pass backward that accumulates dk/dv in VMEM scratch across
# q-blocks (5 GEMMs + one recompute). Measured at the GPT flagship
# shape (B2 H16 S2048 D128 causal, v5e): 2.64 ms/layer fwd+bwd vs 4.59
# ms for the tuned library kernel — 1.74x, worth ~45 ms/step on the
# 1.3B bench.


def _causal_fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, *, scale,
                       bq):
    S = q_ref.shape[1]
    for qi in range(S // bq):
        lo, hi = qi * bq, (qi + 1) * bq
        q = q_ref[0, lo:hi]          # [bq, D]
        k = k_ref[0, :hi]            # [kw, D] — causal prefix only
        v = v_ref[0, :hi]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        rows = jax.lax.broadcasted_iota(jnp.int32, (bq, hi), 0) + lo
        cols = jax.lax.broadcasted_iota(jnp.int32, (bq, hi), 1)
        s = jnp.where(rows >= cols, s, _NEG_INF)
        m = jnp.max(s, axis=-1, keepdims=True)
        p = jnp.exp(s - m)
        l = jnp.sum(p, axis=-1, keepdims=True)
        o = jax.lax.dot_general(p.astype(v.dtype), v,
                                (((1,), (0,)), ((), ())),
                                preferred_element_type=jnp.float32)
        o_ref[0, lo:hi] = (o / l).astype(o_ref.dtype)
        lse_ref[0, :, lo:hi] = jnp.broadcast_to(
            (m + jnp.log(l))[:, 0][None, :], (8, bq))


def _causal_bwd_kernel(q_ref, k_ref, v_ref, o_ref, do_ref, lse_ref,
                       dq_ref, dk_ref, dv_ref, dk_acc, dv_acc, *,
                       scale, bq):
    S = q_ref.shape[1]
    dk_acc[...] = jnp.zeros_like(dk_acc)
    dv_acc[...] = jnp.zeros_like(dv_acc)
    for qi in range(S // bq):
        lo, hi = qi * bq, (qi + 1) * bq
        q = q_ref[0, lo:hi]
        do = do_ref[0, lo:hi]
        o = o_ref[0, lo:hi]
        k = k_ref[0, :hi]
        v = v_ref[0, :hi]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        rows = jax.lax.broadcasted_iota(jnp.int32, (bq, hi), 0) + lo
        cols = jax.lax.broadcasted_iota(jnp.int32, (bq, hi), 1)
        s = jnp.where(rows >= cols, s, _NEG_INF)
        p = jnp.exp(s - lse_ref[0, 0, lo:hi][:, None])
        pb = p.astype(v.dtype)
        dv_acc[:hi] += jax.lax.dot_general(
            pb, do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        delta = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32),
                        axis=-1, keepdims=True)
        ds = (p * (dp - delta) * scale).astype(q_ref.dtype)
        dq = jax.lax.dot_general(ds, k, (((1,), (0,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        dq_ref[0, lo:hi] = dq.astype(dq_ref.dtype)
        dk_acc[:hi] += jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
    dk_ref[0] = dk_acc[...].astype(dk_ref.dtype)
    dv_ref[0] = dv_acc[...].astype(dv_ref.dtype)


def _causal_bq(S, D, itemsize=2):
    """q-block size: largest divisor of S whose live score
    intermediates stay near 10MB. Per-element estimate: s/p f32 plus
    pb/ds at the INPUT precision (10B/elem for bf16 — verified at the
    GPT shape — 16B for f32). 0 = no viable block."""
    per_elem = 10 if itemsize <= 2 else 16
    for bq in (512, 256, 128):
        if S % bq == 0 and per_elem * bq * S <= 11 * 1024 * 1024:
            return bq
    return 0


def _shapes_ok_for_causal(Sq, Skv, D, itemsize=2):
    bq = _causal_bq(Sq, D, itemsize)
    if not (Sq == Skv and D in (64, 128) and bq):
        return False
    if Sq // bq > 16:  # unroll depth (compile time) bound
        return False
    # whole-head residents: k+v (itemsize) + dk/dv f32 accumulators,
    # plus the live per-q-block intermediates. 14MB leaves headroom in
    # the ~16MB/core VMEM (the GPT shape lands at 13MB, verified)
    resident = 2 * Sq * D * itemsize + 2 * Sq * D * 4
    return resident + 10 * bq * Sq <= 14 * 1024 * 1024


def _causal_call_fwd(q, k, v, scale, bq, interpret=False):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    BH, S, D = q.shape

    def blk():
        return pl.BlockSpec((1, S, D), lambda i: (i, 0, 0),
                            memory_space=pltpu.VMEM)

    return pl.pallas_call(
        functools.partial(_causal_fwd_kernel, scale=scale, bq=bq),
        grid=(BH,),
        interpret=interpret,
        in_specs=[blk(), blk(), blk()],
        out_specs=[blk(),
                   pl.BlockSpec((1, 8, S), lambda i: (i, 0, 0),
                                memory_space=pltpu.VMEM)],
        out_shape=[jax.ShapeDtypeStruct((BH, S, D), q.dtype),
                   jax.ShapeDtypeStruct((BH, 8, S), jnp.float32)],
    )(q, k, v)


def _causal_call_bwd(q, k, v, o, do, lse, scale, bq, interpret=False):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    BH, S, D = q.shape

    def blk():
        return pl.BlockSpec((1, S, D), lambda i: (i, 0, 0),
                            memory_space=pltpu.VMEM)

    return pl.pallas_call(
        functools.partial(_causal_bwd_kernel, scale=scale, bq=bq),
        grid=(BH,),
        interpret=interpret,
        in_specs=[blk(), blk(), blk(), blk(), blk(),
                  pl.BlockSpec((1, 8, S), lambda i: (i, 0, 0),
                               memory_space=pltpu.VMEM)],
        out_specs=[blk(), blk(), blk()],
        out_shape=[jax.ShapeDtypeStruct((BH, S, D), q.dtype)] * 3,
        scratch_shapes=[pltpu.VMEM((S, D), jnp.float32),
                        pltpu.VMEM((S, D), jnp.float32)],
    )(q, k, v, o, do, lse)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def _causal_attention(q, k, v, scale, interpret):
    o, _ = _causal_call_fwd(q, k, v, scale,
                            _causal_bq(q.shape[1], q.shape[2],
                                       q.dtype.itemsize),
                            interpret=interpret)
    return o


def _causal_vjp_fwd(q, k, v, scale, interpret):
    o, lse = _causal_call_fwd(q, k, v, scale,
                              _causal_bq(q.shape[1], q.shape[2],
                                         q.dtype.itemsize),
                              interpret=interpret)
    return o, (q, k, v, o, lse)


def _causal_vjp_bwd(scale, interpret, res, do):
    q, k, v, o, lse = res
    return _causal_call_bwd(q, k, v, o, do, lse, scale,
                            _causal_bq(q.shape[1], q.shape[2],
                                       q.dtype.itemsize),
                            interpret=interpret)


_causal_attention.defvjp(_causal_vjp_fwd, _causal_vjp_bwd)


def chunked_causal_attention(q, k, v, scale=None, interpret=False):
    """Fused causal attention, [B,S,H,D] -> [B,S,H,D]. Requirements:
    _shapes_ok_for_causal. Used by flash_attention for decoder
    self-attention shapes."""
    B, S, H, D = q.shape
    scale = scale if scale is not None else 1.0 / math.sqrt(D)

    def to_bh(x):
        return jnp.swapaxes(x, 1, 2).reshape(B * H, S, D)

    out = _causal_attention(to_bh(q), to_bh(k), to_bh(v), scale,
                            interpret)
    return jnp.swapaxes(out.reshape(B, H, S, D), 1, 2)


# ---------------------------------------------------------------------------
# production path: jax's tuned TPU flash attention (fwd+bwd), XLA fallback
# ---------------------------------------------------------------------------

# Which backend each flash_attention *trace* selected — observable so tests
# can assert the pallas path actually engaged (VERDICT r1 weak #2/#4: the
# previous silent `except: pass` shipped dense attention to every caller).
PATH_STATS = {"pallas": 0, "xla": 0}
_fallback_warned = False


def reset_path_stats():
    PATH_STATS["pallas"] = 0
    PATH_STATS["xla"] = 0


def _shapes_ok_for_lib(Sq, Skv, D):
    return (Sq >= 128 and Sq % 128 == 0 and Skv >= 128 and Skv % 128 == 0
            and D % 64 == 0)


def _tuned_block_sizes(Sq, Skv, D):
    """Measured on v5e at the flagship shape (B2 H16 S2048 D128): the
    library defaults leave a 3x on the table; bq=1024/bk=512 ran fwd+bwd
    at 67 TF/s vs 22 TF/s default (see BENCH notes r3). Blocks are halved
    until they divide the sequence lengths (both are multiples of 128 per
    _shapes_ok_for_lib); >=2048-wide blocks fail to compile on v5e VMEM.
    Tuned at D=128 — for wider heads the per-block VMEM doubles and a
    Mosaic VMEM error would surface at enclosing-jit compile time (outside
    our trace-time fallback), so defer to the library defaults there."""
    from jax.experimental.pallas.ops.tpu.flash_attention import BlockSizes

    if D > 128:
        return None  # library auto-derives safe defaults

    def fit(block, seq):
        while seq % block:
            block //= 2
        return block

    bq = fit(min(1024, Sq), Sq)
    bk = fit(min(512, Skv), Skv)
    return BlockSizes(
        block_q=bq, block_k_major=bk, block_k=bk, block_b=1,
        block_q_major_dkv=bq, block_k_major_dkv=bk, block_k_dkv=bk,
        block_q_dkv=bq,
        block_k_major_dq=bk, block_k_dq=bk, block_q_dq=bq)


def _on_tpu():
    try:
        return jax.devices()[0].platform == "tpu" or \
            jax.default_backend() in ("tpu", "axon")
    except Exception:
        return False


def flash_attention(q, k, v, causal: bool = True, scale=None):
    """[B,S,H,D] -> [B,S,H,D]; differentiable; picks the best backend.

    Routes to jax.experimental.pallas.ops.tpu.flash_attention (tuned
    fwd+bwd kernels) with our measured v5e block sizes
    (_tuned_block_sizes) on TPU for library-friendly shapes, else dense
    XLA attention. A failed pallas trace falls back with a *logged*
    warning — never silently."""
    global _fallback_warned
    B, Sq, H, D = q.shape
    Skv = k.shape[1]
    scale = scale if scale is not None else 1.0 / math.sqrt(D)
    if _on_tpu() and not causal and _shapes_ok_for_shortseq(Sq, Skv, D):
        # encoder shapes: the fused whole-seq kernel (see above)
        try:
            out = shortseq_attention(q, k, v, scale=scale)
            PATH_STATS["pallas"] += 1
            return out
        except Exception as e:  # noqa: BLE001 — fall through, loudly
            if not _fallback_warned:
                import warnings

                warnings.warn(
                    f"shortseq_attention unavailable, trying library "
                    f"flash attention: {type(e).__name__}: {e}")
                _fallback_warned = True
    if _on_tpu() and causal and \
            _shapes_ok_for_causal(Sq, Skv, D, q.dtype.itemsize):
        # decoder self-attention: the chunked causal kernel (see above)
        try:
            out = chunked_causal_attention(q, k, v, scale=scale)
            PATH_STATS["pallas"] += 1
            return out
        except Exception as e:  # noqa: BLE001 — fall through, loudly
            if not _fallback_warned:
                import warnings

                warnings.warn(
                    f"chunked_causal_attention unavailable, trying "
                    f"library flash attention: {type(e).__name__}: {e}")
                _fallback_warned = True
    qh = jnp.swapaxes(q, 1, 2)
    kh = jnp.swapaxes(k, 1, 2)
    vh = jnp.swapaxes(v, 1, 2)
    if _on_tpu() and _shapes_ok_for_lib(Sq, Skv, D) and (not causal or Sq == Skv):
        try:
            from jax.experimental.pallas.ops.tpu.flash_attention import (
                flash_attention as lib_flash,
            )

            out = lib_flash(qh, kh, vh, causal=causal, sm_scale=scale,
                            block_sizes=_tuned_block_sizes(Sq, Skv, D))
            PATH_STATS["pallas"] += 1
            return jnp.swapaxes(out, 1, 2)
        except Exception as e:  # noqa: BLE001 — fall back, but loudly
            if not _fallback_warned:
                import warnings

                warnings.warn(
                    f"pallas flash_attention unavailable, falling back to "
                    f"dense XLA attention (perf hit): {type(e).__name__}: {e}")
                _fallback_warned = True
    PATH_STATS["xla"] += 1
    out = _xla_attention(qh, kh, vh, causal, scale)
    return jnp.swapaxes(out, 1, 2)
