"""Shape/layout manipulation ops — analog of python/paddle/tensor/manipulation.py."""
from __future__ import annotations

import builtins

import jax
import jax.numpy as jnp
import numpy as np

builtins_slice = builtins.slice

from paddle_tpu.core.tensor import Tensor

from .dispatch import apply, apply_nograd, as_tensor

__all__ = [
    "reshape", "flatten", "squeeze", "unsqueeze", "transpose", "moveaxis",
    "concat", "stack", "split", "chunk", "unbind", "tile", "expand",
    "expand_as", "broadcast_to", "flip", "roll", "gather", "gather_nd",
    "scatter", "index_select", "masked_select", "take_along_axis",
    "put_along_axis", "slice", "strided_slice", "getitem", "clone",
    "repeat_interleave", "unstack", "as_complex", "as_real", "pad",
    "crop", "rot90", "numel", "tensordot", "squeeze_", "unsqueeze_",
    "swapaxes", "swapdims", "vsplit", "hsplit", "dsplit", "take",
    "as_strided", "diff", "scatter_nd", "searchsorted", "bucketize",
]


def clone(x):
    x = as_tensor(x)
    return apply("clone", lambda a: a + 0 if jnp.issubdtype(a.dtype, jnp.inexact) else jnp.array(a), x)


def reshape(x, shape):
    x = as_tensor(x)
    if isinstance(shape, Tensor):
        shape = shape.tolist()
    def dim(s):
        # coerce ints/0-d Tensors/floats; symbolic dims (jax.export
        # shape polymorphism) raise on int() and pass through untouched
        try:
            return int(s)
        except Exception:  # TypeError, or jax's
            return s       # InconclusiveDimensionOperation for symbols

    shape = tuple(dim(s) for s in shape)
    return apply("reshape", lambda a: jnp.reshape(a, shape), x)


def flatten(x, start_axis=0, stop_axis=-1):
    x = as_tensor(x)
    nd = x.ndim
    sa = start_axis % nd if nd else 0
    so = stop_axis % nd if nd else 0
    new_shape = x.shape[:sa] + [-1] + x.shape[so + 1:]
    return reshape(x, new_shape)


def _norm_axes(axis, ndim):
    if axis is None:
        return None
    if isinstance(axis, (int, np.integer)):
        return (int(axis) % ndim if ndim else 0,)
    return tuple(int(a) % ndim for a in axis)


def squeeze(x, axis=None):
    x = as_tensor(x)
    axes = _norm_axes(axis, x.ndim)
    if axes is not None:
        axes = tuple(a for a in axes if x.shape[a] == 1)
        if not axes:
            return clone(x)
    return apply("squeeze", lambda a: jnp.squeeze(a, axes), x)


def unsqueeze(x, axis):
    x = as_tensor(x)
    if isinstance(axis, (int, np.integer)):
        axis = [int(axis)]
    return apply("unsqueeze", lambda a: jnp.expand_dims(a, tuple(axis)), x)


squeeze_ = squeeze
unsqueeze_ = unsqueeze


def transpose(x, perm=None):
    x = as_tensor(x)
    if perm is None:
        perm = list(range(x.ndim))[::-1]
    perm = tuple(int(p) for p in perm)
    return apply("transpose", lambda a: jnp.transpose(a, perm), x)


def moveaxis(x, source, destination):
    x = as_tensor(x)
    return apply("moveaxis", lambda a: jnp.moveaxis(a, source, destination), x)


def concat(xs, axis=0):
    ts = [as_tensor(t) for t in xs]
    axis = int(axis if not isinstance(axis, Tensor) else axis.item())
    return apply("concat", lambda *arrs: jnp.concatenate(arrs, axis=axis), *ts)


def stack(xs, axis=0):
    ts = [as_tensor(t) for t in xs]
    return apply("stack", lambda *arrs: jnp.stack(arrs, axis=axis), *ts)


def split(x, num_or_sections, axis=0):
    x = as_tensor(x)
    axis = int(axis)
    dim = x.shape[axis]
    if isinstance(num_or_sections, (int, np.integer)):
        n = int(num_or_sections)
        if dim % n != 0:
            raise ValueError(
                f"split: axis {axis} size {dim} is not divisible by {n} "
                f"(paddle semantics; pass explicit section sizes instead)")
        sizes = [dim // n] * n
    else:
        sizes = [int(s) for s in num_or_sections]
        neg = [i for i, s in enumerate(sizes) if s < 0]
        if neg:
            sizes[neg[0]] = dim - sum(s for s in sizes if s >= 0)
    offsets = np.cumsum([0] + sizes[:-1]).tolist()

    def fn(a):
        return tuple(
            jnp.take(a, jnp.arange(o, o + s), axis=axis) for o, s in zip(offsets, sizes)
        )

    return list(apply("split", fn, x)) if len(sizes) > 1 else [clone(x)]


def chunk(x, chunks, axis=0):
    return split(x, chunks, axis)


def unbind(x, axis=0):
    x = as_tensor(x)
    n = x.shape[axis]

    def fn(a):
        return tuple(jnp.squeeze(s, axis) for s in jnp.split(a, n, axis=axis))

    return list(apply("unbind", fn, x))


unstack = unbind


def tile(x, repeat_times):
    x = as_tensor(x)
    rt = tuple(int(r) for r in repeat_times)
    return apply("tile", lambda a: jnp.tile(a, rt), x)


def expand(x, shape):
    x = as_tensor(x)
    shape = tuple(
        x.shape[i - (len(shape) - x.ndim)] if int(s) == -1 else int(s)
        for i, s in enumerate(shape)
    )
    return apply("expand", lambda a: jnp.broadcast_to(a, shape), x)


def expand_as(x, y):
    return expand(x, y.shape)


def broadcast_to(x, shape):
    return expand(x, shape)


def flip(x, axis):
    x = as_tensor(x)
    if isinstance(axis, (int, np.integer)):
        axis = [int(axis)]
    return apply("flip", lambda a: jnp.flip(a, tuple(axis)), x)


def roll(x, shifts, axis=None):
    x = as_tensor(x)
    return apply("roll", lambda a: jnp.roll(a, shifts, axis), x)


def gather(x, index, axis=0):
    x = as_tensor(x)
    idx = index._array if isinstance(index, Tensor) else jnp.asarray(index)
    idx = idx.reshape(-1) if idx.ndim > 1 else idx
    return apply("gather", lambda a: jnp.take(a, idx, axis=axis), x)


def gather_nd(x, index):
    x = as_tensor(x)
    idx = index._array if isinstance(index, Tensor) else jnp.asarray(index)

    def fn(a):
        return a[tuple(jnp.moveaxis(idx, -1, 0))]

    return apply("gather_nd", fn, x)


def scatter(x, index, updates, overwrite=True):
    x = as_tensor(x)
    updates = as_tensor(updates, x)
    idx = index._array if isinstance(index, Tensor) else jnp.asarray(index)
    idx = idx.reshape(-1)

    def fn(a, u):
        if overwrite:
            return a.at[idx].set(u)
        return a.at[idx].add(u)

    return apply("scatter", fn, x, updates)


def index_select(x, index, axis=0):
    return gather(x, index, axis)


def masked_select(x, mask):
    # dynamic shape: host-side only (not jittable); paddle semantics
    x = as_tensor(x)
    m = mask._array if isinstance(mask, Tensor) else jnp.asarray(mask)
    return apply_nograd("masked_select", lambda a: a[np.asarray(m)], x)


def take_along_axis(x, indices, axis):
    x = as_tensor(x)
    idx = indices._array if isinstance(indices, Tensor) else jnp.asarray(indices)
    return apply("take_along_axis", lambda a: jnp.take_along_axis(a, idx, axis=axis), x)


def put_along_axis(x, indices, values, axis):
    x = as_tensor(x)
    values = as_tensor(values, x)
    idx = indices._array if isinstance(indices, Tensor) else jnp.asarray(indices)

    def fn(a, v):
        return jnp.put_along_axis(a, idx, v, axis=axis, inplace=False)

    return apply("put_along_axis", fn, x, values)


def slice(x, axes, starts, ends):
    x = as_tensor(x)
    slices = [builtins_slice(None)] * x.ndim
    for ax, st, en in zip(axes, starts, ends):
        slices[ax] = builtins_slice(int(st), int(en))
    sl = tuple(slices)
    return apply("slice", lambda a: a[sl], x)


def strided_slice(x, axes, starts, ends, strides):
    x = as_tensor(x)
    slices = [builtins_slice(None)] * x.ndim
    for ax, st, en, sd in zip(axes, starts, ends, strides):
        slices[ax] = builtins_slice(int(st), int(en), int(sd))
    sl = tuple(slices)
    return apply("strided_slice", lambda a: a[sl], x)


def _prep_index(item):
    """Convert Tensor indices inside a getitem key to raw arrays."""
    if isinstance(item, Tensor):
        return item._array
    if isinstance(item, tuple):
        return tuple(_prep_index(i) for i in item)
    if isinstance(item, list):
        return [_prep_index(i) for i in item]
    return item


def getitem(x, item):
    x = as_tensor(x)
    key = _prep_index(item)
    return apply("getitem", lambda a: a[key], x)


def setitem(x, item, value):
    """In-place __setitem__ via functional .at[] update.

    When `x` participates in autodiff, the overwrite is recorded as a
    differentiable op (the analog of Paddle's set_value_grad: the input
    cotangent is zeroed at the overwritten positions, the value receives
    the cotangent gathered from them). Without recording, backward through
    a mutated non-leaf silently used the pre-mutation graph (ADVICE r1)."""
    from paddle_tpu.core.autograd import is_grad_enabled

    key = _prep_index(item)
    v = value._array if isinstance(value, Tensor) else jnp.asarray(value)
    if hasattr(v, "astype"):
        v = v.astype(x._array.dtype)

    def _set(a, vv):
        # numpy setitem broadcasting: leading size-1 dims of the value may
        # be dropped to fit the target slot; jax .at[].set is stricter, so
        # only pay the eval_shape trace when the strict form rejects it
        try:
            return a.at[key].set(vv)
        except (ValueError, TypeError):
            tgt_shape = jax.eval_shape(lambda t: t[key], a).shape
            while getattr(vv, "ndim", 0) > len(tgt_shape) and vv.shape[0] == 1:
                vv = vv[0]
            return a.at[key].set(jnp.broadcast_to(vv, tgt_shape))

    needs_grad = is_grad_enabled() and (
        x._creator is not None
        or not x.stop_gradient
        or (isinstance(value, Tensor) and not value.stop_gradient)
    ) and jnp.issubdtype(x._array.dtype, jnp.inexact)

    if not needs_grad:
        x._mutate(_set(x._array, v))
        return x

    if x._creator is None and not x.stop_gradient:
        raise RuntimeError(
            "in-place __setitem__ on a leaf tensor with stop_gradient=False "
            "is not supported (its .grad would no longer match the stored "
            "value); use paddle.no_grad() or assign to a cloned tensor")

    # snapshot x's identity so the tape edge points at the PRE-mutation
    # tensor, then re-point x at the op output (keeps in-place semantics)
    old = Tensor._wrap(x._array, stop_gradient=x.stop_gradient,
                       creator=x._creator, out_idx=x._out_idx)
    if isinstance(value, Tensor):
        new = apply("setitem",
                    lambda a, vv: _set(a, vv.astype(a.dtype)), old, value)
    else:
        new = apply("setitem", lambda a: _set(a, v), old)
    x._mutate(new._array)
    x._creator = new._creator
    x._out_idx = new._out_idx
    x.stop_gradient = new.stop_gradient
    return x


def repeat_interleave(x, repeats, axis=None):
    x = as_tensor(x)
    r = repeats._array if isinstance(repeats, Tensor) else repeats
    return apply("repeat_interleave", lambda a: jnp.repeat(a, r, axis=axis), x)


def as_complex(x):
    x = as_tensor(x)
    return apply("as_complex", lambda a: jax.lax.complex(a[..., 0], a[..., 1]), x)


def as_real(x):
    x = as_tensor(x)
    return apply("as_real", lambda a: jnp.stack([jnp.real(a), jnp.imag(a)], axis=-1), x)


def pad(x, pad, mode="constant", value=0.0, data_format="NCHW"):
    x = as_tensor(x)
    nd = x.ndim
    if len(pad) == nd * 2:
        cfg = [(int(pad[2 * i]), int(pad[2 * i + 1])) for i in range(nd)]
    else:
        # paddle semantics: pad applies to last len(pad)//2 spatial dims of
        # NCHW/NHWC layout, ordered (left,right,top,bottom,...)
        npairs = len(pad) // 2
        pairs = [(int(pad[2 * i]), int(pad[2 * i + 1])) for i in range(npairs)]
        pairs = pairs[::-1]  # paddle lists W first, numpy wants outermost first
        cfg = [(0, 0)] * (nd - npairs) + pairs
        if data_format.endswith("C") and nd - npairs >= 2:  # NHWC: channel last
            cfg = [(0, 0)] + cfg[2:] + [(0, 0)]
    jmode = {"constant": "constant", "reflect": "reflect", "replicate": "edge",
             "circular": "wrap"}[mode]
    if jmode == "constant":
        return apply("pad", lambda a: jnp.pad(a, cfg, mode="constant", constant_values=value), x)
    return apply("pad", lambda a: jnp.pad(a, cfg, mode=jmode), x)


def crop(x, shape, offsets=None):
    x = as_tensor(x)
    if offsets is None:
        offsets = [0] * x.ndim
    sl = tuple(
        builtins_slice(int(o), int(o) + int(s)) for o, s in zip(offsets, shape)
    )
    return apply("crop", lambda a: a[sl], x)


def rot90(x, k=1, axes=(0, 1)):
    x = as_tensor(x)
    return apply("rot90", lambda a: jnp.rot90(a, k, axes), x)


def numel(x):
    return Tensor._wrap(jnp.asarray(int(np.prod(x._array.shape)) if x._array.shape else 1))


def tensordot(x, y, axes=2):
    x, y = as_tensor(x), as_tensor(y)
    return apply("tensordot", lambda a, b: jnp.tensordot(a, b, axes), x, y)


def swapaxes(x, axis1, axis2, name=None):
    x = as_tensor(x)
    return apply("swapaxes", lambda a: jnp.swapaxes(a, axis1, axis2), x)


swapdims = swapaxes


def _axis_split(opname, jfn, min_ndim):
    """numpy/paddle split-family semantics: an int divides into equal
    sections; a list gives the INDICES to split at (not section sizes —
    that is split()'s convention, not this family's)."""
    def op(x, num_or_indices, name=None):
        x = as_tensor(x)
        if x.ndim < min_ndim:
            raise ValueError(
                f"{opname} requires at least {min_ndim}-D input, "
                f"got {x.ndim}-D")
        spec = num_or_indices if isinstance(num_or_indices, int) \
            else [int(i) for i in num_or_indices]
        return apply(opname, lambda a: tuple(jfn(a, spec)), x)

    op.__name__ = opname
    return op


vsplit = _axis_split("vsplit", jnp.vsplit, 2)
hsplit = _axis_split("hsplit", jnp.hsplit, 1)
dsplit = _axis_split("dsplit", jnp.dsplit, 3)


def take(x, index, mode="raise", name=None):
    """Flattened-index gather (paddle take): index anywhere in
    [-numel, numel). mode: 'raise' validates eagerly (clips under a
    trace — XLA cannot raise), 'clip', 'wrap'."""
    if mode not in ("raise", "clip", "wrap"):
        raise ValueError(f"take: invalid mode {mode!r}; "
                         "expected 'raise', 'clip' or 'wrap'")
    x = as_tensor(x)
    idx = index._array if isinstance(index, Tensor) else jnp.asarray(index)
    n = int(np.prod(x.shape)) if x.shape else 1
    if mode == "raise" and not isinstance(idx, jax.core.Tracer):
        bad = (np.asarray(idx) < -n) | (np.asarray(idx) >= n)
        if bad.any():
            raise IndexError(f"take: index out of range for numel {n}")
    if mode == "wrap":
        idx = jnp.mod(idx, n)
    else:  # raise (validated above) and clip both clamp for the gather
        idx = jnp.clip(jnp.where(idx < 0, idx + n, idx), 0, n - 1)
    return apply("take", lambda a: a.reshape(-1)[idx], x)


def as_strided(x, shape, stride, offset=0, name=None):
    """View-by-strides (paddle as_strided). XLA has no aliasing views;
    this materializes the equivalent gather: element [i0,i1,...] =
    flat[offset + sum(ik*stride[k])]."""
    x = as_tensor(x)
    shape = tuple(int(s) for s in shape)
    stride = tuple(int(s) for s in stride)

    def fn(a):
        flat = a.reshape(-1)
        if not shape:
            return flat[offset]
        grids = jnp.meshgrid(*[jnp.arange(s) for s in shape],
                             indexing="ij")
        flat_idx = offset
        for g, st in zip(grids, stride):
            flat_idx = flat_idx + g * st
        return flat[flat_idx]

    return apply("as_strided", fn, x)


def diff(x, n=1, axis=-1, prepend=None, append=None, name=None):
    x = as_tensor(x)
    pre = None if prepend is None else \
        (prepend._array if isinstance(prepend, Tensor)
         else jnp.asarray(prepend))
    app = None if append is None else \
        (append._array if isinstance(append, Tensor)
         else jnp.asarray(append))
    return apply("diff",
                 lambda a: jnp.diff(a, n=n, axis=axis, prepend=pre,
                                    append=app), x)


def scatter_nd(index, updates, shape, name=None):
    """zeros(shape) scatter-ADDED with updates at index (paddle
    scatter_nd; phi scatter_nd_add into zeros)."""
    updates = as_tensor(updates)
    idx = index._array if isinstance(index, Tensor) else jnp.asarray(index)
    shape = tuple(int(s) for s in shape)

    def fn(u):
        z = jnp.zeros(shape, u.dtype)
        return z.at[tuple(jnp.moveaxis(idx, -1, 0))].add(u)

    return apply("scatter_nd", fn, updates)


def searchsorted(sorted_sequence, values, out_int32=False, right=False,
                 name=None):
    seq = as_tensor(sorted_sequence)
    vals = values._array if isinstance(values, Tensor) \
        else jnp.asarray(values)
    side = "right" if right else "left"

    def fn(s):
        if s.ndim == 1:
            out = jnp.searchsorted(s, vals, side=side)
        else:  # batched rows (paddle nd semantics: last dim sorted)
            out = jax.vmap(lambda row, v:
                           jnp.searchsorted(row, v, side=side))(
                s.reshape(-1, s.shape[-1]),
                vals.reshape(-1, vals.shape[-1]))
            out = out.reshape(vals.shape)
        # int64 only when the runtime allows it (x64-disabled jax
        # truncates int64 to int32 with a warning); out_int32 forces 32
        wide = jnp.int64 if jax.config.jax_enable_x64 else jnp.int32
        return out.astype(jnp.int32 if out_int32 else wide)

    return apply_nograd("searchsorted", fn, seq)


def bucketize(x, sorted_sequence, out_int32=False, right=False, name=None):
    return searchsorted(sorted_sequence, x, out_int32=out_int32,
                        right=right)
