"""Op dispatch: the eager execution + autograd-recording boundary.

TPU-native analog of the reference's generated `foo_ad_func` layer
(paddle/fluid/eager/api/generated/.../dygraph_functions.cc, emitted by
eager_gen.py:1049) plus PHI kernel dispatch
(paddle/phi/core/kernel_factory.cc:158). Where the reference selects a
(backend, layout, dtype) kernel and separately generates a GradNode per
op, here every op is ONE pure jax function: `jax.vjp` gives both the
forward value and the backward closure, XLA does kernel selection and
fusion, and the same code path works under tracing (to_static).

AMP autocast (the analog of eager_amp_auto_cast.h) is applied here, at
dispatch time, before the op runs.
"""
from __future__ import annotations

from typing import Callable, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from paddle_tpu.core import dtype as dtypes
from paddle_tpu.core.autograd import Node, is_grad_enabled
from paddle_tpu.core.tensor import Tensor

__all__ = ["apply", "apply_nograd", "as_tensor", "unwrap", "OpStats"]


class OpStats:
    """Per-op dispatch counters (profiler hook point).

    span_hook, when set by the Profiler, receives
    (name, start_us, end_us, synced) for every eager op dispatch —
    synced=True means the dispatch blocked until outputs were ready
    (ProfilerTarget.TPU sync timing: the span approximates
    host-dispatch + device-execute, the CUPTI-attribution analog)."""

    counts: dict = {}
    enabled = False
    span_hook = None
    sync_spans = False

    @classmethod
    def record(cls, name):
        if cls.enabled:
            cls.counts[name] = cls.counts.get(name, 0) + 1


def _timed_dispatch(name, run):
    """Wrap one op dispatch with the profiler span hook (no-op fast
    path when no profiler is recording)."""
    hook = OpStats.span_hook
    if hook is None:
        return run()
    import time as _time

    t0 = _time.perf_counter_ns() // 1000
    out = run()
    synced = False
    if OpStats.sync_spans:
        arrs = [o._array for o in out] if isinstance(out, tuple) \
            else [out._array]
        # block_until_ready is a no-op on tracers (it does NOT raise),
        # so trace-time dispatches must be tagged host-side explicitly
        # or the device column absorbs tracing/compile time
        if not any(isinstance(a, jax.core.Tracer) for a in arrs):
            try:
                jax.block_until_ready(arrs)
                if jax.default_backend() == "axon":
                    # the axon tunnel's block_until_ready can return
                    # early; a 1-element readback forces completion
                    # (this is what makes sync profiling cost a tunnel
                    # round-trip per op — documented trade-off)
                    np.asarray(arrs[0].ravel()[:1])
                synced = True
            except Exception:
                pass  # non-array outputs: host span only
    hook(name, t0, _time.perf_counter_ns() // 1000, synced)
    return out


def _maybe_check_numerics(op_name, arrays):
    """FLAGS_check_nan_inf hook (nan_inf_utils.h:37 analog): checks every
    op's outputs when the debug flag is on — concrete arrays host-side,
    tracer outputs via a staged in-graph check."""
    from paddle_tpu.framework import nan_inf

    if not nan_inf.check_enabled():
        return
    concrete = [a for a in arrays if not isinstance(a, jax.core.Tracer)
                and hasattr(a, "dtype")]
    traced = [a for a in arrays if isinstance(a, jax.core.Tracer)]
    if concrete:
        nan_inf.check_eager(op_name, concrete)
    if traced:
        nan_inf.stage_check(
            [(f"output[{i}]", a) for i, a in enumerate(traced)],
            f"op '{op_name}'")


def as_tensor(x, ref: Tensor = None) -> Tensor:
    """Coerce scalars / arrays to Tensor. Python scalars adopt the ref
    tensor's dtype (paddle scalar-promotion semantics: `x * 2.0` keeps
    x's dtype)."""
    if isinstance(x, Tensor):
        return x
    if isinstance(x, (bool, int, float)) and ref is not None and dtypes.is_inexact(ref.dtype):
        return Tensor._wrap(jnp.asarray(x, ref._array.dtype))
    if isinstance(x, (bool, int, float)) and ref is not None:
        # int scalar with int tensor: keep tensor dtype
        if isinstance(x, int) and not isinstance(x, bool):
            return Tensor._wrap(jnp.asarray(x, ref._array.dtype))
    return Tensor(x)


def unwrap(x):
    if isinstance(x, Tensor):
        return x._array
    return x


def _wrap_outputs(out_arrays, node, needs_grad, op_name=None):
    single = not isinstance(out_arrays, (tuple, list))
    outs = [out_arrays] if single else list(out_arrays)
    _maybe_check_numerics(op_name or (node.name if node else "op"), outs)
    tensors = []
    for i, arr in enumerate(outs):
        diffable = needs_grad and jnp.issubdtype(arr.dtype, jnp.inexact)
        t = Tensor._wrap(
            arr,
            stop_gradient=not diffable,
            creator=node if diffable else None,
            out_idx=i,
        )
        tensors.append(t)
    return tensors[0] if single else tuple(tensors)


def apply(name: str, fn: Callable, *inputs: Tensor, amp_policy: str = None):
    """Run differentiable op `fn(*arrays)`; record a tape Node if needed.

    `fn` must be a pure function of the input arrays (static attrs go in
    the closure). Returns Tensor or tuple of Tensors.
    """
    if OpStats.span_hook is not None:
        return _timed_dispatch(
            name, lambda: _apply_impl(name, fn, *inputs,
                                      amp_policy=amp_policy))
    return _apply_impl(name, fn, *inputs, amp_policy=amp_policy)


def _apply_impl(name: str, fn: Callable, *inputs: Tensor,
                amp_policy: str = None):
    OpStats.record(name)
    from paddle_tpu.amp.auto_cast import maybe_autocast  # lazy; amp optional

    inputs = maybe_autocast(name, inputs, amp_policy)
    arrays = [t._array for t in inputs]
    needs_grad = is_grad_enabled() and any(
        (not t.stop_gradient) and jnp.issubdtype(t._array.dtype, jnp.inexact)
        for t in inputs
    )
    if not needs_grad:
        out = fn(*arrays)
        return _wrap_outputs(out, None, False, op_name=name)

    if any(isinstance(a, jax.core.Tracer) for a in arrays):
        # Inside an outer jax trace (TrainStep's value_and_grad, to_static,
        # vmap...): run fn directly so the OUTER AD differentiates it —
        # eagerly calling jax.vjp here would linearize at trace time and
        # force higher-order AD through custom_vjp ops (this is what
        # silently knocked the pallas flash kernel back to dense attention
        # in round 1). The tape node gets a lazy vjp for the rare case of
        # tape backward under trace.
        out = fn(*arrays)
        outs = out if isinstance(out, (tuple, list)) else (out,)
        out_specs = [(o.shape, o.dtype) for o in outs]

        def lazy_vjp(cts, _fn=fn, _arrays=arrays):
            _, vjp_fn = jax.vjp(_fn, *_arrays)
            return vjp_fn(cts)

        node = Node(name, lazy_vjp, inputs, out_specs)
        return _wrap_outputs(out, node, True)

    out, vjp_fn = jax.vjp(fn, *arrays)
    outs = out if isinstance(out, (tuple, list)) else (out,)
    out_specs = [(o.shape, o.dtype) for o in outs]
    node = Node(name, vjp_fn, inputs, out_specs)
    return _wrap_outputs(out, node, True)


def apply_nograd(name: str, fn: Callable, *inputs: Tensor):
    """Run a non-differentiable op (comparisons, argmax, casts to int...)."""
    if OpStats.span_hook is not None:
        return _timed_dispatch(
            name, lambda: _apply_nograd_impl(name, fn, *inputs))
    return _apply_nograd_impl(name, fn, *inputs)


def _apply_nograd_impl(name: str, fn: Callable, *inputs: Tensor):
    OpStats.record(name)
    arrays = [t._array for t in inputs]
    out = fn(*arrays)
    return _wrap_outputs(out, None, False, op_name=name)


def apply_with_cpu_fallback(apply_fn: Callable, name: str, fn: Callable,
                            t: Tensor, supported: Callable[[], bool],
                            complex_stays_on_cpu: bool = False):
    """apply()/apply_nograd() with an eager CPU hop on backends missing a
    capability (`supported()` False) — used by fft (no complex buffers on
    the axon tunnel) and cpp_extension (no host callbacks there).

    Concrete inputs move to the CPU backend around the op — inside
    jax.default_device(cpu) so internal constants are created CPU-side —
    and real results rejoin the accelerator (device_put transfers are
    differentiable: jax transposes them, so gradients land back on the
    original device). Under a jit trace there is no fallback: the op
    lowers natively and an unsupported backend fails loudly rather than
    silently degrading."""
    if isinstance(t._array, jax.core.Tracer) or supported():
        return apply_fn(name, fn, t)
    try:
        cpu = jax.devices("cpu")[0]
    except Exception:  # no cpu plugin in this config: lower natively
        return apply_fn(name, fn, t)
    try:
        dev = next(iter(t._array.devices()))
    except Exception:
        dev = None

    def hop(a):
        with jax.default_device(cpu):
            out = fn(jax.device_put(a, cpu))
        if dev is None or (complex_stays_on_cpu and
                           jnp.issubdtype(out.dtype, jnp.complexfloating)):
            # a backend without complex buffers can't take the result
            # back; chained transforms keep working on CPU and rejoin at
            # the first real-valued output
            return out
        return jax.device_put(out, dev)

    return apply_fn(name, hop, t)
