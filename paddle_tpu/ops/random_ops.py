"""Random sampling ops — analog of python/paddle/tensor/random.py.

Every op draws a fresh subkey from the global Generator (core/random.py),
the functional analog of the reference's stateful Philox generator
(paddle/phi/core/generator.h:23).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from paddle_tpu.core import dtype as dtypes
from paddle_tpu.core.random import next_key
from paddle_tpu.core.tensor import Tensor

from .creation import _shape_tuple
from .dispatch import apply_nograd, as_tensor

__all__ = [
    "rand", "randn", "randint", "randint_like", "randperm", "uniform",
    "normal", "standard_normal", "bernoulli", "multinomial", "poisson",
    "exponential", "shuffle", "uniform_", "normal_",
]


def rand(shape, dtype=None):
    return uniform(shape, dtype=dtype, min=0.0, max=1.0)


def randn(shape, dtype=None):
    d = dtypes.to_jax(dtype)
    return Tensor._wrap(jax.random.normal(next_key(), _shape_tuple(shape), d))


def standard_normal(shape, dtype=None):
    return randn(shape, dtype)


def uniform(shape, dtype=None, min=-1.0, max=1.0, seed=0):
    d = dtypes.to_jax(dtype)
    return Tensor._wrap(
        jax.random.uniform(next_key(), _shape_tuple(shape), d, minval=min, maxval=max)
    )


def normal(mean=0.0, std=1.0, shape=None):
    if isinstance(mean, Tensor) or isinstance(std, Tensor):
        m = mean._array if isinstance(mean, Tensor) else mean
        s = std._array if isinstance(std, Tensor) else std
        shp = m.shape if hasattr(m, "shape") else s.shape
        return Tensor._wrap(m + s * jax.random.normal(next_key(), shp))
    d = dtypes.to_jax(None)
    return Tensor._wrap(
        mean + std * jax.random.normal(next_key(), _shape_tuple(shape), d)
    )


def randint(low=0, high=None, shape=(1,), dtype="int64"):
    if high is None:
        low, high = 0, low
    d = dtypes.to_jax(dtype)
    return Tensor._wrap(
        jax.random.randint(next_key(), _shape_tuple(shape), low, high, d)
    )


def randint_like(x, low=0, high=None, dtype=None):
    x = as_tensor(x)
    return randint(low, high, tuple(x.shape), dtype or x.dtype)


def randperm(n, dtype="int64"):
    return Tensor._wrap(
        jax.random.permutation(next_key(), n).astype(dtypes.to_jax(dtype))
    )


def bernoulli(x):
    x = as_tensor(x)
    key = next_key()
    return apply_nograd(
        "bernoulli", lambda a: jax.random.bernoulli(key, a).astype(a.dtype), x
    )


def multinomial(x, num_samples=1, replacement=False):
    x = as_tensor(x)
    key = next_key()

    def fn(a):
        logits = jnp.log(jnp.maximum(a, 1e-30))
        if replacement:
            return jax.random.categorical(
                key, logits, axis=-1, shape=(num_samples,) + a.shape[:-1]
            ).T if a.ndim > 1 else jax.random.categorical(
                key, logits, shape=(num_samples,))
        # without replacement: Gumbel top-k trick
        g = jax.random.gumbel(key, a.shape)
        _, idx = jax.lax.top_k(logits + g, num_samples)
        return idx

    out = apply_nograd("multinomial", fn, x)
    return out


def poisson(x):
    x = as_tensor(x)
    key = next_key()
    return apply_nograd(
        "poisson", lambda a: jax.random.poisson(key, a).astype(a.dtype), x
    )


def exponential(x, lam=1.0):
    x = as_tensor(x)
    key = next_key()
    return apply_nograd(
        "exponential",
        lambda a: (jax.random.exponential(key, a.shape, a.dtype) / lam),
        x,
    )


def shuffle(x, axis=0):
    x = as_tensor(x)
    key = next_key()
    return apply_nograd(
        "shuffle", lambda a: jax.random.permutation(key, a, axis=axis), x
    )


def uniform_(x, min=-1.0, max=1.0):
    x._mutate(jax.random.uniform(
        next_key(), x._array.shape, x._array.dtype, minval=min, maxval=max
    ))
    return x


def normal_(x, mean=0.0, std=1.0):
    x._mutate(mean + std * jax.random.normal(next_key(), x._array.shape, x._array.dtype))
    return x
