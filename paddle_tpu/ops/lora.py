"""Batched paged-LoRA apply — the op tier under the multi-tenant
adapter subsystem (paddle_tpu/adapters/).

S-LoRA / Punica-style batched low-rank updates, XLA edition: every
serving slot may carry a DIFFERENT tenant adapter, and one compiled
step serves any tenant mix. The adapter weights live in a paged
on-device pool (`adapters.PagedAdapterPool` — same block/refcount/LRU
story as the paged KV cache), stacked per target site:

- `a_<site>`: `[pages, layers, max_rank, in_dim]` — the LoRA A factors
  (rank-major, rank-padded with EXACT zeros past each adapter's rank);
- `b_<site>`: `[pages, layers, max_rank, ...out layout]` — the B
  factors in the layout the base matmul's output takes (`b_qkv` is
  head-grouped `[.., heads, 3, head_dim]` so it shards on the heads
  axis exactly like the engine's `_tp_plan` qkv weight; the linear
  sites' `[.., out]` shard their output columns);
- `scaling`: `[pages]` f32 — each adapter's `alpha / rank` factor.

`LoraState` is the traced-side view one compiled engine step holds: the
pool arrays plus a `[slots]` int32 page row (the per-slot adapter page,
resolved host-side from adapter ids by the pool). Page 0 is the NULL
adapter: all-zero factors and zero scaling, so a base-model slot's
delta is EXACTLY zero (`base + 0.0` — adapter id 0 stays bit-identical
to an engine with no adapter subsystem at all). Rank padding works the
same way: a rank-r adapter's rows past r are exact zeros, so ONE trace
shape (`max_rank`) serves every rank without masks or per-rank
programs.

Numerics: both einsums of the delta (`x . A^T` then `. B^T`) pin fp32
accumulation (`preferred_element_type`), the per-slot scaling is
applied in fp32, and the result is cast to the activation dtype ONCE —
the same policy as the paged-attention PV accumulation. No collectives
at any mp: A rides replicated against the full-length activation, B is
output-column-sharded, so each shard computes exactly its own slice of
the delta and the existing all-gathers reassemble base + delta
together.
"""
from __future__ import annotations

import jax.numpy as jnp

from .dispatch import apply, as_tensor

__all__ = ["LORA_SITES", "LoraState", "lora_linear_delta",
           "lora_qkv_delta"]

#: The base-model matmuls an adapter may target, in pool-array order.
#: (qkv/out are the attention projections, fc1/fc2 the MLP — the four
#: per-step weight reads the serving engine's int8 weight path also
#: targets.)
LORA_SITES = ("qkv", "out", "fc1", "fc2")


def lora_linear_delta(x, a, b, rows, scaling, layer):
    """Per-slot low-rank delta for one linear site, one layer.

    x: `[B, S, in]` — the SAME activation the base matmul consumes.
    a: `[pages, layers, max_rank, in]`; b: `[pages, layers, max_rank,
    out]` (out may be the per-shard column count under mp).
    rows: `[B]` int32 adapter-pool page per slot (0 = null adapter).
    scaling: `[pages]` f32. layer: python int (static).

    Returns `[B, S, out]` in x.dtype: `(x . A^T . B^T) * scaling`,
    fp32-accumulated, exact zeros for null/rank-padded rows."""
    x, a, b = as_tensor(x), as_tensor(a), as_tensor(b)
    rows, scaling = as_tensor(rows), as_tensor(scaling)

    def fn(xa, av, bv, rw, sc):
        al = av[rw, layer]                         # [B, R, in]
        bl = bv[rw, layer]                         # [B, R, out]
        s = sc[rw].astype(jnp.float32)             # [B]
        xr = jnp.einsum("bsi,bri->bsr", xa, al,
                        preferred_element_type=jnp.float32)
        d = jnp.einsum("bsr,bro->bso", xr, bl,
                       preferred_element_type=jnp.float32)
        return (d * s[:, None, None]).astype(xa.dtype)

    return apply("lora_linear_delta", fn, x, a, b, rows, scaling)


def lora_qkv_delta(x, a, b, rows, scaling, layer, head_major):
    """The qkv site's delta, in the layout the base qkv projection
    takes: b is head-grouped `[pages, layers, max_rank, heads, 3, D]`
    (per-shard heads under mp). `head_major=True` returns
    `[B, S, heads, 3, D]` (the sharded `_qkv_heads` layout),
    False returns `[B, S, 3, heads, D]` (the unsharded reshape)."""
    x, a, b = as_tensor(x), as_tensor(a), as_tensor(b)
    rows, scaling = as_tensor(rows), as_tensor(scaling)
    out = "bshtd" if head_major else "bsthd"

    def fn(xa, av, bv, rw, sc):
        al = av[rw, layer]                         # [B, R, H]
        bl = bv[rw, layer]                         # [B, R, heads, 3, D]
        s = sc[rw].astype(jnp.float32)
        xr = jnp.einsum("bsi,bri->bsr", xa, al,
                        preferred_element_type=jnp.float32)
        d = jnp.einsum(f"bsr,brhtd->{out}", xr, bl,
                       preferred_element_type=jnp.float32)
        return (d * s[:, None, None, None, None]).astype(xa.dtype)

    return apply("lora_qkv_delta", fn, x, a, b, rows, scaling)


class LoraState:
    """One compiled step's view of the adapter pool: the pool arrays
    (traced args, in `adapters.adapter_pool_spec` order) plus the
    per-slot `[B]` page row. Built INSIDE the step body; the model's
    forward paths call the delta methods per layer and add the result
    to the base matmul's output."""

    def __init__(self, arrays, rows):
        (self.a_qkv, self.b_qkv, self.a_out, self.b_out,
         self.a_fc1, self.b_fc1, self.a_fc2, self.b_fc2,
         self.scaling) = arrays
        self.rows = rows

    def qkv_delta(self, x, layer, head_major):
        return lora_qkv_delta(x, self.a_qkv, self.b_qkv, self.rows,
                              self.scaling, layer, head_major)

    def linear_delta(self, site, x, layer):
        a, b = {"out": (self.a_out, self.b_out),
                "fc1": (self.a_fc1, self.b_fc1),
                "fc2": (self.a_fc2, self.b_fc2)}[site]
        return lora_linear_delta(x, a, b, self.rows, self.scaling,
                                 layer)
