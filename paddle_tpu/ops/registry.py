"""Op registry over ops.yaml — the runtime side of the op schema
(analog of the PHI yaml op system, paddle/phi/api/yaml/ + generator;
SURVEY §2 item 6). Where the reference generates C++ API/GradNode/
bindings from yaml, here jax.vjp already provides kernel+VJP and Python
IS the binding — so the yaml's runtime authority is the parts codegen
can't subsume: the op inventory (tooling, docs, drift tests) and the
AMP white/black policy consumed by amp.auto_cast at import.
"""
from __future__ import annotations

import functools
import os

__all__ = ["all_ops", "get", "search", "amp_white", "amp_black"]


@functools.lru_cache(maxsize=1)
def _load():
    import yaml

    path = os.path.join(os.path.dirname(__file__), "ops.yaml")
    with open(path) as f:
        doc = yaml.safe_load(f)
    return doc


@functools.lru_cache(maxsize=1)
def all_ops():
    """List of op entries: {op, module, signature, tensor_method, amp}."""
    return list(_load()["ops"])


@functools.lru_cache(maxsize=1)
def _by_name():
    return {e["op"]: e for e in all_ops()}


def get(name):
    return _by_name().get(name)


def search(pattern):
    """Substring search over op names: registry.search('conv')."""
    p = pattern.lower()
    return [e for e in all_ops() if p in e["op"].lower()]


def _amp(category):
    """Tolerant of hand-edited entries: a missing amp key means 'none',
    a missing amp_extra section means empty — one malformed entry must
    not wholesale invalidate the schema."""
    doc = _load()
    names = frozenset(e["op"] for e in doc.get("ops", [])
                      if e.get("amp") == category)
    extra = doc.get("amp_extra", {}) or {}
    return names | frozenset(extra.get(category, []) or [])


@functools.lru_cache(maxsize=1)
def amp_white():
    return _amp("white")


@functools.lru_cache(maxsize=1)
def amp_black():
    return _amp("black")
