"""Activation ops — analogs of paddle/phi/kernels/activation_kernel.* and
python/paddle/nn/functional/activation.py. All are single fused jax fns;
XLA folds them into adjacent matmuls on TPU.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .dispatch import apply, as_tensor

__all__ = [
    "relu", "relu6", "leaky_relu", "elu", "selu", "celu", "gelu", "silu",
    "swish", "sigmoid", "hardsigmoid", "hardswish", "hardtanh", "hardshrink",
    "softshrink", "tanhshrink", "softplus", "softsign", "mish", "prelu",
    "log_sigmoid", "softmax", "log_softmax", "gumbel_softmax", "maxout",
    "glu", "tanh",
    "thresholded_relu", "rrelu",
]


def _unary(name, fn):
    def op(x, *args, **kwargs):
        x = as_tensor(x)
        return apply(name, lambda a: fn(a, *args, **kwargs), x)

    op.__name__ = name
    return op


relu = _unary("relu", jax.nn.relu)
relu6 = _unary("relu6", jax.nn.relu6)
sigmoid = _unary("sigmoid", jax.nn.sigmoid)
silu = _unary("silu", jax.nn.silu)
softsign = _unary("softsign", jax.nn.soft_sign)
log_sigmoid = _unary("log_sigmoid", jax.nn.log_sigmoid)
tanh = _unary("tanh", jnp.tanh)
mish = _unary("mish", lambda a: a * jnp.tanh(jax.nn.softplus(a)))


def leaky_relu(x, negative_slope=0.01):
    x = as_tensor(x)
    return apply("leaky_relu", lambda a: jax.nn.leaky_relu(a, negative_slope), x)


def elu(x, alpha=1.0):
    x = as_tensor(x)
    return apply("elu", lambda a: jax.nn.elu(a, alpha), x)


def selu(x, scale=1.0507009873554805, alpha=1.6732632423543772):
    x = as_tensor(x)
    return apply("selu", lambda a: scale * jnp.where(a > 0, a, alpha * jnp.expm1(a)), x)


def celu(x, alpha=1.0):
    x = as_tensor(x)
    return apply("celu", lambda a: jax.nn.celu(a, alpha), x)


def gelu(x, approximate=False):
    x = as_tensor(x)
    return apply("gelu", lambda a: jax.nn.gelu(a, approximate=approximate), x)


def swish(x):
    return silu(x)


def hardsigmoid(x, slope=1.0 / 6, offset=0.5):
    x = as_tensor(x)
    return apply("hardsigmoid", lambda a: jnp.clip(slope * a + offset, 0.0, 1.0), x)


def hardswish(x):
    x = as_tensor(x)
    return apply("hardswish", lambda a: a * jnp.clip(a + 3.0, 0.0, 6.0) / 6.0, x)


def hardtanh(x, min=-1.0, max=1.0):
    x = as_tensor(x)
    return apply("hardtanh", lambda a: jnp.clip(a, min, max), x)


def hardshrink(x, threshold=0.5):
    x = as_tensor(x)
    return apply(
        "hardshrink", lambda a: jnp.where(jnp.abs(a) > threshold, a, 0.0), x
    )


def softshrink(x, threshold=0.5):
    x = as_tensor(x)
    return apply(
        "softshrink",
        lambda a: jnp.where(a > threshold, a - threshold,
                            jnp.where(a < -threshold, a + threshold, 0.0)),
        x,
    )


def tanhshrink(x):
    x = as_tensor(x)
    return apply("tanhshrink", lambda a: a - jnp.tanh(a), x)


def softplus(x, beta=1.0, threshold=20.0):
    x = as_tensor(x)
    return apply(
        "softplus",
        lambda a: jnp.where(beta * a > threshold, a, jax.nn.softplus(beta * a) / beta),
        x,
    )


def prelu(x, weight):
    x, weight = as_tensor(x), as_tensor(weight)

    def fn(a, w):
        if w.size == 1:
            return jnp.where(a > 0, a, w.reshape(()) * a)
        # channel-wise (NCHW): broadcast weight over spatial dims
        shape = [1] * a.ndim
        shape[1] = w.size
        return jnp.where(a > 0, a, w.reshape(shape) * a)

    return apply("prelu", fn, x, weight)


def softmax(x, axis=-1, dtype=None):
    from paddle_tpu.core import dtype as dtypes

    x = as_tensor(x)

    def fn(a):
        if dtype is not None:
            a = a.astype(dtypes.to_jax(dtype))
        return jax.nn.softmax(a, axis=axis)

    return apply("softmax", fn, x)


def log_softmax(x, axis=-1):
    x = as_tensor(x)
    return apply("log_softmax", lambda a: jax.nn.log_softmax(a, axis=axis), x)


def gumbel_softmax(x, temperature=1.0, hard=False, axis=-1):
    from paddle_tpu.core.random import next_key

    x = as_tensor(x)
    key = next_key()

    def fn(a):
        g = jax.random.gumbel(key, a.shape, a.dtype)
        y = jax.nn.softmax((a + g) / temperature, axis=axis)
        if hard:
            idx = jnp.argmax(y, axis=axis, keepdims=True)
            y_hard = jnp.zeros_like(y)
            y_hard = jnp.put_along_axis(y_hard, idx, 1.0, axis=axis, inplace=False)
            y = y_hard - jax.lax.stop_gradient(y) + y
        return y

    return apply("gumbel_softmax", fn, x)


def maxout(x, groups, axis=1):
    x = as_tensor(x)

    def fn(a):
        shape = list(a.shape)
        c = shape[axis]
        shape[axis:axis + 1] = [c // groups, groups]
        return jnp.max(a.reshape(shape), axis=axis + 1)

    return apply("maxout", fn, x)


def glu(x, axis=-1):
    x = as_tensor(x)
    return apply("glu", lambda a: jax.nn.glu(a, axis=axis), x)


def thresholded_relu(x, threshold=1.0, name=None):
    x = as_tensor(x)
    return apply("thresholded_relu",
                 lambda a: jnp.where(a > threshold, a, 0.0), x)


def rrelu(x, lower=1.0 / 8.0, upper=1.0 / 3.0, training=True, name=None):
    """Randomized leaky relu: train draws the negative slope uniformly
    per element; eval uses the mean slope (functional/activation.py)."""
    from paddle_tpu.core import random as random_mod

    x = as_tensor(x)
    if not training:
        mid = (lower + upper) / 2.0
        return apply("rrelu",
                     lambda a: jnp.where(a >= 0, a, mid * a), x)
    from paddle_tpu.ops.nn_ops import _warn_if_constant_key

    _warn_if_constant_key(x._array, "rrelu")
    key = random_mod.next_key()

    def fn(a):
        slope = jax.random.uniform(key, a.shape, minval=lower,
                                   maxval=upper).astype(a.dtype)
        return jnp.where(a >= 0, a, slope * a)

    return apply("rrelu", fn, x)
