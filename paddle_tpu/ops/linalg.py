"""Linear algebra ops — analog of python/paddle/tensor/linalg.py.

matmul is THE op on TPU: it maps onto the 128x128 MXU systolic array. We
request bf16-friendly `preferred_element_type` so mixed-precision
accumulation stays fp32 even when activations are bf16.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from paddle_tpu.core.tensor import Tensor

from .dispatch import apply, as_tensor

__all__ = [
    "matmul", "mm", "bmm", "dot", "outer", "inner", "t", "norm", "dist",
    "cross", "cholesky", "inverse", "pinv", "solve", "triangular_solve",
    "svd", "qr", "eigh", "det", "slogdet", "matrix_power", "trace",
    "diagonal", "kron", "mv", "histogram",
    "einsum", "baddbmm", "renorm", "corrcoef", "cov",
]


def matmul(x, y, transpose_x=False, transpose_y=False):
    x, y = as_tensor(x), as_tensor(y)

    def fn(a, b):
        if transpose_x:
            a = jnp.swapaxes(a, -1, -2) if a.ndim >= 2 else a
        if transpose_y:
            b = jnp.swapaxes(b, -1, -2) if b.ndim >= 2 else b
        # accumulate in fp32 on the MXU regardless of input precision
        pet = jnp.float32 if jnp.issubdtype(a.dtype, jnp.floating) else None
        out = jnp.matmul(a, b, preferred_element_type=pet)
        return out.astype(jnp.promote_types(a.dtype, b.dtype)) if pet else out

    return apply("matmul", fn, x, y)


def mm(x, y):
    return matmul(x, y)


def bmm(x, y):
    return matmul(x, y)


def dot(x, y):
    x, y = as_tensor(x), as_tensor(y)
    return apply("dot", lambda a, b: jnp.sum(a * b, axis=-1), x, y)


def outer(x, y):
    x, y = as_tensor(x), as_tensor(y)
    return apply("outer", lambda a, b: jnp.outer(a, b), x, y)


def inner(x, y):
    x, y = as_tensor(x), as_tensor(y)
    return apply("inner", lambda a, b: jnp.inner(a, b), x, y)


def t(x):
    x = as_tensor(x)
    if x.ndim < 2:
        from .manipulation import clone

        return clone(x)
    return apply("t", lambda a: jnp.swapaxes(a, -1, -2), x)


def mv(x, vec):
    x, vec = as_tensor(x), as_tensor(vec)
    return apply("mv", lambda a, v: jnp.matmul(a, v), x, vec)


def norm(x, p="fro", axis=None, keepdim=False):
    x = as_tensor(x)

    def fn(a):
        if p == "fro" or (p == 2 and axis is None):
            return jnp.sqrt(jnp.sum(jnp.square(a), axis=axis, keepdims=keepdim))
        if p == float("inf"):
            return jnp.max(jnp.abs(a), axis=axis, keepdims=keepdim)
        if p == float("-inf"):
            return jnp.min(jnp.abs(a), axis=axis, keepdims=keepdim)
        if p == 0:
            return jnp.sum((a != 0).astype(a.dtype), axis=axis, keepdims=keepdim)
        if p == 1:
            return jnp.sum(jnp.abs(a), axis=axis, keepdims=keepdim)
        return jnp.power(
            jnp.sum(jnp.power(jnp.abs(a), p), axis=axis, keepdims=keepdim), 1.0 / p
        )

    return apply("norm", fn, x)


def dist(x, y, p=2):
    from .math import subtract

    return norm(subtract(x, y), p=float(p) if p != 2 else 2)


def cross(x, y, axis=-1):
    x, y = as_tensor(x), as_tensor(y)
    return apply("cross", lambda a, b: jnp.cross(a, b, axis=axis), x, y)


def cholesky(x, upper=False):
    x = as_tensor(x)

    def fn(a):
        L = jnp.linalg.cholesky(a)
        return jnp.swapaxes(L, -1, -2) if upper else L

    return apply("cholesky", fn, x)


def inverse(x):
    x = as_tensor(x)
    return apply("inverse", lambda a: jnp.linalg.inv(a), x)


def pinv(x, rcond=1e-15):
    x = as_tensor(x)
    return apply("pinv", lambda a: jnp.linalg.pinv(a, rtol=rcond), x)


def solve(x, y):
    x, y = as_tensor(x), as_tensor(y)
    return apply("solve", lambda a, b: jnp.linalg.solve(a, b), x, y)


def triangular_solve(x, y, upper=True, transpose=False, unitriangular=False):
    x, y = as_tensor(x), as_tensor(y)

    def fn(a, b):
        return jax.scipy.linalg.solve_triangular(
            a, b, lower=not upper, trans=1 if transpose else 0,
            unit_diagonal=unitriangular,
        )

    return apply("triangular_solve", fn, x, y)


def svd(x, full_matrices=False):
    x = as_tensor(x)
    return apply("svd", lambda a: jnp.linalg.svd(a, full_matrices=full_matrices), x)


def qr(x, mode="reduced"):
    x = as_tensor(x)
    return apply("qr", lambda a: jnp.linalg.qr(a, mode=mode), x)


def eigh(x, UPLO="L"):
    x = as_tensor(x)
    return apply("eigh", lambda a: jnp.linalg.eigh(a, UPLO=UPLO), x)


def det(x):
    x = as_tensor(x)
    return apply("det", lambda a: jnp.linalg.det(a), x)


def slogdet(x):
    x = as_tensor(x)
    return apply("slogdet", lambda a: tuple(jnp.linalg.slogdet(a)), x)


def matrix_power(x, n):
    x = as_tensor(x)
    return apply("matrix_power", lambda a: jnp.linalg.matrix_power(a, n), x)


def trace(x, offset=0, axis1=0, axis2=1):
    x = as_tensor(x)
    return apply("trace", lambda a: jnp.trace(a, offset, axis1, axis2), x)


def diagonal(x, offset=0, axis1=0, axis2=1):
    x = as_tensor(x)
    return apply("diagonal", lambda a: jnp.diagonal(a, offset, axis1, axis2), x)


def kron(x, y):
    x, y = as_tensor(x), as_tensor(y)
    return apply("kron", lambda a, b: jnp.kron(a, b), x, y)


def histogram(x, bins=100, min=0, max=0):
    from .dispatch import apply_nograd

    x = as_tensor(x)
    lo, hi = (None, None) if (min == 0 and max == 0) else (min, max)

    def fn(a):
        rng = (lo, hi) if lo is not None else (a.min(), a.max())
        h, _ = jnp.histogram(a, bins=bins, range=rng)
        return h

    return apply_nograd("histogram", fn, x)


def einsum(equation, *operands, name=None):
    """paddle.einsum — one MXU-friendly contraction (XLA lowers einsum
    straight to dot_general chains)."""
    ts = [as_tensor(o) for o in operands]
    return apply("einsum", lambda *arrs: jnp.einsum(equation, *arrs), *ts)


def baddbmm(input, x, y, beta=1.0, alpha=1.0, name=None):
    """beta*input + alpha*(x @ y) batched (paddle baddbmm)."""
    i, x, y = as_tensor(input), as_tensor(x), as_tensor(y)
    return apply("baddbmm",
                 lambda a, b, c: beta * a + alpha *
                 jnp.matmul(b, c), i, x, y)


def renorm(x, p, axis, max_norm, name=None):
    """Clamp each slice along `axis` to p-norm <= max_norm."""
    x = as_tensor(x)

    def fn(a):
        red = tuple(i for i in range(a.ndim) if i != axis % a.ndim)
        norms = jnp.sum(jnp.abs(a) ** p, axis=red, keepdims=True) \
            ** (1.0 / p)
        factor = jnp.where(norms > max_norm, max_norm / (norms + 1e-7),
                           1.0)
        return a * factor

    return apply("renorm", fn, x)


def corrcoef(x, rowvar=True, name=None):
    x = as_tensor(x)
    return apply("corrcoef", lambda a: jnp.corrcoef(a, rowvar=rowvar), x)


def cov(x, rowvar=True, ddof=True, fweights=None, aweights=None,
        name=None):
    x = as_tensor(x)
    fw = None if fweights is None else \
        (fweights._array if isinstance(fweights, Tensor)
         else jnp.asarray(fweights))
    aw = None if aweights is None else \
        (aweights._array if isinstance(aweights, Tensor)
         else jnp.asarray(aweights))
    return apply("cov",
                 lambda a: jnp.cov(a, rowvar=rowvar,
                                   ddof=1 if ddof else 0,
                                   fweights=fw, aweights=aw), x)
