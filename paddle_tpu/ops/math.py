"""Elementwise / binary math ops — analog of python/paddle/tensor/math.py.

Each op is a pure jax fn passed through dispatch.apply; XLA fuses chains
of these into single kernels when run under jit, and the VJPs come from
jax.vjp instead of hand-written grad kernels
(cf. paddle/phi/kernels/elementwise_*).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from paddle_tpu.core.tensor import Tensor

from .dispatch import apply, apply_nograd, as_tensor

__all__ = [
    "add", "subtract", "multiply", "divide", "floor_divide", "mod", "pow",
    "maximum", "minimum", "fmax", "fmin", "atan2",
    "exp", "log", "log2", "log10", "log1p", "expm1", "sqrt", "rsqrt",
    "abs", "neg", "sign", "floor", "ceil", "round", "trunc", "reciprocal",
    "sin", "cos", "tan", "asin", "acos", "atan", "sinh", "cosh", "tanh",
    "asinh", "acosh", "atanh", "erf", "erfinv", "square",
    "clip", "scale", "lerp", "addmm",
    "equal", "not_equal", "less_than", "less_equal", "greater_than",
    "greater_equal", "logical_and", "logical_or", "logical_not", "logical_xor",
    "isnan", "isinf", "isfinite", "bitwise_and", "bitwise_or", "bitwise_xor",
    "bitwise_not", "where", "cast", "increment", "stanh", "multiplex",
    "nan_to_num",
    "frac", "sinc", "signbit", "digamma", "lgamma", "i0", "angle", "real",
    "imag", "conj", "sgn", "logit", "polygamma", "copysign", "nextafter",
    "heaviside", "hypot", "logaddexp", "fmod", "remainder", "true_divide",
    "float_power", "isclose", "allclose", "equal_all", "multiply_",
]


def _binary(name, fn):
    def op(x, y):
        if not isinstance(x, Tensor):
            x = as_tensor(x, y if isinstance(y, Tensor) else None)
        y = as_tensor(y, x)
        xa, ya = x._array, y._array
        # match dtypes (paddle promotes to the "higher" dtype)
        if xa.dtype != ya.dtype:
            common = jnp.promote_types(xa.dtype, ya.dtype)
            return apply(name, lambda a, b: fn(a.astype(common), b.astype(common)), x, y)
        return apply(name, fn, x, y)

    op.__name__ = name
    return op


def _binary_nograd(name, fn):
    def op(x, y):
        if not isinstance(x, Tensor):
            x = as_tensor(x, y if isinstance(y, Tensor) else None)
        y = as_tensor(y, x)
        return apply_nograd(name, fn, x, y)

    op.__name__ = name
    return op


def _unary(opname, fn, nograd=False):
    ap = apply_nograd if nograd else apply

    def op(x, name=None):
        x = as_tensor(x)
        return ap(opname, fn, x)

    op.__name__ = opname
    return op


add = _binary("add", jnp.add)
subtract = _binary("subtract", jnp.subtract)
multiply = _binary("multiply", jnp.multiply)
divide = _binary("divide", lambda a, b: jnp.divide(a, b))
floor_divide = _binary_nograd("floor_divide", jnp.floor_divide)
mod = _binary("mod", jnp.mod)
pow = _binary("pow", jnp.power)
maximum = _binary("maximum", jnp.maximum)
minimum = _binary("minimum", jnp.minimum)
fmax = _binary("fmax", jnp.fmax)
fmin = _binary("fmin", jnp.fmin)
atan2 = _binary("atan2", jnp.arctan2)

exp = _unary("exp", jnp.exp)
log = _unary("log", jnp.log)
log2 = _unary("log2", jnp.log2)
log10 = _unary("log10", jnp.log10)
log1p = _unary("log1p", jnp.log1p)
expm1 = _unary("expm1", jnp.expm1)
sqrt = _unary("sqrt", jnp.sqrt)
rsqrt = _unary("rsqrt", lambda a: jax.lax.rsqrt(a))
abs = _unary("abs", jnp.abs)
neg = _unary("neg", jnp.negative)
sign = _unary("sign", jnp.sign)
floor = _unary("floor", jnp.floor)
ceil = _unary("ceil", jnp.ceil)
round = _unary("round", jnp.round)
trunc = _unary("trunc", jnp.trunc)
reciprocal = _unary("reciprocal", jnp.reciprocal)
sin = _unary("sin", jnp.sin)
cos = _unary("cos", jnp.cos)
tan = _unary("tan", jnp.tan)
asin = _unary("asin", jnp.arcsin)
acos = _unary("acos", jnp.arccos)
atan = _unary("atan", jnp.arctan)
sinh = _unary("sinh", jnp.sinh)
cosh = _unary("cosh", jnp.cosh)
tanh = _unary("tanh", jnp.tanh)
asinh = _unary("asinh", jnp.arcsinh)
acosh = _unary("acosh", jnp.arccosh)
atanh = _unary("atanh", jnp.arctanh)
erf = _unary("erf", jax.scipy.special.erf)
erfinv = _unary("erfinv", jax.scipy.special.erfinv)
square = _unary("square", jnp.square)


def clip(x, min=None, max=None):
    x = as_tensor(x)
    lo = min._array if isinstance(min, Tensor) else min
    hi = max._array if isinstance(max, Tensor) else max
    return apply("clip", lambda a: jnp.clip(a, lo, hi), x)


def scale(x, scale=1.0, bias=0.0, bias_after_scale=True, act=None):
    x = as_tensor(x)
    s, b = float(scale), float(bias)
    if bias_after_scale:
        out = apply("scale", lambda a: a * s + b, x)
    else:
        out = apply("scale", lambda a: (a + b) * s, x)
    if act is not None:
        from . import activation

        out = getattr(activation, act)(out)
    return out


def lerp(x, y, weight):
    x, y = as_tensor(x), as_tensor(y)
    w = weight._array if isinstance(weight, Tensor) else weight
    return apply("lerp", lambda a, b: a + w * (b - a), x, y)


def addmm(input, x, y, beta=1.0, alpha=1.0):
    input, x, y = as_tensor(input), as_tensor(x), as_tensor(y)
    return apply(
        "addmm", lambda i, a, b: beta * i + alpha * jnp.matmul(a, b), input, x, y
    )


equal = _binary_nograd("equal", jnp.equal)
not_equal = _binary_nograd("not_equal", jnp.not_equal)
less_than = _binary_nograd("less_than", jnp.less)
less_equal = _binary_nograd("less_equal", jnp.less_equal)
greater_than = _binary_nograd("greater_than", jnp.greater)
greater_equal = _binary_nograd("greater_equal", jnp.greater_equal)
logical_and = _binary_nograd("logical_and", jnp.logical_and)
logical_or = _binary_nograd("logical_or", jnp.logical_or)
logical_xor = _binary_nograd("logical_xor", jnp.logical_xor)
bitwise_and = _binary_nograd("bitwise_and", jnp.bitwise_and)
bitwise_or = _binary_nograd("bitwise_or", jnp.bitwise_or)
bitwise_xor = _binary_nograd("bitwise_xor", jnp.bitwise_xor)


def logical_not(x):
    return apply_nograd("logical_not", jnp.logical_not, as_tensor(x))


def bitwise_not(x):
    return apply_nograd("bitwise_not", jnp.bitwise_not, as_tensor(x))


def isnan(x):
    return apply_nograd("isnan", jnp.isnan, as_tensor(x))


def isinf(x):
    return apply_nograd("isinf", jnp.isinf, as_tensor(x))


def isfinite(x):
    return apply_nograd("isfinite", jnp.isfinite, as_tensor(x))


def where(condition, x=None, y=None):
    if x is None and y is None:
        arr = condition._array if isinstance(condition, Tensor) else jnp.asarray(condition)
        return tuple(Tensor._wrap(i) for i in jnp.nonzero(arr))
    cond = condition._array if isinstance(condition, Tensor) else jnp.asarray(condition)
    x, y = as_tensor(x), as_tensor(y, x)
    return apply("where", lambda a, b: jnp.where(cond, a, b), x, y)


def cast(x, dtype):
    from paddle_tpu.core import dtype as dtypes

    x = as_tensor(x)
    jd = dtypes.to_jax(dtype)
    if jnp.issubdtype(jd, jnp.inexact) and jnp.issubdtype(x._array.dtype, jnp.inexact):
        return apply("cast", lambda a: a.astype(jd), x)
    return apply_nograd("cast", lambda a: a.astype(jd), x)


def increment(x, value=1.0):
    x._mutate(x._array + value)
    return x


def stanh(x, scale_a=0.67, scale_b=1.7159):
    x = as_tensor(x)
    return apply("stanh", lambda a: scale_b * jnp.tanh(scale_a * a), x)


def multiplex(inputs, index):
    idx = index._array if isinstance(index, Tensor) else jnp.asarray(index)
    idx = idx.reshape(-1)
    ts = [as_tensor(t) for t in inputs]

    def fn(*arrs):
        stacked = jnp.stack(arrs, axis=0)  # [n, batch, ...]
        return jnp.take_along_axis(
            stacked, idx.reshape(1, -1, *([1] * (stacked.ndim - 2))), axis=0
        )[0]

    return apply("multiplex", fn, *ts)


def nan_to_num(x, nan=0.0, posinf=None, neginf=None):
    x = as_tensor(x)
    return apply(
        "nan_to_num", lambda a: jnp.nan_to_num(a, nan=nan, posinf=posinf, neginf=neginf), x
    )


# -- special functions / complex / residual elementwise parity ----------
frac = _unary("frac", lambda a: a - jnp.trunc(a))
sinc = _unary("sinc", jnp.sinc)
signbit = _unary("signbit", jnp.signbit, nograd=True)
digamma = _unary("digamma", lambda a: jax.scipy.special.digamma(a))
lgamma = _unary("lgamma", lambda a: jax.scipy.special.gammaln(a))
i0 = _unary("i0", lambda a: jax.scipy.special.i0(a))
angle = _unary("angle", jnp.angle)
real = _unary("real", jnp.real)
imag = _unary("imag", jnp.imag)
conj = _unary("conj", jnp.conj)


def sgn(x, name=None):
    """sign for real; x/|x| (0 -> 0) for complex (paddle sgn)."""
    x = as_tensor(x)

    def fn(a):
        if jnp.issubdtype(a.dtype, jnp.complexfloating):
            m = jnp.abs(a)
            return jnp.where(m == 0, 0.0 + 0.0j, a / jnp.where(m == 0, 1, m))
        return jnp.sign(a)

    return apply("sgn", fn, x)


def logit(x, eps=None, name=None):
    x = as_tensor(x)

    def fn(a):
        p = a if eps is None else jnp.clip(a, eps, 1.0 - eps)
        return jnp.log(p / (1.0 - p))

    return apply("logit", fn, x)


def polygamma(x, n, name=None):
    x = as_tensor(x)
    return apply("polygamma",
                 lambda a: jax.scipy.special.polygamma(int(n), a), x)


copysign = _binary("copysign", jnp.copysign)
nextafter = _binary("nextafter", jnp.nextafter)
heaviside = _binary("heaviside", jnp.heaviside)
hypot = _binary("hypot", jnp.hypot)
logaddexp = _binary("logaddexp", jnp.logaddexp)
fmod = _binary("fmod", jnp.fmod)
remainder = _binary("remainder", jnp.remainder)


def true_divide(x, y, name=None):
    """Always-float division (paddle true_divide)."""
    if not isinstance(x, Tensor):
        x = as_tensor(x, y if isinstance(y, Tensor) else None)
    y = as_tensor(y, x)
    return apply("true_divide", jnp.true_divide, x, y)


def float_power(x, y, name=None):
    if not isinstance(x, Tensor):
        x = as_tensor(x, y if isinstance(y, Tensor) else None)
    y = as_tensor(y, x)
    return apply("float_power", jnp.float_power, x, y)


def isclose(x, y, rtol=1e-5, atol=1e-8, equal_nan=False, name=None):
    x, y = as_tensor(x), as_tensor(y)
    return apply_nograd(
        "isclose",
        lambda a, b: jnp.isclose(a, b, rtol=rtol, atol=atol,
                                 equal_nan=equal_nan), x, y)


def allclose(x, y, rtol=1e-5, atol=1e-8, equal_nan=False, name=None):
    x, y = as_tensor(x), as_tensor(y)
    return apply_nograd(
        "allclose",
        lambda a, b: jnp.allclose(a, b, rtol=rtol, atol=atol,
                                  equal_nan=equal_nan), x, y)


def equal_all(x, y, name=None):
    x, y = as_tensor(x), as_tensor(y)

    def fn(a, b):
        if a.shape != b.shape:  # static: works traced and concrete
            return jnp.asarray(False)
        return (a == b).all()

    return apply_nograd("equal_all", fn, x, y)


def multiply_(x, y, name=None):
    """In-place multiply (paddle inplace-op parity): x <- x * y.
    Like paddle, in-place mutation of a tensor that requires grad is
    refused (the tape cannot alias the overwritten value)."""
    x = as_tensor(x)
    if not x.stop_gradient:
        raise RuntimeError(
            "multiply_: in-place op on a tensor that requires grad; use "
            "x = x * y (out-of-place) inside differentiated code")
    new = multiply(x, y)
    x._mutate(new._array)
    return x
