"""Paged KV-cache attention helpers — the op tier under the
continuous-batching generation engine (paddle_tpu/inference/engine.py).

vLLM-PagedAttention-style layout, XLA edition: each layer's KV cache is
a global pool `[num_layers, num_blocks, block_size, heads, head_dim]`
shared by every in-flight request; a per-slot block table maps logical
token positions to pool blocks, so requests of different lengths share
HBM without per-request max-seq allocation. Block 0 is reserved as the
NULL block: idle decode slots and padded prefill positions write there,
and no allocator ever hands it out, so garbage writes can never alias a
live request's context.

`paged_attention_step` is a backend-dispatching seam:

- `"pallas"`: the fused TPU kernel (`ops/pallas/paged_attention.py`) —
  one program per slot walks the block table and streams only the
  blocks at or below that slot's position from HBM into VMEM.
  O(active context) HBM traffic per slot per step. Off-TPU it runs
  through the Pallas interpreter (CPU CI tests it token-exactly).
- `"dense"`: an XLA fallback that online-softmaxes over a
  `lax.fori_loop` bounded by the BATCH's high-water block count
  (`max(positions) // block_size + 1`) — O(high-water) work per step
  instead of the O(max_model_len) full-table gather PR 1 shipped. The
  trip count is a traced scalar, so one compiled program serves every
  context depth (the engine's decode-traces == 1 contract holds).
- `"auto"`: resolves per `resolve_backend` — pallas on TPU at
  serving-scale shapes, dense otherwise (see DESIGN_DECISIONS:
  "Paged-attention backend crossover").

Numerics (both backends): logits and the online-softmax state are
fp32; the PV product accumulates in fp32 (`preferred_element_type`)
and the output is cast to q.dtype ONCE at the end — a bf16 pool loses
only the matmul-input rounding, not the accumulation.

Prefix-cache sharing (PR 6): with the engine's prefix cache on, several
slots' block tables may point at the SAME pool block (a shared system
prompt computed once). Both decode backends tolerate that by
construction — context blocks are only ever READ through the table, and
the step's single write lands at the slot's own feed position, which
the engine guarantees sits in an exclusively-owned block (copy-on-write
promotes a shared block to a private copy via `copy_pool_block` before
any write could touch it). `paged_prefill_chunk` is the incremental
prefill step that makes tail-only prefill possible: it writes one
fixed-shape chunk of prompt KV and attends the chunk's queries over
everything the slot's table covers so far — including read-only shared
prefix blocks another request prefilled.

Speculative decoding (PR 7): `paged_verify_window` is the K-token
verify step's attention — a fixed `[slots, K+1]` window per decode
lane (the feed token plus up to K drafted tokens), per-row base
positions and draft lengths both traced, so ONE compiled program
serves every acceptance outcome. Window row `i` of slot `b` lives at
absolute position `positions[b] + i` and is LIVE iff
`i <= draft_lens[b]`; live rows write their k/v through the slot's
block table (the engine COW-promotes every block the window touches
first), dead rows (draft shorter than K, idle lanes) write the null
block. Each window query attends causally over the slot's context up
to its own position — so the target model scores all K+1 positions in
one pass, and rejected tokens need no cleanup: the engine simply does
not advance the slot position past them, and position-bounded masking
makes their stale KV rows unreachable until overwritten. Dispatches
through the same backend seam (`dense` fori-loop fallback /
`pallas` fused kernel, interpreter-run off-TPU).

Tensor-parallel serving (PR 8): every op here is HEAD-COUNT AGNOSTIC —
the head axis is read from the arrays, never from model config — so
the sharded engine runs the SAME ops per shard inside its shard_map
steps with per-shard pools `[L, blocks, bs, heads/mp, D]` and q/k/v
carrying heads/mp heads. Attention is independent per head, so no
collectives appear at this tier; the block tables and positions arrive
replicated (one logical allocator on the host), which is why a block
id means the same row range on every shard.

Implementation notes:
- functional `.at[].set` / aliased-pool writes chain through the layer
  stack; under the engine's donated compiled step XLA aliases them in
  place, so the pool is updated in HBM, not copied per layer.
- scatter/gather indices are per-slot vectors: one program serves any
  mix of slot positions (shape-stable steady-state decode — no
  per-request recompiles).
"""
from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from paddle_tpu.analysis.trace.contracts import TraceContract, \
    register_contract
from paddle_tpu.jit import introspect

from .dispatch import apply, as_tensor

__all__ = ["paged_attention_step", "paged_verify_window",
           "paged_prefill_write", "paged_prefill_chunk",
           "copy_pool_block", "export_pool_block", "ingest_pool_block",
           "dense_gather_reference",
           "resolve_backend", "PAGED_BACKENDS", "PAGED_PATH_STATS",
           "KV_QUANT_EPS"]

PAGED_BACKENDS = ("auto", "dense", "pallas")

#: Scale floor of the int8 per-block-quantized KV cache. Freshly
#: allocated blocks have their scale rows reset here (PagedKVCache
#: .allocate), so a stale previous owner's scale can never poison a
#: new tenant's quantization grid; a first write whose absmax is below
#: 127*EPS quantizes against the floor (absolute error <= ~1e-6).
KV_QUANT_EPS = 1e-8

# which backend paged_attention_step dispatched to, incremented per
# call (so per TRACE under jit — the engine's compiled decode bumps it
# once per layer at compile time, never per step). Tests read it to
# prove the requested kernel actually engaged; the engine's
# kernel-backend gauge is set separately from resolve_backend() at
# construction. flash_attention.PATH_STATS precedent: never a silent
# fallback.
PAGED_PATH_STATS = {"dense": 0, "pallas": 0}


def reset_paged_path_stats():
    PAGED_PATH_STATS["dense"] = 0
    PAGED_PATH_STATS["pallas"] = 0


def _on_tpu():
    try:
        return jax.devices()[0].platform == "tpu" or \
            jax.default_backend() in ("tpu", "axon")
    except Exception:
        return False


def resolve_backend(backend, head_dim, block_size):
    """Resolve `auto`/`dense`/`pallas` to the backend a step will run.

    `auto` picks the fused kernel only on TPU and only at
    serving-scale shapes — head_dim >= 64 (the MXU lane width the
    kernel's per-block einsum needs to not run mostly-padded) and
    block_size >= 8 (sublane multiple; smaller blocks make the
    per-block DMA smaller than its descriptor overhead). Narrow-head /
    tiny-block configs stay dense: at those shapes the per-slot grid +
    per-block DMA overhead exceeds the gather traffic it saves —
    mirroring the `_xla_attention_bf16` crossover note in
    `ops/pallas/flash_attention.py`. Explicit `dense`/`pallas` always
    wins (off-TPU, `pallas` runs the interpreter — the CPU CI path)."""
    if backend not in PAGED_BACKENDS:
        raise ValueError(f"backend must be one of {PAGED_BACKENDS}, "
                         f"got {backend!r}")
    if backend != "auto":
        return backend
    if _on_tpu() and head_dim >= 64 and block_size >= 8:
        return "pallas"
    return "dense"


def paged_attention_step(q, k, v, kpool, vpool, layer, block_tables,
                         positions, scale=None, backend="auto",
                         scales=None, mp_axis=None):
    """One batched decode step against the paged cache, for one layer.

    With `scales` (the int8 engine's `[layers, num_blocks, 2]`
    per-block K/V scale array) the pools are int8: the step
    quantizes-on-write (growing + requantizing the written blocks'
    grids), dequantizes the streamed blocks inside the matmuls, and
    returns a FOUR-tuple `(out, new_kpool, new_vpool, new_scales)`.
    `mp_axis` names the mesh axis whose shards must agree on the
    per-block grid (one lax.pmax per layer); None off-mesh. Without
    `scales` the fp path below is bit-identical to pre-int8 behavior.

    q/k/v: `[slots, 1, heads, head_dim]` — this step's projections.
    kpool/vpool: `[layers, num_blocks, block_size, heads, head_dim]`.
    layer: python int (static) — which layer's pool plane to use.
    block_tables: `[slots, max_blocks]` int32 pool-block ids per slot.
    positions: `[slots]` int32 — the incoming token's absolute position
    per slot (its write address; attention covers positions <= it).
    backend: `auto` | `dense` | `pallas` (see module docstring).

    Writes k/v at `(block_tables[s, pos//bs], pos%bs)` per slot, then
    attends q over the slot's context. Idle slots are encoded by the
    caller as (position 0, all-null table): they write into the null
    block and attend only their own garbage row, and the engine
    discards their token. Decode-only op: gradients are not defined
    through it. Returns `(out [slots,1,heads,head_dim], new_kpool,
    new_vpool)`.
    """
    q, k, v = as_tensor(q), as_tensor(k), as_tensor(v)
    kpool, vpool = as_tensor(kpool), as_tensor(vpool)
    block_tables, positions = as_tensor(block_tables), as_tensor(positions)

    resolved = resolve_backend(backend, head_dim=q.shape[3],
                               block_size=kpool.shape[2])
    PAGED_PATH_STATS[resolved] += 1
    if scales is not None:
        scales = as_tensor(scales)
        if resolved == "pallas":
            from .pallas.paged_attention import paged_decode_attention

            interpret = not _on_tpu()

            def fn(qa, ka, va, kp, vp, sc, bt, pos):
                kp, vp, sc, kq, vq = _quant_write_decode(
                    kp, vp, sc, ka, va, bt, pos, layer, mp_axis)
                out, kp, vp = paged_decode_attention(
                    qa, kq[:, None], vq[:, None], kp, vp, layer, bt,
                    pos, scale=scale, interpret=interpret,
                    kv_scales=sc[layer])
                return out, kp, vp, sc
        else:
            def fn(qa, ka, va, kp, vp, sc, bt, pos):
                return _dense_step_q(qa, ka, va, kp, vp, sc, layer,
                                     bt, pos, scale, mp_axis)

        return apply("paged_attention_step", fn, q, k, v, kpool,
                     vpool, scales, block_tables, positions)
    if resolved == "pallas":
        from .pallas.paged_attention import paged_decode_attention

        interpret = not _on_tpu()

        def fn(qa, ka, va, kp, vp, bt, pos):
            return paged_decode_attention(qa, ka, va, kp, vp, layer,
                                          bt, pos, scale=scale,
                                          interpret=interpret)
    else:
        def fn(qa, ka, va, kp, vp, bt, pos):
            return _dense_step(qa, ka, va, kp, vp, layer, bt, pos,
                               scale)

    return apply("paged_attention_step", fn, q, k, v, kpool, vpool,
                 block_tables, positions)


def _dense_step(qa, ka, va, kp, vp, layer, bt, pos, scale):
    """XLA fallback: per-block online softmax over a fori_loop bounded
    by the batch high-water block count. Work per step is
    O(max(positions)) — the live-context high-water mark — not
    O(max_model_len) like a full-table gather; the traced trip count
    keeps the program shape-stable (no recompiles as context grows)."""
    B = qa.shape[0]
    heads, d = qa.shape[2], qa.shape[3]
    bs = kp.shape[2]
    bid_w = jnp.take_along_axis(bt, (pos // bs)[:, None], axis=1)[:, 0]
    off = pos % bs
    kp = kp.at[layer, bid_w, off].set(ka[:, 0])
    vp = vp.at[layer, bid_w, off].set(va[:, 0])
    s = scale if scale is not None else 1.0 / np.sqrt(d)
    # QK inputs stay at the pool dtype (bf16 MXU pass on TPU) with
    # fp32 accumulation — the SAME policy as the pallas kernel, so the
    # two backends see identical logits rounding and the cross-backend
    # token-exact contract holds at bf16, not just fp32
    qf = qa[:, 0].astype(kp.dtype)                 # [B, heads, d]
    hw_blocks = jnp.max(pos) // bs + 1             # traced scalar

    def body(j, carry):
        m, l, acc = carry
        bid = jax.lax.dynamic_index_in_dim(bt, j, axis=1,
                                           keepdims=False)   # [B]
        keys = kp[layer, bid]                      # [B, bs, heads, d]
        vals = vp[layer, bid]
        logits = jnp.einsum("bhd,bkhd->bhk", qf, keys,
                            preferred_element_type=jnp.float32) * s
        allowed = (j * bs + jnp.arange(bs))[None, :] <= pos[:, None]
        logits = jnp.where(allowed[:, None, :], logits, -1e30)
        m_new = jnp.maximum(m, jnp.max(logits, axis=-1, keepdims=True))
        p = jnp.exp(logits - m_new)                # [B, heads, bs] f32
        alpha = jnp.exp(m - m_new)
        l_new = alpha * l + jnp.sum(p, axis=-1, keepdims=True)
        # PV accumulates in fp32 (preferred_element_type): probs enter
        # the matmul at the pool dtype (bf16 MXU pass on TPU) but the
        # product never rounds to bf16 mid-accumulation
        pv = jnp.einsum("bhk,bkhd->bhd", p.astype(vals.dtype), vals,
                        preferred_element_type=jnp.float32)
        return m_new, l_new, acc * alpha + pv

    m0 = jnp.full((B, heads, 1), -1e30, jnp.float32)
    l0 = jnp.zeros((B, heads, 1), jnp.float32)
    acc0 = jnp.zeros((B, heads, d), jnp.float32)
    _, l, acc = jax.lax.fori_loop(0, hw_blocks, body, (m0, l0, acc0))
    out = (acc / jnp.maximum(l, 1e-30)).astype(qa.dtype)  # cast ONCE
    return out[:, None], kp, vp


# ---------------------------------------------------------------------------
# int8 per-block-scaled KV quantization (PR 11)
#
# Layout: int8 pools + ONE f32 scale array `[layers, num_blocks, 2]`
# (column 0 = K scale, column 1 = V scale) riding the compiled steps
# alongside the pools. Policy, shared verbatim by every write path so
# cold/warm/chunked/bucketed runs quantize byte-identically:
#
# - symmetric absmax, clip to +/-127 (-128 unused);
# - per-block scales are MONOTONE: a write whose row absmax exceeds
#   the block's current grid grows the scale and REQUANTIZES the
#   written block's existing rows (round(q * s_old/s_new) — factor
#   <= 1, so no clipping) before the new rows land. Only the written
#   (engine-guaranteed private) blocks are touched, so shared /
#   prefix-cached blocks and their scales are never mutated by a
#   borrower — the COW/prefix sharing story is unchanged;
# - under tensor parallel the pools are head-sharded but the scales
#   are per-(layer, block) GLOBAL: one lax.pmax over the mp axis per
#   layer write folds the shards' absmax, so mp=N quantizes on the
#   same grid as mp=1 (token-identical int8 serving across mesh
#   shapes; the budget lives in GPT_SERVING_COLLECTIVES);
# - dequant is fused into the streamed-block matmuls: logits and PV
#   are computed over the int8 values cast to f32 and scaled ONCE per
#   block (linearity: q . (K*s) == (q . K) * s), fp32 online softmax
#   unchanged. Both backends use the identical operation order so the
#   dense fallback and the Pallas kernel agree token-for-token.
# ---------------------------------------------------------------------------

def _requant_grow(blk, factor):
    """Rescale a written block's existing int8 rows onto a grown grid:
    factor = s_old/s_new <= 1, so round() never needs a clip."""
    return jnp.round(blk.astype(jnp.float32) * factor).astype(jnp.int8)


def _quant_rows(rows, s):
    """Quantize fp rows onto the block grid `s` (broadcast f32)."""
    return jnp.clip(jnp.round(rows.astype(jnp.float32) / s),
                    -127, 127).astype(jnp.int8)


def _fold_amax(amax, mp_axis):
    """Per-block scale candidates must cover ALL heads; under a
    head-sharded mesh each shard sees only its own, so fold with one
    cross-shard max (exact — max is associative/commutative)."""
    if mp_axis is None:
        return amax
    return jax.lax.pmax(amax, mp_axis)


def _quant_write_decode(kp, vp, sc, ka, va, bt, pos, layer, mp_axis):
    """Quant-on-write bookkeeping for one decode row per slot: grow +
    requantize each slot's write block, update its scale row, and
    return the QUANTIZED new rows (not yet written — each backend
    lands them its own way: the dense path scatters, the Pallas
    kernel DMAs). Returns (kp, vp, sc, kq [B,heads,D], vq)."""
    bs = kp.shape[2]
    bid_w = jnp.take_along_axis(bt, (pos // bs)[:, None], axis=1)[:, 0]
    ak = jnp.max(jnp.abs(ka[:, 0].astype(jnp.float32)), axis=(1, 2))
    av = jnp.max(jnp.abs(va[:, 0].astype(jnp.float32)), axis=(1, 2))
    amax = _fold_amax(jnp.stack([ak, av], axis=-1) / 127.0, mp_axis)
    s_old = sc[layer, bid_w]                             # [B, 2]
    s_new = jnp.maximum(jnp.maximum(s_old, amax), KV_QUANT_EPS)
    fac = s_old / s_new
    kp = kp.at[layer, bid_w].set(
        _requant_grow(kp[layer, bid_w], fac[:, 0][:, None, None, None]))
    vp = vp.at[layer, bid_w].set(
        _requant_grow(vp[layer, bid_w], fac[:, 1][:, None, None, None]))
    sc = sc.at[layer, bid_w].set(s_new)
    kq = _quant_rows(ka[:, 0], s_new[:, 0][:, None, None])
    vq = _quant_rows(va[:, 0], s_new[:, 1][:, None, None])
    return kp, vp, sc, kq, vq


def _quant_write_window(kp, vp, sc, ka, va, bt, pos, dlen, layer,
                        mp_axis):
    """Window edition of `_quant_write_decode`: W contiguous write
    positions per slot (the speculative verify window). The window
    spans a STATIC number of candidate table slots, so the grow +
    requantize pass gathers just those blocks. Dead rows (i > dlen)
    are excluded from the absmax and quantize to garbage the engine
    never reads. Returns (kp, vp, sc, kq [B,W,heads,D], vq)."""
    B, W = ka.shape[0], ka.shape[1]
    bs = kp.shape[2]
    maxb = bt.shape[1]
    nb = (W - 1) // bs + 2                 # static candidate count
    wpos = pos[:, None] + jnp.arange(W)[None, :]         # [B, W]
    live = jnp.arange(W)[None, :] <= dlen[:, None]       # [B, W]
    first = pos // bs                                    # [B]
    seg = jnp.clip(wpos // bs - first[:, None], 0, nb - 1)
    # candidates past the table route to the NULL block — a clamped
    # index must never scatter-race the real last block's grid
    cand = first[:, None] + jnp.arange(nb)[None, :]      # [B, nb]
    ti = jnp.minimum(cand, maxb - 1)
    bids = jnp.where(cand <= maxb - 1,
                     jnp.take_along_axis(bt, ti, axis=1), 0)
    rk = jnp.max(jnp.abs(ka.astype(jnp.float32)), axis=(2, 3))
    rv = jnp.max(jnp.abs(va.astype(jnp.float32)), axis=(2, 3))
    zero = jnp.zeros((B, nb), jnp.float32)
    need_k = zero.at[jnp.arange(B)[:, None], seg].max(
        jnp.where(live, rk, 0.0))
    need_v = zero.at[jnp.arange(B)[:, None], seg].max(
        jnp.where(live, rv, 0.0))
    amax = _fold_amax(jnp.stack([need_k, need_v], axis=-1) / 127.0,
                      mp_axis)                           # [B, nb, 2]
    s_old = sc[layer, bids]                              # [B, nb, 2]
    s_new = jnp.maximum(jnp.maximum(s_old, amax), KV_QUANT_EPS)
    fac = s_old / s_new
    kp = kp.at[layer, bids].set(
        _requant_grow(kp[layer, bids],
                      fac[..., 0][..., None, None, None]))
    vp = vp.at[layer, bids].set(
        _requant_grow(vp[layer, bids],
                      fac[..., 1][..., None, None, None]))
    sc = sc.at[layer, bids].set(s_new)
    s_row = jnp.take_along_axis(s_new, seg[..., None], axis=1)  # [B,W,2]
    kq = _quant_rows(ka, s_row[..., 0][..., None, None])
    vq = _quant_rows(va, s_row[..., 1][..., None, None])
    return kp, vp, sc, kq, vq


def _dense_step_q(qa, ka, va, kp, vp, sc, layer, bt, pos, scale,
                  mp_axis):
    """int8 edition of `_dense_step`: quant-on-write, then the SAME
    fori_loop online softmax with dequant fused into the per-block
    matmuls (one scale multiply per streamed block; fp32 logits,
    softmax state and PV accumulation unchanged)."""
    B = qa.shape[0]
    heads, d = qa.shape[2], qa.shape[3]
    bs = kp.shape[2]
    kp, vp, sc, kq, vq = _quant_write_decode(kp, vp, sc, ka, va, bt,
                                             pos, layer, mp_axis)
    bid_w = jnp.take_along_axis(bt, (pos // bs)[:, None], axis=1)[:, 0]
    off = pos % bs
    kp = kp.at[layer, bid_w, off].set(kq)
    vp = vp.at[layer, bid_w, off].set(vq)
    s = scale if scale is not None else 1.0 / np.sqrt(d)
    qf = qa[:, 0].astype(jnp.float32)              # [B, heads, d]
    hw_blocks = jnp.max(pos) // bs + 1             # traced scalar

    def body(j, carry):
        m, l, acc = carry
        bid = jax.lax.dynamic_index_in_dim(bt, j, axis=1,
                                           keepdims=False)   # [B]
        keys = kp[layer, bid].astype(jnp.float32)  # [B, bs, heads, d]
        vals = vp[layer, bid].astype(jnp.float32)
        ks, vs = sc[layer, bid, 0], sc[layer, bid, 1]        # [B]
        logits = jnp.einsum("bhd,bkhd->bhk", qf, keys,
                            preferred_element_type=jnp.float32) * s
        logits = logits * ks[:, None, None]        # fused dequant (K)
        allowed = (j * bs + jnp.arange(bs))[None, :] <= pos[:, None]
        logits = jnp.where(allowed[:, None, :], logits, -1e30)
        m_new = jnp.maximum(m, jnp.max(logits, axis=-1, keepdims=True))
        p = jnp.exp(logits - m_new)                # [B, heads, bs] f32
        alpha = jnp.exp(m - m_new)
        l_new = alpha * l + jnp.sum(p, axis=-1, keepdims=True)
        pv = jnp.einsum("bhk,bkhd->bhd", p, vals,
                        preferred_element_type=jnp.float32)
        return m_new, l_new, acc * alpha + pv * vs[:, None, None]

    m0 = jnp.full((B, heads, 1), -1e30, jnp.float32)
    l0 = jnp.zeros((B, heads, 1), jnp.float32)
    acc0 = jnp.zeros((B, heads, d), jnp.float32)
    _, l, acc = jax.lax.fori_loop(0, hw_blocks, body, (m0, l0, acc0))
    out = (acc / jnp.maximum(l, 1e-30)).astype(qa.dtype)  # cast ONCE
    return out[:, None], kp, vp, sc


def _dense_verify_q(qa, ka, va, kp, vp, sc, layer, bt, pos, dlen,
                    scale, mp_axis):
    """int8 edition of `_dense_verify`: window quant-on-write, then
    the W-query online softmax with per-block fused dequant."""
    B, W = qa.shape[0], qa.shape[1]
    heads, d = qa.shape[2], qa.shape[3]
    bs = kp.shape[2]
    maxb = bt.shape[1]
    kp, vp, sc, kq, vq = _quant_write_window(kp, vp, sc, ka, va, bt,
                                             pos, dlen, layer, mp_axis)
    wpos = pos[:, None] + jnp.arange(W)[None, :]       # [B, W] absolute
    live = jnp.arange(W)[None, :] <= dlen[:, None]     # [B, W]
    bid = jnp.where(
        live, jnp.take_along_axis(bt, jnp.minimum(wpos // bs, maxb - 1),
                                  axis=1), 0)
    off = wpos % bs
    kp = kp.at[layer, bid, off].set(kq)                # [B, W, heads, d]
    vp = vp.at[layer, bid, off].set(vq)
    s = scale if scale is not None else 1.0 / np.sqrt(d)
    qf = qa.astype(jnp.float32)                        # [B, W, heads, d]
    hw_blocks = jnp.max(pos + dlen) // bs + 1          # traced scalar

    def body(j, carry):
        m, l, acc = carry
        bidj = jax.lax.dynamic_index_in_dim(bt, j, axis=1,
                                            keepdims=False)    # [B]
        keys = kp[layer, bidj].astype(jnp.float32)  # [B, bs, heads, d]
        vals = vp[layer, bidj].astype(jnp.float32)
        ks, vs = sc[layer, bidj, 0], sc[layer, bidj, 1]        # [B]
        logits = jnp.einsum("bwhd,bkhd->bhwk", qf, keys,
                            preferred_element_type=jnp.float32) * s
        logits = logits * ks[:, None, None, None]   # fused dequant (K)
        allowed = (j * bs + jnp.arange(bs))[None, None, :] \
            <= wpos[:, :, None]                  # [B, W, bs]
        logits = jnp.where(allowed[:, None, :, :], logits, -1e30)
        m_new = jnp.maximum(m, jnp.max(logits, axis=-1, keepdims=True))
        p = jnp.exp(logits - m_new)              # [B, heads, W, bs] f32
        alpha = jnp.exp(m - m_new)
        l_new = alpha * l + jnp.sum(p, axis=-1, keepdims=True)
        pv = jnp.einsum("bhwk,bkhd->bhwd", p, vals,
                        preferred_element_type=jnp.float32)
        return (m_new, l_new,
                acc * alpha + pv * vs[:, None, None, None])

    m0 = jnp.full((B, heads, W, 1), -1e30, jnp.float32)
    l0 = jnp.zeros((B, heads, W, 1), jnp.float32)
    acc0 = jnp.zeros((B, heads, W, d), jnp.float32)
    _, l, acc = jax.lax.fori_loop(0, hw_blocks, body, (m0, l0, acc0))
    out = (acc / jnp.maximum(l, 1e-30)).astype(qa.dtype)  # cast ONCE
    return out.transpose(0, 2, 1, 3), kp, vp, sc   # [B, W, heads, d]


def paged_verify_window(q, k, v, kpool, vpool, layer, block_tables,
                        positions, draft_lens, scale=None,
                        backend="auto", scales=None, mp_axis=None):
    """Speculative-verify attention over a fixed `[slots, W]` token
    window (W = K+1), for one layer. With `scales` the int8
    quantized-KV contract of `paged_attention_step` applies (window
    edition) and a four-tuple `(out, kpool, vpool, scales)` returns.

    q/k/v: `[slots, W, heads, head_dim]` — the window's projections
    (feed token at row 0, drafted tokens after it).
    kpool/vpool: `[layers, num_blocks, block_size, heads, head_dim]`.
    layer: python int (static).
    block_tables: `[slots, max_blocks]` int32 pool-block ids per slot.
    positions: `[slots]` int32 — absolute position of window row 0
    (the slot's feed position).
    draft_lens: `[slots]` int32 in `[0, W-1]` — row `i` is live iff
    `i <= draft_lens[s]`; dead rows write the null block and their
    outputs are garbage the engine ignores.

    Live rows write k/v at `(table[(pos+i)//bs], (pos+i)%bs)`; every
    query attends causally over context `<= pos+i`. A draft_len of 0
    degenerates to `paged_attention_step` semantics on row 0 (the
    engine's draftless fallback under pool pressure). Idle lanes are
    (position 0, draft_len 0, all-null table), exactly the decode
    contract. Returns `(out [slots, W, heads, head_dim], new_kpool,
    new_vpool)`."""
    q, k, v = as_tensor(q), as_tensor(k), as_tensor(v)
    kpool, vpool = as_tensor(kpool), as_tensor(vpool)
    block_tables = as_tensor(block_tables)
    positions, draft_lens = as_tensor(positions), as_tensor(draft_lens)

    resolved = resolve_backend(backend, head_dim=q.shape[3],
                               block_size=kpool.shape[2])
    PAGED_PATH_STATS[resolved] += 1
    if scales is not None:
        scales = as_tensor(scales)
        if resolved == "pallas":
            from .pallas.paged_attention import paged_verify_attention

            interpret = not _on_tpu()

            def fn(qa, ka, va, kp, vp, sc, bt, pos, dlen):
                kp, vp, sc, kq, vq = _quant_write_window(
                    kp, vp, sc, ka, va, bt, pos, dlen, layer, mp_axis)
                out, kp, vp = paged_verify_attention(
                    qa, kq, vq, kp, vp, layer, bt, pos, dlen,
                    scale=scale, interpret=interpret,
                    kv_scales=sc[layer])
                return out, kp, vp, sc
        else:
            def fn(qa, ka, va, kp, vp, sc, bt, pos, dlen):
                return _dense_verify_q(qa, ka, va, kp, vp, sc, layer,
                                       bt, pos, dlen, scale, mp_axis)

        return apply("paged_verify_window", fn, q, k, v, kpool,
                     vpool, scales, block_tables, positions,
                     draft_lens)
    if resolved == "pallas":
        from .pallas.paged_attention import paged_verify_attention

        interpret = not _on_tpu()

        def fn(qa, ka, va, kp, vp, bt, pos, dlen):
            return paged_verify_attention(qa, ka, va, kp, vp, layer,
                                          bt, pos, dlen, scale=scale,
                                          interpret=interpret)
    else:
        def fn(qa, ka, va, kp, vp, bt, pos, dlen):
            return _dense_verify(qa, ka, va, kp, vp, layer, bt, pos,
                                 dlen, scale)

    return apply("paged_verify_window", fn, q, k, v, kpool, vpool,
                 block_tables, positions, draft_lens)


def _dense_verify(qa, ka, va, kp, vp, layer, bt, pos, dlen, scale):
    """XLA fallback for the verify window: the `_dense_step` online
    softmax widened to W queries per slot. Work per step is
    O(max(pos + dlen)) — the batch high-water mark including the
    window — with the traced trip count keeping one program for every
    (position, draft-length) mix."""
    B, W = qa.shape[0], qa.shape[1]
    heads, d = qa.shape[2], qa.shape[3]
    bs = kp.shape[2]
    maxb = bt.shape[1]
    wpos = pos[:, None] + jnp.arange(W)[None, :]       # [B, W] absolute
    live = jnp.arange(W)[None, :] <= dlen[:, None]     # [B, W]
    # dead rows (and any clamp overflow) land in the null block 0; the
    # table index is clamped so a dead row past the table stays in
    # bounds before the where() routes it to null
    bid = jnp.where(
        live, jnp.take_along_axis(bt, jnp.minimum(wpos // bs, maxb - 1),
                                  axis=1), 0)
    off = wpos % bs
    kp = kp.at[layer, bid, off].set(ka)                # [B, W, heads, d]
    vp = vp.at[layer, bid, off].set(va)
    s = scale if scale is not None else 1.0 / np.sqrt(d)
    # QK at the pool dtype with fp32 accumulation — the _dense_step
    # policy, so verify and plain decode share one rounding story
    qf = qa.astype(kp.dtype)                           # [B, W, heads, d]
    hw_blocks = jnp.max(pos + dlen) // bs + 1          # traced scalar

    def body(j, carry):
        m, l, acc = carry
        bidj = jax.lax.dynamic_index_in_dim(bt, j, axis=1,
                                            keepdims=False)    # [B]
        keys = kp[layer, bidj]                   # [B, bs, heads, d]
        vals = vp[layer, bidj]
        logits = jnp.einsum("bwhd,bkhd->bhwk", qf, keys,
                            preferred_element_type=jnp.float32) * s
        # causal per window row: key j*bs+k visible to window query w
        # iff it sits at or before that query's absolute position
        allowed = (j * bs + jnp.arange(bs))[None, None, :] \
            <= wpos[:, :, None]                  # [B, W, bs]
        logits = jnp.where(allowed[:, None, :, :], logits, -1e30)
        m_new = jnp.maximum(m, jnp.max(logits, axis=-1, keepdims=True))
        p = jnp.exp(logits - m_new)              # [B, heads, W, bs] f32
        alpha = jnp.exp(m - m_new)
        l_new = alpha * l + jnp.sum(p, axis=-1, keepdims=True)
        pv = jnp.einsum("bhwk,bkhd->bhwd", p.astype(vals.dtype), vals,
                        preferred_element_type=jnp.float32)
        return m_new, l_new, acc * alpha + pv

    m0 = jnp.full((B, heads, W, 1), -1e30, jnp.float32)
    l0 = jnp.zeros((B, heads, W, 1), jnp.float32)
    acc0 = jnp.zeros((B, heads, W, d), jnp.float32)
    _, l, acc = jax.lax.fori_loop(0, hw_blocks, body, (m0, l0, acc0))
    out = (acc / jnp.maximum(l, 1e-30)).astype(qa.dtype)  # cast ONCE
    return out.transpose(0, 2, 1, 3), kp, vp       # [B, W, heads, d]


def paged_prefill_write(kpool, vpool, kstack, vstack, block_row, plen,
                        scales=None, mp_axis=None):
    """Scatter a prefilled prompt's per-layer k/v into the pools.

    With `scales` (int8 pools) each written block's grid is computed
    from the rows landing in it this call. The bucketed path always
    writes into FRESHLY allocated blocks (scale rows reset to
    KV_QUANT_EPS by the allocator), so the grid only ever grows from
    the floor via an order-independent scatter-max and the stale int8
    bytes beyond `plen` — unreachable through position-bounded
    attention — need no requantization. Returns
    `(kpool, vpool, scales)`.

    kstack/vstack: `[layers, 1, S, heads, head_dim]` from
    `GPTModel.forward_prefill` over the (bucket-padded) prompt.
    block_row: `[max_blocks]` int32 — the slot's block table.
    plen: true prompt length (may be traced — one compiled program per
    bucket size S, shared across every prompt length in the bucket).

    Positions >= plen (bucket padding) are routed to the null block 0,
    so padding never lands in allocated blocks. Returns the updated
    `(kpool, vpool)`.
    """
    kpool, vpool = as_tensor(kpool), as_tensor(vpool)
    kstack, vstack = as_tensor(kstack), as_tensor(vstack)
    block_row, plen = as_tensor(block_row), as_tensor(plen)

    if scales is not None:
        scales = as_tensor(scales)

        def fnq(kp, vp, sc, ks, vs, row, n):
            L, S = ks.shape[0], ks.shape[2]
            bs = kp.shape[2]
            nb = (S - 1) // bs + 1             # static: bucket blocks
            pos = jnp.arange(S)
            valid = pos < n
            bid = jnp.where(valid, row[pos // bs], 0)
            off = pos % bs
            seg = pos // bs                    # [S] in [0, nb)
            rk = jnp.max(jnp.abs(ks[:, 0].astype(jnp.float32)),
                         axis=(2, 3))          # [L, S]
            rv = jnp.max(jnp.abs(vs[:, 0].astype(jnp.float32)),
                         axis=(2, 3))
            zero = jnp.zeros((L, nb), jnp.float32)
            need_k = zero.at[:, seg].max(jnp.where(valid, rk, 0.0))
            need_v = zero.at[:, seg].max(jnp.where(valid, rv, 0.0))
            need = _fold_amax(
                jnp.stack([need_k, need_v], axis=-1) / 127.0, mp_axis)
            # candidate block per segment: null 0 when the segment has
            # no valid rows (its `need` is 0 there — a no-op max)
            bids = jnp.where((jnp.arange(nb) * bs) < n, row[:nb], 0)
            s_fin = jnp.maximum(
                jnp.maximum(sc[:, bids], need), KV_QUANT_EPS)
            sc = sc.at[:, bids].max(s_fin)     # order-independent
            s_row = s_fin[:, seg]              # [L, S, 2]
            kq = _quant_rows(ks[:, 0], s_row[..., 0][..., None, None])
            vq = _quant_rows(vs[:, 0], s_row[..., 1][..., None, None])
            kp = kp.at[:, bid, off].set(kq)    # [layers, S, heads, D]
            vp = vp.at[:, bid, off].set(vq)
            return kp, vp, sc

        return apply("paged_prefill_write", fnq, kpool, vpool, scales,
                     kstack, vstack, block_row, plen)

    def fn(kp, vp, ks, vs, row, n):
        S = ks.shape[2]
        bs = kp.shape[2]
        pos = jnp.arange(S)
        bid = jnp.where(pos < n, row[pos // bs], 0)
        off = pos % bs
        kp = kp.at[:, bid, off].set(ks[:, 0])    # [layers, S, heads, D]
        vp = vp.at[:, bid, off].set(vs[:, 0])
        return kp, vp

    return apply("paged_prefill_write", fn, kpool, vpool, kstack, vstack,
                 block_row, plen)


def paged_prefill_chunk(q, k, v, kpool, vpool, layer, block_row, start,
                        plen, scale=None, scales=None, mp_axis=None):
    """One chunked-prefill step for ONE slot, for one layer: write the
    chunk's k/v into the pool, then attend the chunk's queries over the
    slot's whole context so far (shared prefix blocks + earlier chunks
    + the chunk itself, causally).

    q/k/v: `[1, C, heads, head_dim]` — this chunk's projections; C is
    the FIXED chunk width, so one compiled program serves every prompt
    length (`start` and `plen` are traced scalars).
    block_row: `[max_blocks]` int32 — the slot's block table.
    start: absolute position of the chunk's first token.
    plen: true prompt length. Chunk positions >= plen (tail padding)
    write to the null block 0 and their query outputs are garbage the
    caller ignores (same contract as bucketed prefill padding).

    Work is O(chunk x context-so-far) via the same traced-trip-count
    `fori_loop` online softmax as the dense decode step — identical
    numerics policy (fp32 logits/softmax state, fp32 PV accumulation,
    one cast at the end). Reads may cross blocks OTHER slots own (the
    prefix cache seats them read-only); writes never do — the chunk's
    write blocks were allocated exclusively to this slot. Returns
    `(out [1, C, heads, head_dim], new_kpool, new_vpool)`."""
    q, k, v = as_tensor(q), as_tensor(k), as_tensor(v)
    kpool, vpool = as_tensor(kpool), as_tensor(vpool)
    block_row = as_tensor(block_row)
    start, plen = as_tensor(start), as_tensor(plen)

    if scales is not None:
        scales = as_tensor(scales)

        def fnq(qa, ka, va, kp, vp, sc, row, s0, n):
            C = qa.shape[1]
            heads, d = qa.shape[2], qa.shape[3]
            bs = kp.shape[2]
            maxb = row.shape[0]
            nb = (C - 1) // bs + 2         # static candidate blocks
            pos = s0 + jnp.arange(C)                       # absolute [C]
            valid = pos < n
            first = s0 // bs
            seg = jnp.clip(pos // bs - first, 0, nb - 1)   # [C]
            # a chunk may finish a block an EARLIER chunk started, so
            # the grid must grow + requantize (unlike the bucketed
            # fresh-block writer). Candidates with no valid rows keep
            # their scale (need 0) and requantize by factor 1 — exact.
            # Candidates past the table route to the NULL block so a
            # clamped index can never scatter-race the real last block.
            cand = first + jnp.arange(nb)
            ti = jnp.minimum(cand, maxb - 1)
            bids = jnp.where(cand <= maxb - 1, row[ti], 0)  # [nb]
            rk = jnp.max(jnp.abs(ka[0].astype(jnp.float32)),
                         axis=(1, 2))                      # [C]
            rv = jnp.max(jnp.abs(va[0].astype(jnp.float32)),
                         axis=(1, 2))
            zero = jnp.zeros(nb, jnp.float32)
            need_k = zero.at[seg].max(jnp.where(valid, rk, 0.0))
            need_v = zero.at[seg].max(jnp.where(valid, rv, 0.0))
            amax = _fold_amax(
                jnp.stack([need_k, need_v], axis=-1) / 127.0, mp_axis)
            s_old = sc[layer, bids]                        # [nb, 2]
            s_new = jnp.maximum(jnp.maximum(s_old, amax), KV_QUANT_EPS)
            fac = s_old / s_new
            kp = kp.at[layer, bids].set(
                _requant_grow(kp[layer, bids],
                              fac[:, 0][:, None, None, None]))
            vp = vp.at[layer, bids].set(
                _requant_grow(vp[layer, bids],
                              fac[:, 1][:, None, None, None]))
            sc = sc.at[layer, bids].set(s_new)
            s_row = s_new[seg]                             # [C, 2]
            kq = _quant_rows(ka[0], s_row[:, 0][:, None, None])
            vq = _quant_rows(va[0], s_row[:, 1][:, None, None])
            bid = jnp.where(valid,
                            row[jnp.minimum(pos // bs, maxb - 1)], 0)
            off = pos % bs
            kp = kp.at[layer, bid, off].set(kq)            # [C, heads, d]
            vp = vp.at[layer, bid, off].set(vq)
            s = scale if scale is not None else 1.0 / np.sqrt(d)
            qf = qa[0].astype(jnp.float32)                 # [C, heads, d]
            end = jnp.minimum(s0 + C, n)                   # past-last pos
            hw_blocks = jnp.maximum(end - 1, 0) // bs + 1  # traced

            def body(j, carry):
                m, l, acc = carry
                b = row[j]
                keys = kp[layer, b].astype(jnp.float32)  # [bs, heads, d]
                vals = vp[layer, b].astype(jnp.float32)
                ks, vs = sc[layer, b, 0], sc[layer, b, 1]
                logits = jnp.einsum(
                    "chd,khd->hck", qf, keys,
                    preferred_element_type=jnp.float32) * s
                logits = logits * ks           # fused dequant (K)
                allowed = (j * bs + jnp.arange(bs))[None, :] \
                    <= pos[:, None]
                logits = jnp.where(allowed[None, :, :], logits, -1e30)
                m_new = jnp.maximum(m, jnp.max(logits, axis=-1,
                                               keepdims=True))
                p = jnp.exp(logits - m_new)    # [heads, C, bs]
                alpha = jnp.exp(m - m_new)
                l_new = alpha * l + jnp.sum(p, axis=-1, keepdims=True)
                pv = jnp.einsum("hck,khd->hcd", p, vals,
                                preferred_element_type=jnp.float32)
                return m_new, l_new, acc * alpha + pv * vs

            m0 = jnp.full((heads, C, 1), -1e30, jnp.float32)
            l0 = jnp.zeros((heads, C, 1), jnp.float32)
            acc0 = jnp.zeros((heads, C, d), jnp.float32)
            _, l, acc = jax.lax.fori_loop(0, hw_blocks, body,
                                          (m0, l0, acc0))
            out = (acc / jnp.maximum(l, 1e-30)).astype(qa.dtype)
            return out.transpose(1, 0, 2)[None], kp, vp, sc

        return apply("paged_prefill_chunk", fnq, q, k, v, kpool,
                     vpool, scales, block_row, start, plen)

    def fn(qa, ka, va, kp, vp, row, s0, n):
        C = qa.shape[1]
        heads, d = qa.shape[2], qa.shape[3]
        bs = kp.shape[2]
        maxb = row.shape[0]
        pos = s0 + jnp.arange(C)                       # absolute [C]
        valid = pos < n
        bid = jnp.where(valid,
                        row[jnp.minimum(pos // bs, maxb - 1)], 0)
        off = pos % bs
        kp = kp.at[layer, bid, off].set(ka[0])         # [C, heads, d]
        vp = vp.at[layer, bid, off].set(va[0])
        s = scale if scale is not None else 1.0 / np.sqrt(d)
        # QK at pool dtype, fp32 accumulation — the _dense_step policy,
        # so chunked and bucketed prefill see the same rounding story
        qf = qa[0].astype(kp.dtype)                    # [C, heads, d]
        end = jnp.minimum(s0 + C, n)                   # past-last pos
        hw_blocks = jnp.maximum(end - 1, 0) // bs + 1  # traced scalar

        def body(j, carry):
            m, l, acc = carry
            b = row[j]
            keys = kp[layer, b]                        # [bs, heads, d]
            vals = vp[layer, b]
            logits = jnp.einsum(
                "chd,khd->hck", qf, keys,
                preferred_element_type=jnp.float32) * s
            # causal over absolute positions: key j*bs+k visible to
            # query c iff it is at or before the query's position
            allowed = (j * bs + jnp.arange(bs))[None, :] <= pos[:, None]
            logits = jnp.where(allowed[None, :, :], logits, -1e30)
            m_new = jnp.maximum(m, jnp.max(logits, axis=-1,
                                           keepdims=True))
            p = jnp.exp(logits - m_new)                # [heads, C, bs]
            alpha = jnp.exp(m - m_new)
            l_new = alpha * l + jnp.sum(p, axis=-1, keepdims=True)
            pv = jnp.einsum("hck,khd->hcd", p.astype(vals.dtype), vals,
                            preferred_element_type=jnp.float32)
            return m_new, l_new, acc * alpha + pv

        m0 = jnp.full((heads, C, 1), -1e30, jnp.float32)
        l0 = jnp.zeros((heads, C, 1), jnp.float32)
        acc0 = jnp.zeros((heads, C, d), jnp.float32)
        _, l, acc = jax.lax.fori_loop(0, hw_blocks, body, (m0, l0, acc0))
        out = (acc / jnp.maximum(l, 1e-30)).astype(qa.dtype)
        return out.transpose(1, 0, 2)[None], kp, vp    # [1,C,heads,d]

    return apply("paged_prefill_chunk", fn, q, k, v, kpool, vpool,
                 block_row, start, plen)


# tpu-verify contract for the engine's compiled COW step (the op
# right below): donates both pools (introspect is the shared table),
# runs no collectives at any mp (plain jit over the sharded pools —
# the copy is row-local per shard), and must never bake constants or
# call back to host. Declared here because this module owns the step
# body.
register_contract(TraceContract(
    name="engine_cow_copy",
    declared_at="paddle_tpu/ops/paged_attention.py",
    donate_argnums=introspect.ENGINE_COW_DONATE_ARGNUMS))


def copy_pool_block(kpool, vpool, src, dst, scales=None):
    """Copy one block's KV rows across every layer plane: the engine's
    copy-on-write step. `src`/`dst` may be traced scalars, so the
    engine compiles this ONCE and reuses it for every COW promotion
    (donated pools: XLA rewrites the dst rows in place in HBM). With
    `scales` (int8 pools) the block's per-layer K/V scale rows ride
    along — a COW copy of quantized KV without its grid would
    dequantize on the destination's stale scale. Raw jnp arrays
    in/out — this is a compiled-step body, not a user op."""
    srows = jax.lax.dynamic_index_in_dim(kpool, src, axis=1,
                                         keepdims=False)
    kpool = jax.lax.dynamic_update_index_in_dim(kpool, srows, dst,
                                                axis=1)
    srows = jax.lax.dynamic_index_in_dim(vpool, src, axis=1,
                                         keepdims=False)
    vpool = jax.lax.dynamic_update_index_in_dim(vpool, srows, dst,
                                                axis=1)
    if scales is None:
        return kpool, vpool
    srow = jax.lax.dynamic_index_in_dim(scales, src, axis=1,
                                        keepdims=False)
    scales = jax.lax.dynamic_update_index_in_dim(scales, srow, dst,
                                                 axis=1)
    return kpool, vpool, scales


def export_pool_block(kpool, vpool, src, scales=None):
    """Gather ONE block's KV rows across every layer plane out of a
    pool: the disaggregated-serving transfer unit's READ half. `src`
    is a traced scalar, so the fleet compiles this once per source
    pool shape and reuses it for every handed-off block. Returns
    (`[layers, block_size, heads, head_dim]` k rows, same-shape v
    rows[, the block's `[layers, 2]` scale rows under int8 pools —
    quantized codes without their grid would dequantize wrong on the
    destination]). Pools are READ, never donated: the source replica
    keeps serving from them. Raw jnp arrays in/out — a compiled-step
    body, not a user op."""
    kb = jax.lax.dynamic_index_in_dim(kpool, src, axis=1,
                                      keepdims=False)
    vb = jax.lax.dynamic_index_in_dim(vpool, src, axis=1,
                                      keepdims=False)
    if scales is None:
        return kb, vb
    srow = jax.lax.dynamic_index_in_dim(scales, src, axis=1,
                                        keepdims=False)
    return kb, vb, srow


def ingest_pool_block(kpool, vpool, kblock, vblock, dst, scales=None,
                      scale_row=None):
    """Scatter one exported block's KV rows into pool block `dst`:
    the transfer unit's WRITE half — a prefill replica's finished
    prompt KV lands in a decode replica's pool through this one
    compiled program (traced `dst`, donated destination pools, so the
    handoff is an in-place HBM write, not a pool rebuild). Under int8
    pools the block's `[layers, 2]` scale rows ride along into the
    destination's scale array. The payload is bit-copied, never
    re-quantized — decode over ingested blocks reads exactly the
    bytes the prefill wrote, which is what makes disaggregated output
    token-identical to a colocated engine. Raw jnp arrays in/out."""
    kpool = jax.lax.dynamic_update_index_in_dim(kpool, kblock, dst,
                                                axis=1)
    vpool = jax.lax.dynamic_update_index_in_dim(vpool, vblock, dst,
                                                axis=1)
    if scales is None:
        return kpool, vpool
    scales = jax.lax.dynamic_update_index_in_dim(scales, scale_row,
                                                 dst, axis=1)
    return kpool, vpool, scales


def dense_gather_reference(kpool, vpool, layer, block_row, length,
                           scales=None):
    """Parity probe: reassemble one slot's first `length` cached k/v
    rows from the pools into dense `[length, heads, head_dim]` arrays
    (host-side, concrete values). Tests compare this against the dense
    fixed-buffer cache the single-request decode path carries — and,
    across two engines, against each other (the pallas-vs-dense pool
    parity probe). With `scales` (int8 pools) the rows come back
    DEQUANTIZED to f32 through the per-block grid."""
    kp = np.asarray(as_tensor(kpool)._array)[layer]
    vp = np.asarray(as_tensor(vpool)._array)[layer]
    row = np.asarray(as_tensor(block_row)._array)
    bs = kp.shape[1]
    pos = np.arange(int(length))
    bids = row[pos // bs]
    if scales is not None:
        # int8 pools: reconstruct the fp rows through the per-block
        # grid, so quantized parity probes compare VALUES, not codes
        sc = np.asarray(as_tensor(scales)._array)[layer]
        return (kp[bids, pos % bs].astype(np.float32)
                * sc[bids, 0][:, None, None],
                vp[bids, pos % bs].astype(np.float32)
                * sc[bids, 1][:, None, None])
    return (kp[bids, pos % bs], vp[bids, pos % bs])
