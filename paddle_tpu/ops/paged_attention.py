"""Paged KV-cache attention helpers — the op tier under the
continuous-batching generation engine (paddle_tpu/inference/engine.py).

vLLM-PagedAttention-style layout, XLA edition: each layer's KV cache is
a global pool `[num_layers, num_blocks, block_size, heads, head_dim]`
shared by every in-flight request; a per-slot block table maps logical
token positions to pool blocks, so requests of different lengths share
HBM without per-request max-seq allocation. Block 0 is reserved as the
NULL block: idle decode slots and padded prefill positions write there,
and no allocator ever hands it out, so garbage writes can never alias a
live request's context.

`paged_attention_step` is a backend-dispatching seam:

- `"pallas"`: the fused TPU kernel (`ops/pallas/paged_attention.py`) —
  one program per slot walks the block table and streams only the
  blocks at or below that slot's position from HBM into VMEM.
  O(active context) HBM traffic per slot per step. Off-TPU it runs
  through the Pallas interpreter (CPU CI tests it token-exactly).
- `"dense"`: an XLA fallback that online-softmaxes over a
  `lax.fori_loop` bounded by the BATCH's high-water block count
  (`max(positions) // block_size + 1`) — O(high-water) work per step
  instead of the O(max_model_len) full-table gather PR 1 shipped. The
  trip count is a traced scalar, so one compiled program serves every
  context depth (the engine's decode-traces == 1 contract holds).
- `"auto"`: resolves per `resolve_backend` — pallas on TPU at
  serving-scale shapes, dense otherwise (see DESIGN_DECISIONS:
  "Paged-attention backend crossover").

Numerics (both backends): logits and the online-softmax state are
fp32; the PV product accumulates in fp32 (`preferred_element_type`)
and the output is cast to q.dtype ONCE at the end — a bf16 pool loses
only the matmul-input rounding, not the accumulation.

Prefix-cache sharing (PR 6): with the engine's prefix cache on, several
slots' block tables may point at the SAME pool block (a shared system
prompt computed once). Both decode backends tolerate that by
construction — context blocks are only ever READ through the table, and
the step's single write lands at the slot's own feed position, which
the engine guarantees sits in an exclusively-owned block (copy-on-write
promotes a shared block to a private copy via `copy_pool_block` before
any write could touch it). `paged_prefill_chunk` is the incremental
prefill step that makes tail-only prefill possible: it writes one
fixed-shape chunk of prompt KV and attends the chunk's queries over
everything the slot's table covers so far — including read-only shared
prefix blocks another request prefilled.

Speculative decoding (PR 7): `paged_verify_window` is the K-token
verify step's attention — a fixed `[slots, K+1]` window per decode
lane (the feed token plus up to K drafted tokens), per-row base
positions and draft lengths both traced, so ONE compiled program
serves every acceptance outcome. Window row `i` of slot `b` lives at
absolute position `positions[b] + i` and is LIVE iff
`i <= draft_lens[b]`; live rows write their k/v through the slot's
block table (the engine COW-promotes every block the window touches
first), dead rows (draft shorter than K, idle lanes) write the null
block. Each window query attends causally over the slot's context up
to its own position — so the target model scores all K+1 positions in
one pass, and rejected tokens need no cleanup: the engine simply does
not advance the slot position past them, and position-bounded masking
makes their stale KV rows unreachable until overwritten. Dispatches
through the same backend seam (`dense` fori-loop fallback /
`pallas` fused kernel, interpreter-run off-TPU).

Tensor-parallel serving (PR 8): every op here is HEAD-COUNT AGNOSTIC —
the head axis is read from the arrays, never from model config — so
the sharded engine runs the SAME ops per shard inside its shard_map
steps with per-shard pools `[L, blocks, bs, heads/mp, D]` and q/k/v
carrying heads/mp heads. Attention is independent per head, so no
collectives appear at this tier; the block tables and positions arrive
replicated (one logical allocator on the host), which is why a block
id means the same row range on every shard.

Implementation notes:
- functional `.at[].set` / aliased-pool writes chain through the layer
  stack; under the engine's donated compiled step XLA aliases them in
  place, so the pool is updated in HBM, not copied per layer.
- scatter/gather indices are per-slot vectors: one program serves any
  mix of slot positions (shape-stable steady-state decode — no
  per-request recompiles).
"""
from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from paddle_tpu.analysis.trace.contracts import TraceContract, \
    register_contract
from paddle_tpu.jit import introspect

from .dispatch import apply, as_tensor

__all__ = ["paged_attention_step", "paged_verify_window",
           "paged_prefill_write", "paged_prefill_chunk",
           "copy_pool_block", "dense_gather_reference",
           "resolve_backend", "PAGED_BACKENDS", "PAGED_PATH_STATS"]

PAGED_BACKENDS = ("auto", "dense", "pallas")

# which backend paged_attention_step dispatched to, incremented per
# call (so per TRACE under jit — the engine's compiled decode bumps it
# once per layer at compile time, never per step). Tests read it to
# prove the requested kernel actually engaged; the engine's
# kernel-backend gauge is set separately from resolve_backend() at
# construction. flash_attention.PATH_STATS precedent: never a silent
# fallback.
PAGED_PATH_STATS = {"dense": 0, "pallas": 0}


def reset_paged_path_stats():
    PAGED_PATH_STATS["dense"] = 0
    PAGED_PATH_STATS["pallas"] = 0


def _on_tpu():
    try:
        return jax.devices()[0].platform == "tpu" or \
            jax.default_backend() in ("tpu", "axon")
    except Exception:
        return False


def resolve_backend(backend, head_dim, block_size):
    """Resolve `auto`/`dense`/`pallas` to the backend a step will run.

    `auto` picks the fused kernel only on TPU and only at
    serving-scale shapes — head_dim >= 64 (the MXU lane width the
    kernel's per-block einsum needs to not run mostly-padded) and
    block_size >= 8 (sublane multiple; smaller blocks make the
    per-block DMA smaller than its descriptor overhead). Narrow-head /
    tiny-block configs stay dense: at those shapes the per-slot grid +
    per-block DMA overhead exceeds the gather traffic it saves —
    mirroring the `_xla_attention_bf16` crossover note in
    `ops/pallas/flash_attention.py`. Explicit `dense`/`pallas` always
    wins (off-TPU, `pallas` runs the interpreter — the CPU CI path)."""
    if backend not in PAGED_BACKENDS:
        raise ValueError(f"backend must be one of {PAGED_BACKENDS}, "
                         f"got {backend!r}")
    if backend != "auto":
        return backend
    if _on_tpu() and head_dim >= 64 and block_size >= 8:
        return "pallas"
    return "dense"


def paged_attention_step(q, k, v, kpool, vpool, layer, block_tables,
                         positions, scale=None, backend="auto"):
    """One batched decode step against the paged cache, for one layer.

    q/k/v: `[slots, 1, heads, head_dim]` — this step's projections.
    kpool/vpool: `[layers, num_blocks, block_size, heads, head_dim]`.
    layer: python int (static) — which layer's pool plane to use.
    block_tables: `[slots, max_blocks]` int32 pool-block ids per slot.
    positions: `[slots]` int32 — the incoming token's absolute position
    per slot (its write address; attention covers positions <= it).
    backend: `auto` | `dense` | `pallas` (see module docstring).

    Writes k/v at `(block_tables[s, pos//bs], pos%bs)` per slot, then
    attends q over the slot's context. Idle slots are encoded by the
    caller as (position 0, all-null table): they write into the null
    block and attend only their own garbage row, and the engine
    discards their token. Decode-only op: gradients are not defined
    through it. Returns `(out [slots,1,heads,head_dim], new_kpool,
    new_vpool)`.
    """
    q, k, v = as_tensor(q), as_tensor(k), as_tensor(v)
    kpool, vpool = as_tensor(kpool), as_tensor(vpool)
    block_tables, positions = as_tensor(block_tables), as_tensor(positions)

    resolved = resolve_backend(backend, head_dim=q.shape[3],
                               block_size=kpool.shape[2])
    PAGED_PATH_STATS[resolved] += 1
    if resolved == "pallas":
        from .pallas.paged_attention import paged_decode_attention

        interpret = not _on_tpu()

        def fn(qa, ka, va, kp, vp, bt, pos):
            return paged_decode_attention(qa, ka, va, kp, vp, layer,
                                          bt, pos, scale=scale,
                                          interpret=interpret)
    else:
        def fn(qa, ka, va, kp, vp, bt, pos):
            return _dense_step(qa, ka, va, kp, vp, layer, bt, pos,
                               scale)

    return apply("paged_attention_step", fn, q, k, v, kpool, vpool,
                 block_tables, positions)


def _dense_step(qa, ka, va, kp, vp, layer, bt, pos, scale):
    """XLA fallback: per-block online softmax over a fori_loop bounded
    by the batch high-water block count. Work per step is
    O(max(positions)) — the live-context high-water mark — not
    O(max_model_len) like a full-table gather; the traced trip count
    keeps the program shape-stable (no recompiles as context grows)."""
    B = qa.shape[0]
    heads, d = qa.shape[2], qa.shape[3]
    bs = kp.shape[2]
    bid_w = jnp.take_along_axis(bt, (pos // bs)[:, None], axis=1)[:, 0]
    off = pos % bs
    kp = kp.at[layer, bid_w, off].set(ka[:, 0])
    vp = vp.at[layer, bid_w, off].set(va[:, 0])
    s = scale if scale is not None else 1.0 / np.sqrt(d)
    # QK inputs stay at the pool dtype (bf16 MXU pass on TPU) with
    # fp32 accumulation — the SAME policy as the pallas kernel, so the
    # two backends see identical logits rounding and the cross-backend
    # token-exact contract holds at bf16, not just fp32
    qf = qa[:, 0].astype(kp.dtype)                 # [B, heads, d]
    hw_blocks = jnp.max(pos) // bs + 1             # traced scalar

    def body(j, carry):
        m, l, acc = carry
        bid = jax.lax.dynamic_index_in_dim(bt, j, axis=1,
                                           keepdims=False)   # [B]
        keys = kp[layer, bid]                      # [B, bs, heads, d]
        vals = vp[layer, bid]
        logits = jnp.einsum("bhd,bkhd->bhk", qf, keys,
                            preferred_element_type=jnp.float32) * s
        allowed = (j * bs + jnp.arange(bs))[None, :] <= pos[:, None]
        logits = jnp.where(allowed[:, None, :], logits, -1e30)
        m_new = jnp.maximum(m, jnp.max(logits, axis=-1, keepdims=True))
        p = jnp.exp(logits - m_new)                # [B, heads, bs] f32
        alpha = jnp.exp(m - m_new)
        l_new = alpha * l + jnp.sum(p, axis=-1, keepdims=True)
        # PV accumulates in fp32 (preferred_element_type): probs enter
        # the matmul at the pool dtype (bf16 MXU pass on TPU) but the
        # product never rounds to bf16 mid-accumulation
        pv = jnp.einsum("bhk,bkhd->bhd", p.astype(vals.dtype), vals,
                        preferred_element_type=jnp.float32)
        return m_new, l_new, acc * alpha + pv

    m0 = jnp.full((B, heads, 1), -1e30, jnp.float32)
    l0 = jnp.zeros((B, heads, 1), jnp.float32)
    acc0 = jnp.zeros((B, heads, d), jnp.float32)
    _, l, acc = jax.lax.fori_loop(0, hw_blocks, body, (m0, l0, acc0))
    out = (acc / jnp.maximum(l, 1e-30)).astype(qa.dtype)  # cast ONCE
    return out[:, None], kp, vp


def paged_verify_window(q, k, v, kpool, vpool, layer, block_tables,
                        positions, draft_lens, scale=None,
                        backend="auto"):
    """Speculative-verify attention over a fixed `[slots, W]` token
    window (W = K+1), for one layer.

    q/k/v: `[slots, W, heads, head_dim]` — the window's projections
    (feed token at row 0, drafted tokens after it).
    kpool/vpool: `[layers, num_blocks, block_size, heads, head_dim]`.
    layer: python int (static).
    block_tables: `[slots, max_blocks]` int32 pool-block ids per slot.
    positions: `[slots]` int32 — absolute position of window row 0
    (the slot's feed position).
    draft_lens: `[slots]` int32 in `[0, W-1]` — row `i` is live iff
    `i <= draft_lens[s]`; dead rows write the null block and their
    outputs are garbage the engine ignores.

    Live rows write k/v at `(table[(pos+i)//bs], (pos+i)%bs)`; every
    query attends causally over context `<= pos+i`. A draft_len of 0
    degenerates to `paged_attention_step` semantics on row 0 (the
    engine's draftless fallback under pool pressure). Idle lanes are
    (position 0, draft_len 0, all-null table), exactly the decode
    contract. Returns `(out [slots, W, heads, head_dim], new_kpool,
    new_vpool)`."""
    q, k, v = as_tensor(q), as_tensor(k), as_tensor(v)
    kpool, vpool = as_tensor(kpool), as_tensor(vpool)
    block_tables = as_tensor(block_tables)
    positions, draft_lens = as_tensor(positions), as_tensor(draft_lens)

    resolved = resolve_backend(backend, head_dim=q.shape[3],
                               block_size=kpool.shape[2])
    PAGED_PATH_STATS[resolved] += 1
    if resolved == "pallas":
        from .pallas.paged_attention import paged_verify_attention

        interpret = not _on_tpu()

        def fn(qa, ka, va, kp, vp, bt, pos, dlen):
            return paged_verify_attention(qa, ka, va, kp, vp, layer,
                                          bt, pos, dlen, scale=scale,
                                          interpret=interpret)
    else:
        def fn(qa, ka, va, kp, vp, bt, pos, dlen):
            return _dense_verify(qa, ka, va, kp, vp, layer, bt, pos,
                                 dlen, scale)

    return apply("paged_verify_window", fn, q, k, v, kpool, vpool,
                 block_tables, positions, draft_lens)


def _dense_verify(qa, ka, va, kp, vp, layer, bt, pos, dlen, scale):
    """XLA fallback for the verify window: the `_dense_step` online
    softmax widened to W queries per slot. Work per step is
    O(max(pos + dlen)) — the batch high-water mark including the
    window — with the traced trip count keeping one program for every
    (position, draft-length) mix."""
    B, W = qa.shape[0], qa.shape[1]
    heads, d = qa.shape[2], qa.shape[3]
    bs = kp.shape[2]
    maxb = bt.shape[1]
    wpos = pos[:, None] + jnp.arange(W)[None, :]       # [B, W] absolute
    live = jnp.arange(W)[None, :] <= dlen[:, None]     # [B, W]
    # dead rows (and any clamp overflow) land in the null block 0; the
    # table index is clamped so a dead row past the table stays in
    # bounds before the where() routes it to null
    bid = jnp.where(
        live, jnp.take_along_axis(bt, jnp.minimum(wpos // bs, maxb - 1),
                                  axis=1), 0)
    off = wpos % bs
    kp = kp.at[layer, bid, off].set(ka)                # [B, W, heads, d]
    vp = vp.at[layer, bid, off].set(va)
    s = scale if scale is not None else 1.0 / np.sqrt(d)
    # QK at the pool dtype with fp32 accumulation — the _dense_step
    # policy, so verify and plain decode share one rounding story
    qf = qa.astype(kp.dtype)                           # [B, W, heads, d]
    hw_blocks = jnp.max(pos + dlen) // bs + 1          # traced scalar

    def body(j, carry):
        m, l, acc = carry
        bidj = jax.lax.dynamic_index_in_dim(bt, j, axis=1,
                                            keepdims=False)    # [B]
        keys = kp[layer, bidj]                   # [B, bs, heads, d]
        vals = vp[layer, bidj]
        logits = jnp.einsum("bwhd,bkhd->bhwk", qf, keys,
                            preferred_element_type=jnp.float32) * s
        # causal per window row: key j*bs+k visible to window query w
        # iff it sits at or before that query's absolute position
        allowed = (j * bs + jnp.arange(bs))[None, None, :] \
            <= wpos[:, :, None]                  # [B, W, bs]
        logits = jnp.where(allowed[:, None, :, :], logits, -1e30)
        m_new = jnp.maximum(m, jnp.max(logits, axis=-1, keepdims=True))
        p = jnp.exp(logits - m_new)              # [B, heads, W, bs] f32
        alpha = jnp.exp(m - m_new)
        l_new = alpha * l + jnp.sum(p, axis=-1, keepdims=True)
        pv = jnp.einsum("bhwk,bkhd->bhwd", p.astype(vals.dtype), vals,
                        preferred_element_type=jnp.float32)
        return m_new, l_new, acc * alpha + pv

    m0 = jnp.full((B, heads, W, 1), -1e30, jnp.float32)
    l0 = jnp.zeros((B, heads, W, 1), jnp.float32)
    acc0 = jnp.zeros((B, heads, W, d), jnp.float32)
    _, l, acc = jax.lax.fori_loop(0, hw_blocks, body, (m0, l0, acc0))
    out = (acc / jnp.maximum(l, 1e-30)).astype(qa.dtype)  # cast ONCE
    return out.transpose(0, 2, 1, 3), kp, vp       # [B, W, heads, d]


def paged_prefill_write(kpool, vpool, kstack, vstack, block_row, plen):
    """Scatter a prefilled prompt's per-layer k/v into the pools.

    kstack/vstack: `[layers, 1, S, heads, head_dim]` from
    `GPTModel.forward_prefill` over the (bucket-padded) prompt.
    block_row: `[max_blocks]` int32 — the slot's block table.
    plen: true prompt length (may be traced — one compiled program per
    bucket size S, shared across every prompt length in the bucket).

    Positions >= plen (bucket padding) are routed to the null block 0,
    so padding never lands in allocated blocks. Returns the updated
    `(kpool, vpool)`.
    """
    kpool, vpool = as_tensor(kpool), as_tensor(vpool)
    kstack, vstack = as_tensor(kstack), as_tensor(vstack)
    block_row, plen = as_tensor(block_row), as_tensor(plen)

    def fn(kp, vp, ks, vs, row, n):
        S = ks.shape[2]
        bs = kp.shape[2]
        pos = jnp.arange(S)
        bid = jnp.where(pos < n, row[pos // bs], 0)
        off = pos % bs
        kp = kp.at[:, bid, off].set(ks[:, 0])    # [layers, S, heads, D]
        vp = vp.at[:, bid, off].set(vs[:, 0])
        return kp, vp

    return apply("paged_prefill_write", fn, kpool, vpool, kstack, vstack,
                 block_row, plen)


def paged_prefill_chunk(q, k, v, kpool, vpool, layer, block_row, start,
                        plen, scale=None):
    """One chunked-prefill step for ONE slot, for one layer: write the
    chunk's k/v into the pool, then attend the chunk's queries over the
    slot's whole context so far (shared prefix blocks + earlier chunks
    + the chunk itself, causally).

    q/k/v: `[1, C, heads, head_dim]` — this chunk's projections; C is
    the FIXED chunk width, so one compiled program serves every prompt
    length (`start` and `plen` are traced scalars).
    block_row: `[max_blocks]` int32 — the slot's block table.
    start: absolute position of the chunk's first token.
    plen: true prompt length. Chunk positions >= plen (tail padding)
    write to the null block 0 and their query outputs are garbage the
    caller ignores (same contract as bucketed prefill padding).

    Work is O(chunk x context-so-far) via the same traced-trip-count
    `fori_loop` online softmax as the dense decode step — identical
    numerics policy (fp32 logits/softmax state, fp32 PV accumulation,
    one cast at the end). Reads may cross blocks OTHER slots own (the
    prefix cache seats them read-only); writes never do — the chunk's
    write blocks were allocated exclusively to this slot. Returns
    `(out [1, C, heads, head_dim], new_kpool, new_vpool)`."""
    q, k, v = as_tensor(q), as_tensor(k), as_tensor(v)
    kpool, vpool = as_tensor(kpool), as_tensor(vpool)
    block_row = as_tensor(block_row)
    start, plen = as_tensor(start), as_tensor(plen)

    def fn(qa, ka, va, kp, vp, row, s0, n):
        C = qa.shape[1]
        heads, d = qa.shape[2], qa.shape[3]
        bs = kp.shape[2]
        maxb = row.shape[0]
        pos = s0 + jnp.arange(C)                       # absolute [C]
        valid = pos < n
        bid = jnp.where(valid,
                        row[jnp.minimum(pos // bs, maxb - 1)], 0)
        off = pos % bs
        kp = kp.at[layer, bid, off].set(ka[0])         # [C, heads, d]
        vp = vp.at[layer, bid, off].set(va[0])
        s = scale if scale is not None else 1.0 / np.sqrt(d)
        # QK at pool dtype, fp32 accumulation — the _dense_step policy,
        # so chunked and bucketed prefill see the same rounding story
        qf = qa[0].astype(kp.dtype)                    # [C, heads, d]
        end = jnp.minimum(s0 + C, n)                   # past-last pos
        hw_blocks = jnp.maximum(end - 1, 0) // bs + 1  # traced scalar

        def body(j, carry):
            m, l, acc = carry
            b = row[j]
            keys = kp[layer, b]                        # [bs, heads, d]
            vals = vp[layer, b]
            logits = jnp.einsum(
                "chd,khd->hck", qf, keys,
                preferred_element_type=jnp.float32) * s
            # causal over absolute positions: key j*bs+k visible to
            # query c iff it is at or before the query's position
            allowed = (j * bs + jnp.arange(bs))[None, :] <= pos[:, None]
            logits = jnp.where(allowed[None, :, :], logits, -1e30)
            m_new = jnp.maximum(m, jnp.max(logits, axis=-1,
                                           keepdims=True))
            p = jnp.exp(logits - m_new)                # [heads, C, bs]
            alpha = jnp.exp(m - m_new)
            l_new = alpha * l + jnp.sum(p, axis=-1, keepdims=True)
            pv = jnp.einsum("hck,khd->hcd", p.astype(vals.dtype), vals,
                            preferred_element_type=jnp.float32)
            return m_new, l_new, acc * alpha + pv

        m0 = jnp.full((heads, C, 1), -1e30, jnp.float32)
        l0 = jnp.zeros((heads, C, 1), jnp.float32)
        acc0 = jnp.zeros((heads, C, d), jnp.float32)
        _, l, acc = jax.lax.fori_loop(0, hw_blocks, body, (m0, l0, acc0))
        out = (acc / jnp.maximum(l, 1e-30)).astype(qa.dtype)
        return out.transpose(1, 0, 2)[None], kp, vp    # [1,C,heads,d]

    return apply("paged_prefill_chunk", fn, q, k, v, kpool, vpool,
                 block_row, start, plen)


# tpu-verify contract for the engine's compiled COW step (the op
# right below): donates both pools (introspect is the shared table),
# runs no collectives at any mp (plain jit over the sharded pools —
# the copy is row-local per shard), and must never bake constants or
# call back to host. Declared here because this module owns the step
# body.
register_contract(TraceContract(
    name="engine_cow_copy",
    declared_at="paddle_tpu/ops/paged_attention.py",
    donate_argnums=introspect.ENGINE_COW_DONATE_ARGNUMS))


def copy_pool_block(kpool, vpool, src, dst):
    """Copy one block's KV rows across every layer plane: the engine's
    copy-on-write step. `src`/`dst` may be traced scalars, so the
    engine compiles this ONCE and reuses it for every COW promotion
    (donated pools: XLA rewrites the dst rows in place in HBM). Raw
    jnp arrays in/out — this is a compiled-step body, not a user op."""
    srows = jax.lax.dynamic_index_in_dim(kpool, src, axis=1,
                                         keepdims=False)
    kpool = jax.lax.dynamic_update_index_in_dim(kpool, srows, dst,
                                                axis=1)
    srows = jax.lax.dynamic_index_in_dim(vpool, src, axis=1,
                                         keepdims=False)
    vpool = jax.lax.dynamic_update_index_in_dim(vpool, srows, dst,
                                                axis=1)
    return kpool, vpool


def dense_gather_reference(kpool, vpool, layer, block_row, length):
    """Parity probe: reassemble one slot's first `length` cached k/v
    rows from the pools into dense `[length, heads, head_dim]` arrays
    (host-side, concrete values). Tests compare this against the dense
    fixed-buffer cache the single-request decode path carries — and,
    across two engines, against each other (the pallas-vs-dense pool
    parity probe)."""
    kp = np.asarray(as_tensor(kpool)._array)[layer]
    vp = np.asarray(as_tensor(vpool)._array)[layer]
    row = np.asarray(as_tensor(block_row)._array)
    bs = kp.shape[1]
    pos = np.arange(int(length))
    return (kp[row[pos // bs], pos % bs],
            vp[row[pos // bs], pos % bs])
