"""Paged KV-cache attention helpers — the op tier under the
continuous-batching generation engine (paddle_tpu/inference/engine.py).

vLLM-PagedAttention-style layout, XLA edition: each layer's KV cache is
a global pool `[num_layers, num_blocks, block_size, heads, head_dim]`
shared by every in-flight request; a per-slot block table maps logical
token positions to pool blocks, so requests of different lengths share
HBM without per-request max-seq allocation. Block 0 is reserved as the
NULL block: idle decode slots and padded prefill positions write there,
and no allocator ever hands it out, so garbage writes can never alias a
live request's context.

Implementation notes (the dense-gather fallback):
- the per-step attention GATHERS each slot's blocks back into a
  contiguous `[slots, max_len, heads, head_dim]` view and runs plain
  masked attention — O(max_len) HBM traffic per slot per step, which is
  exactly what a fused Pallas paged-attention kernel (one core per
  slot, block-table-driven async copies HBM->VMEM) would remove. The
  helper is the single seam where that kernel slots in; everything
  above it (engine, model, tests) is layout-agnostic.
- functional `.at[].set` writes chain through the layer stack; under
  the engine's donated compiled step XLA aliases them in place, so the
  pool is updated in HBM, not copied per layer.
- scatter/gather indices are per-slot vectors: one program serves any
  mix of slot positions (shape-stable steady-state decode — no
  per-request recompiles).
"""
from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from .dispatch import apply, as_tensor

__all__ = ["paged_attention_step", "paged_prefill_write",
           "dense_gather_reference"]


def paged_attention_step(q, k, v, kpool, vpool, layer, block_tables,
                         positions, scale=None):
    """One batched decode step against the paged cache, for one layer.

    q/k/v: `[slots, 1, heads, head_dim]` — this step's projections.
    kpool/vpool: `[layers, num_blocks, block_size, heads, head_dim]`.
    layer: python int (static) — which layer's pool plane to use.
    block_tables: `[slots, max_blocks]` int32 pool-block ids per slot.
    positions: `[slots]` int32 — the incoming token's absolute position
    per slot (its write address; attention covers positions <= it).

    Writes k/v at `(block_tables[s, pos//bs], pos%bs)` per slot, then
    attends q over the slot's gathered context. Idle slots are encoded
    by the caller as (position 0, all-null table): they write into the
    null block and attend garbage, and the engine discards their token.
    Returns `(out [slots,1,heads,head_dim], new_kpool, new_vpool)`.
    """
    q, k, v = as_tensor(q), as_tensor(k), as_tensor(v)
    kpool, vpool = as_tensor(kpool), as_tensor(vpool)
    block_tables, positions = as_tensor(block_tables), as_tensor(positions)

    def fn(qa, ka, va, kp, vp, bt, pos):
        B = qa.shape[0]
        bs = kp.shape[2]
        bid = jnp.take_along_axis(bt, (pos // bs)[:, None], axis=1)[:, 0]
        off = pos % bs
        kp = kp.at[layer, bid, off].set(ka[:, 0])
        vp = vp.at[layer, bid, off].set(va[:, 0])
        # gather the slot's context back contiguous (the part a Pallas
        # paged kernel replaces with block-table-driven VMEM copies)
        keys = kp[layer][bt]      # [B, max_blocks, bs, heads, D]
        vals = vp[layer][bt]
        T = bt.shape[1] * bs
        keys = keys.reshape(B, T, keys.shape[3], keys.shape[4])
        vals = vals.reshape(B, T, vals.shape[3], vals.shape[4])
        d = qa.shape[-1]
        s = scale if scale is not None else 1.0 / np.sqrt(d)
        logits = jnp.einsum("bqhd,bkhd->bhqk", qa, keys,
                            preferred_element_type=jnp.float32) * s
        allowed = jnp.arange(T)[None, :] <= pos[:, None]     # [B, T]
        logits = jnp.where(allowed[:, None, None, :], logits, -1e30)
        probs = jax.nn.softmax(logits, axis=-1).astype(qa.dtype)
        out = jnp.einsum("bhqk,bkhd->bqhd", probs, vals)
        return out, kp, vp

    return apply("paged_attention_step", fn, q, k, v, kpool, vpool,
                 block_tables, positions)


def paged_prefill_write(kpool, vpool, kstack, vstack, block_row, plen):
    """Scatter a prefilled prompt's per-layer k/v into the pools.

    kstack/vstack: `[layers, 1, S, heads, head_dim]` from
    `GPTModel.forward_prefill` over the (bucket-padded) prompt.
    block_row: `[max_blocks]` int32 — the slot's block table.
    plen: true prompt length (may be traced — one compiled program per
    bucket size S, shared across every prompt length in the bucket).

    Positions >= plen (bucket padding) are routed to the null block 0,
    so padding never lands in allocated blocks. Returns the updated
    `(kpool, vpool)`.
    """
    kpool, vpool = as_tensor(kpool), as_tensor(vpool)
    kstack, vstack = as_tensor(kstack), as_tensor(vstack)
    block_row, plen = as_tensor(block_row), as_tensor(plen)

    def fn(kp, vp, ks, vs, row, n):
        S = ks.shape[2]
        bs = kp.shape[2]
        pos = jnp.arange(S)
        bid = jnp.where(pos < n, row[pos // bs], 0)
        off = pos % bs
        kp = kp.at[:, bid, off].set(ks[:, 0])    # [layers, S, heads, D]
        vp = vp.at[:, bid, off].set(vs[:, 0])
        return kp, vp

    return apply("paged_prefill_write", fn, kpool, vpool, kstack, vstack,
                 block_row, plen)


def dense_gather_reference(kpool, vpool, layer, block_row, length):
    """Parity probe: reassemble one slot's first `length` cached k/v
    rows from the pools into dense `[length, heads, head_dim]` arrays
    (host-side, concrete values). Tests compare this against the dense
    fixed-buffer cache the single-request decode path carries."""
    kp = np.asarray(as_tensor(kpool)._array)[layer]
    vp = np.asarray(as_tensor(vpool)._array)[layer]
    row = np.asarray(as_tensor(block_row)._array)
    bs = kp.shape[1]
    pos = np.arange(int(length))
    return (kp[row[pos // bs], pos % bs],
            vp[row[pos // bs], pos % bs])
