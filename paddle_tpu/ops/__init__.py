"""Op namespace + Tensor method installation.

Analog of the reference's generated python-C op table
(paddle/fluid/pybind/eager_op_function.cc exposed as core.eager.ops via
python/paddle/_C_ops.py:19) and the tensor method patch
(eager_math_op_patch.cc). Here the "registry" is plain python modules of
jax-backed ops, and install_tensor_methods() wires them onto Tensor.
"""
from __future__ import annotations

from paddle_tpu.core.tensor import Tensor

from . import (  # noqa: F401
    activation,
    creation,
    dispatch,
    linalg,
    manipulation,
    math,
    nn_ops,
    paged_attention,
    random_ops,
    reduction,
)
from .dispatch import apply, apply_nograd, as_tensor


def _install_tensor_methods():
    T = Tensor
    m, r, mp, lg, act = math, reduction, manipulation, linalg, activation

    # arithmetic dunders
    T.__add__ = lambda s, o: m.add(s, o)
    T.__radd__ = lambda s, o: m.add(o, s)
    T.__sub__ = lambda s, o: m.subtract(s, o)
    T.__rsub__ = lambda s, o: m.subtract(o, s)
    T.__mul__ = lambda s, o: m.multiply(s, o)
    T.__rmul__ = lambda s, o: m.multiply(o, s)
    T.__truediv__ = lambda s, o: m.divide(s, o)
    T.__rtruediv__ = lambda s, o: m.divide(o, s)
    T.__floordiv__ = lambda s, o: m.floor_divide(s, o)
    T.__mod__ = lambda s, o: m.mod(s, o)
    T.__pow__ = lambda s, o: m.pow(s, o)
    T.__rpow__ = lambda s, o: m.pow(o, s)
    T.__matmul__ = lambda s, o: lg.matmul(s, o)
    T.__rmatmul__ = lambda s, o: lg.matmul(o, s)
    T.__neg__ = lambda s: m.neg(s)
    T.__abs__ = lambda s: m.abs(s)
    T.__invert__ = lambda s: m.logical_not(s)
    # comparisons
    T.__eq__ = lambda s, o: m.equal(s, o)
    T.__ne__ = lambda s, o: m.not_equal(s, o)
    T.__lt__ = lambda s, o: m.less_than(s, o)
    T.__le__ = lambda s, o: m.less_equal(s, o)
    T.__gt__ = lambda s, o: m.greater_than(s, o)
    T.__ge__ = lambda s, o: m.greater_equal(s, o)
    T.__and__ = lambda s, o: m.logical_and(s, o)
    T.__or__ = lambda s, o: m.logical_or(s, o)
    T.__xor__ = lambda s, o: m.logical_xor(s, o)
    # indexing
    T.__getitem__ = lambda s, item: mp.getitem(s, item)
    T.__setitem__ = lambda s, item, v: mp.setitem(s, item, v)

    # named methods (paddle Tensor method surface)
    for name, fn in [
        ("add", m.add), ("subtract", m.subtract), ("multiply", m.multiply),
        ("divide", m.divide), ("mod", m.mod), ("pow", m.pow),
        ("maximum", m.maximum), ("minimum", m.minimum),
        ("exp", m.exp), ("log", m.log), ("sqrt", m.sqrt), ("rsqrt", m.rsqrt),
        ("abs", m.abs), ("sign", m.sign), ("floor", m.floor), ("ceil", m.ceil),
        ("round", m.round), ("reciprocal", m.reciprocal), ("square", m.square),
        ("sin", m.sin), ("cos", m.cos), ("tan", m.tan), ("tanh", m.tanh),
        ("erf", m.erf), ("clip", m.clip), ("scale", m.scale), ("cast", m.cast),
        ("astype", m.cast), ("isnan", m.isnan), ("isinf", m.isinf),
        ("isfinite", m.isfinite), ("equal", m.equal), ("not_equal", m.not_equal),
        ("less_than", m.less_than), ("greater_than", m.greater_than),
        ("logical_and", m.logical_and), ("logical_or", m.logical_or),
        ("logical_not", m.logical_not), ("where", m.where),
        # reductions
        ("sum", r.sum), ("mean", r.mean), ("max", r.max), ("min", r.min),
        ("prod", r.prod), ("std", r.std), ("var", r.var),
        ("argmax", r.argmax), ("argmin", r.argmin), ("argsort", r.argsort),
        ("sort", r.sort), ("topk", r.topk), ("all", r.all), ("any", r.any),
        ("cumsum", r.cumsum), ("cumprod", r.cumprod), ("logsumexp", r.logsumexp),
        ("unique", r.unique), ("nonzero", r.nonzero),
        # manipulation
        ("reshape", mp.reshape), ("flatten", mp.flatten),
        ("squeeze", mp.squeeze), ("unsqueeze", mp.unsqueeze),
        ("transpose", mp.transpose), ("split", mp.split), ("chunk", mp.chunk),
        ("tile", mp.tile), ("expand", mp.expand), ("expand_as", mp.expand_as),
        ("broadcast_to", mp.broadcast_to), ("flip", mp.flip), ("roll", mp.roll),
        ("gather", mp.gather), ("gather_nd", mp.gather_nd),
        ("scatter", mp.scatter), ("index_select", mp.index_select),
        ("masked_select", mp.masked_select), ("unbind", mp.unbind),
        ("repeat_interleave", mp.repeat_interleave), ("numel", mp.numel),
        ("pad", mp.pad),
        # linalg
        ("matmul", lg.matmul), ("mm", lg.mm), ("bmm", lg.bmm), ("dot", lg.dot),
        ("norm", lg.norm), ("dist", lg.dist), ("t", lg.t), ("trace", lg.trace),
        ("cholesky", lg.cholesky), ("inverse", lg.inverse),
        # activation-ish
        ("softmax", act.softmax), ("sigmoid", act.sigmoid), ("relu", act.relu),
        # op-parity batch (special fns / complex / index / misc)
        ("frac", m.frac), ("lgamma", m.lgamma), ("digamma", m.digamma),
        ("conj", m.conj), ("real", m.real), ("imag", m.imag),
        ("angle", m.angle), ("sgn", m.sgn), ("logit", m.logit),
        ("erfinv", m.erfinv), ("expm1", m.expm1), ("fmax", m.fmax),
        ("fmin", m.fmin), ("remainder", m.remainder), ("fmod", m.fmod),
        ("copysign", m.copysign), ("hypot", m.hypot),
        ("isclose", m.isclose), ("allclose", m.allclose),
        ("equal_all", m.equal_all), ("multiply_", m.multiply_),
        ("take", mp.take), ("diff", mp.diff), ("swapaxes", mp.swapaxes),
        ("swapdims", mp.swapdims),
        ("as_strided", mp.as_strided), ("bucketize", mp.bucketize),
        ("nanmedian", r.nanmedian), ("trapezoid", r.trapezoid),
        ("cov", lg.cov), ("corrcoef", lg.corrcoef),
    ]:
        setattr(T, name, fn)

    T.T = property(lambda s: mp.transpose(s))
    T.item = T.item  # keep
    T.dim = lambda s: s.ndim


_install_tensor_methods()
