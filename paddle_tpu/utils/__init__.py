"""paddle.utils analog: custom-op toolchain (cpp_extension) and model
utilities."""
from . import cpp_extension

__all__ = ["cpp_extension"]
