// paddle_tpu custom-op C ABI (get_include() ships this header).
// Elementwise op:  PT_EXPORT void f(const T* x, T* y, int64_t n);
// Its backward:    PT_EXPORT void f_grad(const T* x, const T* gy,
//                                        T* gx, int64_t n);
#pragma once
#include <cstdint>
#define PT_EXPORT extern "C"
