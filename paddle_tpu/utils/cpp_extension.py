"""Custom-op toolchain — analog of python/paddle/utils/cpp_extension/
(CppExtension/CUDAExtension/load at cpp_extension.py; C++ side
framework/custom_operator.cc, phi/api/ext/op_meta_info.h).

TPU-native split of the capability:

- **C++ host ops** (`load` + `CustomOpLibrary.wrap_elementwise`): user
  C++ compiled with g++ into a shared library, invoked through
  jax.pure_callback — runs host-side, works eagerly and inside jit
  (XLA inserts the host transfer), differentiable when a backward
  symbol is provided (jax.custom_vjp). This is the "extend without
  forking" seam for host preprocessing / CPU reference kernels.
- **Device custom kernels** (`custom_op`): arbitrary jax/Pallas
  functions registered as paddle ops with optional custom VJP — the
  TPU path for performance-critical fused kernels (the CUDAExtension
  analog; see ops/pallas/flash_attention.py for the house style).
- **Wheel builds** (`CppExtension` + `BuildExtension` + `setup`): thin
  setuptools passthroughs so a reference-style setup.py keeps working.
"""
from __future__ import annotations

import ctypes
import os
import subprocess
import tempfile
from typing import Callable, Optional, Sequence

import numpy as np

__all__ = ["CppExtension", "CUDAExtension", "BuildExtension", "setup",
           "load", "get_include", "CustomOpLibrary", "custom_op"]

_DTYPES = {
    "float32": (ctypes.c_float, np.float32),
    "float64": (ctypes.c_double, np.float64),
    "int32": (ctypes.c_int32, np.int32),
    "int64": (ctypes.c_int64, np.int64),
}


def get_include() -> str:
    """Directory containing paddle_ext.h — the PD_BUILD_OP analog: a
    plain C ABI instead of a macro DSL (shipped as package data)."""
    return os.path.join(os.path.dirname(__file__), "include")


def load(name: str, sources: Sequence[str], extra_cflags=None,
         extra_ldflags=None, build_directory: Optional[str] = None,
         verbose: bool = False) -> "CustomOpLibrary":
    """JIT-compile C++ sources into a shared library and load it
    (cpp_extension.load parity). Returns a CustomOpLibrary."""
    import hashlib

    build_dir = build_directory or os.path.join(
        tempfile.gettempdir(), "paddle_tpu_extensions", name)
    os.makedirs(build_dir, exist_ok=True)
    # build options are part of the cache identity (reference load()
    # hashes them too): changed flags must not reuse a stale binary
    tag = hashlib.sha1(repr((sorted(extra_cflags or []),
                             sorted(extra_ldflags or [])))
                       .encode()).hexdigest()[:8]
    so_path = os.path.join(build_dir, f"{name}-{tag}.so")
    cmd = ["g++", "-O2", "-shared", "-fPIC", "-std=c++17",
           f"-I{get_include()}", *list(sources),
           *(extra_cflags or []), *(extra_ldflags or []), "-o", so_path]
    # rebuild only when a source is newer than the library
    if not os.path.exists(so_path) or any(
            os.path.getmtime(s) > os.path.getmtime(so_path)
            for s in sources):
        if verbose:
            print("compiling:", " ".join(cmd))
        res = subprocess.run(cmd, capture_output=True, text=True)
        if res.returncode != 0:
            raise RuntimeError(
                f"custom-op build failed:\n{res.stderr[:4000]}")
    return CustomOpLibrary(name, so_path)


def _callback_apply(apply_fn, opname, f, t):
    """apply() with an eager CPU hop on backends that cannot lower host
    callbacks (shared protocol: ops.dispatch.apply_with_cpu_fallback)."""
    from paddle_tpu.core.device import supports_host_callback
    from paddle_tpu.ops.dispatch import apply_with_cpu_fallback

    return apply_with_cpu_fallback(apply_fn, opname, f, t,
                                   supports_host_callback)


class CustomOpLibrary:
    """A loaded custom-op shared library. Raw symbols via .symbol(name);
    differentiable paddle ops via .wrap_elementwise(...)."""

    def __init__(self, name: str, so_path: str):
        self.name = name
        self.so_path = so_path
        self._lib = ctypes.CDLL(so_path)

    def symbol(self, name: str):
        return getattr(self._lib, name)

    def wrap_elementwise(self, symbol: str, backward: Optional[str] = None,
                         dtype: str = "float32") -> Callable:
        """Expose `void symbol(const T* x, T* y, int64_t n)` as a
        differentiable paddle op. `backward` names
        `void b(const T* x, const T* gy, T* gx, int64_t n)`; without it
        the op is forward-only (stop_gradient outputs)."""
        import jax
        import jax.numpy as jnp

        from paddle_tpu.ops.dispatch import apply, apply_nograd, as_tensor

        cptr, npdt = _DTYPES[dtype]
        fwd_c = self.symbol(symbol)
        fwd_c.argtypes = [ctypes.POINTER(cptr), ctypes.POINTER(cptr),
                          ctypes.c_int64]
        fwd_c.restype = None

        def host_fwd(x):
            x = np.ascontiguousarray(x, npdt)
            y = np.empty_like(x)
            fwd_c(x.ctypes.data_as(ctypes.POINTER(cptr)),
                  y.ctypes.data_as(ctypes.POINTER(cptr)),
                  ctypes.c_int64(x.size))
            return y

        jdt = jnp.dtype(npdt)

        def check_dtype(t):
            if jnp.dtype(t._array.dtype) != jdt:
                raise TypeError(
                    f"custom op {symbol!r} is registered for {dtype}; got "
                    f"a {t._array.dtype} tensor — cast the input or wrap "
                    f"the symbol for that dtype")
            return t

        def cb_fwd(a):
            return jax.pure_callback(
                host_fwd, jax.ShapeDtypeStruct(a.shape, jdt), a,
                vmap_method="sequential")

        if backward is None:
            def op(x):
                return _callback_apply(apply_nograd, symbol, cb_fwd,
                                       check_dtype(as_tensor(x)))
            op.__name__ = symbol
            return op

        bwd_c = self.symbol(backward)
        bwd_c.argtypes = [ctypes.POINTER(cptr), ctypes.POINTER(cptr),
                          ctypes.POINTER(cptr), ctypes.c_int64]
        bwd_c.restype = None

        def host_bwd(x, gy):
            x = np.ascontiguousarray(x, npdt)
            gy = np.ascontiguousarray(gy, npdt)
            gx = np.empty_like(x)
            bwd_c(x.ctypes.data_as(ctypes.POINTER(cptr)),
                  gy.ctypes.data_as(ctypes.POINTER(cptr)),
                  gx.ctypes.data_as(ctypes.POINTER(cptr)),
                  ctypes.c_int64(x.size))
            return gx

        @jax.custom_vjp
        def f(a):
            return cb_fwd(a)

        def f_fwd(a):
            return cb_fwd(a), a

        def f_bwd(a, ct):
            gx = jax.pure_callback(
                host_bwd, jax.ShapeDtypeStruct(a.shape, jdt), a, ct,
                vmap_method="sequential")
            return (gx,)

        f.defvjp(f_fwd, f_bwd)

        def op(x):
            return _callback_apply(apply, symbol, f,
                                   check_dtype(as_tensor(x)))
        op.__name__ = symbol
        return op


def custom_op(name: Optional[str] = None, fwd: Optional[Callable] = None,
              bwd: Optional[Callable] = None):
    """Register a jax/Pallas function as a paddle op (the device-side
    custom-kernel path — CUDAExtension's role on TPU).

        @custom_op(name="fused_swiglu")
        def fused_swiglu(a, b):            # jnp / pallas_call code
            return a * jax.nn.sigmoid(a) * b

    With `fwd`/`bwd` the op gets a custom VJP (jax.custom_vjp contract:
    fwd(*args) -> (out, residuals); bwd(residuals, ct) -> grads tuple),
    which survives both eager autograd and jit tracing."""

    def deco(fn):
        import jax

        from paddle_tpu.ops.dispatch import apply, as_tensor

        opname = name or fn.__name__
        if (fwd is None) != (bwd is None):
            raise ValueError("custom_op needs both fwd and bwd, or neither")
        if fwd is not None:
            f = jax.custom_vjp(fn)
            f.defvjp(fwd, bwd)
        else:
            f = fn

        def op(*xs, **kw):
            # scalar args adopt the first *Tensor* arg's dtype (as_tensor
            # dereferences ref._array — a raw ndarray ref would crash)
            ref = next((x for x in xs if hasattr(x, "_array")), None)
            tensors = [as_tensor(x, ref) for x in xs]
            return apply(opname, lambda *arrs: f(*arrs, **kw), *tensors)
        op.__name__ = opname
        op.raw = f
        return op

    return deco


# -- wheel-build tier (setuptools passthrough) ---------------------------
def CppExtension(name=None, sources=(), *args, **kwargs):
    """setuptools.Extension preconfigured with our include dir
    (reference CppExtension parity for setup.py builds)."""
    from setuptools import Extension

    kwargs.setdefault("include_dirs", []).append(get_include())
    kwargs.setdefault("language", "c++")
    return Extension(name or "paddle_tpu_ext", list(sources),
                     *args, **kwargs)


def CUDAExtension(*args, **kwargs):
    raise NotImplementedError(
        "this build targets TPU with zero CUDA; write device kernels in "
        "Pallas and register them with paddle.utils.cpp_extension."
        "custom_op (see ops/pallas/flash_attention.py)")


def BuildExtension(*args, **kwargs):
    from setuptools.command.build_ext import build_ext

    return build_ext(*args, **kwargs) if args else build_ext


def setup(**kwargs):
    import setuptools

    kwargs.setdefault("cmdclass", {})["build_ext"] = BuildExtension
    return setuptools.setup(**kwargs)
