"""Global FLAGS registry — analog of the reference's gflags-style flag
system (paddle/utils/flags.h, python paddle.set_flags/get_flags via
pybind GlobalVarGetterSetterRegistry). Flags initialize from the
environment (FLAGS_xxx=1, the reference's export convention).

Debug flags wired in:
  FLAGS_check_nan_inf        — eager ops AND compiled train steps verify
                               outputs/grads are finite
                               (fluid/eager/nan_inf_utils.h:37 analog;
                               inside compiled programs this stages a
                               jax.debug.callback, SURVEY §7 hard-part)
  FLAGS_check_nan_inf_level  — 0: raise on nan/inf; 3: warn only
"""
from __future__ import annotations

import os

__all__ = ["set_flags", "get_flags"]

_DEFAULTS = {
    "FLAGS_check_nan_inf": False,
    "FLAGS_check_nan_inf_level": 0,
    "FLAGS_cudnn_deterministic": False,   # accepted for parity; XLA on
    "FLAGS_embedding_deterministic": 0,   # TPU is deterministic already
}


def _coerce(name, value):
    proto = _DEFAULTS[name]
    if isinstance(proto, bool):
        if isinstance(value, str):
            return value.lower() in ("1", "true", "yes", "on")
        return bool(value)
    if isinstance(proto, int):
        return int(value)
    return value


_FLAGS = {k: _coerce(k, os.environ[k]) if k in os.environ else v
          for k, v in _DEFAULTS.items()}


_EPOCH = [0]


def debug_epoch():
    """Bumped by set_flags. Compiled-program caches (TrainStep,
    StaticFunction, hapi eval) key on this so flag changes take effect
    on already-compiled paths — flags are read at trace time, so a stale
    cache would silently ignore a toggle."""
    return _EPOCH[0]


def set_flags(flags: dict):
    """paddle.set_flags parity: {'FLAGS_check_nan_inf': 1}."""
    for k, v in flags.items():
        if k not in _DEFAULTS:
            raise ValueError(f"unknown flag {k!r}; known: "
                             f"{sorted(_DEFAULTS)}")
        _FLAGS[k] = _coerce(k, v)
    _EPOCH[0] += 1


def get_flags(flags):
    """paddle.get_flags parity: name or list of names -> dict."""
    names = [flags] if isinstance(flags, str) else list(flags)
    out = {}
    for k in names:
        if k not in _FLAGS:
            raise ValueError(f"unknown flag {k!r}")
        out[k] = _FLAGS[k]
    return out


def flag(name):
    """Fast internal read."""
    return _FLAGS[name]
