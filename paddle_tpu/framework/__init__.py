from .io import load, save

__all__ = ["save", "load"]
