"""Sharded (per-host) checkpointing — analog of the reference's
distributed save/load (fleet save_persistables per-rank shards,
group_sharded save; SURVEY §5 checkpoint row).

Each process writes ONLY the shards it holds in addressable memory
(jax.Array.addressable_shards), so a multi-host job checkpoints in
parallel with no gather traffic; a meta.json records global shapes. Load
reassembles arrays from every host file and (optionally) re-places them
onto a NEW sharding layout — topology can change between save and load
(the reshard-on-load contract orbax popularized; implemented directly so
the format stays a plain npz + json any tool can read).
"""
from __future__ import annotations

import json
import os

import jax
import numpy as np

from paddle_tpu.core.tensor import Tensor

__all__ = ["save_sharded", "load_sharded"]


def _slice_key(idx, ndim):
    """Serialize a shard's global-slice tuple: 'a:b,c:d,...'."""
    parts = []
    full = idx if idx else (slice(None),) * ndim
    for s in full:
        start = 0 if s.start is None else int(s.start)
        stop = -1 if s.stop is None else int(s.stop)
        parts.append(f"{start}:{stop}")
    return ",".join(parts)


def save_sharded(state_dict, path):
    """state_dict: name -> Tensor/array. Writes
    {path}/meta.json + {path}/shard_{proc}.npz (this process's shards
    only; every process must call this)."""
    os.makedirs(path, exist_ok=True)
    proc = jax.process_index()
    meta = {}
    blobs = {}
    for name, t in state_dict.items():
        arr = t._array if isinstance(t, Tensor) else t
        meta[name] = {"shape": list(np.shape(arr)),
                      "dtype": str(np.asarray(arr).dtype
                                   if not hasattr(arr, "dtype")
                                   else arr.dtype)}
        def to_np(a):
            a = np.asarray(a)
            if a.dtype.name == "bfloat16":  # npz has no bf16: bitcast
                return a.view(np.uint16)
            return a

        if hasattr(arr, "addressable_shards"):
            written = set()
            for sh in arr.addressable_shards:
                key = _slice_key(sh.index, arr.ndim)
                if key in written:  # replicated: one copy is enough
                    continue
                written.add(key)
                blobs[f"{name}|{key}"] = to_np(sh.data)
        else:
            blobs[f"{name}|{_slice_key((), np.ndim(arr))}"] = to_np(arr)
    np.savez(os.path.join(path, f"shard_{proc}.npz"), **blobs)
    if proc == 0:
        with open(os.path.join(path, "meta.json"), "w") as f:
            json.dump({"tensors": meta,
                       "process_count": jax.process_count()}, f)


def _parse_slices(key, shape):
    out = []
    for part, dim in zip(key.split(","), shape):
        a, b = part.split(":")
        out.append(slice(int(a), dim if int(b) == -1 else int(b)))
    return tuple(out)


def load_sharded(path, shardings=None):
    """Reassemble {name: np.ndarray} from all shard files; with
    `shardings` (name -> jax Sharding) the arrays are device_put onto the
    NEW layout — resharding across topologies is just a different
    shardings map."""
    import glob as _glob

    import ml_dtypes

    with open(os.path.join(path, "meta.json")) as f:
        meta = json.load(f)["tensors"]
    bf16 = {name for name, m in meta.items() if m["dtype"] == "bfloat16"}
    out = {name: np.zeros(m["shape"],
                          ml_dtypes.bfloat16 if name in bf16
                          else np.dtype(m["dtype"]))
           for name, m in meta.items()}

    for fn in sorted(_glob.glob(os.path.join(path, "shard_*.npz"))):
        with np.load(fn, allow_pickle=False) as z:
            for key in z.files:
                name, slices = key.split("|", 1)
                data = z[key]
                if name in bf16:
                    data = data.view(ml_dtypes.bfloat16)
                out[name][_parse_slices(slices, meta[name]["shape"])] = data

    result = {}
    for name, arr in out.items():
        a = arr
        if shardings and name in shardings:
            a = jax.device_put(a, shardings[name])
        result[name] = a
    return result
