"""NaN/Inf debugging — analog of FLAGS_check_nan_inf
(paddle/fluid/eager/nan_inf_utils.h:37 CheckTensorHasNanOrInf, legacy
framework/details/nan_inf_utils_detail.*).

Eager ops check concrete outputs directly. Inside compiled programs
(TrainStep, to_static, run_scan) the check is STAGED: finiteness flags
are computed in-graph (cheap fused reductions) and a jax.debug.callback
raises host-side with the offending names — the SURVEY §7 "debug inside
compiled programs" hard-part. Enable with
paddle.set_flags({'FLAGS_check_nan_inf': 1}); level 3 warns instead of
raising.
"""
from __future__ import annotations

import warnings

import jax
import jax.numpy as jnp
import numpy as np

from .flags import flag

__all__ = ["check_enabled", "check_eager", "stage_check"]


def check_enabled():
    return flag("FLAGS_check_nan_inf")


def _report(bad_names, where):
    from paddle_tpu.observability.metrics import get_registry

    get_registry().counter(
        "nan_inf_events_total",
        "NaN/Inf detections (FLAGS_check_nan_inf); each event may "
        "cover several tensors of one op/step.").inc()
    msg = (f"nan/inf detected in {where}: {', '.join(bad_names)} "
           "(FLAGS_check_nan_inf)")
    if flag("FLAGS_check_nan_inf_level") >= 3:
        warnings.warn(msg)
    else:
        raise FloatingPointError(msg)


def check_eager(op_name, arrays):
    """Concrete (non-tracer) outputs of one eager op."""
    bad = [f"output[{i}]" for i, a in enumerate(arrays)
           if jnp.issubdtype(a.dtype, jnp.inexact) and
           not bool(jnp.isfinite(a).all())]
    if bad:
        _report(bad, f"op '{op_name}'")


def stage_check(named_arrays, where):
    """Inside a trace: stage finite-checks + one host callback. The
    in-graph part is a per-tensor all-finite reduction (XLA fuses these);
    the callback only sees booleans, so the hot data never leaves HBM."""
    named = [(n, a) for n, a in named_arrays
             if hasattr(a, "dtype") and jnp.issubdtype(a.dtype, jnp.inexact)]
    if not named:
        return
    flags = jnp.stack([jnp.isfinite(a).all() for _, a in named])
    names = [n for n, _ in named]

    def cb(ok):
        ok = np.asarray(ok)
        if not ok.all():
            _report([n for n, o in zip(names, ok) if not o], where)

    jax.debug.callback(cb, flags)
