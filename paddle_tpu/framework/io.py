"""paddle.save / paddle.load analog (python/paddle/framework/io.py:637/:879).

Pickle-compatible nested state dicts; Tensors serialize as numpy arrays
(the DenseTensor-proto analog of phi/core/serialization.cc). bfloat16
round-trips via a tagged uint16 view (numpy has no native bf16).
"""
from __future__ import annotations

import os
import pickle

import jax.numpy as jnp
import numpy as np

from paddle_tpu.core.tensor import Tensor

_BF16_TAG = "__paddle_tpu_bf16__"


def _to_host(obj):
    if isinstance(obj, Tensor):
        arr = np.asarray(obj._array)
        if arr.dtype == jnp.bfloat16:
            return {_BF16_TAG: True, "data": arr.view(np.uint16)}
        return arr
    if isinstance(obj, dict):
        return {k: _to_host(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return type(obj)(_to_host(v) for v in obj)
    return obj


def _from_host(obj):
    if isinstance(obj, dict):
        if obj.get(_BF16_TAG):
            return Tensor(obj["data"].view(jnp.bfloat16))
        return {k: _from_host(v) for k, v in obj.items()}
    if isinstance(obj, np.ndarray):
        return Tensor(obj)
    if isinstance(obj, (list, tuple)):
        return type(obj)(_from_host(v) for v in obj)
    return obj


def save(obj, path, protocol=4, **configs):
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    with open(path, "wb") as f:
        pickle.dump(_to_host(obj), f, protocol=protocol)


def load(path, **configs):
    with open(path, "rb") as f:
        obj = pickle.load(f)
    if configs.get("return_numpy"):
        return obj
    return _from_host(obj)
