"""ASP — automatic structured (n:m) sparsity, analog of
python/paddle/incubate/asp/ (prune_model, decorate, calculate_density).

TPU note: v5e has no sparse-math unit, so n:m sparsity here is a model
-compression capability (mask + keep-masked-through-training), not a
speedup; masks are enforced after every optimizer step by decorate()
exactly like the reference's OptimizerWithSparsityGuarantee.

State scoping: each pruned parameter carries its own mask
(`param._asp_mask`) and exclusions live on the model
(`model._asp_excluded`) — nothing is process-global, so independent
models never interact and discarded models are garbage-collected.
"""
from __future__ import annotations

import numpy as np

import paddle_tpu.nn as nn

__all__ = ["calculate_density", "create_mask", "check_mask_1d",
           "prune_model", "decorate", "set_excluded_layers",
           "reset_excluded_layers"]


def calculate_density(mat) -> float:
    a = np.asarray(mat)
    return float(np.count_nonzero(a)) / max(a.size, 1)


def create_mask(weight, n=2, m=4) -> np.ndarray:
    """n:m mask along the input (reduction) dim: within every group of m
    consecutive weights, keep the n largest |w| (mask_1d algorithm).
    A non-divisible trailing remainder (dim % m) stays dense."""
    w = np.asarray(weight, np.float32)
    if w.ndim < 2 or w.shape[0] < m:
        return np.ones_like(w, np.float32)
    main = (w.shape[0] // m) * m
    flat = np.abs(w[:main]).reshape(main // m, m, -1)
    order = np.argsort(flat, axis=1)
    mask_main = np.ones_like(flat)
    drop = order[:, : m - n, :]
    np.put_along_axis(mask_main, drop, 0.0, axis=1)
    mask = np.ones_like(w, np.float32)
    mask[:main] = mask_main.reshape(main, *w.shape[1:])
    return mask


def check_mask_1d(mat, n=2, m=4) -> bool:
    """True iff every complete m-group keeps at most n nonzeros (the
    dense remainder of a non-divisible dim is ignored)."""
    a = np.asarray(mat)
    if a.ndim < 2 or a.shape[0] < m:
        return False
    main = (a.shape[0] // m) * m
    nz = (np.abs(a[:main]).reshape(main // m, m, -1) > 0).sum(axis=1)
    return bool((nz <= n).all())


def set_excluded_layers(model, layer_names):
    """Exclude named sublayers of THIS model from prune_model."""
    excl = getattr(model, "_asp_excluded", None)
    if excl is None:
        object.__setattr__(model, "_asp_excluded", set())
        excl = model._asp_excluded
    excl.update(layer_names)


def reset_excluded_layers(model=None):
    if model is not None and hasattr(model, "_asp_excluded"):
        model._asp_excluded.clear()


def prune_model(model, n=2, m=4, mask_algo="mask_1d", with_mask=True):
    """Mask every Linear weight to n:m sparsity. Masks are recorded on
    each pruned layer so a decorate()'d optimizer managing its params
    re-applies them after every step. Returns {param_name: mask} for
    the layers whose weights actually changed."""
    import jax.numpy as jnp

    if mask_algo not in ("mask_1d",):
        raise NotImplementedError(f"mask_algo={mask_algo!r}; 'mask_1d' only")
    excluded = getattr(model, "_asp_excluded", set())
    out = {}
    for name, sub in model.named_sublayers():
        if name in excluded or not isinstance(sub, nn.Linear):
            continue
        w = sub.weight
        mask = create_mask(np.asarray(w._array), n=n, m=m)
        if not (mask == 0).any():
            continue  # nothing prunable (e.g. dim < m): not "pruned"
        w._array = (jnp.asarray(np.asarray(w._array, np.float32) * mask)
                    .astype(w._array.dtype))
        if with_mask:
            w._asp_mask = mask  # decorate() reads this off the param
        out[f"{name}.weight"] = mask
    return out


def decorate(optimizer):
    """Wrap optimizer.step to re-apply pruning masks after the update
    (OptimizerWithSparsityGuarantee analog). Only parameters managed by
    THIS optimizer are re-masked."""
    import jax.numpy as jnp

    orig_step = optimizer.step

    def step_with_masks(*a, **kw):
        r = orig_step(*a, **kw)
        # masks are read off the params lazily: prune_model may run
        # before or after decorate
        for p in optimizer._parameter_list:
            mask = getattr(p, "_asp_mask", None)
            if mask is not None:
                p._array = (jnp.asarray(
                    np.asarray(p._array, np.float32) * mask)
                    .astype(p._array.dtype))
        return r

    optimizer.step = step_with_masks
    return optimizer
