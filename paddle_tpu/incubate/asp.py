"""ASP — automatic structured (n:m) sparsity, analog of
python/paddle/incubate/asp/ (prune_model, decorate, calculate_density).

TPU note: v5e has no sparse-math unit, so n:m sparsity here is a model
-compression capability (mask + keep-masked-through-training), not a
speedup; masks are enforced after every optimizer step by decorate()
exactly like the reference's OptimizerWithSparsityGuarantee.

State scoping: each pruned parameter carries its own mask
(`param._asp_mask`) and exclusions live on the model
(`model._asp_excluded`) — nothing is process-global, so independent
models never interact and discarded models are garbage-collected.
"""
from __future__ import annotations

import numpy as np

import paddle_tpu.nn as nn

__all__ = ["calculate_density", "create_mask", "check_mask_1d",
           "create_mask_2d_greedy", "check_mask_2d", "prune_model",
           "decorate", "set_excluded_layers", "reset_excluded_layers"]


def calculate_density(mat) -> float:
    a = np.asarray(mat)
    return float(np.count_nonzero(a)) / max(a.size, 1)


def create_mask(weight, n=2, m=4) -> np.ndarray:
    """n:m mask along the input (reduction) dim: within every group of m
    consecutive weights, keep the n largest |w| (mask_1d algorithm).
    A non-divisible trailing remainder (dim % m) stays dense."""
    w = np.asarray(weight, np.float32)
    if w.ndim < 2 or w.shape[0] < m:
        return np.ones_like(w, np.float32)
    main = (w.shape[0] // m) * m
    flat = np.abs(w[:main]).reshape(main // m, m, -1)
    order = np.argsort(flat, axis=1)
    mask_main = np.ones_like(flat)
    drop = order[:, : m - n, :]
    np.put_along_axis(mask_main, drop, 0.0, axis=1)
    mask = np.ones_like(w, np.float32)
    mask[:main] = mask_main.reshape(main, *w.shape[1:])
    return mask


def create_mask_2d_greedy(weight, n=2, m=4) -> np.ndarray:
    """2-D n:m mask (asp mask_2d_greedy analog): within every m x m
    block, keep entries so that EVERY row and EVERY column of the block
    has at most n survivors, chosen greedily by |w| descending. Blocks
    beyond a non-divisible edge stay dense."""
    w = np.asarray(weight, np.float32)
    if w.ndim != 2 or w.shape[0] < m or w.shape[1] < m:
        return np.ones_like(w, np.float32)
    R = (w.shape[0] // m) * m
    C = (w.shape[1] // m) * m
    mask = np.ones_like(w, np.float32)
    blk = np.abs(w[:R, :C]).reshape(R // m, m, C // m, m) \
        .transpose(0, 2, 1, 3).reshape(-1, m, m)
    Nb = blk.shape[0]
    patterns = _block_patterns_2d(n, m)
    if patterns is not None:
        # EXACT for small m: every valid keep-pattern (row sums == col
        # sums == n; 90 patterns at 2:4) scored for all blocks in one
        # matmul — both faster and denser-optimal than per-pick greedy
        # (~16% of random blocks dead-end a sequential greedy)
        scores = blk.reshape(Nb, -1) @ patterns.reshape(
            patterns.shape[0], -1).T                       # [Nb, P]
        keep = patterns[np.argmax(scores, axis=1)]
    else:
        # larger m: vectorized greedy (caps hold; possibly sparser)
        order = np.argsort(blk.reshape(Nb, -1), axis=1)[:, ::-1]
        rows = np.zeros((Nb, m), np.int64)
        cols = np.zeros((Nb, m), np.int64)
        keep = np.zeros((Nb, m, m), np.float32)
        taken = np.zeros(Nb, np.int64)
        bidx = np.arange(Nb)
        for pos in range(m * m):
            i, j = np.divmod(order[:, pos], m)
            ok = (rows[bidx, i] < n) & (cols[bidx, j] < n) & \
                (taken < n * m)
            rows[bidx[ok], i[ok]] += 1
            cols[bidx[ok], j[ok]] += 1
            keep[bidx[ok], i[ok], j[ok]] = 1.0
            taken[ok] += 1
    mask[:R, :C] = keep.reshape(R // m, C // m, m, m) \
        .transpose(0, 2, 1, 3).reshape(R, C)
    return mask


_PATTERN_CACHE: dict = {}


def _block_patterns_2d(n, m):
    """All m x m 0/1 matrices with every row and column summing to n
    (None when the enumeration would be too large). 2:4 -> 90."""
    import itertools

    key = (n, m)
    if key in _PATTERN_CACHE:
        return _PATTERN_CACHE[key]
    from math import comb

    if comb(m, n) ** m > 500_000:
        _PATTERN_CACHE[key] = None
        return None
    col_sets = list(itertools.combinations(range(m), n))
    out = []
    for combo in itertools.product(col_sets, repeat=m):
        counts = [0] * m
        for rc in combo:
            for j in rc:
                counts[j] += 1
        if all(c == n for c in counts):
            p = np.zeros((m, m), np.float32)
            for i, rc in enumerate(combo):
                p[i, list(rc)] = 1.0
            out.append(p)
    _PATTERN_CACHE[key] = np.stack(out) if out else None
    return _PATTERN_CACHE[key]


def check_mask_2d(mat, n=2, m=4) -> bool:
    """True iff every complete m x m block keeps <= n nonzeros per row
    AND per column. A matrix with no complete m x m block is vacuously
    compliant (matches check_mask_1d's remainder contract — small layers
    survive a prune-then-verify round trip)."""
    a = np.asarray(mat)
    if a.ndim != 2:
        return False
    if a.shape[0] < m or a.shape[1] < m:
        return True
    R = (a.shape[0] // m) * m
    C = (a.shape[1] // m) * m
    for r0 in range(0, R, m):
        for c0 in range(0, C, m):
            blk = np.abs(a[r0:r0 + m, c0:c0 + m]) > 0
            if (blk.sum(axis=1) > n).any() or (blk.sum(axis=0) > n).any():
                return False
    return True


def check_mask_1d(mat, n=2, m=4) -> bool:
    """True iff every complete m-group keeps at most n nonzeros (the
    dense remainder of a non-divisible dim is ignored; a matrix with no
    complete group is vacuously compliant, same as check_mask_2d)."""
    a = np.asarray(mat)
    if a.ndim < 2:
        return False
    if a.shape[0] < m:
        return True
    main = (a.shape[0] // m) * m
    nz = (np.abs(a[:main]).reshape(main // m, m, -1) > 0).sum(axis=1)
    return bool((nz <= n).all())


def set_excluded_layers(model, layer_names):
    """Exclude named sublayers of THIS model from prune_model."""
    excl = getattr(model, "_asp_excluded", None)
    if excl is None:
        object.__setattr__(model, "_asp_excluded", set())
        excl = model._asp_excluded
    excl.update(layer_names)


def reset_excluded_layers(model=None):
    if model is not None and hasattr(model, "_asp_excluded"):
        model._asp_excluded.clear()


def prune_model(model, n=2, m=4, mask_algo="mask_1d", with_mask=True):
    """Mask every Linear weight to n:m sparsity ('mask_1d' along the
    reduction dim, or 'mask_2d_greedy' per m x m block). Masks are
    recorded on each pruned layer so a decorate()'d optimizer managing
    its params re-applies them after every step. Returns
    {param_name: mask} for the layers whose weights actually changed."""
    import jax.numpy as jnp

    makers = {"mask_1d": create_mask,
              "mask_2d_greedy": create_mask_2d_greedy}
    if mask_algo not in makers:
        raise NotImplementedError(
            f"mask_algo={mask_algo!r}; valid: {sorted(makers)}")
    excluded = getattr(model, "_asp_excluded", set())
    out = {}
    for name, sub in model.named_sublayers():
        if name in excluded or not isinstance(sub, nn.Linear):
            continue
        w = sub.weight
        mask = makers[mask_algo](np.asarray(w._array), n=n, m=m)
        if not (mask == 0).any():
            continue  # nothing prunable (e.g. dim < m): not "pruned"
        w._array = (jnp.asarray(np.asarray(w._array, np.float32) * mask)
                    .astype(w._array.dtype))
        if with_mask:
            w._asp_mask = mask  # decorate() reads this off the param
        out[f"{name}.weight"] = mask
    return out


def decorate(optimizer):
    """Wrap optimizer.step to re-apply pruning masks after the update
    (OptimizerWithSparsityGuarantee analog). Only parameters managed by
    THIS optimizer are re-masked."""
    import jax.numpy as jnp

    orig_step = optimizer.step

    def step_with_masks(*a, **kw):
        r = orig_step(*a, **kw)
        # masks are read off the params lazily: prune_model may run
        # before or after decorate
        for p in optimizer._parameter_list:
            mask = getattr(p, "_asp_mask", None)
            if mask is not None:
                p._array = (jnp.asarray(
                    np.asarray(p._array, np.float32) * mask)
                    .astype(p._array.dtype))
        return r

    optimizer.step = step_with_masks
    return optimizer
