"""paddle.incubate analog — stable aliases for features the reference
ships under incubate (python/paddle/incubate/): the MoE layer
(incubate/distributed/models/moe/) and fused transformer functionality
live in their first-class homes here; incubate re-exports them for
import-path parity.
"""
from paddle_tpu.distributed.moe import MoELayer, switch_gating, top2_gating
from paddle_tpu.nn import TransformerEncoderLayer as FusedTransformerLayer

from . import asp, autograd, checkpoint, distributed, optimizer
from .optimizer import LookAhead, ModelAverage

__all__ = ["MoELayer", "top2_gating", "switch_gating",
           "FusedTransformerLayer", "distributed", "asp", "autograd",
           "checkpoint", "optimizer", "LookAhead", "ModelAverage"]
