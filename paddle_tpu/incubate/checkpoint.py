"""Auto-checkpoint — analog of
python/paddle/fluid/incubate/checkpoint/auto_checkpoint.py: epoch-range
training that snapshots state on an interval and transparently resumes
after a restart (the fault-tolerance story for long runs; pairs with
the elastic launcher's pod restart).

    for epoch in acp.train_epoch_range(10, save_dir="ckpt",
                                       state={"model": m, "opt": opt}):
        train_one_epoch(...)

On restart the loop continues from the first incomplete epoch with
model/optimizer state restored.
"""
from __future__ import annotations

import json
import os
import time

__all__ = ["train_epoch_range", "AutoCheckpointRange"]


class AutoCheckpointRange:
    def __init__(self, max_epoch_num, save_dir, state=None,
                 save_checkpoint_inter=1, name="acp"):
        self.max_epoch = int(max_epoch_num)
        self.save_dir = save_dir
        self.state = dict(state or {})
        self.interval = max(int(save_checkpoint_inter), 1)
        self.name = name
        os.makedirs(save_dir, exist_ok=True)
        self._meta_path = os.path.join(save_dir, f"{name}_meta.json")

    def _load_meta(self):
        if os.path.exists(self._meta_path):
            with open(self._meta_path) as f:
                return json.load(f)
        return {"next_epoch": 0}

    def _restore(self):
        import paddle_tpu

        for key, obj in self.state.items():
            path = os.path.join(self.save_dir, f"{self.name}_{key}.pd")
            if os.path.exists(path) and hasattr(obj, "set_state_dict"):
                obj.set_state_dict(paddle_tpu.load(path))

    def _snapshot(self, next_epoch):
        import paddle_tpu

        # every file lands via tmp + os.replace: a crash mid-save must
        # never leave a torn state file behind a valid meta (the meta is
        # replaced LAST, so it only ever points at complete snapshots)
        for key, obj in self.state.items():
            if hasattr(obj, "state_dict"):
                path = os.path.join(self.save_dir, f"{self.name}_{key}.pd")
                paddle_tpu.save(obj.state_dict(), path + ".tmp")
                os.replace(path + ".tmp", path)
        tmp = self._meta_path + ".tmp"
        with open(tmp, "w") as f:
            json.dump({"next_epoch": next_epoch, "time": time.time()}, f)
        os.replace(tmp, self._meta_path)

    def __iter__(self):
        meta = self._load_meta()
        start = int(meta.get("next_epoch", 0))
        if start > 0:
            self._restore()
        for epoch in range(start, self.max_epoch):
            yield epoch
            # epoch completed: snapshot on the interval (and always on
            # the final epoch so a finished run is fully recorded)
            if (epoch + 1) % self.interval == 0 or \
                    epoch + 1 == self.max_epoch:
                self._snapshot(epoch + 1)


def train_epoch_range(max_epoch_num, save_dir="auto_checkpoint",
                      state=None, save_checkpoint_inter=1, name="acp"):
    return AutoCheckpointRange(max_epoch_num, save_dir, state,
                               save_checkpoint_inter, name)
