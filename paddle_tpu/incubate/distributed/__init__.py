"""incubate.distributed path parity: models.moe lives at
paddle_tpu.distributed.moe (first-class)."""
from . import models

__all__ = ["models"]
