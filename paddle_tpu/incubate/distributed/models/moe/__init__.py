"""Alias of paddle_tpu.distributed.moe (reference path:
python/paddle/incubate/distributed/models/moe/moe_layer.py)."""
from paddle_tpu.distributed.moe import (MoELayer, switch_gating,
                                        top2_gating)

__all__ = ["MoELayer", "top2_gating", "switch_gating"]
