"""paddle.incubate.optimizer — LookAhead and ModelAverage, analogs of
python/paddle/incubate/optimizer/lookahead.py and modelaverage.py.

LookAhead is expressed through the standard _single_update contract, so
it composes with jit.TrainStep / DistributedTrainStep (the slow weights
are just one more accumulator slot, conditionally synced with
jnp.where on the step counter). ModelAverage is an eager-side EMA-style
evaluation aid (apply/restore swap), matching the reference's usage.
"""
from __future__ import annotations

import contextlib

import jax.numpy as jnp
import numpy as np

from paddle_tpu.optimizer.optimizer import Optimizer

__all__ = ["LookAhead", "ModelAverage"]


class LookAhead(Optimizer):
    """k-step lookahead (Zhang et al. 2019): the inner optimizer moves
    the fast weights; every k steps the slow weights interpolate toward
    them (slow += alpha*(fast-slow)) and the fast weights reset to slow.

        opt = LookAhead(paddle.optimizer.Adam(..., parameters=ps),
                        alpha=0.5, k=5)
    """

    def __init__(self, inner_optimizer: Optimizer, alpha=0.5, k=5,
                 name=None):
        super().__init__(learning_rate=inner_optimizer._learning_rate,
                         parameters=inner_optimizer._parameter_list,
                         grad_clip=inner_optimizer._grad_clip)
        if not 0.0 <= alpha <= 1.0:
            raise ValueError(f"alpha must be in [0,1], got {alpha}")
        if int(k) < 1:
            raise ValueError(f"k must be >= 1, got {k}")
        self.inner_optimizer = inner_optimizer
        self.alpha = float(alpha)
        self.k = int(k)

    def _create_accumulators(self):
        self.inner_optimizer._ensure_state()
        accs = dict(self.inner_optimizer._accumulators)
        # a real copy: slow weights must not alias the (donated) param
        # buffers — `f(donate(a), donate(a))` is rejected by jax
        accs["slow_param"] = [jnp.array(p._array, copy=True)
                              for p in self._parameter_list]
        return accs

    def _per_param_extras(self, i):
        return self.inner_optimizer._per_param_extras(i)

    def _single_update(self, param, grad, accums, lr, step, extras=None):
        inner_acc = {k: v for k, v in accums.items() if k != "slow_param"}
        fast, new_acc = self.inner_optimizer._single_update(
            param, grad, inner_acc, lr, step, extras=extras)
        slow = accums["slow_param"]
        sync = ((step + 1) % self.k) == 0
        slow2 = jnp.where(sync,
                          slow + self.alpha * (fast.astype(slow.dtype) - slow),
                          slow)
        fast2 = jnp.where(sync, slow2.astype(fast.dtype), fast)
        out = dict(new_acc)
        out["slow_param"] = slow2
        return fast2, out


class ModelAverage(Optimizer):
    """Running average of parameters for evaluation
    (modelaverage.py parity): call .step() after each optimizer.step();
    evaluate inside `with ma.apply(): ...` (weights swapped to the
    average), train again after restore.

        ma = ModelAverage(0.15, parameters=model.parameters(),
                          min_average_window=2, max_average_window=10)
    """

    def __init__(self, average_window_rate, parameters=None,
                 min_average_window=10000, max_average_window=10000,
                 name=None):
        super().__init__(learning_rate=0.0, parameters=parameters)
        self.avg_rate = float(average_window_rate)
        self.min_window = int(min_average_window)
        self.max_window = int(max_average_window)
        zeros = lambda: [np.zeros_like(np.asarray(p._array, np.float32))
                         for p in self._parameter_list]
        # two-bucket rotation (the reference's sum_1/sum_2 scheme): the
        # current bucket fills until the window cap, then rotates into
        # `old`; the average always spans old+current, so a rotation
        # halves the history instead of discarding it entirely
        self._cur, self._old = zeros(), zeros()
        self._cur_n = 0
        self._old_n = 0
        self._total = 0
        self._backup = None

    def _window(self):
        """Effective window: rate*steps, clamped to [min,max] — the
        documented knobs (modelaverage.py semantics)."""
        return max(self.min_window,
                   min(self.max_window,
                       int(self._total * self.avg_rate) + 1))

    def step(self):
        if self._cur_n >= self._window():
            self._old, self._cur = self._cur, self._old
            self._old_n = self._cur_n
            for s in self._cur:
                s *= 0.0
            self._cur_n = 0
        for s, p in zip(self._cur, self._parameter_list):
            s += np.asarray(p._array, np.float32)
        self._cur_n += 1
        self._total += 1

    def _average(self):
        n = self._cur_n + self._old_n
        assert n > 0, "ModelAverage.step() never ran"
        return [(c + o) / n for c, o in zip(self._cur, self._old)]

    @contextlib.contextmanager
    def apply(self, executor=None, need_restore=True):
        import jax

        self._backup = [p._array for p in self._parameter_list]
        for p, avg in zip(self._parameter_list, self._average()):
            p._array = jnp.asarray(avg.astype(np.asarray(p._array).dtype))
        try:
            yield
        finally:
            if need_restore:
                self.restore(executor)

    def restore(self, executor=None):
        if self._backup is not None:
            for p, b in zip(self._parameter_list, self._backup):
                p._array = b
            self._backup = None
