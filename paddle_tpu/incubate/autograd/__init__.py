"""paddle.incubate.autograd analog — functional differentiation over
jax's transform machinery.

Reference surface (python/paddle/incubate/autograd/functional.py):
``vjp`` (:22), ``jvp`` (:80), ``Jacobian`` (:171, lazy row-indexed),
``Hessian`` (:260) and ``primapi.forward_grad`` (primapi.py:25).

The reference implements these by replaying the eager tape (``_grad``
over ``paddle.grad``) or, for forward mode, by rewriting a static
program into primitive ops. On this stack all five are direct
applications of jax's functional transforms: ``jax.vjp`` / ``jax.jvp``
give the products, and the Jacobian/Hessian classes keep the
reference's lazy row-cached indexing contract on top of the vjp
pullback (rows) and jvp pushforward (single columns) instead of
materialising the full matrix eagerly.
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp

from paddle_tpu.core.tensor import Tensor

__all__ = ["vjp", "jvp", "Jacobian", "Hessian", "forward_grad"]


def _as_tensor_tuple(xs):
    """Normalize the paddle-style ``Tensor | Sequence[Tensor]`` input
    contract; returns (tuple_of_tensors, was_sequence)."""
    if isinstance(xs, (tuple, list)):
        ts = tuple(x if isinstance(x, Tensor) else Tensor(x) for x in xs)
        return ts, True
    return (xs if isinstance(xs, Tensor) else Tensor(xs),), False


def _arrays(ts):
    return tuple(t._array for t in ts)


def _wrap_func(func, xs_is_seq):
    """Lift a Tensor->Tensor user function to arrays->arrays for jax.
    ``meta`` records whether the traced output was a sequence so results
    unwrap with the same structure the user returned."""
    meta = {}

    def jf(*arrays):
        args = [Tensor._wrap(a, stop_gradient=False) for a in arrays]
        out = func(*args) if xs_is_seq else func(args[0])
        multi = isinstance(out, (tuple, list))
        meta["multi"] = multi
        outs = tuple(out) if multi else (out,)
        return tuple(o._array if isinstance(o, Tensor) else jnp.asarray(o)
                     for o in outs)

    return jf, meta


def _pack(arrays, multi):
    ts = tuple(Tensor._wrap(a, stop_gradient=False) for a in arrays)
    return ts if multi else ts[0]


def _check_v(v, refs, kind):
    """The reference's _check_v_shape: v must match ``refs`` pairwise in
    length and shape (dtype needs no check here — Tensor construction
    canonicalizes it, and jvp re-casts tangents to the primal dtype)."""
    vs, _ = _as_tensor_tuple(v)
    if len(vs) != len(refs):
        raise RuntimeError(
            f"The length of {kind} v ({len(vs)}) does not match the "
            f"number of tensors it pairs with ({len(refs)})")
    for vi, ri in zip(vs, refs):
        if tuple(vi._array.shape) != tuple(ri.shape):
            raise RuntimeError(
                f"The v[{kind}] shape {tuple(vi._array.shape)} does not "
                f"match the paired tensor shape {tuple(ri.shape)}")
    return _arrays(vs)


def vjp(func, xs, v=None):
    """Vector-Jacobian product (reverse mode), reference
    functional.py:22. Returns ``(func_out, vjp_result)``; ``v`` defaults
    to all-ones matching ``func``'s outputs."""
    ts, is_seq = _as_tensor_tuple(xs)
    jf, meta = _wrap_func(func, is_seq)
    ys, pullback = jax.vjp(jf, *_arrays(ts))
    if v is None:
        cots = tuple(jnp.ones_like(y) for y in ys)
    else:
        cots = _check_v(v, ys, "output")
    grads = pullback(cots)
    return (_pack(ys, meta["multi"]),
            _pack(grads, is_seq))


def jvp(func, xs, v=None):
    """Jacobian-vector product (forward mode), reference
    functional.py:80. Returns ``(func_out, jvp_result)``; ``v`` defaults
    to all-ones matching ``xs``."""
    ts, is_seq = _as_tensor_tuple(xs)
    arrays = _arrays(ts)
    jf, meta = _wrap_func(func, is_seq)
    if v is None:
        tangents = tuple(jnp.ones_like(a) for a in arrays)
    else:
        tangents = _check_v(v, arrays, "input")
        tangents = tuple(jnp.asarray(t, a.dtype)
                         for t, a in zip(tangents, arrays))
    ys, dys = jax.jvp(jf, arrays, tangents)
    return (_pack(ys, meta["multi"]), _pack(dys, meta["multi"]))


class _FlatFunc:
    """func over the reference's flattened calling convention: all
    inputs flattened (batch axis kept when batched) and concatenated to
    one [N] / [B, N] array; outputs likewise to [M] / [B, M]."""

    def __init__(self, func, xs, is_batched):
        ts, self.is_seq = _as_tensor_tuple(xs)
        self.arrays = _arrays(ts)
        self.is_batched = bool(is_batched)
        if self.is_batched:
            b = self.arrays[0].shape[0]
            for a in self.arrays:
                if a.shape[0] != b:
                    raise ValueError(
                        "is_batched=True requires every input to share "
                        f"the leading batch axis; got {a.shape[0]} vs {b}")
            self.batch = b
            self.in_shapes = [a.shape[1:] for a in self.arrays]
            self.in_sizes = [max(1, math.prod(s)) for s in self.in_shapes]
            self.flat_x = jnp.concatenate(
                [a.reshape(self.batch, -1) for a in self.arrays], axis=-1)
        else:
            self.batch = None
            self.in_shapes = [a.shape for a in self.arrays]
            self.in_sizes = [int(a.size) for a in self.arrays]
            self.flat_x = jnp.concatenate(
                [a.reshape(-1) for a in self.arrays])
        self.func = func

    def __call__(self, flat_x):
        parts = []
        off = 0
        for shape, size in zip(self.in_shapes, self.in_sizes):
            sl = flat_x[..., off:off + size]
            full = (sl.reshape((self.batch,) + tuple(shape))
                    if self.is_batched else sl.reshape(shape))
            parts.append(full)
            off += size
        jf, _ = _wrap_func(self.func, self.is_seq)
        outs = jf(*parts)
        if self.is_batched:
            return jnp.concatenate(
                [o.reshape(self.batch, -1) for o in outs], axis=-1)
        return jnp.concatenate([o.reshape(-1) for o in outs])


class Jacobian:
    """Lazily indexed Jacobian matrix, reference functional.py:171.

    Shape is ``[M, N]`` (or ``[B, M, N]`` with ``is_batched=True``)
    over flatten-and-concatenated outputs/inputs. Rows are evaluated on
    demand through the cached vjp pullback and memoized; a single-column
    request without rows uses one jvp pushforward instead of M
    pullbacks. ``J[...]`` supports int/slice indexes per axis.
    """

    def __init__(self, func, xs, is_batched=False):
        self._f = _FlatFunc(func, xs, is_batched)
        ys, self._pullback = jax.vjp(self._f, self._f.flat_x)
        self._ys = ys
        self._rows: dict = {}
        self._cols: dict = {}
        if is_batched:
            self._B, self._M = ys.shape
            self._N = self._f.flat_x.shape[-1]
        else:
            self._M = int(ys.shape[0])
            self._N = int(self._f.flat_x.shape[-1])

    @property
    def shape(self):
        if self._f.is_batched:
            return (self._B, self._M, self._N)
        return (self._M, self._N)

    # -- evaluation --------------------------------------------------------
    def _row(self, i):
        """d flat_y[(:,) i] / d flat_x — shape [N] or [B, N]."""
        if i not in self._rows:
            if self._f.is_batched:
                cot = jnp.zeros((self._B, self._M),
                                self._ys.dtype).at[:, i].set(1.0)
            else:
                cot = jnp.zeros((self._M,), self._ys.dtype).at[i].set(1.0)
            self._rows[i] = self._pullback(cot)[0]
        return self._rows[i]

    def _col(self, j):
        """d flat_y / d flat_x[(:,) j] via ONE forward-mode pass
        (memoized, like rows)."""
        if j not in self._cols:
            if self._f.is_batched:
                tan = jnp.zeros((self._B, self._N),
                                self._f.flat_x.dtype).at[:, j].set(1.0)
            else:
                tan = jnp.zeros((self._N,),
                                self._f.flat_x.dtype).at[j].set(1.0)
            _, dy = jax.jvp(self._f, (self._f.flat_x,), (tan,))
            self._cols[j] = dy
        return self._cols[j]

    def _fill_rows(self, wanted):
        """Evaluate every uncached row in ``wanted`` with ONE vmapped
        pullback call — on high-dispatch-latency backends (axon tunnel)
        M separate pullbacks would cost ~100ms each."""
        missing = [i for i in wanted if i not in self._rows]
        if not missing:
            return
        eye = jnp.eye(self._M, dtype=self._ys.dtype)[jnp.array(missing)]
        if self._f.is_batched:
            cots = jnp.broadcast_to(
                eye[:, None, :], (len(missing), self._B, self._M))
        else:
            cots = eye
        rows = jax.vmap(lambda c: self._pullback(c)[0])(cots)
        for k, i in enumerate(missing):
            self._rows[i] = rows[k]

    # -- indexing ----------------------------------------------------------
    def __getitem__(self, indexes):
        idx = indexes if isinstance(indexes, tuple) else (indexes,)
        if self._f.is_batched:
            if len(idx) > 3:
                raise IndexError(
                    f"too many indexes for a batched Jacobian: {indexes}")
            bidx = idx[0] if len(idx) >= 1 else slice(None)
            ridx = idx[1] if len(idx) >= 2 else slice(None)
            cidx = idx[2] if len(idx) >= 3 else slice(None)
        else:
            if len(idx) > 2:
                raise IndexError(
                    f"too many indexes for a Jacobian: {indexes}")
            bidx = None
            ridx = idx[0] if len(idx) >= 1 else slice(None)
            cidx = idx[1] if len(idx) >= 2 else slice(None)

        full_rows = isinstance(ridx, slice) and ridx == slice(None)
        if (full_rows and isinstance(cidx, int)
                and len(self._rows) < self._M):
            # column fast path: one jvp instead of materializing the
            # uncached rows (taken whenever the row cache can't already
            # serve the column)
            out = self._col(range(self._N)[cidx])  # [N-normalized j]
        else:
            if isinstance(ridx, int):
                ridx = range(self._M)[ridx]  # normalize negatives
                out = self._row(ridx)
            else:
                wanted = list(range(self._M)[ridx])
                self._fill_rows(wanted)
                out = jnp.stack([self._rows[i] for i in wanted],
                                axis=1 if self._f.is_batched else 0)
            out = out[..., cidx]
        if bidx is not None:
            out = out[bidx]
        return Tensor._wrap(out, stop_gradient=False)


class Hessian:
    """Hessian matrix of a scalar-valued ``func``, reference
    functional.py:260 — built exactly as the reference does: the
    Jacobian of the function's (single-row) Jacobian."""

    def __init__(self, func, xs, is_batched=False):
        def _jac_func(*inner):
            xs_in = list(inner) if len(inner) > 1 else inner[0]
            jac = Jacobian(func, xs_in, is_batched=is_batched)
            if (is_batched and jac.shape[1] != 1) or (
                    not is_batched and jac.shape[0] != 1):
                raise RuntimeError(
                    "The function given to Hessian should return a "
                    "single element Tensor or batched single element "
                    "Tensor")
            return jac[:, 0, :] if is_batched else jac[0, :]

        self.symbolic = Jacobian(_jac_func, xs, is_batched=is_batched)

    @property
    def shape(self):
        return self.symbolic.shape

    def __getitem__(self, indexes):
        return self.symbolic[indexes]


def forward_grad(outputs, inputs, grad_inputs=None):
    """Forward-mode differentiation, reference primapi.py:25.

    The reference API is static-graph only: it rewrites a program into
    primitive ops and threads tangents through. On this stack forward
    mode is native (``jax.jvp``), so the natural calling convention is
    functional — pass the FUNCTION as ``outputs``::

        dy = forward_grad(func, xs, v)   # == jvp(func, xs, v)[1]

    Passing already-evaluated eager tensors cannot work here (an eager
    Tensor does not carry a forward graph to re-trace), so that form
    raises with guidance instead of silently returning zeros.
    """
    if callable(outputs):
        return jvp(outputs, inputs, grad_inputs)[1]
    raise TypeError(
        "forward_grad on this backend takes the function itself: "
        "forward_grad(func, xs, v). The reference's "
        "(outputs, inputs) form requires a static primitive program "
        "(primapi.py:25); eager tensors carry no forward graph — "
        "wrap the computation in a function, or use "
        "paddle.incubate.autograd.jvp.")
