"""Dataset abstractions — analog of python/paddle/io/ (fluid/dataloader/dataset.py)."""
from __future__ import annotations

import numpy as np


class Dataset:
    def __getitem__(self, idx):
        raise NotImplementedError

    def __len__(self):
        raise NotImplementedError


class IterableDataset(Dataset):
    def __iter__(self):
        raise NotImplementedError

    def __getitem__(self, idx):
        raise RuntimeError("IterableDataset does not support indexing")

    def __len__(self):
        raise RuntimeError("IterableDataset has no len()")


class TensorDataset(Dataset):
    def __init__(self, tensors):
        from paddle_tpu.core.tensor import Tensor

        self.tensors = tensors
        n = len(tensors[0])
        assert all(len(t) == n for t in tensors)

    def __getitem__(self, idx):
        return tuple(t[idx] for t in self.tensors)

    def __len__(self):
        return len(self.tensors[0])


class ComposeDataset(Dataset):
    def __init__(self, datasets):
        self.datasets = list(datasets)
        assert all(len(d) == len(self.datasets[0]) for d in self.datasets)

    def __len__(self):
        return len(self.datasets[0])

    def __getitem__(self, idx):
        out = []
        for d in self.datasets:
            sample = d[idx]
            out.extend(sample if isinstance(sample, (list, tuple)) else [sample])
        return tuple(out)


class ChainDataset(IterableDataset):
    def __init__(self, datasets):
        self.datasets = list(datasets)

    def __iter__(self):
        for d in self.datasets:
            yield from d


class Subset(Dataset):
    def __init__(self, dataset, indices):
        self.dataset = dataset
        self.indices = list(indices)

    def __getitem__(self, idx):
        return self.dataset[self.indices[idx]]

    def __len__(self):
        return len(self.indices)


def random_split(dataset, lengths, generator=None):
    total = sum(lengths)
    assert total == len(dataset)
    perm = np.random.permutation(total)
    out, off = [], 0
    for ln in lengths:
        out.append(Subset(dataset, perm[off:off + ln].tolist()))
        off += ln
    return out


class ConcatDataset(Dataset):
    """Concatenation of datasets (python/paddle/io/ ConcatDataset)."""

    def __init__(self, datasets):
        self.datasets = list(datasets)
        assert self.datasets, "datasets should not be empty"
        self.cumulative_sizes = []
        total = 0
        for d in self.datasets:
            total += len(d)
            self.cumulative_sizes.append(total)

    def __len__(self):
        return self.cumulative_sizes[-1]

    def __getitem__(self, idx):
        import bisect

        if idx < 0:
            if idx < -len(self):
                raise ValueError(
                    f"index {idx} out of range for length {len(self)}")
            idx += len(self)
        di = bisect.bisect_right(self.cumulative_sizes, idx)
        prev = 0 if di == 0 else self.cumulative_sizes[di - 1]
        return self.datasets[di][idx - prev]
