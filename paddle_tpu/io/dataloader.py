"""DataLoader — analog of python/paddle/fluid/reader.py:311 (DataLoader)
and fluid/dataloader/ (worker.py, collate.py).

TPU-native design: the loader produces pinned host numpy batches and
hands jax the device transfer (jax.device_put is async; XLA overlaps the
h2d copy with compute). Multiprocess workers use the standard
multiprocessing pool with numpy shared transport — the analog of the
reference's shared-memory tensor transport (dataloader/worker.py) without
the custom blocking-queue C++ layer (operators/reader/) which PJRT makes
unnecessary.
"""
from __future__ import annotations

import itertools
import multiprocessing as mp
import queue as queue_mod
import threading

import numpy as np

from paddle_tpu.core.tensor import Tensor

from .dataset import Dataset, IterableDataset
from .sampler import BatchSampler


def default_collate_fn(batch):
    """Analog of fluid/dataloader/collate.py default_collate_fn."""
    sample = batch[0]
    if isinstance(sample, (Tensor,)):
        return Tensor(np.stack([np.asarray(s._array) for s in batch]))
    if isinstance(sample, np.ndarray):
        return Tensor(np.stack(batch))
    if isinstance(sample, (int, float)):
        return Tensor(np.asarray(batch))
    if isinstance(sample, (list, tuple)):
        return tuple(default_collate_fn(list(items)) for items in zip(*batch))
    if isinstance(sample, dict):
        return {k: default_collate_fn([s[k] for s in batch]) for k in sample}
    return batch


def _worker_loop(dataset, index_queue, data_queue, collate_fn):
    while True:
        item = index_queue.get()
        if item is None:
            break
        i, indices = item
        try:
            batch = [dataset[j] for j in indices]
            data = collate_fn(batch)
            data = _to_numpy(data)
            data_queue.put((i, data))
        except Exception as e:  # pragma: no cover
            data_queue.put((i, e))


def _to_numpy(data):
    if isinstance(data, Tensor):
        return np.asarray(data._array)
    if isinstance(data, tuple):
        return tuple(_to_numpy(d) for d in data)
    if isinstance(data, dict):
        return {k: _to_numpy(v) for k, v in data.items()}
    return data


def _to_tensor(data):
    if isinstance(data, np.ndarray):
        return Tensor(data)
    if isinstance(data, tuple):
        return tuple(_to_tensor(d) for d in data)
    if isinstance(data, dict):
        return {k: _to_tensor(v) for k, v in data.items()}
    return data


class DataLoader:
    def __init__(self, dataset, feed_list=None, places=None, return_list=True,
                 batch_sampler=None, batch_size=1, shuffle=False,
                 drop_last=False, collate_fn=None, num_workers=0,
                 use_buffer_reader=True, prefetch_factor=2, use_shared_memory=True,
                 timeout=0, worker_init_fn=None, persistent_workers=False):
        self.dataset = dataset
        self.collate_fn = collate_fn or default_collate_fn
        self.num_workers = int(num_workers)
        self.prefetch_factor = prefetch_factor
        self._iterable_mode = isinstance(dataset, IterableDataset)
        if self._iterable_mode:
            self.batch_size = batch_size
            self.batch_sampler = None
        elif batch_sampler is not None:
            self.batch_sampler = batch_sampler
        else:
            self.batch_sampler = BatchSampler(
                dataset, shuffle=shuffle, batch_size=batch_size,
                drop_last=drop_last)

    def __len__(self):
        if self._iterable_mode:
            raise TypeError("IterableDataset has no len()")
        return len(self.batch_sampler)

    def __iter__(self):
        if self._iterable_mode:
            return self._iter_iterable()
        if self.num_workers == 0:
            return self._iter_single()
        return self._iter_multiprocess()

    def _iter_iterable(self):
        batch = []
        for sample in self.dataset:
            batch.append(sample)
            if len(batch) == self.batch_size:
                yield self.collate_fn(batch)
                batch = []
        if batch:
            yield self.collate_fn(batch)

    def _iter_single(self):
        for indices in self.batch_sampler:
            batch = [self.dataset[i] for i in indices]
            yield self.collate_fn(batch)

    def _iter_multiprocess(self):
        ctx = mp.get_context("fork")
        index_queue = ctx.Queue()
        data_queue = ctx.Queue()
        workers = [
            ctx.Process(
                target=_worker_loop,
                args=(self.dataset, index_queue, data_queue, self.collate_fn),
                daemon=True,
            )
            for _ in range(self.num_workers)
        ]
        for w in workers:
            w.start()
        try:
            batches = list(self.batch_sampler)
            inflight = 0
            max_inflight = self.num_workers * self.prefetch_factor
            next_submit = 0
            buffered = {}
            next_yield = 0
            while next_yield < len(batches):
                while next_submit < len(batches) and inflight < max_inflight:
                    index_queue.put((next_submit, batches[next_submit]))
                    next_submit += 1
                    inflight += 1
                while next_yield not in buffered:
                    i, data = data_queue.get()
                    if isinstance(data, Exception):
                        raise data
                    buffered[i] = data
                    inflight -= 1
                data = buffered.pop(next_yield)
                next_yield += 1
                yield _to_tensor(data)
        finally:
            for _ in workers:
                index_queue.put(None)
            for w in workers:
                w.join(timeout=1)
                if w.is_alive():
                    w.terminate()
