"""ctypes binding for the native batch loader (cpp/fastloader.cc) — the
C++ DataLoader core analog (paddle/fluid/framework/data_feed.cc,
reader/buffered_reader.cc). Batch gather/shuffle runs in C++ worker
threads off the GIL, prefetching into a bounded queue while Python/JAX
work proceeds.

The shared library builds on first use with the system toolchain (g++);
environments without one fall back cleanly (`native_available()` is
False and NativeArrayLoader raises with a clear message).
"""
from __future__ import annotations

import ctypes
import os
import subprocess
import threading

import numpy as np

__all__ = ["native_available", "NativeArrayLoader"]

_lib = None
_lib_err = None
_lock = threading.Lock()


def _build_and_load():
    global _lib, _lib_err
    repo = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    src = os.path.join(repo, "cpp", "fastloader.cc")
    out = os.path.join(repo, "cpp", "libfastloader.so")
    try:
        if not os.path.exists(out) or \
                os.path.getmtime(out) < os.path.getmtime(src):
            # compile to a per-process temp and rename atomically:
            # concurrent processes (the 2-process launcher, parallel
            # pytest) must never dlopen a half-written .so
            tmp = f"{out}.{os.getpid()}.tmp"
            subprocess.run(
                ["g++", "-O3", "-shared", "-fPIC", "-std=c++17",
                 "-o", tmp, src, "-pthread"],
                check=True, capture_output=True, text=True)
            os.replace(tmp, out)
        lib = ctypes.CDLL(out)
    except (OSError, subprocess.CalledProcessError, FileNotFoundError) as e:
        _lib_err = getattr(e, "stderr", None) or str(e)
        return None
    lib.fl_create.restype = ctypes.c_void_p
    lib.fl_create.argtypes = [
        ctypes.c_void_p, ctypes.c_int64, ctypes.c_int64, ctypes.c_int64,
        ctypes.c_int, ctypes.c_int, ctypes.c_uint64, ctypes.c_int64,
        ctypes.c_int]
    lib.fl_next.restype = ctypes.c_int
    lib.fl_next.argtypes = [ctypes.c_void_p, ctypes.c_void_p,
                            ctypes.POINTER(ctypes.c_int64)]
    lib.fl_num_batches.restype = ctypes.c_int64
    lib.fl_num_batches.argtypes = [ctypes.c_void_p]
    lib.fl_epoch.argtypes = [ctypes.c_void_p]
    lib.fl_destroy.argtypes = [ctypes.c_void_p]
    return lib


def _get_lib():
    global _lib
    with _lock:
        if _lib is None and _lib_err is None:
            _lib = _build_and_load()
    return _lib


def native_available():
    return _get_lib() is not None


class NativeArrayLoader:
    """Iterate (batches of) one or more aligned numpy arrays with C++
    worker-thread prefetch. All arrays share dim 0; shuffling is
    deterministic per (seed, epoch) and identical across the arrays
    (each array gets its own native loader seeded alike, stepped in
    lockstep — the multi-field sample case).

        loader = NativeArrayLoader((images, labels), batch_size=256,
                                   shuffle=True, workers=4)
        for epoch in range(E):
            for xb, yb in loader: ...
    """

    def __init__(self, arrays, batch_size, shuffle=False, drop_last=False,
                 seed=0, prefetch=4, workers=2):
        lib = _get_lib()
        if lib is None:
            raise RuntimeError(
                f"native loader unavailable (toolchain?): {_lib_err}")
        self._lib = lib
        if isinstance(arrays, np.ndarray):
            arrays = (arrays,)
        self._arrays = [np.ascontiguousarray(a) for a in arrays]
        n = {len(a) for a in self._arrays}
        if len(n) != 1:
            raise ValueError(f"arrays disagree on dim 0: {sorted(n)}")
        self.batch_size = int(batch_size)
        self._handles = []
        for a in self._arrays:
            item_bytes = a.dtype.itemsize * int(np.prod(a.shape[1:],
                                                        dtype=np.int64))
            h = lib.fl_create(
                a.ctypes.data_as(ctypes.c_void_p), len(a), item_bytes,
                self.batch_size, int(drop_last), int(shuffle),
                int(seed), int(prefetch), int(workers))
            self._handles.append((h, a, item_bytes))
        self._started = False

    def __len__(self):
        return int(self._lib.fl_num_batches(self._handles[0][0]))

    def __iter__(self):
        if self._started:
            for h, _, _ in self._handles:
                self._lib.fl_epoch(h)
        self._started = True
        nb = len(self)
        cnt = ctypes.c_int64()
        bufs = [np.empty((self.batch_size,) + a.shape[1:], a.dtype)
                for _, a, _ in self._handles]
        for _ in range(nb):
            outs = []
            for (h, a, _), buf in zip(self._handles, bufs):
                ok = self._lib.fl_next(
                    h, buf.ctypes.data_as(ctypes.c_void_p),
                    ctypes.byref(cnt))
                if not ok:
                    return
                outs.append(buf[:cnt.value].copy())
            yield tuple(outs) if len(outs) > 1 else outs[0]

    def __del__(self):
        lib = getattr(self, "_lib", None)
        if lib is not None:
            for h, _, _ in getattr(self, "_handles", []):
                lib.fl_destroy(h)
