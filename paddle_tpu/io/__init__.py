from .dataloader import DataLoader
from .dataset import (
    ChainDataset,
    ConcatDataset,
    ComposeDataset,
    Dataset,
    IterableDataset,
    Subset,
    TensorDataset,
    random_split,
)
from .sampler import (
    BatchSampler,
    DistributedBatchSampler,
    RandomSampler,
    Sampler,
    SequenceSampler,
    WeightedRandomSampler,
)

from .native import NativeArrayLoader, native_available

__all__ = [
    "Dataset", "IterableDataset", "TensorDataset", "ComposeDataset",
    "ChainDataset", "ConcatDataset", "Subset", "random_split", "DataLoader", "BatchSampler",
    "DistributedBatchSampler", "Sampler", "RandomSampler", "SequenceSampler",
    "WeightedRandomSampler", "NativeArrayLoader", "native_available",
]
