"""hapi — the high-level API tier (python/paddle/hapi/): Model with
fit/evaluate/predict/save/load plus the callback set."""
from . import callbacks
from .model import Model
from .summary import summary

__all__ = ["Model", "callbacks", "summary"]
