"""hapi Model — the high-level train/eval/predict loop, analog of
python/paddle/hapi/model.py:1039 (Model.fit :1039, evaluate, predict,
save/load, prepare).

TPU-native: train steps run through jit.TrainStep (one fused XLA
program per step, params/opt-state donated); train-time metrics ride
value_and_grad's aux instead of a second forward; eval/predict are one
jitted pure forward with params+buffers bound as traced args (no
retrace across batches of the same shape).
"""
from __future__ import annotations

import numpy as np

from paddle_tpu.core.tensor import Tensor

from .callbacks import config_callbacks

__all__ = ["Model"]


def _np(x):
    return np.asarray(x._array if isinstance(x, Tensor) else x)


def _to_loader(data, batch_size, shuffle, num_workers=0, drop_last=False):
    from paddle_tpu.io import DataLoader, Dataset, IterableDataset

    if data is None or isinstance(data, DataLoader):
        return data
    if isinstance(data, (Dataset, IterableDataset)):
        return DataLoader(data, batch_size=batch_size, shuffle=shuffle,
                          num_workers=num_workers, drop_last=drop_last)
    return data  # any iterable of batches


def _split_batch(batch):
    """DataLoader batch -> (inputs tuple, label). hapi convention:
    last element is the label."""
    if isinstance(batch, (list, tuple)) and len(batch) >= 2:
        *ins, label = batch
        return tuple(ins), label
    return (batch,), None


class Model:
    """Usage (hapi parity):
        model = paddle.Model(net)
        model.prepare(optimizer, loss, metrics=[paddle.metric.Accuracy()])
        model.fit(train_ds, eval_ds, epochs=2, batch_size=64)
        model.evaluate(eval_ds); model.predict(test_ds)
        model.save('ckpt/final')  # or save(path, training=False) -> jit.save
    """

    def __init__(self, network, inputs=None, labels=None):
        self.network = network
        self._inputs = inputs
        self._labels = labels
        self._optimizer = None
        self._loss = None
        self._metrics = []
        self._train_step = None
        self._eval_jit = None
        self.stop_training = False

    # -- setup ------------------------------------------------------------
    def prepare(self, optimizer=None, loss=None, metrics=None,
                amp_configs=None):
        self._optimizer = optimizer
        self._loss = loss
        metrics = metrics or []
        self._metrics = metrics if isinstance(metrics, (list, tuple)) \
            else [metrics]
        self._train_step = None
        self._eval_jit = None
        return self

    def parameters(self):
        return self.network.parameters()

    # -- single-batch ops (train_batch/eval_batch/predict_batch parity) ---
    def train_batch(self, inputs, labels=None):
        from paddle_tpu.jit.api import TrainStep

        if self._train_step is None:
            self.network.train()
            self._train_step = TrainStep(
                self.network, self._optimizer, self._loss,
                with_outputs=bool(self._metrics))
        ins = inputs if isinstance(inputs, (list, tuple)) else [inputs]
        ins = [x if isinstance(x, Tensor) else Tensor(x) for x in ins]
        label = labels if isinstance(labels, Tensor) or labels is None \
            else Tensor(labels)
        if self._metrics:
            loss, out = self._train_step(*ins, label=label)
            self._update_metrics(out, label)
        else:
            loss = self._train_step(*ins, label=label)
        return float(loss._array)

    def _build_eval(self):
        import jax

        network = self.network
        loss_fn = self._loss
        params = [p for p in network.parameters()]
        buffers = list(network.buffers()) if hasattr(network, "buffers") \
            else []

        def pure_eval(param_arrays, buf_arrays, inputs, label):
            from paddle_tpu.jit.api import bound_state

            state = params + buffers
            arrays = list(param_arrays) + list(buf_arrays)
            with bound_state(zip(state, arrays), state):
                out = network(*[Tensor._wrap(i) for i in inputs])
                loss = None
                if loss_fn is not None and label is not None:
                    loss = loss_fn(out, Tensor._wrap(label))
                unwrap = lambda t: t._array if isinstance(t, Tensor) else t
                return (jax.tree_util.tree_map(
                            unwrap, out,
                            is_leaf=lambda t: isinstance(t, Tensor)),
                        None if loss is None else unwrap(loss))

        # cache is valid only for the mode (dropout/BN) + debug-flag
        # epoch it was traced in
        from paddle_tpu.framework.flags import debug_epoch

        return (jax.jit(pure_eval), params, buffers,
                (network.training, debug_epoch()))

    def eval_batch(self, inputs, labels=None):
        from paddle_tpu.framework.flags import debug_epoch

        self.network.eval()
        if self._eval_jit is None or \
                self._eval_jit[3] != (self.network.training, debug_epoch()):
            self._eval_jit = self._build_eval()
        fn, params, buffers, _ = self._eval_jit
        ins = inputs if isinstance(inputs, (list, tuple)) else [inputs]
        out, loss = fn([p._array for p in params],
                       [b._array for b in buffers],
                       tuple(_np(i) for i in ins),
                       None if labels is None else _np(labels))
        return out, loss

    def predict_batch(self, inputs):
        out, _ = self.eval_batch(inputs, None)
        return out

    def _update_metrics(self, out, label):
        pred = out[0] if isinstance(out, (list, tuple)) else out
        for m in self._metrics:
            if hasattr(m, "compute"):
                m.update(m.compute(Tensor._wrap(_np(pred)),
                                   None if label is None
                                   else Tensor._wrap(_np(label))))
            else:
                m.update(_np(pred), _np(label))

    def _metric_logs(self):
        logs = {}
        for m in self._metrics:
            v = m.accumulate()
            logs[m.name() if callable(getattr(m, "name", None)) else m._name] = v
        return logs

    # -- loops ------------------------------------------------------------
    def fit(self, train_data=None, eval_data=None, batch_size=1, epochs=1,
            eval_freq=1, log_freq=10, save_dir=None, save_freq=1, verbose=2,
            drop_last=False, shuffle=True, num_workers=0, callbacks=None):
        loader = _to_loader(train_data, batch_size, shuffle, num_workers,
                            drop_last)
        eval_loader = _to_loader(eval_data, batch_size, False, num_workers)
        steps = len(loader) if hasattr(loader, "__len__") else None
        cbl = config_callbacks(callbacks, self, epochs=epochs, steps=steps,
                               verbose=verbose, log_freq=log_freq,
                               save_dir=save_dir, save_freq=save_freq,
                               metrics=self._metrics)
        self.stop_training = False
        cbl.call("on_train_begin")
        logs = {}
        for epoch in range(epochs):
            self.network.train()
            for m in self._metrics:
                m.reset()
            cbl.call("on_epoch_begin", epoch)
            for step, batch in enumerate(loader):
                cbl.call("on_train_batch_begin", step)
                ins, label = _split_batch(batch)
                loss = self.train_batch(ins, label)
                logs = {"loss": loss, **self._metric_logs()}
                cbl.call("on_train_batch_end", step, logs)
            cbl.call("on_epoch_end", epoch, logs)
            if eval_loader is not None and (epoch + 1) % eval_freq == 0:
                eval_logs = self._run_eval(eval_loader, cbl)
                logs.update({f"eval_{k}": v for k, v in eval_logs.items()})
            if self.stop_training:
                break
        cbl.call("on_train_end", logs)
        return self

    def _run_eval(self, loader, cbl=None):
        self.network.eval()
        for m in self._metrics:
            m.reset()
        if cbl:
            cbl.call("on_eval_begin")
        losses, n = [], 0
        for step, batch in enumerate(loader):
            if cbl:
                cbl.call("on_eval_batch_begin", step)
            ins, label = _split_batch(batch)
            out, loss = self.eval_batch(ins, label)
            if loss is not None:
                losses.append(float(loss))
            self._update_metrics(out, None if label is None
                                 else Tensor(_np(label)))
            if cbl:
                cbl.call("on_eval_batch_end", step,
                         {"loss": losses[-1] if losses else None})
        logs = {**({"loss": float(np.mean(losses))} if losses else {}),
                **self._metric_logs()}
        if cbl:
            cbl.call("on_eval_end", logs)
        return logs

    def evaluate(self, eval_data, batch_size=1, log_freq=10, verbose=2,
                 num_workers=0, callbacks=None):
        loader = _to_loader(eval_data, batch_size, False, num_workers)
        cbl = config_callbacks(callbacks, self, verbose=verbose,
                               log_freq=log_freq, metrics=self._metrics)
        return self._run_eval(loader, cbl)

    def predict(self, test_data, batch_size=1, num_workers=0,
                stack_outputs=True, verbose=1, callbacks=None):
        loader = _to_loader(test_data, batch_size, False, num_workers)
        self.network.eval()
        per_output = None
        for batch in loader:
            ins, _ = _split_batch(batch) if isinstance(batch, (list, tuple)) \
                else ((batch,), None)
            out = self.predict_batch(ins)
            outs = list(out) if isinstance(out, (list, tuple)) else [out]
            if per_output is None:
                per_output = [[] for _ in outs]
            for slot, o in zip(per_output, outs):
                slot.append(np.asarray(o))
        per_output = per_output or []
        if stack_outputs:
            return [np.concatenate(slot, axis=0) for slot in per_output]
        return per_output

    # -- persistence ------------------------------------------------------
    def save(self, path, training=True):
        import os

        import paddle_tpu

        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)
        if training:
            paddle_tpu.save(self.network.state_dict(), path + ".pdparams")
            if self._optimizer is not None:
                paddle_tpu.save(self._optimizer.state_dict(), path + ".pdopt")
        else:
            from paddle_tpu import jit

            jit.save(self.network, path, input_spec=self._inputs)

    def load(self, path, skip_mismatch=False, reset_optimizer=False):
        import os

        import paddle_tpu

        state = paddle_tpu.load(path + ".pdparams")
        if skip_mismatch:
            current = self.network.state_dict()
            state = {k: v for k, v in state.items()
                     if k in current and
                     tuple(np.asarray(_np(v)).shape) ==
                     tuple(np.asarray(current[k]._array).shape)}
        self.network.set_state_dict(state)
        if not reset_optimizer and self._optimizer is not None and \
                os.path.exists(path + ".pdopt"):
            self._optimizer.set_state_dict(paddle_tpu.load(path + ".pdopt"))
        self._train_step = None
        self._eval_jit = None
