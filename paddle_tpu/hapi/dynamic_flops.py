"""paddle.flops — analog of python/paddle/hapi/dynamic_flops.py.

TPU-native twist: the total comes from XLA's own cost analysis of the
jitted forward (exact for whatever the model actually lowers to, fused
ops included), while the optional per-layer table is the reference's
hook-based analytic count for the common layer types.
"""
from __future__ import annotations

import numpy as np

from paddle_tpu.core.tensor import Tensor

__all__ = ["flops"]


def _analytic_flops(layer, inputs, output, custom_ops=None):
    """Per-layer analytic FLOPs for table rows (hook-based, like the
    reference's register_hooks table). `custom_ops` maps layer type ->
    fn(layer, inputs, output) -> flops (reference parity)."""
    import paddle_tpu.nn as nn

    if custom_ops:
        fn = custom_ops.get(type(layer))
        if fn is not None:
            return int(fn(layer, inputs, output))
    x = inputs[0] if isinstance(inputs, (tuple, list)) else inputs
    out = output[0] if isinstance(output, (tuple, list)) else output
    try:
        if isinstance(layer, nn.Linear):
            return 2 * int(np.prod(out.shape)) * layer.weight.shape[0]
        if isinstance(layer, (nn.Conv2D,)):
            kh, kw = layer._kernel_size
            cin = layer._in_channels
            groups = getattr(layer, "_groups", 1)
            return 2 * int(np.prod(out.shape)) * cin // groups * kh * kw
        if isinstance(layer, (nn.BatchNorm2D, nn.BatchNorm1D, nn.LayerNorm)):
            return 2 * int(np.prod(x.shape))
        if isinstance(layer, (nn.ReLU, nn.GELU, nn.Sigmoid, nn.Tanh)):
            return int(np.prod(out.shape))
    except Exception:
        pass
    return 0


def flops(net, input_size=None, inputs=None, custom_ops=None,
          print_detail=False):
    """Total forward FLOPs of `net`.

    `input_size`: shape of a single (batched) float input, e.g.
    [1, 3, 224, 224]; or pass `inputs` (Tensor / array / tuple of them).
    Returns the XLA-measured total; `print_detail` also prints a
    per-layer analytic table (reference dynamic_flops format).
    """
    import jax
    import jax.numpy as jnp

    if inputs is None:
        if input_size is None:
            raise ValueError("flops() needs input_size or inputs")
        inputs = (np.zeros(tuple(input_size), np.float32),)
    elif not isinstance(inputs, (tuple, list)):
        inputs = (inputs,)
    arrays = tuple(np.asarray(i._array if isinstance(i, Tensor) else i)
                   for i in inputs)

    was_training = getattr(net, "training", False)
    net.eval()

    rows = []
    handles = []
    # hooks run unconditionally: they are also the analytic fallback
    # when XLA cost analysis is unavailable on a backend
    def make_hook(name, layer):
        def hook(lyr, ins, out):
            rows.append((name, type(lyr).__name__,
                         sum(int(np.prod(p._array.shape))
                             for p in lyr.parameters(include_sublayers=False))
                         if hasattr(lyr, "parameters") else 0,
                         _analytic_flops(lyr, ins, out, custom_ops)))
        return hook

    for name, sub in net.named_sublayers():
        if not list(sub.sublayers()):  # leaves only
            handles.append(sub.register_forward_post_hook(
                make_hook(name, sub)))

    # eager pass to fire hooks (and sanity-check shapes)
    out = net(*[Tensor(a) for a in arrays])
    for h in handles:
        try:
            h.remove()
        except Exception:
            pass

    # XLA total: jit the pure forward and read the compiled cost analysis
    from paddle_tpu.jit.api import bound_state

    params = list(net.parameters())
    buffers = list(net.buffers()) if hasattr(net, "buffers") else []

    def fwd(param_arrays, buf_arrays, *xs):
        state = params + buffers
        with bound_state(zip(state, list(param_arrays) + list(buf_arrays)),
                         state):
            o = net(*[Tensor._wrap(x) for x in xs])
            return o._array if isinstance(o, Tensor) else o

    total = None
    try:
        compiled = jax.jit(fwd).lower(
            [p._array for p in params], [b._array for b in buffers],
            *[jnp.asarray(a) for a in arrays]).compile()
        ca = compiled.cost_analysis()
        ca = ca[0] if isinstance(ca, (list, tuple)) else ca
        total = int(ca.get("flops", 0)) if ca else None
    except Exception:
        total = None
    if total is None:  # fall back to the analytic sum
        total = sum(r[3] for r in rows)

    if was_training:
        net.train()

    if print_detail:
        print(f"{'Layer':<32}{'Type':<16}{'Params':>12}{'FLOPs':>16}")
        for name, tname, nparam, fl in rows:
            print(f"{name:<32}{tname:<16}{nparam:>12}{fl:>16}")
        print(f"Total params: "
              f"{sum(int(np.prod(p._array.shape)) for p in params)}")
        print(f"Total FLOPs (XLA): {total}")
    return total
