"""paddle.summary analog (python/paddle/hapi/model_summary.py): layer
table with output shapes and parameter counts, collected via forward
post-hooks on one zero-input forward pass."""
from __future__ import annotations

import numpy as np

from paddle_tpu.core.tensor import Tensor

__all__ = ["summary"]


def summary(net, input_size=None, dtypes=None, input=None):
    """Prints the table; returns {'total_params': .., 'trainable_params': ..}."""
    rows = []
    hooks = []

    def make_hook(name, layer):
        def hook(lyr, inputs, output):
            outs = output if isinstance(output, (tuple, list)) else [output]
            shapes = [list(o.shape) for o in outs
                      if isinstance(o, Tensor)]
            n_params = int(sum(np.prod(p.shape)
                               for p in lyr.parameters(include_sublayers=False))) \
                if hasattr(lyr, "parameters") else 0
            rows.append((name, type(lyr).__name__,
                         shapes[0] if shapes else [], n_params))
        return hook

    named = list(net.named_sublayers()) if hasattr(net, "named_sublayers") \
        else []
    for name, layer in named:
        if hasattr(layer, "register_forward_post_hook"):
            hooks.append(layer.register_forward_post_hook(
                make_hook(name, layer)))

    try:
        if input is not None:
            net(input)
        else:
            if input_size is None:
                raise ValueError(
                    "summary needs input_size (a shape, list of shapes, "
                    "or InputSpecs) or a concrete `input` tensor")
            from paddle_tpu.jit.api import InputSpec

            def norm(item):
                """shape tuple / InputSpec -> (concrete shape, dtype)."""
                if isinstance(item, InputSpec):
                    shape, dt = item.shape, item.dtype or "float32"
                else:
                    shape, dt = item, None
                # None/-1/named dims (unspecified batch) -> 1, paddle-style
                shape = [1 if d is None or isinstance(d, str)
                         or (isinstance(d, int) and d < 0)
                         else int(d) for d in shape]
                return shape, dt

            if isinstance(input_size, InputSpec):
                items = [input_size]
            else:
                first = input_size[0]
                items = list(input_size) if isinstance(
                    first, (list, tuple, InputSpec)) else [input_size]
            if dtypes is not None and len(dtypes) != len(items):
                raise ValueError(
                    f"dtypes has {len(dtypes)} entries for {len(items)} "
                    "inputs")
            args = []
            for i, item in enumerate(items):
                shape, spec_dt = norm(item)
                dt = (dtypes[i] if dtypes is not None
                      else spec_dt or "float32")
                args.append(Tensor(np.zeros(shape, np.dtype(dt))))
            net(*args)
    finally:
        for h in hooks:
            h.remove()

    total = int(sum(np.prod(p.shape) for p in net.parameters()))
    trainable = int(sum(np.prod(p.shape) for p in net.parameters()
                        if not p.stop_gradient))
    w = max([len(r[0]) + len(r[1]) for r in rows] + [20]) + 4
    line = "-" * (w + 40)
    print(line)
    print(f"{'Layer (type)':<{w}}{'Output Shape':<22}{'Param #':>12}")
    print(line)
    for name, cls, shape, n in rows:
        print(f"{name + ' (' + cls + ')':<{w}}{str(shape):<22}{n:>12,}")
    print(line)
    print(f"Total params: {total:,}")
    print(f"Trainable params: {trainable:,}")
    print(f"Non-trainable params: {total - trainable:,}")
    print(line)
    return {"total_params": total, "trainable_params": trainable}
