"""hapi callbacks — analog of python/paddle/hapi/callbacks.py
(ProgBarLogger, ModelCheckpoint, EarlyStopping, LRScheduler).

The callback protocol matches the reference: config_callbacks builds a
CallbackList; hooks fire around train/eval loops, epochs and batches,
with `logs` dicts carrying loss/metrics/step counters.
"""
from __future__ import annotations

import os
import time

__all__ = ["Callback", "ProgBarLogger", "ModelCheckpoint", "EarlyStopping",
           "LRScheduler", "MetricsLogger"]


class Callback:
    def __init__(self):
        self.model = None
        self.params = {}

    def set_model(self, model):
        self.model = model

    def set_params(self, params):
        self.params = params or {}

    # train hooks
    def on_train_begin(self, logs=None): ...
    def on_train_end(self, logs=None): ...
    def on_epoch_begin(self, epoch, logs=None): ...
    def on_epoch_end(self, epoch, logs=None): ...
    def on_train_batch_begin(self, step, logs=None): ...
    def on_train_batch_end(self, step, logs=None): ...
    # eval hooks
    def on_eval_begin(self, logs=None): ...
    def on_eval_end(self, logs=None): ...
    def on_eval_batch_begin(self, step, logs=None): ...
    def on_eval_batch_end(self, step, logs=None): ...


class CallbackList:
    def __init__(self, callbacks, model, params):
        self.callbacks = list(callbacks)
        for c in self.callbacks:
            c.set_model(model)
            c.set_params(params)

    def call(self, name, *args, **kwargs):
        for c in self.callbacks:
            getattr(c, name)(*args, **kwargs)


class ProgBarLogger(Callback):
    """Per-epoch console logging (hapi ProgBarLogger, verbosity-gated)."""

    def __init__(self, log_freq=1, verbose=2):
        super().__init__()
        self.log_freq = log_freq
        self.verbose = verbose

    def _fmt(self, logs):
        return " - ".join(f"{k}: {v:.4f}" if isinstance(v, float)
                          else f"{k}: {v}" for k, v in (logs or {}).items()
                          if k not in ("batch_size",))

    def on_epoch_begin(self, epoch, logs=None):
        self._epoch = epoch
        self._t0 = time.time()
        if self.verbose:
            print(f"Epoch {epoch + 1}/{self.params.get('epochs', '?')}")

    def on_train_batch_end(self, step, logs=None):
        if self.verbose > 1 and self.log_freq and \
                (step + 1) % self.log_freq == 0:
            print(f"step {step + 1}/{self.params.get('steps', '?')}"
                  f" - {self._fmt(logs)}")

    def on_epoch_end(self, epoch, logs=None):
        if self.verbose:
            dt = time.time() - self._t0
            print(f"epoch {epoch + 1} done in {dt:.1f}s - {self._fmt(logs)}")

    def on_eval_end(self, logs=None):
        if self.verbose:
            print(f"Eval - {self._fmt(logs)}")


class ModelCheckpoint(Callback):
    """Saves `{save_dir}/{epoch}` + `{save_dir}/final` (hapi parity)."""

    def __init__(self, save_freq=1, save_dir=None):
        super().__init__()
        self.save_freq = save_freq
        self.save_dir = save_dir

    def on_epoch_end(self, epoch, logs=None):
        if self.save_dir and (epoch + 1) % self.save_freq == 0:
            self.model.save(os.path.join(self.save_dir, str(epoch)))

    def on_train_end(self, logs=None):
        if self.save_dir:
            self.model.save(os.path.join(self.save_dir, "final"))


class EarlyStopping(Callback):
    """Stop when `monitor` stops improving (hapi EarlyStopping)."""

    def __init__(self, monitor="loss", mode="auto", patience=0,
                 min_delta=0, baseline=None, save_best_model=False):
        super().__init__()
        self.monitor = monitor
        self.patience = patience
        self.min_delta = abs(min_delta)
        self.baseline = baseline
        if mode == "auto":
            mode = "max" if "acc" in monitor else "min"
        self.mode = mode
        self.stopped_epoch = None
        self.reset()

    def reset(self):
        self.wait = 0
        self.best = self.baseline if self.baseline is not None else (
            -float("inf") if self.mode == "max" else float("inf"))

    def _better(self, v):
        return v > self.best + self.min_delta if self.mode == "max" \
            else v < self.best - self.min_delta

    def on_eval_end(self, logs=None):
        v = (logs or {}).get(self.monitor)
        if v is None:
            return
        if isinstance(v, (list, tuple)):
            v = v[0]
        if self._better(float(v)):
            self.best = float(v)
            self.wait = 0
        else:
            self.wait += 1
            if self.wait > self.patience:
                self.model.stop_training = True
                self.stopped_epoch = True


class MetricsLogger(Callback):
    """Forward hapi train/eval logs into an observability registry.

        model.fit(..., callbacks=[MetricsLogger()])

    Per train batch: step counter + per-key gauges labeled
    phase="train"; per eval end: gauges labeled phase="eval"; per
    epoch: epoch counter. Numeric log values only (hapi metrics may
    return lists — the first element is taken, matching ProgBarLogger's
    display convention)."""

    def __init__(self, registry=None, prefix="hapi"):
        super().__init__()
        if registry is None:
            from paddle_tpu.observability.metrics import get_registry

            registry = get_registry()
        self.registry = registry
        self.prefix = prefix
        self._steps = registry.counter(
            f"{prefix}_steps_total", "hapi train batches completed.")
        self._epochs = registry.counter(
            f"{prefix}_epochs_total", "hapi epochs completed.")
        self._gauges = {}              # per-key handle cache (hot path)
        self._names = {}               # sanitized name -> original key

    def _gauge(self, key):
        g = self._gauges.get(key)
        if g is None:
            import re

            name = re.sub(r"[^a-zA-Z0-9_:]", "_",
                          f"{self.prefix}_{key}")
            prior = self._names.setdefault(name, key)
            if prior != key:
                # two distinct log keys sanitizing to one metric would
                # silently interleave their values — be loud instead
                raise ValueError(
                    f"hapi metric names {prior!r} and {key!r} both "
                    f"sanitize to {name!r}; rename one")
            g = self._gauges[key] = self.registry.gauge(
                name, f"hapi log value {key!r}.", labelnames=("phase",))
        return g

    def _forward(self, logs, phase):
        import numbers

        for k, v in (logs or {}).items():
            if isinstance(v, (list, tuple)):
                v = v[0] if v else None
            # numbers.Real, not (int, float): metric accumulators often
            # hand back numpy scalars (np.float32 is not a float)
            if isinstance(v, bool) or not isinstance(v, numbers.Real):
                continue
            self._gauge(k).labels(phase=phase).set(float(v))

    def on_train_batch_end(self, step, logs=None):
        self._steps.inc()
        self._forward(logs, "train")

    def on_epoch_end(self, epoch, logs=None):
        self._epochs.inc()
        self._forward(logs, "train")

    def on_eval_end(self, logs=None):
        self._forward(logs, "eval")


class LRScheduler(Callback):
    """Steps the optimizer's LRScheduler (hapi LRScheduler callback:
    by_step fires per train batch, else per epoch)."""

    def __init__(self, by_step=True, by_epoch=False):
        super().__init__()
        assert by_step != by_epoch, "choose exactly one cadence"
        self.by_step = by_step

    def _sched(self):
        from paddle_tpu.optimizer.lr import LRScheduler as Sched

        lr = getattr(self.model._optimizer, "_learning_rate", None)
        return lr if isinstance(lr, Sched) else None

    def on_train_batch_end(self, step, logs=None):
        s = self._sched()
        if self.by_step and s is not None:
            s.step()

    def on_epoch_end(self, epoch, logs=None):
        s = self._sched()
        if not self.by_step and s is not None:
            s.step()


def config_callbacks(callbacks, model, epochs=None, steps=None, verbose=2,
                     log_freq=1, save_dir=None, save_freq=1, metrics=None):
    cbs = list(callbacks or [])
    if not any(isinstance(c, ProgBarLogger) for c in cbs):
        cbs.insert(0, ProgBarLogger(log_freq, verbose=verbose))
    if save_dir and not any(isinstance(c, ModelCheckpoint) for c in cbs):
        cbs.append(ModelCheckpoint(save_freq, save_dir))
    if not any(isinstance(c, LRScheduler) for c in cbs):
        cbs.append(LRScheduler())
    params = {"epochs": epochs, "steps": steps, "verbose": verbose,
              "metrics": metrics or []}
    return CallbackList(cbs, model, params)
