"""paddle.audio.functional analog (audio/functional/functional.py,
window.py): windows, mel filterbanks, dct, dB conversion — jnp math so
feature extraction fuses into the same XLA program as the model."""
from __future__ import annotations

import math

import jax.numpy as jnp

__all__ = ["get_window", "hz_to_mel", "mel_to_hz", "mel_frequencies",
           "fft_frequencies", "compute_fbank_matrix", "power_to_db",
           "create_dct"]


def get_window(window, win_length, fftbins=True, dtype=jnp.float32):
    """hann/hamming/blackman/bartlett/ones (window.py get_window)."""
    if isinstance(window, (tuple, list)):
        window = window[0]
    n = win_length
    # periodic (fftbins=True) windows divide by n, symmetric by n-1
    d = n if fftbins else max(n - 1, 1)
    k = jnp.arange(n, dtype=dtype)
    if window in ("hann", "hanning"):
        w = 0.5 - 0.5 * jnp.cos(2 * math.pi * k / d)
    elif window == "hamming":
        w = 0.54 - 0.46 * jnp.cos(2 * math.pi * k / d)
    elif window == "blackman":
        w = 0.42 - 0.5 * jnp.cos(2 * math.pi * k / d) \
            + 0.08 * jnp.cos(4 * math.pi * k / d)
    elif window == "bartlett":
        w = 1.0 - jnp.abs(2 * k / d - 1.0)
    elif window in ("ones", "boxcar", "rectangular"):
        w = jnp.ones((n,), dtype)
    else:
        raise ValueError(f"unsupported window {window!r}")
    return w.astype(dtype)


def hz_to_mel(freq, htk=False):
    f = jnp.asarray(freq, jnp.float32)
    if htk:
        return 2595.0 * jnp.log10(1.0 + f / 700.0)
    # slaney scale (librosa default, matches the reference)
    f_min, f_sp = 0.0, 200.0 / 3
    mels = (f - f_min) / f_sp
    min_log_hz = 1000.0
    min_log_mel = (min_log_hz - f_min) / f_sp
    logstep = math.log(6.4) / 27.0
    return jnp.where(f >= min_log_hz,
                     min_log_mel + jnp.log(jnp.maximum(f, 1e-10)
                                           / min_log_hz) / logstep,
                     mels)


def mel_to_hz(mel, htk=False):
    m = jnp.asarray(mel, jnp.float32)
    if htk:
        return 700.0 * (10.0 ** (m / 2595.0) - 1.0)
    f_min, f_sp = 0.0, 200.0 / 3
    freqs = f_min + f_sp * m
    min_log_hz = 1000.0
    min_log_mel = (min_log_hz - f_min) / f_sp
    logstep = math.log(6.4) / 27.0
    return jnp.where(m >= min_log_mel,
                     min_log_hz * jnp.exp(logstep * (m - min_log_mel)),
                     freqs)


def mel_frequencies(n_mels=64, f_min=0.0, f_max=11025.0, htk=False):
    lo = hz_to_mel(f_min, htk)
    hi = hz_to_mel(f_max, htk)
    return mel_to_hz(jnp.linspace(lo, hi, n_mels), htk)


def fft_frequencies(sr, n_fft):
    return jnp.linspace(0, sr / 2, n_fft // 2 + 1)


def compute_fbank_matrix(sr, n_fft, n_mels=64, f_min=0.0, f_max=None,
                         htk=False, norm="slaney"):
    """Triangular mel filterbank [n_mels, n_fft//2+1]."""
    f_max = f_max or sr / 2.0
    fft_f = fft_frequencies(sr, n_fft)
    mel_f = mel_frequencies(n_mels + 2, f_min, f_max, htk)
    fdiff = jnp.diff(mel_f)
    ramps = mel_f[:, None] - fft_f[None, :]
    lower = -ramps[:-2] / fdiff[:-1, None]
    upper = ramps[2:] / fdiff[1:, None]
    fb = jnp.maximum(0.0, jnp.minimum(lower, upper))
    if norm == "slaney":
        enorm = 2.0 / (mel_f[2:n_mels + 2] - mel_f[:n_mels])
        fb = fb * enorm[:, None]
    return fb


def power_to_db(spect, ref_value=1.0, amin=1e-10, top_db=80.0):
    s = jnp.asarray(spect)
    log_spec = 10.0 * jnp.log10(jnp.maximum(amin, s)) \
        - 10.0 * math.log10(max(amin, ref_value))
    if top_db is not None:
        log_spec = jnp.maximum(log_spec, log_spec.max() - top_db)
    return log_spec


def create_dct(n_mfcc, n_mels, norm="ortho"):
    """DCT-II matrix [n_mels, n_mfcc] (functional.create_dct)."""
    n = jnp.arange(n_mels, dtype=jnp.float32)
    k = jnp.arange(n_mfcc, dtype=jnp.float32)
    dct = jnp.cos(math.pi / n_mels * (n[:, None] + 0.5) * k[None, :])
    if norm == "ortho":
        dct = dct * jnp.sqrt(2.0 / n_mels)
        dct = dct.at[:, 0].multiply(1.0 / jnp.sqrt(2.0))
    else:
        dct = dct * 2.0
    return dct
