"""paddle.audio analog (python/paddle/audio/): feature layers +
functional DSP math, jnp-native so it compiles with the model."""
from . import features, functional
from .features import MFCC, LogMelSpectrogram, MelSpectrogram, Spectrogram

__all__ = ["features", "functional", "Spectrogram", "MelSpectrogram",
           "LogMelSpectrogram", "MFCC"]
