"""paddle.audio.features analog (audio/features/layers.py):
Spectrogram, MelSpectrogram, LogMelSpectrogram, MFCC as nn.Layers.

TPU-native: framing is one strided gather and the STFT is a batched
rfft — everything stays jnp, so the whole feature pipeline compiles
into the model's program (contrast the reference's eager kaldi-style
CPU featurization)."""
from __future__ import annotations

import jax.numpy as jnp

import paddle_tpu.nn as nn
from paddle_tpu.core.tensor import Tensor
from paddle_tpu.ops.dispatch import apply

from .functional import (compute_fbank_matrix, create_dct, get_window,
                         power_to_db)

__all__ = ["Spectrogram", "MelSpectrogram", "LogMelSpectrogram", "MFCC"]


def _frame(x, frame_length, hop_length, center, pad_mode):
    if center:
        pad = frame_length // 2
        widths = [(0, 0)] * (x.ndim - 1) + [(pad, pad)]
        x = jnp.pad(x, widths, mode=pad_mode)
    T = x.shape[-1]
    n_frames = 1 + (T - frame_length) // hop_length
    idx = (jnp.arange(frame_length)[None, :]
           + hop_length * jnp.arange(n_frames)[:, None])
    return x[..., idx]  # [..., n_frames, frame_length]


class Spectrogram(nn.Layer):
    """|STFT|^power: [..., T] -> [..., n_fft//2+1, n_frames]."""

    def __init__(self, n_fft=512, hop_length=None, win_length=None,
                 window="hann", power=2.0, center=True,
                 pad_mode="reflect", dtype="float32"):
        super().__init__()
        self.n_fft = n_fft
        self.hop_length = hop_length or n_fft // 4
        self.win_length = win_length or n_fft
        self.power = power
        self.center = center
        self.pad_mode = pad_mode
        w = get_window(window, self.win_length,
                       dtype=jnp.dtype(dtype))
        if self.win_length < n_fft:  # center-pad the window to n_fft
            lp = (n_fft - self.win_length) // 2
            w = jnp.pad(w, (lp, n_fft - self.win_length - lp))
        self.window = w
        self.dtype = jnp.dtype(dtype)

    def forward(self, x):
        win, n_fft, hop = self.window, self.n_fft, self.hop_length

        def fn(a):
            frames = _frame(a, n_fft, hop, self.center, self.pad_mode)
            spec = jnp.fft.rfft((frames * win).astype(self.dtype),
                                n=n_fft, axis=-1)
            mag = jnp.abs(spec) ** self.power
            return jnp.swapaxes(mag, -1, -2).astype(self.dtype)

        return apply("spectrogram", fn,
                     x if isinstance(x, Tensor) else Tensor(x))


class MelSpectrogram(nn.Layer):
    def __init__(self, sr=22050, n_fft=512, hop_length=None,
                 win_length=None, window="hann", power=2.0, center=True,
                 pad_mode="reflect", n_mels=64, f_min=50.0, f_max=None,
                 htk=False, norm="slaney", dtype="float32"):
        super().__init__()
        self.spectrogram = Spectrogram(n_fft, hop_length, win_length,
                                       window, power, center, pad_mode,
                                       dtype=dtype)
        self.fbank = compute_fbank_matrix(sr, n_fft, n_mels, f_min, f_max,
                                          htk, norm).astype(
                                              jnp.dtype(dtype))

    def forward(self, x):
        spec = self.spectrogram(x)
        fb = self.fbank
        return apply("mel_spectrogram",
                     lambda s: jnp.einsum("mf,...ft->...mt", fb, s), spec)


class LogMelSpectrogram(nn.Layer):
    def __init__(self, sr=22050, n_fft=512, hop_length=None,
                 win_length=None, window="hann", power=2.0, center=True,
                 pad_mode="reflect", n_mels=64, f_min=50.0, f_max=None,
                 htk=False, norm="slaney", ref_value=1.0, amin=1e-10,
                 top_db=None, dtype="float32"):
        super().__init__()
        self.mel = MelSpectrogram(sr, n_fft, hop_length, win_length,
                                  window, power, center, pad_mode, n_mels,
                                  f_min, f_max, htk, norm, dtype=dtype)
        self.ref_value = ref_value
        self.amin = amin
        self.top_db = top_db

    def forward(self, x):
        m = self.mel(x)
        return apply("log_mel",
                     lambda s: power_to_db(s, self.ref_value, self.amin,
                                           self.top_db), m)


class MFCC(nn.Layer):
    def __init__(self, sr=22050, n_mfcc=40, n_fft=512, hop_length=None,
                 win_length=None, window="hann", power=2.0, center=True,
                 pad_mode="reflect", n_mels=64, f_min=50.0, f_max=None,
                 htk=False, norm="slaney", ref_value=1.0, amin=1e-10,
                 top_db=None, dtype="float32"):
        super().__init__()
        self.log_mel = LogMelSpectrogram(
            sr, n_fft, hop_length, win_length, window, power, center,
            pad_mode, n_mels, f_min, f_max, htk, norm, ref_value, amin,
            top_db, dtype=dtype)
        self.dct = create_dct(n_mfcc, n_mels).astype(jnp.dtype(dtype))

    def forward(self, x):
        lm = self.log_mel(x)
        dct = self.dct
        return apply("mfcc",
                     lambda s: jnp.einsum("mk,...mt->...kt", dct, s), lm)
