"""Per-tenant LoRA adapter registry — the HOST-side half of the
multi-tenant adapter subsystem.

An `AdapterRegistry` owns every tenant's low-rank factors in the
device-pool layout (rank-padded to a fixed `max_rank`, B factors
re-grouped to the serving engine's column-parallel output layouts), so
the paged on-device pool (`adapters.pool.PagedAdapterPool`) can swap an
adapter in with one contiguous copy per site and ONE compiled trace
serves every rank. Adapter id 0 is reserved: the null/base adapter
(no registration, all-zero factors, zero scaling) — a request carrying
id 0 decodes bit-identically to an engine with no adapter subsystem.

Registration takes standard LoRA factors per target site per layer:
`A [rank, in]`, `B [out, rank]` with `delta_W = B @ A` and the applied
update `x -> x + (x A^T B^T) * scaling` (scaling defaults to
`alpha / rank` when `alpha` is given). Sites an adapter does not tune
stay exact-zero — a per-site/per-layer no-op.
"""
from __future__ import annotations

import numpy as np

from paddle_tpu.ops.lora import LORA_SITES

__all__ = ["AdapterRegistry", "NULL_ADAPTER_ID"]

#: Reserved id of the null/base adapter (pool page 0, all zeros).
NULL_ADAPTER_ID = 0


class AdapterRegistry:
    """Host-side store of rank-padded per-tenant LoRA factors.

        reg = AdapterRegistry(model.config, max_rank=8)
        reg.register(7, {"qkv": [(A0, B0), (A1, B1)]}, alpha=16)

    `config` is a GPTConfig-like object (num_layers, hidden_size,
    intermediate_size, num_heads). The registry is pure numpy — no
    device state; the paged pool reads `stacks(adapter_id)` to swap a
    tenant in."""

    def __init__(self, config, max_rank=8, dtype=np.float32):
        if max_rank < 1:
            raise ValueError(f"max_rank must be >= 1, got {max_rank}")
        self.max_rank = int(max_rank)
        self.dtype = np.dtype(dtype)
        self.num_layers = int(config.num_layers)
        self.hidden_size = int(config.hidden_size)
        self.intermediate_size = int(config.intermediate_size)
        self.num_heads = int(config.num_heads)
        if self.hidden_size % self.num_heads:
            raise ValueError(
                f"hidden_size={self.hidden_size} not divisible by "
                f"num_heads={self.num_heads}")
        self.head_dim = self.hidden_size // self.num_heads
        self._adapters = {}            # id -> {site stacks + scaling}
        self._groups = {}              # group key -> set of ids

    # -- site geometry ----------------------------------------------------
    def site_dims(self, site):
        """(in_dim, out_dim) of one target matmul."""
        H, I = self.hidden_size, self.intermediate_size
        return {"qkv": (H, 3 * H), "out": (H, H), "fc1": (H, I),
                "fc2": (I, H)}[site]

    # -- registration -----------------------------------------------------
    def register(self, adapter_id, weights, scaling=None, alpha=None,
                 group=None):
        """Register one tenant's adapter. `weights` maps a site name
        (one of LORA_SITES) to a per-layer sequence of `(A, B)` pairs
        (None skips a layer). A is `[rank, in]`, B `[out, rank]`,
        rank <= max_rank — rank-padded to the fixed pool shape with
        exact zeros. `scaling` defaults to `alpha / rank` (alpha given)
        or 1.0. Re-registering a live id raises — tenants update via a
        new id, so a pool page can never silently serve stale bytes.

        `group` (any hashable key) declares a RANK GROUP: one tenant's
        adapter shipped at several ranks (quality/latency variants of
        the same LoRA — the grouped multi-rank tail of the paged-pool
        design). Members of a group share ONE page budget in the paged
        pool: acquiring one variant reuses (and evicts) an idle
        sibling's page in place instead of taking a second page, and
        the pool's leak audit asserts no group ever holds two."""
        aid = int(adapter_id)
        if aid == NULL_ADAPTER_ID:
            raise ValueError(
                "adapter id 0 is reserved for the null/base adapter")
        if aid < 0:
            raise ValueError(f"adapter ids are >= 1, got {aid}")
        if aid in self._adapters:
            raise ValueError(
                f"adapter {aid} is already registered — tenants ship "
                "updates under a fresh id")
        if not weights:
            raise ValueError("an adapter must tune at least one site")
        unknown = set(weights) - set(LORA_SITES)
        if unknown:
            raise ValueError(
                f"unknown LoRA site(s) {sorted(unknown)} — targets are "
                f"{LORA_SITES}")
        L, R = self.num_layers, self.max_rank
        entry = {"rank": 0}
        ranks_seen = set()
        for site in LORA_SITES:
            in_d, out_d = self.site_dims(site)
            a_stack = np.zeros((L, R, in_d), self.dtype)
            b_stack = np.zeros((L, R, out_d), self.dtype)
            per_layer = weights.get(site)
            if per_layer is not None:
                if len(per_layer) != L:
                    raise ValueError(
                        f"site {site!r}: expected {L} per-layer "
                        f"entries, got {len(per_layer)}")
                for li, pair in enumerate(per_layer):
                    if pair is None:
                        continue
                    A, B = pair
                    A = np.asarray(A, self.dtype)
                    B = np.asarray(B, self.dtype)
                    r = A.shape[0]
                    if r < 1 or r > R:
                        raise ValueError(
                            f"site {site!r} layer {li}: rank {r} "
                            f"outside [1, max_rank={R}]")
                    if A.shape != (r, in_d) or B.shape != (out_d, r):
                        raise ValueError(
                            f"site {site!r} layer {li}: want A "
                            f"[{r}, {in_d}] and B [{out_d}, {r}], got "
                            f"A {A.shape} / B {B.shape}")
                    a_stack[li, :r] = A
                    b_stack[li, :r] = B.T
                    ranks_seen.add(r)
                    entry["rank"] = max(entry["rank"], r)
            entry["a_" + site] = a_stack
            entry["b_" + site] = self._b_layout(site, b_stack)
        if entry["rank"] == 0:
            raise ValueError("an adapter must tune at least one "
                             "(site, layer) pair")
        if scaling is None:
            if alpha is not None and len(ranks_seen) > 1:
                # standard LoRA scales each module by alpha/r_module;
                # ONE adapter-wide scaling cannot express that —
                # silently picking a rank would under/over-drive the
                # other sites vs the checkpoint's intent
                raise ValueError(
                    f"alpha with mixed ranks {sorted(ranks_seen)} is "
                    "ambiguous (per-module alpha/rank differs) — pass "
                    "an explicit scaling, or pad the factors to one "
                    "rank")
            scaling = 1.0 if alpha is None else float(alpha) / \
                entry["rank"]
        elif alpha is not None:
            raise ValueError("pass scaling OR alpha, not both")
        entry["scaling"] = float(scaling)
        entry["group"] = group
        self._adapters[aid] = entry
        if group is not None:
            self._groups.setdefault(group, set()).add(aid)
        return aid

    def _b_layout(self, site, b_stack):
        """Re-group a site's `[L, R, out]` B stack into the pool/apply
        layout: qkv becomes head-grouped `[L, R, heads, 3, D]` (the
        `_tp_plan` column-parallel qkv order, so the pool can shard it
        on the heads axis); linear sites stay `[L, R, out]`."""
        if site != "qkv":
            return b_stack
        L, R = b_stack.shape[:2]
        # out index o = (t*heads + h)*D + d  ->  [h, t, d]
        return b_stack.reshape(
            L, R, 3, self.num_heads, self.head_dim).transpose(
                0, 1, 3, 2, 4)

    # -- lookup -----------------------------------------------------------
    def has(self, adapter_id):
        return int(adapter_id) == NULL_ADAPTER_ID \
            or int(adapter_id) in self._adapters

    def ids(self):
        """Registered (non-null) adapter ids, sorted."""
        return sorted(self._adapters)

    def rank_of(self, adapter_id):
        if int(adapter_id) == NULL_ADAPTER_ID:
            return 0
        return self._adapters[int(adapter_id)]["rank"]

    def scaling_of(self, adapter_id):
        if int(adapter_id) == NULL_ADAPTER_ID:
            return 0.0
        return self._adapters[int(adapter_id)]["scaling"]

    def group_of(self, adapter_id):
        """The rank-group key an adapter was registered under (None
        for ungrouped adapters and the null adapter)."""
        aid = int(adapter_id)
        if aid == NULL_ADAPTER_ID or aid not in self._adapters:
            return None
        return self._adapters[aid].get("group")

    def group_ids(self, group):
        """Sorted member ids of one rank group (empty when unknown)."""
        return sorted(self._groups.get(group, ()))

    def stacks(self, adapter_id):
        """The pool-layout host arrays of one adapter:
        {a_<site>/b_<site>: ndarray, scaling: float} — what the paged
        pool copies onto a device page at swap-in."""
        aid = int(adapter_id)
        if aid == NULL_ADAPTER_ID:
            raise KeyError("the null adapter has no stacks — page 0 "
                           "is permanently zero")
        if aid not in self._adapters:
            raise KeyError(f"adapter {aid} is not registered")
        return self._adapters[aid]

    def __len__(self):
        return len(self._adapters)
