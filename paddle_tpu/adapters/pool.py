"""Paged on-device adapter pool — the DEVICE half of the multi-tenant
adapter subsystem.

The same shape the paged KV cache proved out, applied to adapter
weights: a fixed number of device-resident PAGES per target site
(`adapter_pool_spec` is the single layout truth), a host-side
refcount per page, an LRU of refcount-zero (warm but idle) pages, and
stall-and-retry under pressure — `acquire` returns None when every
page is referenced, and the engine's scheduler retries next iteration
exactly like a KV block stall. Page 0 is the NULL page: permanently
held, all-zero factors, zero scaling — adapter id 0 resolves there and
its delta is exactly zero.

Swap-in is HOST-driven: on an `acquire` miss the pool copies the
registry's rank-padded stacks onto a free (or LRU-evicted) page with
one compiled `dynamic_update_index_in_dim` per site array (traced page
index — one program per pool layout, donated so the write is in-place
in HBM). The compiled engine steps only ever READ the pool arrays
(they ride the steps as traced args beside the model state), so a
swap-in between iterations never retraces anything.

Under tensor parallel the B stacks shard their OUTPUT layout over the
mesh's mp axis (`b_qkv` on the heads axis — the `_tp_plan` qkv
grouping — and the linear sites on their column axis), while the A
stacks and scalings replicate: each shard computes exactly its own
slice of every delta with full-length dots, so batched LoRA at mp=N is
bit-identical to mp=1 and adds NO collectives.
"""
from __future__ import annotations

from collections import OrderedDict

import numpy as np

from ..jit import introspect
from .registry import NULL_ADAPTER_ID, AdapterRegistry

__all__ = ["PagedAdapterPool", "adapter_pool_spec"]


def adapter_pool_spec(num_pages, num_layers, max_rank, hidden_size,
                      intermediate_size, num_heads, dtype):
    """The ONE source of truth for the pool's per-site array layout:
    ordered {name: (shape, dtype, shard_axis)} where `shard_axis` is
    the axis an mp mesh shards (None = replicated). Order is the
    `ops.lora.LoraState` constructor order; the constructor, the
    swap-in path, and the engine's shard_map in_specs all derive from
    here, so the layouts cannot drift."""
    P, L, R = int(num_pages), int(num_layers), int(max_rank)
    H, I = int(hidden_size), int(intermediate_size)
    heads = int(num_heads)
    D = H // heads
    return OrderedDict([
        ("a_qkv", ((P, L, R, H), dtype, None)),
        ("b_qkv", ((P, L, R, heads, 3, D), dtype, 3)),
        ("a_out", ((P, L, R, H), dtype, None)),
        ("b_out", ((P, L, R, H), dtype, 3)),
        ("a_fc1", ((P, L, R, H), dtype, None)),
        ("b_fc1", ((P, L, R, I), dtype, 3)),
        ("a_fc2", ((P, L, R, I), dtype, None)),
        ("b_fc2", ((P, L, R, H), dtype, 3)),
        ("scaling", ((P,), np.float32, None)),
    ])


class PagedAdapterPool:
    """Device-resident pages of active adapters + host-side paging.

        reg = AdapterRegistry(model.config, max_rank=8)
        pool = PagedAdapterPool(reg, num_pages=9)
        page = pool.acquire(7)       # swap-in on miss; None = stall
        ...
        pool.release(7)              # refcount down; warm LRU at zero

    `num_pages` INCLUDES the null page 0. The engine sizes the default
    pool at `1 + num_slots` so a full batch of distinct tenants never
    stalls; smaller pools trade HBM for swap-in traffic and ride the
    stall/retry path under pressure."""

    #: Page-recycling surface declared in introspect (the
    #: ENGINE_STEP_DONATION pattern): tpu-race TPU203 orders calls to
    #: these against the engine's dispatch/complete effects — a
    #: release between them can hand a page to a new tenant while a
    #: dispatched step still reads the old weights.
    RACE_RELEASE_METHODS = \
        introspect.ALLOCATOR_RELEASE_EFFECTS["PagedAdapterPool"]

    def __init__(self, registry, num_pages=None, dtype=None, mesh=None,
                 mp_axis="mp", donate=None):
        if not isinstance(registry, AdapterRegistry):
            raise TypeError(
                "PagedAdapterPool takes an AdapterRegistry (the "
                "host-side store it swaps adapters in from)")
        if num_pages is None:
            num_pages = 1 + max(1, len(registry))
        if num_pages < 2:
            raise ValueError("need >= 2 adapter pages (page 0 is the "
                             "null adapter)")
        self.registry = registry
        self.num_pages = int(num_pages)
        self.max_rank = registry.max_rank
        self.dtype = np.dtype(dtype) if dtype is not None \
            else registry.dtype
        self.mesh = mesh
        self.mp_axis = mp_axis if mesh is not None else None
        if mesh is not None:
            mp = mesh.shape[mp_axis]
            for name, dim in (("num_heads", registry.num_heads),
                              ("hidden_size", registry.hidden_size),
                              ("intermediate_size",
                               registry.intermediate_size)):
                if dim % mp:
                    raise ValueError(
                        f"{name}={dim} not divisible by mp degree "
                        f"{mp} — cannot column-shard the adapter B "
                        "pages")
        self._spec = adapter_pool_spec(
            self.num_pages, registry.num_layers, registry.max_rank,
            registry.hidden_size, registry.intermediate_size,
            registry.num_heads, self.dtype)
        self._arrays = self._build_arrays()
        self._updaters = None          # compiled swap-in, built lazily
        if donate is None:
            import jax

            donate = jax.default_backend() != "cpu"
        self._donate = bool(donate)
        # paging state: the PagedKVCache story, page-sized
        self._free = list(range(self.num_pages - 1, 0, -1))
        self._ref = [0] * self.num_pages
        self._ref[0] = 1               # null page: permanently held
        self._page_of = {}             # adapter id -> page
        self._adapter_of = {}          # page -> adapter id
        self._evictable = OrderedDict()    # page -> adapter id (LRU)
        self.swapins = 0
        self.evictions = 0
        # the ONE engine this pool pages for (set at engine adoption):
        # paging state is per-engine — refcounts/LRU/gauges interleaved
        # across replicas would make one replica's drain audit see
        # another's live references
        self._owner = None

    # -- layout -----------------------------------------------------------
    def adapter_pool_spec(self):
        """This pool's `adapter_pool_spec` layout table."""
        return self._spec

    def pool_pspecs(self):
        """PartitionSpecs matching `arrays()` order, for the engine's
        shard_map in_specs (all-empty without a mesh)."""
        from jax.sharding import PartitionSpec

        specs = []
        for shape, _, axis in self._spec.values():
            if self.mp_axis is None or axis is None:
                specs.append(PartitionSpec())
            else:
                dims = [None] * len(shape)
                dims[axis] = self.mp_axis
                specs.append(PartitionSpec(*dims))
        return tuple(specs)

    def _build_arrays(self):
        import jax
        import jax.numpy as jnp

        arrays = []
        pspecs = self.pool_pspecs() if self.mesh is not None else None
        for i, (shape, dt, _) in enumerate(self._spec.values()):
            z = jnp.zeros(shape, dt)
            if self.mesh is not None:
                from jax.sharding import NamedSharding

                z = jax.device_put(
                    z, NamedSharding(self.mesh, pspecs[i]))
            arrays.append(z)
        return arrays

    def arrays(self):
        """The device pool arrays in `LoraState` order — the tuple the
        engine threads through every compiled step."""
        return tuple(self._arrays)

    def pool_nbytes(self):
        return sum(int(a.nbytes) for a in self._arrays)

    # -- swap-in ----------------------------------------------------------
    def _build_updaters(self):
        import jax

        updaters = []
        pspecs = self.pool_pspecs()
        for i, name in enumerate(self._spec):
            def upd(pool, rows, page):
                return jax.lax.dynamic_update_index_in_dim(
                    pool, rows, page, axis=0)

            upd.__name__ = f"adapter_swapin_{name}"
            out_sh = None
            if self.mesh is not None:
                from jax.sharding import NamedSharding

                out_sh = NamedSharding(self.mesh, pspecs[i])
            updaters.append(jax.jit(
                upd, donate_argnums=(0,) if self._donate else (),
                out_shardings=out_sh))
        return updaters

    def _write_page(self, page, stacks, scaling):
        """Copy one adapter's host stacks onto `page` (traced index —
        every swap-in of this pool reuses the same compiled copies)."""
        import jax.numpy as jnp

        if self._updaters is None:
            self._updaters = self._build_updaters()
        for i, name in enumerate(self._spec):
            if name == "scaling":
                rows = jnp.asarray(np.float32(scaling))
            else:
                shape, dt, _ = self._spec[name]
                rows = jnp.asarray(np.asarray(stacks[name], dt))
                if rows.shape != shape[1:]:
                    raise ValueError(
                        f"adapter stack {name} has shape {rows.shape},"
                        f" pool page wants {shape[1:]}")
            self._arrays[i] = self._updaters[i](
                self._arrays[i], rows, jnp.int32(page))

    # -- paging -----------------------------------------------------------
    @property
    def num_free(self):
        """Pages acquirable right now: truly free + warm evictable."""
        return len(self._free) + len(self._evictable)

    @property
    def num_resident(self):
        """Adapters currently materialized on a page (live + warm)."""
        return len(self._page_of)

    def refcount(self, page):
        return self._ref[page]

    def page_of(self, adapter_id):
        """The page an adapter currently occupies (0 for the null
        adapter, None when not resident)."""
        aid = int(adapter_id)
        if aid == NULL_ADAPTER_ID:
            return 0
        return self._page_of.get(aid)

    def _group_sibling_page(self, aid):
        """The page a RANK-GROUP sibling of `aid` currently occupies
        (None when ungrouped or no sibling is resident). A rank group
        — one tenant's adapter at several ranks — shares ONE page
        budget, so the sibling's page is where this adapter must land
        (idle sibling) or why it must stall (referenced sibling)."""
        group = self.registry.group_of(aid)
        if group is None:
            return None
        for sib in self.registry.group_ids(group):
            if sib != aid:
                page = self._page_of.get(sib)
                if page is not None:
                    return page
        return None

    def can_acquire(self, adapter_id):
        """True when `acquire` would succeed right now (resident, a
        page is free/evictable, or the rank group's shared page sits
        idle) — the fleet's placement probe."""
        aid = int(adapter_id)
        if aid == NULL_ADAPTER_ID or aid in self._page_of:
            return True
        sib_page = self._group_sibling_page(aid)
        if sib_page is not None:
            # the group's one-page budget: free only while no live
            # lane references the sibling variant
            return self._ref[sib_page] == 0
        return self.num_free > 0

    def acquire(self, adapter_id):
        """One reference on the adapter's page, swapping it in from
        the registry on miss. Returns the page id, or None when every
        page is referenced by a live lane (caller stalls/retries — the
        KV allocator's contract). Unknown ids raise.

        Rank groups (`AdapterRegistry.register(..., group=...)`) share
        ONE page budget: a miss whose idle sibling is resident evicts
        the sibling and reuses its page in place (counted as eviction
        + swap-in), and a miss whose sibling is still referenced
        stalls — switching rank variants never grows the group's pool
        footprint."""
        aid = int(adapter_id)
        if aid == NULL_ADAPTER_ID:
            return 0
        entry = self.registry.stacks(aid)      # raises when unknown
        page = self._page_of.get(aid)
        if page is not None:
            if self._ref[page] == 0:
                del self._evictable[page]      # revive: live again
            self._ref[page] += 1
            return page
        sib_page = self._group_sibling_page(aid)
        if sib_page is not None:
            if self._ref[sib_page] > 0:
                return None        # group budget busy: stall/retry
            page = sib_page
            del self._evictable[page]
            del self._page_of[self._adapter_of[page]]
            del self._adapter_of[page]
            self.evictions += 1
        elif self._free:
            page = self._free.pop()
        elif self._evictable:
            page, cold = self._evictable.popitem(last=False)
            del self._page_of[cold]
            del self._adapter_of[page]
            self.evictions += 1
        else:
            return None                        # all pages referenced
        self._write_page(page, entry, entry["scaling"])
        self.swapins += 1
        self._ref[page] = 1
        self._page_of[aid] = page
        self._adapter_of[page] = aid
        return page

    def prefetch(self, adapter_id):
        """Warm an adapter's page WITHOUT keeping a reference: swap in
        on miss, then park it refcount-zero in the warm LRU so the
        NEXT `acquire` is a resident hit. Returns the page id, or None
        when no page is obtainable right now (same stall contract as
        `acquire` — prefetch never blocks, never evicts a live page).

        This is the async engine core's latency hider: the host cost
        is one compiled swap-in DISPATCH (the page copy itself runs
        async on device, overlapping the in-flight decode step), so
        admission-time `acquire` finds the page already resident
        instead of paying the copy in the host gap."""
        aid = int(adapter_id)
        if aid == NULL_ADAPTER_ID:
            return 0
        if aid in self._page_of:
            return self._page_of[aid]          # already warm/live
        page = self.acquire(aid)
        if page is None:
            return None
        self.release(aid)                      # park warm, evictable
        return page

    def release(self, adapter_id):
        """Drop one reference; a page at refcount zero parks in the
        warm LRU (still resident — the next acquire of the same tenant
        is a hit) instead of being zeroed. Raises on over-release."""
        aid = int(adapter_id)
        if aid == NULL_ADAPTER_ID:
            return
        page = self._page_of.get(aid)
        if page is None or self._ref[page] <= 0:
            raise RuntimeError(
                f"release of adapter {aid} with no live reference — a "
                "scheduler path double-released an adapter page")
        self._ref[page] -= 1
        if self._ref[page] == 0:
            self._evictable[page] = aid        # newest LRU entry

    def leak_check(self):
        """Page-accounting audit for a QUIESCED pool (no live lanes):
        every non-null page must be on the free list or parked
        refcount-zero in the warm LRU, and no rank group may hold more
        than its one-page budget. Returns leaked page ids —
        `GenerationEngine.drain()` asserts this empty, so a lane that
        finished without releasing its adapter page (or an acquire
        path that let a rank group spread over two pages) fails as
        loudly as a leaked KV block."""
        free = set(self._free)
        leaked = []
        for p in range(1, self.num_pages):
            if self._ref[p] == 0 and (p in free or p in self._evictable):
                continue
            leaked.append(p)
        group_page = {}
        for aid, p in self._page_of.items():
            group = self.registry.group_of(aid)
            if group is None:
                continue
            if group in group_page:
                leaked.append(p)       # a second page for one group
            else:
                group_page[group] = p
        return leaked
