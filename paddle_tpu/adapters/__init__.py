"""Multi-tenant adapter serving: paged batched-LoRA over the
generation engine.

One base model, thousands of per-tenant tuned adapters — the canonical
"millions of users" serving shape (S-LoRA / Punica). Three tiers:

- `AdapterRegistry` (registry.py): host-side store of rank-padded
  LoRA A/B factors per tenant (adapter id 0 = the null/base adapter);
- `PagedAdapterPool` (pool.py): active adapters on-device, paged with
  the PagedKVCache's block/refcount/LRU + stall-and-retry pattern,
  host-side swap-in from the registry on miss
  (`adapter_pool_spec` is the single layout truth);
- `ops.lora`: the batched apply — per-slot A/B pages gathered by a
  traced page row and fused into the qkv/out/fc1/fc2 matmuls with
  fp32 accumulation, shape-stable in `max_rank`.

The serving engine wires them together:
`GenerationEngine(model, adapters=registry)` +
`add_request(..., adapter_id=7)` — see README "Multi-tenant adapters".
"""
from paddle_tpu.adapters.pool import PagedAdapterPool, \
    adapter_pool_spec
from paddle_tpu.adapters.registry import NULL_ADAPTER_ID, \
    AdapterRegistry

__all__ = ["AdapterRegistry", "PagedAdapterPool", "adapter_pool_spec",
           "NULL_ADAPTER_ID"]
