"""paddle.linalg namespace (python/paddle/linalg.py does the same
re-export dance over tensor.linalg): the implementations live in
ops/linalg.py and dispatch through the op layer."""
from paddle_tpu.ops.linalg import (bmm, cholesky, cross, det, dist, dot,
                                   eigh, inner, inverse, kron, matmul,
                                   matrix_power, mm, mv, norm, outer, pinv,
                                   qr, slogdet, solve, svd, t, trace,
                                   triangular_solve)

__all__ = ["matmul", "mm", "bmm", "dot", "outer", "inner", "t", "norm",
           "dist", "cross", "cholesky", "inverse", "pinv", "solve",
           "triangular_solve", "svd", "qr", "eigh", "det", "slogdet",
           "matrix_power", "trace", "kron", "mv"]
