"""paddle.device analog (python/paddle/device/__init__.py)."""
from paddle_tpu.core.device import (
    Place,
    default_jax_device,
    device_count,
    get_device,
    get_place,
    is_compiled_with_cuda,
    set_device,
)


def is_compiled_with_tpu() -> bool:
    import jax

    try:
        return any(d.platform in ("tpu", "axon") for d in jax.devices())
    except Exception:
        return False


def synchronize():
    """Block until all pending device work completes — analog of
    device.cuda.synchronize; PJRT equivalent is draining async dispatch."""
    import jax

    (jax.device_put(0.0) + 0).block_until_ready()


cuda = None  # no CUDA in this build (paddle.device.cuda parity stub)

from paddle_tpu.device.memory import (  # noqa: E402
    max_memory_allocated,
    max_memory_reserved,
    memory_allocated,
    memory_reserved,
    memory_stats,
    reset_peak_memory_stats,
)

__all__ = [
    "set_device", "get_device", "get_place", "device_count", "Place",
    "is_compiled_with_cuda", "is_compiled_with_tpu", "synchronize",
    "memory_stats", "memory_allocated", "max_memory_allocated",
    "memory_reserved", "max_memory_reserved", "reset_peak_memory_stats",
]
