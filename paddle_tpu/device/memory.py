"""Device memory introspection — analog of paddle/fluid/memory/stats.h
(Stat/StatRegistry, memory_allocated/max_memory_allocated) and
python/paddle/device/cuda/__init__.py (max_memory_allocated etc.).

Two sources, best first:
- PJRT per-device memory stats (device.memory_stats(): bytes_in_use,
  peak_bytes_in_use ...) — real allocator counters on backends that
  publish them.
- Live-array accounting: sum of nbytes of jax.live_arrays() on the
  device, with a process-local high-water mark advanced at every query
  (memory_stats/max_memory_allocated/record_peak — NOT automatically
  during training steps: a per-step live_arrays() walk in the hot path
  would cost more than it tells; call record_peak() at the points you
  care about, as bench.py does after each timed run). The axon TPU
  tunnel and the CPU backend return no PJRT stats, so this keeps the
  API functional there; the reference's Stat<T> is likewise a
  host-side counter, not an allocator hook. Note the live-array view
  counts HBM-resident arrays only — in-program activation temps are
  visible through program_memory() instead.

For the true in-program peak (activations + temps inside one XLA
executable — what HBM pressure actually is on TPU), use
`program_memory(compiled)` over a compiled/lowered step; bench.py
prints it per model row.
"""
from __future__ import annotations

from typing import Optional

__all__ = [
    "memory_stats", "memory_allocated", "max_memory_allocated",
    "memory_reserved", "max_memory_reserved", "reset_peak_memory_stats",
    "record_peak", "program_memory",
]

# process-local high-water marks per device, for backends without PJRT
# allocator stats ({device_key: peak_bytes})
_peaks: dict = {}


def _device(device=None):
    import jax

    if device is None:
        from paddle_tpu.core.device import default_jax_device

        d = default_jax_device()
        return d if d is not None else jax.devices()[0]
    if isinstance(device, int):
        return jax.devices()[device]
    if isinstance(device, str):
        from paddle_tpu.core.device import Place

        return Place(device).jax_device()
    return device


def _live_bytes(dev) -> int:
    import jax

    total = 0
    for a in jax.live_arrays():
        try:
            if dev in a.devices():
                # addressable shard bytes on this device
                total += sum(s.data.nbytes for s in a.addressable_shards
                             if s.device == dev)
        except Exception:
            continue
    return total


def record_peak(device=None) -> int:
    """Sample current usage and advance the high-water mark (called by
    the compiled-step dispatchers; callable any time)."""
    dev = _device(device)
    cur = memory_allocated(dev)
    key = str(dev)
    if cur > _peaks.get(key, 0):
        _peaks[key] = cur
    return cur


def memory_stats(device=None) -> dict:
    """All counters for `device` as a dict (paddle.device.cuda
    .memory_stats analog). PJRT-backed where available, else live-array
    accounting (source field says which)."""
    dev = _device(device)
    raw: Optional[dict] = None
    try:
        raw = dev.memory_stats()
    except Exception:
        raw = None
    if raw:
        return {
            "source": "pjrt",
            "allocated_bytes": raw.get("bytes_in_use", 0),
            "peak_allocated_bytes": raw.get("peak_bytes_in_use", 0),
            "reserved_bytes": raw.get("bytes_reserved",
                                      raw.get("bytes_in_use", 0)),
            "peak_reserved_bytes": raw.get("peak_bytes_reserved",
                                           raw.get("peak_bytes_in_use", 0)),
            "largest_free_block_bytes": raw.get(
                "largest_free_block_bytes"),
            "raw": raw,
        }
    cur = _live_bytes(dev)
    key = str(dev)
    if cur > _peaks.get(key, 0):
        _peaks[key] = cur
    return {
        "source": "live_arrays",
        "allocated_bytes": cur,
        "peak_allocated_bytes": _peaks[key],
        "reserved_bytes": cur,
        "peak_reserved_bytes": _peaks[key],
        "largest_free_block_bytes": None,
        "raw": None,
    }


def memory_allocated(device=None) -> int:
    """Bytes currently allocated on `device`
    (paddle.device.cuda.memory_allocated analog)."""
    dev = _device(device)
    try:
        raw = dev.memory_stats()
        if raw and "bytes_in_use" in raw:
            return int(raw["bytes_in_use"])
    except Exception:
        pass
    return _live_bytes(dev)


def max_memory_allocated(device=None) -> int:
    """Peak allocated bytes since process start / last reset
    (paddle.device.cuda.max_memory_allocated analog)."""
    return int(memory_stats(device)["peak_allocated_bytes"])


def memory_reserved(device=None) -> int:
    return int(memory_stats(device)["reserved_bytes"])


def max_memory_reserved(device=None) -> int:
    return int(memory_stats(device)["peak_reserved_bytes"])


def reset_peak_memory_stats(device=None) -> None:
    """Reset the live-array high-water mark (PJRT peaks are allocator-
    lifetime and cannot be reset from here)."""
    _peaks[str(_device(device))] = 0


def program_memory(compiled) -> dict:
    """Peak HBM of ONE compiled XLA program: argument/output/temp/gen
    sizes from compiled.memory_analysis() — temps are the activation
    working set, the number the reference's memory profiler reports per
    iteration. Accepts a jax Compiled (from .lower().compile()) or
    anything exposing memory_analysis()."""
    out = {"argument_bytes": None, "output_bytes": None,
           "temp_bytes": None, "generated_code_bytes": None,
           "total_bytes": None}
    try:
        ma = compiled.memory_analysis()
    except Exception:
        return out
    if ma is None:
        return out
    get = lambda n: getattr(ma, n, None)
    out["argument_bytes"] = get("argument_size_in_bytes")
    out["output_bytes"] = get("output_size_in_bytes")
    out["temp_bytes"] = get("temp_size_in_bytes")
    out["generated_code_bytes"] = get("generated_code_size_in_bytes")
    alias = get("alias_size_in_bytes") or 0
    parts = [out["argument_bytes"], out["output_bytes"],
             out["temp_bytes"], out["generated_code_bytes"]]
    if all(p is not None for p in parts):
        # aliased buffers (donated params) are counted in both argument
        # and output size; subtract one copy
        out["total_bytes"] = sum(parts) - alias
    return out
