"""paddle.sparse analog (python/paddle/sparse/): SparseCooTensor /
SparseCsrTensor with creation, conversion and compute ops.

TPU-native design: XLA has no native sparse storage, and the reference's
cuSPARSE kernels have no TPU counterpart — but sparse compute maps well
onto gather + segment_sum, which XLA lowers to efficient TPU scatter
ops. Values live in a dense [nnz, ...] Tensor, so every op dispatches
through the normal op layer and is differentiable w.r.t. values and any
dense operand (tape + jit alike). Static-shape discipline: nnz is fixed
per tensor (compile-once under jit), matching XLA's static-shape model.
"""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from paddle_tpu.core.tensor import Tensor
from paddle_tpu.ops.dispatch import apply

__all__ = ["sparse_coo_tensor", "sparse_csr_tensor", "SparseCooTensor",
           "SparseCsrTensor", "matmul", "masked_matmul", "add", "relu",
           "tanh", "sqrt", "sin", "transpose", "is_same_shape"]


def _arr(x, dtype=None):
    a = x._array if isinstance(x, Tensor) else jnp.asarray(x)
    return a.astype(dtype) if dtype is not None else a


class SparseCooTensor:
    """COO: indices [sparse_ndim, nnz] int32, values Tensor [nnz, ...]."""

    def __init__(self, indices, values, shape, coalesced=False):
        self._indices = _arr(indices, jnp.int32)
        self._values = values if isinstance(values, Tensor) \
            else Tensor(values)
        self.shape = list(shape)
        self._coalesced = coalesced

    # paddle parity surface
    def indices(self):
        return Tensor._wrap(self._indices)

    def values(self):
        return self._values

    def nnz(self):
        return int(self._indices.shape[1])

    def is_sparse_coo(self):
        return True

    def is_sparse_csr(self):
        return False

    @property
    def dtype(self):
        return self._values.dtype

    @property
    def stop_gradient(self):
        return self._values.stop_gradient

    @stop_gradient.setter
    def stop_gradient(self, v):
        self._values.stop_gradient = v

    def to_dense(self):
        shape = tuple(self.shape)
        sp_nd = self._indices.shape[0]
        idx = tuple(self._indices[d] for d in range(sp_nd))

        def fn(vals):
            dense = jnp.zeros(shape, vals.dtype)
            return dense.at[idx].add(vals)
        return apply("sparse_to_dense", fn, self._values)

    def to_sparse_csr(self):
        if len(self.shape) != 2:
            raise ValueError("CSR requires a 2-D tensor")
        coo = self.coalesce()
        rows, cols = coo._indices[0], coo._indices[1]
        crows = jnp.cumsum(jnp.bincount(rows, length=self.shape[0]))
        crows = jnp.concatenate([jnp.zeros((1,), crows.dtype), crows])
        return SparseCsrTensor(crows, cols, coo._values, self.shape)

    def coalesce(self):
        """Sort + merge duplicate coordinates (host-side index prep; the
        values merge is a tracked segment_sum)."""
        if self._coalesced:
            return self
        sp_nd = self._indices.shape[0]
        flat = np.ravel_multi_index(
            tuple(np.asarray(self._indices)), tuple(self.shape[:sp_nd]))
        uniq, inv = np.unique(flat, return_inverse=True)
        new_idx = jnp.asarray(
            np.stack(np.unravel_index(uniq, tuple(self.shape[:sp_nd]))),
            jnp.int32)
        seg = jnp.asarray(inv, jnp.int32)
        n = len(uniq)

        def fn(vals):
            import jax

            return jax.ops.segment_sum(vals, seg, num_segments=n)
        return SparseCooTensor(new_idx, apply("sparse_coalesce", fn,
                                              self._values),
                               self.shape, coalesced=True)

    def transpose(self, perm):
        if sorted(perm) != list(range(len(self.shape))):
            raise ValueError(f"bad perm {perm}")
        sp_nd = self._indices.shape[0]
        if sp_nd != len(self.shape):
            raise NotImplementedError(
                "transpose of a hybrid COO tensor (dense trailing dims) "
                "is not supported; densify first")
        new_idx = self._indices[jnp.asarray(perm, jnp.int32)]
        return SparseCooTensor(new_idx, self._values,
                               [self.shape[p] for p in perm])

    def __repr__(self):
        return (f"SparseCooTensor(shape={self.shape}, nnz={self.nnz()}, "
                f"dtype={self.dtype})")


class SparseCsrTensor:
    """CSR: crows [M+1], cols [nnz], values Tensor [nnz]."""

    def __init__(self, crows, cols, values, shape):
        self._crows = _arr(crows, jnp.int32)
        self._cols = _arr(cols, jnp.int32)
        self._values = values if isinstance(values, Tensor) \
            else Tensor(values)
        self.shape = list(shape)

    def crows(self):
        return Tensor._wrap(self._crows)

    def cols(self):
        return Tensor._wrap(self._cols)

    def values(self):
        return self._values

    def nnz(self):
        return int(self._cols.shape[0])

    def is_sparse_coo(self):
        return False

    def is_sparse_csr(self):
        return True

    @property
    def dtype(self):
        return self._values.dtype

    @property
    def stop_gradient(self):
        return self._values.stop_gradient

    @stop_gradient.setter
    def stop_gradient(self, v):
        self._values.stop_gradient = v

    def _rows(self):
        counts = jnp.diff(self._crows)
        return jnp.repeat(jnp.arange(len(counts), dtype=jnp.int32), counts,
                          total_repeat_length=self.nnz())

    def to_sparse_coo(self, sparse_dim=2):
        idx = jnp.stack([self._rows(), self._cols])
        return SparseCooTensor(idx, self._values, self.shape,
                               coalesced=True)

    def to_dense(self):
        return self.to_sparse_coo().to_dense()

    def __repr__(self):
        return (f"SparseCsrTensor(shape={self.shape}, nnz={self.nnz()}, "
                f"dtype={self.dtype})")


# -- creation ---------------------------------------------------------------
def sparse_coo_tensor(indices, values, shape=None, dtype=None,
                      place=None, stop_gradient=True):
    idx = _arr(indices, jnp.int32)
    vals = values if isinstance(values, Tensor) else Tensor(values)
    if dtype is not None:
        vals = vals.astype(dtype)
    if shape is None:
        if idx.shape[1] == 0:
            raise ValueError(
                "shape is required for an empty (nnz=0) sparse tensor")
        shape = [int(d) + 1 for d in np.asarray(idx).max(axis=1)]
    t = SparseCooTensor(idx, vals, shape)
    t.stop_gradient = stop_gradient
    return t


def sparse_csr_tensor(crows, cols, values, shape, dtype=None,
                      place=None, stop_gradient=True):
    vals = values if isinstance(values, Tensor) else Tensor(values)
    if dtype is not None:
        vals = vals.astype(dtype)
    t = SparseCsrTensor(crows, cols, vals, shape)
    t.stop_gradient = stop_gradient
    return t


def _coo(x, op):
    if isinstance(x, SparseCsrTensor):
        return x.to_sparse_coo()
    if not isinstance(x, SparseCooTensor):
        raise TypeError(f"sparse.{op} expects a sparse tensor, "
                        f"got {type(x).__name__}")
    return x


# -- compute ----------------------------------------------------------------
def matmul(x, y, name=None):
    """sparse @ dense -> dense (sparse.matmul). COO/CSR [M,K] @ [K,N]:
    gather rows of y at col indices, scale by values, segment_sum into M
    rows — the TPU-efficient SpMM lowering."""
    import jax

    sp = _coo(x, "matmul")
    if len(sp.shape) != 2:
        raise ValueError("sparse.matmul supports 2-D sparse operands")
    rows, cols = sp._indices[0], sp._indices[1]
    M = sp.shape[0]
    dense = y if isinstance(y, Tensor) else Tensor(y)

    def fn(vals, d):
        contrib = vals[:, None] * d[cols]
        return jax.ops.segment_sum(contrib, rows, num_segments=M)
    return apply("sparse_matmul", fn, sp._values, dense)


def masked_matmul(x, y, mask, name=None):
    """dense @ dense evaluated ONLY at mask's nnz positions
    (sparse.masked_matmul): per-nonzero dot products — no dense [M,N]
    product is ever materialized."""
    sp = _coo(mask, "masked_matmul")
    rows, cols = sp._indices[0], sp._indices[1]
    a = x if isinstance(x, Tensor) else Tensor(x)
    b = y if isinstance(y, Tensor) else Tensor(y)

    def fn(aa, bb):
        return (aa[rows] * bb.T[cols]).sum(-1)
    vals = apply("sparse_masked_matmul", fn, a, b)
    return SparseCooTensor(sp._indices, vals, sp.shape,
                           coalesced=sp._coalesced)


def add(x, y, name=None):
    """sparse + sparse (same sparsity pattern fast path; else union via
    concatenation + coalesce)."""
    a, b = _coo(x, "add"), _coo(y, "add")
    if a.shape != b.shape:
        raise ValueError(f"shape mismatch {a.shape} vs {b.shape}")
    if a._indices.shape == b._indices.shape and \
            bool(jnp.all(a._indices == b._indices)):
        vals = apply("sparse_add", lambda u, v: u + v,
                     a._values, b._values)
        return SparseCooTensor(a._indices, vals, a.shape, a._coalesced)
    idx = jnp.concatenate([a._indices, b._indices], axis=1)
    vals = apply("sparse_add_cat",
                 lambda u, v: jnp.concatenate([u, v]),
                 a._values, b._values)
    return SparseCooTensor(idx, vals, a.shape).coalesce()


def _unary(name, fn):
    def op(x, name_=None):
        sp = _coo(x, name)
        vals = apply(f"sparse_{name}", fn, sp._values)
        out = SparseCooTensor(sp._indices, vals, sp.shape, sp._coalesced)
        return out
    op.__name__ = name
    return op


relu = _unary("relu", lambda v: jnp.maximum(v, 0))
tanh = _unary("tanh", jnp.tanh)
sqrt = _unary("sqrt", jnp.sqrt)
sin = _unary("sin", jnp.sin)


def transpose(x, perm, name=None):
    return _coo(x, "transpose").transpose(perm)


def is_same_shape(x, y):
    return list(x.shape) == list(y.shape)


def _dense_to_sparse_coo(self, sparse_dim):
    """Tensor.to_sparse_coo (dense→sparse is data-dependent, so this is
    an eager-only conversion — index discovery happens on host).
    sparse_dim < ndim builds a hybrid COO: indices over the leading
    sparse_dim dims, dense trailing dims ride in the values (a leading
    position is nonzero iff ANY trailing element is)."""
    a = np.asarray(self._array)
    if not 1 <= sparse_dim <= a.ndim:
        raise ValueError(f"sparse_dim={sparse_dim} for ndim={a.ndim}")
    if sparse_dim == a.ndim:
        nz = np.nonzero(a)
        idx = jnp.asarray(np.stack(nz), jnp.int32)
        vals = Tensor._wrap(
            self._array[tuple(jnp.asarray(n) for n in nz)])
        return SparseCooTensor(idx, vals, list(a.shape), coalesced=True)
    mask = (a != 0).any(axis=tuple(range(sparse_dim, a.ndim)))
    nz = np.nonzero(mask)
    idx = jnp.asarray(np.stack(nz), jnp.int32)
    vals = Tensor._wrap(self._array[tuple(jnp.asarray(n) for n in nz)])
    return SparseCooTensor(idx, vals, list(a.shape), coalesced=True)


def _dense_to_sparse_csr(self):
    return _dense_to_sparse_coo(self, 2).to_sparse_csr()


Tensor.to_sparse_coo = _dense_to_sparse_coo
Tensor.to_sparse_csr = _dense_to_sparse_csr
