"""Aggregated profiler statistics — analog of
python/paddle/profiler/profiler_statistic.py: per-name event summaries
(calls / total / avg / max / min for host time and, when sync-timed
device spans exist, device time) sorted by a SortedKeys policy and
rendered as an aligned summary table.

The reference attributes kernel time via CUPTI
(platform/profiler/cuda_tracer.cc); on this stack the high-fidelity
device timeline is jax.profiler's XPlane (PADDLE_TPU_TRACE_DIR), whose
protos aren't parseable in-process — so device columns here come from
SYNC-TIMED op spans: when the Profiler's targets include
ProfilerTarget.TPU, each eager op dispatch blocks until its outputs are
ready and the span approximates host-dispatch + device-execute time.
"""
from __future__ import annotations

from enum import Enum
from typing import Dict, List

__all__ = ["SortedKeys", "EventItem", "StatisticData", "build_table"]


class SortedKeys(Enum):
    """Summary-table sort policy (reference profiler_statistic.py
    SortedKeys; GPU* named DeviceTotal... here — TPU has no per-kernel
    CUPTI split)."""

    CPUTotal = 0
    CPUAvg = 1
    CPUMax = 2
    CPUMin = 3
    DeviceTotal = 4
    DeviceAvg = 5
    DeviceMax = 6
    DeviceMin = 7
    # reference-name aliases
    GPUTotal = 4
    GPUAvg = 5
    GPUMax = 6
    GPUMin = 7


class EventItem:
    """Aggregate of every span sharing one name (reference
    profiler_statistic.py EventSummary items)."""

    __slots__ = ("name", "cpu_call", "device_call", "cpu_time",
                 "max_cpu_time", "min_cpu_time", "device_time",
                 "max_device_time", "min_device_time")

    def __init__(self, name):
        self.name = name
        self.cpu_call = 0
        self.device_call = 0
        self.cpu_time = 0.0
        self.max_cpu_time = 0.0
        self.min_cpu_time = float("inf")
        self.device_time = 0.0
        self.max_device_time = 0.0
        self.min_device_time = float("inf")

    def add(self, dur_ms, device: bool):
        # per-kind call counts: one name can hold BOTH host spans
        # (trace-time dispatches) and sync-timed device spans; a shared
        # denominator would understate both averages
        if device:
            self.device_call += 1
            self.device_time += dur_ms
            self.max_device_time = max(self.max_device_time, dur_ms)
            self.min_device_time = min(self.min_device_time, dur_ms)
        else:
            self.cpu_call += 1
            self.cpu_time += dur_ms
            self.max_cpu_time = max(self.max_cpu_time, dur_ms)
            self.min_cpu_time = min(self.min_cpu_time, dur_ms)

    @property
    def call(self):
        return self.cpu_call + self.device_call

    @property
    def avg_cpu_time(self):
        return self.cpu_time / max(1, self.cpu_call)

    @property
    def avg_device_time(self):
        return self.device_time / max(1, self.device_call)

    def _key(self, sorted_by: SortedKeys):
        return {
            SortedKeys.CPUTotal: self.cpu_time,
            SortedKeys.CPUAvg: self.avg_cpu_time,
            SortedKeys.CPUMax: self.max_cpu_time,
            SortedKeys.CPUMin: -(self.min_cpu_time
                                 if self.min_cpu_time != float("inf")
                                 else 0.0),
            SortedKeys.DeviceTotal: self.device_time,
            SortedKeys.DeviceAvg: self.avg_device_time,
            SortedKeys.DeviceMax: self.max_device_time,
            SortedKeys.DeviceMin: -(self.min_device_time
                                    if self.min_device_time != float("inf")
                                    else 0.0),
        }[sorted_by]


class StatisticData:
    """Span list -> per-category aggregation. Categories follow the
    span's chrome-trace 'cat': 'op' / 'device' spans feed the operator
    summary (device=True for sync-timed 'device' spans), everything
    else lands in the user/host summary (RecordEvent annotations)."""

    def __init__(self, events: List[dict], step_times=None):
        self.op_items: Dict[str, EventItem] = {}
        self.user_items: Dict[str, EventItem] = {}
        self.step_times = list(step_times or [])
        for e in events:
            cat = e.get("cat", "host")
            dur_ms = e.get("dur", 0) / 1000.0
            table = (self.op_items if cat in ("op", "device")
                     else self.user_items)
            table.setdefault(e["name"], EventItem(e["name"])).add(
                dur_ms, device=(cat == "device"))

    def sorted_ops(self, sorted_by: SortedKeys = SortedKeys.CPUTotal):
        return sorted(self.op_items.values(),
                      key=lambda it: -it._key(sorted_by))

    def sorted_user(self, sorted_by: SortedKeys = SortedKeys.CPUTotal):
        return sorted(self.user_items.values(),
                      key=lambda it: -it._key(sorted_by))


def _fmt(ms, unit_div, inf_ok=False):
    if ms == float("inf"):
        return "-" if inf_ok else "0.000"
    return f"{ms / unit_div:.3f}"


def build_table(data: StatisticData,
                sorted_by: SortedKeys = SortedKeys.CPUTotal,
                op_detail: bool = True, time_unit: str = "ms",
                row_limit: int = 30) -> str:
    """Render the aligned summary table (gen_layer_summary /
    _build_table analog)."""
    unit_div = {"s": 1000.0, "ms": 1.0, "us": 1e-3}.get(time_unit, 1.0)
    lines = []
    if data.step_times:
        import numpy as np

        st = np.asarray(data.step_times[1:] or data.step_times) * 1e3
        lines.append(
            f"steps={len(data.step_times)} "
            f"mean={_fmt(st.mean(), unit_div)}{time_unit} "
            f"p50={_fmt(float(np.percentile(st, 50)), unit_div)}{time_unit} "
            f"p99={_fmt(float(np.percentile(st, 99)), unit_div)}{time_unit}")

    def section(title, items):
        if not items:
            return
        w = max(12, min(44, max(len(i.name) for i in items) + 2))
        hdr = (f"{'Name':<{w}} {'Calls':>9} "
               f"{'CPU Total':>11} {'CPU Avg':>9} {'CPU Max':>9} "
               f"{'Dev Total':>11} {'Dev Avg':>9}")
        lines.append("-" * len(hdr))
        lines.append(f"[{title}]  (times in {time_unit}, "
                     f"sorted by {sorted_by.name}; mixed-kind rows "
                     "show Calls as cpu/dev — each Avg divides by ITS "
                     "kind's count)")
        lines.append(hdr)
        for it in items[:row_limit]:
            calls = (f"{it.cpu_call}/{it.device_call}"
                     if it.cpu_call and it.device_call
                     else str(it.call))
            lines.append(
                f"{it.name[:w]:<{w}} {calls:>9} "
                f"{_fmt(it.cpu_time, unit_div):>11} "
                f"{_fmt(it.avg_cpu_time, unit_div):>9} "
                f"{_fmt(it.max_cpu_time, unit_div):>9} "
                f"{_fmt(it.device_time, unit_div):>11} "
                f"{_fmt(it.avg_device_time, unit_div):>9}")

    section("UserDefined / host spans", data.sorted_user(sorted_by))
    if op_detail:
        section("Operator summary", data.sorted_ops(sorted_by))
    return "\n".join(lines) if lines else "(no profiler events)"
