from __future__ import annotations

import contextlib
import enum
import itertools
import json
import os
import threading
import time
from typing import Callable, Iterable, List, Optional


class ProfilerState(enum.Enum):
    CLOSED = 0
    READY = 1
    RECORD = 2
    RECORD_AND_RETURN = 3


class ProfilerTarget(enum.Enum):
    CPU = 0
    TPU = 1


class _HostEventRecorder:
    """Ring-buffer host span recorder (host_event_recorder.h analog)."""

    def __init__(self):
        self.events: List[dict] = []
        self._lock = threading.Lock()
        self.enabled = False

    def record(self, name, start_us, end_us, tid, cat="host"):
        if not self.enabled:
            return
        with self._lock:
            self.events.append(
                {"name": name, "ph": "X", "ts": start_us, "dur": end_us - start_us,
                 "pid": os.getpid(), "tid": tid, "cat": cat})

    def drain(self):
        with self._lock:
            out = self.events
            self.events = []
        return out

    def peek(self):
        """Non-destructive copy of the buffered spans — the tracing
        timeline merge (`observability.tracing.export_timeline`) reads
        the stream without stealing it from a recording Profiler."""
        with self._lock:
            return [dict(e) for e in self.events]


_recorder = _HostEventRecorder()


class RecordEvent:
    """Analog of paddle.profiler.RecordEvent (event_tracing.h RecordEvent)."""

    def __init__(self, name: str, event_type=None):
        self.name = name
        self._start = None

    def begin(self):
        self._start = time.perf_counter_ns() // 1000

    def end(self):
        if self._start is not None:
            _recorder.record(self.name, self._start,
                             time.perf_counter_ns() // 1000,
                             threading.get_ident() % 100000)
            self._start = None

    def __enter__(self):
        self.begin()
        return self

    def __exit__(self, *exc):
        self.end()
        return False


def make_scheduler(*, closed: int, ready: int, record: int, repeat: int = 0,
                   skip_first: int = 0) -> Callable[[int], ProfilerState]:
    """Analog of paddle.profiler.make_scheduler."""
    cycle = closed + ready + record

    def schedule(step: int) -> ProfilerState:
        if step < skip_first:
            return ProfilerState.CLOSED
        s = step - skip_first
        if repeat and s >= cycle * repeat:
            return ProfilerState.CLOSED
        pos = s % cycle
        if pos < closed:
            return ProfilerState.CLOSED
        if pos < closed + ready:
            return ProfilerState.READY
        if pos == cycle - 1:
            return ProfilerState.RECORD_AND_RETURN
        return ProfilerState.RECORD

    return schedule


# monotonic export sequence: two exports within the same wall-clock
# second (scheduler cycles faster than 1 Hz, tests) must land in two
# files — `{name}_{epoch}.json` alone silently overwrites the first
_export_seq = itertools.count()


def export_chrome_tracing(dir_name: str, worker_name: Optional[str] = None):
    def handler(prof: "Profiler"):
        os.makedirs(dir_name, exist_ok=True)
        name = worker_name or f"worker_{os.getpid()}"
        path = os.path.join(
            dir_name,
            f"{name}_{int(time.time())}_{next(_export_seq):04d}.json")
        prof._export_path = path
        prof.export(path)

    return handler


class Profiler:
    """Analog of paddle.profiler.Profiler (profiler.py:344). Also starts a
    jax.profiler trace (XPlane) when `timer_only=False` and a trace dir is
    set via on_trace_ready=export_chrome_tracing(dir)."""

    def __init__(self, *, targets: Optional[Iterable] = None, scheduler=None,
                 on_trace_ready=None, record_shapes=False, profile_memory=False,
                 timer_only=False, with_flops=False):
        self._scheduler = scheduler
        self._on_trace_ready = on_trace_ready
        self._timer_only = timer_only
        # ProfilerTarget.TPU => sync-timed op spans (each dispatch
        # blocks until outputs are ready, approximating device time —
        # the CUPTI-attribution analog; see profiler_statistic.py)
        self._sync_ops = any(t == ProfilerTarget.TPU
                             for t in (targets or []))
        self.step_num = 0
        self._state = ProfilerState.CLOSED
        self._events: List[dict] = []
        self._jax_trace_dir = None
        self._jax_tracing = False
        self._export_path = None
        self._step_t0 = None
        self._step_times = []
        self._trace_ready_fired = False

    # -- lifecycle ---------------------------------------------------------
    def _set_recording(self, on: bool):
        """Toggle the span sinks together: host RecordEvents and the
        per-op dispatch span hook (device-sync when targets say TPU)."""
        from paddle_tpu.ops.dispatch import OpStats

        _recorder.enabled = on
        if on and not self._timer_only:
            OpStats.span_hook = self._op_span
            OpStats.sync_spans = self._sync_ops
        else:
            OpStats.span_hook = None
            OpStats.sync_spans = False

    def _op_span(self, name, start_us, end_us, synced):
        # op spans feed the operator summary; sync-timed ones carry
        # device attribution (see profiler_statistic.py)
        _recorder.record(name, start_us, end_us,
                         threading.get_ident() % 100000,
                         cat="device" if synced else "op")

    def start(self):
        self._state = (self._scheduler(self.step_num)
                       if self._scheduler else ProfilerState.RECORD)
        self._set_recording(self._state in (
            ProfilerState.RECORD, ProfilerState.RECORD_AND_RETURN))
        self._maybe_start_device_trace()
        self._step_t0 = time.perf_counter()
        return self

    def stop(self):
        self._set_recording(False)
        self._events.extend(_recorder.drain())
        self._maybe_stop_device_trace()
        if self._on_trace_ready and not self._trace_ready_fired:
            self._on_trace_ready(self)
        self._trace_ready_fired = False
        self._state = ProfilerState.CLOSED

    def step(self, num_frames: int = 1):
        now = time.perf_counter()
        if self._step_t0 is not None:
            self._step_times.append(now - self._step_t0)
        self._step_t0 = now
        self.step_num += num_frames
        if self._scheduler:
            new_state = self._scheduler(self.step_num)
            if new_state != self._state:
                if new_state in (ProfilerState.RECORD, ProfilerState.RECORD_AND_RETURN):
                    self._set_recording(True)
                    self._trace_ready_fired = False  # new record window
                elif self._state in (ProfilerState.RECORD, ProfilerState.RECORD_AND_RETURN):
                    self._events.extend(_recorder.drain())
                    self._set_recording(False)
                    if new_state == ProfilerState.CLOSED and self._on_trace_ready:
                        # fired here; stop() must not export a duplicate
                        self._on_trace_ready(self)
                        self._trace_ready_fired = True
                self._state = new_state

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()
        return False

    # -- device trace ------------------------------------------------------
    def _maybe_start_device_trace(self):
        if self._timer_only:
            return
        try:
            import jax

            d = os.environ.get("PADDLE_TPU_TRACE_DIR")
            if d:
                jax.profiler.start_trace(d)
                self._jax_tracing = True
        except Exception:
            pass

    def _maybe_stop_device_trace(self):
        if self._jax_tracing:
            try:
                import jax

                jax.profiler.stop_trace()
            except Exception:
                pass
            self._jax_tracing = False

    # -- export / summary --------------------------------------------------
    def export(self, path: str, format: str = "json"):
        self._events.extend(_recorder.drain())
        with open(path, "w") as f:
            json.dump({"traceEvents": self._events}, f)

    def summary(self, sorted_by=None, op_detail=True, thread_sep=False,
                time_unit="ms"):
        """Aggregated statistics report (profiler_statistic.py analog):
        per-name calls/total/avg/max for host spans and op dispatches,
        with device-time attribution when targets included TPU. Prints
        the table and returns the StatisticData for programmatic use."""
        from .profiler_statistic import (
            SortedKeys, StatisticData, build_table,
        )

        self._events.extend(_recorder.drain())
        data = StatisticData(self._events, self._step_times)
        if sorted_by is None:
            # sync-timed profiles put all op time in the device column;
            # sorting them by (all-zero) CPU totals would scramble the
            # table
            sorted_by = (SortedKeys.DeviceTotal if self._sync_ops
                         else SortedKeys.CPUTotal)
        table = build_table(
            data, sorted_by=sorted_by,
            op_detail=op_detail, time_unit=time_unit)
        print("---- profiler summary ----\n" + table)
        return data
