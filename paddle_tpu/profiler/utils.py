from __future__ import annotations

import enum


class SummaryView(enum.Enum):
    DeviceView = 0
    OverView = 1
    ModelView = 2
    DistributedView = 3
    KernelView = 4
    OperatorView = 5
    MemoryView = 6
    MemoryManipulationView = 7
    UDFView = 8


def benchmark():
    """Analog of paddle.profiler.utils.benchmark timer hooks."""
    return None
