"""Profiler — analog of python/paddle/profiler/ (profiler.py:344).

Host spans (RecordEvent, the analog of platform/profiler/event_tracing.h)
are recorded into a ring buffer and exported as chrome://tracing JSON
(ChromeTracingLogger analog). Device-side timing comes from jax.profiler
(XPlane/TensorBoard) when a trace dir is given — the CUPTI analog on TPU.
"""
from .profiler import (
    Profiler,
    ProfilerState,
    ProfilerTarget,
    RecordEvent,
    export_chrome_tracing,
    make_scheduler,
)
from .profiler_statistic import SortedKeys, StatisticData
from .utils import SummaryView

__all__ = [
    "Profiler", "RecordEvent", "ProfilerState", "ProfilerTarget",
    "make_scheduler", "export_chrome_tracing", "SummaryView",
    "SortedKeys", "StatisticData",
]
