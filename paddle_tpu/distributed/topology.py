"""Hybrid-parallel topology — analog of
python/paddle/distributed/fleet/base/topology.py:53 (CommunicateTopology)
and :139 (HybridCommunicateGroup).

TPU-native re-design: instead of building NCCL communicators per
cartesian slice, the topology materializes ONE `jax.sharding.Mesh` whose
named axes are the parallel dimensions. "Communication groups" become
mesh axis names consumed by PartitionSpec / shard_map; XLA compiles the
collectives onto ICI. The reference's dims ["data","pipe","sharding",
"model"] map to axes ("dp","pp","sharding","mp"), extended with "cp"
(context/sequence parallel — absent in the reference, SURVEY §2.5) and
"ep" (expert parallel).
"""
from __future__ import annotations

import itertools
from typing import Dict, List, Optional, Sequence

import numpy as np

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec


class CommunicateTopology:
    """Cartesian process/device topology (topology.py:53 analog)."""

    def __init__(self, hybrid_group_names: Sequence[str] = ("data", "pipe", "sharding", "model"),
                 dims: Sequence[int] = (1, 1, 1, 1)):
        self._parallel_names = list(hybrid_group_names)
        self._dims = list(int(d) for d in dims)
        self.coordinate = list(itertools.product(*(range(d) for d in self._dims)))
        self._coord2rank = {c: i for i, c in enumerate(self.coordinate)}

    def get_hybrid_group_names(self):
        return self._parallel_names

    def get_dim(self, axis_name):
        return self._dims[self._parallel_names.index(axis_name)]

    get_dim_size = get_dim

    def world_size(self):
        return int(np.prod(self._dims))

    def get_rank(self, **kwargs):
        coord = tuple(kwargs[name] for name in self._parallel_names)
        return self._coord2rank[coord]

    def get_coord(self, rank):
        return self.coordinate[rank]

    def get_axis_list(self, axis_name, index):
        """All ranks whose coordinate on `axis_name` equals index."""
        axis = self._parallel_names.index(axis_name)
        return [r for r, c in enumerate(self.coordinate) if c[axis] == index]

    def get_comm_list(self, axis_name):
        """List of rank-groups along `axis_name` (one group per combination
        of the other axes) — the NCCL-group analog; here used for host-side
        bookkeeping and tests."""
        axis = self._parallel_names.index(axis_name)
        others = [n for i, n in enumerate(self._parallel_names) if i != axis]
        groups = []
        for combo in itertools.product(*(range(self.get_dim(n)) for n in others)):
            group = []
            for k in range(self._dims[axis]):
                kw = dict(zip(others, combo))
                kw[axis_name] = k
                group.append(self.get_rank(**kw))
            groups.append(group)
        return groups


# the canonical axis order for the device mesh (outer -> inner).
# dp outermost (DCN-friendly), mp innermost (needs fastest ICI links).
AXIS_ORDER = ("pp", "dp", "sharding", "ep", "cp", "mp")

_PADDLE2MESH = {"data": "dp", "pipe": "pp", "sharding": "sharding",
                "model": "mp", "expert": "ep", "context": "cp",
                "sep": "cp"}


def serving_mesh(mp, *, num_heads=None, vocab_size=None, devices=None):
    """One-axis `('mp',)` device mesh for tensor-parallel SERVING — the
    inference-only convenience the GenerationEngine builds its
    shard_map-compiled steps over, without requiring a full
    dp/pp/sharding launch.

    Validates the model shapes the Megatron-style inference sharding
    needs UP FRONT (attention sharded by heads, lm_head/embedding by
    vocab rows), so a bad degree fails with a clear ValueError here
    instead of deep inside a per-shard reshape."""
    mp = int(mp)
    if mp < 1:
        raise ValueError(f"mp degree must be >= 1, got {mp}")
    if num_heads is not None and num_heads % mp != 0:
        raise ValueError(
            f"num_heads={num_heads} is not divisible by mp degree "
            f"{mp} — head-sharded attention needs num_heads % mp == 0")
    if vocab_size is not None and vocab_size % mp != 0:
        raise ValueError(
            f"vocab_size={vocab_size} is not divisible by mp degree "
            f"{mp} — the vocab-parallel embedding/lm_head needs "
            "vocab % mp == 0")
    devices = list(devices) if devices is not None else jax.devices()
    if mp > len(devices):
        raise ValueError(
            f"serving mesh needs {mp} devices, have {len(devices)}")
    return Mesh(np.asarray(devices[:mp]), ("mp",))


class HybridCommunicateGroup:
    """Analog of HybridCommunicateGroup (topology.py:139): owns the global
    Mesh and answers rank/degree/group queries per parallel dimension."""

    def __init__(self, topology: CommunicateTopology = None,
                 dp: int = 1, mp: int = 1, pp: int = 1, sharding: int = 1,
                 cp: int = 1, ep: int = 1, devices: Optional[list] = None):
        if topology is not None:
            dims = {_PADDLE2MESH.get(n, n): topology.get_dim(n)
                    for n in topology.get_hybrid_group_names()}
            dp = dims.get("dp", 1)
            mp = dims.get("mp", 1)
            pp = dims.get("pp", 1)
            sharding = dims.get("sharding", 1)
            cp = dims.get("cp", 1)
            ep = dims.get("ep", 1)
        self._degrees = {"pp": pp, "dp": dp, "sharding": sharding,
                         "ep": ep, "cp": cp, "mp": mp}
        devices = devices if devices is not None else jax.devices()
        n_needed = int(np.prod(list(self._degrees.values())))
        if n_needed > len(devices):
            raise ValueError(
                f"topology needs {n_needed} devices, have {len(devices)}")
        devices = devices[:n_needed]
        shape = tuple(self._degrees[a] for a in AXIS_ORDER)
        dev_array = np.asarray(devices).reshape(shape)
        self.mesh = Mesh(dev_array, AXIS_ORDER)
        self.global_rank = jax.process_index()
        self.nranks = n_needed

    @classmethod
    def for_serving(cls, mp_degree, devices=None):
        """Inference-only topology: model parallel over `mp_degree`
        chips, every other axis collapsed — the one-line setup for
        tensor-parallel serving (no dp/pp/sharding launch required)."""
        return cls(mp=int(mp_degree), devices=devices)

    # -- degree / rank queries (reference API surface) ----------------------
    def get_parallel_mode(self):
        """Analog of topology.py get_parallel_mode: decides which wrapper
        distributed_model applies (model.py:126-160)."""
        if self._degrees["pp"] > 1:
            return "pipeline"
        if self._degrees["sharding"] > 1:
            return "sharding"
        if self._degrees["mp"] > 1:
            return "tensor"
        return "data"

    def get_data_parallel_world_size(self):
        return self._degrees["dp"]

    def get_model_parallel_world_size(self):
        return self._degrees["mp"]

    def get_pipe_parallel_world_size(self):
        return self._degrees["pp"]

    def get_sharding_parallel_world_size(self):
        return self._degrees["sharding"]

    def get_context_parallel_world_size(self):
        return self._degrees["cp"]

    def get_expert_parallel_world_size(self):
        return self._degrees["ep"]

    def axis_size(self, axis):
        return self._degrees[axis]

    # mesh-native accessors -------------------------------------------------
    def get_mesh(self) -> Mesh:
        return self.mesh

    def submesh(self, *axes) -> Mesh:
        """A mesh over only the given axes (collapses the rest) — used by
        pipeline stages that shard over (dp, mp) within one stage."""
        keep = [a for a in AXIS_ORDER if a in axes]
        sizes = [self._degrees[a] for a in keep]
        devs = np.asarray(self.mesh.devices).reshape(-1)
        return Mesh(devs[: int(np.prod(sizes))].reshape(sizes), keep)

    def sharding_for(self, *spec) -> NamedSharding:
        return NamedSharding(self.mesh, PartitionSpec(*spec))

    def __repr__(self):
        return f"HybridCommunicateGroup({self._degrees})"


_default_hcg: Optional[HybridCommunicateGroup] = None


def set_hybrid_communicate_group(hcg: HybridCommunicateGroup):
    global _default_hcg
    _default_hcg = hcg


def get_hybrid_communicate_group() -> HybridCommunicateGroup:
    global _default_hcg
    if _default_hcg is None:
        # default: pure data parallel over all local devices
        _default_hcg = HybridCommunicateGroup(dp=len(jax.devices()))
    return _default_hcg
