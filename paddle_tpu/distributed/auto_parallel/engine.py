"""Auto-parallel Engine — the high-level semi-automatic SPMD trainer,
analog of python/paddle/distributed/auto_parallel/engine.py:57 (fit
:812, evaluate :982, predict :1092, cost :1698, save/load :1563/:1646).

TPU-native design: the reference's completion (dist-attr propagation
over the graph), partitioner (per-rank program split) and reshard
(send/recv insertion) — ~10k LoC — are all subsumed by XLA SPMD: the
Engine picks a mesh and per-param PartitionSpecs (the "plan"), builds
ONE DistributedTrainStep, and lets the compiler propagate shardings and
insert collectives. The cost model is XLA's own (lowered-module
cost_analysis), not a hand-built estimator; the tuner compares compiled
costs of candidate plans.
"""
from __future__ import annotations

import os
from typing import Optional

import numpy as np

from paddle_tpu.core.tensor import Tensor

from ..spmd import DistributedTrainStep
from ..topology import (
    HybridCommunicateGroup,
    get_hybrid_communicate_group,
    set_hybrid_communicate_group,
)
from .strategy import Strategy

__all__ = ["Engine"]


def _np(x):
    return np.asarray(x._array if isinstance(x, Tensor) else x)


def _to_loader(data, batch_size, shuffle, num_workers=0, drop_last=True):
    from paddle_tpu.io import DataLoader, Dataset, IterableDataset

    if data is None or isinstance(data, DataLoader):
        return data
    if isinstance(data, (Dataset, IterableDataset)):
        return DataLoader(data, batch_size=batch_size, shuffle=shuffle,
                          num_workers=num_workers, drop_last=drop_last)
    return data


def _split_batch(batch):
    if isinstance(batch, (list, tuple)) and len(batch) >= 2:
        *ins, label = batch
        return tuple(ins), label
    return (batch,), None


class Engine:
    """Usage (reference parity, engine.py:57):
        import paddle_tpu.distributed.auto_parallel as auto
        strategy = auto.Strategy(); strategy.sharding.enable = True
        engine = auto.Engine(model, loss, optimizer, metrics, strategy=strategy)
        engine.fit(train_dataset, epochs=2, batch_size=64)
        engine.evaluate(valid_dataset)
        engine.predict(test_dataset)
        engine.cost()         # XLA cost analysis of the planned step
        engine.save/load
    """

    def __init__(self, model=None, loss=None, optimizer=None, metrics=None,
                 cluster=None, strategy: Optional[Strategy] = None):
        self.model = model
        self.loss = loss
        self.optimizer = optimizer
        metrics = metrics or []
        self.metrics = metrics if isinstance(metrics, (list, tuple)) \
            else [metrics]
        self.strategy = strategy or Strategy()
        self._hcg = None
        self._step = None
        self._eval_jit = None
        self._mode = "train"
        self.history = None
        self._prepared_amp = False

    # -- planning ----------------------------------------------------------
    def _ensure_hcg(self) -> HybridCommunicateGroup:
        """The plan's mesh: an explicitly set HybridCommunicateGroup wins
        (semi-automatic mode — the user annotated a topology); otherwise
        derive one from the strategy: sharding.degree over 'sharding',
        remaining devices over 'dp'."""
        if self._hcg is not None:
            return self._hcg
        from .. import topology

        cur = topology._default_hcg
        # an Engine-derived mesh (ours or another Engine's) is NOT a user
        # annotation — each Engine re-plans from its own strategy
        if cur is not None and not getattr(cur, "_engine_derived", False):
            self._hcg = cur
            return self._hcg
        import jax

        ndev = len(jax.devices())
        sh = self.strategy.sharding
        if sh.enable:
            degree = int(sh.degree) or ndev
            if ndev % degree:
                # an explicit degree the mesh cannot realize is an error,
                # not a silent re-plan
                raise ValueError(
                    f"sharding.degree={degree} does not divide the "
                    f"{ndev}-device mesh; pick a divisor of {ndev} or "
                    f"leave degree=0 for automatic")
            self._hcg = HybridCommunicateGroup(dp=ndev // degree,
                                               sharding=degree)
        else:
            self._hcg = HybridCommunicateGroup(dp=ndev)
        self._hcg._engine_derived = True
        set_hybrid_communicate_group(self._hcg)
        return self._hcg

    def _apply_amp(self):
        """strategy.amp: o2 == cast model weights to the AMP dtype
        (bf16-first — the convert_to_mixed_precision analog); o1 relies
        on the dispatch-level autocast lists."""
        amp = self.strategy.amp
        if amp.enable and not self._prepared_amp and \
                str(amp.level).lower() == "o2":
            self.model.to(dtype=amp.dtype)
            self._prepared_amp = True

    def _ensure_step(self) -> DistributedTrainStep:
        if self._step is None:
            hcg = self._ensure_hcg()
            self._apply_amp()
            sh = self.strategy.sharding
            stage = int(sh.stage) if sh.enable else 0
            gm = self.strategy.gradient_merge
            k = int(gm.k_steps) if gm.enable else 1
            self._step = DistributedTrainStep(
                self.model, self.optimizer, self.loss, hcg=hcg,
                sharding_stage=stage, offload=bool(sh.offload),
                accumulate_steps=k, accumulate_avg=bool(gm.avg))
        return self._step

    # -- train/eval/predict loops -----------------------------------------
    def fit(self, train_data=None, valid_data=None, batch_size=1, epochs=1,
            steps_per_epoch=None, log_freq=10, valid_freq=1, verbose=0,
            shuffle=True, num_workers=0, drop_last=True):
        step = self._ensure_step()
        loader = _to_loader(train_data, batch_size, shuffle, num_workers,
                            drop_last)
        history = {"loss": []}
        for epoch in range(epochs):
            self.model.train()
            for m in self.metrics:
                m.reset()
            losses = []
            for i, batch in enumerate(loader):
                if steps_per_epoch is not None and i >= steps_per_epoch:
                    break
                ins, label = _split_batch(batch)
                loss = step(*ins, label=label)
                losses.append(float(loss))
                if verbose and (i % max(log_freq, 1) == 0):
                    print(f"epoch {epoch} step {i}: loss {losses[-1]:.4f}")
            history["loss"].append(float(np.mean(losses)) if losses else None)
            if valid_data is not None and (epoch + 1) % valid_freq == 0:
                logs = self.evaluate(valid_data, batch_size=batch_size,
                                     num_workers=num_workers)
                for k, v in logs.items():
                    history.setdefault(f"eval_{k}", []).append(v)
        self.history = history
        return history

    def _build_eval(self):
        import jax

        network, loss_fn = self.model, self.loss
        params = list(network.parameters())
        buffers = list(network.buffers()) if hasattr(network, "buffers") \
            else []

        def pure_eval(param_arrays, buf_arrays, inputs, label):
            from paddle_tpu.jit.api import bound_state

            state = params + buffers
            arrays = list(param_arrays) + list(buf_arrays)
            with bound_state(zip(state, arrays), state):
                out = network(*[Tensor._wrap(i) for i in inputs])
                loss = None
                if loss_fn is not None and label is not None:
                    loss = loss_fn(out, Tensor._wrap(label))
                unwrap = lambda t: t._array if isinstance(t, Tensor) else t
                return (jax.tree_util.tree_map(
                            unwrap, out,
                            is_leaf=lambda t: isinstance(t, Tensor)),
                        None if loss is None else unwrap(loss))

        return jax.jit(pure_eval), params, buffers

    def _eval_batch(self, ins, label):
        import jax
        from jax.sharding import NamedSharding
        from jax.sharding import PartitionSpec as P

        from ..spmd import _unwrap

        hcg = self._ensure_hcg()
        from paddle_tpu.framework.flags import debug_epoch

        key = (self.model.training, debug_epoch())
        if self._eval_jit is None or self._eval_jit[3] != key:
            self._eval_jit = (*self._build_eval(), key)
        fn, params, buffers, _ = self._eval_jit
        axes = tuple(a for a in ("dp", "sharding")
                     if hcg.axis_size(a) > 1) or None
        nshard = int(np.prod([hcg.axis_size(a) for a in (axes or ())]))

        def put(x):
            a = _unwrap(x)
            a = np.asarray(a) if not hasattr(a, "shape") else a
            # tail batches that don't divide the mesh run replicated
            spec = P(axes) if a.ndim >= 1 and nshard > 1 and \
                a.shape[0] % nshard == 0 else P()
            return jax.device_put(a, NamedSharding(hcg.mesh, spec))

        ins = tuple(put(i) for i in ins)
        label = None if label is None else put(label)
        return fn([p._array for p in params],
                  [b._array for b in buffers], ins, label)

    def evaluate(self, valid_data, batch_size=1, steps=None, log_freq=10,
                 verbose=0, num_workers=0):
        self.model.eval()
        loader = _to_loader(valid_data, batch_size, False, num_workers,
                            drop_last=False)
        for m in self.metrics:
            m.reset()
        losses = []
        for i, batch in enumerate(loader):
            if steps is not None and i >= steps:
                break
            ins, label = _split_batch(batch)
            out, loss = self._eval_batch(ins, label)
            if loss is not None:
                losses.append(float(loss))
            pred = out[0] if isinstance(out, (list, tuple)) else out
            for m in self.metrics:
                if hasattr(m, "compute") and label is not None:
                    m.update(m.compute(Tensor._wrap(_np(pred)),
                                       Tensor._wrap(_np(label))))
                else:
                    m.update(_np(pred), _np(label))
        logs = {"loss": float(np.mean(losses))} if losses else {}
        for m in self.metrics:
            name = m.name() if callable(getattr(m, "name", None)) else m._name
            logs[name] = m.accumulate()
        return logs

    def _forward_arity(self, available):
        """How many positional inputs the model's forward REQUIRES
        (predict's inputs_spec analog). Only no-default positional
        params count — a defaulted trailing param (e.g. mask=None) is
        not an input slot, so a labeled batch never feeds its label
        into it. A *args forward gives no arity signal; fall back to
        the label-split convention (drop the last field of a >=2-field
        batch)."""
        import inspect

        try:
            sig = inspect.signature(self.model.forward)
        except (TypeError, ValueError):
            return max(available - 1, 1) if available >= 2 else available
        n = 0
        for p in sig.parameters.values():
            if p.kind == inspect.Parameter.VAR_POSITIONAL:
                return max(available - 1, 1) if available >= 2 \
                    else available
            if p.kind in (inspect.Parameter.POSITIONAL_ONLY,
                          inspect.Parameter.POSITIONAL_OR_KEYWORD) and \
                    p.default is inspect.Parameter.empty:
                n += 1
        return min(n, available)

    def predict(self, test_data, batch_size=1, steps=None, verbose=0,
                num_workers=0):
        self.model.eval()
        loader = _to_loader(test_data, batch_size, False, num_workers,
                            drop_last=False)
        outs = []
        for i, batch in enumerate(loader):
            if steps is not None and i >= steps:
                break
            # feed as many batch fields as the model's forward accepts
            # (reference Engine splits on inputs_spec; the arity of
            # forward is our spec) — an unlabeled multi-input dataset
            # keeps its last input, a labeled dataset drops the label
            ins = tuple(batch) if isinstance(batch, (list, tuple)) \
                else (batch,)
            ins = ins[:self._forward_arity(len(ins))]
            out, _ = self._eval_batch(ins, None)
            pred = out[0] if isinstance(out, (list, tuple)) else out
            outs.append(np.asarray(pred))
        return np.concatenate(outs, axis=0) if outs else np.empty((0,))

    def dataloader(self, dataset, batch_size=1, shuffle=False,
                   num_workers=0, drop_last=True, mode=None):
        return _to_loader(dataset, batch_size, shuffle, num_workers,
                          drop_last)

    # -- cost model / tuner ------------------------------------------------
    def cost(self, inputs=None, labels=None, mode=None):
        """Compile the planned step and return XLA's cost analysis — the
        reference's auto_parallel/cost_model.py role, answered by the
        real compiler instead of an estimator. `inputs`/`labels` are
        example batches (arrays or Tensors)."""
        if inputs is None:
            raise ValueError("cost() needs an example batch: "
                             "engine.cost(inputs, labels)")
        step = self._ensure_step()
        ins = inputs if isinstance(inputs, (list, tuple)) else (inputs,)
        lowered = step.lower(*ins, label=labels)
        compiled = lowered.compile()
        out = {"flops": None, "bytes_accessed": None,
               "peak_memory_bytes": None}
        try:
            ca = compiled.cost_analysis()
            ca = ca[0] if isinstance(ca, (list, tuple)) else ca
            if ca:
                out["flops"] = ca.get("flops")
                out["bytes_accessed"] = ca.get("bytes accessed")
        except Exception:
            pass
        try:
            ma = compiled.memory_analysis()
            if ma is not None:
                out["peak_memory_bytes"] = (
                    ma.temp_size_in_bytes + ma.argument_size_in_bytes +
                    ma.output_size_in_bytes)
        except Exception:
            pass
        return out

    def tune(self, inputs, labels=None, candidates=(0, 2, 3)):
        """Minimal optimization tuner (reference _optimization_tuning
        :639): compile each candidate sharding stage, pick the lowest
        peak memory (ties -> lower stage). Returns the chosen stage and
        per-candidate costs."""
        from .. import topology

        def replan(stage):
            """A fresh plan per candidate: drop the cached step AND the
            engine-derived mesh so the sharding axis actually changes."""
            self._step = None
            if self._hcg is not None and \
                    getattr(self._hcg, "_engine_derived", False):
                if topology._default_hcg is self._hcg:
                    topology._default_hcg = None
                self._hcg = None
            self.strategy.sharding.enable = stage > 0
            self.strategy.sharding.stage = max(stage, 1)

        saved = (self.strategy.sharding.enable, self.strategy.sharding.stage)
        results = {}
        best, best_key = None, None
        for stage in candidates:
            replan(stage)
            try:
                c = self.cost(inputs, labels)
            except Exception as e:  # a plan that fails to compile loses
                results[stage] = {"error": str(e)[:200]}
                continue
            results[stage] = c
            key = (c["peak_memory_bytes"] if c["peak_memory_bytes"]
                   is not None else float("inf"), stage)
            if best_key is None or key < best_key:
                best_key, best = key, stage
        if best is not None:
            replan(best)
        else:  # every candidate failed: restore the user's strategy
            self._step = None
            self.strategy.sharding.enable, self.strategy.sharding.stage = saved
        if self.strategy.tuning.verbose:
            for s, c in results.items():
                print(f"tune stage={s}: {c}")
        return best, results

    # -- persistence -------------------------------------------------------
    def save(self, path, training=True):
        import paddle_tpu

        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)
        paddle_tpu.save(self.model.state_dict(), path + ".pdparams")
        if training and self.optimizer is not None:
            paddle_tpu.save(self.optimizer.state_dict(), path + ".pdopt")

    def load(self, path, strict=True, load_optimizer=True):
        import paddle_tpu

        self.model.set_state_dict(paddle_tpu.load(path + ".pdparams"))
        opt_path = path + ".pdopt"
        if load_optimizer and self.optimizer is not None \
                and os.path.exists(opt_path):
            self.optimizer.set_state_dict(paddle_tpu.load(opt_path))
        # params changed out from under any compiled step
        self._step = None
        self._eval_jit = None

    # -- mode plumbing (reference parity) ----------------------------------
    def to_mode(self, mode):
        assert mode in ("train", "eval", "predict")
        self._mode = mode
        return self

    @property
    def main_program(self):  # static-graph parity: nearest analog
        raise NotImplementedError(
            "no Program IR on the TPU build; the compiled artifact is the "
            "jitted step (DistributedTrainStep) — see engine.cost() for "
            "its XLA analysis")
