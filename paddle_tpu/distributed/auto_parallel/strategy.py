"""Auto-parallel Strategy — config sections mirroring the reference's
python/paddle/distributed/auto_parallel/strategy.py (BaseConfig at :20,
Strategy at :129) and constants.py defaults.

TPU-native: the sections that matter map onto our SPMD step factory
(sharding stage, recompute, amp dtype, gradient merge); the reference's
program-rewrite passes become arguments to DistributedTrainStep.
"""
from __future__ import annotations

import copy


class BaseConfig:
    _defaults: dict = {}

    def __init__(self, config_dict=None):
        for k, v in self._defaults.items():
            setattr(self, k, copy.deepcopy(v))
        if config_dict:
            self.from_dict(config_dict)

    def from_dict(self, config_dict):
        for k, v in dict(config_dict).items():
            if k not in self._defaults:
                raise ValueError(
                    f"unknown {type(self).__name__} field {k!r}; "
                    f"valid: {sorted(self._defaults)}")
            setattr(self, k, v)
        return self

    def to_dict(self):
        return {k: getattr(self, k) for k in self._defaults}

    def get(self, k, d=None):
        return getattr(self, k, d)

    def __repr__(self):
        body = ", ".join(f"{k}={getattr(self, k)!r}" for k in self._defaults)
        return f"{type(self).__name__}({body})"


class RecomputeConfig(BaseConfig):
    _defaults = {"enable": False, "checkpoints": None,
                 "no_recompute_segments": []}


class AMPConfig(BaseConfig):
    # bf16-first: the TPU mixed-precision default; fp16 kept for parity
    _defaults = {"enable": False, "dtype": "bfloat16", "level": "o2",
                 "init_loss_scaling": 32768.0, "use_master_weights": True}


class ShardingConfig(BaseConfig):
    _defaults = {"enable": False, "stage": 1, "degree": 0,
                 "offload": False}


class GradientMergeConfig(BaseConfig):
    _defaults = {"enable": False, "k_steps": 1, "avg": True}


class TuningConfig(BaseConfig):
    _defaults = {"enable": False, "profile_start_step": 1,
                 "profile_end_step": 1, "verbose": True}


class DatasetConfig(BaseConfig):
    _defaults = {"enable": False, "num_shards": 1}


class Strategy(BaseConfig):
    """Usage (reference parity):
        strategy = auto.Strategy()
        strategy.sharding.enable = True
        strategy.sharding.stage = 2
        engine = auto.Engine(model, loss, opt, strategy=strategy)
    """

    _defaults = {"auto_mode": "semi", "seed": None, "split_data": True}
    _sections = {
        "recompute": RecomputeConfig,
        "amp": AMPConfig,
        "sharding": ShardingConfig,
        "gradient_merge": GradientMergeConfig,
        "tuning": TuningConfig,
        "dataset": DatasetConfig,
    }

    def __init__(self, config=None):
        config = dict(config or {})
        section_cfg = {k: config.pop(k) for k in list(config)
                       if k in self._sections}
        super().__init__(config)
        for name, cls in self._sections.items():
            setattr(self, name, cls(section_cfg.get(name)))

    def to_dict(self):
        d = super().to_dict()
        for name in self._sections:
            d[name] = getattr(self, name).to_dict()
        return d
