"""paddle.distributed.auto_parallel — semi-automatic SPMD.

Reference: python/paddle/distributed/auto_parallel/ (35.6k LoC). On TPU
the completion/partitioner/reshard machinery is XLA SPMD; what remains
user-facing is the mesh/annotation API (sharding_api), the Strategy
config, and the Engine trainer.
"""
from ..sharding_api import (
    ProcessMesh,
    get_mesh,
    shard_tensor,
    with_sharding_constraint,
)
from .engine import Engine
from .strategy import Strategy

__all__ = [
    "Engine", "Strategy", "ProcessMesh", "shard_tensor",
    "with_sharding_constraint", "get_mesh",
]
