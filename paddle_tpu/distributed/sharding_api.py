"""Sharding annotation API — the auto-parallel/pjit surface.

Analog of the reference's auto_parallel descriptors
(distributed/auto_parallel/process_mesh.py, dist_tensor.py dims_mapping)
— which SURVEY §2.5 notes map 1:1 onto jax.sharding.Mesh+PartitionSpec.
Here they ARE Mesh+PartitionSpec.
"""
from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from paddle_tpu.core.tensor import Tensor

from .topology import get_hybrid_communicate_group


class ProcessMesh:
    """Analog of paddle.distributed.ProcessMesh (auto_parallel/process_mesh.py);
    thin named wrapper over jax.sharding.Mesh."""

    def __init__(self, mesh=None, dim_names=None, shape=None):
        if isinstance(mesh, Mesh):
            self.jax_mesh = mesh
        else:
            arr = np.asarray(mesh) if mesh is not None else None
            if shape is not None and arr is None:
                n = int(np.prod(shape))
                devs = np.asarray(jax.devices()[:n]).reshape(shape)
            else:
                flat = arr.reshape(-1)
                devs = np.asarray([jax.devices()[i] for i in flat]).reshape(arr.shape)
            dim_names = dim_names or [f"d{i}" for i in range(devs.ndim)]
            self.jax_mesh = Mesh(devs, tuple(dim_names))

    @property
    def shape(self):
        return list(self.jax_mesh.devices.shape)

    @property
    def dim_names(self):
        return list(self.jax_mesh.axis_names)

    def __enter__(self):
        self._ctx = self.jax_mesh
        self._ctx.__enter__()
        return self

    def __exit__(self, *exc):
        self._ctx.__exit__(*exc)


def shard_tensor(x: Tensor, mesh=None, placement=None) -> Tensor:
    """Place a tensor with an explicit sharding. Analog of
    paddle.distributed.shard_tensor (auto_parallel API): dims_mapping ->
    PartitionSpec."""
    m = mesh.jax_mesh if isinstance(mesh, ProcessMesh) else (
        mesh or get_hybrid_communicate_group().mesh)
    spec = placement if isinstance(placement, PartitionSpec) else PartitionSpec(
        *(placement or ()))
    sharded = jax.device_put(x._array, NamedSharding(m, spec))
    out = Tensor._wrap(sharded, stop_gradient=x.stop_gradient)
    return out


def with_sharding_constraint(x: Tensor, *spec) -> Tensor:
    """In-jit sharding hint — analog of auto-parallel's per-tensor
    dims_mapping annotations consumed by completion.py; here XLA SPMD does
    the propagation. No-op in eager (non-traced) execution, mirroring the
    reference's identity behavior at mp_degree=1."""
    from paddle_tpu.ops.dispatch import apply

    if not isinstance(x._array, jax.core.Tracer):
        return x
    mesh = get_hybrid_communicate_group().mesh
    ns = NamedSharding(mesh, PartitionSpec(*spec))
    return apply("sharding_constraint",
                 lambda a: jax.lax.with_sharding_constraint(a, ns), x)


def get_mesh() -> Mesh:
    return get_hybrid_communicate_group().mesh
