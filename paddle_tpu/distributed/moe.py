"""Mixture-of-Experts with expert parallelism — analog of
python/paddle/incubate/distributed/models/moe/moe_layer.py:260 (MoELayer)
with gates (gate/gshard_gate.py, switch_gate.py, naive_gate.py), capacity
limiting (utils.py limit_by_capacity) and the global_scatter/global_gather
all-to-all dispatch ops (operators/collective/global_scatter_op.cu.cc).

TPU-native design: token dispatch is dense one-hot einsum routing into a
[experts, capacity, d] buffer (the GShard/Switch formulation XLA loves —
static shapes, MXU-friendly), and the cross-device exchange over the 'ep'
axis is lax.all_to_all inside the SPMD program instead of NCCL alltoall
kernels. With ep degree 1 everything stays local and the layer is a dense
jax computation.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

import paddle_tpu.nn as nn
from paddle_tpu.core.tensor import Tensor
from paddle_tpu.ops.dispatch import apply

from .topology import get_hybrid_communicate_group


def top2_gating(logits, capacity, second_policy_train="random", key=None):
    """GShard top-2 gating (gate/gshard_gate.py analog): returns
    combine_weights [T, E, C] and dispatch_mask [T, E, C] plus aux loss.
    Pure jax; T=tokens, E=experts, C=capacity."""
    T, E = logits.shape
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)

    g1_idx = jnp.argmax(probs, axis=-1)  # [T]
    g1 = jnp.take_along_axis(probs, g1_idx[:, None], axis=-1)[:, 0]
    probs_wo1 = probs * (1 - jax.nn.one_hot(g1_idx, E))
    g2_idx = jnp.argmax(probs_wo1, axis=-1)
    g2 = jnp.take_along_axis(probs_wo1, g2_idx[:, None], axis=-1)[:, 0]

    # aux load-balance loss (GShard eq.4): mean_prob * fraction_routed
    me = probs.mean(axis=0)
    ce = jax.nn.one_hot(g1_idx, E).mean(axis=0)
    aux_loss = jnp.sum(me * ce) * E

    # position within each expert queue via cumsum over one-hot
    mask1 = jax.nn.one_hot(g1_idx, E)
    pos1 = (jnp.cumsum(mask1, axis=0) - 1) * mask1  # [T,E]
    mask2 = jax.nn.one_hot(g2_idx, E)
    pos2 = (jnp.cumsum(mask2, axis=0) - 1 + mask1.sum(0)[None, :]) * mask2

    keep1 = (pos1 < capacity) & (mask1 > 0)
    keep2 = (pos2 < capacity) & (mask2 > 0)

    loc1 = pos1.sum(axis=-1).astype(jnp.int32)  # slot for primary expert
    loc2 = pos2.sum(axis=-1).astype(jnp.int32)

    denom = jnp.maximum(g1 + g2, 1e-9)
    w1 = g1 / denom
    w2 = g2 / denom

    cap_oh1 = jax.nn.one_hot(loc1, capacity) * keep1.max(-1, keepdims=True)
    cap_oh2 = jax.nn.one_hot(loc2, capacity) * keep2.max(-1, keepdims=True)
    combine = (w1[:, None, None] * mask1[:, :, None] * cap_oh1[:, None, :]
               + w2[:, None, None] * mask2[:, :, None] * cap_oh2[:, None, :])
    dispatch = combine > 0
    return combine.astype(logits.dtype), dispatch, aux_loss


def switch_gating(logits, capacity):
    """Switch-transformer top-1 gating (switch_gate.py analog)."""
    T, E = logits.shape
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
    idx = jnp.argmax(probs, axis=-1)
    gate = jnp.take_along_axis(probs, idx[:, None], axis=-1)[:, 0]
    me = probs.mean(axis=0)
    ce = jax.nn.one_hot(idx, E).mean(axis=0)
    aux_loss = jnp.sum(me * ce) * E
    mask = jax.nn.one_hot(idx, E)
    pos = (jnp.cumsum(mask, axis=0) - 1) * mask
    keep = (pos < capacity) & (mask > 0)
    loc = pos.sum(axis=-1).astype(jnp.int32)
    cap_oh = jax.nn.one_hot(loc, capacity) * keep.max(-1, keepdims=True)
    combine = gate[:, None, None] * mask[:, :, None] * cap_oh[:, None, :]
    return combine.astype(logits.dtype), combine > 0, aux_loss


class ExpertFFN(nn.Layer):
    """One expert MLP; MoELayer stacks E of these into batched weights."""

    def __init__(self, d_model, d_hidden):
        super().__init__()
        self.fc1 = nn.Linear(d_model, d_hidden)
        self.fc2 = nn.Linear(d_hidden, d_model)

    def forward(self, x):
        import paddle_tpu.nn.functional as F

        return self.fc2(F.gelu(self.fc1(x)))


class MoELayer(nn.Layer):
    """Analog of incubate MoELayer (moe_layer.py:260).

    Experts are stored BATCHED: w1 [E, d, h], w2 [E, h, d] — one einsum
    runs all local experts on the MXU. With ep degree 1 the whole layer
    is a dense local computation; with ep > 1 the forward switches to an
    explicit shard_map over the 'ep' mesh axis with lax.all_to_all token
    dispatch and return (_forward_ep — the global_scatter/global_gather
    analog), and the expert weights carry dist_spec P('ep') so the
    surrounding pjit keeps them sharded at rest.
    """

    def __init__(self, d_model, d_hidden, num_experts, gate="gshard",
                 capacity_factor=1.25, ep_group=None, name=None):
        super().__init__()
        from jax.sharding import PartitionSpec as P

        from paddle_tpu.nn import initializer as I

        self.d_model = d_model
        self.num_experts = num_experts
        self.capacity_factor = capacity_factor
        self.gate_type = gate
        self.gate_proj = nn.Linear(d_model, num_experts, bias_attr=False)
        init = I.XavierUniform()
        self.w1 = self.create_parameter([num_experts, d_model, d_hidden],
                                        default_initializer=init)
        self.b1 = self.create_parameter([num_experts, 1, d_hidden], is_bias=True)
        self.w2 = self.create_parameter([num_experts, d_hidden, d_model],
                                        default_initializer=init)
        self.b2 = self.create_parameter([num_experts, 1, d_model], is_bias=True)
        ep = get_hybrid_communicate_group().axis_size("ep")
        if ep > 1:
            if num_experts % ep:
                raise ValueError(
                    f"ep={ep} must divide num_experts={num_experts}")
            for p in (self.w1, self.b1, self.w2, self.b2):
                p.dist_spec = P("ep")
        self.aux_loss = None

    def _gating(self, gt, cap):
        if self.gate_type == "switch":
            return switch_gating(gt, cap)
        return top2_gating(gt, cap)

    def forward(self, x):
        B, S, D = x.shape
        E = self.num_experts
        ep = get_hybrid_communicate_group().axis_size("ep")
        gate_t = self.gate_proj(x)  # [B,S,E] tracked op

        if ep > 1:
            return self._forward_ep(x, gate_t, ep)

        cap = int(self.capacity_factor * B * S / E) or 1

        def fn(xa, ga, w1, b1, w2, b2):
            T = B * S
            xt = xa.reshape(T, D)
            gt = ga.reshape(T, E)
            combine, dispatch, aux = self._gating(gt, cap)
            # dispatch: [T,E,C] one-hot -> expert buffers [E,C,D]
            buf = jnp.einsum("tec,td->ecd", dispatch.astype(xt.dtype), xt)
            h = jnp.einsum("ecd,edh->ech", buf, w1) + b1
            h = jax.nn.gelu(h)
            out = jnp.einsum("ech,ehd->ecd", h, w2) + b2
            # combine back: weighted gather [T,E,C] x [E,C,D] -> [T,D]
            y = jnp.einsum("tec,ecd->td", combine, out)
            return y.reshape(B, S, D), aux

        out, aux = apply("moe", fn, x, gate_t, self.w1, self.b1, self.w2,
                         self.b2)
        self.aux_loss = aux
        return out

    def _forward_ep(self, x, gate_t, ep):
        """Expert-parallel forward: shard_map over 'ep' with explicit
        lax.all_to_all token exchange — the global_scatter/global_gather
        analog (operators/collective/global_scatter_op.cu.cc,
        moe_utils.py). Tokens are sharded over 'ep'; each shard gates its
        local tokens, ships per-expert buffers to the expert owners,
        runs its local experts, and ships results back."""
        from jax.sharding import PartitionSpec as P
        from jax import shard_map

        B, S, D = x.shape
        E = self.num_experts
        if E % ep:
            raise ValueError(
                f"ep={ep} must divide num_experts={E}")
        E_loc = E // ep
        T = B * S
        if T % ep:
            raise ValueError(
                f"ep={ep} must divide token count {T}")
        T_loc = T // ep
        cap = int(self.capacity_factor * T_loc / E) or 1
        mesh = get_hybrid_communicate_group().mesh

        def shard_fn(xt, gt, w1, b1, w2, b2):
            # per-shard: xt [T_loc, D], gt [T_loc, E], w1 [E_loc, D, H]...
            combine, dispatch, aux = self._gating(gt[0], cap)
            buf = jnp.einsum("tec,td->ecd", dispatch.astype(xt.dtype), xt[0])
            # [E, cap, D] -> [ep, E_loc, cap, D]; all_to_all sends slice j
            # to ep-rank j (every expert's tokens to its owner)
            buf = buf.reshape(ep, E_loc, cap, D)
            recv = jax.lax.all_to_all(buf, "ep", split_axis=0, concat_axis=0,
                                      tiled=False)
            # recv[j] = rank j's tokens for MY experts -> [E_loc, ep*cap, D]
            recv = jnp.swapaxes(recv, 0, 1).reshape(E_loc, ep * cap, D)
            h = jnp.einsum("ecd,edh->ech", recv, w1[0]) + b1[0]
            h = jax.nn.gelu(h)
            out = jnp.einsum("ech,ehd->ecd", h, w2[0]) + b2[0]
            # ship results back: [E_loc, ep, cap, D] -> [ep, E_loc, cap, D]
            out = jnp.swapaxes(out.reshape(E_loc, ep, cap, D), 0, 1)
            back = jax.lax.all_to_all(out, "ep", split_axis=0, concat_axis=0,
                                      tiled=False)
            # back = my tokens' outputs from every expert group -> [E,cap,D]
            back = back.reshape(E, cap, D)
            y = jnp.einsum("tec,ecd->td", combine, back)
            aux = jax.lax.pmean(aux, "ep")
            return y[None], aux[None]

        smapped = shard_map(
            shard_fn, mesh=mesh,
            in_specs=(P("ep"), P("ep"), P("ep"), P("ep"), P("ep"), P("ep")),
            out_specs=(P("ep"), P("ep")))

        def fn(xa, ga, w1, b1, w2, b2):
            xt = xa.reshape(ep, T_loc, D)
            gt = ga.reshape(ep, T_loc, E)
            y, aux = smapped(xt, gt, w1.reshape(ep, E_loc, D, -1),
                             b1.reshape(ep, E_loc, 1, -1),
                             w2.reshape(ep, E_loc, -1, D),
                             b2.reshape(ep, E_loc, 1, D))
            return y.reshape(B, S, D), jnp.mean(aux)

        out, aux = apply("moe_ep", fn, x, gate_t, self.w1, self.b1, self.w2,
                         self.b2)
        self.aux_loss = aux
        return out
