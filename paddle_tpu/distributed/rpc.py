"""paddle.distributed.rpc — analog of python/paddle/distributed/rpc/
rpc.py (init_rpc, rpc_sync, rpc_async, shutdown, get_worker_info over a
brpc transport with a master-based WorkerInfo rendezvous).

TPU-native lite: plain TCP + pickle between trusted cluster hosts (the
same trust model as the reference's brpc). Each worker runs a daemon
server thread executing incoming (func, args, kwargs); the master
(rank 0) collects name->endpoint registrations and broadcasts the full
WorkerInfo table. rpc_async returns a concurrent.futures.Future.
"""
from __future__ import annotations

import pickle
import socket
import socketserver
import struct
import threading
import time
from collections import namedtuple
from concurrent.futures import ThreadPoolExecutor

__all__ = ["init_rpc", "shutdown", "rpc_sync", "rpc_async",
           "get_worker_info", "get_all_worker_infos", "WorkerInfo"]

WorkerInfo = namedtuple("WorkerInfo", ["name", "rank", "ip", "port"])

_state = {}


def _send_msg(sock, obj):
    data = pickle.dumps(obj)
    sock.sendall(struct.pack(">Q", len(data)) + data)


def _recv_msg(sock):
    head = b""
    while len(head) < 8:
        chunk = sock.recv(8 - len(head))
        if not chunk:
            raise ConnectionError("peer closed")
        head += chunk
    n = struct.unpack(">Q", head)[0]
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(min(1 << 20, n - len(buf)))
        if not chunk:
            raise ConnectionError("peer closed")
        buf += chunk
    return pickle.loads(buf)


class _Handler(socketserver.BaseRequestHandler):
    def handle(self):
        try:
            kind, payload = _recv_msg(self.request)
        except ConnectionError:
            return
        if kind == "call":
            func, args, kwargs = payload
            try:
                _send_msg(self.request, ("ok", func(*args, **kwargs)))
            except Exception as e:  # ship the failure back to the caller
                _send_msg(self.request, ("err", e))
        elif kind == "register":  # master only
            with _state["reg_lock"]:
                _state["registry"][payload.rank] = payload
                if len(_state["registry"]) == _state["world_size"]:
                    _state["reg_done"].set()
            if not _state["reg_done"].wait(timeout=300):
                _send_msg(self.request, ("err", TimeoutError(
                    f"rpc rendezvous: only {len(_state['registry'])}/"
                    f"{_state['world_size']} workers registered "
                    "within 300s")))
                return
            _send_msg(self.request,
                      ("ok", sorted(_state["registry"].values(),
                                    key=lambda w: w.rank)))


class _Server(socketserver.ThreadingTCPServer):
    allow_reuse_address = True
    daemon_threads = True


def init_rpc(name, rank=None, world_size=None, master_endpoint=None):
    """Start the local RPC server and rendezvous the WorkerInfo table
    through the master (rank 0 doubles as the master, like the
    reference's master_endpoint contract)."""
    import os

    rank = int(os.environ.get("PADDLE_TRAINER_ID", 0)) if rank is None \
        else rank
    world_size = int(os.environ.get("PADDLE_TRAINERS_NUM", 1)) \
        if world_size is None else world_size
    if master_endpoint is None:
        # preferred: the launcher/spawn-probed job-private endpoint
        # (PADDLE_RPC_MASTER) — guaranteed collision-free across
        # concurrent jobs. Fallback: collective master's port + 1 (the
        # PADDLE_MASTER port itself is owned by jax's coordination
        # service) for explicit-master multi-host launches, where the
        # convention must be computable on every host.
        master_endpoint = os.environ.get("PADDLE_RPC_MASTER")
    if master_endpoint is None:
        ip, port = os.environ.get("PADDLE_MASTER",
                                  "127.0.0.1:29339").split(":")
        master_endpoint = f"{ip}:{int(port) + 1}"

    _state.clear()
    _state.update(world_size=world_size, rank=rank, name=name,
                  registry={}, reg_lock=threading.Lock(),
                  reg_done=threading.Event(),
                  pool=ThreadPoolExecutor(max_workers=8))

    m_ip, m_port = master_endpoint.split(":")
    if rank == 0:
        try:
            # master serves on the well-known endpoint
            srv = _Server((m_ip, int(m_port)), _Handler)
        except OSError as e:
            raise OSError(
                f"rpc master endpoint {master_endpoint} is unavailable "
                f"({e}); the default is the collective coordinator port "
                "+ 1 — pass master_endpoint to init_rpc to choose "
                "another") from e
        port = srv.server_address[1]
    else:
        srv = _Server(("0.0.0.0", 0), _Handler)
        port = srv.server_address[1]
    _state["server"] = srv
    threading.Thread(target=srv.serve_forever, daemon=True).start()

    if rank == 0:
        me = WorkerInfo(name, rank, m_ip, port)
        with _state["reg_lock"]:
            _state["registry"][0] = me
            if len(_state["registry"]) == world_size:
                _state["reg_done"].set()
        if not _state["reg_done"].wait(timeout=300):
            raise TimeoutError(
                f"rpc rendezvous: only {len(_state['registry'])}/"
                f"{world_size} workers registered within 300s")
        workers = sorted(_state["registry"].values(), key=lambda w: w.rank)
    else:
        # register with the master; retry while it comes up. The
        # advertised ip is THIS host's address on the route to the
        # master (multi-host peers must be able to dial it back).
        for attempt in range(120):
            try:
                with socket.create_connection((m_ip, int(m_port)),
                                              timeout=310) as s:
                    my_ip = s.getsockname()[0]
                    me = WorkerInfo(name, rank, my_ip, port)
                    _send_msg(s, ("register", me))
                    status, payload = _recv_msg(s)
                if status == "err":
                    raise payload
                workers = payload
                break
            except ConnectionError:
                time.sleep(0.25)
            except OSError:
                time.sleep(0.25)
        else:
            raise TimeoutError(f"rpc master {master_endpoint} unreachable")
    _state["workers"] = {w.name: w for w in workers}
    _state["by_rank"] = {w.rank: w for w in workers}
    _p2p_mailbox_reset()
    return me


def _p2p_mailbox_reset():
    """A fresh rpc world must not see leftover p2p payloads."""
    try:
        from paddle_tpu.distributed.collective import _p2p_reset

        _p2p_reset()
    except Exception:
        pass


def get_worker_info(name=None):
    ws = _state["workers"]
    if name is None:
        return ws[_state["name"]]
    return ws[name]


def get_worker_info_by_rank(rank):
    """O(1) rank lookup (send/recv address peers by rank)."""
    return _state.get("by_rank", {}).get(rank)


def get_all_worker_infos():
    return sorted(_state["workers"].values(), key=lambda w: w.rank)


def _call(to, fn, args, kwargs):
    w = get_worker_info(to)
    with socket.create_connection((w.ip, w.port), timeout=120) as s:
        _send_msg(s, ("call", (fn, args, kwargs)))
        status, payload = _recv_msg(s)
    if status == "err":
        raise payload
    return payload


def rpc_sync(to, fn, args=(), kwargs=None, timeout=None):
    """Run fn(*args, **kwargs) ON worker `to`, return its result."""
    return _call(to, fn, tuple(args), kwargs or {})


def rpc_async(to, fn, args=(), kwargs=None, timeout=None):
    """Like rpc_sync but returns a Future (reference returns a
    FutureWrapper with .wait())."""
    fut = _state["pool"].submit(_call, to, fn, tuple(args), kwargs or {})
    fut.wait = fut.result  # paddle parity: fut.wait()
    return fut


def shutdown():
    srv = _state.get("server")
    if srv is not None:
        srv.shutdown()
        srv.server_close()
    pool = _state.get("pool")
    if pool is not None:
        pool.shutdown(wait=False)
    _state.clear()
    _p2p_mailbox_reset()
