"""paddle.distributed analog — mesh-native parallelism (SURVEY §2.5).

Design: one jax.sharding.Mesh with named axes ('pp','dp','sharding','ep',
'cp','mp') replaces the reference's per-dimension NCCL process groups;
collectives compile into the training step (XLA over ICI/DCN); the
paddle-parity eager API is kept as a thin façade.
"""
from jax.sharding import PartitionSpec

from . import (auto_parallel, fleet, functional, moe, mp_layers, pipeline,
               ps, ring_attention, rpc, sharding)
from .localsgd import LocalSGD
from .spawn import spawn
from .pipeline import (
    LayerDesc,
    PipelineLayer,
    PipelineStack,
    SegmentLayers,
    SharedLayerDesc,
)
from .mp_layers import (
    ColumnParallelLinear,
    ParallelCrossEntropy,
    RowParallelLinear,
    VocabParallelEmbedding,
    get_rng_state_tracker,
)
from .moe import MoELayer
from .recompute import recompute
from .ring_attention import ring_attention, ulysses_attention
from .sharding import (group_sharded_parallel, make_sharded_step,
                       save_group_sharded_model)
from .spmd import DistributedTrainStep
from .collective import (
    Group,
    ReduceOp,
    all_gather,
    all_reduce,
    alltoall,
    barrier,
    broadcast,
    get_group,
    new_group,
    recv,
    irecv,
    isend,
    P2POp,
    batch_isend_irecv,
    reduce,
    reduce_scatter,
    scatter,
    send,
)
from .parallel import (
    ParallelEnv,
    get_rank,
    get_world_size,
    init_parallel_env,
    is_initialized,
)
from .sharding_api import (
    ProcessMesh,
    get_mesh,
    shard_tensor,
    with_sharding_constraint,
)
from .topology import (
    AXIS_ORDER,
    CommunicateTopology,
    HybridCommunicateGroup,
    get_hybrid_communicate_group,
    serving_mesh,
    set_hybrid_communicate_group,
)

__all__ = [
    "init_parallel_env", "get_rank", "get_world_size", "ParallelEnv",
    "all_reduce", "all_gather", "broadcast", "reduce", "scatter", "alltoall",
    "reduce_scatter", "send", "recv", "isend", "irecv", "P2POp", "batch_isend_irecv", "barrier", "new_group", "get_group",
    "ReduceOp", "Group", "functional", "CommunicateTopology",
    "HybridCommunicateGroup", "get_hybrid_communicate_group",
    "set_hybrid_communicate_group", "ProcessMesh", "shard_tensor",
    "with_sharding_constraint", "get_mesh", "PartitionSpec", "AXIS_ORDER",
    "serving_mesh",
]
