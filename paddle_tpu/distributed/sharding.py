"""GroupSharded (ZeRO) user API — analog of
python/paddle/distributed/sharding/group_sharded.py:37
(group_sharded_parallel, stages 1/2/3 + offload) and the stage
implementations meta_parallel/sharding/group_sharded_stage2.py /
group_sharded_optimizer_stage2.py / group_sharded_stage3.py.

TPU-native: the reference implements ZeRO with explicit flat buffers,
grad-ready hooks and reduce-scatter calls. Under SPMD all three stages
are SHARDING DECISIONS on the same compiled step:
  stage 1 — optimizer states sharded over 'sharding' (accum_pspec);
  stage 2 — + gradients effectively sharded (XLA reduce-scatters grads
            feeding sharded opt-state updates instead of all-reducing);
  stage 3 — + parameters sharded, with XLA inserting just-in-time
            all-gathers where full weights are needed.
The API returns the model/optimizer plus a configured
DistributedTrainStep factory so the call-sites match the reference's.
"""
from __future__ import annotations

from .spmd import DistributedTrainStep
from .topology import get_hybrid_communicate_group


def group_sharded_parallel(model, optimizer, level="os_g", scaler=None,
                           group=None, offload=False, sync_buffers=False,
                           buffer_max_size=None, segment_size=None,
                           sync_comm=False):
    """Analog of group_sharded_parallel (group_sharded.py:37).

    level: 'os' (stage1) | 'os_g' (stage2) | 'p_g_os' (stage3) —
    reference naming.
    """
    stage = {"os": 1, "os_g": 2, "p_g_os": 3}[level]
    # consumed by DistributedTrainStep.__init__ (stage/offload default
    # from these attrs), so reference-style callers get the real thing
    model._sharding_stage = stage
    model._sharding_offload = bool(offload)
    model._sharding_scaler = scaler
    if offload:
        import warnings
        warnings.warn(
            "offload takes effect in compiled steps (DistributedTrainStep /"
            " make_sharded_step); a plain eager loss.backward()/opt.step()"
            " loop keeps optimizer state on device", stacklevel=2)
    return model, optimizer, scaler


def save_group_sharded_model(model, output, optimizer=None):
    """Analog of save_group_sharded_model: gathers shards and saves the
    full state dict (device_put to replicated before host transfer)."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec

    import paddle_tpu

    mesh = get_hybrid_communicate_group().mesh
    repl = NamedSharding(mesh, PartitionSpec())
    state = {}
    for k, v in model.state_dict().items():
        arr = v._array
        if hasattr(arr, "sharding"):
            arr = jax.device_put(arr, repl)
        state[k] = type(v)._wrap(arr) if hasattr(type(v), "_wrap") else v
    paddle_tpu.save(state, output if output.endswith(".pdparams")
                    else output + ".pdparams")
    if optimizer is not None:
        paddle_tpu.save(optimizer.state_dict(), output + ".pdopt")


def make_sharded_step(model, optimizer, loss_fn=None, level=None,
                      offload=None):
    """Convenience: the compiled ZeRO step for this model/opt pair.
    level/offload default to whatever group_sharded_parallel recorded on
    the model (explicit arguments win)."""
    stage = None if level is None else {"os": 1, "os_g": 2,
                                        "p_g_os": 3}[level]
    return DistributedTrainStep(model, optimizer, loss_fn,
                                sharding_stage=stage, offload=offload)
