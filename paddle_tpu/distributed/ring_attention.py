"""Context/sequence parallelism: ring attention + Ulysses (all-to-all)
attention over the 'cp' mesh axis.

The reference has NO sequence parallelism (SURVEY §2.5: absent in v2.4 —
it scales long sequences only via recompute + TP/PP memory splitting).
This module supplies the capability TPU-natively:

- ring_attention: K/V blocks rotate around the 'cp' ring via
  lax.ppermute (ICI neighbor exchange) while each device keeps its Q
  shard; softmax is accumulated online (flash-attention style running
  max/denominator), so the full S×S score matrix never materializes.
  Compute/communication overlap is XLA's job (the ppermute for step i+1
  can overlap the matmul of step i).
- ulysses_attention: all-to-all swaps the sequence shard for a head
  shard (seq-parallel -> head-parallel), runs dense local attention,
  and swaps back — cheaper than ring when heads % cp == 0 and sequence
  lengths are moderate.

Both are pure jax functions over raw arrays intended for use inside
shard_map with axis 'cp' (or any named axis passed in).
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax


def _block_attn(q, k, v, scale, mask):
    """One block: returns (unnormalized out, running max, denom).
    q:[B,H,Sq,D] k,v:[B,H,Sk,D] mask:[Sq,Sk] or None."""
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k,
                   preferred_element_type=jnp.float32) * scale
    if mask is not None:
        s = jnp.where(mask, s, -1e30)
    m = jnp.max(s, axis=-1)  # [B,H,Sq]
    p = jnp.exp(s - m[..., None])
    if mask is not None:
        # fully-masked rows would otherwise contribute exp(0)=1 per entry
        p = jnp.where(mask, p, 0.0)
    l = jnp.sum(p, axis=-1)
    o = jnp.einsum("bhqk,bhkd->bhqd", p.astype(v.dtype), v)
    return o, m, l


def ring_attention(q, k, v, axis_name: str = "cp", causal: bool = True,
                   scale=None):
    """Blockwise ring attention inside shard_map.

    Args are LOCAL shards [B, S_local, H, D] (paddle layout); returns the
    local output shard [B, S_local, H, D]. The global sequence is the
    concatenation over the 'cp' axis in axis-index order.
    """
    n = lax.axis_size(axis_name)
    my = lax.axis_index(axis_name)
    B, S, H, D = q.shape
    scale = scale if scale is not None else 1.0 / (D ** 0.5)

    # [B,H,S,D] layout for the MXU
    qh = jnp.swapaxes(q, 1, 2)
    kh = jnp.swapaxes(k, 1, 2)
    vh = jnp.swapaxes(v, 1, 2)

    q_pos = my * S + jnp.arange(S)  # global positions of my queries

    shift = [(i, (i + 1) % n) for i in range(n)]

    def step(i, carry):
        o, m, l, kc, vc = carry
        # kc currently holds the block originally owned by (my - i) mod n
        src = (my - i) % n
        k_pos = src * S + jnp.arange(S)
        if causal:
            mask = q_pos[:, None] >= k_pos[None, :]
        else:
            mask = None
        bo, bm, bl = _block_attn(qh, kc, vc, scale, mask)
        # online softmax merge — accumulator stays fp32 regardless of the
        # input dtype (bf16 inputs would otherwise change the carry type)
        new_m = jnp.maximum(m, bm)
        alpha = jnp.exp(m - new_m)
        beta = jnp.exp(bm - new_m)
        o = o * alpha[..., None] + bo.astype(jnp.float32) * beta[..., None]
        l = l * alpha + bl * beta
        # rotate k/v to the next device; the last iteration's rotation
        # would be unused, so skip the ICI exchange there
        kc, vc = lax.cond(
            i < n - 1,
            lambda ks, vs: (lax.ppermute(ks, axis_name, shift),
                            lax.ppermute(vs, axis_name, shift)),
            lambda ks, vs: (ks, vs),
            kc, vc)
        return o, new_m, l, kc, vc

    # initial carries must be marked varying over the mesh axis for the
    # fori_loop carry types to match (shard_map vma rules)
    def _varying(x):
        try:
            return lax.pcast(x, (axis_name,), to="varying")
        except (AttributeError, TypeError):
            return x

    o0 = _varying(jnp.zeros((B, H, S, D), jnp.float32))
    m0 = _varying(jnp.full((B, H, S), -jnp.inf, jnp.float32))
    l0 = _varying(jnp.zeros((B, H, S), jnp.float32))
    o, m, l, _, _ = lax.fori_loop(0, n, step, (o0, m0, l0, kh, vh))
    out = (o / jnp.maximum(l, 1e-30)[..., None]).astype(q.dtype)
    return jnp.swapaxes(out, 1, 2)  # back to [B,S,H,D]


def ulysses_attention(q, k, v, axis_name: str = "cp", causal: bool = True,
                      scale=None):
    """Ulysses/DeepSpeed-style sequence parallelism: all-to-all the head
    dim against the sequence dim so each device holds ALL positions for
    H/cp heads, then dense local attention, then all-to-all back.
    Local shards [B, S_local, H, D] with H % cp == 0.
    """
    n = lax.axis_size(axis_name)
    B, S, H, D = q.shape
    assert H % n == 0, f"heads {H} not divisible by cp degree {n}"

    def seq2head(x):
        # [B, S, H, D] -> [B, S*n, H/n, D]
        return lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1,
                              tiled=True)

    def head2seq(x):
        return lax.all_to_all(x, axis_name, split_axis=1, concat_axis=2,
                              tiled=True)

    qg, kg, vg = seq2head(q), seq2head(k), seq2head(v)
    s = scale if scale is not None else 1.0 / (D ** 0.5)
    qh = jnp.swapaxes(qg, 1, 2)
    kh = jnp.swapaxes(kg, 1, 2)
    vh = jnp.swapaxes(vg, 1, 2)
    logits = jnp.einsum("bhqd,bhkd->bhqk", qh, kh,
                        preferred_element_type=jnp.float32) * s
    if causal:
        Sg = logits.shape[-1]
        mask = jnp.tril(jnp.ones((Sg, Sg), bool))
        logits = jnp.where(mask, logits, -1e30)
    probs = jax.nn.softmax(logits, axis=-1).astype(vh.dtype)
    out = jnp.einsum("bhqk,bhkd->bhqd", probs, vh)
    out = jnp.swapaxes(out, 1, 2)
    return head2seq(out)
