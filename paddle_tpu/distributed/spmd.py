"""SPMD distributed training step — the TPU-native replacement for the
reference's entire hybrid-parallel execution stack (SURVEY §3.2):
EagerReducer bucketed allreduce (collective/reducer.h:89), sharding
stage-1/2 reduce-scatter hooks (group_sharded_stage2.py), mp allreduces
(mp_ops.py) and HybridParallelOptimizer's fused_allreduce_gradients
(hybrid_parallel_util.py:206) — all of which become sharding annotations
on ONE jitted step; XLA SPMD inserts the (bucketed, overlapped)
collectives on ICI.

Sharding rules:
- batch inputs: sharded over ('dp','sharding') on axis 0 (dp and ZeRO
  sharding both consume the batch axis — ZeRO's grad reduce-scatter
  emerges from XLA partitioning the grad computation);
- params: `Tensor.dist_spec` if set (mp layers set it); else, with
  zero1/2/3 enabled, large params/opt-states shard dim-0 over 'sharding'
  (the GroupSharded stage1/2/3 analog); else replicated;
- optimizer accumulators follow param sharding for stage>=1 (that IS
  ZeRO-1); for stage 3 the params themselves shard (param allgather is
  inserted by XLA where needed).
"""
from __future__ import annotations

from typing import Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding
from jax.sharding import PartitionSpec as P

from paddle_tpu.core.tensor import Tensor
from paddle_tpu.jit import introspect

from .topology import HybridCommunicateGroup, get_hybrid_communicate_group


def _unwrap(x):
    if isinstance(x, Tensor):
        return x._array
    if isinstance(x, (list, tuple)) and x and \
            all(np.isscalar(e) or getattr(e, "ndim", None) == 0 for e in x):
        # a DataLoader label batch collated as a list of scalars —
        # device_put would treat it as a pytree of rank-0 leaves; lists
        # of arrays stay pytrees (structured model inputs)
        return np.asarray(x)
    return x


def param_pspec(param, hcg: HybridCommunicateGroup, sharding_stage: int):
    """Decide the PartitionSpec for one parameter."""
    if param.dist_spec is not None:
        return param.dist_spec
    if sharding_stage >= 3 and hcg.axis_size("sharding") > 1:
        # ZeRO-3: shard params dim0 over 'sharding' when divisible
        if param._array.ndim >= 1 and \
                param._array.shape[0] % hcg.axis_size("sharding") == 0 and \
                param._array.shape[0] >= hcg.axis_size("sharding"):
            return P("sharding")
    return P()


def accum_pspec(param_spec, param, hcg: HybridCommunicateGroup,
                sharding_stage: int):
    """Optimizer-state sharding: ZeRO-1/2 shards opt states even when the
    params stay replicated (dygraph_sharding_optimizer.py analog)."""
    if tuple(param_spec) != ():
        return param_spec
    if sharding_stage >= 1 and hcg.axis_size("sharding") > 1:
        if param._array.ndim >= 1 and \
                param._array.shape[0] % hcg.axis_size("sharding") == 0 and \
                param._array.shape[0] >= hcg.axis_size("sharding"):
            return P("sharding")
    return P()


class DistributedTrainStep:
    """One compiled SPMD training step over the hybrid mesh.

    Usage (the fleet.distributed_model + distributed_optimizer analog):
        hcg = HybridCommunicateGroup(dp=2, mp=2, sharding=2)
        set_hybrid_communicate_group(hcg)
        step = DistributedTrainStep(model, opt, loss_fn, sharding_stage=2)
        loss = step(x, y)   # x,y sharded over dp+sharding batch axes
    """

    def __init__(self, model, optimizer, loss_fn=None,
                 hcg: Optional[HybridCommunicateGroup] = None,
                 sharding_stage: Optional[int] = None,
                 batch_axes=("dp", "sharding"),
                 donate: bool = True, offload: Optional[bool] = None,
                 accumulate_steps: int = 1, accumulate_avg: bool = True):
        self.model = model
        self.optimizer = optimizer
        self.loss_fn = loss_fn
        self.hcg = hcg or get_hybrid_communicate_group()
        # group_sharded_parallel() records its stage/offload on the model;
        # an explicit argument wins, so both entry styles work
        if sharding_stage is None:
            sharding_stage = getattr(model, "_sharding_stage", 0)
        self.sharding_stage = sharding_stage
        if offload is None:
            offload = getattr(model, "_sharding_offload", False)
        self.offload = bool(offload)
        self.batch_axes = tuple(a for a in batch_axes
                                if self.hcg.axis_size(a) > 1) or None
        optimizer._ensure_state()
        # trainable ∩ optimizer-owned params (frozen params stay baked as
        # replicated constants; accumulator slots indexed via _acc_idx)
        opt_index = {id(p): j for j, p in enumerate(optimizer._parameter_list)}
        from paddle_tpu.jit.api import dedup_params, model_buffers
        self._params = dedup_params(
            p for p in model.parameters()
            if not p.stop_gradient and id(p) in opt_index)
        self._acc_idx = [opt_index[id(p)] for p in self._params]
        self._buffers = model_buffers(model)
        self._jitted = None
        self._donate = donate
        self._placed = False
        # gradient merge (GradientMergeOptimizer k_steps analog, mesh
        # edition): K micro-batch calls accumulate into fp32 buffers
        # sharded like the optimizer state (ZeRO stages shard them),
        # the K-th call applies the MEAN
        self.accumulate_steps = int(accumulate_steps)
        self.accumulate_avg = bool(accumulate_avg)
        self._accum_count = 0
        self._grad_bufs = None
        if self.accumulate_steps > 1 and self.offload:
            raise NotImplementedError(
                "accumulate_steps with optimizer-state offload is not "
                "supported")

    # -- sharding plan -----------------------------------------------------
    def _param_shardings(self):
        mesh = self.hcg.mesh
        specs = [param_pspec(p, self.hcg, self.sharding_stage)
                 for p in self._params]
        return specs, [NamedSharding(mesh, s) for s in specs]

    def _buf_shardings(self):
        """Buffers (BN stats, spectral-norm u/v) follow their dist_spec
        when a parallel layer set one, else replicate."""
        mesh = self.hcg.mesh
        return [NamedSharding(mesh, b.dist_spec)
                if getattr(b, "dist_spec", None) is not None
                else NamedSharding(mesh, P()) for b in self._buffers]

    def place_params(self):
        """Device-put params (and later opt state) onto the mesh according
        to the plan — the param-broadcast step of distributed_model
        (tensor_parallel.py:31-40 analog, minus the broadcast: placement
        IS the distribution)."""
        specs, shardings = self._param_shardings()
        for p, ns in zip(self._params, shardings):
            p._array = jax.device_put(p._array, ns)
        for b, ns in zip(self._buffers, self._buf_shardings()):
            b._array = jax.device_put(b._array, ns)
        opt = self.optimizer
        opt._ensure_state()
        rest = self._acc_host_shardings() if self.offload \
            else self._acc_dev_shardings()
        for k, lst in opt._accumulators.items():
            for out_pos, j in enumerate(self._acc_idx):
                lst[j] = jax.device_put(lst[j], rest[out_pos])
        self._placed = True

    def _acc_dev_shardings(self):
        """Per-param accumulator NamedShardings (device memory), cached —
        the offload path rebuilds these on every step otherwise."""
        if getattr(self, "_acc_dev_cache", None) is None:
            specs, _ = self._param_shardings()
            self._acc_dev_cache = [
                NamedSharding(self.hcg.mesh,
                              accum_pspec(specs[i], self._params[i],
                                          self.hcg, self.sharding_stage))
                for i in range(len(self._params))]
        return self._acc_dev_cache

    def _acc_host_shardings(self):
        """Same specs, pinned_host memory kind: offload parks optimizer
        state in host RAM between steps (group_sharded offload analog);
        __call__ stages it to device around the compiled update."""
        if getattr(self, "_acc_host_cache", None) is None:
            self._acc_host_cache = [
                NamedSharding(self.hcg.mesh, ns.spec,
                              memory_kind="pinned_host")
                for ns in self._acc_dev_shardings()]
        return self._acc_host_cache

    def _build(self):
        model = self.model
        opt = self.optimizer
        loss_fn = self.loss_fn
        params = self._params
        hcg = self.hcg
        mesh = hcg.mesh
        from paddle_tpu.jit.api import build_step_fn

        opt._ensure_state()
        accum_names = list(opt._accumulators.keys())
        pspecs, param_shardings = self._param_shardings()
        dev = self._acc_dev_shardings()
        acc_shardings = {k: dev for k in accum_names}
        repl = NamedSharding(mesh, P())

        step_fn = build_step_fn(model, opt, loss_fn, params, self._acc_idx,
                                buffers=self._buffers)

        # input shardings are taken from the committed arrays (params/accums
        # are device_put by place_params, the batch by __call__); pinning
        # out_shardings keeps params/opt-state sharded across steps.
        out_shardings = (
            repl,
            param_shardings,
            {k: acc_shardings[k] for k in accum_names},
            self._buf_shardings(),
        )
        donate = introspect.TRAINSTEP_DONATE_ARGNUMS if self._donate \
            else ()
        return jax.jit(step_fn, donate_argnums=donate,
                       out_shardings=out_shardings)

    def _prep_args(self, inputs, label, advance_rng=True):
        """Place params, (re)build the jitted step, and stage one call's
        argument tuple (shared by __call__ and lower)."""
        if not self._placed:
            self.place_params()
        from paddle_tpu.framework.flags import debug_epoch

        if self._jitted is None or \
                getattr(self, "_flags_epoch", None) != debug_epoch():
            self._jitted = self._build()
            self._flags_epoch = debug_epoch()
        opt = self.optimizer
        mesh = self.hcg.mesh
        bs = NamedSharding(mesh, P(self.batch_axes))
        in_arrays = tuple(
            jax.device_put(_unwrap(i), bs) for i in inputs)
        label_arr = jax.device_put(_unwrap(label), bs) if label is not None else None
        from paddle_tpu.core import random as random_mod
        from paddle_tpu.jit.api import gather_accums

        param_arrays = [p._array for p in self._params]
        accums = gather_accums(opt, self._acc_idx)
        if self.offload:
            # stage host-resident opt state into device memory for the
            # compiled update; the device copies are donated by the jit
            dev = self._acc_dev_shardings()
            accums = {k: [jax.device_put(a, dev[i])
                          for i, a in enumerate(lst)]
                      for k, lst in accums.items()}
        lr = jnp.asarray(opt.get_lr(), jnp.float32)
        stepc = jnp.asarray(opt._step_count, jnp.int32)
        if advance_rng:
            key = random_mod.next_key()
        else:  # lowering only traces — don't perturb the global stream
            # same (typed) key flavor as next_key() so the lowered
            # signature matches the executed one (no duplicate compile)
            key = jax.random.key(0)
        bufs = [b._array for b in self._buffers]
        return (param_arrays, accums, bufs, lr, stepc, in_arrays,
                label_arr, key)

    @staticmethod
    def _split_label(inputs, label):
        """Positional-label convention: step(x, y) == step(x, label=y)."""
        if label is None and len(inputs) >= 2:
            *inputs, label = inputs
        return tuple(inputs), label

    def lower(self, *inputs, label=None):
        """jax .lower() of the compiled step on these inputs — feeds the
        Engine cost model (XLA's own cost analysis replaces the
        reference's hand-built auto_parallel/cost_model.py)."""
        inputs, label = self._split_label(inputs, label)
        # also builds self._jitted
        args = self._prep_args(inputs, label, advance_rng=False)
        return self._jitted.lower(*args)

    # -- gradient merge ----------------------------------------------------
    def _build_accum_fns(self):
        """Mesh edition of gradient merge: the SAME closure pair as
        TrainStep (jit.api.make_accum_fns — nan-check and avg/sum
        semantics can't drift), jitted with mesh shardings. Buffer
        shardings follow accum_pspec, so ZeRO stages reduce-scatter the
        merge buffers instead of replicating them; the dp grad psum is
        inserted by XLA from the batch sharding."""
        from paddle_tpu.jit.api import make_accum_fns

        acc_fn, upd_fn = make_accum_fns(
            self.model, self.optimizer, self.loss_fn, self._params,
            self._acc_idx, self.accumulate_steps,
            avg=self.accumulate_avg)
        mesh = self.hcg.mesh
        repl = NamedSharding(mesh, P())
        buf_sh = self._acc_dev_shardings()
        _, param_sh = self._param_shardings()
        accum_names = list(self.optimizer._accumulators.keys())
        acc_sh = {k: buf_sh for k in accum_names}

        donate = (0, 2) if self._donate else ()
        acc_jit = jax.jit(acc_fn, donate_argnums=donate,
                          out_shardings=(repl, buf_sh,
                                         self._buf_shardings()))
        upd_jit = jax.jit(
            upd_fn,
            donate_argnums=introspect.TRAINSTEP_DONATE_ARGNUMS
            if self._donate else (),
            out_shardings=(param_sh, acc_sh, buf_sh))
        return acc_jit, upd_jit

    def _call_accumulate(self, inputs, label):
        from paddle_tpu.core import random as random_mod
        from paddle_tpu.framework.flags import debug_epoch
        from paddle_tpu.jit.api import gather_accums, scatter_accums

        if not self._placed:
            self.place_params()
        if getattr(self, "_acc_jitted", None) is None or \
                getattr(self, "_acc_epoch", None) != debug_epoch():
            self._acc_jitted, self._upd_jitted = self._build_accum_fns()
            self._acc_epoch = debug_epoch()
        opt = self.optimizer
        mesh = self.hcg.mesh
        bs = NamedSharding(mesh, P(self.batch_axes))
        in_arrays = tuple(jax.device_put(_unwrap(i), bs) for i in inputs)
        label_arr = None if label is None else \
            jax.device_put(_unwrap(label), bs)
        if self._grad_bufs is None:
            sh = self._acc_dev_shardings()
            self._grad_bufs = [
                jax.device_put(jnp.zeros(p._array.shape, jnp.float32),
                               sh[i])
                for i, p in enumerate(self._params)]
        loss, self._grad_bufs, new_model_bufs = self._acc_jitted(
            self._grad_bufs, [p._array for p in self._params],
            [b._array for b in self._buffers],
            in_arrays, label_arr, random_mod.next_key())
        for b, a in zip(self._buffers, new_model_bufs):
            b._array = a
        self._accum_count += 1
        if self._accum_count >= self.accumulate_steps:
            lr = jnp.asarray(opt.get_lr(), jnp.float32)
            stepc = jnp.asarray(opt._step_count, jnp.int32)
            new_params, new_accums, self._grad_bufs = self._upd_jitted(
                [p._array for p in self._params],
                gather_accums(opt, self._acc_idx), self._grad_bufs,
                lr, stepc)
            for p, a in zip(self._params, new_params):
                p._in_place_update(a)
            scatter_accums(opt, self._acc_idx, new_accums)
            opt._step_count += 1
            self._accum_count = 0
        return Tensor._wrap(loss)

    def __call__(self, *inputs, label=None):
        inputs, label = self._split_label(inputs, label)
        if self.accumulate_steps > 1:
            return self._call_accumulate(inputs, label)
        args = self._prep_args(inputs, label)
        from paddle_tpu.jit.api import scatter_accums

        opt = self.optimizer
        loss, new_params, new_accums, new_bufs = self._jitted(*args)
        for p, a in zip(self._params, new_params):
            p._in_place_update(a)
        for b, a in zip(self._buffers, new_bufs):
            b._array = a
        if self.offload:
            host = self._acc_host_shardings()
            new_accums = {
                k: [jax.device_put(a, host[i]) for i, a in enumerate(lst)]
                for k, lst in new_accums.items()}
        scatter_accums(opt, self._acc_idx, new_accums)
        opt._step_count += 1
        return Tensor._wrap(loss)
