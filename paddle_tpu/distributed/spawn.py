"""paddle.distributed.spawn — analog of python/paddle/distributed/
spawn.py: launch `func` in nprocs fresh processes with the collective
env contract set, so `init_parallel_env()` inside func just works.

Uses the multiprocessing 'spawn' start method (fresh interpreters — a
forked jax runtime is unusable), a held probe socket for the coordinator
port (same race-avoidance as the launcher CLI), and re-raises the first
failing rank's traceback in the parent (the reference's
MultiprocessContext.join error surfacing)."""
from __future__ import annotations

import multiprocessing as mp
import os
import socket
import traceback

__all__ = ["spawn", "probe_free_port"]


def probe_free_port(host="127.0.0.1"):
    """Bind an OS-assigned port with SO_REUSEADDR and HOLD the socket
    (caller closes just before the real binder starts, shrinking the
    steal window to microseconds). Returns (socket, "host:port")."""
    s = socket.socket()
    s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
    s.bind((host, 0))
    return s, f"{host}:{s.getsockname()[1]}"


def rank_env_overrides(rank, nprocs, master, backend=None,
                       devices_per_proc=1, nservers=0, server_rank=None,
                       rpc_master=None):
    """The collective env contract for one rank, as an overrides dict
    (value None = unset). SHARED by dist.spawn and the launcher CLI —
    the single definition of PADDLE_*/MASTER_*/backend env.
    server_rank is not None => a PS server process (TRAINING_ROLE=
    PSERVER): servers join the rpc world but never the device
    collective, so they are pinned to the CPU backend.
    rpc_master, when given, is a job-private probed-free endpoint for
    the rpc rendezvous — without it init_rpc falls back to coordinator
    port + 1, which collides when jobs run concurrently."""
    if server_rank is not None:
        env = {
            "TRAINING_ROLE": "PSERVER",
            "PADDLE_PSERVER_ID": str(server_rank),
            "PADDLE_PSERVER_NUM": str(nservers),
            "PADDLE_TRAINERS_NUM": str(nprocs),
            "PADDLE_MASTER": master,
            # a table server must not grab a TPU chip
            "JAX_PLATFORMS": "cpu",
            "PALLAS_AXON_POOL_IPS": None,
        }
        # None UNSETS a stale endpoint inherited from an enclosing job
        # so init_rpc falls back to the explicit-master convention
        env["PADDLE_RPC_MASTER"] = rpc_master or None
        env["MASTER_ADDR"], env["MASTER_PORT"] = master.split(":")
        return env
    env = {
        "TRAINING_ROLE": "TRAINER",
        "PADDLE_TRAINER_ID": str(rank),
        "PADDLE_TRAINERS_NUM": str(nprocs),
        "PADDLE_MASTER": master,
        "PADDLE_RPC_MASTER": rpc_master or None,
    }
    if nservers:
        env["PADDLE_PSERVER_NUM"] = str(nservers)
    env["MASTER_ADDR"], env["MASTER_PORT"] = master.split(":")
    if backend == "cpu":
        env["JAX_PLATFORMS"] = "cpu"
        # a TPU-plugin sitecustomize (if present) must not grab the
        # backend before jax.distributed.initialize runs in the rank
        env["PALLAS_AXON_POOL_IPS"] = None
        flags = os.environ.get("XLA_FLAGS", "")
        flags = " ".join(
            f for f in flags.split()
            if not f.startswith("--xla_force_host_platform_device_count"))
        env["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count="
            + str(devices_per_proc)).strip()
    elif backend == "tpu":
        env["JAX_PLATFORMS"] = "tpu"
    return env


def _worker(func, args, err_q, rank):
    try:
        func(*args)
    except Exception:
        err_q.put((rank, traceback.format_exc()))
        raise


def spawn(func, args=(), nprocs=1, join=True, daemon=False, backend=None,
          devices_per_proc=1, **options):
    """paddle.distributed.spawn parity. func runs in each rank's process
    with PADDLE_TRAINER_ID/PADDLE_TRAINERS_NUM/MASTER_* set."""
    ctx = mp.get_context("spawn")
    err_q = ctx.Queue()

    probe, master = probe_free_port()
    # second probed-free port for the rpc rendezvous: job-private, so
    # concurrent jobs never collide on the old coordinator+1 default
    rpc_probe, rpc_master = probe_free_port()

    procs = []
    for rank in range(nprocs):
        if rank == 0:
            probe.close()  # release just before rank 0 can bind it
            rpc_probe.close()
        # the rank env must be live in the PARENT at start(): the spawn
        # child inherits it at exec, BEFORE any sitecustomize (e.g. a
        # TPU plugin's) imports jax — in-child os.environ writes would
        # come too late to steer backend selection
        overrides = rank_env_overrides(rank, nprocs, master, backend,
                                       devices_per_proc,
                                       rpc_master=rpc_master)
        saved = {k: os.environ.get(k) for k in overrides}
        try:
            for k, v in overrides.items():
                if v is None:
                    os.environ.pop(k, None)
                else:
                    os.environ[k] = v
            p = ctx.Process(target=_worker,
                            args=(func, tuple(args), err_q, rank),
                            daemon=daemon)
            p.start()
        finally:
            for k, v in saved.items():
                if v is None:
                    os.environ.pop(k, None)
                else:
                    os.environ[k] = v
        procs.append(p)

    if not join:
        return procs
    # poll-based watch (launcher watch-loop semantics): first failure
    # terminates the surviving ranks instead of blocking on their join
    import time

    rc = 0
    pending = set(range(nprocs))
    while pending:
        for i in list(pending):
            code = procs[i].exitcode
            if code is not None:
                pending.discard(i)
                if code != 0 and rc == 0:
                    rc = code
                    for j in pending:
                        if procs[j].is_alive():
                            procs[j].terminate()
        if pending:
            time.sleep(0.1)
    if rc:
        detail = ""
        if not err_q.empty():
            rank, tb = err_q.get()
            detail = f"\n--- rank {rank} traceback ---\n{tb}"
        raise RuntimeError(f"spawn: a rank exited with code {rc}{detail}")
    return procs
