"""LocalSGD — analog of the reference's localsgd meta-optimizer
(fleet/meta_optimizers/localsgd_optimizer.py, dygraph edition): each
data-parallel worker takes k local optimizer steps on its own gradients,
then parameters are averaged across the dp group. Communication drops by
k× at the cost of staleness — the DCN-friendly strategy when workers
are linked by slow fabric.

TPU-native placement: within one SPMD program dp gradients are already
globally reduced per step (there is nothing to localize), so LocalSGD
lives at the MULTI-PROCESS tier: local steps run the plain optimizer,
and the periodic sync is one eager cross-process all_reduce per
parameter (collective.py). With one process it degrades to the inner
optimizer exactly.

Adaptive variant (adaptive_localsgd): the sync interval grows as the
loss falls (Lin et al. 2018's step-wise schedule), capped by max_k.
"""
from __future__ import annotations

from typing import Optional

__all__ = ["LocalSGD"]


class LocalSGD:
    """Wrap any optimizer:

        opt = LocalSGD(paddle.optimizer.SGD(...), k_steps=4)
        loss.backward(); opt.step(); opt.clear_grad()

    Every k_steps-th step() triggers the parameter average across the
    dp group."""

    def __init__(self, optimizer, k_steps: int = 1, group=None,
                 adaptive: bool = False, init_k_steps: Optional[int] = None,
                 max_k_steps: int = 16):
        if int(k_steps) < 1:
            raise ValueError(f"k_steps must be >= 1, got {k_steps}")
        self._inner = optimizer
        self.k_steps = int(init_k_steps if adaptive and init_k_steps
                           else k_steps)
        self.group = group
        self.adaptive = bool(adaptive)
        self.max_k_steps = int(max_k_steps)
        self._local = 0
        self._best_loss = None

    # -- delegation (optimizer surface) ------------------------------------
    def __getattr__(self, name):
        return getattr(self._inner, name)

    def clear_grad(self, *a, **kw):
        return self._inner.clear_grad(*a, **kw)

    def minimize(self, loss, startup_program=None, parameters=None,
                 no_grad_set=None):
        """Must route through THIS step() (the inner minimize would
        bypass the k-step sync entirely)."""
        loss.backward()
        self.step(loss)
        self.clear_grad()

    def state_dict(self):
        d = self._inner.state_dict()
        d["localsgd"] = {"k_steps": self.k_steps, "local": self._local}
        return d

    def set_state_dict(self, state):
        meta = dict(state).pop("localsgd", None)
        self._inner.set_state_dict(
            {k: v for k, v in state.items() if k != "localsgd"})
        if meta:
            self.k_steps = int(meta.get("k_steps", self.k_steps))
            self._local = int(meta.get("local", 0))

    # -- the strategy ------------------------------------------------------
    def step(self, loss=None):
        self._inner.step()
        self._local += 1
        if self.adaptive and loss is not None:
            self._adapt(float(loss))
        if self._local >= self.k_steps:
            self.sync_params()
            self._local = 0

    def _adapt(self, loss):
        """Grow the interval when the loss has improved (train is in a
        flat, communication-tolerant regime); shrink it when the loss
        regresses."""
        if self._best_loss is None or loss < self._best_loss:
            self._best_loss = loss if self._best_loss is None else \
                min(self._best_loss, loss)
            self.k_steps = min(self.k_steps * 2, self.max_k_steps)
        else:
            self.k_steps = max(self.k_steps // 2, 1)

    def sync_params(self):
        """Average parameters across the dp group (one eager AVG
        all_reduce per param; no-op with world size 1)."""
        from . import collective as C

        if len(C._member_ranks(self.group)) <= 1:
            return
        for p in self._inner._parameter_list:
            C.all_reduce(p, op=C.ReduceOp.AVG, group=self.group)
