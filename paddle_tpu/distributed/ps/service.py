"""PS service tier: standalone table-server processes + the trainer-side
communicator.

Reference analogs:
- server: paddle/fluid/distributed/ps/service/brpc_ps_server.h (table
  RPC service), python/paddle/distributed/ps/the_one_ps.py
  (init_server/run_server lifecycle);
- client: brpc_ps_client pull_sparse/push_sparse;
- communicator: python/paddle/distributed/communicator.py — the
  sync / a_sync (async) / geo push modes of fleet's PS training.

TPU-native shape: the transport is paddle_tpu.distributed.rpc (TCP +
pickle between trusted hosts — the same trust model as brpc). Trainers
and servers form ONE rpc world: trainer ranks [0, T) named
"trainer:<i>", server ranks [T, T+S) named "server:<j>". A server
process hosts one hash-slice (id % S == j) of every named table in its
RAM and applies accessor updates on push; it never touches a TPU.
Launch with `python -m paddle_tpu.distributed.launch --nprocs T
--servers S train.py` — server processes get TRAINING_ROLE=PSERVER and
should call `run_server()`.

Modes (Communicator):
- sync: push RPCs complete before the step returns (the default
  sync-PS semantics — every trainer's pull sees all prior pushes).
- async: pushes ride a bounded background queue; pulls proceed without
  waiting (the reference's a_sync=True communicator — bounded
  staleness, higher throughput).
- geo: per-id gradient deltas accumulate locally and ship every
  `k_steps` pushes (GeoCommunicator / geo-sgd).
"""
from __future__ import annotations

import os
import queue
import threading

import numpy as np

from .table import MemorySparseTable, SparseAdagradRule, SparseSGDRule

__all__ = [
    "role", "is_server", "is_worker", "num_servers", "num_trainers",
    "server_index", "trainer_index", "init_ps_rpc", "run_server",
    "stop_servers", "TableClient", "GraphTableClient", "Communicator",
]


# ---------------------------------------------------------------------------
# roles (reference: TRAINING_ROLE env contract of fleet PS mode)
# ---------------------------------------------------------------------------

def role() -> str:
    return os.environ.get("TRAINING_ROLE", "TRAINER").upper()


def is_server() -> bool:
    return role() == "PSERVER"


def is_worker() -> bool:
    return not is_server()


def num_trainers() -> int:
    return int(os.environ.get("PADDLE_TRAINERS_NUM", "1"))


def num_servers() -> int:
    return int(os.environ.get("PADDLE_PSERVER_NUM", "0"))


def trainer_index() -> int:
    return int(os.environ.get("PADDLE_TRAINER_ID", "0"))


def server_index() -> int:
    return int(os.environ.get("PADDLE_PSERVER_ID", "0"))


def init_ps_rpc(master_endpoint=None):
    """Join the trainer+server rpc world under this process's role."""
    from paddle_tpu.distributed import rpc

    world = num_trainers() + num_servers()
    if is_server():
        name = f"server:{server_index()}"
        rank = num_trainers() + server_index()
    else:
        name = f"trainer:{trainer_index()}"
        rank = trainer_index()
    return rpc.init_rpc(name, rank=rank, world_size=world,
                        master_endpoint=master_endpoint)


# ---------------------------------------------------------------------------
# server side
# ---------------------------------------------------------------------------

_TABLES: dict = {}          # name -> MemorySparseTable
_TABLE_LOCKS: dict = {}     # name -> Lock (rpc handler threads race)
_CREATE_LOCK = threading.Lock()
_STOP = threading.Event()
_STOP_CALLERS: set = set()
_STOP_LOCK = threading.Lock()

_RULES = {"sgd": SparseSGDRule, "adagrad": SparseAdagradRule}


_TABLE_SPECS: dict = {}


def _srv_ensure_table(name, dim, rule_kind, rule_kwargs, seed,
                      ssd_max_mem_rows=None):
    """Idempotent table creation (every trainer configures every
    server; first call wins — guarded: concurrent ensure RPCs from two
    trainers must not each create and clobber the other's table). A
    CONFLICTING re-ensure (different dim/rule/seed) fails here, at the
    misconfiguration, not later as a shape error in pull().
    ssd_max_mem_rows enables the disk-spill tier on the server: hot
    rows beyond the budget LRU-evict to the server's local disk
    (ssd_sparse_table.h analog)."""
    spec = (dim, rule_kind, tuple(sorted(rule_kwargs.items())), seed,
            ssd_max_mem_rows)
    with _CREATE_LOCK:
        if name in _TABLES:
            if _TABLE_SPECS[name] != spec:
                raise ValueError(
                    f"table {name!r} already exists with spec "
                    f"{_TABLE_SPECS[name]}, conflicting with {spec}")
            return True
        rule = _RULES[rule_kind](**rule_kwargs)
        _TABLE_LOCKS[name] = threading.Lock()
        if ssd_max_mem_rows:
            from .table import SSDSparseTable

            _TABLES[name] = SSDSparseTable(
                dim, rule=rule, nshards=1, seed=seed, name=name,
                per_id_init=True, max_mem_rows=ssd_max_mem_rows)
        else:
            _TABLES[name] = MemorySparseTable(
                dim, rule=rule, nshards=1, seed=seed, name=name,
                per_id_init=True)
        _TABLE_SPECS[name] = spec
    return True


def _srv_pull(name, ids):
    with _TABLE_LOCKS[name]:
        return _TABLES[name].pull(np.asarray(ids, np.int64))


def _srv_push(name, ids, grads):
    with _TABLE_LOCKS[name]:
        _TABLES[name].push(np.asarray(ids, np.int64),
                           np.asarray(grads, np.float32))
    return True


def _srv_touched(name):
    with _TABLE_LOCKS[name]:
        return _TABLES[name].touched


def _srv_stats(name):
    """Row-placement stats (SSD tier introspection)."""
    with _TABLE_LOCKS[name]:
        t = _TABLES[name]
        return {"touched": t.touched,
                "mem_rows": getattr(t, "mem_rows", t.touched),
                "disk_rows": getattr(t, "disk_rows", 0)}


def _srv_state_dict(name):
    with _TABLE_LOCKS[name]:
        return _TABLES[name].state_dict()


def _srv_set_state_dict(name, state):
    with _TABLE_LOCKS[name]:
        _TABLES[name].set_state_dict(state)
    return True


def _srv_stop(caller):
    """A server exits once EVERY trainer has said stop (a crashed pod
    is torn down by the launcher instead)."""
    with _STOP_LOCK:
        _STOP_CALLERS.add(caller)
        if len(_STOP_CALLERS) >= num_trainers():
            _STOP.set()
    return True


def run_server(master_endpoint=None):
    """Server-process main: join the rpc world, serve table RPCs until
    all trainers call stop_servers(). (the_one_ps run_server analog —
    the serving itself is the rpc module's daemon handler threads.)"""
    from paddle_tpu.distributed import rpc

    init_ps_rpc(master_endpoint)
    _STOP.wait()
    rpc.shutdown()


def stop_servers():
    """Trainer-side: tell every server this trainer is done."""
    from paddle_tpu.distributed import rpc

    me = trainer_index()
    for j in range(num_servers()):
        rpc.rpc_sync(f"server:{j}", _srv_stop, args=(me,))


# ---------------------------------------------------------------------------
# trainer side
# ---------------------------------------------------------------------------

def _discover_servers():
    """Sorted server names from the rpc world (shared by TableClient
    and GraphTableClient)."""
    from paddle_tpu.distributed import rpc

    servers = sorted(
        (w.name for w in rpc.get_all_worker_infos()
         if w.name.startswith("server:")),
        key=lambda n: int(n.split(":")[1]))
    if not servers:
        raise RuntimeError(
            "no PS servers in the rpc world — launch with "
            "--servers N and call init_ps_rpc() first")
    return servers


def _rule_spec(rule):
    if rule is None:
        return "adagrad", {}
    if isinstance(rule, SparseSGDRule):
        return "sgd", {"learning_rate": rule.lr}
    if isinstance(rule, SparseAdagradRule):
        return "adagrad", {"learning_rate": rule.lr,
                           "initial_g2sum": rule.g0, "eps": rule.eps}
    raise ValueError(f"unknown accessor rule {type(rule).__name__}; "
                     "sync it to the server with a (kind, kwargs) pair")


class TableClient:
    """Trainer-side handle to a table sharded over the server
    processes (brpc_ps_client pull_sparse/push_sparse analog). Same
    pull/push surface as MemorySparseTable, so DistributedEmbedding
    takes it via its `table=` argument unchanged."""

    def __init__(self, name, dim, rule=None, seed=0, communicator=None,
                 ssd_max_mem_rows=None):
        from paddle_tpu.distributed import rpc

        self.name = name
        self.dim = dim
        self._servers = _discover_servers()
        kind, kwargs = _rule_spec(rule)
        for s in self._servers:
            rpc.rpc_sync(s, _srv_ensure_table,
                         args=(name, dim, kind, kwargs, seed,
                               ssd_max_mem_rows))
        self.communicator = communicator
        if communicator is not None:
            communicator.bind(self)

    def _owner(self, ids):
        return np.asarray(ids) % len(self._servers)

    def pull(self, ids):
        from paddle_tpu.distributed import rpc

        ids = np.asarray(ids, np.int64).ravel()
        owners = self._owner(ids)
        futs = {}
        for j, s in enumerate(self._servers):
            sel = ids[owners == j]
            if len(sel):
                futs[j] = rpc.rpc_async(s, _srv_pull,
                                        args=(self.name, sel))
        out = np.empty((len(ids), self.dim), np.float32)
        for j, f in futs.items():
            out[owners == j] = f.result()
        return out

    def push(self, ids, grads):
        if self.communicator is not None:
            self.communicator.push(ids, grads)
        else:
            self.push_direct(ids, grads)

    def push_direct(self, ids, grads, wait=True):
        from paddle_tpu.distributed import rpc

        ids = np.asarray(ids, np.int64).ravel()
        grads = np.asarray(grads, np.float32).reshape(len(ids), self.dim)
        owners = self._owner(ids)
        futs = []
        for j, s in enumerate(self._servers):
            m = owners == j
            if m.any():
                futs.append(rpc.rpc_async(
                    s, _srv_push, args=(self.name, ids[m], grads[m])))
        if wait:
            for f in futs:
                f.result()
        return futs

    def touched(self):
        from paddle_tpu.distributed import rpc

        return sum(rpc.rpc_sync(s, _srv_touched, args=(self.name,))
                   for s in self._servers)

    def stats(self):
        """Aggregated row-placement stats across servers."""
        from paddle_tpu.distributed import rpc

        out = {"touched": 0, "mem_rows": 0, "disk_rows": 0}
        for s in self._servers:
            st = rpc.rpc_sync(s, _srv_stats, args=(self.name,))
            for k in out:
                out[k] += st[k]
        return out

    def state_dict(self):
        from paddle_tpu.distributed import rpc

        out = {}
        for s in self._servers:
            out.update(rpc.rpc_sync(s, _srv_state_dict,
                                    args=(self.name,)))
        return out

    def set_state_dict(self, state):
        """Restore a checkpoint: rows route to their owning server by
        id (id keys make the checkpoint independent of the server
        count, like MemorySparseTable.set_state_dict)."""
        from paddle_tpu.distributed import rpc

        per_server: dict = {j: {} for j in range(len(self._servers))}
        for key, row_state in state.items():
            per_server[int(key) % len(self._servers)][key] = row_state
        futs = [rpc.rpc_async(s, _srv_set_state_dict,
                              args=(self.name, per_server[j]))
                for j, s in enumerate(self._servers) if per_server[j]]
        for f in futs:
            f.result()


class Communicator:
    """The push-side scheduler (python/paddle/distributed/
    communicator.py analog). mode:
    - "sync": push completes inline;
    - "async": bounded background queue (a_sync communicator) —
      `queue_size` caps staleness; flush() drains;
    - "geo": per-id delta accumulation, shipped every `k_steps` pushes
      (GeoCommunicator).

    Transport-agnostic: pushes go through the bound TableClient's
    push_direct, so the merge/queue logic unit-tests without servers.
    """

    def __init__(self, mode="async", k_steps=4, queue_size=64):
        if mode not in ("sync", "async", "geo"):
            raise ValueError(f"mode={mode!r}; expected sync|async|geo")
        self.mode = mode
        self.k_steps = int(k_steps)
        self._client = None
        self._queue: queue.Queue = queue.Queue(maxsize=queue_size)
        self._thread = None
        self._err = None
        self._stopped = False
        self._geo_acc: dict = {}
        self._geo_count = 0
        self._lock = threading.Lock()

    def bind(self, client):
        self._client = client
        self._stopped = False
        if self.mode == "async" and self._thread is None:
            self._thread = threading.Thread(target=self._drain,
                                            daemon=True)
            self._thread.start()

    def _raise_pending(self):
        """Surface a drain-thread error exactly once — a stale _err must
        not poison every later push/flush after the caller handled it.
        The swap happens under the lock so a concurrent drain failure
        can't be clobbered to None."""
        with self._lock:
            err, self._err = self._err, None
        if err is not None:
            raise err

    def _drain(self):
        while True:
            item = self._queue.get()
            try:
                if item is None:
                    return
                ids, grads = item
                self._client.push_direct(ids, grads, wait=True)
            except Exception as e:  # surface on the next push/flush
                with self._lock:
                    self._err = e
            finally:
                self._queue.task_done()

    def push(self, ids, grads):
        if self._stopped:
            raise RuntimeError(
                "Communicator.push after stop(): the communicator is "
                "stopped (in async mode the drain thread is gone and a "
                "push would block forever) — call bind() again or "
                "create a new Communicator")
        self._raise_pending()
        ids = np.asarray(ids, np.int64).ravel()
        grads = np.asarray(grads, np.float32).reshape(
            len(ids), self._client.dim)
        if self.mode == "sync":
            self._client.push_direct(ids, grads, wait=True)
        elif self.mode == "async":
            self._queue.put((ids, grads))  # blocks at queue_size: the
            # staleness bound of a_sync mode
        else:  # geo
            with self._lock:
                for i, g in zip(ids, grads):
                    i = int(i)
                    if i in self._geo_acc:
                        self._geo_acc[i] += g
                    else:
                        self._geo_acc[i] = g.copy()
                self._geo_count += 1
                ship = self._geo_count >= self.k_steps
            if ship:
                self._ship_geo()

    def _ship_geo(self):
        with self._lock:
            acc, self._geo_acc = self._geo_acc, {}
            self._geo_count = 0
        if acc:
            ids = np.fromiter(acc.keys(), np.int64, len(acc))
            grads = np.stack(list(acc.values()))
            self._client.push_direct(ids, grads, wait=True)

    def flush(self):
        """Drain every outstanding push (end of epoch / before eval /
        before checkpoint): queue.join waits for the in-flight push
        too (task_done fires after push_direct returns)."""
        if self.mode == "async":
            self._queue.join()
        elif self.mode == "geo":
            self._ship_geo()
        self._raise_pending()

    def stop(self):
        # flush FIRST in every mode: geo deltas accumulated since the
        # last k-step boundary must ship, thread or no thread. The
        # shutdown itself runs even when flush surfaces a drain error —
        # otherwise the push-after-stop guard never engages on exactly
        # the failure path it exists for.
        try:
            self.flush()
        finally:
            self._stopped = True
            if self._thread is not None:
                self._queue.put(None)
                self._thread.join(timeout=10)
                self._thread = None


# ---------------------------------------------------------------------------
# graph table service (common_graph_table.h served over brpc, here the
# same rpc world as the sparse tables — shard = id % num_servers)
# ---------------------------------------------------------------------------

_GRAPH_TABLES: dict = {}
_GRAPH_LOCKS: dict = {}


def _srv_graph_ensure(name):
    from .graph_table import GraphTable

    with _CREATE_LOCK:
        if name not in _GRAPH_TABLES:
            # each server holds ONE shard; cross-server partitioning is
            # the client's id % num_servers routing
            _GRAPH_TABLES[name] = GraphTable(nshards=1)
            _GRAPH_LOCKS[name] = threading.Lock()
    return True


def _srv_graph_add_edges(name, src, dst, w):
    with _GRAPH_LOCKS[name]:
        # dst registration is the CLIENT's cross-shard routing job
        _GRAPH_TABLES[name].add_edges(src, dst, w, register_dst=False)
    return True


def _srv_graph_add_nodes(name, ids):
    with _GRAPH_LOCKS[name]:
        _GRAPH_TABLES[name].add_graph_node(ids)
    return True


def _srv_graph_set_feat(name, ids, values, fname):
    # (ids, values) ride the client's per-id scatter; fname is extra
    with _GRAPH_LOCKS[name]:
        _GRAPH_TABLES[name].set_node_feat(ids, fname, values)
    return True


def _srv_graph_get_feat(name, ids, fname, width, default):
    with _GRAPH_LOCKS[name]:
        return _GRAPH_TABLES[name].get_node_feat(ids, fname,
                                                 default=default,
                                                 width=width)


def _srv_graph_feat_width(name, fname):
    """This server's registered shape for feature `fname` (None if it
    never stored it) — lets a pure-reader client learn the width."""
    with _GRAPH_LOCKS[name]:
        w = _GRAPH_TABLES[name]._feat_width.get(fname)
        return None if w is None else tuple(w)


def _srv_graph_register_width(name, fname, width):
    """Register `fname`'s shape on THIS server before any rows land —
    called on EVERY server at set time, so two writers fixing
    different widths for the same feature collide loudly at the second
    write instead of poisoning a later read with a broadcast error."""
    with _GRAPH_LOCKS[name]:
        have = _GRAPH_TABLES[name]._feat_width.setdefault(
            fname, tuple(width))
        if tuple(have) != tuple(width):
            raise ValueError(
                f"feature {fname!r} is fixed at shape {tuple(have)} "
                f"on this server; a writer tried {tuple(width)}")
    return True


def _srv_graph_sample_neighbors(name, ids, k, seed, need_weight):
    # fold the server index into the seed: every server replaying the
    # SAME RandomState(seed) would make cross-shard samples perfectly
    # correlated (identical pick-index patterns for equal-degree nodes)
    seed = (int(seed) + 1000003 * server_index()) % (2 ** 31)
    with _GRAPH_LOCKS[name]:
        return _GRAPH_TABLES[name].random_sample_neighbors(
            ids, k, seed=seed, need_weight=need_weight)


def _srv_graph_node_ids(name):
    with _GRAPH_LOCKS[name]:
        return np.asarray(_GRAPH_TABLES[name].node_ids())


def _srv_graph_stats(name):
    with _GRAPH_LOCKS[name]:
        return _GRAPH_TABLES[name].stats()


class GraphTableClient:
    """Trainer-side handle to a graph table sharded over the server
    processes — the GraphTable API re-exposed over rpc with
    id % num_servers routing (the role brpc serving plays for
    common_graph_table.h). The client is the width authority for node
    features, so shards that never stored a feature still return
    correctly shaped defaults."""

    def __init__(self, name):
        from paddle_tpu.distributed import rpc

        self.name = name
        self._servers = _discover_servers()
        self._feat_width: dict = {}
        self._ids_cache = None  # sorted global ids; invalidated on
        #                         THIS client's mutations (another
        #                         trainer's writes need a fresh client
        #                         call after its own mutation, or
        #                         refresh_node_ids())
        for s in self._servers:
            rpc.rpc_sync(s, _srv_graph_ensure, args=(name,))

    def _owner(self, ids):
        return np.asarray(ids, np.int64) % len(self._servers)

    def _scatter(self, fn, ids, *per_id_cols, extra=()):
        """Partition ids (and aligned per-id columns) by owner, rpc
        each server once, return {server_idx: (future, mask)}."""
        from paddle_tpu.distributed import rpc

        ids = np.asarray(ids, np.int64).ravel()
        owners = self._owner(ids)
        futs = {}
        for j, s in enumerate(self._servers):
            mask = owners == j
            if mask.any():
                cols = tuple(np.asarray(c)[mask] for c in per_id_cols)
                futs[j] = (rpc.rpc_async(
                    s, fn, args=(self.name, ids[mask]) + cols + extra),
                    mask)
        return ids, futs

    def add_graph_node(self, ids):
        self._ids_cache = None
        _, futs = self._scatter(_srv_graph_add_nodes, ids)
        for f, _ in futs.values():
            f.result()

    def add_edges(self, src_ids, dst_ids, weights=None):
        """Edges live with their SOURCE node's server (the reference's
        partition — neighbors are sampled where src lives); dst nodes
        register on their own servers."""
        src = np.asarray(src_ids, np.int64).ravel()
        dst = np.asarray(dst_ids, np.int64).ravel()
        if len(src) != len(dst):
            raise ValueError(f"src/dst length mismatch: "
                             f"{len(src)} vs {len(dst)}")
        w = (np.ones(len(src), np.float32) if weights is None
             else np.asarray(weights, np.float32).ravel())
        if len(w) != len(src):
            raise ValueError(f"weights length mismatch: "
                             f"{len(w)} vs {len(src)} edges")
        self._ids_cache = None
        _, futs = self._scatter(_srv_graph_add_edges, src, dst, w)
        for f, _ in futs.values():
            f.result()
        self.add_graph_node(dst)

    def set_node_feat(self, ids, fname, values):
        from paddle_tpu.distributed import rpc

        self._ids_cache = None  # a feature write registers its node
        vals = np.asarray(values)
        if len(vals) != len(np.asarray(ids).ravel()):
            raise ValueError(f"values length mismatch: {len(vals)} vs "
                             f"{len(np.asarray(ids).ravel())} ids")
        want = self._feat_width.setdefault(fname, vals.shape[1:])
        if vals.shape[1:] != want:
            raise ValueError(f"feature {fname!r} is fixed at shape "
                             f"{want}; got {vals.shape[1:]}")
        # width registers on EVERY server first (not just the owners)
        # so concurrent writers with conflicting widths collide here,
        # loudly, instead of at a later read
        for f in [rpc.rpc_async(s, _srv_graph_register_width,
                                args=(self.name, fname, tuple(want)))
                  for s in self._servers]:
            f.result()
        _, futs = self._scatter(_srv_graph_set_feat, ids, vals,
                                extra=(fname,))
        # NOTE extra goes AFTER per-id cols: server signature is
        # (name, ids, values, fname)
        for f, _ in futs.values():
            f.result()

    def _width_of(self, fname):
        """Feature width: locally registered, else learned from the
        servers (a pure-reader client never called set_node_feat).
        One parallel round-trip, not S sequential ones."""
        if fname not in self._feat_width:
            from paddle_tpu.distributed import rpc

            futs = [rpc.rpc_async(s, _srv_graph_feat_width,
                                  args=(self.name, fname))
                    for s in self._servers]
            for f in futs:
                w = f.result()
                if w is not None:
                    self._feat_width.setdefault(fname, tuple(w))
        return self._feat_width.get(fname, (1,))

    def get_node_feat(self, ids, fname, default=0.0):
        width = self._width_of(fname)
        ids, futs = self._scatter(_srv_graph_get_feat, ids,
                                  extra=(fname, width, default))
        out = np.full((len(ids),) + tuple(width), default, np.float32)
        for f, mask in futs.values():
            out[mask] = f.result()
        return out

    def random_sample_neighbors(self, ids, sample_size, seed=0,
                                need_weight=False):
        ids, futs = self._scatter(
            _srv_graph_sample_neighbors, ids,
            extra=(sample_size, seed, need_weight))
        out = np.full((len(ids), sample_size), -1, np.int64)
        wout = np.zeros((len(ids), sample_size), np.float32)
        for f, mask in futs.values():
            r = f.result()
            if need_weight:
                out[mask], wout[mask] = r
            else:
                out[mask] = r
        return (out, wout) if need_weight else out

    def node_ids(self):
        if self._ids_cache is None:
            from paddle_tpu.distributed import rpc

            # parallel fan-out (servers guaranteed non-empty by
            # _discover_servers)
            parts = [f.result() for f in
                     [rpc.rpc_async(s, _srv_graph_node_ids,
                                    args=(self.name,))
                      for s in self._servers]]
            ids = np.sort(np.concatenate(parts))
            ids.setflags(write=False)
            self._ids_cache = ids
        return self._ids_cache

    def refresh_node_ids(self):
        """Drop the cached id list (another trainer mutated the
        graph); the next node_ids() re-fetches from the servers."""
        self._ids_cache = None

    def random_sample_nodes(self, n, seed=0):
        from .graph_table import uniform_sample_ids

        return uniform_sample_ids(self.node_ids(), n, seed)

    def pull_graph_list(self, start, size):
        """Deterministic node-id window over the sorted global id list
        (same contract as GraphTable.pull_graph_list)."""
        return self.node_ids()[start:start + size]

    def stats(self):
        from paddle_tpu.distributed import rpc

        per = [f.result() for f in
               [rpc.rpc_async(s, _srv_graph_stats, args=(self.name,))
                for s in self._servers]]
        return {"nodes": sum(p["nodes"] for p in per),
                "edges": sum(p["edges"] for p in per),
                "nshards": len(self._servers)}
