"""PS-lite: host-RAM sparse embedding tables with pull/push semantics.

TPU-native analog of the reference parameter server
(paddle/fluid/distributed/ps/table/memory_sparse_table.h, SGD rules
paddle/fluid/distributed/ps/table/sparse_sgd_rule.h, python runtime
python/paddle/distributed/ps/the_one_ps.py:1031). The reference shards
a huge id->row hash map across brpc PS server processes; trainers pull
touched rows, compute on GPU, and push sparse gradients back.

Here the "servers" are the TPU hosts themselves: each process owns the
rows whose `id % nshards` hash to it, stored in host RAM (numpy, lazily
materialized like the reference's on-first-touch entries — vocab never
needs to be materialized densely). A training step pulls only the
touched rows to device HBM, runs the dense math on the MXU, and pushes
per-row gradients back to the host table, where the accessor rule
(SGD/Adagrad with per-row state) applies the update. Cross-process
pulls/pushes ride the eager alltoall (collective.py) — the
global_scatter-style id exchange — with count-padding so every process
participates with equal shapes.
"""
from __future__ import annotations

import numpy as np

__all__ = ["SparseSGDRule", "SparseAdagradRule", "MemorySparseTable",
           "SSDSparseTable"]


class SparseSGDRule:
    """Per-row plain SGD (sparse_sgd_rule.h `SparseNaiveSGDRule`)."""

    state_width = 0

    def __init__(self, learning_rate=0.01):
        self.lr = learning_rate

    def init_state(self, dim):
        return np.zeros((0,), np.float32)

    def update(self, row, state, grad):
        row -= self.lr * grad
        return row, state


class SparseAdagradRule:
    """Per-row Adagrad with a scalar accumulator per element
    (sparse_sgd_rule.h `SparseAdaGradSGDRule`)."""

    def __init__(self, learning_rate=0.05, initial_g2sum=0.0, eps=1e-8):
        self.lr = learning_rate
        self.g0 = initial_g2sum
        self.eps = eps

    def init_state(self, dim):
        return np.full((dim,), self.g0, np.float32)

    def update(self, row, state, grad):
        state += grad * grad
        row -= self.lr * grad / (np.sqrt(state) + self.eps)
        return row, state


class _Shard:
    """One hash shard: id -> (row, accessor state), lazily created.

    per_id_init=True derives each row's rng from (seed, id) instead of
    the shard's materialization order — the same id then initializes
    identically under ANY sharding/process/server topology, which is
    what makes sync-vs-async PS runs comparable (and checkpoints
    portable before first touch)."""

    def __init__(self, dim, rule, initializer, seed, per_id_init=False,
                 base_seed=None):
        self.dim = dim
        self.rule = rule
        self.rows: dict[int, np.ndarray] = {}
        self.states: dict[int, np.ndarray] = {}
        self._init = initializer
        # per-id rng derives from the TABLE's base seed, never the
        # shard-varying seed — otherwise the same id would initialize
        # differently under a different nshards/process topology,
        # breaking the portability the mode exists for
        self._base_seed = seed if base_seed is None else base_seed
        self._per_id = per_id_init
        self._rng = np.random.RandomState(seed)

    def _materialize(self, i):
        if i not in self.rows:
            rng = np.random.RandomState(
                (self._base_seed * 1000003 + i) & 0x7FFFFFFF) \
                if self._per_id else self._rng
            self.rows[i] = self._init(rng, self.dim).astype(np.float32)
            self.states[i] = self.rule.init_state(self.dim)
        return self.rows[i]

    def pull(self, ids):
        return np.stack([self._materialize(int(i)) for i in ids]) \
            if len(ids) else np.zeros((0, self.dim), np.float32)

    def push(self, ids, grads):
        for i, g in zip(ids, grads):
            i = int(i)
            self._materialize(i)
            self.rows[i], self.states[i] = self.rule.update(
                self.rows[i], self.states[i], g)


def _default_init(rng, dim):
    bound = 1.0 / np.sqrt(dim)
    return rng.uniform(-bound, bound, size=(dim,))


class MemorySparseTable:
    """Sharded host-RAM sparse table with pull/push.

    Single process: `nshards` local hash shards (parallelism-ready
    layout; pulls concatenate across shards). Multi-process (after
    init_parallel_env): shard p lives on process p — pulls/pushes for
    remote ids ride the eager alltoall, so every host serves its share
    of the vocabulary from its own RAM (the brpc PS server analog).
    """

    def __init__(self, dim, rule=None, nshards=None, initializer=None,
                 seed=0, name="sparse_table", per_id_init=False):
        import jax

        self.dim = dim
        self.rule = rule or SparseAdagradRule()
        self.name = name
        self._nproc = jax.process_count()
        self._rank = jax.process_index()
        if self._nproc > 1:
            nshards = self._nproc
        self.nshards = nshards or 1
        init = initializer or _default_init
        if self._nproc > 1:
            # one local shard: the slice of the hash space this host owns
            self._shards = {self._rank: _Shard(dim, self.rule, init,
                                               seed + self._rank,
                                               per_id_init,
                                               base_seed=seed)}
        else:
            self._shards = {s: _Shard(dim, self.rule, init, seed + s,
                                      per_id_init, base_seed=seed)
                            for s in range(self.nshards)}

    # -- local (single-process) path ------------------------------------
    def _owner(self, ids):
        return np.asarray(ids) % self.nshards

    def pull(self, ids):
        """ids [N] int -> rows [N, dim] float32 (host numpy)."""
        ids = np.asarray(ids, np.int64).ravel()
        if self._nproc > 1:
            return self._pull_remote(ids)
        owners = self._owner(ids)
        out = np.empty((len(ids), self.dim), np.float32)
        for s, shard in self._shards.items():
            m = owners == s
            if m.any():
                out[m] = shard.pull(ids[m])
        return out

    def push(self, ids, grads):
        """Apply per-row gradients (accessor update) to the table."""
        ids = np.asarray(ids, np.int64).ravel()
        grads = np.asarray(grads, np.float32).reshape(len(ids), self.dim)
        if self._nproc > 1:
            self._push_remote(ids, grads)
            return
        owners = self._owner(ids)
        for s, shard in self._shards.items():
            m = owners == s
            if m.any():
                shard.push(ids[m], grads[m])

    # -- cross-process path (global_scatter/global_gather analog) --------
    # 64-bit ids travel as two int32 words (jax runs x64-disabled, so an
    # int64 or float32 round trip would silently truncate ids >= 2^31 /
    # 2^24); a hi-word of -1 marks padding, so no count exchange needed.

    def _exchange_ids(self, ids, owners):
        """One max-size all_reduce + one alltoall: every owner gets the
        ids requested of it (ragged, recovered via the hi>=0 mask)."""
        import paddle_tpu as paddle
        import paddle_tpu.distributed as dist

        counts = [int((owners == p).sum()) for p in range(self._nproc)]
        maxc = paddle.to_tensor(np.array([max(counts)], np.float32))
        dist.all_reduce(maxc, op=dist.ReduceOp.MAX)
        M = max(int(np.asarray(maxc._array)[0]), 1)
        ins = []
        for p in range(self._nproc):
            pad = np.full((M, 2), -1, np.int32)
            sel = ids[owners == p]
            pad[:len(sel), 0] = (sel & 0xFFFFFFFF).astype(np.uint32) \
                                                  .view(np.int32)
            pad[:len(sel), 1] = (sel >> 32).astype(np.int32)
            ins.append(paddle.to_tensor(pad))
        outs = []
        dist.alltoall(ins, outs)
        got = []
        for o in outs:
            w = np.asarray(o._array)
            w = w[w[:, 1] >= 0]
            got.append((w[:, 1].astype(np.int64) << 32)
                       | (w[:, 0].view(np.uint32).astype(np.int64)))
        return got, M, counts

    def _exchange_rows(self, per_peer_rows, M):
        """One float32 alltoall of [M, dim] blocks; the caller knows the
        true per-peer counts, so padding needs no signalling."""
        import paddle_tpu as paddle
        import paddle_tpu.distributed as dist

        ins = []
        for a in per_peer_rows:
            pad = np.zeros((M, a.shape[1]), np.float32)
            pad[:len(a)] = a
            ins.append(paddle.to_tensor(pad))
        outs = []
        dist.alltoall(ins, outs)
        return [np.asarray(o._array) for o in outs]

    def _pull_remote(self, ids):
        owners = np.asarray(ids) % self._nproc
        got_ids, M, sent_counts = self._exchange_ids(ids, owners)
        shard = self._shards[self._rank]
        served = [shard.pull(g) for g in got_ids]
        rows_back = self._exchange_rows(served, M)
        out = np.empty((len(ids), self.dim), np.float32)
        for p in range(self._nproc):
            out[owners == p] = rows_back[p][:sent_counts[p]]
        return out

    def _push_remote(self, ids, grads):
        owners = np.asarray(ids) % self._nproc
        got_ids, M, _ = self._exchange_ids(ids, owners)
        blocks = [grads[owners == p] for p in range(self._nproc)]
        got_grads = self._exchange_rows(blocks, M)
        shard = self._shards[self._rank]
        for gi, gg in zip(got_ids, got_grads):
            if len(gi):
                shard.push(gi, gg[:len(gi)])

    # -- introspection / checkpoint --------------------------------------
    @property
    def touched(self):
        """Materialized row count (local shards)."""
        return sum(len(s.rows) for s in self._shards.values())

    def state_dict(self):
        """Point-in-time copy (rules update rows in place). Keys are the
        ids themselves: shard placement is derivable, so a checkpoint
        reloads under any nshards/process count."""
        return {str(i): (shard.rows[i].copy(), shard.states[i].copy())
                for shard in self._shards.values()
                for i in shard.rows}

    def set_state_dict(self, state):
        for key, (row, st) in state.items():
            i = int(key)
            s = i % self.nshards
            if s not in self._shards:
                continue  # another process owns this id
            shard = self._shards[s]
            shard.rows[i] = np.array(row, np.float32)
            shard.states[i] = np.array(st, np.float32)


class SSDSparseTable(MemorySparseTable):
    """Disk-spilling sparse table — analog of the reference's SSD tier
    (paddle/fluid/distributed/ps/table/ssd_sparse_table.h: hot rows in
    a memory cache, cold rows in RocksDB; the "100-billion-feature"
    README claim rides this). Host-RAM rows beyond `max_mem_rows` are
    LRU-evicted to an on-disk store (sqlite3 — stdlib, one file per
    table, crash-safe enough for a cache tier); a pull of an evicted id
    loads it back and re-heats it. The accessor state spills alongside
    its row, so optimizer semantics are identical to the in-memory
    table at any cache size.
    """

    def __init__(self, dim, rule=None, max_mem_rows=100_000, path=None,
                 **kwargs):
        import sqlite3
        import tempfile
        import threading
        import weakref
        from collections import OrderedDict

        super().__init__(dim, rule=rule, **kwargs)
        self.max_mem_rows = max(int(max_mem_rows), 1)
        self._own_path = path is None
        if path is None:
            f = tempfile.NamedTemporaryFile(
                prefix=f"{self.name}_", suffix=".sqlite", delete=False)
            path = f.name
            f.close()
        self._db_path = path
        # the PS service executes table ops from rpc handler THREADS:
        # share one connection under a lock
        self._db = sqlite3.connect(path, check_same_thread=False)
        self._db_lock = threading.Lock()
        self._db.execute(
            "CREATE TABLE IF NOT EXISTS rows (id INTEGER PRIMARY KEY, "
            "row BLOB, state BLOB)")
        self._lru: "OrderedDict[int, None]" = OrderedDict()
        # weakref finalizer, NOT atexit: a dropped table must be
        # collectable, and a self-made temp file must not linger
        self._finalizer = weakref.finalize(
            self, _close_ssd_store, self._db,
            path if self._own_path else None)
        # wrap each shard's materializer with the spill-aware version
        for shard in self._shards.values():
            shard._materialize = self._spill_materialize(shard)

    def _close(self):
        self._finalizer()

    def _touch(self, i):
        # under _db_lock like every other _lru mutation: callers
        # (materialize, set_state_dict) invoke this AFTER releasing
        # the lock, and relying on the PS service's external per-table
        # lock instead would leave direct in-process users racing
        # _maybe_evict's popitem
        with self._db_lock:
            self._lru.pop(i, None)
            self._lru[i] = None

    def _spill_materialize(self, shard):
        base = type(shard)._materialize

        def materialize(i):
            if i not in shard.rows:
                with self._db_lock:
                    got = self._db.execute(
                        "SELECT row, state FROM rows WHERE id=?",
                        (int(i),)).fetchone()
                    if got is not None:
                        self._db.execute(
                            "DELETE FROM rows WHERE id=?", (int(i),))
                if got is not None:  # cold row: load back from disk
                    shard.rows[i] = np.frombuffer(
                        got[0], np.float32).copy()
                    shard.states[i] = np.frombuffer(
                        got[1], np.float32).copy()
            row = base(shard, i)
            self._touch(i)
            return row

        return materialize

    # one eviction sweep (and at most one fsync) per BATCH, not per row
    def pull(self, ids):
        out = super().pull(ids)
        self._maybe_evict()
        return out

    def push(self, ids, grads):
        super().push(ids, grads)
        self._maybe_evict()

    def set_state_dict(self, state):
        super().set_state_dict(state)
        with self._db_lock:
            # restored rows are authoritative: stale disk copies of the
            # same ids must not shadow them in a later state_dict()
            for key in state:
                self._db.execute("DELETE FROM rows WHERE id=?",
                                 (int(key),))
            self._db.commit()
        for key in state:
            i = int(key)
            for shard in self._shards.values():
                if i in shard.rows:
                    self._touch(i)  # restored rows join the LRU
                    break
        self._maybe_evict()

    def _mem_rows(self):
        return sum(len(s.rows) for s in self._shards.values())

    def _maybe_evict(self):
        wrote = False
        with self._db_lock:
            while self._mem_rows() > self.max_mem_rows and self._lru:
                victim, _ = self._lru.popitem(last=False)  # least recent
                for shard in self._shards.values():
                    if victim in shard.rows:
                        self._db.execute(
                            "INSERT OR REPLACE INTO rows VALUES "
                            "(?, ?, ?)",
                            (int(victim),
                             shard.rows.pop(victim).astype(np.float32)
                             .tobytes(),
                             shard.states.pop(victim).astype(np.float32)
                             .tobytes()))
                        wrote = True
                        break
            if wrote:
                self._db.commit()

    @property
    def touched(self):
        """Total materialized rows: hot (RAM) + spilled (disk)."""
        with self._db_lock:
            n_disk = self._db.execute(
                "SELECT COUNT(*) FROM rows").fetchone()[0]
        return self._mem_rows() + n_disk

    @property
    def mem_rows(self):
        return self._mem_rows()

    @property
    def disk_rows(self):
        with self._db_lock:
            return self._db.execute(
                "SELECT COUNT(*) FROM rows").fetchone()[0]

    def state_dict(self):
        out = super().state_dict()  # the hot rows
        with self._db_lock:
            rows = self._db.execute(
                "SELECT id, row, state FROM rows").fetchall()
        for i, row, st in rows:
            out[str(i)] = (np.frombuffer(row, np.float32).copy(),
                           np.frombuffer(st, np.float32).copy())
        return out


def _close_ssd_store(db, temp_path):
    """Finalizer for SSDSparseTable (module-level: a bound method would
    pin the table alive)."""
    try:
        db.close()
    except Exception:
        pass
    if temp_path is not None:
        import os

        try:
            os.unlink(temp_path)
        except OSError:
            pass
