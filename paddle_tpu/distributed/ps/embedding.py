"""DistributedEmbedding: the trainer-side lookup over a PS table.

Analog of the reference's distributed_lookup path: the PS program
builder replaces `lookup_table` ops with `distributed_lookup` /
`distributed_push_sparse` against the PS service
(python/paddle/distributed/ps/utils/ps_program_builder.py,
the_one_ps.py:1164 _init_worker). Here the pull materializes ONLY the
touched rows on device (dense [U, dim], MXU-friendly), the lookup is a
tracked gather so the tape delivers per-row gradients, and
`push_gradients()` ships them back to the host table where the accessor
rule updates — the wide&deep / DeepFM training loop shape.
"""
from __future__ import annotations

import numpy as np

import paddle_tpu.nn as nn
from paddle_tpu.core.tensor import Tensor
from paddle_tpu.ops import manipulation

from .table import MemorySparseTable

__all__ = ["DistributedEmbedding"]


class DistributedEmbedding(nn.Layer):
    """Embedding whose weight lives in a host-RAM MemorySparseTable
    instead of a device parameter. Use exactly like nn.Embedding in the
    forward; call `push_gradients()` after `loss.backward()` (the
    distributed_push_sparse step). The table IS the optimizer for these
    rows — they never appear in `parameters()`.
    """

    def __init__(self, num_embeddings, embedding_dim, table=None,
                 rule=None, nshards=None, name=None):
        super().__init__()
        self._num_embeddings = num_embeddings  # advisory; table is sparse
        self._embedding_dim = embedding_dim
        self.table = table or MemorySparseTable(
            embedding_dim, rule=rule, nshards=nshards,
            name=name or "embedding_table")
        self._pending = []

    def forward(self, ids):
        ids_np = np.asarray(ids._array if isinstance(ids, Tensor)
                            else ids).astype(np.int64)
        uniq, inv = np.unique(ids_np.ravel(), return_inverse=True)
        pulled = Tensor(self.table.pull(uniq))
        pulled.stop_gradient = False  # leaf: backward accumulates .grad
        if self.training:
            self._pending.append((uniq, pulled))
        out = manipulation.gather(pulled, Tensor(inv.astype(np.int32)))
        return out.reshape(list(ids_np.shape) + [self._embedding_dim])

    def push_gradients(self):
        """Push accumulated per-row grads into the table (one training
        step's distributed_push_sparse)."""
        for uniq, pulled in self._pending:
            if pulled.grad is not None:
                self.table.push(uniq, np.asarray(pulled.grad._array))
        self._pending.clear()

    def clear_gradients(self):
        self._pending.clear()
        super().clear_gradients()
