"""FL coordinator — minimal analog of the reference's federated-
learning PS tier (python/paddle/distributed/ps/coordinator.py:1, 378
LoC: ClientInfoAttr / FLStrategy / ClientSelector(Base) / FLClient over
brpc + the_one_ps protos).

TPU-build shape: the coordinator is a small TCP service (same pickle
framing as distributed/rpc.py) holding a client registry; a
ClientSelector decides each client's per-round strategy
(JOIN/WAIT/FINISH); JOINed clients train locally and push weighted
state_dict updates which the coordinator folds into the global model by
FedAvg (sample-count-weighted average — the role the reference's PS
push/pull plays for its FL workers). Everything numpy host-side; the
local training itself runs wherever the client runs it (TPU step, CPU
test).

SECURITY: the wire format is the rpc tier's unauthenticated pickle
framing — `pickle.loads` on every message, in BOTH directions. Run the
coordinator on loopback or a trusted network segment ONLY (the default
bind is 127.0.0.1); never expose the port to semi-trusted FL clients
across a boundary you don't control. Authenticated JSON+ndarray framing
(elastic.py's choice for exactly this reason) is the upgrade path if
that deployment shape is ever needed. See DESIGN_DECISIONS.md.
"""
from __future__ import annotations

import socket
import socketserver
import threading

import numpy as np

from paddle_tpu.distributed.rpc import _recv_msg, _send_msg

__all__ = ["ClientInfoAttr", "FLStrategy", "ClientSelectorBase",
           "ClientSelector", "Coordinator", "FLClient"]


class ClientInfoAttr:
    """coordinator.py:38 ClientInfoAttr parity."""

    CLIENT_ID = 0
    DEVICE_TYPE = 1
    COMPUTE_CAPACITY = 2
    BANDWIDTH = 3


class FLStrategy:
    """coordinator.py:45 FLStrategy parity."""

    JOIN = 0
    WAIT = 1
    FINISH = 2


class ClientSelectorBase:
    """coordinator.py:51 ClientSelectorBase: subclass and implement
    select(clients_info, round_idx) -> {client_id: FLStrategy.*}."""

    def select(self, clients_info: dict, round_idx: int) -> dict:
        raise NotImplementedError


class ClientSelector(ClientSelectorBase):
    """Default selector (coordinator.py:82 ClientSelector): every
    registered client JOINs each round until `max_rounds`, then
    FINISH. Subclasses can use the registered capability info (e.g.
    drop low-BANDWIDTH clients to WAIT)."""

    def __init__(self, max_rounds: int = 1):
        self.max_rounds = int(max_rounds)

    def select(self, clients_info, round_idx):
        state = (FLStrategy.FINISH if round_idx >= self.max_rounds
                 else FLStrategy.JOIN)
        return {cid: state for cid in clients_info}


class _Handler(socketserver.StreamRequestHandler):
    def handle(self):
        try:
            cmd, payload = _recv_msg(self.request)
        except (ConnectionError, EOFError):
            return
        coord: "Coordinator" = self.server.coordinator  # type: ignore
        try:
            _send_msg(self.request, ("ok", coord._dispatch(cmd, payload)))
        except Exception as e:  # surface coordinator errors clientside
            _send_msg(self.request, ("err", e))


class _Server(socketserver.ThreadingTCPServer):
    allow_reuse_address = True
    daemon_threads = True


class Coordinator:
    """The FL server: client registry + round loop + FedAvg fold."""

    def __init__(self, initial_state: dict, selector=None,
                 min_clients: int = 1, host="127.0.0.1", port=0):
        self.global_state = {k: np.asarray(v, np.float32)
                             for k, v in initial_state.items()}
        self.selector = selector or ClientSelector()
        # cohort gate: until min_clients have registered, every pull
        # returns WAIT — otherwise a fast first client completes early
        # rounds solo and FedAvg silently averages a subset
        self.min_clients = int(min_clients)
        self.clients_info: dict = {}
        self.round_idx = 0
        self._round_updates: dict = {}
        self._round_done = threading.Condition()
        self._lock = threading.Lock()
        self._srv = _Server((host, port), _Handler)
        self._srv.coordinator = self  # type: ignore
        threading.Thread(target=self._srv.serve_forever,
                         daemon=True).start()

    @property
    def endpoint(self):
        ip, port = self._srv.server_address[:2]
        return f"{ip}:{port}"

    # -- protocol ----------------------------------------------------------
    def _dispatch(self, cmd, payload):
        if cmd == "register":
            cid, info = payload
            with self._lock:
                self.clients_info[cid] = info
            return True
        if cmd == "pull":
            cid = payload
            with self._lock:
                return (self._strategy_of(cid), self.round_idx,
                        dict(self.global_state))
        if cmd == "round":
            # lightweight poll: strategy + round index WITHOUT the
            # global state (WAIT/advance polling must not ship weights)
            cid = payload
            with self._lock:
                return (self._strategy_of(cid), self.round_idx)
        if cmd == "push":
            cid, round_idx, state, n_samples = payload
            n = float(n_samples)
            if not np.isfinite(n) or n < 0:
                # zero is legitimate (participation without weight);
                # negative/NaN weights would corrupt the average
                raise ValueError(
                    f"push from {cid!r} with invalid "
                    f"n_samples={n_samples}")
            # key/shape validation BEFORE the update is stored: a
            # malformed push failing inside the fold (after the
            # all-pushed gate) would leave _round_updates populated and
            # the round index stuck — wedging every OTHER client's poll
            # loop. Error the bad client instead; the round stays
            # foldable.
            missing = set(self.global_state) - set(state)
            extra = set(state) - set(self.global_state)
            if missing or extra:
                raise ValueError(
                    f"push from {cid!r} does not match global_state: "
                    f"missing keys {sorted(missing)}, unknown keys "
                    f"{sorted(extra)}")
            for k, v in state.items():
                arr = np.asarray(v, np.float32)
                want = self.global_state[k].shape
                if arr.shape != want:
                    raise ValueError(
                        f"push from {cid!r}: state[{k!r}] has shape "
                        f"{arr.shape}, global_state expects {want}")
                if not np.isfinite(arr).all():
                    # a diverged client must not poison every future
                    # round's average with NaN/Inf weights
                    raise ValueError(
                        f"push from {cid!r}: state[{k!r}] contains "
                        "non-finite values (diverged local training?)")
            self._fold(cid, round_idx, state, n)
            return True
        raise ValueError(f"unknown FL command {cmd!r}")

    def _strategy_of(self, cid):
        """Per-client strategy under the lock; WAIT while the cohort is
        still assembling (min_clients gate)."""
        if len(self.clients_info) < self.min_clients:
            return FLStrategy.WAIT
        return self.selector.select(
            self.clients_info, self.round_idx).get(cid, FLStrategy.WAIT)

    def _fold(self, cid, round_idx, state, n_samples):
        """Collect one client's update; when every JOINed client of the
        round has pushed, fold the sample-weighted average into the
        global model and advance the round (FedAvg)."""
        with self._lock:
            if round_idx != self.round_idx:
                return  # stale update from a past round: dropped
            self._round_updates[cid] = (state, float(n_samples))
            if len(self.clients_info) < self.min_clients:
                return  # cohort still assembling
            joined = {c for c, s in self.selector.select(
                self.clients_info, self.round_idx).items()
                if s == FLStrategy.JOIN}
            # fold only when EVERY joined client pushed, and average
            # only the joined clients' updates — a stray push from a
            # WAITed client must neither trigger the fold early nor
            # contaminate the round's average
            if not joined or not joined <= set(self._round_updates):
                return
            # sorted: the weighted fold below sums floats in `folded`
            # order — set order varies with the hash seed, making the
            # folded global model irreproducible (tpu-lint TPU006)
            folded = {c: self._round_updates[c] for c in sorted(joined)}
            total = sum(n for _, n in folded.values())
            # a zero-sample push still counts as round PARTICIPATION
            # (rejecting it would wedge the fold gate and deadlock the
            # cohort) but contributes weight 0; if EVERY joined client
            # pushed zero samples there is nothing to average — the
            # global model stands and the round just advances
            if total > 0:
                new = {}
                for k in self.global_state:
                    new[k] = sum(
                        np.asarray(st[k], np.float32) * (n / total)
                        for st, n in folded.values())
                self.global_state = new
            self._round_updates = {}
            self.round_idx += 1
        with self._round_done:
            self._round_done.notify_all()

    def wait_rounds(self, n, timeout=120):
        """Block until `n` FedAvg rounds completed."""
        with self._round_done:
            self._round_done.wait_for(lambda: self.round_idx >= n,
                                      timeout=timeout)
        return self.round_idx

    def close(self):
        self._srv.shutdown()
        self._srv.server_close()


class _CoordClient:
    def __init__(self, endpoint):
        ip, port = endpoint.rsplit(":", 1)
        self._addr = (ip, int(port))

    def call(self, cmd, payload):
        with socket.create_connection(self._addr, timeout=60) as s:
            _send_msg(s, (cmd, payload))
            status, out = _recv_msg(s)
        if status == "err":
            raise out
        return out


class FLClient:
    """coordinator.py:105 FLClientBase analog: register capability
    info, then run the pull-strategy / local-train / push-update loop.

        client = FLClient(endpoint, client_id=0,
                          info={ClientInfoAttr.DEVICE_TYPE: "tpu"})
        client.run(train_fn)   # train_fn(global_state) ->
                               #   (new_state, n_samples)
    """

    def __init__(self, endpoint, client_id, info=None):
        self._rpc = _CoordClient(endpoint)
        self.client_id = client_id
        self.info = info or {}
        self._rpc.call("register", (client_id, self.info))

    def pull(self):
        """-> (FLStrategy.*, round_idx, global_state)."""
        return self._rpc.call("pull", self.client_id)

    def poll_round(self):
        """-> (FLStrategy.*, round_idx) — no weights shipped."""
        return self._rpc.call("round", self.client_id)

    def push(self, round_idx, state, n_samples):
        self._rpc.call("push",
                       (self.client_id, round_idx, state, n_samples))

    def run(self, train_fn, poll_interval=0.05):
        """The reference FL worker loop: JOIN -> local train + push;
        WAIT -> poll; FINISH -> return rounds participated."""
        import time

        rounds = 0
        while True:
            strategy, round_idx = self.poll_round()
            if strategy == FLStrategy.FINISH:
                return rounds
            if strategy == FLStrategy.WAIT:
                time.sleep(poll_interval)
                continue
            _, round_idx, global_state = self.pull()
            new_state, n = train_fn(global_state)
            self.push(round_idx, new_state, n)
            rounds += 1
            # wait for the round to advance before pulling again so a
            # fast client doesn't re-train the same round (lightweight
            # poll: the weights are only fetched when JOINing)
            while self.poll_round()[1] == round_idx:
                time.sleep(poll_interval)
