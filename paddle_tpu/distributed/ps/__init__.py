"""paddle.distributed.ps analog — PS-lite for TPU hosts.

The reference runs dedicated brpc parameter-server processes
(distributed/ps/service/brpc_ps_server.h) holding sharded sparse tables
(table/memory_sparse_table.h) with pluggable accessors/SGD rules; the
TPU-native design keeps the table/accessor/pull/push taxonomy
(ps/README.md) with TWO service modes:
- in-trainer (table.py): shards live in the TPU hosts' own RAM and the
  id exchange rides the eager alltoall — the sync-collective mode;
- service tier (service.py): standalone table-server processes reached
  over rpc, with a trainer-side Communicator in sync / async / geo
  modes — the brpc PS server + communicator.py analog. Launch with
  `--servers N`.
"""
from . import service
from .embedding import DistributedEmbedding
from .coordinator import (ClientSelector, ClientSelectorBase,
                          Coordinator, FLClient, FLStrategy)
from .graph_table import GraphShard, GraphTable
from .index_dataset import Index, TreeIndex
from .service import (Communicator, GraphTableClient, TableClient,
                      init_ps_rpc, is_server,
                      is_worker, run_server, stop_servers)
from .table import (MemorySparseTable, SparseAdagradRule, SparseSGDRule,
                    SSDSparseTable)

__all__ = ["Coordinator", "FLClient", "FLStrategy",
           "ClientSelector", "ClientSelectorBase",
           "GraphTable", "GraphShard", "GraphTableClient", "Index", "TreeIndex",
           "MemorySparseTable", "SSDSparseTable", "SparseAdagradRule",
           "SparseSGDRule",
           "DistributedEmbedding", "service", "TableClient",
           "Communicator", "init_ps_rpc", "is_server", "is_worker",
           "run_server", "stop_servers"]
