"""paddle.distributed.ps analog — PS-lite for TPU hosts.

The reference runs dedicated brpc parameter-server processes
(distributed/ps/service/brpc_ps_server.h) holding sharded sparse tables
(table/memory_sparse_table.h) with pluggable accessors/SGD rules; the
TPU-native design keeps the table/accessor/pull/push taxonomy
(ps/README.md) but serves shards from the TPU hosts' own RAM and rides
the eager alltoall for the id exchange (SURVEY §7 PS row).
"""
from .embedding import DistributedEmbedding
from .table import MemorySparseTable, SparseAdagradRule, SparseSGDRule

__all__ = ["MemorySparseTable", "SparseAdagradRule", "SparseSGDRule",
           "DistributedEmbedding"]
