"""Tree index for recall models — minimal analog of the reference's
index_dataset tier (paddle/fluid/distributed/index_dataset/
index_wrapper.h TreeIndex + index_sampler.h LayerWiseSampler;
python surface python/paddle/distributed/fleet/dataset/
index_dataset.py:24 TreeIndex).

The reference stores a TDM-style complete k-ary tree of items in a
protobuf KV file and serves code/ancestor lookups + layerwise negative
sampling to trainers. Here the tree is a host-side numpy structure with
the same code arithmetic (root code 0; children of c are
c*branch+1 .. c*branch+branch) and the same API surface; persistence is
a pickle (save/load) instead of the proto KV store.
"""
from __future__ import annotations

import pickle

import numpy as np

__all__ = ["Index", "TreeIndex"]


class Index:
    """index_dataset.py:20 base."""

    def __init__(self, name):
        self._name = name


class TreeIndex(Index):
    """Complete `branch`-ary tree over items; leaves sit at the deepest
    level, left-aligned. Items keep their uint64 ids; internal nodes
    get synthetic ids above max(item id)."""

    def __init__(self, name, path=None):
        super().__init__(name)
        self._sampler = None
        if path is not None:
            with open(path, "rb") as f:
                d = pickle.load(f)
            (self._branch, self._height, self._codes, self._ids,
             self._is_leaf, self._prob) = d
            self._build_lookups()

    def _build_lookups(self):
        """O(total_nodes) ONCE: code<->id dicts + per-level sorted code
        arrays, so per-row sampling work is O(1)/O(log) instead of
        full-table scans (a TDM-scale tree has millions of nodes)."""
        self._code2id = {int(c): int(i)
                         for c, i in zip(self._codes, self._ids)}
        self._id2code = {int(i): int(c)
                         for c, i in zip(self._codes, self._ids)}
        self._level_codes = [self._layer_codes_scan(lv)
                             for lv in range(self._height)]

    @classmethod
    def from_items(cls, name, item_ids, branch=2, probabilities=None):
        """Build the tree from leaf item ids (TreeIndex builder
        analog). height = levels count; leaves at level height-1."""
        item_ids = np.asarray(item_ids, np.uint64)
        n = len(item_ids)
        if n == 0:
            raise ValueError("empty item list")
        branch = int(branch)
        if branch < 2:
            raise ValueError(
                f"branch={branch}: a tree needs branch >= 2 (a "
                "1-ary 'tree' cannot hold more than one item per "
                "level)")
        height = 1
        while branch ** (height - 1) < n:
            height += 1
        if probabilities is not None and len(probabilities) != n:
            raise ValueError(f"probabilities length mismatch: "
                             f"{len(probabilities)} vs {n} items")
        t = cls(name)
        t._branch = branch
        t._height = height
        first_leaf = (branch ** (height - 1) - 1) // (branch - 1)
        leaf_codes = first_leaf + np.arange(n)
        # code -> (id, is_leaf, prob) maps, ancestors get synthetic ids
        codes = [leaf_codes]
        ids = [item_ids]
        leaf = [np.ones(n, bool)]
        prob = [np.asarray(probabilities, np.float32)
                if probabilities is not None
                else np.full(n, 1.0 / n, np.float32)]
        next_id = int(item_ids.max()) + 1
        cur_codes, cur_prob = leaf_codes, prob[0]
        while cur_codes[0] != 0:
            parents, inv = np.unique((cur_codes - 1) // branch,
                                     return_inverse=True)
            pprob = np.zeros(len(parents), np.float32)
            np.add.at(pprob, inv, cur_prob)
            codes.append(parents)
            ids.append(np.arange(next_id, next_id + len(parents),
                                 dtype=np.uint64))
            next_id += len(parents)
            leaf.append(np.zeros(len(parents), bool))
            prob.append(pprob)
            cur_codes, cur_prob = parents, pprob
        t._codes = np.concatenate(codes)
        t._ids = np.concatenate(ids)
        t._is_leaf = np.concatenate(leaf)
        t._prob = np.concatenate(prob)
        t._build_lookups()
        return t

    def save(self, path):
        with open(path, "wb") as f:
            pickle.dump((self._branch, self._height, self._codes,
                         self._ids, self._is_leaf, self._prob), f)

    # -- metadata (index_dataset.py:36-48 parity) ------------------------
    def height(self):
        return self._height

    def branch(self):
        return self._branch

    def total_node_nums(self):
        return len(self._codes)

    def emb_size(self):
        """Embedding-table size needed for node ids (max id + 1)."""
        return int(self._ids.max()) + 1

    def get_all_leafs(self):
        return self._ids[self._is_leaf]

    # -- code arithmetic --------------------------------------------------
    def _level_of(self, code):
        lvl = 0
        c = int(code)
        while c != 0:
            c = (c - 1) // self._branch
            lvl += 1
        return lvl

    def _code_of_id(self, nid):
        try:
            return self._id2code[int(nid)]
        except KeyError:
            raise KeyError(f"id {nid} not in tree") from None

    def get_nodes(self, codes):
        """codes -> node ids (missing codes raise)."""
        return np.asarray([self._code2id[int(c)] for c in codes],
                          np.uint64)

    def _layer_codes_scan(self, level):
        # branch >= 2 guaranteed by from_items' validation
        lo = (self._branch ** level - 1) // (self._branch - 1)
        hi = (self._branch ** (level + 1) - 1) // (self._branch - 1)
        mask = (self._codes >= lo) & (self._codes < hi)
        return np.sort(self._codes[mask])

    def get_layer_codes(self, level):
        return self._level_codes[level]

    def get_travel_codes(self, nid, start_level=0):
        """Leaf id -> [leaf code, parent, ..., level start_level]
        (index_dataset.py:57)."""
        c = self._code_of_id(nid)
        out = []
        lvl = self._level_of(c)
        while lvl >= start_level:
            out.append(c)
            if c == 0:
                break
            c = (c - 1) // self._branch
            lvl -= 1
        return np.asarray(out, np.int64)

    def get_ancestor_codes(self, ids, level):
        out = []
        for nid in ids:
            c = self._code_of_id(nid)
            lvl = self._level_of(c)
            while lvl > level:
                c = (c - 1) // self._branch
                lvl -= 1
            out.append(c)
        return np.asarray(out, np.int64)

    def get_children_codes(self, ancestor_code, level):
        alvl = self._level_of(ancestor_code)
        codes = np.asarray([int(ancestor_code)], np.int64)
        for _ in range(level - alvl):
            codes = (codes[:, None] * self._branch + 1 +
                     np.arange(self._branch)).ravel()
        present = np.isin(codes, self._codes)
        return codes[present]

    def get_pi_relation(self, ids, level):
        """{item id: its level-`level` ancestor code}."""
        anc = self.get_ancestor_codes(ids, level)
        return {int(i): int(a) for i, a in zip(ids, anc)}

    # -- layerwise sampling (index_sampler.h LayerWiseSampler) -----------
    def init_layerwise_sampler(self, layer_sample_counts,
                               start_sample_layer=1, seed=0):
        if len(layer_sample_counts) != self._height - start_sample_layer:
            raise ValueError(
                f"need {self._height - start_sample_layer} layer counts "
                f"(layers {start_sample_layer}..{self._height - 1}), "
                f"got {len(layer_sample_counts)}")
        self._sampler = (list(layer_sample_counts),
                         int(start_sample_layer),
                         np.random.RandomState(seed))

    def layerwise_sample(self, user_input, index_input,
                         with_hierarchy=False):
        """TDM training sample expansion: for each (user features,
        target item) pair emit, per layer, the positive ancestor
        (label 1) plus `layer_sample_counts[l]` uniform negatives from
        that layer (label 0). Returns (users, node_ids, labels)."""
        if self._sampler is None:
            raise RuntimeError("call init_layerwise_sampler first")
        if with_hierarchy:
            raise NotImplementedError(
                "with_hierarchy=True (the reference's hierarchical "
                "user-feature expansion) is not implemented — flat "
                "expansion only")
        counts, start, rng = self._sampler
        users, nodes, labels = [], [], []
        for u, item in zip(user_input, index_input):
            for li, k in enumerate(counts):
                level = start + li
                layer = self.get_layer_codes(level)
                pos = self.get_ancestor_codes([item], level)[0]
                neg_pool = layer[layer != pos]
                take = min(k, len(neg_pool))
                negs = rng.choice(neg_pool, size=take, replace=False) \
                    if take else np.empty(0, np.int64)
                for code, lab in [(pos, 1)] + [(c, 0) for c in negs]:
                    users.append(u)
                    nodes.append(self.get_nodes([code])[0])
                    labels.append(lab)
        return (np.asarray(users), np.asarray(nodes, np.uint64),
                np.asarray(labels, np.int64))
