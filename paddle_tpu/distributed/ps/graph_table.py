"""Host-side graph table — minimal analog of the reference's
GraphTable/GraphShard tier
(paddle/fluid/distributed/ps/table/common_graph_table.h:501 GraphTable,
:54 GraphShard; 854 LoC of brpc-served C++): adjacency + node features
sharded by id hash, with the sampling primitives GNN trainers pull
through the PS (random_sample_neighbors, random_sample_nodes,
pull_graph_list, get/set_node_feat).

Design matches the rest of PS-lite (table.py): shards are plain
host-RAM dicts keyed `id % nshards`, a trainer-side facade fans
requests out per shard, and everything returns padded numpy so the
device side can consume fixed shapes. Weighted neighbor sampling uses
cumulative-sum inverse transform per node — the reference's
WeightedSampler tree serves the same distribution.
"""
from __future__ import annotations

import numpy as np

__all__ = ["GraphShard", "GraphTable", "uniform_sample_ids"]


def uniform_sample_ids(all_ids, n, seed=0):
    """n uniform draws (with replacement) from an id array — shared by
    the local table and the rpc client."""
    if len(all_ids) == 0:
        return np.empty(0, np.int64)
    rng = np.random.RandomState(seed)
    return np.asarray(all_ids)[rng.randint(0, len(all_ids), size=n)]


class GraphShard:
    """One shard's adjacency + features (common_graph_table.h:54)."""

    def __init__(self):
        self.neighbors: dict = {}   # id -> (ids np.int64[k], w np.f32[k])
        self.feats: dict = {}       # id -> {name: np.ndarray}

    def add_node(self, nid):
        self.neighbors.setdefault(int(nid),
                                  (np.empty(0, np.int64),
                                   np.empty(0, np.float32)))

    def add_edges(self, src, dsts, weights):
        ids0, w0 = self.neighbors.get(
            int(src), (np.empty(0, np.int64), np.empty(0, np.float32)))
        self.neighbors[int(src)] = (
            np.concatenate([ids0, np.asarray(dsts, np.int64)]),
            np.concatenate([w0, np.asarray(weights, np.float32)]))


class GraphTable:
    """Sharded graph store + sampling facade (common_graph_table.h:501).

    ids are uint64-ish python ints; `nshards` mirrors the PS server
    count (shard = id % nshards, the same partition rule as
    MemorySparseTable). All sampling takes an explicit seed so
    distributed runs stay reproducible.
    """

    def __init__(self, nshards: int = 1):
        self.nshards = int(nshards)
        self.shards = [GraphShard() for _ in range(self.nshards)]
        self._feat_width: dict = {}   # name -> fixed feature shape
        self._ids_cache = None        # sorted global ids (invalidated
        #                               on any mutation)

    def _shard(self, nid) -> GraphShard:
        return self.shards[int(nid) % self.nshards]

    # -- construction (add_graph_node / build_graph analogs) ------------
    def add_graph_node(self, ids):
        self._ids_cache = None
        for nid in np.asarray(ids, np.int64).ravel():
            self._shard(nid).add_node(nid)

    def add_edges(self, src_ids, dst_ids, weights=None,
                  register_dst=True):
        """register_dst=False skips dst node registration — the
        rpc-served path routes dst nodes to THEIR owning shard
        client-side; registering them here (the src's shard) would
        double-count nodes across servers."""
        src = np.asarray(src_ids, np.int64).ravel()
        dst = np.asarray(dst_ids, np.int64).ravel()
        if len(src) != len(dst):
            raise ValueError(f"src/dst length mismatch: "
                             f"{len(src)} vs {len(dst)}")
        w = (np.ones(len(src), np.float32) if weights is None
             else np.asarray(weights, np.float32).ravel())
        if len(w) != len(src):
            raise ValueError(f"weights length mismatch: "
                             f"{len(w)} vs {len(src)} edges")
        self._ids_cache = None
        order = np.argsort(src, kind="stable")
        src, dst, w = src[order], dst[order], w[order]
        uniq = np.unique(src)
        bounds = np.searchsorted(src, uniq)
        for i, s in enumerate(uniq):
            hi = bounds[i + 1] if i + 1 < len(bounds) else len(src)
            self._shard(s).add_edges(s, dst[bounds[i]:hi],
                                     w[bounds[i]:hi])
        if register_dst:
            self.add_graph_node(dst)

    def set_node_feat(self, ids, name, values):
        """Set feature `name` on nodes; the FIRST set fixes the
        feature's shape (fixed-width contract — the device side
        consumes static shapes), later mismatches raise."""
        vals = np.asarray(values)
        self._ids_cache = None
        for nid, v in zip(np.asarray(ids, np.int64).ravel(), vals):
            v = np.asarray(v)
            want = self._feat_width.setdefault(name, v.shape)
            if v.shape != want:
                raise ValueError(
                    f"feature {name!r} is fixed at shape {want}; got "
                    f"{v.shape} for node {int(nid)}")
            self._shard(nid).add_node(nid)
            self._shard(nid).feats.setdefault(int(nid), {})[name] = v

    # -- queries ---------------------------------------------------------
    def get_node_feat(self, ids, name, default=0.0, width=None):
        """[len(ids), *feat_shape] array — the shape registered at the
        first set_node_feat (call-order independent); missing nodes
        fill with `default` (the reference returns empty strings
        there). `width` overrides the shape for shards that never saw
        the feature (the rpc-served path, where the CLIENT is the
        width authority)."""
        ids = np.asarray(ids, np.int64).ravel()
        width = tuple(width) if width is not None \
            else self._feat_width.get(name, (1,))
        out = np.full((len(ids),) + tuple(width), default, np.float32)
        for i, nid in enumerate(ids):
            f = self._shard(nid).feats.get(int(nid), {}).get(name)
            if f is not None:
                out[i] = f
        return out

    def random_sample_neighbors(self, ids, sample_size, seed=0,
                                need_weight=False):
        """Per-id weighted sample WITH replacement ->
        neighbors [len(ids), sample_size] int64 (-1 pads isolated
        nodes) and optionally their weights
        (common_graph_table.h:540 random_sample_neighbors)."""
        ids = np.asarray(ids, np.int64).ravel()
        rng = np.random.RandomState(seed)
        out = np.full((len(ids), sample_size), -1, np.int64)
        wout = np.zeros((len(ids), sample_size), np.float32)
        for i, nid in enumerate(ids):
            nbrs, w = self._shard(nid).neighbors.get(
                int(nid), (np.empty(0, np.int64), None))
            if len(nbrs) == 0:
                continue
            p = w / w.sum() if w.sum() > 0 else None
            pick = rng.choice(len(nbrs), size=sample_size, p=p)
            out[i] = nbrs[pick]
            wout[i] = w[pick]
        return (out, wout) if need_weight else out

    def random_sample_nodes(self, n, seed=0):
        """n node ids drawn uniformly from the whole graph
        (random_sample_nodes analog)."""
        return uniform_sample_ids(self.node_ids(), n, seed)

    def pull_graph_list(self, start, size):
        """Deterministic node-id window [start, start+size) over the
        sorted global id list (batch iteration for GNN epochs —
        pull_graph_list analog)."""
        return self.node_ids()[start:start + size]

    def node_ids(self):
        if self._ids_cache is None:
            ids = [i for sh in self.shards for i in sh.neighbors]
            self._ids_cache = np.sort(np.asarray(ids, np.int64))
            # callers get the cache by reference; read-only so caller
            # mutation can't corrupt it (views inherit the flag)
            self._ids_cache.setflags(write=False)
        return self._ids_cache

    def stats(self):
        return {"nodes": sum(len(s.neighbors) for s in self.shards),
                "edges": sum(len(v[0]) for s in self.shards
                             for v in s.neighbors.values()),
                "nshards": self.nshards}
