"""Collective communication API — analog of
python/paddle/distributed/communication/ (all_reduce.py etc.) and the
C++ ProcessGroup (paddle/fluid/distributed/collective/process_group.h:53).

Two layers, reflecting the TPU execution model:

1. **In-mesh (compiled) collectives** — `paddle_tpu.distributed.functional`:
   jax.lax psum/all_gather/ppermute/all_to_all used inside shard_map/pjit.
   These are THE high-performance path: XLA compiles them onto ICI. The
   reference's c_allreduce/c_allgather ops inside a static Program are the
   moral equivalent.

2. **Eager host-level collectives** (this module) — the paddle-parity
   paddle.distributed.all_reduce(tensor) surface. Each call stages a tiny
   jitted program over a mesh of one device per participating process:
   the local tensor becomes one shard of a global array
   (jax.make_array_from_single_device_arrays), the program reduces /
   gathers / permutes it, and the replicated (or resharded) output is
   read back locally. This is the ProcessGroupXLA facade SURVEY §5
   sketches: multi-controller SPMD, so — exactly like NCCL — every
   member of the group must call the collective, in the same order.

   With one participant every collective degenerates to the identity
   (reference semantics for world_size=1).
"""
from __future__ import annotations

import functools
from typing import List, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding
from jax.sharding import PartitionSpec as P

from paddle_tpu.core.tensor import Tensor

from .topology import get_hybrid_communicate_group


class ReduceOp:
    SUM = "sum"
    MAX = "max"
    MIN = "min"
    PROD = "prod"
    AVG = "avg"


_REDUCERS = {
    ReduceOp.SUM: jnp.sum,
    ReduceOp.MAX: jnp.max,
    ReduceOp.MIN: jnp.min,
    ReduceOp.PROD: jnp.prod,
    ReduceOp.AVG: jnp.mean,
}


class Group:
    """Communication group — analog of paddle.distributed.collective.Group.

    ranks are PROCESS indices (one device per process carries the eager
    collectives; in-mesh collectives use `axis` instead). A group with an
    `axis` identifies a mesh axis for the compiled path."""

    def __init__(self, ranks: List[int], axis: Optional[str] = None, gid: int = 0):
        self.ranks = ranks
        self.axis = axis
        self.id = gid
        self.nranks = len(ranks)

    @property
    def world_size(self):
        return self.nranks

    def get_group_rank(self, rank):
        return self.ranks.index(rank) if rank in self.ranks else -1

    def __repr__(self):
        return f"Group(axis={self.axis}, ranks={self.ranks})"


_group_counter = 0
_groups = {}


def new_group(ranks=None, backend=None, axis=None) -> Group:
    """Analog of paddle.distributed.new_group (collective.py:185)."""
    global _group_counter
    _group_counter += 1
    if ranks is None:
        ranks = list(range(jax.process_count()))
    g = Group(list(ranks), axis=axis, gid=_group_counter)
    _groups[g.id] = g
    return g


def get_group(gid=0) -> Optional[Group]:
    return _groups.get(gid)


# ---------------------------------------------------------------------------
# eager cross-process machinery
# ---------------------------------------------------------------------------

@functools.lru_cache(maxsize=None)
def _proc_device(pid: int):
    """First device owned by process pid (the rank's collective device —
    FLAGS_selected_gpus analog)."""
    for d in jax.devices():
        if d.process_index == pid:
            return d
    raise ValueError(f"no device for process {pid}")


@functools.lru_cache(maxsize=None)
def _group_mesh(ranks: tuple):
    """1-D mesh over one device per member process. Only member processes
    launch programs on it (multi-controller SPMD)."""
    return Mesh(np.array([_proc_device(r) for r in ranks]), ("world",))


def _axis_member_ranks(axis: str):
    """Processes in the caller's slice along `axis` of the hybrid mesh.
    A mesh-axis group's "ranks" are devices inside compiled programs; the
    eager host collective over it is only meaningful when each step along
    the axis is a distinct process."""
    hcg = get_hybrid_communicate_group()
    degree = hcg.axis_size(axis)
    if degree <= 1:
        return (jax.process_index(),)
    mesh = hcg.mesh
    devs = mesh.devices
    me = jax.process_index()
    my_coord = None
    for coord, d in np.ndenumerate(devs):
        if d.process_index == me:
            my_coord = coord
            break
    if my_coord is None:
        raise ValueError(f"process {me} owns no device in the hybrid mesh")
    ax = mesh.axis_names.index(axis)
    sl = list(my_coord)
    sl[ax] = slice(None)
    group_devs = devs[tuple(sl)].ravel()
    ranks = tuple(sorted({d.process_index for d in group_devs}))
    if len(ranks) < degree:
        raise NotImplementedError(
            f"eager collective over mesh axis {axis!r}: the axis spans "
            f"devices within one process — use paddle_tpu.distributed."
            f"functional inside shard_map / DistributedTrainStep "
            f"(compiled path)")
    return ranks


def _member_ranks(group: Optional[Group]):
    if group is not None:
        if group.axis is not None:
            return _axis_member_ranks(group.axis)
        return tuple(group.ranks)
    try:
        return tuple(range(jax.process_count()))
    except Exception:
        return (0,)


def _as_global(arr, mesh):
    """Local array -> global [P, *shape] array, one shard per process."""
    me = jax.process_index()
    sharding = NamedSharding(mesh, P("world"))
    local = jax.device_put(arr[None], _proc_device(me))
    P_ = mesh.devices.size
    return jax.make_array_from_single_device_arrays(
        (P_,) + tuple(arr.shape), sharding, [local])


def _replicated(mesh):
    return NamedSharding(mesh, P())


def _program_for(kind: str):
    if kind == "identity":
        return lambda g: g
    if kind == "swap01":
        return lambda g: jnp.swapaxes(g, 0, 1)
    if kind.startswith("reduce_"):
        red = _REDUCERS[kind[len("reduce_"):]]
        return functools.partial(lambda red, g: red(g, axis=0), red)
    if kind.startswith("select_"):
        i = int(kind[len("select_"):])
        return functools.partial(lambda i, g: g[i], i)
    raise KeyError(kind)


@functools.lru_cache(maxsize=None)
def _jitted_program(kind: str, ranks: tuple):
    """One compiled program per (collective kind, group) — jax.jit caches
    on function identity, so per-call lambdas would retrace+recompile on
    every invocation (hundreds of ms each on TPU)."""
    mesh = _group_mesh(ranks)
    return jax.jit(_program_for(kind), out_shardings=_replicated(mesh))


def _run_collective(arr, ranks, kind):
    """Stage the `kind` program over the group mesh on the stacked global
    array and return the replicated result (locally addressable)."""
    mesh = _group_mesh(ranks)
    g = _as_global(arr, mesh)
    out = _jitted_program(kind, ranks)(g)
    # the output is replicated: read this process's local copy
    return np.asarray(out.addressable_shards[0].data)


def _ret(tensor: Tensor, value) -> Tensor:
    tensor.set_value(jnp.asarray(value, tensor._array.dtype))
    return tensor


def _stack_list(tensor_list, ranks, what):
    if len(tensor_list) != len(ranks):
        raise ValueError(
            f"{what} needs exactly one tensor per group member "
            f"({len(ranks)}), got {len(tensor_list)}")
    return jnp.stack([t._array if isinstance(t, Tensor) else jnp.asarray(t)
                      for t in tensor_list])


# ---------------------------------------------------------------------------
# the collectives (paddle.distributed.* parity surface)
# ---------------------------------------------------------------------------

def all_reduce(tensor: Tensor, op=ReduceOp.SUM, group=None, sync_op=True):
    """Reference: distributed/communication/all_reduce.py; ProcessGroup::
    AllReduce."""
    ranks = _member_ranks(group)
    if len(ranks) <= 1:
        return tensor
    out = _run_collective(tensor._array, ranks, f"reduce_{op}")
    return _ret(tensor, out)


def all_gather(tensor_list, tensor: Tensor, group=None, sync_op=True):
    """Reference: communication/all_gather.py."""
    ranks = _member_ranks(group)
    if len(ranks) <= 1:
        tensor_list.append(tensor)
        return tensor_list
    out = _run_collective(tensor._array, ranks, "identity")
    for i in range(len(ranks)):
        tensor_list.append(Tensor._wrap(jnp.asarray(out[i])))
    return tensor_list


def broadcast(tensor: Tensor, src=0, group=None, sync_op=True):
    """Reference: communication/broadcast.py."""
    ranks = _member_ranks(group)
    if len(ranks) <= 1:
        return tensor
    if src not in ranks:
        raise ValueError(f"broadcast src={src} is not in group ranks {ranks}")
    si = ranks.index(src)
    out = _run_collective(tensor._array, ranks, f"select_{si}")
    return _ret(tensor, out)


def reduce(tensor: Tensor, dst=0, op=ReduceOp.SUM, group=None, sync_op=True):
    """Reference: communication/reduce.py. All members compute the
    reduction (on TPU the replicated result is free); only dst's tensor
    is updated, matching the reference's contract that non-dst outputs
    are unspecified."""
    ranks = _member_ranks(group)
    if len(ranks) <= 1:
        return tensor
    out = _run_collective(tensor._array, ranks, f"reduce_{op}")
    if jax.process_index() == dst:
        return _ret(tensor, out)
    return tensor


def scatter(tensor: Tensor, tensor_list=None, src=0, group=None, sync_op=True):
    """Reference: communication/scatter.py. src provides tensor_list;
    every member receives its slot."""
    ranks = _member_ranks(group)
    if len(ranks) <= 1:
        if tensor_list:
            tensor.set_value(tensor_list[0])
        return tensor
    me = jax.process_index()
    if src not in ranks:
        raise ValueError(f"scatter src={src} is not in group ranks {ranks}")
    si = ranks.index(src)
    my = ranks.index(me)
    if me == src:
        stacked = _stack_list(tensor_list, ranks, "scatter tensor_list")
    else:
        stacked = jnp.zeros((len(ranks),) + tuple(tensor._array.shape),
                            tensor._array.dtype)
    out = _run_collective(stacked, ranks, f"select_{si}")
    return _ret(tensor, out[my])


def alltoall(in_tensor_list, out_tensor_list, group=None, sync_op=True):
    """Reference: communication/all_to_all.py. Each member sends
    in_tensor_list[j] to member j."""
    ranks = _member_ranks(group)
    if len(ranks) <= 1:
        out_tensor_list.extend(in_tensor_list)
        return out_tensor_list
    me = ranks.index(jax.process_index())
    stacked = _stack_list(in_tensor_list, ranks, "alltoall in_tensor_list")
    # global [P, P, *s]: row i = process i's send list; my receives = column me
    out = _run_collective(stacked, ranks, "swap01")
    for j in range(len(ranks)):
        out_tensor_list.append(Tensor._wrap(jnp.asarray(out[me][j])))
    return out_tensor_list


def reduce_scatter(tensor: Tensor, tensor_list, op=ReduceOp.SUM, group=None,
                   sync_op=True):
    """Reference: communication/reduce_scatter.py."""
    ranks = _member_ranks(group)
    if len(ranks) <= 1:
        tensor.set_value(tensor_list[0])
        return tensor
    me = ranks.index(jax.process_index())
    stacked = _stack_list(tensor_list, ranks, "reduce_scatter tensor_list")
    out = _run_collective(stacked, ranks, f"reduce_{op}")
    return _ret(tensor, out[me])


# -- host p2p over rpc -------------------------------------------------------
# Device-to-device p2p inside a compiled program stays pipeline-internal
# (distributed.pipeline shift registers — XLA collective-permute on ICI).
# THIS surface is the eager host-level send/recv of the reference
# (communication/send.py, recv.py, batch_isend_irecv.py over NCCL p2p):
# payloads travel over the rpc transport and land in a per-process
# mailbox keyed (src, tag); recv blocks until the matching message
# arrives. Requires paddle.distributed.rpc.init_rpc() (the launcher's
# trainer world) — the PS service tier shares the same rpc world.

import threading as _threading

_P2P_BOX: dict = {}
_P2P_LOCK = _threading.Condition()


def _p2p_state():
    return _P2P_BOX, _P2P_LOCK


def _p2p_reset():
    """Drop undelivered payloads — called by rpc.init_rpc/shutdown so a
    new rpc world can't consume a stale message from the previous one."""
    with _P2P_LOCK:
        _P2P_BOX.clear()


def _p2p_deliver(src, tag, payload):
    box, lock = _p2p_state()
    with lock:
        box.setdefault((src, tag), []).append(payload)
        lock.notify_all()
    return True


def _rpc_peer_name(rank):
    from paddle_tpu.distributed import rpc

    w = rpc.get_worker_info_by_rank(rank)
    if w is None:
        raise ValueError(f"no rpc worker at rank {rank}")
    return w.name


def send(tensor: Tensor, dst=0, group=None, sync_op=True):
    """Host p2p send (communication/send.py analog). Ranks are
    RPC-world ranks (recv matches on the same), so p2p works in rpc
    worlds that never called init_parallel_env. sync_op=False returns a
    waitable task (reference task semantics) instead of blocking on the
    rpc round-trip."""
    if not sync_op:
        return isend(tensor, dst, group)
    import numpy as np

    from paddle_tpu.distributed import rpc

    me = rpc.get_worker_info().rank
    arr = np.asarray(tensor._array if isinstance(tensor, Tensor)
                     else tensor)
    rpc.rpc_sync(_rpc_peer_name(dst), _p2p_deliver,
                 args=(me, 0, arr))
    return tensor


def recv(tensor: Tensor, src=0, group=None, sync_op=True, timeout=300):
    """Host p2p recv: blocks until a message from `src` arrives, then
    writes it into `tensor` (in-place, reference semantics).
    sync_op=False returns a waitable task."""
    if not sync_op:
        return irecv(tensor, src, group, timeout=timeout)
    box, lock = _p2p_state()
    with lock:
        ok = lock.wait_for(lambda: box.get((src, 0)), timeout=timeout)
        if not ok:
            raise TimeoutError(f"recv from rank {src}: no message "
                               f"within {timeout}s")
        payload = box[(src, 0)].pop(0)
        if not box[(src, 0)]:
            del box[(src, 0)]
    tensor.set_value(jnp.asarray(payload).astype(tensor._array.dtype))
    return tensor


class P2POp:
    """paddle.distributed.P2POp analog for batch_isend_irecv."""

    def __init__(self, op, tensor, peer, group=None):
        if op not in (isend, irecv, send, recv):
            raise ValueError(
                "P2POp op must be one of isend/irecv/send/recv")
        self.op = isend if op in (isend, send) else irecv
        self.tensor = tensor
        self.peer = peer
        self.group = group


class _P2PTask:
    def __init__(self, fn):
        self._err = None

        def run():
            try:
                fn()
            except Exception as e:  # surfaced on wait()
                self._err = e

        self._t = _threading.Thread(target=run, daemon=True)
        self._t.start()

    def wait(self, timeout=300):
        self._t.join(timeout)
        if self._t.is_alive():
            raise TimeoutError(
                f"p2p op still pending after {timeout}s")
        if self._err is not None:
            raise self._err


def isend(tensor: Tensor, dst=0, group=None):
    return _P2PTask(lambda: send(tensor, dst, group))


def irecv(tensor: Tensor, src=0, group=None, timeout=300):
    return _P2PTask(lambda: recv(tensor, src, group, True, timeout))


def batch_isend_irecv(p2p_op_list):
    """communication/batch_isend_irecv.py analog: launch every op,
    return the task list (caller waits each)."""
    return [op.op(op.tensor, op.peer, op.group) for op in p2p_op_list]


def barrier(group=None):
    """Real cross-process barrier: a world all-reduce of a scalar, read
    back synchronously (every member blocks until all have launched)."""
    ranks = _member_ranks(group)
    if len(ranks) <= 1:
        (jnp.zeros(()) + 0).block_until_ready()
        return
    _run_collective(jnp.zeros((), jnp.int32), ranks, "reduce_sum")
