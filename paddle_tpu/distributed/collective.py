"""Collective communication API — analog of
python/paddle/distributed/communication/ (all_reduce.py etc.) and the
C++ ProcessGroup (paddle/fluid/distributed/collective/process_group.h:53).

Two layers, reflecting the TPU execution model:

1. **In-mesh (compiled) collectives** — `paddle_tpu.distributed.functional`:
   jax.lax psum/all_gather/ppermute/all_to_all used inside shard_map/pjit.
   These are THE high-performance path: XLA compiles them onto ICI. The
   reference's c_allreduce/c_allgather ops inside a static Program are the
   moral equivalent.

2. **Eager host-level collectives** (this module) — the paddle-parity
   paddle.distributed.all_reduce(tensor) surface. Implemented by staging a
   tiny shard_map program over the relevant mesh axis on the fly, or a
   no-op identity when the axis degree is 1 (single process, single
   device). Asynchronous semantics follow PJRT: dispatch is async, arrays
   are futures.
"""
from __future__ import annotations

from typing import List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from paddle_tpu.core.tensor import Tensor

from . import functional as F
from .topology import get_hybrid_communicate_group


class ReduceOp:
    SUM = "sum"
    MAX = "max"
    MIN = "min"
    PROD = "prod"
    AVG = "avg"


class Group:
    """Communication group — analog of paddle.distributed.collective.Group.
    TPU-native: identifies a mesh axis (collectives compile onto it), plus
    rank bookkeeping for API parity."""

    def __init__(self, ranks: List[int], axis: Optional[str] = None, gid: int = 0):
        self.ranks = ranks
        self.axis = axis
        self.id = gid
        self.nranks = len(ranks)

    @property
    def world_size(self):
        return self.nranks

    def get_group_rank(self, rank):
        return self.ranks.index(rank) if rank in self.ranks else -1

    def __repr__(self):
        return f"Group(axis={self.axis}, ranks={self.ranks})"


_group_counter = 0
_groups = {}


def new_group(ranks=None, backend=None, axis=None) -> Group:
    """Analog of paddle.distributed.new_group (collective.py:185)."""
    global _group_counter
    _group_counter += 1
    if ranks is None:
        ranks = list(range(jax.process_count()))
    g = Group(list(ranks), axis=axis, gid=_group_counter)
    _groups[g.id] = g
    return g


def get_group(gid=0) -> Optional[Group]:
    return _groups.get(gid)


def _axis_degree(group: Optional[Group]) -> int:
    if group is not None and group.axis is not None:
        return get_hybrid_communicate_group().axis_size(group.axis)
    try:
        return jax.process_count()
    except Exception:
        return 1


def _eager_collective(tensor: Tensor, group, per_shard_fn, identity_ok=True):
    """Run a collective eagerly. With one participant it is the identity
    (matching reference semantics for world_size=1)."""
    if _axis_degree(group) <= 1:
        return tensor
    raise NotImplementedError(
        "eager cross-process collectives require the compiled path: wrap "
        "your step with paddle_tpu.distributed.shard_step or use "
        "paddle_tpu.distributed.functional inside shard_map")


def all_reduce(tensor: Tensor, op=ReduceOp.SUM, group=None, sync_op=True):
    return _eager_collective(tensor, group, F.all_reduce)


def all_gather(tensor_list, tensor: Tensor, group=None, sync_op=True):
    if _axis_degree(group) <= 1:
        tensor_list.append(tensor)
        return tensor_list
    raise NotImplementedError("see all_reduce note")


def broadcast(tensor: Tensor, src=0, group=None, sync_op=True):
    return _eager_collective(tensor, group, F.broadcast)


def reduce(tensor: Tensor, dst=0, op=ReduceOp.SUM, group=None, sync_op=True):
    return _eager_collective(tensor, group, F.all_reduce)


def scatter(tensor: Tensor, tensor_list=None, src=0, group=None, sync_op=True):
    if _axis_degree(group) <= 1:
        if tensor_list:
            tensor.set_value(tensor_list[0])
        return tensor
    raise NotImplementedError("see all_reduce note")


def alltoall(in_tensor_list, out_tensor_list, group=None, sync_op=True):
    if _axis_degree(group) <= 1:
        out_tensor_list.extend(in_tensor_list)
        return out_tensor_list
    raise NotImplementedError("see all_reduce note")


def reduce_scatter(tensor: Tensor, tensor_list, op=ReduceOp.SUM, group=None,
                   sync_op=True):
    if _axis_degree(group) <= 1:
        tensor.set_value(tensor_list[0])
        return tensor
    raise NotImplementedError("see all_reduce note")


def send(tensor: Tensor, dst=0, group=None, sync_op=True):
    raise NotImplementedError("p2p send is a pipeline-internal op on TPU; "
                              "use distributed.pipeline")


def recv(tensor: Tensor, src=0, group=None, sync_op=True):
    raise NotImplementedError("p2p recv is a pipeline-internal op on TPU; "
                              "use distributed.pipeline")


def barrier(group=None):
    """Host barrier: block until all pending device work completes; with
    multiple processes PJRT's coordination service sequences program
    launches, so draining dispatch is the correct analog."""
    (jnp.zeros(()) + 0).block_until_ready()
