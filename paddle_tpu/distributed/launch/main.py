"""Launcher CLI — analog of python/paddle/distributed/launch/main.py and
controllers/collective.py:21 (CollectiveController).

`python -m paddle_tpu.distributed.launch --nprocs N train.py args...`
spawns one process per rank on this host with the env contract the
reference's launcher sets (PADDLE_TRAINER_ID / PADDLE_TRAINERS_NUM /
PADDLE_MASTER), plus the JAX coordination-service address consumed by
init_parallel_env (jax.distributed.initialize — the TCPStore+NCCL-id
rendezvous analog, process_group_nccl.h:202).

TPU-native differences from the reference:
- one process per HOST, not per device: a JAX process drives all its
  local chips, so --nprocs is a host/pod-slice count (on one machine,
  useful mainly with the CPU backend for tests/CI);
- no per-device FLAGS_selected_gpus: device visibility is the backend's;
  with --backend cpu each rank gets --xla_force_host_platform_device_count
  =devices_per_proc virtual devices (the reference test pattern,
  SURVEY §4 multi-node-without-a-cluster).

Controller behavior (controllers/controller.py:34 watch loop): streams
children's output with a rank prefix, waits for completion, and on the
first failure kills the remaining ranks and exits nonzero.
"""
from __future__ import annotations

import argparse
import os
import signal
import socket
import subprocess
import sys
import threading
import time


def build_parser():
    p = argparse.ArgumentParser(
        prog="python -m paddle_tpu.distributed.launch",
        description="spawn a collective job: one process per rank")
    p.add_argument("--nprocs", type=int, default=1,
                   help="number of ranks (processes) to launch "
                        "(single-node form; see --nnodes for the "
                        "node x procs-per-node form)")
    p.add_argument("--nnodes", type=int, default=1,
                   help="number of nodes in the job (reference "
                        "launch --nnodes). With --nprocs-per-node M "
                        "the world size is nnodes*M and rank = "
                        "node_rank*M + local_rank")
    p.add_argument("--nprocs-per-node", type=int, default=0,
                   help="ranks per node (reference's per-node proc "
                        "count). 0 = classic --nprocs mode")
    p.add_argument("--node-rank", type=int, default=None,
                   help="this invocation's node index: spawn ONLY that "
                        "node's local ranks (real multi-host use — one "
                        "launcher per host, shared --master). Default: "
                        "simulate ALL nodes on this host")
    p.add_argument("--servers", type=int, default=0,
                   help="parameter-server processes to launch alongside "
                        "the trainers (TRAINING_ROLE=PSERVER; the "
                        "script should branch on paddle.distributed."
                        "ps.service.is_server() and call run_server())")
    p.add_argument("--master", default=None,
                   help="coordinator ip:port (default: 127.0.0.1:<free port>)")
    p.add_argument("--backend", default=None, choices=[None, "cpu", "tpu"],
                   help="force a jax backend for the ranks (cpu for tests)")
    p.add_argument("--devices-per-proc", type=int, default=1,
                   help="virtual device count per rank (cpu backend only)")
    p.add_argument("--log-dir", default=None,
                   help="write per-rank logs to files instead of stdout")
    p.add_argument("--max-restarts", type=int, default=0,
                   help="elastic fault tolerance: relaunch the whole pod "
                        "up to N times after a rank failure (the "
                        "ElasticManager watch/restart analog, "
                        "fleet/elastic/manager.py)")
    p.add_argument("--elastic-min", type=int, default=0,
                   help="elastic scale-in: on each restart drop one rank "
                        "(a lost host leaves the pod) down to this "
                        "minimum — ranks renumber 0..n-1 and the new "
                        "world re-rendezvouses; 0 disables (restarts "
                        "keep the original size). Scripts resume from "
                        "their checkpoint under the new "
                        "PADDLE_TRAINERS_NUM (elastic/manager.py:126 "
                        "membership-change analog)")
    p.add_argument("script", help="training script")
    p.add_argument("script_args", nargs=argparse.REMAINDER)
    return p


def _normalize_topology(args):
    """--nnodes N without --nprocs-per-node keeps its pre-r4 meaning of
    N ranks (one per simulated node) instead of being silently ignored."""
    if args.nnodes > 1 and not args.nprocs_per_node:
        args.nprocs_per_node = 1


def _world_size(args) -> int:
    if args.nprocs_per_node:
        return args.nnodes * args.nprocs_per_node
    return args.nprocs


def _rank_env(args, rank: int, master: str, server_rank=None,
              node_rank=None) -> dict:
    from paddle_tpu.distributed.spawn import rank_env_overrides

    env = dict(os.environ)
    for k, v in rank_env_overrides(rank, _world_size(args), master,
                                   args.backend, args.devices_per_proc,
                                   nservers=args.servers,
                                   server_rank=server_rank).items():
        if v is None:
            env.pop(k, None)
        else:
            env[k] = v
    if args.nprocs_per_node and server_rank is None:
        # node topology env (reference: PADDLE_TRAINERS_NUM plus the
        # node/local split the multi-node launcher derives rank from)
        env["PADDLE_NNODES"] = str(args.nnodes)
        env["PADDLE_NODE_RANK"] = str(node_rank)
        env["PADDLE_LOCAL_RANK"] = str(rank -
                                       node_rank * args.nprocs_per_node)
        env["PADDLE_LOCAL_SIZE"] = str(args.nprocs_per_node)
    return env


def _stream(proc, label):
    for line in proc.stdout:
        sys.stdout.write(f"[{label}] {line.decode(errors='replace')}")
        sys.stdout.flush()


def launch(argv=None) -> int:
    args = build_parser().parse_args(argv)
    _normalize_topology(args)
    if args.master:
        master, probe = args.master, None
    else:
        # hold the probe socket (SO_REUSEADDR) until the ranks are
        # spawned so another process can't grab the auto-picked
        # coordinator port in the selection->bind window; rank 0's
        # coordination service binds with reuse and takes over
        probe = socket.socket()
        probe.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        probe.bind(("127.0.0.1", 0))
        master = f"127.0.0.1:{probe.getsockname()[1]}"
    rc = _launch_once(args, master, probe)
    # elastic restart loop (ElasticManager.watch -> restart analog):
    # a failed pod is torn down and relaunched — whole by default, or
    # scaled in by one rank per restart with --elastic-min (the
    # membership-change path: the new pod re-rendezvouses at the
    # smaller world size and scripts resume from their checkpoint)
    restarts = 0
    while rc != 0 and restarts < args.max_restarts:
        restarts += 1
        if args.elastic_min and args.nprocs_per_node:
            if args.nnodes > args.elastic_min:
                args.nnodes -= 1  # a lost NODE leaves the pod
                sys.stderr.write(
                    f"[launch] scale-in: relaunching with "
                    f"{args.nnodes} nodes\n")
        elif args.elastic_min and args.nprocs > args.elastic_min:
            args.nprocs -= 1
            sys.stderr.write(
                f"[launch] scale-in: relaunching with "
                f"{args.nprocs} ranks\n")
        sys.stderr.write(
            f"[launch] pod failed (rc={rc}); restart "
            f"{restarts}/{args.max_restarts}\n")
        rc = _launch_once(args, master, None, attempt=restarts)
    return rc


def _launch_once(args, master: str, probe, attempt: int = 0) -> int:
    procs = []
    streams = []
    logs = []
    # spawn AND watch inside one try so a mid-spawn failure still tears
    # down the ranks already started
    rc = 0
    # (kind, rank, node): trainers first, then PS server processes
    if args.nprocs_per_node:
        per = args.nprocs_per_node
        nodes = [args.node_rank] if args.node_rank is not None \
            else range(args.nnodes)
        members = [("trainer", node * per + local, node)
                   for node in nodes for local in range(per)]
        if args.node_rank not in (None, 0) and not args.master:
            raise SystemExit("--node-rank > 0 needs --master "
                             "(the coordinator lives on node 0)")
    else:
        members = [("trainer", r, 0) for r in range(args.nprocs)]
    if args.node_rank in (None, 0):
        # PS servers live on node 0 only: with per-host launchers every
        # node would otherwise spawn colliding server ranks
        members += [("server", s, 0) for s in range(args.servers)]
    try:
        for kind, rank, node in members:
            env = _rank_env(args, rank, master,
                            server_rank=rank if kind == "server"
                            else None,
                            node_rank=node)
            if probe is not None:
                # release the coordinator port at the last moment (rank
                # 0's bind happens moments later; a same-port steal now
                # needs to win a microsecond window instead of the whole
                # env-setup span)
                probe.close()
                probe = None
            label = f"rank{rank}" if kind == "trainer" else f"ps{rank}"
            if args.log_dir:
                os.makedirs(args.log_dir, exist_ok=True)
                # attempt-suffixed on elastic restarts: the failed
                # attempt's logs are the crash evidence — keep them
                suffix = "" if attempt == 0 else f".restart{attempt}"
                logf = open(os.path.join(
                    args.log_dir, f"{label}{suffix}.log"), "w")
                logs.append(logf)
                proc = subprocess.Popen(
                    [sys.executable, args.script] + args.script_args,
                    env=env, stdout=logf, stderr=subprocess.STDOUT)
            else:
                proc = subprocess.Popen(
                    [sys.executable, args.script] + args.script_args,
                    env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT)
                t = threading.Thread(target=_stream, args=(proc, label))
                t.daemon = True
                t.start()
                streams.append(t)
            procs.append(proc)

        # watch loop (ControllerBase.watch analog): first failure kills the pod
        pending = set(range(len(procs)))
        while pending:
            for i in list(pending):
                r = procs[i].poll()
                if r is None:
                    continue
                pending.discard(i)
                if r != 0:
                    rc = r
                    for j in pending:
                        procs[j].send_signal(signal.SIGTERM)
                    deadline = time.time() + 10
                    for j in pending:
                        try:
                            procs[j].wait(max(0.1, deadline - time.time()))
                        except subprocess.TimeoutExpired:
                            procs[j].kill()
                    pending.clear()
                    break
            time.sleep(0.2)
    except BaseException:
        for p in procs:
            if p.poll() is None:
                p.kill()
        raise
    finally:
        for t in streams:
            t.join(timeout=5)
        for f in logs:
            f.close()
    return rc


def main():
    sys.exit(launch())


if __name__ == "__main__":
    main()
