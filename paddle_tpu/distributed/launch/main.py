"""Launcher CLI — analog of python/paddle/distributed/launch/main.py and
controllers/collective.py:21 (CollectiveController).

`python -m paddle_tpu.distributed.launch --nprocs N train.py args...`
spawns one process per rank on this host with the env contract the
reference's launcher sets (PADDLE_TRAINER_ID / PADDLE_TRAINERS_NUM /
PADDLE_MASTER), plus the JAX coordination-service address consumed by
init_parallel_env (jax.distributed.initialize — the TCPStore+NCCL-id
rendezvous analog, process_group_nccl.h:202).

TPU-native differences from the reference:
- one process per HOST, not per device: a JAX process drives all its
  local chips, so --nprocs is a host/pod-slice count (on one machine,
  useful mainly with the CPU backend for tests/CI);
- no per-device FLAGS_selected_gpus: device visibility is the backend's;
  with --backend cpu each rank gets --xla_force_host_platform_device_count
  =devices_per_proc virtual devices (the reference test pattern,
  SURVEY §4 multi-node-without-a-cluster).

Controller behavior (controllers/controller.py:34 watch loop): streams
children's output with a rank prefix, waits for completion, and on the
first failure kills the remaining ranks and exits nonzero.
"""
from __future__ import annotations

import argparse
import os
import signal
import subprocess
import sys
import threading
import time


def build_parser():
    p = argparse.ArgumentParser(
        prog="python -m paddle_tpu.distributed.launch",
        description="spawn a collective job: one process per rank")
    p.add_argument("--nprocs", type=int, default=1,
                   help="number of ranks (processes) to launch "
                        "(single-node form; see --nnodes for the "
                        "node x procs-per-node form)")
    p.add_argument("--nnodes", type=int, default=1,
                   help="number of nodes in the job (reference "
                        "launch --nnodes). With --nprocs-per-node M "
                        "the world size is nnodes*M and rank = "
                        "node_rank*M + local_rank")
    p.add_argument("--nprocs-per-node", type=int, default=0,
                   help="ranks per node (reference's per-node proc "
                        "count). 0 = classic --nprocs mode")
    p.add_argument("--node-rank", type=int, default=None,
                   help="this invocation's node index: spawn ONLY that "
                        "node's local ranks (real multi-host use — one "
                        "launcher per host, shared --master). Default: "
                        "simulate ALL nodes on this host")
    p.add_argument("--servers", type=int, default=0,
                   help="parameter-server processes to launch alongside "
                        "the trainers (TRAINING_ROLE=PSERVER; the "
                        "script should branch on paddle.distributed."
                        "ps.service.is_server() and call run_server())")
    p.add_argument("--master", default=None,
                   help="coordinator ip:port (default: 127.0.0.1:<free port>)")
    p.add_argument("--backend", default=None, choices=[None, "cpu", "tpu"],
                   help="force a jax backend for the ranks (cpu for tests)")
    p.add_argument("--devices-per-proc", type=int, default=1,
                   help="virtual device count per rank (cpu backend only)")
    p.add_argument("--log-dir", default=None,
                   help="write per-rank logs to files instead of stdout")
    p.add_argument("--max-restarts", type=int, default=0,
                   help="elastic fault tolerance: relaunch the whole pod "
                        "up to N times after a rank failure (the "
                        "ElasticManager watch/restart analog, "
                        "fleet/elastic/manager.py)")
    p.add_argument("--elastic-min", type=int, default=0,
                   help="elastic mode: on each restart the pod is "
                        "resized to the membership registry's LIVE set "
                        "(survivors + rejoined members), clamped to "
                        "[min, --elastic-max] — ranks renumber 0..n-1 "
                        "and the new world re-rendezvouses; 0 disables "
                        "(restarts keep the original size, the "
                        "reference's FAULT_TOLERANCE level). Scripts "
                        "resume from their checkpoint under the new "
                        "PADDLE_TRAINERS_NUM (elastic/manager.py:126 "
                        "ElasticManager analog)")
    p.add_argument("--elastic-max", type=int, default=0,
                   help="elastic scale-out ceiling (reference --np "
                        "MIN:MAX upper bound, manager.py:498). 0 = the "
                        "initial world size")
    p.add_argument("--elastic-master", default=None,
                   help="ip:port to serve the membership registry on "
                        "(the etcd/ETCDMaster analog). Default: an "
                        "auto-picked port. Give an explicit endpoint so "
                        "recovered hosts can rejoin via `python -m "
                        "paddle_tpu.distributed.launch.elastic join`")
    p.add_argument("script", help="training script")
    p.add_argument("script_args", nargs=argparse.REMAINDER)
    return p


def _normalize_topology(args):
    """--nnodes N without --nprocs-per-node keeps its pre-r4 meaning of
    N ranks (one per simulated node) instead of being silently ignored."""
    if args.nnodes > 1 and not args.nprocs_per_node:
        args.nprocs_per_node = 1


def _world_size(args) -> int:
    if args.nprocs_per_node:
        return args.nnodes * args.nprocs_per_node
    return args.nprocs


def _rank_env(args, rank: int, master: str, server_rank=None,
              node_rank=None, rpc_master=None,
              elastic_endpoint=None, elastic_token=None) -> dict:
    from paddle_tpu.distributed.spawn import rank_env_overrides

    env = dict(os.environ)
    for k, v in rank_env_overrides(rank, _world_size(args), master,
                                   args.backend, args.devices_per_proc,
                                   nservers=args.servers,
                                   server_rank=server_rank,
                                   rpc_master=rpc_master).items():
        if v is None:
            env.pop(k, None)
        else:
            env[k] = v
    if elastic_endpoint:
        # lets a recovered host's agent (or a test worker standing in
        # for one) find the membership registry
        env["PADDLE_ELASTIC_MASTER"] = elastic_endpoint
        if elastic_token:
            env["PADDLE_ELASTIC_TOKEN"] = elastic_token
    if args.nprocs_per_node and server_rank is None:
        # node topology env (reference: PADDLE_TRAINERS_NUM plus the
        # node/local split the multi-node launcher derives rank from)
        env["PADDLE_NNODES"] = str(args.nnodes)
        env["PADDLE_NODE_RANK"] = str(node_rank)
        env["PADDLE_LOCAL_RANK"] = str(rank -
                                       node_rank * args.nprocs_per_node)
        env["PADDLE_LOCAL_SIZE"] = str(args.nprocs_per_node)
    return env


def _stream(proc, label):
    for line in proc.stdout:
        sys.stdout.write(f"[{label}] {line.decode(errors='replace')}")
        sys.stdout.flush()


def launch(argv=None) -> int:
    args = build_parser().parse_args(argv)
    _normalize_topology(args)
    from paddle_tpu.distributed.spawn import probe_free_port

    if args.master:
        # multi-host: the rpc master must be deterministic across
        # launchers, so init_rpc keeps the coordinator+1 convention
        # relative to the EXPLICIT master (single-host concurrent jobs
        # — the collision case — always auto-pick below)
        master, probes, rpc_master = args.master, [], None
    else:
        # hold the probe sockets (SO_REUSEADDR) until the ranks are
        # spawned so another process can't grab the auto-picked ports
        # in the selection->bind window; rank 0's services bind with
        # reuse and take over. The second port is the job-private rpc
        # rendezvous endpoint (r4 weak #4: coordinator+1 collided
        # across concurrent jobs).
        p1, master = probe_free_port()
        p2, rpc_master = probe_free_port()
        probes = [p1, p2]

    # membership registry (etcd/ETCDMaster analog) — started whenever
    # restarts are possible, so the restart size comes from the LIVE
    # set instead of a blind decrement (manager.py:422 host matching).
    # Node 0 only: elastic restart coordination spans ONE launcher's
    # pod; per-host launchers (--node-rank > 0) restart independently
    # and cross-host membership is out of scope (a recovered host
    # rejoins the node-0 pod via `launch.elastic join`).
    emaster = None
    if args.max_restarts > 0 and args.node_rank in (None, 0):
        import secrets

        from .elastic import ElasticMaster

        # per-job token (ADVICE r5): wire-level register/leave/put on
        # the rendezvous port require it; ranks/joiners get it via
        # PADDLE_ELASTIC_TOKEN (printed once for operators running
        # `launch.elastic join` from a recovered host)
        token = secrets.token_hex(16)
        if args.elastic_master:
            eip, eport = args.elastic_master.rsplit(":", 1)
            emaster = ElasticMaster(eip, int(eport), token=token)
        else:
            emaster = ElasticMaster(token=token)
        # printed for BOTH branches: an operator running
        # `launch.elastic join` from a recovered host needs endpoint +
        # token regardless of whether the port was auto-picked
        sys.stderr.write(
            f"[launch] elastic registry on {emaster.endpoint} "
            f"(join token: {token})\n")
        # the scale-out ceiling is fixed at job start (reference --np
        # MIN:MAX), independent of later scale-ins
        if not args.elastic_max:
            args.elastic_max = (args.nnodes if args.nprocs_per_node
                                else args.nprocs)

    def _scale_out_ok(restarts_used):
        """A joiner-triggered teardown is only worth it when a restart
        slot remains to relaunch AND the pod isn't already at the
        ceiling — otherwise a late joiner would convert a healthy job
        into a failure (or burn a slot relaunching at the same size)."""
        current = args.nnodes if args.nprocs_per_node else args.nprocs
        return (args.elastic_min > 0
                and restarts_used < args.max_restarts
                and current < args.elastic_max)

    try:
        rc = _launch_once(args, master, probes, rpc_master=rpc_master,
                          emaster=emaster,
                          allow_scale_out=_scale_out_ok(0))
        # elastic restart loop (ElasticManager.watch -> restart analog):
        # a failed pod is torn down and relaunched — at the same size by
        # default (FAULT_TOLERANCE), or resized to the registry's live
        # set with --elastic-min (ELASTIC level: true survivor-count
        # scale-in, manager.py:521, and rejoin scale-out, :498)
        restarts = 0
        while rc != 0 and restarts < args.max_restarts:
            restarts += 1
            if args.elastic_min and emaster is not None:
                _elastic_resize(args, emaster)
            sys.stderr.write(
                f"[launch] pod failed (rc={rc}); restart "
                f"{restarts}/{args.max_restarts}\n")
            rc = _launch_once(args, master, [], attempt=restarts,
                              rpc_master=rpc_master, emaster=emaster,
                              allow_scale_out=_scale_out_ok(restarts))
        return rc
    finally:
        if emaster is not None:
            emaster.close()


def _elastic_resize(args, emaster):
    """Resize the pod to the registry's live set at a restart boundary:
    launcher-owned survivors (failed members already left) plus any
    externally rejoined members, clamped to [--elastic-min,
    --elastic-max]. ONLY the joiners actually absorbed into the new
    world size have their registration consumed (the relaunch spawns
    their capacity as local ranks); a joiner the elastic_max clamp left
    out keeps its TTL lease — its heartbeat agent stays live and it is
    picked up at a later restart boundary instead of silently
    retiring."""
    node_mode = bool(args.nprocs_per_node)
    current = args.nnodes if node_mode else args.nprocs
    live = emaster.live()
    joiners = sorted(m for m, info in live.items()
                     if info.get("_external"))
    survivors = len(live) - len(joiners)
    if len(live) == 0:
        return  # every member died: plain fixed-size restart
    new = max(min(survivors + len(joiners), args.elastic_max),
              args.elastic_min)
    absorbed = max(0, min(len(joiners), new - survivors))
    for j in joiners[:absorbed]:
        emaster.leave(j)
    if new == current:
        return
    unit = "nodes" if node_mode else "ranks"
    verb = "scale-in" if new < current else "scale-out"
    sys.stderr.write(
        f"[launch] {verb}: relaunching with {new} {unit}\n")
    if node_mode:
        args.nnodes = new
    else:
        args.nprocs = new


# returned by _launch_once when the pod was torn down because NEW
# members joined (re-rendezvous at the bigger world); any nonzero value
# drives the restart loop, this one just names the reason (EX_TEMPFAIL)
SCALE_OUT_RC = 75


def _teardown(procs, pending):
    """SIGTERM the surviving ranks and reap them (kill stragglers)."""
    for j in pending:
        procs[j].send_signal(signal.SIGTERM)
    deadline = time.time() + 10
    for j in pending:
        try:
            procs[j].wait(max(0.1, deadline - time.time()))
        except subprocess.TimeoutExpired:
            procs[j].kill()
    pending.clear()


def _launch_once(args, master: str, probes, attempt: int = 0,
                 rpc_master=None, emaster=None,
                 allow_scale_out: bool = False) -> int:
    procs = []
    streams = []
    logs = []
    # spawn AND watch inside one try so a mid-spawn failure still tears
    # down the ranks already started
    rc = 0
    # (kind, rank, node): trainers first, then PS server processes
    if args.nprocs_per_node:
        per = args.nprocs_per_node
        nodes = [args.node_rank] if args.node_rank is not None \
            else range(args.nnodes)
        members = [("trainer", node * per + local, node)
                   for node in nodes for local in range(per)]
        if args.node_rank not in (None, 0) and not args.master:
            raise SystemExit("--node-rank > 0 needs --master "
                             "(the coordinator lives on node 0)")
    else:
        members = [("trainer", r, 0) for r in range(args.nprocs)]
    if args.node_rank in (None, 0):
        # PS servers live on node 0 only: with per-host launchers every
        # node would otherwise spawn colliding server ranks
        members += [("server", s, 0) for s in range(args.servers)]

    def _member_name(i):
        """Registry identity for proc i: per-node in node mode (a lost
        host is the membership unit), per-rank otherwise. Servers are
        not elastic members."""
        kind, rank, node = members[i]
        if kind != "trainer":
            return None
        return f"node{node}" if args.nprocs_per_node else f"rank{rank}"

    if emaster is not None:
        # launcher-owned members: permanent lease, perfect liveness
        # information — failure is reported via leave() below. Stale
        # identities from the previous attempt are cleared first.
        emaster.clear_owned()
        for i in range(len(members)):
            name = _member_name(i)
            if name is not None:
                emaster.register(name, info={"attempt": attempt})
    try:
        for kind, rank, node in members:
            env = _rank_env(args, rank, master,
                            server_rank=rank if kind == "server"
                            else None,
                            node_rank=node, rpc_master=rpc_master,
                            elastic_endpoint=(emaster.endpoint
                                              if emaster else None),
                            elastic_token=(emaster.token
                                           if emaster else None))
            if probes:
                # release the probed ports at the last moment (rank 0's
                # binds happen moments later; a same-port steal now
                # needs to win a microsecond window instead of the whole
                # env-setup span)
                for p in probes:
                    p.close()
                probes = []
            label = f"rank{rank}" if kind == "trainer" else f"ps{rank}"
            if args.log_dir:
                os.makedirs(args.log_dir, exist_ok=True)
                # attempt-suffixed on elastic restarts: the failed
                # attempt's logs are the crash evidence — keep them
                suffix = "" if attempt == 0 else f".restart{attempt}"
                logf = open(os.path.join(
                    args.log_dir, f"{label}{suffix}.log"), "w")
                logs.append(logf)
                proc = subprocess.Popen(
                    [sys.executable, args.script] + args.script_args,
                    env=env, stdout=logf, stderr=subprocess.STDOUT)
            else:
                proc = subprocess.Popen(
                    [sys.executable, args.script] + args.script_args,
                    env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT)
                t = threading.Thread(target=_stream, args=(proc, label))
                t.daemon = True
                t.start()
                streams.append(t)
            procs.append(proc)

        # watch loop (ControllerBase.watch analog): first failure kills
        # the pod — after a short grace sweep so SIMULTANEOUS failures
        # (a multi-rank host loss) are all counted before teardown and
        # the registry's survivor set is exact. In elastic mode the
        # loop also watches the registry for newly joined members and
        # re-rendezvouses at the bigger world (the reference's
        # host_call_back -> need_sync restart, manager.py:240-267,:498)
        elastic_scan = emaster is not None and allow_scale_out
        last_scan = time.time()
        pending = set(range(len(procs)))
        while pending:
            failed = set()
            for i in list(pending):
                r = procs[i].poll()
                if r is None:
                    continue
                pending.discard(i)
                if r != 0:
                    rc = r
                    failed.add(i)
            if failed:
                if emaster is not None:
                    # grace: catch co-dying ranks so the survivor set
                    # is exact; pointless without a registry, where the
                    # fail-fast teardown shouldn't pay 0.8s
                    time.sleep(0.8)
                    for i in list(pending):
                        r = procs[i].poll()
                        if r is not None and r != 0:
                            pending.discard(i)
                            failed.add(i)
                    gone = {_member_name(i) for i in failed}
                    for name in gone:
                        if name is not None:
                            emaster.leave(name)
                _teardown(procs, pending)
            elif (pending and elastic_scan
                    and time.time() - last_scan >= 1.0):
                last_scan = time.time()
                if any(v.get("_external")
                       for v in emaster.live().values()):
                    sys.stderr.write(
                        "[launch] membership grew: restarting for "
                        "scale-out\n")
                    rc = SCALE_OUT_RC
                    _teardown(procs, pending)
            time.sleep(0.2)
    except BaseException:
        for p in procs:
            if p.poll() is None:
                p.kill()
        raise
    finally:
        for t in streams:
            t.join(timeout=5)
        for f in logs:
            f.close()
    return rc


def main():
    sys.exit(launch())


if __name__ == "__main__":
    main()
