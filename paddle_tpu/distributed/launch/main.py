"""Launcher CLI — analog of python/paddle/distributed/launch/main.py and
controllers/collective.py:21 (CollectiveController).

`python -m paddle_tpu.distributed.launch --nprocs N train.py args...`
spawns one process per rank on this host with the env contract the
reference's launcher sets (PADDLE_TRAINER_ID / PADDLE_TRAINERS_NUM /
PADDLE_MASTER), plus the JAX coordination-service address consumed by
init_parallel_env (jax.distributed.initialize — the TCPStore+NCCL-id
rendezvous analog, process_group_nccl.h:202).

TPU-native differences from the reference:
- one process per HOST, not per device: a JAX process drives all its
  local chips, so --nprocs is a host/pod-slice count (on one machine,
  useful mainly with the CPU backend for tests/CI);
- no per-device FLAGS_selected_gpus: device visibility is the backend's;
  with --backend cpu each rank gets --xla_force_host_platform_device_count
  =devices_per_proc virtual devices (the reference test pattern,
  SURVEY §4 multi-node-without-a-cluster).

Controller behavior (controllers/controller.py:34 watch loop): streams
children's output with a rank prefix, waits for completion, and on the
first failure kills the remaining ranks and exits nonzero.
"""
from __future__ import annotations

import argparse
import os
import signal
import socket
import subprocess
import sys
import threading
import time


def build_parser():
    p = argparse.ArgumentParser(
        prog="python -m paddle_tpu.distributed.launch",
        description="spawn a collective job: one process per rank")
    p.add_argument("--nprocs", "--nnodes", type=int, default=1,
                   help="number of ranks (processes) to launch")
    p.add_argument("--master", default=None,
                   help="coordinator ip:port (default: 127.0.0.1:<free port>)")
    p.add_argument("--backend", default=None, choices=[None, "cpu", "tpu"],
                   help="force a jax backend for the ranks (cpu for tests)")
    p.add_argument("--devices-per-proc", type=int, default=1,
                   help="virtual device count per rank (cpu backend only)")
    p.add_argument("--log-dir", default=None,
                   help="write per-rank logs to files instead of stdout")
    p.add_argument("--max-restarts", type=int, default=0,
                   help="elastic fault tolerance: relaunch the whole pod "
                        "up to N times after a rank failure (the "
                        "ElasticManager watch/restart analog, "
                        "fleet/elastic/manager.py)")
    p.add_argument("script", help="training script")
    p.add_argument("script_args", nargs=argparse.REMAINDER)
    return p


def _rank_env(args, rank: int, master: str) -> dict:
    from paddle_tpu.distributed.spawn import rank_env_overrides

    env = dict(os.environ)
    for k, v in rank_env_overrides(rank, args.nprocs, master, args.backend,
                                   args.devices_per_proc).items():
        if v is None:
            env.pop(k, None)
        else:
            env[k] = v
    return env


def _stream(proc, rank):
    for line in proc.stdout:
        sys.stdout.write(f"[rank {rank}] {line.decode(errors='replace')}")
        sys.stdout.flush()


def launch(argv=None) -> int:
    args = build_parser().parse_args(argv)
    if args.master:
        master, probe = args.master, None
    else:
        # hold the probe socket (SO_REUSEADDR) until the ranks are
        # spawned so another process can't grab the auto-picked
        # coordinator port in the selection->bind window; rank 0's
        # coordination service binds with reuse and takes over
        probe = socket.socket()
        probe.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        probe.bind(("127.0.0.1", 0))
        master = f"127.0.0.1:{probe.getsockname()[1]}"
    rc = _launch_once(args, master, probe)
    # elastic restart loop (ElasticManager.watch -> restart analog):
    # a failed pod is torn down and relaunched whole, same endpoints
    restarts = 0
    while rc != 0 and restarts < args.max_restarts:
        restarts += 1
        sys.stderr.write(
            f"[launch] pod failed (rc={rc}); restart "
            f"{restarts}/{args.max_restarts}\n")
        rc = _launch_once(args, master, None, attempt=restarts)
    return rc


def _launch_once(args, master: str, probe, attempt: int = 0) -> int:
    procs = []
    streams = []
    logs = []
    # spawn AND watch inside one try so a mid-spawn failure still tears
    # down the ranks already started
    rc = 0
    try:
        for rank in range(args.nprocs):
            env = _rank_env(args, rank, master)
            if probe is not None:
                # release the coordinator port at the last moment (rank
                # 0's bind happens moments later; a same-port steal now
                # needs to win a microsecond window instead of the whole
                # env-setup span)
                probe.close()
                probe = None
            if args.log_dir:
                os.makedirs(args.log_dir, exist_ok=True)
                # attempt-suffixed on elastic restarts: the failed
                # attempt's logs are the crash evidence — keep them
                suffix = "" if attempt == 0 else f".restart{attempt}"
                logf = open(os.path.join(
                    args.log_dir, f"rank{rank}{suffix}.log"), "w")
                logs.append(logf)
                proc = subprocess.Popen(
                    [sys.executable, args.script] + args.script_args,
                    env=env, stdout=logf, stderr=subprocess.STDOUT)
            else:
                proc = subprocess.Popen(
                    [sys.executable, args.script] + args.script_args,
                    env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT)
                t = threading.Thread(target=_stream, args=(proc, rank))
                t.daemon = True
                t.start()
                streams.append(t)
            procs.append(proc)

        # watch loop (ControllerBase.watch analog): first failure kills the pod
        pending = set(range(len(procs)))
        while pending:
            for i in list(pending):
                r = procs[i].poll()
                if r is None:
                    continue
                pending.discard(i)
                if r != 0:
                    rc = r
                    for j in pending:
                        procs[j].send_signal(signal.SIGTERM)
                    deadline = time.time() + 10
                    for j in pending:
                        try:
                            procs[j].wait(max(0.1, deadline - time.time()))
                        except subprocess.TimeoutExpired:
                            procs[j].kill()
                    pending.clear()
                    break
            time.sleep(0.2)
    except BaseException:
        for p in procs:
            if p.poll() is None:
                p.kill()
        raise
    finally:
        for t in streams:
            t.join(timeout=5)
        for f in logs:
            f.close()
    return rc


def main():
    sys.exit(launch())


if __name__ == "__main__":
    main()
