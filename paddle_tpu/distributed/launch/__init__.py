from .main import build_parser, launch, main

__all__ = ["launch", "main", "build_parser"]
