"""Elastic membership for the launcher — the KV-master analog of the
reference's etcd-backed elastic stack:

- `ElasticMaster` plays etcd + ETCDMaster
  (launch/controllers/master.py:177): a tiny TCP KV registry holding
  job members under TTL leases.
- Members register and keep their lease alive with heartbeats
  (fleet/elastic/manager.py:254-267 lease_heartbeat analog).
- The live set is computed from unexpired leases (manager.py:422
  `_match` host-list matching analog).
- At each restart boundary the launcher relaunches with the ACTUAL
  survivor count — scale-in (manager.py:521 `_update_elastic_scale_in`)
  — and absorbs newly registered members — scale-out / rejoin
  (manager.py:498 `_update_elastic_scale_out`).

Two membership classes, mirroring how the reference distinguishes the
local pod from remote hosts:

- **launcher-owned members** (the ranks this launcher spawned): managed
  synchronously — the parent has perfect liveness information, so their
  lease is permanent and failure is reported via `leave()`. This is the
  single-host analog of a node manager updating etcd for its own pods.
- **external members** (a recovered host rejoining the job via
  `python -m paddle_tpu.distributed.launch.elastic join`): TTL-leased,
  kept alive only by heartbeats — exactly the etcd lease mechanism,
  because there is no parent/child relationship to rely on. Elastic
  restart coordination spans one launcher's pod (node 0); per-host
  launchers restart independently.
"""
from __future__ import annotations

# Wire format: newline-delimited JSON, deliberately NOT the rpc tier's
# length-prefixed pickle framing — membership records are tiny, and a
# human (or the `launch.elastic live` CLI) can poke the registry with
# netcat when debugging a wedged pod; pickle would also let a rogue
# host on the rendezvous port execute code in the launcher.
import hmac
import json
import os
import socket
import socketserver
import threading
import time

__all__ = ["ElasticMaster", "ElasticClient", "ElasticAgent"]

_DEFAULT_TTL = 6.0

# wire commands that mutate membership/KV state: with a job token set,
# these require it. Reads (live/get) stay open — they are the debugging
# surface ("poke with netcat") and leak only what the launcher already
# prints. heartbeat IS authed: a rogue peer replaying heartbeats could
# otherwise keep a dead joiner's lease alive forever, and the next
# elastic resize would absorb the phantom into the new world size
# (ElasticClient attaches the token to every call, so no legitimate
# caller changes).
_AUTHED_CMDS = ("register", "heartbeat", "leave", "put")


def _send(sock, obj):
    sock.sendall((json.dumps(obj) + "\n").encode())


def _recv(f):
    line = f.readline()
    if not line:
        raise ConnectionError("elastic master closed the connection")
    return json.loads(line)


class _Handler(socketserver.StreamRequestHandler):
    def handle(self):
        try:
            req = _recv(self.rfile)
        except (ConnectionError, json.JSONDecodeError):
            return
        master: "ElasticMaster" = self.server.master  # type: ignore
        cmd = req.get("cmd")
        member = req.get("member")
        now = time.monotonic()
        if master.token is not None and cmd in _AUTHED_CMDS \
                and not hmac.compare_digest(
                    str(req.get("token") or "").encode(
                        "utf-8", "surrogatepass"),
                    master.token.encode("utf-8", "surrogatepass")):
            # reject before taking the lock or touching state: a rogue
            # host on the rendezvous port must not be able to register
            # phantom members (inflating the next elastic resize),
            # evict live ones, or poison the KV space
            _send(self.connection,
                  {"ok": False, "error": f"unauthorized {cmd!r}: "
                   "missing/invalid job token"})
            return
        with master._lock:
            if cmd == "register":
                ttl = req.get("ttl")
                master._members[member] = {
                    "info": req.get("info") or {},
                    "deadline": None if ttl is None else now + float(ttl),
                    "ttl": ttl,
                }
                resp = {"ok": True}
            elif cmd == "heartbeat":
                m = master._members.get(member)
                if m is not None and m["ttl"] is not None \
                        and m["deadline"] <= now:
                    # an expired lease is terminal: a late heartbeat
                    # must not resurrect a member the resize already
                    # discounted — the host re-registers explicitly
                    master._members.pop(member)
                    m = None
                if m is None:
                    resp = {"ok": False}
                else:
                    if m["ttl"] is not None:
                        m["deadline"] = now + float(m["ttl"])
                    resp = {"ok": True}
            elif cmd == "leave":
                master._members.pop(member, None)
                resp = {"ok": True}
            elif cmd == "live":
                master._prune(now)
                # same shape as ElasticMaster.live(): _external marks
                # TTL-leased joiners vs launcher-owned members
                resp = {"ok": True, "members": {
                    k: dict(v["info"], _external=v["ttl"] is not None)
                    for k, v in master._members.items()}}
            elif cmd == "put":
                master._kv[req["key"]] = req.get("value")
                resp = {"ok": True}
            elif cmd == "get":
                resp = {"ok": True, "value": master._kv.get(req["key"])}
            else:
                resp = {"ok": False, "error": f"unknown cmd {cmd!r}"}
        _send(self.connection, resp)


class _Server(socketserver.ThreadingTCPServer):
    allow_reuse_address = True
    daemon_threads = True


class ElasticMaster:
    """In-launcher KV membership registry (etcd + ETCDMaster analog).

    `token`: per-job shared secret (ADVICE r5). When set, wire-level
    register/leave/put must present it (the launcher generates one and
    hands it to ranks via PADDLE_ELASTIC_TOKEN; `launch.elastic join`
    reads the same env or --token). None or empty = open registry
    (tests, ad-hoc debugging) — an empty string must not LOOK
    authenticated while accepting every tokenless client."""

    def __init__(self, host="127.0.0.1", port=0, token=None):
        self.token = token or None
        self._members: dict = {}
        self._kv: dict = {}
        self._lock = threading.Lock()
        self._srv = _Server((host, port), _Handler)
        self._srv.master = self  # type: ignore
        threading.Thread(target=self._srv.serve_forever,
                         daemon=True).start()

    @property
    def endpoint(self) -> str:
        ip, port = self._srv.server_address[:2]
        return f"{ip}:{port}"

    # -- direct (in-process) access for the owning launcher ---------------
    def register(self, member, info=None, ttl=None):
        now = time.monotonic()
        with self._lock:
            self._members[member] = {
                "info": info or {},
                "deadline": None if ttl is None else now + float(ttl),
                "ttl": ttl,
            }

    def leave(self, member):
        with self._lock:
            self._members.pop(member, None)

    def clear_owned(self):
        """Drop every launcher-owned (permanent-lease) member — called
        at each attempt boundary so stale rank identities from the
        previous (larger) pod can't inflate the next live-set count.
        External TTL members (rejoiners) survive."""
        with self._lock:
            self._members = {k: v for k, v in self._members.items()
                             if v["ttl"] is not None}

    def _prune(self, now):
        """Drop expired leases for good (must hold the lock). Ghost
        joiners would otherwise linger forever and a late heartbeat
        could resurrect one the resize already discounted."""
        self._members = {              # guarded-by: _lock
            k: v for k, v in self._members.items()
            if v["deadline"] is None or v["deadline"] > now}

    def live(self) -> dict:
        with self._lock:
            self._prune(time.monotonic())
            return {k: dict(v["info"], _external=v["ttl"] is not None)
                    for k, v in self._members.items()}

    def close(self):
        self._srv.shutdown()
        self._srv.server_close()


class ElasticClient:
    """TCP client for a remote ElasticMaster (external members and
    node-rank launchers use this; the owning launcher talks directly)."""

    def __init__(self, endpoint: str, timeout: float = 10.0,
                 token=None):
        ip, port = endpoint.rsplit(":", 1)
        self._addr = (ip, int(port))
        self._timeout = timeout
        # default to the launcher-provided job token so in-job callers
        # (workers, rejoin agents) authenticate without plumbing
        self._token = token if token is not None \
            else os.environ.get("PADDLE_ELASTIC_TOKEN")

    def _call(self, check=True, **req):
        if self._token is not None:
            req.setdefault("token", self._token)
        with socket.create_connection(self._addr,
                                      timeout=self._timeout) as s:
            _send(s, req)
            resp = _recv(s.makefile("r"))
        if check and not resp.get("ok"):
            raise RuntimeError(
                f"elastic master error: {resp.get('error', resp)}")
        return resp

    def register(self, member, info=None, ttl=_DEFAULT_TTL):
        self._call(cmd="register", member=member, info=info or {},
                   ttl=ttl)

    def heartbeat(self, member) -> bool:
        """False (no raise) when the lease is gone — expired or
        absorbed into the pod; the member must re-register to count
        again."""
        return bool(self._call(check=False, cmd="heartbeat",
                               member=member)["ok"])

    def leave(self, member):
        self._call(cmd="leave", member=member)

    def live(self) -> dict:
        return self._call(cmd="live")["members"]

    def put(self, key, value):
        self._call(cmd="put", key=key, value=value)

    def get(self, key):
        return self._call(cmd="get", key=key)["value"]


class ElasticAgent:
    """Register an external member and keep its lease alive with a
    background heartbeat thread (manager.py lease_heartbeat analog).
    Used by a recovered host to rejoin the job, and by --node-rank
    launchers to report node liveness to node 0's master."""

    def __init__(self, endpoint: str, member: str, info=None,
                 ttl: float = _DEFAULT_TTL, interval: float | None = None,
                 token=None):
        self.client = ElasticClient(endpoint, token=token)
        self.member = member
        self.ttl = ttl
        self.interval = interval if interval is not None else ttl / 3.0
        self._stop = threading.Event()
        self.client.register(member, info=info, ttl=ttl)
        self._thread = threading.Thread(target=self._beat, daemon=True)
        self._thread.start()

    def _beat(self):
        while not self._stop.wait(self.interval):
            try:
                # a failed heartbeat (expired or ABSORBED into the pod
                # at a restart boundary) is terminal for this lease —
                # re-registering here would double-count an absorbed
                # member at the next resize, so the agent retires
                if not self.client.heartbeat(self.member):
                    return
            except OSError:
                pass  # master briefly unreachable; keep trying

    def stop(self, leave=True):
        self._stop.set()
        self._thread.join(timeout=5)
        if leave:
            try:
                self.client.leave(self.member)
            except OSError:
                pass


def main(argv=None):
    """`python -m paddle_tpu.distributed.launch.elastic join --master
    ip:port --member name [--ttl s] [--hold s]` — register a member and
    heartbeat until killed (a recovered host announcing itself)."""
    import argparse

    p = argparse.ArgumentParser(prog="launch.elastic")
    p.add_argument("action", choices=["join", "live"])
    p.add_argument("--master", required=True)
    p.add_argument("--member", default=None)
    p.add_argument("--ttl", type=float, default=_DEFAULT_TTL)
    p.add_argument("--hold", type=float, default=0,
                   help="seconds to keep heartbeating (0 = forever)")
    p.add_argument("--token", default=None,
                   help="per-job registry token (default: "
                        "$PADDLE_ELASTIC_TOKEN; required to join a "
                        "launcher-started registry)")
    args = p.parse_args(argv)
    if args.action == "live":
        print(json.dumps(
            ElasticClient(args.master, token=args.token).live()))
        return 0
    member = args.member or f"joiner-{socket.gethostname()}"
    agent = ElasticAgent(args.master, member, ttl=args.ttl,
                         token=args.token)
    print(f"joined as {member}", flush=True)
    try:
        if args.hold:
            time.sleep(args.hold)
        else:
            while True:
                time.sleep(60)
    except KeyboardInterrupt:
        pass
    finally:
        agent.stop()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
