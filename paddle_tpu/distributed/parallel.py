"""Process bootstrap — analog of python/paddle/distributed/parallel.py:318
(init_parallel_env) and collective.py:139.

TPU-native: multi-host initialization is jax.distributed.initialize (the
PJRT coordination service plays the role the TCPStore+NCCL-id exchange
plays in the reference, process_group_nccl.h:202); within a host, all
local devices belong to this one process (SPMD), so there is no
process-per-device fan-out. Environment variables mirror the reference's
launcher contract (PADDLE_TRAINER_ID / PADDLE_TRAINERS_NUM).
"""
from __future__ import annotations

import os
from typing import Optional

import jax

_initialized = False


def init_parallel_env(backend: str = "xla") -> None:
    """Analog of paddle.distributed.init_parallel_env (parallel.py:318)."""
    global _initialized
    if _initialized:
        return
    coord = os.environ.get("PADDLE_MASTER") or os.environ.get("MASTER_ADDR")
    nprocs = int(os.environ.get("PADDLE_TRAINERS_NUM", "1"))
    pid = int(os.environ.get("PADDLE_TRAINER_ID", "0"))
    if nprocs > 1 and coord:
        if os.environ.get("JAX_PLATFORMS", "").split(",")[0] == "cpu":
            # CPU multiprocess collectives need an explicit transport
            # (the test/CI backend has no ICI): route them over gloo.
            # Env-sniffed, NOT jax.default_backend() — that would
            # initialize the backend before distributed.initialize,
            # which multiprocess CPU forbids.
            try:
                jax.config.update(
                    "jax_cpu_collectives_implementation", "gloo")
            except Exception as e:
                # don't swallow silently: without gloo the collectives
                # below fail with an opaque backend error
                import warnings

                warnings.warn(
                    "could not enable gloo CPU collectives "
                    f"({e}); multiprocess CPU collectives may fail",
                    RuntimeWarning)
        port = os.environ.get("MASTER_PORT", "8476")
        jax.distributed.initialize(
            coordinator_address=f"{coord.split(':')[0]}:{port}",
            num_processes=nprocs,
            process_id=pid,
        )
    _initialized = True


def get_rank() -> int:
    """Global process index (paddle.distributed.get_rank)."""
    try:
        return jax.process_index()
    except Exception:
        return 0


def get_world_size() -> int:
    """Number of processes (paddle.distributed.get_world_size). Note: on
    TPU each process drives all its local chips; device-level parallelism
    is expressed through the mesh, not extra processes."""
    try:
        return jax.process_count()
    except Exception:
        return 1


def get_device_count() -> int:
    return len(jax.devices())


def is_initialized() -> bool:
    return _initialized


class ParallelEnv:
    """Analog of paddle.distributed.ParallelEnv."""

    @property
    def rank(self):
        return get_rank()

    @property
    def world_size(self):
        return get_world_size()

    @property
    def device_id(self):
        return 0

    @property
    def dev_id(self):
        return 0
