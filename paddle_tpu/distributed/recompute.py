"""Activation recomputation — analog of
python/paddle/distributed/fleet/recompute/recompute.py (RecomputeFunction
PyLayer :69, _recompute_without_reentrant :220).

TPU-native: the segment is wrapped in jax.checkpoint (remat) and run
through jax.vjp. The VJP closure then stores ONLY the segment inputs;
the forward is re-run inside the backward pass — identical memory/compute
trade to the reference, but the recompute happens inside the compiled XLA
program (fused, on-chip) rather than as a Python re-execution. RNG state
capture/restore (the swith_rng_state_tracker dance, recompute.py:57) is
unnecessary: jax PRNG keys are values, so the replay is deterministic by
construction.
"""
from __future__ import annotations

import jax

from paddle_tpu.core.autograd import Node, is_grad_enabled
from paddle_tpu.core.tensor import Tensor
from paddle_tpu.ops.dispatch import OpStats


def recompute(function, *args, use_reentrant=True, **kwargs):
    """fleet.utils.recompute analog. `function` may be a Layer or any
    callable over Tensors; its parameters participate in autodiff."""
    import jax.numpy as jnp

    layer_params = list(function.parameters()) if hasattr(function, "parameters") else []
    layer_buffers = list(function.buffers()) if hasattr(function, "buffers") else []
    tensor_args = [a for a in args if isinstance(a, Tensor)]
    diff_inputs = tensor_args + [p for p in layer_params if not p.stop_gradient]

    def pure(*arrays):
        """Returns (outputs, new_buffer_arrays): buffer mutations made
        by the segment (BN running stats) ride along as vjp aux — they
        are computed in the UNREMATTED forward, restored here so no
        tracer leaks, and written back by the caller below (the same
        capture contract as jit.api.make_forward_loss)."""
        n_args = len(tensor_args)
        originals = [p._array for p in diff_inputs[n_args:]]
        buf_originals = [b._array for b in layer_buffers]
        it = iter(arrays[:n_args])
        new_args = [
            Tensor._wrap(next(it), stop_gradient=a.stop_gradient)
            if isinstance(a, Tensor) else a
            for a in args
        ]
        try:
            for p, arr in zip(diff_inputs[n_args:], arrays[n_args:]):
                p._array = arr
            out = function(*new_args, **kwargs)
            tree_out = jax.tree_util.tree_map(
                lambda t: t._array if isinstance(t, Tensor) else t, out,
                is_leaf=lambda t: isinstance(t, Tensor))
            new_bufs = [jax.lax.stop_gradient(b._array)
                        for b in layer_buffers]
            return tree_out, new_bufs
        finally:
            for p, o in zip(diff_inputs[n_args:], originals):
                p._array = o
            for b, o in zip(layer_buffers, buf_originals):
                b._array = o

    def write_bufs(new_bufs):
        # tracer writes are safe only where something downstream
        # captures+restores them (a bound_state scope); else they would
        # leak into the eager world — same guard as SpectralNorm
        from paddle_tpu.jit.api import buffer_writes_captured

        for b, a in zip(layer_buffers, new_bufs):
            if buffer_writes_captured() or \
                    not isinstance(a, jax.core.Tracer):
                b._array = a

    arrays = [t._array for t in diff_inputs]
    needs_grad = is_grad_enabled() and any(
        not t.stop_gradient for t in diff_inputs)
    OpStats.record("recompute")
    if not needs_grad:
        out, new_bufs = pure(*arrays)
        write_bufs(new_bufs)
        single = not isinstance(out, (tuple, list))
        outs = [out] if single else list(out)
        wrapped = [Tensor._wrap(o) for o in outs]
        return wrapped[0] if single else tuple(wrapped)

    ckpt = jax.checkpoint(pure)
    out, vjp_fn, new_bufs = jax.vjp(ckpt, *arrays, has_aux=True)
    write_bufs(new_bufs)
    single = not isinstance(out, (tuple, list))
    outs = [out] if single else list(out)
    specs = [(o.shape, o.dtype) for o in outs]
    node = Node("recompute", vjp_fn, diff_inputs, specs)
    wrapped = [
        Tensor._wrap(o, stop_gradient=False, creator=node, out_idx=i)
        for i, o in enumerate(outs)
    ]
    return wrapped[0] if single else tuple(wrapped)
