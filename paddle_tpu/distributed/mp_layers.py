"""Megatron-style tensor-parallel layers — analog of
python/paddle/distributed/fleet/layers/mpu/mp_layers.py
(VocabParallelEmbedding :35, ColumnParallelLinear :173, RowParallelLinear
:332, ParallelCrossEntropy :498) and the comm primitives in mp_ops.py.

TPU-native re-design: instead of materializing per-rank weight shards and
issuing explicit NCCL identity/allreduce ops (mp_ops.py:27/:219), each
layer creates the FULL logical weight annotated with a PartitionSpec over
the 'mp' mesh axis (`Tensor.dist_spec`) and places a
with_sharding_constraint on its activations. Under spmd.DistributedTrainStep
XLA SPMD partitions the weights and inserts the all-reduces/all-gathers on
ICI — the same math Megatron does by hand. In eager single-device mode the
layers behave exactly like their dense counterparts, matching the
reference's mp_degree=1 behavior.
"""
from __future__ import annotations

import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

import paddle_tpu.nn as nn
from paddle_tpu.core.tensor import Tensor
from paddle_tpu.ops import nn_ops
from paddle_tpu.ops.dispatch import apply

from .sharding_api import with_sharding_constraint
from .topology import get_hybrid_communicate_group


def _mp_degree():
    return get_hybrid_communicate_group().get_model_parallel_world_size()


class ColumnParallelLinear(nn.Layer):
    """Weight [in, out] sharded over 'mp' on the OUT (column) dim.
    gather_output=True adds an all-gather (spec constraint to replicated)
    like the reference's concat path (mp_layers.py:173)."""

    def __init__(self, in_features, out_features, weight_attr=None,
                 has_bias=True, gather_output=True, fuse_matmul_bias=False,
                 mp_group=None, name=None):
        super().__init__()
        self.in_features = in_features
        self.out_features = out_features
        self.gather_output = gather_output
        assert out_features % max(_mp_degree(), 1) == 0, (
            f"out_features {out_features} not divisible by mp degree {_mp_degree()}")
        self.weight = self.create_parameter([in_features, out_features],
                                            attr=weight_attr)
        self.weight.dist_spec = P(None, "mp")
        self.bias = self.create_parameter([out_features], attr=has_bias or None,
                                          is_bias=True) if has_bias else None
        if self.bias is not None:
            self.bias.dist_spec = P("mp")

    def forward(self, x):
        out = nn_ops.linear(x, self.weight, self.bias)
        if _mp_degree() > 1:
            if self.gather_output:
                out = with_sharding_constraint(out, *([None] * (out.ndim - 1)), None)
            else:
                out = with_sharding_constraint(out, *([None] * (out.ndim - 1)), "mp")
        return out


class RowParallelLinear(nn.Layer):
    """Weight [in, out] sharded over 'mp' on the IN (row) dim; the partial
    products are summed by an SPMD-inserted all-reduce (the reference's
    explicit mp_allreduce, mp_ops.py:219). input_is_parallel skips the
    input re-shard."""

    def __init__(self, in_features, out_features, weight_attr=None,
                 has_bias=True, input_is_parallel=False, fuse_matmul_bias=False,
                 mp_group=None, name=None):
        super().__init__()
        self.in_features = in_features
        self.out_features = out_features
        self.input_is_parallel = input_is_parallel
        assert in_features % max(_mp_degree(), 1) == 0
        self.weight = self.create_parameter([in_features, out_features],
                                            attr=weight_attr)
        self.weight.dist_spec = P("mp", None)
        self.bias = self.create_parameter([out_features], attr=has_bias or None,
                                          is_bias=True) if has_bias else None
        # bias replicated (added after the reduce)

    def forward(self, x):
        if _mp_degree() > 1 and self.input_is_parallel:
            x = with_sharding_constraint(x, *([None] * (x.ndim - 1)), "mp")
        out = nn_ops.linear(x, self.weight, self.bias)
        if _mp_degree() > 1:
            out = with_sharding_constraint(out, *([None] * out.ndim))
        return out


class VocabParallelEmbedding(nn.Layer):
    """Embedding sharded over 'mp' on the vocab dim (mp_layers.py:35). XLA
    SPMD turns the masked-lookup+allreduce dance into a partitioned gather."""

    def __init__(self, num_embeddings, embedding_dim, weight_attr=None,
                 mp_group=None, name=None):
        super().__init__()
        from paddle_tpu.nn import initializer as I

        assert num_embeddings % max(_mp_degree(), 1) == 0
        self.weight = self.create_parameter(
            [num_embeddings, embedding_dim], attr=weight_attr,
            default_initializer=I.Normal(0.0, 0.02))
        self.weight.dist_spec = P("mp", None)

    def forward(self, x):
        return nn_ops.embedding(x, self.weight)


class ParallelCrossEntropy(nn.Layer):
    """CE over mp-sharded logits (mp_layers.py:498). Under SPMD the
    softmax reduction over the sharded class dim compiles into the same
    allreduce(max)+allreduce(sum) pattern as _c_softmax_with_cross_entropy
    (mp_ops.py:375)."""

    def __init__(self, mp_group=None, name=None, ignore_index=-100):
        super().__init__()
        self.ignore_index = ignore_index

    def forward(self, input, label):
        return nn_ops.softmax_with_cross_entropy(
            input, label, ignore_index=self.ignore_index)


class RNGStatesTracker:
    """Analog of fleet/layers/mpu/random.py:35 RNGStatesTracker: named RNG
    states so dropout inside mp regions can be local (different per mp
    rank) or global (identical across mp ranks). Functional-PRNG version:
    named seeds fold the mesh axis index in when local."""

    def __init__(self):
        self.states = {}

    def add(self, name, seed):
        import jax

        if name in self.states:
            raise ValueError(f"state {name} already exists")
        self.states[name] = jax.random.key(seed)

    def rng_state(self, name="model-parallel-rng"):
        import contextlib

        @contextlib.contextmanager
        def ctx():
            from paddle_tpu.core import random as prandom

            if name not in self.states:
                raise ValueError(f"state {name} not added")
            gen = prandom.default_generator()
            saved = gen.get_state()
            import jax

            gen._key = self.states[name]
            try:
                yield
            finally:
                self.states[name] = gen._key
                gen.set_state(saved)

        return ctx()


_RNG_STATE_TRACKER = RNGStatesTracker()


def get_rng_state_tracker():
    return _RNG_STATE_TRACKER


def model_parallel_random_seed(seed=2021):
    """Analog of mpu/random.py model_parallel_random_seed: distinct seed
    per mp rank for local dropout, shared global seed otherwise."""
    import paddle_tpu

    global _RNG_STATE_TRACKER
    _RNG_STATE_TRACKER = RNGStatesTracker()
    # under SPMD there is one program: fold the mp axis into the key when
    # local randomness is requested inside shard_map regions
    _RNG_STATE_TRACKER.add("global_seed", seed)
    _RNG_STATE_TRACKER.add("local_seed", seed + 2718)
    paddle_tpu.seed(seed)
