"""In-mesh collective primitives for use inside shard_map/pjit — the
compiled, ICI-riding path. Analog of the reference's collective ops
(paddle/fluid/operators/collective/c_allreduce_op.h, c_allgather,
global_scatter/global_gather, partial_send/recv) — except these lower to
XLA HLO collectives instead of launching NCCL kernels.

All functions take/return raw jax arrays (they run inside shard_map) and
an `axis` name bound to the enclosing mesh.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax


def all_reduce(x, axis: str, op: str = "sum"):
    if op == "sum":
        return lax.psum(x, axis)
    if op == "max":
        return lax.pmax(x, axis)
    if op == "min":
        return lax.pmin(x, axis)
    if op == "avg" or op == "mean":
        return lax.pmean(x, axis)
    if op == "prod":
        return jnp.exp(lax.psum(jnp.log(x), axis))
    raise ValueError(f"unknown reduce op {op}")


def all_gather(x, axis: str, concat_axis: int = 0, tiled: bool = True):
    return lax.all_gather(x, axis, axis=concat_axis, tiled=tiled)


def reduce_scatter(x, axis: str, scatter_axis: int = 0):
    return lax.psum_scatter(x, axis, scatter_dimension=scatter_axis, tiled=True)


def all_to_all(x, axis: str, split_axis: int, concat_axis: int):
    return lax.all_to_all(x, axis, split_axis=split_axis,
                          concat_axis=concat_axis, tiled=True)


def ppermute(x, axis: str, perm):
    return lax.ppermute(x, axis, perm)


def shift_right(x, axis: str, n_axis: int):
    """Ring shift (rank r -> r+1 mod n); building block of ring attention."""
    perm = [(i, (i + 1) % n_axis) for i in range(n_axis)]
    return lax.ppermute(x, axis, perm)


def shift_left(x, axis: str, n_axis: int):
    perm = [(i, (i - 1) % n_axis) for i in range(n_axis)]
    return lax.ppermute(x, axis, perm)


def broadcast(x, axis: str, src: int = 0):
    idx = lax.axis_index(axis)
    # select src's value: all_gather then take (XLA folds this into a bcast)
    gathered = lax.all_gather(x, axis, axis=0, tiled=False)
    return gathered[src]


def axis_index(axis: str):
    return lax.axis_index(axis)
