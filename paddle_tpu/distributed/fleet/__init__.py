"""Fleet facade — analog of python/paddle/distributed/fleet/fleet.py:169
(init), model.py:30 (distributed_model), optimizer.py:65
(distributed_optimizer) and base/distributed_strategy.py (2556 LoC).

On TPU the facade configures ONE mesh (HybridCommunicateGroup) from the
strategy's hybrid_configs and returns wrappers whose collectives live in
the compiled SPMD step (spmd.DistributedTrainStep) rather than in NCCL
process groups.
"""
from __future__ import annotations

from typing import Optional

from ..topology import (
    CommunicateTopology,
    HybridCommunicateGroup,
    get_hybrid_communicate_group,
    set_hybrid_communicate_group,
)
from .distributed_strategy import DistributedStrategy
from .. import mp_layers as _mpu
from ..mp_layers import (
    ColumnParallelLinear,
    ParallelCrossEntropy,
    RowParallelLinear,
    VocabParallelEmbedding,
    get_rng_state_tracker,
)
from ..recompute import recompute

_fleet_state = {"initialized": False, "strategy": None}


def init(role_maker=None, is_collective=True, strategy: Optional[DistributedStrategy] = None):
    """Analog of fleet.init (fleet.py:169): builds the hybrid topology
    from strategy.hybrid_configs and installs the global mesh. The
    degree product is validated against the visible device count HERE
    so a wrong hybrid_configs fails with the reference-style topology
    error instead of an opaque mesh error at first compile."""
    strategy = strategy or DistributedStrategy()
    hc = strategy.hybrid_configs
    degree_keys = {"dp_degree", "mp_degree", "pp_degree",
                   "sharding_degree", "cp_degree", "ep_degree"}
    # non-degree keys the reference accepts ride along untouched
    # ("order", nested "*_configs" blocks); anything else is probably a
    # typo'd degree — warn, don't break reference-style configs
    passthrough = {"order", "dp_configs", "mp_configs", "pp_configs",
                   "sharding_configs", "cp_configs", "ep_configs"}
    unknown = set(hc) - degree_keys - passthrough
    if unknown:
        import warnings

        warnings.warn(
            f"hybrid_configs keys {sorted(unknown)} are not understood "
            f"and will be ignored (degrees: {sorted(degree_keys)})")
    # sorted: every rank must build `degrees` in the same order — set
    # order varies with the hash seed across processes (tpu-lint TPU006)
    degrees = {k: int(hc.get(k, 1)) for k in sorted(degree_keys)}
    bad = {k: v for k, v in degrees.items() if v < 1}
    if bad:
        raise ValueError(f"hybrid_configs degrees must be >= 1: {bad}")
    import math

    import jax

    need = math.prod(degrees.values())
    ndev = len(jax.devices())
    if need > ndev or ndev % need != 0:
        asked = " x ".join(f"{k.split('_')[0]}={v}"
                           for k, v in sorted(degrees.items())
                           if v > 1) or "1"
        raise ValueError(
            f"hybrid_configs asks for {asked} = {need} devices, but "
            f"{ndev} are visible — the degree product must divide the "
            "device count. (The reference requires nranks == degree "
            "product exactly; this build additionally supports a "
            "prefix mesh over the first `product` devices, so any "
            "divisor of the device count is accepted.)")
    hcg = HybridCommunicateGroup(
        dp=degrees["dp_degree"],
        mp=degrees["mp_degree"],
        pp=degrees["pp_degree"],
        sharding=degrees["sharding_degree"],
        cp=degrees["cp_degree"],
        ep=degrees["ep_degree"],
    )
    set_hybrid_communicate_group(hcg)
    _fleet_state["initialized"] = True
    _fleet_state["strategy"] = strategy
    return hcg


def is_initialized():
    return _fleet_state["initialized"]


def get_hybrid_communicate_group_():
    return get_hybrid_communicate_group()


def distributed_model(model):
    """Analog of fleet.distributed_model (model.py:30). Under SPMD there
    is nothing to wrap for dp/mp/sharding — shardings are annotations and
    the collectives compile into the step — so the model is returned
    as-is; pipeline wrapping (PipelineLayer) is explicit, as in the
    reference."""
    return model


def distributed_optimizer(optimizer, strategy=None):
    """Analog of fleet.distributed_optimizer (optimizer.py:65): returns
    the optimizer unchanged — grad synchronization is part of the
    compiled SPMD step (see spmd.DistributedTrainStep), which subsumes
    HybridParallelOptimizer's fused_allreduce_gradients."""
    return optimizer


def get_strategy():
    return _fleet_state["strategy"] or DistributedStrategy()


# re-exports for parity with fleet.meta_parallel / fleet.layers.mpu
meta_parallel = _mpu
layers = _mpu
