"""DistributedStrategy — analog of
python/paddle/distributed/fleet/base/distributed_strategy.py (protobuf-
backed, hybrid_configs at :1651). Plain-dict config here; serializable
via to_dict/from_dict (the proto is an implementation detail we drop).
"""
from __future__ import annotations

import copy
import json


_DEFAULTS = {
    "amp": False,
    "amp_configs": {
        "init_loss_scaling": 32768.0,
        "use_pure_bf16": True,
        "custom_white_list": [],
        "custom_black_list": [],
    },
    "recompute": False,
    "recompute_configs": {"checkpoints": []},
    "sharding": False,
    "sharding_configs": {"stage": 1, "degree": 1, "offload": False},
    "pipeline": False,
    "pipeline_configs": {"accumulate_steps": 1, "micro_batch_size": 1,
                         "schedule_mode": "1F1B"},
    "hybrid_configs": {
        "dp_degree": 1,
        "mp_degree": 1,
        "pp_degree": 1,
        "sharding_degree": 1,
        "cp_degree": 1,
        "ep_degree": 1,
    },
    "gradient_merge": False,
    "gradient_merge_configs": {"k_steps": 1, "avg": True},
    "lamb": False,
    "localsgd": False,
    "dgc": False,
    "gradient_scale_configs": {"scale_strategy": "avg"},
    "find_unused_parameters": False,
    "fuse_all_reduce_ops": True,
    "fuse_grad_size_in_MB": 32,
}


class DistributedStrategy:
    def __init__(self):
        self._conf = copy.deepcopy(_DEFAULTS)

    def __getattr__(self, name):
        conf = object.__getattribute__(self, "_conf")
        if name in conf:
            return conf[name]
        raise AttributeError(name)

    def __setattr__(self, name, value):
        if name == "_conf":
            object.__setattr__(self, name, value)
            return
        if name in self._conf:
            if name.endswith("_configs") and isinstance(value, dict):
                self._conf[name].update(value)
            else:
                self._conf[name] = value
        else:
            object.__setattr__(self, name, value)

    def to_dict(self):
        return copy.deepcopy(self._conf)

    def from_dict(self, d):
        for k, v in d.items():
            setattr(self, k, v)
        return self

    def save_to_prototxt(self, path):  # reference-API name; JSON payload
        with open(path, "w") as f:
            json.dump(self._conf, f, indent=2)

    def load_from_prototxt(self, path):
        with open(path) as f:
            self.from_dict(json.load(f))

    def __repr__(self):
        return f"DistributedStrategy({json.dumps(self._conf, indent=1)})"
