from .auto_cast import BLACK_LIST, WHITE_LIST, amp_state, auto_cast
from .grad_scaler import GradScaler

autocast = auto_cast

__all__ = ["auto_cast", "autocast", "GradScaler", "WHITE_LIST", "BLACK_LIST"]
