"""Autograd-correct dtype casts used by AMP autocast."""
from __future__ import annotations

import jax.numpy as jnp


def cast_tensor_list(inputs, to_dtype):
    """Cast floating Tensors to to_dtype via a tracked op so gradients
    flow back in the original dtype (the cast's VJP casts the cotangent
    back — exactly what jax.vjp of astype gives us)."""
    from paddle_tpu.core.tensor import Tensor
    from paddle_tpu.ops.dispatch import apply

    out = []
    for t in inputs:
        if (
            isinstance(t, Tensor)
            and jnp.issubdtype(t._array.dtype, jnp.floating)
            and t._array.dtype != to_dtype
        ):
            out.append(apply("amp_cast", lambda a: a.astype(to_dtype), t))
        else:
            out.append(t)
    return out
