"""AMP autocast — analog of python/paddle/amp/auto_cast.py (white/black
lists at amp/auto_cast.py:76-93) and the eager insertion point
eager_amp_auto_cast.h.

TPU-first policy: bf16 is the default low precision (no loss scaling
needed); fp16 kept only for API parity. Casting happens at op dispatch
(ops/dispatch.py) and compiles into the surrounding XLA computation under
jit — zero eager overhead when disabled.
"""
from __future__ import annotations

import contextlib

from paddle_tpu.core import dtype as dtypes

# The white list (MXU ops that benefit from low precision) and black
# list (numerically sensitive, pinned fp32) are AUTHORED in the op
# schema — ops/ops.yaml `amp:` fields + `amp_extra` for dispatch-only
# names — and loaded here (the PHI-yaml-is-authoritative design,
# SURVEY §2 item 6). Fallbacks cover a broken/absent schema file.
_FALLBACK_WHITE = {
    "matmul", "linear", "conv2d", "conv1d", "conv3d", "conv2d_transpose",
    "mm", "bmm", "einsum", "sdpa", "resnet_stem_s2d",
}
_FALLBACK_BLACK = {
    "exp", "log", "log2", "log10", "log1p", "pow", "square", "sqrt", "rsqrt",
    "softmax", "log_softmax", "softmax_ce", "softmax_ce_soft", "cross_entropy",
    "layer_norm", "batch_norm", "group_norm", "instance_norm", "rms_norm",
    "mse_loss", "l1_loss", "bce_loss", "bce_logits", "kl_div", "sum", "mean",
    "norm", "logsumexp", "cumsum",
}

try:
    from paddle_tpu.ops import registry as _registry

    WHITE_LIST = set(_registry.amp_white())
    BLACK_LIST = set(_registry.amp_black())
except Exception as _e:  # schema unreadable: keep amp functional, LOUDLY
    import warnings

    warnings.warn(
        f"ops.yaml schema unreadable ({_e!r}); AMP falling back to "
        "built-in white/black lists — fix the schema, the fallback may "
        "lag the authored policy")
    WHITE_LIST = set(_FALLBACK_WHITE)
    BLACK_LIST = set(_FALLBACK_BLACK)

_state = {"enabled": False, "dtype": "bfloat16", "level": "O1"}


def amp_state():
    return _state


@contextlib.contextmanager
def auto_cast(enable=True, custom_white_list=None, custom_black_list=None,
              level="O1", dtype="bfloat16"):
    """paddle.amp.auto_cast analog."""
    prev = dict(_state)
    prev_extra = (_state.get("extra_white"), _state.get("extra_black"))
    _state.update(
        enabled=bool(enable),
        dtype=dtypes.canonical_name(dtype),
        level=level,
        extra_white=frozenset(custom_white_list or ()),
        extra_black=frozenset(custom_black_list or ()),
    )
    try:
        yield
    finally:
        _state.clear()
        _state.update(prev)
        if prev_extra[0] is not None:
            _state["extra_white"], _state["extra_black"] = prev_extra


def maybe_autocast(op_name, inputs, policy=None):
    """Called from ops.dispatch.apply before running an op. Casts floating
    inputs to the amp dtype for white-list ops, to fp32 for black-list ops
    (O1); casts everything low-precision except blacklist in O2."""
    if not _state["enabled"] or op_name == "amp_cast":
        return inputs
    import jax.numpy as jnp  # noqa: F401

    white = WHITE_LIST | _state.get("extra_white", frozenset())
    black = BLACK_LIST | _state.get("extra_black", frozenset())
    low = dtypes.to_jax(_state["dtype"])
    level = _state["level"]

    # Tracked casts (ops, not raw astype) keep autograd correct.
    from .cast_helper import cast_tensor_list

    if op_name in black:
        return cast_tensor_list(inputs, jnp.float32)
    if op_name in white or level == "O2":
        return cast_tensor_list(inputs, low)
    return inputs
