"""GradScaler — analog of python/paddle/amp/grad_scaler.py (1218 LoC).

On TPU the default AMP dtype is bf16, which needs no loss scaling; the
scaler then degenerates to a passthrough (enable=False path). The dynamic
scaling logic is kept for fp16 parity.
"""
from __future__ import annotations

import jax.numpy as jnp

from paddle_tpu.core.tensor import Tensor


class GradScaler:
    def __init__(self, enable=True, init_loss_scaling=2.0 ** 15,
                 incr_ratio=2.0, decr_ratio=0.5,
                 incr_every_n_steps=1000, decr_every_n_nan_or_inf=2,
                 use_dynamic_loss_scaling=True):
        self._enable = enable
        self._scale = float(init_loss_scaling)
        self._incr_ratio = incr_ratio
        self._decr_ratio = decr_ratio
        self._incr_every_n_steps = incr_every_n_steps
        self._decr_every_n = decr_every_n_nan_or_inf
        self._dynamic = use_dynamic_loss_scaling
        self._good_steps = 0
        self._bad_steps = 0
        self._found_inf = False
        self._unscaled = False

    def scale(self, var: Tensor) -> Tensor:
        if not self._enable:
            return var
        return var * self._scale

    def unscale_(self, optimizer):
        if not self._enable or self._unscaled:
            return
        self._unscaled = True
        inv = 1.0 / self._scale
        found_inf = False
        for p in optimizer._parameter_list:
            if p.grad is not None:
                g = p.grad._array * inv
                if not bool(jnp.all(jnp.isfinite(g))):
                    found_inf = True
                p.grad = Tensor._wrap(g)
        self._found_inf = found_inf

    def step(self, optimizer):
        if not self._enable:
            optimizer.step()
            return
        self.unscale_(optimizer)
        if not self._found_inf:
            optimizer.step()
        self.update()

    def minimize(self, optimizer, scaled_loss):
        self.step(optimizer)

    def update(self):
        self._unscaled = False
        if not (self._enable and self._dynamic):
            return
        if self._found_inf:
            self._bad_steps += 1
            self._good_steps = 0
            if self._bad_steps >= self._decr_every_n:
                self._scale = max(self._scale * self._decr_ratio, 1.0)
                self._bad_steps = 0
        else:
            self._good_steps += 1
            self._bad_steps = 0
            if self._good_steps >= self._incr_every_n_steps:
                self._scale *= self._incr_ratio
                self._good_steps = 0
        self._found_inf = False

    def is_enable(self):
        return self._enable

    def get_scale(self):
        return self._scale

    def state_dict(self):
        return {
            "scale": self._scale,
            "good_steps": self._good_steps,
            "bad_steps": self._bad_steps,
        }

    def load_state_dict(self, state):
        self._scale = state["scale"]
        self._good_steps = state["good_steps"]
        self._bad_steps = state["bad_steps"]
