"""paddle.distribution analog (python/paddle/distribution/): probability
distributions with sample/rsample/log_prob/entropy plus a kl_divergence
registry.

TPU-native: densities are jnp math dispatched through the op layer (so
log_prob is differentiable on the tape and under jit), sampling draws
from the framework PRNG (core.random), and reparameterized rsample keeps
gradients flowing on TPU-compiled training steps.
"""
from .distributions import (Bernoulli, Beta, Categorical, Distribution,
                            Exponential, Gumbel, Laplace, LogNormal,
                            Multinomial, Normal, Uniform)
from .kl import kl_divergence, register_kl

__all__ = ["Distribution", "Normal", "Uniform", "Categorical", "Bernoulli",
           "Beta", "Exponential", "Laplace", "Gumbel", "LogNormal",
           "Multinomial", "kl_divergence", "register_kl"]
