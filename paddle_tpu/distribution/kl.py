"""kl_divergence + register_kl — analog of
python/paddle/distribution/kl.py (dispatch by distribution types)."""
from __future__ import annotations

import jax.numpy as jnp

from paddle_tpu.core.tensor import Tensor

_REGISTRY = {}

__all__ = ["kl_divergence", "register_kl"]


def register_kl(p_cls, q_cls):
    def deco(fn):
        _REGISTRY[(p_cls, q_cls)] = fn
        return fn
    return deco


def kl_divergence(p, q):
    for (pc, qc), fn in _REGISTRY.items():
        if isinstance(p, pc) and isinstance(q, qc):
            return fn(p, q)
    raise NotImplementedError(
        f"no KL registered for ({type(p).__name__}, {type(q).__name__})")


def _t(a):
    return Tensor._wrap(a)


from paddle_tpu.ops.dispatch import apply  # noqa: E402
from .distributions import (Bernoulli, Categorical, Exponential,  # noqa
                            Laplace, Normal, Uniform)

# every rule dispatches through apply() on the distributions' KEPT
# parameter Tensors (_p), so a KL regularizer (e.g. a VAE's) actually
# trains the parameters instead of silently detaching


@register_kl(Normal, Normal)
def _kl_normal(p, q):
    def fn(pl, ps, ql, qs):
        vr = (ps / qs) ** 2
        return 0.5 * (vr + ((pl - ql) / qs) ** 2 - 1 - jnp.log(vr))
    return apply("kl_normal_normal", fn, p._p("loc"), p._p("scale"),
                 q._p("loc"), q._p("scale"))


@register_kl(Uniform, Uniform)
def _kl_uniform(p, q):
    inside = (q.low <= p.low) & (p.high <= q.high)
    kl = jnp.log((q.high - q.low) / (p.high - p.low))
    return _t(jnp.where(inside, kl, jnp.inf))


@register_kl(Categorical, Categorical)
def _kl_categorical(p, q):
    pn, qn = p._norm_logits_fn(), q._norm_logits_fn()

    def fn(ps, qs):
        pl, ql = pn(ps), qn(qs)
        return (jnp.exp(pl) * (pl - ql)).sum(-1)
    return apply("kl_categorical", fn, p._src(), q._src())


@register_kl(Bernoulli, Bernoulli)
def _kl_bernoulli(p, q):
    def fn(pa, qa):
        a = jnp.clip(pa, 1e-7, 1 - 1e-7)
        b = jnp.clip(qa, 1e-7, 1 - 1e-7)
        return a * (jnp.log(a) - jnp.log(b)) \
            + (1 - a) * (jnp.log1p(-a) - jnp.log1p(-b))
    return apply("kl_bernoulli", fn, p._p("probs_"), q._p("probs_"))


@register_kl(Exponential, Exponential)
def _kl_exponential(p, q):
    def fn(pr, qr):
        return jnp.log(pr) - jnp.log(qr) + qr / pr - 1.0
    return apply("kl_exponential", fn, p._p("rate"), q._p("rate"))
