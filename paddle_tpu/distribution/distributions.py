"""Distribution classes — analogs of python/paddle/distribution/
(distribution.py Distribution base, normal.py, uniform.py,
categorical.py, bernoulli.py, beta.py, ...). Math is jnp through the op
layer; samples come from the framework PRNG so paddle.seed governs them.
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp

from paddle_tpu.core import random as random_mod
from paddle_tpu.core.tensor import Tensor
from paddle_tpu.ops.dispatch import apply, apply_nograd

__all__ = ["Distribution", "Normal", "Uniform", "Categorical", "Bernoulli",
           "Beta", "Exponential", "Laplace", "Gumbel", "LogNormal",
           "Multinomial"]


def _arr(x, dtype=jnp.float32):
    if isinstance(x, Tensor):
        return x._array.astype(dtype)
    return jnp.asarray(x, dtype)


def _t(a):
    return Tensor._wrap(a)


def _shape(sample_shape, base_shape):
    return tuple(sample_shape) + tuple(base_shape)


class Distribution:
    """Base (distribution.py:Distribution). Subclasses define
    _batch_shape and the math; sample() draws via the framework PRNG.
    Constructors keep the ORIGINAL parameter Tensors (_keep/_p) so
    log_prob/rsample/kl_divergence gradients reach them."""

    def __init__(self, batch_shape=()):
        self._batch_shape = tuple(batch_shape)

    def _keep(self, **named):
        self._param_t = {k: v for k, v in named.items()
                         if isinstance(v, Tensor)}

    def _p(self, name):
        t = getattr(self, "_param_t", {}).get(name)
        return t if t is not None else _t(getattr(self, name))

    @property
    def batch_shape(self):
        return self._batch_shape

    def sample(self, shape=()):
        raise NotImplementedError

    def rsample(self, shape=()):
        raise NotImplementedError

    def log_prob(self, value):
        raise NotImplementedError

    def prob(self, value):
        return apply("dist_prob", jnp.exp, self.log_prob(value))

    def entropy(self):
        raise NotImplementedError

    def kl_divergence(self, other):
        from .kl import kl_divergence

        return kl_divergence(self, other)


class Normal(Distribution):
    """normal.py:Normal — loc/scale gaussian."""

    def __init__(self, loc, scale, name=None):
        self.loc = _arr(loc)
        self.scale = _arr(scale)
        super().__init__(jnp.broadcast_shapes(self.loc.shape,
                                              self.scale.shape))
        self._keep(loc=loc, scale=scale)

    @property
    def mean(self):
        return _t(jnp.broadcast_to(self.loc, self.batch_shape))

    @property
    def variance(self):
        return _t(jnp.broadcast_to(self.scale ** 2, self.batch_shape))

    def sample(self, shape=()):
        eps = jax.random.normal(random_mod.next_key(),
                                _shape(shape, self.batch_shape))
        return _t(self.loc + self.scale * eps)

    def rsample(self, shape=()):
        # reparameterized: gradient flows to loc/scale through the tape
        eps = jax.random.normal(random_mod.next_key(),
                                _shape(shape, self.batch_shape))
        return apply("normal_rsample", lambda l, s: l + s * eps,
                     self._p("loc"), self._p("scale"))

    def log_prob(self, value):
        def fn(v, loc, scale):
            var = scale ** 2
            return -((v - loc) ** 2) / (2 * var) - jnp.log(scale) \
                - 0.5 * math.log(2 * math.pi)
        v = value if isinstance(value, Tensor) else _t(_arr(value))
        return apply("normal_log_prob", fn, v, self._p("loc"),
                     self._p("scale"))

    def entropy(self):
        return _t(jnp.broadcast_to(
            0.5 + 0.5 * math.log(2 * math.pi) + jnp.log(self.scale),
            self.batch_shape))


class LogNormal(Distribution):
    def __init__(self, loc, scale):
        self.base = Normal(loc, scale)
        super().__init__(self.base.batch_shape)

    def sample(self, shape=()):
        return apply("lognormal_sample", jnp.exp, self.base.sample(shape))

    def log_prob(self, value):
        v = _arr(value)
        lp = self.base.log_prob(_t(jnp.log(v)))  # tape-tracked
        return apply("lognormal_log_prob", lambda a: a - jnp.log(v), lp)

    def entropy(self):
        return apply("lognormal_entropy",
                     lambda e, l: e + l,
                     self.base.entropy(), self.base._p("loc"))


class Uniform(Distribution):
    """uniform.py:Uniform on [low, high)."""

    def __init__(self, low, high, name=None):
        self.low = _arr(low)
        self.high = _arr(high)
        super().__init__(jnp.broadcast_shapes(self.low.shape,
                                              self.high.shape))

    def sample(self, shape=()):
        u = jax.random.uniform(random_mod.next_key(),
                               _shape(shape, self.batch_shape))
        return _t(self.low + (self.high - self.low) * u)

    rsample = sample

    def log_prob(self, value):
        v = _arr(value)
        inside = (v >= self.low) & (v < self.high)
        lp = jnp.where(inside, -jnp.log(self.high - self.low), -jnp.inf)
        return _t(lp)

    def entropy(self):
        return _t(jnp.broadcast_to(jnp.log(self.high - self.low),
                                   self.batch_shape))


class Categorical(Distribution):
    """categorical.py:Categorical over the LAST axis of logits."""

    def __init__(self, logits=None, probs=None, name=None):
        if (logits is None) == (probs is None):
            raise ValueError("pass exactly one of logits/probs")
        if probs is not None:
            p = _arr(probs)
            self.logits = jnp.log(p / p.sum(-1, keepdims=True))
        else:
            lg = _arr(logits)
            self.logits = lg - jax.nn.logsumexp(lg, -1, keepdims=True)
        self._src_kind = "probs" if probs is not None else "logits"
        super().__init__(self.logits.shape[:-1])
        self._keep(_src=probs if probs is not None else logits)

    def _norm_logits_fn(self):
        """(src_array) -> normalized log-probs, in-graph (for tracked
        gradient paths like kl_divergence)."""
        if self._src_kind == "probs":
            return lambda p: jnp.log(p / p.sum(-1, keepdims=True))
        return lambda lg: lg - jax.nn.logsumexp(lg, -1, keepdims=True)

    def _src(self):
        t = getattr(self, "_param_t", {}).get("_src")
        return t if t is not None else _t(self.logits) \
            if self._src_kind == "logits" else _t(jnp.exp(self.logits))

    @property
    def probs(self):
        return _t(jnp.exp(self.logits))

    def sample(self, shape=()):
        return _t(jax.random.categorical(
            random_mod.next_key(), self.logits,
            shape=_shape(shape, self.batch_shape)))

    def log_prob(self, value):
        idx = _arr(value, jnp.int32)
        return _t(jnp.take_along_axis(
            self.logits, idx[..., None], axis=-1)[..., 0])

    def entropy(self):
        return _t(-(jnp.exp(self.logits) * self.logits).sum(-1))


class Bernoulli(Distribution):
    def __init__(self, probs, name=None):
        self.probs_ = _arr(probs)
        super().__init__(self.probs_.shape)
        self._keep(probs_=probs)

    @property
    def mean(self):
        return _t(self.probs_)

    @property
    def variance(self):
        return _t(self.probs_ * (1 - self.probs_))

    def sample(self, shape=()):
        u = jax.random.uniform(random_mod.next_key(),
                               _shape(shape, self.batch_shape))
        return _t((u < self.probs_).astype(jnp.float32))

    def log_prob(self, value):
        v = _arr(value)
        p = jnp.clip(self.probs_, 1e-7, 1 - 1e-7)
        return _t(v * jnp.log(p) + (1 - v) * jnp.log1p(-p))

    def entropy(self):
        p = jnp.clip(self.probs_, 1e-7, 1 - 1e-7)
        return _t(-(p * jnp.log(p) + (1 - p) * jnp.log1p(-p)))


class Multinomial(Distribution):
    def __init__(self, total_count, probs):
        self.total_count = int(total_count)
        p = _arr(probs)
        self.probs_ = p / p.sum(-1, keepdims=True)
        super().__init__(self.probs_.shape[:-1])

    def sample(self, shape=()):
        logits = jnp.log(self.probs_)
        draws = jax.random.categorical(
            random_mod.next_key(), logits,
            shape=(self.total_count,) + _shape(shape, self.batch_shape))
        k = self.probs_.shape[-1]
        return _t(jax.nn.one_hot(draws, k).sum(0))

    def log_prob(self, value):
        v = _arr(value)
        logp = (v * jnp.log(self.probs_)).sum(-1)
        coeff = jax.scipy.special.gammaln(self.total_count + 1.0) \
            - jax.scipy.special.gammaln(v + 1.0).sum(-1)
        return _t(coeff + logp)


class Beta(Distribution):
    def __init__(self, alpha, beta):
        self.alpha = _arr(alpha)
        self.beta = _arr(beta)
        super().__init__(jnp.broadcast_shapes(self.alpha.shape,
                                              self.beta.shape))

    @property
    def mean(self):
        return _t(self.alpha / (self.alpha + self.beta))

    def sample(self, shape=()):
        return _t(jax.random.beta(random_mod.next_key(), self.alpha,
                                  self.beta,
                                  _shape(shape, self.batch_shape)))

    def log_prob(self, value):
        v = _arr(value)
        lbeta = (jax.scipy.special.gammaln(self.alpha)
                 + jax.scipy.special.gammaln(self.beta)
                 - jax.scipy.special.gammaln(self.alpha + self.beta))
        return _t((self.alpha - 1) * jnp.log(v)
                  + (self.beta - 1) * jnp.log1p(-v) - lbeta)


class Exponential(Distribution):
    def __init__(self, rate):
        self.rate = _arr(rate)
        super().__init__(self.rate.shape)
        self._keep(rate=rate)

    def sample(self, shape=()):
        e = jax.random.exponential(random_mod.next_key(),
                                   _shape(shape, self.batch_shape))
        return _t(e / self.rate)

    def log_prob(self, value):
        v = _arr(value)
        return _t(jnp.log(self.rate) - self.rate * v)

    def entropy(self):
        return _t(1.0 - jnp.log(self.rate)
                  + jnp.zeros(self.batch_shape, jnp.float32))


class Laplace(Distribution):
    def __init__(self, loc, scale):
        self.loc = _arr(loc)
        self.scale = _arr(scale)
        super().__init__(jnp.broadcast_shapes(self.loc.shape,
                                              self.scale.shape))

    def sample(self, shape=()):
        l = jax.random.laplace(random_mod.next_key(),
                               _shape(shape, self.batch_shape))
        return _t(self.loc + self.scale * l)

    def log_prob(self, value):
        v = _arr(value)
        return _t(-jnp.abs(v - self.loc) / self.scale
                  - jnp.log(2 * self.scale))


class Gumbel(Distribution):
    def __init__(self, loc, scale):
        self.loc = _arr(loc)
        self.scale = _arr(scale)
        super().__init__(jnp.broadcast_shapes(self.loc.shape,
                                              self.scale.shape))

    def sample(self, shape=()):
        g = jax.random.gumbel(random_mod.next_key(),
                              _shape(shape, self.batch_shape))
        return _t(self.loc + self.scale * g)

    def log_prob(self, value):
        z = (_arr(value) - self.loc) / self.scale
        return _t(-(z + jnp.exp(-z)) - jnp.log(self.scale))
