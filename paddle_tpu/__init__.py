"""paddle_tpu — a TPU-native deep-learning framework with the capability
surface of PaddlePaddle (reference: linsheng011/Paddle, surveyed in
/root/repo/SURVEY.md). Eager define-by-run tensors over jax.Array/PJRT,
whole-function jit (the to_static analog), and mesh-based hybrid
parallelism over ICI/DCN. Top-level namespace mirrors `paddle.*`
(python/paddle/__init__.py of the reference).
"""
from __future__ import annotations

__version__ = "0.1.0"

from paddle_tpu.core import (
    Parameter,
    Tensor,
    enable_grad,
    get_default_dtype,
    get_device,
    grad,
    no_grad,
    seed,
    set_default_dtype,
    set_device,
)
from paddle_tpu.core.random import get_rng_state, set_rng_state
from paddle_tpu import ops
from paddle_tpu.ops.creation import (
    arange,
    complex,
    diagflat,
    logspace,
    vander,
    diag,
    empty,
    empty_like,
    eye,
    full,
    full_like,
    linspace,
    meshgrid,
    one_hot,
    ones,
    ones_like,
    to_tensor,
    tril,
    triu,
    zeros,
    zeros_like,
)
from paddle_tpu.ops.math import (
    abs, add, atan2, cast, ceil, clip, cos, cosh, divide, equal, erf, exp,
    floor, floor_divide, greater_equal, greater_than, increment, isfinite,
    isinf, isnan, less_equal, less_than, lerp, log, log1p, log2, log10,
    logical_and, logical_not, logical_or, logical_xor, maximum, minimum, mod,
    multiply, multiplex, nan_to_num, neg, not_equal, pow, reciprocal, round,
    rsqrt, scale, sign, sin, sinh, sqrt, square, subtract, tan, tanh, trunc,
    where, addmm, erfinv, expm1, fmax, fmin,
    frac, sinc, signbit, digamma, lgamma, i0, angle, real, imag, conj,
    sgn, logit, polygamma, copysign, nextafter, heaviside, hypot,
    logaddexp, fmod, remainder, true_divide, float_power, isclose,
    allclose, equal_all, multiply_,
)
from paddle_tpu.ops.manipulation import (
    broadcast_to, chunk, clone, concat, crop, expand, expand_as, flatten,
    flip, gather, gather_nd, index_select, masked_select, moveaxis, numel,
    put_along_axis, repeat_interleave, reshape, roll, rot90, scatter, slice,
    split, squeeze, stack, strided_slice, take_along_axis, tile, transpose,
    unbind, unsqueeze, unstack, as_complex, as_real, tensordot,
    swapaxes, swapdims, vsplit, hsplit, dsplit, take, as_strided, diff,
    scatter_nd, searchsorted, bucketize,
)
from paddle_tpu.ops.reduction import (
    all, amax, amin, any, argmax, argmin, argsort, bincount, count_nonzero,
    cumprod, cumsum, kthvalue, logsumexp, max, mean, median, min, mode,
    nanmean, nansum, nonzero, prod, quantile, sort, std, sum, topk, unique,
    var, nanmedian, trapezoid,
)
from paddle_tpu.ops.linalg import (
    bmm, cross, det, diagonal, dist, dot, eigh, histogram, inner, inverse,
    kron, matmul, mm, mv, norm, outer, pinv, qr, slogdet, solve, svd, t,
    trace, einsum, baddbmm, renorm, corrcoef, cov,
)
from paddle_tpu.ops.random_ops import (
    bernoulli, multinomial, normal, poisson, rand, randint, randint_like,
    randn, randperm, shuffle, standard_normal, uniform,
)

from paddle_tpu import autograd  # noqa: E402
from paddle_tpu.core.pylayer import PyLayer  # noqa: E402
from paddle_tpu import amp  # noqa: E402
from paddle_tpu import nn  # noqa: E402
from paddle_tpu import optimizer  # noqa: E402
from paddle_tpu import io  # noqa: E402
from paddle_tpu import jit  # noqa: E402
from paddle_tpu import distributed  # noqa: E402
from paddle_tpu.framework.io import load, save  # noqa: E402
from paddle_tpu.framework.flags import get_flags, set_flags  # noqa: E402
from paddle_tpu import device  # noqa: E402
from paddle_tpu import vision  # noqa: E402
from paddle_tpu import metric  # noqa: E402
from paddle_tpu import profiler  # noqa: E402
from paddle_tpu import hapi  # noqa: E402
from paddle_tpu import distribution  # noqa: E402
from paddle_tpu import sparse  # noqa: E402
from paddle_tpu import quantization  # noqa: E402
from paddle_tpu import text  # noqa: E402
from paddle_tpu import audio  # noqa: E402
from paddle_tpu.hapi import Model, summary  # noqa: E402
from paddle_tpu import static  # noqa: E402
from paddle_tpu import incubate  # noqa: E402
from paddle_tpu import linalg  # noqa: E402
from paddle_tpu import fft  # noqa: E402
from paddle_tpu import utils  # noqa: E402
from paddle_tpu import onnx  # noqa: E402
from paddle_tpu import inference  # noqa: E402
from paddle_tpu.hapi.dynamic_flops import flops  # noqa: E402
from paddle_tpu.hapi import callbacks  # noqa: E402

# paddle-style helpers
def is_grad_enabled():
    from paddle_tpu.core.autograd import is_grad_enabled as _f

    return _f()


def in_dynamic_mode():
    return True


disable_static = lambda: None
enable_static = lambda: None

bfloat16 = "bfloat16"
float16 = "float16"
float32 = "float32"
float64 = "float64"
int8 = "int8"
int16 = "int16"
int32 = "int32"
int64 = "int64"
uint8 = "uint8"
bool = "bool"
complex64 = "complex64"
