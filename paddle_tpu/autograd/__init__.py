"""paddle.autograd namespace — mirrors python/paddle/autograd/__init__.py:
backward helpers, functional grad, and user-defined PyLayer ops."""
from paddle_tpu.core.autograd import (  # noqa: F401
    enable_grad,
    grad,
    is_grad_enabled,
    no_grad,
    run_backward,
    set_grad_enabled,
)
from paddle_tpu.core.pylayer import PyLayer, PyLayerContext  # noqa: F401


def backward(tensors, grad_tensors=None, retain_graph=False):
    """Analog of paddle.autograd.backward."""
    run_backward(tensors, grad_tensors, retain_graph=retain_graph)


__all__ = ["PyLayer", "PyLayerContext", "backward", "grad", "no_grad",
           "enable_grad", "set_grad_enabled", "is_grad_enabled"]
