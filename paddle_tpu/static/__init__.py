"""paddle.static analog — the subset that survives the TPU-native
design.

The reference's static-graph stack (Program/Executor/feed-fetch,
python/paddle/static/) exists because its eager mode couldn't compile;
here EVERY compiled path goes through jit.to_static/TrainStep, so the
Program surface is deliberately absent. What remains meaningful:
InputSpec (the AOT signature contract — shared with jit), and
device_guard/name_scope as no-op context managers for source
compatibility (placement is mesh-driven; naming is for humans).
"""
from __future__ import annotations

import contextlib

from paddle_tpu.jit.api import InputSpec

__all__ = ["InputSpec", "device_guard", "name_scope"]


@contextlib.contextmanager
def device_guard(device=None):
    """No-op: placement is controlled by the mesh/shardings, not
    per-op guards. Kept so reference code imports run."""
    yield


@contextlib.contextmanager
def name_scope(prefix=None):
    yield
