"""paddle.inference analog — the deployment/serving API tier.

Reference analogs:
- `Config` / `create_predictor` / `Predictor.run`:
  paddle/fluid/inference/api/analysis_predictor.h:95 (AnalysisPredictor)
  + paddle_inference_api.h. Here the "analysis pass pipeline" is XLA
  compilation of the saved exported program (jit.load), and
  mixed-precision convert is the artifact's convert="bfloat16" mode.
- `DistModel`: distributed/fleet_executor/dist_model.cc — multi-rank
  pipelined serving. TPU-native: ONE SPMD program over a device mesh
  (dp batch sharding × mp weight sharding; a PipelineLayer model brings
  its own pp stages), with host-side micro-batch streaming that rides
  jax's async dispatch for overlap instead of brpc interceptor actors.
"""
from __future__ import annotations

from typing import Optional, Sequence

import numpy as np

from paddle_tpu.core.tensor import Tensor
from paddle_tpu.inference.engine import (PRIORITY_CLASSES,
                                         GenerationEngine, PagedKVCache,
                                         Request, prefix_key)
from paddle_tpu.inference.fleet import REPLICA_ROLES, ServingFleet
from paddle_tpu.inference.sampling import SamplingParams
from paddle_tpu.inference.speculative import GptDrafter, NgramDrafter

__all__ = ["Config", "Predictor", "create_predictor", "DistModel",
           "DistModelConfig", "GenerationEngine", "PagedKVCache",
           "Request", "PRIORITY_CLASSES", "NgramDrafter", "GptDrafter",
           "ServingFleet", "REPLICA_ROLES", "prefix_key",
           "SamplingParams"]


def _stream_micro_batches(forward, ins, mbs, pad_to=1):
    """Shared serving loop: slice `ins` (list of batch-major arrays)
    into micro-batches of `mbs`, pad each chunk to a multiple of
    `pad_to` (dp sharding divisibility; padded rows trimmed after
    readback), dispatch ALL chunks (jax async dispatch overlaps host
    prep of chunk i+1 with device compute of chunk i), then gather into
    per-output concatenated arrays."""
    from paddle_tpu.ops.dispatch import unwrap

    def normalize(out):
        outs = out if isinstance(out, (list, tuple)) else [out]
        return [np.asarray(unwrap(o)) for o in outs]

    B = unwrap(ins[0]).shape[0] if ins else 0
    if not ins or B == 0 or ((not mbs or mbs >= B) and pad_to <= 1):
        # fast path: single dispatch, inputs passed through zero-copy
        # (no host round trip for device-resident tensors)
        return normalize(forward(*[unwrap(i) for i in ins]))

    ins = [np.asarray(unwrap(i)) for i in ins]
    mbs = mbs or B
    pending, tails = [], []
    for lo in range(0, B, mbs):
        chunk = [a[lo:lo + mbs] for a in ins]
        n = chunk[0].shape[0]
        pad = (-n) % max(pad_to, 1)
        if pad:
            chunk = [np.concatenate(
                [c, np.repeat(c[-1:], pad, axis=0)], axis=0)
                for c in chunk]
        tails.append(n)
        pending.append(forward(*chunk))
    rows = [[o[:n] for o in normalize(out)]
            for out, n in zip(pending, tails)]
    return [np.concatenate([r[j] for r in rows], axis=0)
            for j in range(len(rows[0]))]


class Config:
    """AnalysisConfig analog. Minimal surface: model path prefix,
    mixed-precision toggle, micro-batching for DistModel."""

    def __init__(self, prog_file: Optional[str] = None,
                 params_file: Optional[str] = None):
        # reference takes (model_dir) or (prog, params); our artifact is
        # a single path prefix — accept it in either slot
        self.model_path = prog_file or params_file
        self._mixed_precision = False
        self._micro_batch_size = None
        self._dp = 1
        self._mp = 1

    def set_model(self, path):
        self.model_path = path

    def enable_mixed_precision(self, enable=True):
        """Require a bf16 program (the convert_to_mixed_precision.cc
        analog). The conversion happens at SAVE time —
        jit.save(..., convert='bfloat16') — because the exported
        program's dtypes are fixed; this flag verifies the artifact was
        saved that way (Predictor raises otherwise)."""
        self._mixed_precision = bool(enable)

    def set_micro_batch_size(self, n: int):
        """Predictor.run streams requests in micro-batches of n."""
        self._micro_batch_size = int(n)

    def set_dist_degrees(self, dp: int = 1, mp: int = 1):
        """Serve the loaded artifact dp x mp on the local mesh: the
        deserialized exported program is called inside an outer pjit
        whose batch inputs are 'dp'-sharded and whose weights are laid
        out by the dist_specs RECORDED AT SAVE TIME (jit.save stores
        each weight's layer-level PartitionSpec, e.g.
        ColumnParallelLinear's P(None, 'mp')); XLA's SPMD partitioner
        then re-partitions the single-device program — the
        dist_model.cc multi-rank-serving analog."""
        self._dp = int(dp)
        self._mp = int(mp)

    # no-op knobs kept for reference-API parity (GPU/IR notions)
    def disable_gpu(self):
        pass

    def switch_ir_optim(self, enable=True):
        pass

    def enable_memory_optim(self, enable=True):
        pass


def _shard_translated(tl, dp, mp=1):
    """Wrap a loaded TranslatedLayer's exported program for dp x mp
    serving: batch inputs shard over 'dp', weights are placed by the
    dist_spec recorded per weight at save time (replicated when none —
    so plain dp serving is the mp=1 special case), and the outer jit
    lets XLA SPMD re-partition the single-device program
    (dist_model.cc resharding analog)."""
    import jax
    from jax.sharding import Mesh, NamedSharding
    from jax.sharding import PartitionSpec as P

    from paddle_tpu.jit.save_load import spec_from_json
    from paddle_tpu.ops.dispatch import unwrap

    devs = jax.devices()
    if dp * mp > len(devs):
        raise ValueError(f"dp*mp={dp * mp} exceeds {len(devs)} devices")
    mesh = Mesh(np.array(devs[:dp * mp]).reshape(dp, mp), ("dp", "mp"))
    specs = tl._meta.get("state_dist_specs") or [None] * len(tl._state_args)
    if mp > 1 and not any(specs):
        names = tl._meta.get("state_names", [])
        raise ValueError(
            "mp>1 serving needs weight dist_specs in the artifact, but "
            f"none were recorded ({len(names)} weights, all replicated) "
            "— save a model whose layers carry mp shardings "
            "(ColumnParallelLinear/RowParallelLinear/"
            "VocabParallelEmbedding) with this version's jit.save")
    def usable(sj):
        """Recorded spec restricted to THIS mesh's axes: a weight
        sharded over an axis the serving mesh doesn't model (MoE 'ep',
        pipeline 'pp') is served replicated along that dim — dp/mp
        serving of such artifacts keeps working."""
        if sj is None:
            return P()
        axes = {"dp", "mp"}

        def dim(e):
            if isinstance(e, list):
                kept = [x for x in e if x in axes]
                return tuple(kept) if kept else None
            return e if e in axes else None

        return spec_from_json([dim(e) for e in sj])

    state_args = []
    for a, sj, name in zip(
            tl._state_args, specs,
            tl._meta.get("state_names", [None] * len(tl._state_args))):
        spec = usable(sj)
        try:
            state_args.append(jax.device_put(
                np.asarray(a), NamedSharding(mesh, spec)))
        except ValueError as e:
            raise ValueError(
                f"weight {name!r} {np.asarray(a).shape} cannot be laid "
                f"out as {spec} on a dp={dp} x mp={mp} mesh ({e})") from e
    bs = NamedSharding(mesh, P("dp"))
    exported = tl._exported

    @jax.jit
    def jitted(state, *xs):
        return exported.call(state, *xs)

    def run_fwd(*xs):
        arrs = [jax.device_put(np.asarray(unwrap(x)), bs) for x in xs]
        return jitted(state_args, *arrs)

    return run_fwd


class Predictor:
    """Loaded single-program predictor (AnalysisPredictor.Run parity:
    list-of-arrays in, list-of-arrays out). With
    Config.set_dist_degrees(dp=N) the saved program serves N-way
    data-parallel (batch sharded, weights replicated)."""

    def __init__(self, config: Config):
        from paddle_tpu.jit.save_load import load

        if not config.model_path:
            raise ValueError("Config has no model path")
        self._layer = load(config.model_path)
        self._config = config
        if config._mixed_precision and \
                self._layer._meta.get("convert") != "bfloat16":
            raise ValueError(
                "enable_mixed_precision() needs a bf16 artifact; re-save "
                "with paddle.jit.save(layer, path, input_spec=[...], "
                "convert='bfloat16')")
        self._forward = self._layer
        if config._dp > 1 or config._mp > 1:
            if self._layer._exported is None:
                raise ValueError("set_dist_degrees needs an executable "
                                 "artifact (saved with input_spec)")
            self._forward = _shard_translated(self._layer, config._dp,
                                              config._mp)

    def get_input_names(self):
        spec = self._layer.input_spec or []
        return [s.get("name") or f"x{i}" for i, s in enumerate(spec)]

    def run(self, inputs: Sequence):
        return _stream_micro_batches(self._forward, list(inputs),
                                     self._config._micro_batch_size,
                                     pad_to=self._config._dp)

    __call__ = run


def create_predictor(config: Config) -> Predictor:
    return Predictor(config)


class DistModelConfig:
    """dist_model.h DistModelConfig analog: where the model is and how
    to lay it out on the mesh."""

    def __init__(self, model_path=None, layer=None, dp: int = 1,
                 mp: int = 1, micro_batch_size: Optional[int] = None):
        self.model_path = model_path
        self.layer = layer
        self.dp = int(dp)
        self.mp = int(mp)
        self.micro_batch_size = micro_batch_size


class DistModel:
    """Mesh-sharded, micro-batch-streaming serving (DistModel::Run
    analog). Takes an nn.Layer (mp layers keep their dist_spec; a
    PipelineLayer brings pp) or a saved-model path.

        cfg = DistModelConfig(layer=model, dp=4, mp=2,
                              micro_batch_size=8)
        dm = DistModel(cfg); dm.init()
        outs = dm.run(inputs)        # streams micro-batches
    """

    def __init__(self, config: DistModelConfig):
        self.config = config
        self._forward = None
        self._hcg = None

    def init(self):
        import jax

        from paddle_tpu.distributed.topology import (
            HybridCommunicateGroup,
            set_hybrid_communicate_group,
        )

        cfg = self.config
        ndev = len(jax.devices())
        need = cfg.dp * cfg.mp
        if need > ndev:
            raise ValueError(f"dp*mp={need} exceeds {ndev} devices")
        self._hcg = HybridCommunicateGroup(dp=cfg.dp, mp=cfg.mp,
                                           devices=jax.devices()[:need])
        set_hybrid_communicate_group(self._hcg)

        if cfg.layer is not None:
            self._init_from_layer(cfg.layer)
        elif cfg.model_path:
            from paddle_tpu.jit.save_load import load

            self._translated = load(cfg.model_path)
            if cfg.dp > 1 or cfg.mp > 1:
                if self._translated._exported is None:
                    raise ValueError(
                        f"dp={cfg.dp} x mp={cfg.mp} serving needs an "
                        "executable artifact (saved with input_spec); "
                        f"{cfg.model_path} is weights-only — serving "
                        "it single-device would silently discard the "
                        "requested layout")
                # saved on 1 device, served dp x mp: the outer pjit
                # reshards using the artifact's recorded dist_specs
                self._forward = _shard_translated(self._translated,
                                                  cfg.dp, cfg.mp)
            else:
                self._forward = self._run_translated
        else:
            raise ValueError("DistModelConfig needs layer or model_path")
        return self

    def _init_from_layer(self, layer):
        import jax
        from jax.sharding import NamedSharding
        from jax.sharding import PartitionSpec as P

        from paddle_tpu.distributed.spmd import param_pspec
        from paddle_tpu.jit.api import bound_state

        layer.eval()
        params = list(layer.parameters())
        buffers = list(layer.buffers()) if hasattr(layer, "buffers") else []
        mesh = self._hcg.mesh
        # placement IS distribution: the shared training-path policy
        # (dist_spec from mp layers, else replicated; stage 0 = no ZeRO)
        for p in params:
            spec = param_pspec(p, self._hcg, sharding_stage=0)
            p._array = jax.device_put(p._array, NamedSharding(mesh, spec))

        from paddle_tpu.ops.dispatch import unwrap

        def pure_fwd(param_arrays, buf_arrays, *xs):
            state = params + buffers
            with bound_state(
                    zip(state, list(param_arrays) + list(buf_arrays)),
                    state):
                out = layer(*[Tensor._wrap(x) for x in xs])
                return jax.tree_util.tree_map(
                    unwrap, out,
                    is_leaf=lambda t: isinstance(t, Tensor))

        jitted = jax.jit(pure_fwd)
        batch_sharding = NamedSharding(
            mesh, P("dp" if self._hcg.axis_size("dp") > 1 else None))

        def run_fwd(*xs):
            arrs = [jax.device_put(np.asarray(unwrap(x)), batch_sharding)
                    for x in xs]
            return jitted([p._array for p in params],
                          [b._array for b in buffers], *arrs)

        self._forward = run_fwd

    def _run_translated(self, *xs):
        import jax

        from paddle_tpu.ops.dispatch import unwrap

        out = self._translated(*xs)
        return jax.tree_util.tree_map(
            unwrap, out, is_leaf=lambda t: isinstance(t, Tensor))

    def run(self, inputs: Sequence):
        """Serve one request batch (the interceptor-actor overlap, minus
        the actors: see _stream_micro_batches)."""
        if self._forward is None:
            self.init()
        ins = list(inputs) if isinstance(inputs, (list, tuple)) \
            else [inputs]
        dp = self._hcg.axis_size("dp") if self._hcg is not None else 1
        return _stream_micro_batches(self._forward, ins,
                                     self.config.micro_batch_size,
                                     pad_to=dp)
