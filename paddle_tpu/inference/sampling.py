"""Per-request sampling parameters for the generation engine — the
host half of the probabilistic serving subsystem (the device half is
`paddle_tpu/ops/sampling.py`).

`SamplingParams(temperature, top_k, top_p, seed)` rides a request
through `GenerationEngine.add_request` / `ServingFleet.add_request`
(and the disaggregated `adopt_request` handoff) and is carried PER
SLOT through the fixed-shape compiled decode and verify steps as
traced per-row arrays — params are data, never trace keys, so
`decode_traces == 1` holds per (backend, K, mp, kv_dtype) for ANY mix
of live greedy and sampled lanes.

Seeding contract: every sampled request owns one integer seed
(explicit, or engine-assigned from a deterministic counter when None).
The seed becomes a `[2]` uint32 base key row (`key_row`) the slot
carries on device; each draw folds the slot's ABSOLUTE position (and a
draw-purpose salt) into it, so the token at position P+1 is drawn with
the key folded from P whatever path produced it — chunked or bucketed
prefill, cold or warm cache, plain decode or a speculative window.
Same (seed, trace, config) => same tokens; `temperature=0` (the
default-off state) is bit-identical to the greedy engine.

`oracle_probs` is the CPU (numpy) reference of the masked sampling
distribution — an independent implementation the statistical
acceptance tests chi-square the device draws against.
"""
from __future__ import annotations

import dataclasses
from dataclasses import dataclass

import numpy as np

__all__ = ["SamplingParams", "key_row", "oracle_probs"]


@dataclass(frozen=True)
class SamplingParams:
    """Per-request sampling knobs.

    temperature: 0 = greedy (argmax — bit-identical to a no-sampling
      engine, whatever the other knobs say); > 0 scales the logits by
      1/temperature before the draw.
    top_k: keep only the k highest-probability tokens (0 = off).
    top_p: nucleus sampling — keep the smallest descending-probability
      prefix whose mass reaches top_p (1.0 = off).
    seed: the request's reproducibility anchor. None lets the engine
      (or the fleet, which must resolve it BEFORE a disaggregated
      handoff splits the request across replicas) assign one from its
      deterministic counter.
    """

    temperature: float = 1.0
    top_k: int = 0
    top_p: float = 1.0
    seed: int = None

    def __post_init__(self):
        if not self.temperature >= 0:
            raise ValueError(
                f"temperature must be >= 0 (0 = greedy), got "
                f"{self.temperature!r}")
        if int(self.top_k) < 0:
            raise ValueError(f"top_k must be >= 0 (0 = off), got "
                             f"{self.top_k!r}")
        if not 0 < self.top_p <= 1.0:
            raise ValueError(f"top_p must be in (0, 1], got "
                             f"{self.top_p!r}")
        if self.seed is not None and int(self.seed) != self.seed:
            raise ValueError(f"seed must be an integer, got "
                             f"{self.seed!r}")

    @property
    def greedy(self):
        """True when this request decodes greedily (argmax) — the
        bit-exact path; the other knobs are inert."""
        return self.temperature <= 0

    def with_seed(self, seed):
        return dataclasses.replace(self, seed=int(seed))


def key_row(seed):
    """Host-side `[2]` uint32 base key row for a request seed — the
    per-slot key state the compiled steps fold positions into. Derived
    once at admission (and again, identically, when a disaggregated
    decode replica adopts the lane with the same seed). Distinct seeds
    get distinct keys across the full 64-bit range (the low word seeds
    the key, the high word folds in), so hash-derived and negative
    seeds never silently collide."""
    import jax

    s = int(seed) & 0xFFFFFFFFFFFFFFFF
    base = jax.random.PRNGKey(np.uint32(s & 0xFFFFFFFF))
    return np.asarray(jax.random.fold_in(base, np.uint32(s >> 32)),
                      np.uint32)


def oracle_probs(logits, params):
    """CPU (numpy) oracle of the masked sampling distribution one
    logits row induces under `params` — independent of the jnp path in
    `ops/sampling.py`, so the statistical acceptance tests compare two
    implementations, not one with itself. Returns float64 `[V]` probs
    (greedy params: a one-hot at the argmax)."""
    lg = np.asarray(logits, np.float64).reshape(-1)
    V = lg.shape[0]
    if params.greedy:
        p = np.zeros(V)
        p[int(np.argmax(lg))] = 1.0
        return p
    lg = lg / float(params.temperature)
    if params.top_k and params.top_k < V:
        kth = np.sort(lg)[::-1][int(params.top_k) - 1]
        lg = np.where(lg >= kth, lg, -np.inf)
    order = np.argsort(-lg, kind="stable")
    e = np.exp(lg[order] - np.max(lg))
    p_desc = e / e.sum()
    keep_desc = (np.cumsum(p_desc) - p_desc) < float(params.top_p)
    keep_desc[0] = True
    keep = np.empty(V, bool)
    keep[order] = keep_desc
    lg = np.where(keep, lg, -np.inf)
    e = np.exp(lg - np.max(lg))
    return e / e.sum()
