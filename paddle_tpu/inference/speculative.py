"""Drafters for speculative decoding on the generation engine.

Speculative decoding amortizes the HBM-bandwidth-bound decode step
(weights + KV read once per target-model pass) over several tokens: a
cheap DRAFTER proposes up to K continuation tokens per lane, the
engine scores all K+1 positions in ONE compiled verify pass
(`GPTModel.forward_verify_paged`), and the longest draft prefix whose
tokens equal the target's own argmax is accepted. Because the engine
decodes greedily, acceptance is EXACT: the emitted stream is
token-identical to the non-speculative path whatever the drafter
proposes — a bad draft only costs wasted verify columns, never a wrong
token.

The drafter contract (the seam a tiny draft GPT plugs into):

    drafter.propose(prompt, generated, k) -> sequence of <= k ints

- `prompt` is the request's int32 prompt array, `generated` the list
  of tokens emitted so far (host-side concrete values — the drafter
  runs between compiled steps and must never trace);
- return up to `k` proposed continuation tokens (fewer, or empty, is
  always legal — the engine falls back to a plain one-token step);
- proposals are suggestions only: correctness never depends on them.

`NgramDrafter` is the shipped model-free baseline (prompt-lookup /
n-gram matching, as in "Prompt Lookup Decoding" and the Leviathan et
al. (2023) model-free discussion): it matches the lane's most recent
n-gram against its own earlier context (prompt + generated tokens) and
proposes the continuation that followed the latest previous
occurrence. Summarization/code/chat workloads repeat long spans of
their prompt, so this hits often at zero draft-model cost.

`GptDrafter` is the learned drafter the protocol was built for (the
PR 7 follow-up): a SMALL GPT sharing the target's tokenizer,
greedy-decoded host-side between compiled steps. Drafter quality never
changes greedy output tokens (the exact-acceptance contract) and never
changes a sampled request's DISTRIBUTION (the rejection-sampling
contract) — a better drafter only raises the accepted-tokens-per-step
rate. Both drafters are deterministic (their draft distribution is a
point mass), which is exactly the case the engine's on-device
rejection sampler assumes.
"""
from __future__ import annotations

import numpy as np

__all__ = ["NgramDrafter", "GptDrafter", "draft_window"]


def draft_window(drafter, prompt, generated, budget, vocab):
    """One lane's proposal for its next verify window, junk-filtered.

    Runs `drafter.propose(prompt, generated, budget)` and keeps the
    longest prefix of in-vocab tokens, capped at `budget` — the exact
    filter the engine's serial scheduler applies, factored out so the
    async core's drafter thread and the serial path share one
    definition (a single divergence here would break the serial-vs-
    async token-identity gate for sampled lanes, whose acceptance
    coins are compared against the DRAFT token at each position).

    Thread-safety contract: both shipped drafters are pure functions
    of (prompt, generated, budget) — `NgramDrafter` is numpy over a
    private copy of the context, `GptDrafter` runs eager jax forwards
    with no mutable state — so this helper may run off the step thread
    as long as the caller passes a SNAPSHOT of `generated` (the step
    thread appends to the live list when lanes advance).
    """
    draft = []
    if budget > 0:
        for t in drafter.propose(prompt, generated, budget):
            t = int(t)
            if not 0 <= t < vocab or len(draft) >= budget:
                break                  # junk proposal: verify nothing
            draft.append(t)
    return draft


class NgramDrafter:
    """Model-free prompt-lookup drafter.

    Tries the longest n-gram first (`max_ngram` down to `min_ngram`):
    take the lane's last n tokens, find the most recent EARLIER
    occurrence of that n-gram in the lane's context, and propose the
    tokens that followed it. No proposal when nothing matches — the
    engine then runs a plain one-token step for that lane.
    """

    def __init__(self, max_ngram=3, min_ngram=1):
        if min_ngram < 1:
            raise ValueError("min_ngram must be >= 1")
        if max_ngram < min_ngram:
            raise ValueError("max_ngram must be >= min_ngram")
        self.max_ngram = int(max_ngram)
        self.min_ngram = int(min_ngram)

    def propose(self, prompt, generated, k):
        if k <= 0:
            return []
        ctx = np.asarray(prompt, np.int64).reshape(-1)
        if len(generated):
            ctx = np.concatenate(
                [ctx, np.asarray(list(generated), np.int64)])
        L = len(ctx)
        # n is capped so a match can still offer >= 1 continuation
        for n in range(min(self.max_ngram, L - 1),
                       self.min_ngram - 1, -1):
            pat = ctx[L - n:]
            win = np.lib.stride_tricks.sliding_window_view(ctx, n)
            starts = np.nonzero((win == pat).all(axis=1))[0]
            # drop matches with no room for a continuation token —
            # including the query suffix itself (start == L - n)
            starts = starts[starts <= L - n - 1]
            if starts.size:
                s0 = int(starts[-1])           # most recent occurrence
                return [int(t) for t in ctx[s0 + n:s0 + n + k]]
        return []


class GptDrafter:
    """Learned tiny-GPT drafter: greedy host-side decode of a small
    draft model through the `propose(prompt, generated, k)` protocol.

        draft = GPTForCausalLM(GPTConfig.tiny(...)); draft.eval()
        engine = GenerationEngine(model, spec_decode_k=4,
                                  drafter=GptDrafter(draft))

    The draft model must share the target's tokenizer (same id space);
    a context containing ids outside the draft vocab proposes nothing
    (the engine falls back to a plain one-token step — correctness
    never depends on the drafter). Proposals are the draft model's
    argmax continuations of `prompt + generated`, re-fed one token at
    a time with the context window clipped from the LEFT to the draft
    model's position table; the forwards run EAGERLY between compiled
    engine steps (host-side, never traced), so a deep draft model
    costs host latency, not target-step recompiles."""

    def __init__(self, model, max_context=None):
        cfg = model.config
        if model.training and cfg.dropout > 0:
            raise ValueError(
                "GptDrafter decodes deterministically (no dropout) — "
                "call draft_model.eval() first")
        self.model = model
        self.max_context = cfg.max_seq_len if max_context is None \
            else int(max_context)
        if self.max_context < 1 \
                or self.max_context > cfg.max_seq_len:
            raise ValueError(
                f"max_context={self.max_context} must be in "
                f"[1, {cfg.max_seq_len}] (the draft position table)")

    def _next_token(self, window):
        from paddle_tpu.core.tensor import Tensor

        ids = Tensor._wrap(np.asarray(window, np.int32)[None])
        logits = self.model(ids)               # [1, S, V] eager
        return int(np.argmax(np.asarray(logits._array)[0, -1]))

    def propose(self, prompt, generated, k):
        if k <= 0:
            return []
        ctx = [int(t) for t in np.asarray(prompt, np.int64).reshape(-1)]
        ctx += [int(t) for t in generated]
        vocab = self.model.config.vocab_size
        if any(t < 0 or t >= vocab for t in ctx):
            return []                  # disjoint id space: don't guess
        out = []
        for _ in range(int(k)):
            t = self._next_token(ctx[-self.max_context:])
            out.append(t)
            ctx.append(t)
        return out
