"""Replica-parallel serving tier: a prefix-affinity dp router over N
GenerationEngine replicas, with optional disaggregated prefill/decode.

PR 8 finished the mp axis — one engine spans a chip mesh. This module
is the dp axis: `ServingFleet` fronts N engine replicas (each
optionally mp-sharded and/or int8-quantized via the existing knobs)
with ONE host-side router, so aggregate tokens/s scales with replicas
while every per-engine win PRs 6-11 bought (prefix cache, QoS,
speculation, quantization) keeps paying per replica. Three layers:

- **Routing** (`add_request`): admission control (fleet `max_queue`
  shed — the HTTP-429 of this tier), QoS passthrough (priority rides
  to the replica's own class queues), and PREFIX-CACHE-AFFINITY
  placement: the router hashes the prompt's full-block chain with the
  exact `prefix_key` digests `PagedKVCache.match_prefix` /
  `register_prefix` key their block map with (one shared helper — a
  router key IS a cache key, the two cannot drift) and steers the
  request to the replica whose cache owns the deepest warm chain
  (`warm_prefix_tokens`, a read-only peek). Affinity yields to load
  with HYSTERESIS: the warm replica is used unless its backlog
  exceeds the least-loaded replica's by more than `affinity_slack`
  requests — so a hot tenant's shared prompt keeps hitting its warm
  blocks, but can't starve one replica while others idle. Cold
  requests go least-loaded (stable index tie-break), which is what
  keeps a 1-replica fleet BIT-IDENTICAL to a bare engine: same
  arrival order, same engine, same compiled steps. Under multi-tenant
  adapter serving (`engine_options["adapters"]`) the chain is SALTED
  with each request's adapter id — exactly the salt the caches use —
  so a hot base prompt under two tenants routes and caches
  independently.
- **Disaggregated prefill/decode** (`num_prefill_replicas > 0`):
  dedicated prefill replicas run chunked prefill to completion
  (`prefill_only` requests — max_new_tokens=1, the token the final
  chunk yields), then the router moves the finished prompt KV into a
  decode replica's pool BLOCK BY BLOCK: `export_pool_block` gathers
  each block's rows (plus its `[layers, 2]` int8 scale rows —
  `pool_spec()`/`scale_spec()` define the transfer unit) from the
  source pool, `ingest_pool_block` scatters them into
  freshly-allocated destination blocks (one compiled program each,
  traced block ids — shape-stable, donated destination pools), and
  `adopt_request` seats the lane mid-stream. Payloads are bit-copied,
  never re-quantized, so disaggregated output is TOKEN-IDENTICAL to a
  colocated engine — while long-prompt admission burns prefill-replica
  FLOPs only, never a decode step's.
- **Operations**: fleet metrics fold every replica's registry through
  `label_snapshot` + `merge_snapshots` (host-side, no collectives —
  replica-labeled TTFT/TPOT/pool/shed series, counters summing
  exactly); replicas register on the `distributed/launch` elastic
  registry (PADDLE_ELASTIC_TOKEN-authed, permanent leases — the
  launcher-owned-member class) and leave it through a graceful
  `drain`: stop admitting, finish in-flight lanes, leak-check the
  pool (`GenerationEngine.drain`), then drop the membership.

The fleet is single-process and host-driven like the engine itself:
`step()` round-robins every replica's scheduler iteration (jax's async
dispatch overlaps their device work), `run()` drives to completion.
Engines are the unit of failure and of elasticity; the router holds no
device state, so `add_replica`/`remove_replica` are metadata moves
plus (for remove) a drain.
"""
from __future__ import annotations

import time
from collections import OrderedDict

import numpy as np

import jax
import jax.numpy as jnp

from paddle_tpu.inference.engine import (PRIORITY_CLASSES,
                                         GenerationEngine, prefix_key)
from paddle_tpu.inference.sampling import SamplingParams
from paddle_tpu.observability.metrics import (LATENCY_BUCKETS,
                                              MetricsRegistry,
                                              label_snapshot,
                                              merge_snapshots)
from paddle_tpu.observability.tracing import (TraceRecorder,
                                              export_timeline,
                                              new_trace_id, now_us,
                                              profiler_host_events)

__all__ = ["ServingFleet", "REPLICA_ROLES"]

#: A replica either serves end-to-end ("mixed", the default fleet) or
#: one side of the disaggregated split ("prefill" runs chunked prefill
#: to completion and hands KV blocks off; "decode" only ever adopts
#: handed-off lanes and decodes them).
REPLICA_ROLES = ("mixed", "prefill", "decode")

_ELASTIC_PREFIX = "fleet-replica-"


class _Replica:
    """One engine replica plus its router-side identity: stable id
    (never reused — removal must not re-key another replica's metrics
    or elastic membership), role, retirement flag (a retiring replica
    finishes its in-flight work but takes no new routes), and the
    replica-local compiled block export/ingest pair."""

    def __init__(self, rid, engine, role):
        self.rid = rid
        self.engine = engine
        self.role = role
        self.retired = False
        self._export, self._ingest = _build_transfer(engine)

    @property
    def load(self):
        """Router load signal: requests this replica has accepted but
        not finished (queued + seated)."""
        return self.engine.num_pending + self.engine.num_active


def _build_transfer(engine):
    """Compile the (export, ingest) pair for one replica's pool
    layout. Traced block ids — ONE program each serves every
    handed-off block. Ingest donates the destination pools (the same
    decision the engine made for its steps, read off its
    `_donate_argnums`) and pins the pool out_shardings at mp>1
    exactly like the engine's own steps, so the handoff write is
    in-place in HBM, never a pool rebuild. Export never donates: the
    source replica keeps serving from its pools."""
    from paddle_tpu.ops.paged_attention import (export_pool_block,
                                                ingest_pool_block)

    donate = bool(engine._donate_argnums)
    out_sh = engine._step_out_shardings(0)
    if engine.kv_dtype == "int8":
        def fleet_block_export(kp, vp, src, sc):
            return export_pool_block(kp, vp, src, sc)

        def fleet_block_ingest(kp, vp, kb, vb, dst, sc, srow):
            return ingest_pool_block(kp, vp, kb, vb, dst, sc, srow)

        exp = jax.jit(fleet_block_export)
        ing = jax.jit(fleet_block_ingest,
                      donate_argnums=(0, 1, 5) if donate else (),
                      out_shardings=out_sh)
    else:
        exp = jax.jit(export_pool_block)
        ing = jax.jit(ingest_pool_block,
                      donate_argnums=(0, 1) if donate else (),
                      out_shardings=out_sh)
    return exp, ing


class ServingFleet:
    """N GenerationEngine replicas behind one prefix-affinity router.

        fleet = ServingFleet(model, num_replicas=2, num_slots=8)
        fleet.add_request([1, 2, 3], max_new_tokens=32)
        results = fleet.run()            # {req_id: prompt + tokens}

    Disaggregated prefill/decode:

        fleet = ServingFleet(model, num_replicas=1,
                             num_prefill_replicas=1, num_slots=8)

    `engine_options` forwards to every replica's GenerationEngine
    (num_slots, block_size, attention_backend, spec_decode_k,
    kv_dtype/weight_dtype, mp_degree, ... — replicas are homogeneous;
    heterogeneous fleets route wrong on load). Each replica keeps its
    OWN metrics registry; `metrics_snapshot()` folds them
    replica-labeled. `elastic_endpoint` (+ token, default
    $PADDLE_ELASTIC_TOKEN) registers every replica on the launcher's
    elastic registry and `remove_replica`/`drain` leave it."""

    def __init__(self, model, num_replicas=1, num_prefill_replicas=0,
                 max_queue=None, affinity_slack=None,
                 elastic_endpoint=None, elastic_token=None,
                 registry=None, **engine_options):
        if num_replicas < 1:
            raise ValueError(
                f"need >= 1 serving replica, got {num_replicas}")
        if num_prefill_replicas < 0:
            raise ValueError(
                f"num_prefill_replicas must be >= 0, got "
                f"{num_prefill_replicas}")
        self.model = model
        self._engine_options = dict(engine_options)
        self.disaggregated = num_prefill_replicas > 0
        self.max_queue = None if max_queue is None else int(max_queue)
        self._elastic = None
        if elastic_endpoint is not None:
            from paddle_tpu.distributed.launch.elastic import \
                ElasticClient

            self._elastic = ElasticClient(elastic_endpoint,
                                          token=elastic_token)
        self._replicas = OrderedDict()     # rid -> _Replica, id order
        self._next_rid = 0
        self._requests = {}                # rid -> routing record
        self._pending_handoffs = []        # exported, awaiting a lane
        self._handoff_seq = 0
        self._done = {}
        self._auto_id = 0
        # probabilistic serving: None seeds resolve HERE, before a
        # disaggregated handoff splits the request across replicas —
        # the prefill replica's first-token draw and the decode
        # replica's adopted key state must come from the SAME seed
        self._seed_counter = 0
        self._draining = False
        self.metrics = registry if registry is not None \
            else MetricsRegistry()
        self._init_metrics()
        decode_role = "decode" if self.disaggregated else "mixed"
        for _ in range(num_replicas):
            self.add_replica(role=decode_role)
        for _ in range(num_prefill_replicas):
            self.add_replica(role="prefill")
        # the affinity hysteresis: a warm replica keeps winning routes
        # until its backlog exceeds the least-loaded replica's by more
        # than this many requests. Default one full batch — deep
        # enough that a popular prefix stays where its blocks are,
        # shallow enough that a flood spills to idle replicas.
        if affinity_slack is None:
            affinity_slack = self._any_engine().num_slots
        self.affinity_slack = int(affinity_slack)
        # request-scoped tracing follows the replicas' knob (replicas
        # are homogeneous): the router keeps its OWN span ring so
        # routing/handoff decisions land on a separate Perfetto track
        # from any engine's spans, all on the shared monotonic clock
        self.tracing = bool(self._any_engine().tracing)
        self.tracer = TraceRecorder(process_name="fleet.router") \
            if self.tracing else None

    # -- replica management ------------------------------------------------
    def _any_engine(self):
        rep = next(iter(self._replicas.values()))
        return rep.engine

    def _build_engine(self):
        return GenerationEngine(self.model, **self._engine_options)

    def add_replica(self, role=None):
        """Bring one replica into the fleet: build its engine, compile
        nothing new beyond its own steps (first use warms them),
        register it on the elastic registry (permanent lease — the
        launcher-owned-member class; the registry rejects the call
        without the job token). Returns the replica id."""
        if self._draining:
            raise RuntimeError("fleet is draining — no new replicas")
        if role is None:
            role = "decode" if self.disaggregated else "mixed"
        if role not in REPLICA_ROLES:
            raise ValueError(
                f"role must be one of {REPLICA_ROLES}, got {role!r}")
        if self.disaggregated and role == "mixed":
            raise ValueError(
                "a disaggregated fleet has prefill and decode "
                "replicas — 'mixed' would let long-prompt prefill "
                "steal decode-step FLOPs again")
        if not self.disaggregated and role != "mixed":
            raise ValueError(
                f"role {role!r} needs a disaggregated fleet "
                "(num_prefill_replicas > 0)")
        rid = self._next_rid
        self._next_rid += 1
        rep = _Replica(rid, self._build_engine(), role)
        self._replicas[rid] = rep
        if self._elastic is not None:
            self._elastic.register(
                f"{_ELASTIC_PREFIX}{rid}",
                info={"role": role,
                      "num_slots": rep.engine.num_slots,
                      "mp_degree": rep.engine.mp_degree},
                ttl=None)
        self._update_replica_gauges()
        return rid

    def remove_replica(self, rid):
        """Graceful elastic leave: retire the replica from routing,
        drive the fleet until its in-flight work (and any handoffs it
        sourced) finished, drain it (admissions closed + pool
        leak-check), drop its elastic membership. Finished results
        stay collectable via run()/pop of the remaining fleet."""
        rep = self._replicas.get(rid)
        if rep is None:
            raise KeyError(f"no replica {rid}")
        peers = [r for r in self._routable(rep.role) if r.rid != rid]
        if not peers:
            raise ValueError(
                f"replica {rid} is the last {rep.role!r}-capable "
                "replica — removing it would strand the queue (drain "
                "the fleet instead)")
        rep.retired = True
        while rep.engine.num_pending or rep.engine.num_active \
                or rep.engine._handoffs:
            if self.step() == 0:
                raise RuntimeError(
                    f"cannot drain replica {rid}: its lanes are "
                    "stalled and no fleet progress is possible")
        rep.engine.drain()                 # instant: audits the pool
        if self._elastic is not None:
            self._elastic.leave(f"{_ELASTIC_PREFIX}{rid}")
        del self._replicas[rid]
        self._update_replica_gauges()

    def _routable(self, role):
        """Replicas a request of `role`'s kind could route to (live,
        not retiring), in stable id order."""
        return [r for r in self._replicas.values()
                if r.role == role and not r.retired]

    @property
    def num_replicas(self):
        return len(self._replicas)

    # -- metrics -----------------------------------------------------------
    def _init_metrics(self):
        m = self.metrics
        self._m_replicas = m.gauge(
            "fleet_replicas",
            "Live serving replicas, by role.", labelnames=("role",))
        self._m_routed = m.counter(
            "fleet_routed_total",
            "Requests routed, by replica id and why it won (affinity "
            "= deepest warm prefix chain within the hysteresis band; "
            "least_loaded = cold or affinity yielded to load).",
            labelnames=("replica", "reason"))
        self._m_affinity_tokens = m.counter(
            "fleet_affinity_hit_tokens_total",
            "Prompt tokens the router placed onto a replica already "
            "owning their warm prefix blocks (the tokens the affinity "
            "decision saved from recomputation).")
        self._m_shed = m.counter(
            "fleet_shed_total",
            "Requests shed at fleet admission (max_queue exceeded), "
            "by priority class.", labelnames=("priority",))
        self._m_handoffs = m.counter(
            "fleet_handoffs_total",
            "Prefill->decode handoffs completed (prompt KV exported "
            "from a prefill replica and adopted by a decode "
            "replica).")
        self._m_handoff_blocks = m.counter(
            "fleet_handoff_blocks_total",
            "KV pool blocks moved across replicas by the "
            "disaggregated handoff path.")
        self._m_handoff_stalls = m.counter(
            "fleet_handoff_stalls_total",
            "Iterations a finished prefill sat exported-but-unplaced "
            "for want of a decode lane or pool blocks.")
        self._m_pending_handoffs = m.gauge(
            "fleet_pending_handoffs",
            "Finished prefills currently awaiting a decode replica.")
        self._m_handoff_wait = m.histogram(
            "fleet_handoff_wait_seconds",
            "Prefill-finish to decode-adoption latency (the "
            "disaggregation seam's contribution to TBT).",
            buckets=LATENCY_BUCKETS)

    def _update_replica_gauges(self):
        counts = {role: 0 for role in REPLICA_ROLES}
        for rep in self._replicas.values():
            counts[rep.role] += 1
        for role in REPLICA_ROLES:
            self._m_replicas.labels(role=role).set(counts[role])

    def reset_metrics(self):
        """Zero the fleet registry and every replica registry in
        place (bench warmup / per-window scrapes — same semantics as
        `MetricsRegistry.reset`)."""
        self.metrics.reset()
        for rep in self._replicas.values():
            rep.engine.metrics.reset()

    def metrics_snapshot(self):
        """Fleet-level snapshot: the router's own series plus every
        replica engine's registry, each stamped `replica=<id>` and
        folded through the exact-merge machinery (`merge_snapshots`) —
        counters/buckets sum exactly, the replica label keeps
        per-replica series side-by-side. Host-side, no collectives:
        replicas live in this process; multi-HOST fleets fold these
        merged snapshots again through observability.aggregate()."""
        snaps = [self.metrics.snapshot()]
        for rid in sorted(self._replicas):
            snaps.append(label_snapshot(
                self._replicas[rid].engine.metrics.snapshot(),
                replica=str(rid)))
        return merge_snapshots(snaps)

    def export_trace(self, path, include_profiler=True):
        """One Perfetto timeline for the whole fleet: the router's
        routing/handoff spans plus every replica engine's span ring,
        one track group each (replicas share this process's monotonic
        clock, so a disaggregated request's prefill, handoff, and
        decode spans line up — follow its `trace_id` across tracks).
        Returns the event count written."""
        if self.tracer is None:
            raise RuntimeError(
                "tracing is off — build the fleet with tracing=True "
                "(or PADDLE_SERVE_TRACING=1) to record spans")
        groups = [("fleet.router", self.tracer.snapshot())]
        for rid in sorted(self._replicas):
            rep = self._replicas[rid]
            if rep.engine.tracer is not None:
                groups.append((f"replica {rid} ({rep.role})",
                               rep.engine.tracer.snapshot()))
        if include_profiler:
            ev = profiler_host_events()
            if ev:
                groups.append(("profiler", ev))
        return export_timeline(path, groups)

    # -- routing -----------------------------------------------------------
    def _route(self, prompt, adapter_id=0):
        """Pick the intake replica: deepest warm `prefix_key` chain
        wins while its backlog stays within `affinity_slack` of the
        least-loaded intake replica; otherwise least-loaded (stable
        id tie-break). The chain is salted with `adapter_id` — router
        keys stay == cache keys, so a hot base prompt under two
        tenants routes (and caches) independently: each adapter's
        chain warms its own replica and can never claim affinity to
        KV another tenant's projections wrote. Returns
        (replica, reason, warm_tokens)."""
        intake = self._routable(
            "prefill" if self.disaggregated else "mixed")
        if not intake:
            raise RuntimeError("fleet has no intake replica")
        loads = {r.rid: r.load for r in intake}
        min_load = min(loads.values())
        best, best_hit, keys = None, 0, None
        for r in intake:
            if not r.engine.enable_prefix_cache:
                continue
            if keys is None:
                # hash the prompt ONCE; every replica peek reuses the
                # digests (replicas are homogeneous in block_size)
                keys = prefix_key(prompt, r.engine.block_size,
                                  adapter_id)
            hit = r.engine.cache.warm_prefix_tokens(prompt, keys=keys)
            if hit > best_hit:
                best, best_hit = r, hit
        if best is not None \
                and loads[best.rid] <= min_load + self.affinity_slack:
            return best, "affinity", best_hit
        cold = min(intake, key=lambda r: (loads[r.rid], r.rid))
        return cold, "least_loaded", 0

    def add_request(self, prompt, max_new_tokens, eos_token_id=None,
                    req_id=None, priority="standard", adapter_id=0,
                    sampling_params=None):
        """Admit one request into the fleet. Same contract as
        `GenerationEngine.add_request` (priority QoS, auto ids,
        validation, per-tenant `adapter_id` when the replicas carry an
        adapter registry), plus fleet admission control: with
        `max_queue` set and that many requests already queued
        fleet-wide, the incoming request is shed (result None — the
        HTTP-429 of this tier; per-replica `max_queue` still does
        priority-aware shedding inside each engine). Routing is
        prefix-affinity first (adapter-salted — a hot base prompt
        under two tenants warms two independent chains), least-loaded
        otherwise; in a disaggregated fleet the request lands on a
        prefill replica as `prefill_only` and the decode budget rides
        the handoff.

        `sampling_params` (needs replicas built with `sampling=True`)
        rides to the serving replica AND through the disaggregated
        handoff: a None seed is resolved by the FLEET's deterministic
        counter before routing, so the prefill replica's first-token
        draw and the decode replica's adopted key state share one
        seed — disaggregated sampled output is token-identical to
        colocated."""
        if self._draining:
            raise RuntimeError(
                "fleet is draining — admissions are closed")
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        if prompt.size == 0:
            raise ValueError("empty prompt")
        if max_new_tokens < 1:
            raise ValueError("max_new_tokens must be >= 1")
        if priority not in PRIORITY_CLASSES:
            raise ValueError(f"priority must be one of "
                             f"{PRIORITY_CLASSES}, got {priority!r}")
        # validate the adapter id BEFORE any router state mutates
        # (replicas are homogeneous — any engine's checker speaks for
        # all): an unknown id must reject cleanly, not leave a phantom
        # in-flight request that deadlocks every later run()
        adapter_id = self._any_engine()._check_adapter(adapter_id)
        # same pre-mutation discipline for sampling: validate against
        # any (homogeneous) replica, then pin a None seed fleet-side
        sampling_params = self._any_engine()._check_sampling(
            sampling_params)
        if sampling_params is not None and sampling_params.seed is None:
            sampling_params = sampling_params.with_seed(
                self._seed_counter)
            self._seed_counter += 1
        total = prompt.size + int(max_new_tokens)
        limit = self._any_engine().max_model_len
        if total > limit:
            raise ValueError(
                f"prompt({prompt.size}) + max_new({max_new_tokens}) ="
                f" {total} exceeds max_model_len={limit}")
        if req_id is None:
            while self._auto_id in self._requests \
                    or self._auto_id in self._done:
                self._auto_id += 1
            req_id = self._auto_id
            self._auto_id += 1
        elif req_id in self._requests or req_id in self._done:
            raise ValueError(f"req_id {req_id!r} is already in flight "
                             "or awaiting collection")
        if self.max_queue is not None and self.max_queue <= sum(
                r.engine.num_pending
                for r in self._replicas.values()) \
                + len(self._pending_handoffs):
            self._m_shed.labels(priority=priority).inc()
            self._done[req_id] = None
            return req_id
        trace_id = new_trace_id() if self.tracing else None
        t_route = now_us()
        rep, reason, warm = self._route(prompt, adapter_id)
        if self.tracer is not None:
            self.tracer.add_span(
                "fleet.route", t_route, now_us(), trace_id=trace_id,
                cat="router",
                args={"req_id": str(req_id), "replica": rep.rid,
                      "reason": reason, "affinity_tokens": warm})
        self._m_routed.labels(replica=str(rep.rid),
                              reason=reason).inc()
        if warm:
            self._m_affinity_tokens.inc(warm)
        # resolve the EFFECTIVE eos (engine default fallback) so the
        # handoff path's already-finished short-circuit agrees with
        # what the prefill replica will actually treat as EOS
        if eos_token_id is None:
            eos_token_id = rep.engine.eos_token_id
        info = {"prompt": prompt, "max_new": int(max_new_tokens),
                "eos": eos_token_id, "priority": priority,
                "arrived": time.perf_counter(), "replica": rep.rid,
                "adapter_id": int(adapter_id),
                "sampling": sampling_params,
                "trace_id": trace_id,
                "phase": "prefill" if self.disaggregated else "serve"}
        self._requests[req_id] = info
        if self.disaggregated:
            rep.engine.add_request(prompt, 1,
                                   eos_token_id=eos_token_id,
                                   req_id=req_id, priority=priority,
                                   prefill_only=True,
                                   adapter_id=adapter_id,
                                   sampling_params=sampling_params,
                                   trace_id=trace_id)
        else:
            rep.engine.add_request(prompt, max_new_tokens,
                                   eos_token_id=eos_token_id,
                                   req_id=req_id, priority=priority,
                                   adapter_id=adapter_id,
                                   sampling_params=sampling_params,
                                   trace_id=trace_id)
        return req_id

    def best_of_n(self, prompt, n, max_new_tokens,
                  sampling_params=None, eos_token_id=None,
                  priority="standard", adapter_id=0):
        """Fleet edition of `GenerationEngine.best_of_n`: candidate 0
        is served to completion first (its prefill warms ONE replica's
        prefix chain), then candidates 1..n-1 — same prompt, seeds
        `base+1..base+n-1` — route by prefix affinity to that warm
        replica and seat the prompt's blocks read-only (seated once
        fleet-wide, not n times). Drives `run()`; other in-flight work
        is served along the way and stays collectable. Returns the n
        candidate token lists in seed order."""
        from paddle_tpu.inference.engine import (_best_of_n_fanout,
                                                 _best_of_n_intake)

        params, base, self._seed_counter = _best_of_n_intake(
            self._any_engine(), sampling_params, n,
            self._seed_counter)
        out, stash = _best_of_n_fanout(
            lambda p: self.add_request(
                prompt, max_new_tokens, eos_token_id=eos_token_id,
                priority=priority, adapter_id=adapter_id,
                sampling_params=p),
            self.run, params, n, base)
        self._done.update(stash)       # bystander finishes collectable
        return out

    # -- disaggregated handoff ---------------------------------------------
    def _export_handoff(self, rep, req_id, toks):
        """A prefill replica finished `req_id`: claim its parked
        blocks, gather every block's rows (plus int8 scale rows) out
        of the source pool with the compiled export step, release the
        source blocks (prefix-cached ones stay warm for the router),
        and queue the payload for a decode lane. An EOS'd or
        single-token request is already complete — no decode leg."""
        info = self._requests[req_id]
        eng = rep.engine
        blocks, _hit = eng.take_handoff(req_id)
        first = int(toks[-1])
        done_eos = info["eos"] is not None and first == info["eos"]
        if done_eos or info["max_new"] <= 1:
            # already complete (EOS'd / single-token budget): no
            # decode leg, so exporting the KV would be pure waste
            eng.release_handoff(blocks)
            self._finalize(req_id, toks)
            return
        c = eng.cache
        t_exp = now_us()
        payload = []
        for b in blocks:
            if c.scales is not None:
                payload.append(rep._export(c.kpool, c.vpool,
                                           jnp.int32(b), c.scales))
            else:
                payload.append(rep._export(c.kpool, c.vpool,
                                           jnp.int32(b)))
        eng.release_handoff(blocks)
        if self.tracer is not None:
            self.tracer.add_span(
                "handoff.export", t_exp, now_us(),
                trace_id=info.get("trace_id"), cat="handoff",
                args={"req_id": str(req_id), "from_replica": rep.rid,
                      "blocks": len(blocks)})
        info["phase"] = "handoff"
        self._pending_handoffs.append(
            {"req_id": req_id, "payload": payload, "first": first,
             "seq": self._handoff_seq,
             "parked_at": time.perf_counter()})
        self._handoff_seq += 1
        self._m_pending_handoffs.set(len(self._pending_handoffs))

    def _place_handoff(self, h):
        """Try to land one exported prefill on a decode replica:
        least-loaded replica with a free lane, destination blocks
        allocated from ITS pool, each payload block ingested through
        the compiled scatter (donated pools), then the lane adopted
        mid-stream. False = no lane/blocks this iteration (the
        handoff stays queued; the stall is counted by the caller)."""
        info = self._requests[h["req_id"]]
        targets = sorted((r for r in self._routable("decode")
                          if r.engine.free_lanes > 0
                          and r.engine.adapter_page_available(
                              info.get("adapter_id", 0))),
                         key=lambda r: (r.load, r.rid))
        need = len(h["payload"])
        rep = blocks = None
        for cand in targets:
            # fall through on pool pressure: a busier replica with
            # free blocks beats stalling the handoff (and every lower
            # priority class behind it) on the least-loaded one
            blocks = cand.engine.cache.allocate(need)
            if blocks is not None:
                rep = cand
                break
        if rep is None:
            return False
        eng = rep.engine
        c = eng.cache
        t_ing = now_us()
        for parts, dst in zip(h["payload"], blocks):
            if c.scales is not None:
                kb, vb, srow = parts
                c.kpool, c.vpool, c.scales = rep._ingest(
                    c.kpool, c.vpool, kb, vb, jnp.int32(dst),
                    c.scales, srow)
            else:
                kb, vb = parts
                c.kpool, c.vpool = rep._ingest(
                    c.kpool, c.vpool, kb, vb, jnp.int32(dst))
        req_id = h["req_id"]
        eng.adopt_request(info["prompt"], h["first"], blocks,
                          info["max_new"],
                          eos_token_id=info["eos"], req_id=req_id,
                          priority=info["priority"],
                          arrived_at=info["arrived"],
                          adapter_id=info.get("adapter_id", 0),
                          sampling_params=info.get("sampling"),
                          trace_id=info.get("trace_id"))
        if self.tracer is not None:
            self.tracer.add_span(
                "handoff.ingest", t_ing, now_us(),
                trace_id=info.get("trace_id"), cat="handoff",
                args={"req_id": str(req_id), "to_replica": rep.rid,
                      "blocks": need})
        info["phase"] = "decode"
        info["replica"] = rep.rid
        self._m_handoffs.inc()
        self._m_handoff_blocks.inc(need)
        self._m_handoff_wait.observe(
            time.perf_counter() - h["parked_at"])
        return True

    def _flush_handoffs(self):
        """Place as many queued handoffs as decode capacity allows,
        best priority class first (FIFO within a class — the same
        strict ordering the engine's own admission uses)."""
        if not self._pending_handoffs:
            return 0
        self._pending_handoffs.sort(key=lambda h: (
            PRIORITY_CLASSES.index(
                self._requests[h["req_id"]]["priority"]), h["seq"]))
        placed, remaining = 0, []
        blocked = set()
        for h in self._pending_handoffs:
            cls = self._requests[h["req_id"]]["priority"]
            # strict priority: a blocked class also blocks everything
            # below it (otherwise a small batch job could leapfrog a
            # stalled interactive handoff into the last free lane)
            if cls in blocked or any(
                    PRIORITY_CLASSES.index(b) <
                    PRIORITY_CLASSES.index(cls) for b in blocked):
                remaining.append(h)
                continue
            if self._place_handoff(h):
                placed += 1
            else:
                self._m_handoff_stalls.inc()
                blocked.add(cls)
                remaining.append(h)
        self._pending_handoffs = remaining
        self._m_pending_handoffs.set(len(self._pending_handoffs))
        return placed

    # -- drive -------------------------------------------------------------
    def _finalize(self, req_id, toks):
        self._done[req_id] = toks
        self._requests.pop(req_id, None)

    def _collect(self, rep, results):
        for req_id in sorted(results, key=str):
            toks = results[req_id]
            info = self._requests.get(req_id)
            if info is None or toks is None:
                # shed by the replica's own max_queue (or unknown):
                # final answer, no decode leg
                self._finalize(req_id, toks)
                continue
            if info["phase"] == "prefill":
                self._export_handoff(rep, req_id, toks)
            else:
                self._finalize(req_id, toks)

    def step(self):
        """One fleet iteration: place queued handoffs, then one
        scheduler iteration on every replica with work, collecting
        finishes as they land. Returns the number of placements /
        engine progress units / finishes — 0 means the fleet cannot
        currently move.

        With async-core replicas the handoff work is the latency
        hiding ROADMAP item 3 promised: each `eng.step()` returns with
        a dispatch-ahead decode step still IN FLIGHT, so the second
        placement pass below (and the leading pass of the NEXT
        iteration) runs its compiled export/ingest scatters and
        adoption bookkeeping while every replica's device is busy —
        not against an idle device as the serial fleet did."""
        progressed = self._flush_handoffs()
        for rid in list(self._replicas):
            rep = self._replicas[rid]
            eng = rep.engine
            if eng.num_pending or eng.num_active:
                progressed += eng.step()
            results = eng.pop_results()
            if results:
                progressed += len(results)
                self._collect(rep, results)
        if self._pending_handoffs:
            # lanes vacated by the steps above can seat exported
            # prefills NOW instead of next iteration (one full fleet
            # sweep earlier) — overlapped with the in-flight steps
            # when replicas run the async core
            progressed += self._flush_handoffs()
            # still-queued handoffs: warm the adapter page their
            # adoption will need on the likeliest target replica while
            # the devices crunch
            self._prestage_handoffs()
        return progressed

    def _prestage_handoffs(self):
        """Adapter prefetch for queued handoffs (async latency
        hiding): for each pending handoff whose tenant carries an
        adapter, warm that adapter's page on the least-loaded decode
        replica that could take the placement — the compiled swap-in
        copy overlaps the replicas' in-flight steps, and the eventual
        `_place_handoff` adoption acquires a RESIDENT page instead of
        paying the transfer in the placement path. Best-effort only:
        no references taken, no placement decisions made here."""
        staged = set()
        for h in self._pending_handoffs:
            info = self._requests.get(h["req_id"])
            if info is None:
                continue
            aid = int(info.get("adapter_id", 0) or 0)
            if not aid or aid in staged:
                continue
            targets = sorted(self._routable("decode"),
                             key=lambda r: (r.load, r.rid))
            for rep in targets:
                pool = rep.engine.adapter_pool
                if pool is None \
                        or not pool.registry.has(aid):
                    continue
                if pool.page_of(aid) is not None \
                        or pool.prefetch(aid) is not None:
                    staged.add(aid)
                    rep.engine.flight.record(
                        "adapter_prefetch", h["req_id"], adapter=aid,
                        page=pool.page_of(aid))
                    break

    @property
    def num_outstanding(self):
        """Requests admitted but not yet finished (any phase)."""
        return len(self._requests)

    def run(self):
        """Drive until every admitted request finished; returns (and
        drains) {req_id: prompt + generated tokens; None for a shed
        request} — the engine `run()` contract, fleet-wide."""
        while self._requests:
            if self.step() == 0:
                pend = len(self._pending_handoffs)
                frees = {r.rid: r.engine.cache.num_free
                         for r in self._replicas.values()}
                raise RuntimeError(
                    "serving fleet deadlocked: "
                    f"{len(self._requests)} request(s) in flight, "
                    f"{pend} handoff(s) unplaceable, free blocks per "
                    f"replica {frees} — grow num_blocks/num_slots or "
                    "add replicas")
        out, self._done = self._done, {}
        return out

    def drain(self):
        """Fleet-wide graceful shutdown: close admissions, finish
        every in-flight request (handoffs included), then drain each
        replica (its own admission close + pool leak-check) and drop
        every elastic membership. Returns the final results."""
        self._draining = True
        out = self.run()
        for rid in list(self._replicas):
            rep = self._replicas.pop(rid)
            rep.retired = True
            rep.engine.drain()
            if self._elastic is not None:
                self._elastic.leave(f"{_ELASTIC_PREFIX}{rid}")
        self._update_replica_gauges()      # fleet_replicas -> 0
        return out
