"""Continuous-batching generation engine over a paged KV cache.

The serving tier the north star's "heavy traffic" clause asks for:
instead of one request at a time against a per-request fixed-size cache
(`GPTForCausalLM.generate`), MANY requests decode in ONE compiled step
(Orca-style iteration-level scheduling) against a global block pool
shared by all of them (vLLM-style PagedAttention layout).

Three pieces, each shape-stable so steady-state serving never
recompiles:

- `PagedKVCache`: per-layer `[num_blocks, block_size, heads, head_dim]`
  pool planes stacked on a leading layer axis, plus a host-side free
  list. Requests own `ceil(context/block_size)` blocks, allocated on
  demand as their context grows and returned the moment they finish —
  HBM is shared by live CONTEXT, not reserved per request at max
  sequence length. Block 0 is the null block (idle-slot writes land
  there; never allocated).
- a slot scheduler: `num_slots` decode lanes. Between decode
  iterations, finished requests vacate their lane and queued requests
  are admitted into free lanes (priority classes first, FIFO within a
  class). Prefill is CHUNKED by default: each scheduler iteration runs
  at most ONE fixed-shape compiled prefill chunk, so a long admission
  interleaves with the in-flight decode batch instead of monopolizing
  an iteration — and the chunk program compiles ONCE for every prompt
  length (`start`/`plen` are traced). Passing `prefill_buckets`
  selects the legacy whole-prompt bucketed prefill, kept as the parity
  foil CI proves the chunked path token-identical against. A lane that
  cannot get a block this iteration simply skips it (masked to the
  null block) and retries — graceful degradation under pool pressure
  instead of an abort.
- a prefix cache (chunked mode, on by default): `PagedKVCache` keeps a
  chain-hash → block map over FULL prompt blocks with per-block
  refcounts. Admission seats the longest cached block-aligned prefix
  read-only in the slot's table — hit tokens are never recomputed,
  only the tail is prefilled. Shared blocks are copy-on-write: a
  decode write landing in one first promotes it to a private copy via
  a tiny compiled block-copy step, so token streams stay identical to
  the uncached path. Cold cached blocks (refcount 0) form an LRU pool
  that `allocate` evicts from under pressure — the existing
  stall/retry path, unchanged.
- admission QoS: `add_request(..., priority=...)` with
  `PRIORITY_CLASSES` ordering, priority-labeled TTFT/TPOT histograms,
  and `max_queue` shed-on-saturation (shed requests resolve to None —
  the HTTP-429 of this API). Priority is STRICT: under sustained
  higher-class saturation a seated batch lane's prefill can starve —
  that is the contract (`batch` means "whenever there's room");
  `max_queue` shedding, not aging, is the overload control.
- one donated compiled decode step (`jax.jit`, the TrainStep idiom:
  model state threaded as traced args, pools donated so XLA updates
  them in place in HBM): `[slots, 1]` tokens + `[slots]` positions +
  `[slots, max_blocks]` block tables -> next token per slot. Fixed
  shapes regardless of which lanes are live, so arrivals/completions
  never retrace — `jit.count_traces` probes prove it in CI.

Greedy decoding matches `GPTForCausalLM.generate(use_cache=True)`
token-for-token per request (the parity contract CI enforces) — under
either paged-attention backend: `attention_backend` (or the
`PADDLE_PAGED_ATTENTION_BACKEND` env override) picks `auto` / `dense` /
`pallas` per `ops.paged_attention.resolve_backend`, resolved once at
construction so the compiled decode step is fixed; the selection is
published as the `engine_attention_backend_info` gauge and every decode
dispatch lands in the backend-labeled `engine_decode_step_seconds`
histogram.

Speculative decoding (PR 7): decode is HBM-bandwidth-bound (every
step re-reads the weights and the live KV), so the engine can amortize
one target-model pass over several tokens: with `spec_decode_k=K > 0`
(env override `PADDLE_SPEC_DECODE_K`), a host-side DRAFTER
(`inference/speculative.NgramDrafter` by default — model-free
prompt-lookup; any `propose(prompt, generated, k)` object plugs in)
proposes up to K tokens per lane, and ONE fixed-shape compiled verify
step (`forward_verify_paged`: `[slots, K+1]` tokens, traced per-row
positions and draft lengths) scores all K+1 positions against the
paged pools, writing their KV through the block tables. Acceptance is
EXACT under the greedy contract: the longest draft prefix matching the
target's own argmax is emitted (plus the target's next token — every
verify step nets >= 1 token), so output streams are token-identical
to the non-speculative engine for ANY drafter. Rejected positions
need no cleanup — the slot position simply does not advance past
them, position-bounded attention makes their stale KV unreachable,
and the next window overwrites them. Writes landing in shared or
prefix-cached blocks COW-promote first, for EVERY block the window
touches, exactly like plain decode. Per-lane variable acceptance
stays inside one program via masking, so `decode_traces == 1` holds
per (backend, K); K=0 builds today's decode step unchanged
(bit-for-bit the same program). Multi-token steps keep the latency
books honest: every accepted token lands in the TPOT histogram
against its producing step (the step gap amortized per token), and
`engine_spec_accepted_tokens` / `engine_spec_draft_hit_rate` track
how much the drafter is actually buying.

Tensor-parallel sharded serving (PR 8): `GenerationEngine(model,
mp_degree=N)` (or `mesh=serving_mesh(N)`, env `PADDLE_SERVE_MP`) runs
the SAME host-side scheduler — allocator, prefix cache, COW, QoS,
speculative acceptance all unchanged — while every compiled step
(prefill, chunked prefill, decode, K-token verify) becomes ONE
shard_map program over an `mp`-axis device mesh. Attention is sharded
by heads: per-shard paged KV pools `[L, blocks, bs, heads/mp, D]`
with the block tables REPLICATED across shards, so a block id means
the same thing everywhere and the host allocator stays mesh-oblivious;
both paged-attention backends (dense fori-loop and the Pallas kernel)
run per-shard unchanged, since neither reads the head count from
config. Weights are sharded Megatron-style but COLUMN-parallel
end-to-end (qkv head-grouped, out_proj/fc1/fc2 output-sharded,
activations reassembled by tiled all-gathers; vocab-parallel embedding
via masked-gather+psum; lm_head logits all-gathered once for the
host's greedy/acceptance) — every floating-point dot stays full
length, so mp=N output is TOKEN-EXACT vs mp=1, not merely close
(DESIGN_DECISIONS r12). The shape-stable single-trace contract holds
per mesh shape (`decode_traces == 1` per (backend, K, mp)) and the
sharded pools stay donated. CPU CI runs the real mp=2/mp=4 program on
a virtual device mesh (`--xla_force_host_platform_device_count`).

Quantized serving (PR 11): decode's other wall is the BYTES — every
step re-streams the live KV and the weights. `kv_dtype='int8'` (env
`PADDLE_SERVE_KV_DTYPE`) stores the paged pools as int8 codes plus a
`[layers, blocks, 2]` per-block K/V scale array threaded through
every compiled step beside the pools: quant-on-write grows and
requantizes only the written (engine-private) block's grid, dequant
is fused into both backends' streamed-block matmuls (fp32 online
softmax unchanged), COW copies scale rows with blocks, and the
prefix cache shares them by block id — so pool bytes halve vs bf16
and warm/speculative runs replay exactly. `weight_dtype='int8'` (env
`PADDLE_SERVE_WEIGHT_DTYPE`, re-snapshot via `quantize_weights()`)
serves qkv/out/fc1/fc2 as (int8, per-channel scale) pairs
dequantized inside the step to the compute dtype — int8 in HBM, fp32
accumulation (tpu-verify TPU103). Both knobs off is BIT-identical to
the unquantized engine; quantized output is tolerance-gated against
the fp path (see README "Quantized serving"), token-exact across
mesh shapes (per-block grids pmax-fold at mp>1) and across backends.

Multi-tenant adapter serving (PR 13): one base model, thousands of
per-tenant LoRA adapters — `GenerationEngine(adapters=registry)` wires
the `paddle_tpu/adapters/` subsystem in: an `AdapterRegistry` holds
rank-padded A/B factors host-side, a `PagedAdapterPool` pages active
adapters on-device (the PagedKVCache block/refcount/LRU +
stall-and-retry pattern, page-sized; host-side swap-in from the
registry on miss), and every compiled step gains a traced `[slots]`
adapter page row that gathers each lane's factors and fuses the
low-rank delta `x·Aᵀ·Bᵀ·scaling` into the qkv/out/fc1/fc2 matmuls
(`ops/lora.py`, fp32 accumulation) — shape-stable in `max_rank`, so
`decode_traces == 1` holds for ANY tenant mix. Adapter id 0 is the
null/base adapter (exact-zero delta); the prefix-cache chain hash is
SALTED with the adapter id, so a base prompt's KV under one tenant can
never alias another's, while id-0 reuse keys exactly as before.
Composes with everything above: speculation verifies under the adapted
model, mp>1 shards the B pages column-parallel (no new collectives,
bit-identical across mesh shapes), and int8 KV/weights quantize the
BASE path while adapters ride fp.

Probabilistic serving (PR 15): `GenerationEngine(sampling=True)` (env
`PADDLE_SERVE_SAMPLING`) turns on per-request on-device sampling —
`add_request(..., sampling_params=SamplingParams(temperature, top_k,
top_p, seed))` carries each request's knobs PER SLOT through the
fixed-shape decode and verify steps as traced per-row arrays (params
are data, never trace keys: `decode_traces == 1` holds per
(backend, K, mp, kv_dtype) for any live mix of greedy and sampled
lanes). Each sampled slot owns a `[2]` uint32 base key row derived
from its seed; every draw folds the slot's absolute position (plus a
draw-purpose salt) into it on device (`ops/sampling.py`), so same
(seed, trace, config) means same tokens across prefill modes, cache
states and backends — while greedy lanes (`temperature=0`, and every
lane of a `sampling=False` engine, whose programs are byte-identical
to the pre-sampling ones) keep taking the literal argmax. With
speculation on, acceptance upgrades from exact argmax equality to
Leviathan-style REJECTION SAMPLING at the verify step: all K+1 logit
positions are already in hand, so the compiled program computes
per-row accept coins and residual/bonus resamples in the same pass,
and the host walk emits `drafts[:n] + choices[n]` — provably
preserving the target distribution for any (deterministic) drafter,
and degenerating to the bit-exact greedy contract at temperature 0.
`best_of_n` fans one prompt into n sampled lanes that share its
prefix-cache blocks (seated once, read-only).

Serving telemetry (PR 2): every engine carries a metrics registry
(`engine.metrics`, observability tier) — TTFT/TPOT histograms, queue/
slot/pool gauges with a high-water mark, admission/finish/stall
counters, and a decode-recompile counter wired to the count_traces
probes (steady-state contract: 0). Scheduler iterations and compiled
prefill/decode dispatches also emit `engine.*` spans into the profiler
recorder, so a chrome trace shows the scheduler timeline next to the
metrics story.
"""
from __future__ import annotations

import hashlib
import math
import os
import threading
import time
from collections import OrderedDict, deque
from contextlib import contextmanager
from dataclasses import dataclass, field

import numpy as np

import jax
import jax.numpy as jnp

from paddle_tpu.analysis.trace.contracts import TraceContract, \
    register_contract
from paddle_tpu.core.tensor import Tensor
from paddle_tpu.inference.sampling import SamplingParams
from paddle_tpu.inference.sampling import key_row as _sampling_key_row
from paddle_tpu.inference.speculative import draft_window
from paddle_tpu.jit import introspect
from paddle_tpu.jit.api import bound_state, count_traces, dedup_params, \
    model_buffers
from paddle_tpu.observability.metrics import LATENCY_BUCKETS, \
    MetricsRegistry
from paddle_tpu.observability.tracing import (FlightRecorder,
                                              PhaseTimer, TraceRecorder,
                                              export_timeline,
                                              new_trace_id, now_us,
                                              profiler_host_events)
from paddle_tpu.profiler import RecordEvent

__all__ = ["PagedKVCache", "GenerationEngine", "Request",
           "PRIORITY_CLASSES", "prefix_key", "iter_prefix_key",
           "SamplingParams"]


def iter_prefix_key(tokens, block_size, adapter_id=0):
    """Lazy form of `prefix_key`: yields the chain digests one full
    block at a time, so walkers that break at the first cache miss
    (`match_prefix`, `warm_prefix_tokens` on a cold cache) hash only
    as deep as they look."""
    tokens = np.asarray(tokens, np.int32)
    bs = int(block_size)
    # adapter-id SALT (multi-tenant LoRA serving): a tenant adapter
    # changes the qkv projections, so the KV a prompt's prefill writes
    # depends on the adapter — the same base prompt under two adapters
    # must hash to DISJOINT chains or a cache hit would seat the wrong
    # tenant's KV. Adapter 0 (the null/base adapter) salts with the
    # empty seed, so base-model prefix reuse keys exactly as before.
    h = b"" if not adapter_id else hashlib.blake2b(
        b"adapter:%d" % int(adapter_id), digest_size=16).digest()
    for i in range(len(tokens) // bs):
        h = hashlib.blake2b(
            h + tokens[i * bs:(i + 1) * bs].tobytes(),
            digest_size=16).digest()
        yield h


def prefix_key(tokens, block_size, adapter_id=0):
    """Chain digests over the FULL blocks of `tokens`: digest `i` is
    blake2b(digest[i-1] ‖ block_i_tokens), seeded with an adapter-id
    salt (0 — the null/base adapter — seeds empty), so a digest names
    a block's content AND its whole prefix AND the adapter whose
    projections wrote its KV — position/prefix/tenant-safe by
    construction. Returns a tuple of 16-byte digests, one per full
    block (the ragged tail contributes nothing).

    This is the ONE hashing truth shared by the prefix cache
    (`PagedKVCache.match_prefix`/`register_prefix` key their block map
    with these digests) and the fleet router
    (`inference.fleet.ServingFleet` steers a request to the replica
    whose cache owns the deepest digest of its prompt) — factored out
    so the two can never drift: a router key IS a cache key."""
    return tuple(iter_prefix_key(tokens, block_size, adapter_id))


def _best_of_n_intake(eng, sampling_params, n, counter):
    """Shared best-of-n validation + None-seed RANGE claim (engine and
    fleet editions both run this, so the checks and the seed-claim
    invariant can never drift between them). `eng` is the serving
    engine (any fleet replica — they're homogeneous), `counter` the
    caller's deterministic seed counter. Returns (params, base,
    advanced counter); advancing by one instead of n would hand seeds
    base+1..base+n-1 out again to later None-seed requests, replaying
    candidates."""
    if n < 1:
        raise ValueError(f"need n >= 1 candidates, got {n}")
    if not eng.sampling:
        raise ValueError(
            "best_of_n needs sampling=True engines "
            "(GenerationEngine(sampling=True); fleets pass it in "
            "engine_options) — n greedy lanes would be n identical "
            "candidates")
    if not eng.enable_prefix_cache:
        raise ValueError(
            "best_of_n needs the prefix cache (chunked prefill) — "
            "without it every candidate re-prefills the prompt")
    params = eng._check_sampling(
        sampling_params if sampling_params is not None
        else SamplingParams())
    if params.greedy:
        raise ValueError(
            "best_of_n needs temperature > 0 — greedy candidates "
            "would all be the same continuation")
    if params.seed is None:
        return params, counter, counter + int(n)
    return params, params.seed, counter


def _best_of_n_fanout(add, run, params, n, base):
    """The shared best-of-n candidate loop (engine AND fleet edition
    call this, so the fan-out protocol can never drift between them):
    candidate 0 is served to completion FIRST — its prefill writes and
    registers the prompt's full blocks once — then candidates 1..n-1
    admit against the warm prefix, seeds `base..base+n-1`. Returns
    (candidates in seed order, bystander finishes the two run() calls
    collected along the way)."""
    ids = [add(params.with_seed(base))]
    stash = run()
    for i in range(1, int(n)):
        ids.append(add(params.with_seed(base + i)))
    stash.update(run())
    out = [stash.pop(i) for i in ids]
    if any(c is None for c in out):
        # a candidate was load-shed at admission (max_queue pressure
        # with no lower-priority victim) — a silent None in the
        # returned list would violate the n-candidates contract
        raise RuntimeError(
            f"best_of_n: {sum(c is None for c in out)} of {n} "
            "candidates were shed at admission under max_queue "
            "pressure — serve best_of_n with queue headroom for n "
            "candidates (or raise max_queue)")
    return out, stash


class PagedKVCache:
    """Global paged KV pool + host-side block allocator, refcounts, and
    hash-based prefix cache.

    kpool/vpool: `[layers, num_blocks, block_size, heads, head_dim]`
    device arrays, functionally updated by the compiled steps (donated,
    so updated in place on device). Block 0 is reserved as the null
    block — `allocate` never returns it.

    Every live block carries a reference count: `allocate` hands blocks
    out at refcount 1, `share` seats an existing block in another
    owner's table (+1), `free` decrements and only recycles at zero.
    The prefix cache is a chain-hash → block-id map over FULL prompt
    blocks (`register_prefix` publishes them once a prompt's KV is
    completely written; `match_prefix` walks the chain and takes a
    reference on every hit). A cached block whose refcount drops to
    zero is NOT returned to the free list — it parks in an LRU side
    pool, still addressable by hash, and is only evicted (hash dropped,
    block recycled) when `allocate` runs out of truly-free blocks. So
    cache pressure rides the engine's existing stall/retry path: an
    allocation that fails after eviction is the same stall it always
    was."""

    #: Block-recycling surface declared in introspect (the
    #: ENGINE_STEP_DONATION pattern: the framework names its effect
    #: methods, tpu-race TPU203 reads the table — no method-name
    #: strings live in the analyzer). Calling one of these between a
    #: dispatched step and its completion is the zombie-write hazard.
    RACE_RELEASE_METHODS = \
        introspect.ALLOCATOR_RELEASE_EFFECTS["PagedKVCache"]

    def __init__(self, num_layers, num_blocks, block_size, num_heads,
                 head_dim, dtype=jnp.float32, mesh=None, mp_axis="mp",
                 kv_dtype=None):
        if num_blocks < 2:
            raise ValueError("need >= 2 blocks (block 0 is the null "
                             "block)")
        if kv_dtype not in (None, "int8"):
            raise ValueError(
                f"kv_dtype must be None (fp pools) or 'int8', got "
                f"{kv_dtype!r}")
        self.block_size = int(block_size)
        self.num_blocks = int(num_blocks)
        self.num_layers = int(num_layers)
        self.num_heads = int(num_heads)
        self.head_dim = int(head_dim)
        # int8 per-block-scaled KV (PR 11): the pools store int8 codes
        # and `self.scales` `[layers, num_blocks, 2]` f32 carries each
        # block's symmetric K/V absmax grid (column 0 = K, 1 = V),
        # threaded through every compiled step alongside the pools.
        # `dtype` stays the MODEL compute dtype the attention output
        # casts back to; pool_spec() is still the one layout truth.
        self.kv_dtype = kv_dtype
        self.dtype = dtype
        # tensor-parallel serving: pools sharded on the HEADS axis over
        # the mesh's mp axis (per-shard planes [L, B, bs, H/mp, D]);
        # the block tables stay host-side and replicated, so the
        # allocator/prefix-cache/COW logic below is mesh-oblivious
        self.mesh = mesh
        self.mp_axis = mp_axis if mesh is not None else None
        shape, dt = self.pool_spec()
        if mesh is not None:
            from jax.sharding import NamedSharding

            mp = mesh.shape[mp_axis]
            if self.num_heads % mp:
                raise ValueError(
                    f"num_heads={num_heads} not divisible by mp "
                    f"degree {mp} — cannot head-shard the KV pools")
            sharding = NamedSharding(mesh, self.pool_pspec())
            self.kpool = jax.device_put(jnp.zeros(shape, dt), sharding)
            self.vpool = jax.device_put(jnp.zeros(shape, dt), sharding)
        else:
            self.kpool = jnp.zeros(shape, dt)
            self.vpool = jnp.zeros(shape, dt)
        if self.kv_dtype == "int8":
            from paddle_tpu.ops.paged_attention import KV_QUANT_EPS

            self._scale_eps = KV_QUANT_EPS
            scales = jnp.full(self.scale_spec()[0], KV_QUANT_EPS,
                              self.scale_spec()[1])
            if mesh is not None:
                from jax.sharding import NamedSharding, PartitionSpec

                # per-(layer, block) grids are GLOBAL across the
                # head-sharded pools (the steps pmax-fold the shards'
                # absmax), so the array replicates on the mesh
                scales = jax.device_put(
                    scales, NamedSharding(mesh, PartitionSpec()))
            self.scales = scales
        else:
            self.scales = None
        # LIFO free list: recently-freed (cache-warm) blocks reused first
        self._free = list(range(num_blocks - 1, 0, -1))
        self._ref = [0] * self.num_blocks
        self._ref[0] = 1               # null block: permanently held
        self._block_of = {}            # chain hash -> cached block id
        self._hash_of = {}             # cached block id -> chain hash
        # refcount-zero cached blocks, LRU order (oldest first): the
        # reclaimable tail of the prefix cache
        self._evictable = OrderedDict()   # block id -> chain hash
        # optional observer called with each block id the allocator
        # reclaims from the prefix cache (engine flight recorder)
        self.on_evict = None

    def pool_spec(self):
        """The ONE source of truth for a pool plane's logical
        `([layers, blocks, block_size, heads, head_dim], dtype)`: the
        sharded and unsharded constructors (and anything rebuilding a
        pool-shaped buffer) derive it from here, so the two layouts
        cannot drift. Under `kv_dtype='int8'` the dtype is int8 (the
        codes); the per-block grids live in `scale_spec()`."""
        dt = jnp.int8 if self.kv_dtype == "int8" else self.dtype
        return ((self.num_layers, self.num_blocks, self.block_size,
                 self.num_heads, self.head_dim), dt)

    def scale_spec(self):
        """Layout of the int8 pools' per-block scale array:
        `([layers, blocks, 2], float32)` — column 0 is the K grid,
        column 1 the V grid. None for fp pools."""
        if self.kv_dtype != "int8":
            return None
        return ((self.num_layers, self.num_blocks, 2), jnp.float32)

    def pool_nbytes(self):
        """Total bytes of the paged KV state: both pool planes plus
        (int8 mode) the per-block scale array — the number the
        capacity claim and the `engine_pool_bytes` gauge report."""
        n = int(self.kpool.nbytes) + int(self.vpool.nbytes)
        if self.scales is not None:
            n += int(self.scales.nbytes)
        return n

    def pool_pspec(self):
        """PartitionSpec sharding the pools' HEADS axis over the mp
        mesh axis (empty spec — replicated/single-chip — without a
        mesh). Shared by the constructor, the engine's shard_map
        in/out specs, and the donated-step sharding contract."""
        from jax.sharding import PartitionSpec

        if self.mp_axis is None:
            return PartitionSpec()
        return PartitionSpec(None, None, None, self.mp_axis, None)

    @property
    def num_free(self):
        """Blocks allocatable right now: truly free + evictable cached
        (the prefix cache's reclaimable tail)."""
        return len(self._free) + len(self._evictable)

    @property
    def num_cached_blocks(self):
        """Blocks the prefix cache can currently serve hits from."""
        return len(self._block_of)

    def refcount(self, block):
        return self._ref[block]

    def allocate(self, n):
        """n pool blocks at refcount 1, or None (caller stalls/retries)
        if the pool cannot serve them even after evicting every
        refcount-zero prefix-cache block (LRU first)."""
        if n > self.num_free:
            return None
        take = min(n, len(self._free))
        got = self._free[-take:] if take else []
        del self._free[-take:]
        while len(got) < n:            # reclaim cold cache blocks
            block, h = self._evictable.popitem(last=False)
            del self._block_of[h]
            del self._hash_of[block]
            got.append(block)
            if self.on_evict is not None:
                # observability hook (engine flight recorder): a warm
                # prefix block just lost its cached content
                self.on_evict(block)
        for b in got:
            self._ref[b] = 1
        if got and self.scales is not None:
            # a recycled block's grid belongs to its PREVIOUS tenant:
            # reset to the floor so the new owner's first write sets a
            # fresh grid instead of quantizing against stale scales
            self.scales = self.scales.at[:, np.asarray(got), :].set(
                self._scale_eps)
        return got

    def free(self, blocks):
        """Drop one reference per block; recycle at refcount zero
        (cached blocks park in the evictable LRU instead of the free
        list). Raises on the null block and on double-free — a
        scheduler bug must fail loudly, not silently double-allocate a
        live block. Blocks are processed deepest-first so that when a
        finished request's chain goes cold, LRU eviction reclaims the
        deepest (least re-usable) links before their parents."""
        for b in reversed(list(blocks)):
            b = int(b)
            if b == 0:
                raise ValueError("refusing to free the null block 0")
            if self._ref[b] <= 0:
                raise RuntimeError(
                    f"double free of pool block {b} (refcount already "
                    "0) — a live block would have been handed out twice")
            self._ref[b] -= 1
            if self._ref[b] == 0:
                h = self._hash_of.get(b)
                if h is None:
                    self._free.append(b)
                else:
                    self._evictable[b] = h   # newest LRU entry

    def share(self, blocks):
        """Take an extra reference on live blocks (seating them
        read-only in another slot's table)."""
        for b in blocks:
            if self._ref[b] <= 0:
                raise RuntimeError(f"cannot share dead block {b}")
            self._ref[b] += 1

    def needs_cow(self, block):
        """True when writing into `block` would corrupt state another
        owner (a slot OR the prefix cache) still reads: shared
        refcount, or registered as cached prefix content."""
        return self._ref[block] > 1 or block in self._hash_of

    def match_prefix(self, tokens, adapter_id=0):
        """Longest cached block-aligned prefix of `tokens` under
        `adapter_id`'s salted chain: walks the `prefix_key` digests
        over full blocks, takes a reference on every hit (reviving
        evictable ones), and returns (blocks, hit_tokens). Hit tokens
        never need recomputing — their KV is already in the pool,
        byte-for-byte what this (prompt, adapter)'s prefill would
        write; a different adapter's chain can never alias it."""
        blocks = []
        for h in iter_prefix_key(tokens, self.block_size, adapter_id):
            b = self._block_of.get(h)
            if b is None:
                break
            if self._ref[b] == 0:
                del self._evictable[b]     # revive: live again
            self._ref[b] += 1
            blocks.append(b)
        return blocks, len(blocks) * self.block_size

    def warm_prefix_tokens(self, tokens, keys=None, adapter_id=0):
        """Prompt tokens a `match_prefix` would serve from this cache
        RIGHT NOW — a read-only peek (no references taken, evictable
        entries left parked) for the fleet router's affinity decision:
        the replica owning the deepest warm chain gets the request.
        Same digests as `match_prefix` (both walk the `prefix_key`
        chain), so a router hit is exactly a cache hit. `keys` lets a
        caller probing SEVERAL caches (the router) hash the prompt
        once and reuse the digests."""
        hit = 0
        for h in (keys if keys is not None
                  else iter_prefix_key(tokens, self.block_size,
                                       adapter_id)):
            if h not in self._block_of:
                break
            hit += self.block_size
        return hit

    def register_prefix(self, tokens, blocks, adapter_id=0):
        """Publish a fully-prefilled prompt's FULL blocks into the
        prefix map under `adapter_id`'s salted chain (call only once
        every one of those blocks' KV rows is written). First writer
        wins: a hash that is already mapped keeps its original block
        and the racing copy stays private to its slot. Returns the
        number of blocks newly cached."""
        added = 0
        keys = iter_prefix_key(tokens, self.block_size, adapter_id)
        for h, blk in zip(keys, blocks):
            b = int(blk)
            if h in self._block_of or b in self._hash_of:
                continue
            self._block_of[h] = b
            self._hash_of[b] = h
            added += 1
        return added

    def leak_check(self):
        """Block-accounting audit for a QUIESCED pool (no live slots):
        every non-null block must either sit on the free list or be a
        refcount-zero prefix-cache block parked in the evictable LRU.
        Returns the list of leaked block ids — blocks still referenced
        or unaccounted for. `GenerationEngine.drain()` asserts this
        empty: it catches the leak class the allocator's double-free
        hardening cannot see (a block freed zero times instead of
        twice)."""
        free = set(self._free)
        leaked = []
        for b in range(1, self.num_blocks):
            if self._ref[b] == 0 and (
                    b in free or b in self._evictable):
                continue
            leaked.append(b)
        return leaked


# admission QoS classes, best-served-first; add_request validates
# against this tuple and the TTFT/TPOT histograms are labeled by it
PRIORITY_CLASSES = ("interactive", "standard", "batch")


@dataclass(eq=False)
class Request:
    """One generation request (prompt in, greedy continuation out).
    Identity equality (eq=False): the prompt is an ndarray, and two
    requests with equal content are still distinct requests."""

    req_id: object
    prompt: np.ndarray                 # int32 [plen]
    max_new_tokens: int
    eos_token_id: int = None
    arrived_at: float = None           # perf_counter at add_request
    priority: str = "standard"         # one of PRIORITY_CLASSES
    # disaggregated serving: a prefill-only request runs the prompt to
    # completion, emits its FIRST token, then parks its KV blocks in
    # the engine's handoff buffer (take_handoff) instead of decoding —
    # the fleet moves those blocks into a decode replica's pool
    prefill_only: bool = False
    # multi-tenant adapter serving: the tenant LoRA adapter this
    # request decodes under (0 = the null/base adapter — the plain
    # base model, bit-identical to a no-adapter engine)
    adapter_id: int = 0
    # probabilistic serving: the request's SamplingParams (seed already
    # resolved at intake), or None for the greedy/argmax contract
    sampling: object = None
    # request-scoped tracing: the id every span this request produces
    # carries — minted at intake (engine or fleet) and riding the
    # disaggregated handoff, so one timeline follows the request
    # across replicas. None on a tracing-disabled engine.
    trace_id: object = None


@dataclass(eq=False)
class _Slot:
    """A live decode lane: the request plus its paged-cache footprint.
    Identity equality: `self._slots.index(slot)` must find THIS lane,
    not a content-equal one."""

    req: Request
    blocks: list                       # owned/shared pool block ids
    generated: list = field(default_factory=list)
    last_token_at: float = None        # perf_counter of newest token
    prefill_pos: int = 0               # next prompt position to prefill
    hit_tokens: int = 0                # prefix-cache tokens never computed
    admit_seq: int = 0                 # admission order tiebreak
    adapter_page: int = 0              # adapter-pool page (0 = null)
    # per-slot sampling state threaded into the compiled steps as
    # traced per-row data (greedy lanes: 0 / 0 / 1.0 / zero key row)
    temp: float = 0.0
    top_k: int = 0
    top_p: float = 1.0
    key_row: object = None             # [2] uint32 base PRNG key

    @property
    def prefilling(self):
        """Still has prompt tokens to push through the chunked
        prefill (a full-prefix hit skips straight past this)."""
        return self.prefill_pos < len(self.req.prompt)

    @property
    def feed_pos(self):
        """Absolute position of the token about to be fed. With
        `generated` non-empty that is the newest generated token;
        empty `generated` is the full-prefix-hit state, where the
        first decode feeds the LAST PROMPT token (its logits produce
        the first generated token — the one step a full hit cannot
        skip)."""
        return len(self.req.prompt) + len(self.generated) - 1

    @property
    def feed_token(self):
        return self.generated[-1] if self.generated \
            else int(self.req.prompt[-1])


@dataclass(eq=False)
class _InFlight:
    """The single in-flight result slot of the dispatch-ahead
    pipeline: one dispatched decode/verify step whose device output
    has NOT been waited on yet. The async core leaves exactly one of
    these across `step()` calls (depth 1 — see DESIGN_DECISIONS r21);
    the serial core completes it inline within the same step."""

    out: object                        # device output(s), not yet read
    runnable: list                     # lane indices dispatched
    slots: list                        # the _Slot objects, snapshotted
    drafts: dict = None                # lane -> draft (verify steps)
    t_dec: float = 0.0                 # perf_counter at dispatch
    t_span: int = 0                    # now_us at schedule end
    seq: int = 0                       # pipeline sequence number


class GenerationEngine:
    """Iteration-level scheduler + compiled steps over a paged cache.

        engine = GenerationEngine(model, num_slots=8, block_size=16)
        engine.add_request([1, 2, 3], max_new_tokens=32)
        ...                                  # add more any time
        results = engine.run()               # {req_id: full token list}

    `model` is a GPTForCausalLM (or anything exposing
    `gpt.forward_prefill`, `gpt.forward_decode_paged` and `_logits_of`
    with the same contracts). Generation is eval-mode; the engine
    refuses a model left in training mode with active dropout, same as
    `generate(use_cache=True)`.
    """

    #: Dispatch/complete surface of the (async) step pipeline, declared
    #: in introspect so tpu-race TPU203 can order allocator releases
    #: against in-flight device steps (see RACE_RELEASE_METHODS on
    #: PagedKVCache / PagedAdapterPool).
    RACE_DISPATCH_METHODS = introspect.ENGINE_DISPATCH_EFFECTS
    RACE_COMPLETE_CALLS = introspect.STEP_COMPLETE_CALLS

    def __init__(self, model, num_slots=8, block_size=16,
                 num_blocks=None, prefill_buckets=None,
                 max_model_len=None, eos_token_id=None, donate=None,
                 registry=None, attention_backend=None,
                 prefill_chunk="auto", enable_prefix_cache=None,
                 max_queue=None, spec_decode_k=0, drafter=None,
                 mesh=None, mp_degree=None, kv_dtype=None,
                 weight_dtype=None, adapters=None,
                 adapter_pool_pages=None, sampling=None,
                 tracing=None, trace_capacity=4096,
                 flight_capacity=256, async_core=None):
        from paddle_tpu.ops.paged_attention import (copy_pool_block,
                                                    resolve_backend)

        cfg = model.config
        if model.training and cfg.dropout > 0:
            raise ValueError("GenerationEngine decodes deterministically "
                             "(no dropout) — call model.eval() first")
        self.model = model
        self.num_slots = int(num_slots)
        self.block_size = int(block_size)
        # tensor-parallel serving mesh: constructor mesh/mp_degree,
        # env PADDLE_SERVE_MP override wins (deploy-time knob, like
        # the attention backend). mp=1 (the default) is exactly the
        # single-chip engine — no mesh, no shard_map, no resharding.
        self._resolve_mesh(mesh, mp_degree, cfg)
        self.max_model_len = int(max_model_len or cfg.max_seq_len)
        if self.max_model_len > cfg.max_seq_len:
            raise ValueError(
                f"max_model_len={self.max_model_len} exceeds the "
                f"model's position table ({cfg.max_seq_len})")
        self.max_blocks = math.ceil(self.max_model_len / self.block_size)
        self.eos_token_id = eos_token_id
        self.max_queue = None if max_queue is None else int(max_queue)
        # prefill strategy: chunked (default) runs the prompt through a
        # FIXED-shape compiled chunk step, one chunk per scheduler
        # iteration — long admissions interleave with decode instead of
        # monopolizing an iteration, and prefill traces are bounded by
        # the chunk shape (1), not a bucket ladder. Passing
        # prefill_buckets (or prefill_chunk=None) selects the legacy
        # whole-prompt bucketed prefill — kept as the parity foil CI
        # proves the chunked path token-identical against.
        if prefill_chunk == "auto":
            prefill_chunk = None if prefill_buckets is not None \
                else min(128, self.max_model_len)
        elif prefill_chunk is not None and prefill_buckets is not None:
            raise ValueError("prefill_chunk and prefill_buckets are "
                             "mutually exclusive prefill strategies")
        self.prefill_chunk = None if prefill_chunk is None \
            else max(1, min(int(prefill_chunk), self.max_model_len))
        self.chunked_prefill = self.prefill_chunk is not None
        # prefix cache: content-hash block reuse needs tail-only
        # prefill, which only the chunked path can run
        if enable_prefix_cache is None:
            enable_prefix_cache = self.chunked_prefill
        if enable_prefix_cache and not self.chunked_prefill:
            raise ValueError("the prefix cache needs chunked prefill "
                             "(bucketed prefill always recomputes from "
                             "position 0)")
        self.enable_prefix_cache = bool(enable_prefix_cache)
        # quantized serving (PR 11): kv_dtype='int8' stores the paged
        # pools as int8 codes + per-block scales (halves the HBM bytes
        # every decode step streams and doubles effective prefix-cache
        # capacity); weight_dtype='int8' serves qkv/out/fc1/fc2 as
        # int8 + per-channel scales, dequantized inside the compiled
        # steps. Env overrides win (deploy-time knobs, like the
        # backend); None keeps today's fp path BIT-identical.
        self.kv_dtype = self._resolve_dtype_knob(
            "PADDLE_SERVE_KV_DTYPE", kv_dtype)
        self.weight_dtype = self._resolve_dtype_knob(
            "PADDLE_SERVE_WEIGHT_DTYPE", weight_dtype)
        # probabilistic serving (PR 15): sampling=True threads per-slot
        # SamplingParams (temperature/top-k/top-p + a [slots, 2] uint32
        # key row) through every compiled step as traced DATA. Off (the
        # default) threads nothing — the engine's programs stay
        # byte-identical to the pre-sampling ones. Env override wins
        # (deploy-time knob, like the backend).
        self.sampling = self._resolve_bool_knob(
            "PADDLE_SERVE_SAMPLING", sampling)
        self._seed_counter = 0
        # request-scoped tracing (PR 17): host-side spans ONLY — no
        # tracing state ever becomes a compiled-program argument, so a
        # tracing-enabled engine runs byte-identical programs to a
        # disabled one (the sampling=False precedent, held trivially
        # by construction). Env override wins (deploy-time knob).
        self.tracing = self._resolve_bool_knob(
            "PADDLE_SERVE_TRACING", tracing)
        self.tracer = TraceRecorder(capacity=trace_capacity) \
            if self.tracing else None
        # async engine core (ROADMAP item 3): a one-step dispatch-ahead
        # pipeline — `step()` leaves the decode/verify dispatch IN
        # FLIGHT and the next call's host work (admissions, prefill
        # chunk, drafter proposals on a helper thread, adapter-page
        # prefetch) overlaps its device time. Pure host restructuring:
        # the compiled programs are byte-identical and the emitted
        # token streams token-identical to the serial core (CI's
        # serial-vs-async parity matrix). Env override wins
        # (deploy-time knob, like the backend); off (the default)
        # keeps today's serial step loop op-for-op.
        self.async_core = self._resolve_bool_knob(
            "PADDLE_SERVE_ASYNC", async_core)
        self._inflight = None          # the single in-flight step slot
        self._ahead = None             # (helper thread, results dict)
        self._next_drafts = {}         # slot -> precomputed draft
        self._step_seq = 0
        # the flight recorder and the step-phase clock are ALWAYS on:
        # both are bounded host-side bookkeeping (a few appends /
        # perf_counter calls per step) and they feed the always-on
        # leak-audit postmortem and host-gap histograms
        self.flight = FlightRecorder(capacity=flight_capacity)
        self._phases = PhaseTimer()
        # default pool covers every slot at full context (+ null block):
        # correctness-first; serving deployments size it to live-context
        # expectations and lean on the stall/retry path under pressure
        self.cache = PagedKVCache(
            cfg.num_layers,
            int(num_blocks or 1 + self.num_slots * self.max_blocks),
            self.block_size, cfg.num_heads,
            cfg.hidden_size // cfg.num_heads,
            dtype=model.gpt.wte.weight._array.dtype, mesh=self.mesh,
            kv_dtype=self.kv_dtype)
        self.cache.on_evict = lambda b: self.flight.record(
            "prefix_evict", block=b)
        # multi-tenant adapter serving (paged batched-LoRA): an
        # AdapterRegistry (or a prebuilt PagedAdapterPool) turns on
        # per-slot adapter ids through every compiled step. None (the
        # default) threads nothing — the engine's programs are
        # BIT-identical to the pre-adapter ones.
        self._resolve_adapters(adapters, adapter_pool_pages, cfg,
                               model, donate)
        if self.chunked_prefill:
            self.prefill_buckets = ()
        else:
            self.prefill_buckets = tuple(sorted(
                prefill_buckets or self._default_buckets()))
            if self.prefill_buckets[-1] < self.max_model_len:
                raise ValueError("largest prefill bucket "
                                 f"({self.prefill_buckets[-1]}) must "
                                 "cover max_model_len="
                                 f"{self.max_model_len}")
        # paged-attention kernel backend: constructor arg, overridden by
        # the env (deploy-time switch without a code change), resolved
        # ONCE to a concrete backend so the compiled decode step is
        # fixed — `auto` never changes mid-engine (decode traces == 1)
        requested = os.environ.get("PADDLE_PAGED_ATTENTION_BACKEND") \
            or attention_backend or "auto"
        self.attention_backend_requested = requested
        self.attention_backend = resolve_backend(
            requested, head_dim=cfg.hidden_size // cfg.num_heads,
            block_size=self.block_size)
        # speculative decoding: K drafted tokens verified per compiled
        # step. Env override wins (deploy-time knob, like the backend);
        # K=0 builds today's one-token decode step unchanged.
        env_k = os.environ.get("PADDLE_SPEC_DECODE_K")
        if env_k not in (None, ""):
            try:
                k = int(env_k)
            except ValueError:
                raise ValueError(
                    f"PADDLE_SPEC_DECODE_K={env_k!r} is not an integer")
        else:
            k = int(spec_decode_k)
        if k < 0:
            raise ValueError(f"spec_decode_k must be >= 0, got {k}")
        self.spec_decode_k = k
        if k > 0:
            from paddle_tpu.inference.speculative import NgramDrafter

            self.drafter = drafter if drafter is not None \
                else NgramDrafter()
        else:
            self.drafter = None
        # the state threading of TrainStep: params+buffers ride as traced
        # args, so weight updates are visible without retracing
        self._state = dedup_params(list(model.parameters())) + \
            model_buffers(model)
        # int8 weight serving: qkv/out/fc1/fc2 ride the steps as
        # (int8 codes, per-output-channel scale) pairs and dequantize
        # INSIDE the compiled step (fp32 accumulation pinned by
        # tpu-verify TPU103) — the per-step HBM weight read shrinks to
        # the int8 bytes. `_qmeta[i]` is the entry's dequant target
        # dtype (None = unquantized); quantize_weights() (re)builds
        # the snapshot.
        self._wq_plan = self._weight_quant_plan() \
            if self.weight_dtype == "int8" else {}
        self._qmeta = [None] * len(self._state)
        self._q_arrays = None
        # tensor parallel: a serving-time SNAPSHOT of the state, each
        # array device_put onto the mesh with its Megatron
        # column-parallel spec (qkv weights head-grouped first); the
        # specs double as the shard_map in_specs. refresh_weights()
        # re-snapshots after a live weight update.
        self._tp_arrays = self._tp_specs = None
        self.quantize_weights()
        donate = (jax.default_backend() != "cpu") if donate is None \
            else donate
        # the one donation table both analyzers and the engine read:
        # introspect.ENGINE_STEP_DONATION (tpu-lint TPU004 resolves
        # the constants, tpu-verify TPU101 checks the lowered aliases)
        self._donate_argnums = introspect.ENGINE_STEP_DONATE_ARGNUMS \
            if donate else ()
        # with speculation on, the verify step IS the engine's decode
        # step: same probe, same donation, same traces==1 contract —
        # one program per (backend, K). Under sampling the verify step
        # leads with TWO replicated outputs (choices, accepts).
        self._decode_pure = count_traces(
            self._build_verify() if k > 0 else self._build_decode())
        self._decode_n_out = 2 if (k > 0 and self.sampling) else 1
        self._decode = jax.jit(
            self._decode_pure, donate_argnums=self._donate_argnums,
            out_shardings=self._step_out_shardings(self._decode_n_out))
        self._prefill_pure = count_traces(
            self._build_prefill_chunk() if self.chunked_prefill
            else self._build_prefill())
        self._prefill = jax.jit(self._prefill_pure,
                                donate_argnums=self._donate_argnums,
                                out_shardings=self._step_out_shardings(1))
        # copy-on-write promotion: one tiny compiled gather/scatter,
        # traced src/dst so every COW reuses the same program
        cow = count_traces(copy_pool_block)
        cow.__name__ = "engine_cow_copy"
        self._cow_pure = cow
        self._cow = jax.jit(
            cow,
            donate_argnums=introspect.ENGINE_COW_DONATE_ARGNUMS
            if donate else (),
            out_shardings=self._step_out_shardings(0))
        self._queues = {p: deque() for p in PRIORITY_CLASSES}
        self._slots = [None] * self.num_slots
        self._results = {}
        self._handoffs = {}            # req_id -> (blocks, hit_tokens)
        self._draining = False
        self._auto_id = 0
        self._admit_counter = 0
        self.tokens_generated = 0
        self.prefix_hit_tokens = 0
        # serving telemetry: per-engine registry by default so counter
        # exactness survives multiple engines in one process; pass
        # observability.get_registry() to publish on the process default
        self.metrics = registry if registry is not None \
            else MetricsRegistry()
        self._init_metrics()

    # -- tensor-parallel serving (mesh) ------------------------------------
    def _resolve_mesh(self, mesh, mp_degree, cfg):
        """Resolve (mesh, mp_degree, env) to the serving mesh. Env
        PADDLE_SERVE_MP wins; an explicit mesh must agree with it and
        must carry an 'mp' axis. Degree 1 means single-chip (no mesh).
        Validates the Megatron divisibility constraints up front."""
        from paddle_tpu.distributed.topology import serving_mesh

        env = os.environ.get("PADDLE_SERVE_MP")
        env_mp = None
        if env not in (None, ""):
            try:
                env_mp = int(env)
            except ValueError:
                raise ValueError(
                    f"PADDLE_SERVE_MP={env!r} is not an integer")
        requested = env_mp if env_mp is not None else \
            (int(mp_degree) if mp_degree is not None else None)
        if mesh is not None:
            if "mp" not in mesh.axis_names:
                raise ValueError(
                    "serving mesh needs an 'mp' axis — build one with "
                    "distributed.serving_mesh(mp) or "
                    "HybridCommunicateGroup.for_serving(mp).get_mesh()")
            mesh_mp = mesh.shape["mp"]
            if requested is not None and requested != mesh_mp:
                raise ValueError(
                    f"mesh mp axis has {mesh_mp} devices but "
                    + ("PADDLE_SERVE_MP" if env_mp is not None
                       else "mp_degree")
                    + f"={requested} — drop one of the two")
            self.mp_degree = int(mesh_mp)
            self.mesh = mesh if self.mp_degree > 1 else None
        else:
            self.mp_degree = 1 if requested is None else int(requested)
            if self.mp_degree < 1:
                raise ValueError(
                    f"mp degree must be >= 1, got {self.mp_degree}")
            self.mesh = None if self.mp_degree == 1 else serving_mesh(
                self.mp_degree)
        if self.mp_degree > 1:
            # fail HERE with the shape story, not deep in a per-shard
            # reshape (the serving_mesh contract, re-checked for an
            # explicitly passed mesh too)
            serving_mesh(self.mp_degree, num_heads=cfg.num_heads,
                         vocab_size=cfg.vocab_size,
                         devices=list(self.mesh.devices.reshape(-1)))
            if cfg.intermediate_size % self.mp_degree:
                raise ValueError(
                    f"intermediate_size={cfg.intermediate_size} is not "
                    f"divisible by mp degree {self.mp_degree} — cannot "
                    "column-shard the MLP")
        self._mp_axis = "mp" if self.mp_degree > 1 else None

    @staticmethod
    def _resolve_dtype_knob(env_name, requested):
        """Resolve a quantization knob: env override wins, '' means
        unset, only None/'int8' are valid (the fp path is the absence
        of the knob, not a named dtype)."""
        env = os.environ.get(env_name)
        if env not in (None, ""):
            requested = env
        if requested in (None, ""):
            return None
        if requested != "int8":
            raise ValueError(
                f"{env_name}/ctor value must be unset or 'int8', got "
                f"{requested!r}")
        return "int8"

    @staticmethod
    def _resolve_bool_knob(env_name, requested):
        """Resolve a boolean serving knob: env override wins, ''
        means unset, None defaults to off."""
        env = os.environ.get(env_name)
        if env not in (None, ""):
            low = env.lower()
            if low in ("1", "true", "on", "yes"):
                return True
            if low in ("0", "false", "off", "no"):
                return False
            raise ValueError(
                f"{env_name}={env!r} is not a boolean (use 0/1)")
        return bool(requested) if requested is not None else False

    # -- probabilistic serving (per-slot sampling) -------------------------
    def _check_sampling(self, params):
        """Validate intake sampling params: None always passes (the
        greedy contract); anything else needs the sampling subsystem
        on. Returns the params unchanged (seed may still be None —
        `_resolve_seed` assigns one)."""
        if params is None:
            return None
        if not isinstance(params, SamplingParams):
            raise TypeError(
                "sampling_params takes a SamplingParams, got "
                f"{type(params).__name__}")
        if not self.sampling:
            raise ValueError(
                "sampling_params needs GenerationEngine(sampling=True) "
                "— this engine decodes greedily")
        return params

    def _resolve_seed(self, params):
        """Pin a request's seed: explicit seeds pass through, None
        draws from the engine's deterministic counter — same admission
        order, same seeds, same tokens."""
        if params is None or params.seed is not None:
            return params
        seed = self._seed_counter
        self._seed_counter += 1
        return params.with_seed(seed)

    @staticmethod
    def _slot_sampling_fields(req):
        """The per-slot sampling state a request seats with: greedy
        (or param-less) lanes ride the inert defaults (temp 0, zero
        key row)."""
        p = req.sampling
        if p is None or p.greedy:
            return {}
        return dict(temp=float(p.temperature), top_k=int(p.top_k),
                    top_p=float(p.top_p),
                    key_row=_sampling_key_row(p.seed))

    def _sampling_host_rows(self):
        """The four per-row sampling arrays of one decode/verify
        dispatch as RAW NUMPY: [slots] temperature/top-k/top-p plus
        the [slots, 2] uint32 key rows. Idle and greedy lanes ride
        temp 0 / zero keys — their sampled columns are garbage the
        argmax select (device) and the host both ignore."""
        temps = np.zeros(self.num_slots, np.float32)
        tks = np.zeros(self.num_slots, np.int32)
        tps = np.ones(self.num_slots, np.float32)
        keys = np.zeros((self.num_slots, 2), np.uint32)
        for i, slot in enumerate(self._slots):
            if slot is None or slot.key_row is None:
                continue
            temps[i] = slot.temp
            tks[i] = slot.top_k
            tps[i] = slot.top_p
            keys[i] = slot.key_row
        return [temps, tks, tps, keys]

    def _sampling_host_args(self):
        """`_sampling_host_rows` as device arrays (the prefill paths'
        per-dispatch transfer; the decode paths batch the rows through
        `_put_host_args` instead)."""
        return [jnp.asarray(a) for a in self._sampling_host_rows()]

    def _put_host_args(self, rows):
        """Move one step's dynamic host rows to the device. Serial
        core: one `jnp.asarray` per row, in row order — op-for-op
        today's path. Async core: ONE fused `jax.device_put` over the
        whole tree (positions, draft windows, sampling rows, page rows
        ride a single transfer instead of 3-8 round trips). The leaf
        avals are identical either way, so the compiled step programs
        — and TRACE_BASELINE.json — cannot move."""
        if not self.async_core:
            return [jnp.asarray(a) for a in rows]
        if self.mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec

            return list(jax.device_put(
                tuple(rows), NamedSharding(self.mesh, PartitionSpec())))
        return list(jax.device_put(tuple(rows)))

    @staticmethod
    def _sampling_host_args_one(slot):
        """[1]-row edition of `_sampling_host_args` for the prefill
        steps (one slot per dispatch, like the adapter page row)."""
        greedy = slot.key_row is None
        return [jnp.asarray(np.asarray(
                    [0.0 if greedy else slot.temp], np.float32)),
                jnp.asarray(np.asarray(
                    [0 if greedy else slot.top_k], np.int32)),
                jnp.asarray(np.asarray(
                    [1.0 if greedy else slot.top_p], np.float32)),
                jnp.asarray(np.zeros((1, 2), np.uint32) if greedy
                            else slot.key_row[None])]

    # -- multi-tenant adapter serving (paged batched-LoRA) -----------------
    def _resolve_adapters(self, adapters, pages, cfg, model, donate):
        """Wire the paged adapter pool: an AdapterRegistry builds a
        pool on this engine's mesh (`adapter_pool_pages` pages,
        default 1 + num_slots so a full batch of distinct tenants
        never stalls); a prebuilt PagedAdapterPool is adopted after a
        mesh/geometry check. None disables the subsystem entirely."""
        if adapters is None:
            if pages is not None:
                raise ValueError(
                    "adapter_pool_pages needs adapters= (a registry "
                    "or pool) — pages of nothing would be a no-op")
            self.adapter_pool = None
            return
        from paddle_tpu.adapters import AdapterRegistry, \
            PagedAdapterPool

        if isinstance(adapters, PagedAdapterPool):
            if pages is not None:
                raise ValueError("adapter_pool_pages conflicts with a "
                                 "prebuilt PagedAdapterPool")
            if adapters.mesh is not self.mesh:
                raise ValueError(
                    "the prebuilt adapter pool's mesh differs from "
                    "the engine's — build it with the engine's mesh "
                    "(or pass the registry and let the engine build "
                    "the pool)")
            if adapters._owner is not None \
                    and adapters._owner is not self:
                raise ValueError(
                    "this PagedAdapterPool already pages for another "
                    "engine — paging state (refcounts/LRU/gauges) is "
                    "per-engine. Pass the AdapterRegistry instead and "
                    "let each engine build its own pool (the registry "
                    "is safely shared).")
            pool, reg = adapters, adapters.registry
        elif isinstance(adapters, AdapterRegistry):
            reg = adapters
            pool = PagedAdapterPool(
                reg, num_pages=int(pages) if pages is not None
                else 1 + self.num_slots,
                dtype=model.gpt.wte.weight._array.dtype,
                mesh=self.mesh, donate=donate)
        else:
            raise TypeError(
                "adapters= takes an AdapterRegistry or a "
                f"PagedAdapterPool, got {type(adapters).__name__}")
        for name, want in (("num_layers", cfg.num_layers),
                           ("hidden_size", cfg.hidden_size),
                           ("intermediate_size", cfg.intermediate_size),
                           ("num_heads", cfg.num_heads)):
            if getattr(reg, name) != want:
                raise ValueError(
                    f"adapter registry {name}={getattr(reg, name)} "
                    f"does not match the served model's {want}")
        pool._owner = self
        self.adapter_pool = pool

    def _check_adapter(self, adapter_id):
        """Validate an intake adapter id: 0 always passes (null/base);
        anything else needs the adapter subsystem on and the id
        registered."""
        aid = int(adapter_id)
        if aid == 0:
            return 0
        if self.adapter_pool is None:
            raise ValueError(
                f"adapter_id={aid} needs GenerationEngine("
                "adapters=...) — this engine serves the base model "
                "only")
        if not self.adapter_pool.registry.has(aid):
            raise ValueError(f"adapter {aid} is not registered")
        return aid

    def adapter_page_available(self, adapter_id):
        """True when seating a request under `adapter_id` would not
        stall on an adapter page right now — the fleet's placement
        probe (mirrors `free_lanes` for KV headroom)."""
        return self.adapter_pool is None or int(adapter_id) == 0 \
            or self.adapter_pool.can_acquire(adapter_id)

    # -- int8 weight serving ----------------------------------------------
    def _weight_quant_plan(self):
        """id(state tensor) -> (scale_transform, scale PartitionSpec)
        for every weight served int8: the attention qkv/out and MLP
        fc1/fc2 matmuls (the per-step weight-read floor), per-OUTPUT-
        channel absmax scales via quantization.quantize_absmax(axis=1).
        Embeddings/norms/biases stay fp — the logit head's quality is
        the tolerance budget's scarcest resource. The scale transform
        mirrors `_tp_plan`'s qkv head-grouping so scales shard exactly
        like their weights."""
        from jax.sharding import PartitionSpec as P

        D = self.model.config.hidden_size // self.model.config.num_heads

        def qkv_s(s):                  # [1, 3H] -> [1, heads, 3, D]
            return s.reshape(1, 3, -1, D).transpose(0, 2, 1, 3)

        plan = {}
        for blk in self.model.gpt.blocks:
            attn, mlp = blk.attn, blk.mlp
            plan[id(attn.qkv_proj.weight)] = (qkv_s,
                                              P(None, "mp", None, None))
            for lin in (attn.out_proj, mlp.fc1, mlp.fc2):
                plan[id(lin.weight)] = (None, P(None, "mp"))
        return plan

    def quantize_weights(self):
        """(Re)build the served weight snapshot: the tensor-parallel
        mesh placement (mp > 1) and/or the int8 quantized state
        (weight_dtype='int8'). Called by the constructor and by
        `refresh_weights()`; a no-op for the plain fp mp=1 engine,
        which reads the live tensors every step."""
        if self._mp_axis is not None:
            self._tp_arrays, self._tp_specs = self._build_tp_state()
        elif self.weight_dtype == "int8":
            self._q_arrays = self._build_quant_state()

    def _build_quant_state(self):
        """mp=1 int8 snapshot: state entries become (int8, scale)
        pairs per `_weight_quant_plan`, everything else rides live."""
        from paddle_tpu.quantization import quantize_absmax

        arrays = []
        for i, t in enumerate(self._state):
            if id(t) in self._wq_plan:
                q, s = quantize_absmax(t._array, axis=1)
                arrays.append((q, s))
                self._qmeta[i] = t._array.dtype
            else:
                arrays.append(t._array)
        return arrays

    def _materialize_state(self, state_arrays):
        """Inside a compiled step: dequantize the (int8, scale) state
        entries straight to their compute dtype (the dequantize(dtype=)
        seam) so the matmuls run fp with fp32 accumulation while HBM
        holds — and the step reads — int8 bytes."""
        if not self._wq_plan:
            return state_arrays
        from paddle_tpu.quantization import dequantize

        return [dequantize(e[0], e[1], dtype=meta)
                if meta is not None else e
                for e, meta in zip(state_arrays, self._qmeta)]

    def _tp_plan(self):
        """id(state tensor) -> (transform, PartitionSpec): the Megatron
        column-parallel serving layout. qkv weights are re-grouped
        head-major (`[H, heads, 3, D]`) so a contiguous heads-axis
        shard holds complete (q, k, v) triples for ITS heads;
        out_proj/fc1/fc2 shard their OUTPUT columns (full-length dots,
        all-gathered activations — bit-exact vs mp=1, see
        DESIGN_DECISIONS r12); wte shards vocab rows. Everything else
        (layer norms, wpe) replicates."""
        from jax.sharding import PartitionSpec as P

        D = self.model.config.hidden_size // self.model.config.num_heads

        def qkv_w(w):
            return w.reshape(w.shape[0], 3, -1, D).transpose(0, 2, 1, 3)

        def qkv_b(b):
            return b.reshape(3, -1, D).transpose(1, 0, 2)

        plan = {}
        gpt = self.model.gpt
        plan[id(gpt.wte.weight)] = (None, P("mp", None))
        for blk in gpt.blocks:
            attn, mlp = blk.attn, blk.mlp
            plan[id(attn.qkv_proj.weight)] = (qkv_w,
                                              P(None, "mp", None, None))
            if attn.qkv_proj.bias is not None:
                plan[id(attn.qkv_proj.bias)] = (qkv_b,
                                                P("mp", None, None))
            for lin in (attn.out_proj, mlp.fc1, mlp.fc2):
                plan[id(lin.weight)] = (None, P(None, "mp"))
                if lin.bias is not None:
                    plan[id(lin.bias)] = (None, P("mp"))
        return plan

    def _build_tp_state(self):
        """Shard the model state onto the serving mesh per `_tp_plan`.
        Returns (committed arrays, PartitionSpecs) aligned with
        `self._state` — the arrays ride the compiled steps as traced
        args (weight-stationary: placed once, never re-sharded per
        step) and the specs are the steps' shard_map in_specs."""
        from jax.sharding import NamedSharding
        from jax.sharding import PartitionSpec as P

        plan = self._tp_plan()
        arrays, specs = [], []
        for i, t in enumerate(self._state):
            transform, spec = plan.get(id(t), (None, P()))
            a = t._array
            if id(t) in self._wq_plan:
                # quantize on the ORIGINAL layout (per-output-channel
                # scales), then ship codes + scale through the same
                # head-grouping/sharding as the fp weight would take
                from paddle_tpu.quantization import quantize_absmax

                q, s = quantize_absmax(a, axis=1)
                s_tf, s_spec = self._wq_plan[id(t)]
                if transform is not None:
                    q = transform(q)
                if s_tf is not None:
                    s = s_tf(s)
                arrays.append((
                    jax.device_put(q, NamedSharding(self.mesh, spec)),
                    jax.device_put(s, NamedSharding(self.mesh,
                                                    s_spec))))
                specs.append((spec, s_spec))
                self._qmeta[i] = a.dtype
                continue
            if transform is not None:
                a = transform(a)
            arrays.append(
                jax.device_put(a, NamedSharding(self.mesh, spec)))
            specs.append(spec)
        return arrays, specs

    def refresh_weights(self):
        """Re-snapshot the (tensor-parallel and/or int8-quantized)
        serving state from the live model parameters — call after a
        weight update. Plain fp mp=1 engines read the live tensors
        every step and never need this."""
        self.quantize_weights()

    def _step_out_shardings(self, n_repl):
        """Explicit out_shardings for a compiled step's jit: `n_repl`
        replicated leading outputs (token ids) followed by the two
        pool planes at the pool's sharding. None at mp=1 (jit infers).
        At mp>1 this is LOAD-BEARING for donation, not decoration:
        with inferred output shardings jax demotes donate_argnums to
        best-effort `jax.buffer_donor` markers, while matching
        explicit shardings let lowering PIN input/output aliases
        (`tf.aliasing_output`) — the difference between the paged
        pools provably updating in place and XLA merely being allowed
        to. tpu-verify TPU101 gates on the pinned form."""
        if self.mesh is None:
            return None
        from jax.sharding import NamedSharding
        from jax.sharding import PartitionSpec as P

        pool = NamedSharding(self.mesh, self.cache.pool_pspec())
        repl = NamedSharding(self.mesh, P())
        # int8 KV: the per-block scale array trails the pools in every
        # step's outputs, replicated (the steps pmax-fold it exact)
        tail = (repl,) if self.kv_dtype == "int8" else ()
        return (repl,) * n_repl + (pool, pool) + tail

    def _shard_steps(self, fn, n_repl, n_out=1):
        """Wrap a compiled-step body in shard_map over the serving
        mesh: state per `_tp_specs`, pools head-sharded, the `n_repl`
        trailing host args (tokens/positions/tables/sampling rows/...)
        replicated; outputs (`n_out` replicated leading outputs —
        token ids, and under sampling the verify step's
        choices/accepts pair — then sharded pools). Identity at
        mp=1."""
        if self._mp_axis is None:
            return fn
        from jax.experimental.shard_map import shard_map
        from jax.sharding import PartitionSpec as P

        pool = self.cache.pool_pspec()
        # int8 KV: the replicated scale array rides between the pools
        # and the host args (inputs) and trails the pools (outputs)
        scales = (P(),) if self.kv_dtype == "int8" else ()
        # adapters: the pool-array tuple rides before the host args
        # (B pages output-sharded, A pages replicated) and the traced
        # per-slot page row is one extra replicated host arg
        lora = () if self.adapter_pool is None \
            else (self.adapter_pool.pool_pspecs(),)
        if lora:
            n_repl += 1
        sharded = shard_map(
            fn, mesh=self.mesh,
            in_specs=(list(self._tp_specs), pool, pool) + scales
            + lora + (P(),) * n_repl,
            out_specs=(P(),) * n_out + (pool, pool) + scales,
            # all-gathered logits/argmax are replicated by
            # construction; the static rep-checker can't prove it
            check_rep=False)
        sharded.__name__ = fn.__name__
        return sharded

    def _init_metrics(self):
        m = self.metrics
        self._m_ttft = m.histogram(
            "engine_ttft_seconds",
            "Request arrival to first generated token (includes queue "
            "wait and prefill), labeled by QoS priority class.",
            labelnames=("priority",), buckets=LATENCY_BUCKETS)
        self._m_tpot = m.histogram(
            "engine_tpot_seconds",
            "Per-output-token latency, labeled by QoS priority class: "
            "time since the slot's PREVIOUS token, so block-stall "
            "waits show up (not just the producing iteration's wall "
            "time). A request that only ever produces one token "
            "records that token's producing-step latency instead of "
            "staying invisible.",
            labelnames=("priority",), buckets=LATENCY_BUCKETS)
        self._m_queue = m.gauge(
            "engine_queue_depth", "Requests waiting for a slot.")
        self._m_active = m.gauge(
            "engine_active_slots", "Decode lanes currently occupied.")
        self._m_admissions = m.counter(
            "engine_admissions_total", "Requests admitted into a lane.")
        self._m_finished = m.counter(
            "engine_finished_total",
            "Requests finished (lane vacated).", labelnames=("reason",))
        # pool-pressure/utilization series carry a `shard` label (this
        # engine rank's shard id) so multi-host serving ranks each
        # publish their own series and metrics.aggregate() folds the
        # per-shard snapshots exactly — distinct label sets merge
        # side-by-side instead of min/max/meaning across shards
        self._shard = str(jax.process_index())
        self._m_stalls = m.counter(
            "engine_block_stalls_total",
            "Iterations a lane/admission skipped for want of a pool "
            "block (path=spec_degrade: a speculative lane shed its "
            "draft window instead of skipping), labeled by engine "
            "shard.",
            labelnames=("path", "shard"))
        self._m_tokens = m.counter(
            "engine_tokens_generated_total", "New tokens emitted.")
        self._m_pool_used = m.gauge(
            "engine_pool_used_blocks",
            "KV pool blocks in use, by engine shard.",
            labelnames=("shard",)).labels(shard=self._shard)
        kv_name = self.kv_dtype or np.dtype(
            self.cache.pool_spec()[1]).name
        self._m_pool_util = m.gauge(
            "engine_pool_utilization",
            "Used fraction of allocatable KV pool blocks, by engine "
            "shard and pool dtype (int8 = quantized KV serving).",
            labelnames=("shard", "kv_dtype")).labels(
                shard=self._shard, kv_dtype=kv_name)
        self._m_pool_bytes = m.gauge(
            "engine_pool_bytes",
            "Total bytes of the paged KV state (both pool planes plus "
            "the int8 per-block scale array when quantized), by shard "
            "and pool dtype — the capacity-claim number: int8 pools "
            "must come in at <= 0.55x their fp16/bf16 size.",
            labelnames=("shard", "kv_dtype")).labels(
                shard=self._shard, kv_dtype=kv_name)
        self._m_kv_dtype = m.gauge(
            "engine_kv_dtype_info",
            "Paged KV cache storage dtype this engine serves with "
            "(1 = selected).", labelnames=("kv_dtype",))
        self._m_kv_dtype.labels(kv_dtype=kv_name).set(1)
        w_name = self.weight_dtype or np.dtype(
            self.model.gpt.wte.weight._array.dtype).name
        self._m_weight_dtype = m.gauge(
            "engine_weight_dtype_info",
            "Served matmul-weight storage dtype (int8 = qkv/out/fc1/"
            "fc2 ride the compiled steps quantized; 1 = selected).",
            labelnames=("weight_dtype",))
        self._m_weight_dtype.labels(weight_dtype=w_name).set(1)
        self._m_pool_hw = m.gauge(
            "engine_pool_used_high_water_blocks",
            "High-water mark of KV pool blocks in use, by engine "
            "shard.",
            labelnames=("shard",)).labels(shard=self._shard)
        self._m_decode_traces = m.gauge(
            "engine_decode_traces",
            "Times the decode step traced (steady-state contract: 1).")
        self._m_prefill_traces = m.gauge(
            "engine_prefill_traces",
            "Times prefill traced (chunked: bounded by the one chunk "
            "shape; bucketed: by len(prefill_buckets)).")
        self._m_prefill_chunks = m.counter(
            "engine_prefill_chunks_total",
            "Compiled prefill-chunk dispatches (prefix-cache hits "
            "shrink this: hit tokens skip prefill compute).")
        self._m_hit_tokens = m.counter(
            "engine_prefix_cache_hit_tokens_total",
            "Prompt tokens served from the prefix cache instead of "
            "being recomputed.")
        self._m_cached_blocks = m.gauge(
            "engine_prefix_cached_blocks",
            "Pool blocks the prefix cache can currently serve hits "
            "from (live + evictable).")
        self._m_cow = m.counter(
            "engine_cow_copies_total",
            "Copy-on-write block promotions: a decode write landed in "
            "a shared/cached block and got a private copy first.")
        self._m_shed = m.counter(
            "engine_shed_total",
            "Requests shed at saturation (max_queue exceeded), by "
            "priority class.", labelnames=("priority",))
        self._m_spec_accepted = m.histogram(
            "engine_spec_accepted_tokens",
            "Tokens emitted per speculative verify step per lane "
            "(1 = no draft token survived; K+1 = the whole window "
            "accepted).",
            buckets=(1.0, 2.0, 3.0, 4.0, 5.0, 6.0, 8.0, 12.0, 16.0))
        self._m_spec_hit_rate = m.gauge(
            "engine_spec_draft_hit_rate",
            "Fraction of drafted tokens the target model confirmed "
            "(exact-acceptance matches / proposals) since the last "
            "registry reset.")
        # hit-rate numerator/denominator live IN the registry so a
        # metrics.reset() (bench warmup, per-window scrapes) restarts
        # the rate instead of averaging over all-time
        spec_drafted = m.counter(
            "engine_spec_draft_tokens_total",
            "Drafted tokens offered to the verify step, by whether "
            "the target's argmax confirmed them.",
            labelnames=("result",))
        self._m_spec_ok = spec_drafted.labels(result="accepted")
        self._m_spec_rej = spec_drafted.labels(result="rejected")
        self._m_recompiles = m.counter(
            "engine_decode_recompiles_total",
            "Decode retraces past the first compile — nonzero means a "
            "shape-stability bug.")
        self._m_sampling = m.gauge(
            "engine_sampling_info",
            "Probabilistic serving state (1 = this engine threads "
            "per-slot sampling params through its compiled steps; "
            "greedy-only engines run the pre-sampling programs "
            "byte-identically).", labelnames=("enabled",))
        self._m_sampling.labels(
            enabled="1" if self.sampling else "0").set(1)
        # registered only when the subsystem is on, so a plain
        # engine's exposition is unchanged (the adapter precedent)
        self._m_sampled_tokens = None
        if self.sampling:
            self._m_sampled_tokens = m.counter(
                "engine_sampled_tokens_total",
                "Tokens emitted by sampled (temperature > 0) lanes — "
                "greedy lanes count only in "
                "engine_tokens_generated_total.")
        self._m_backend = m.gauge(
            "engine_attention_backend_info",
            "Paged-attention kernel backend the compiled decode step "
            "dispatches to (1 = selected).", labelnames=("backend",))
        self._m_backend.labels(backend=self.attention_backend).set(1)
        self._m_mesh = m.gauge(
            "engine_mesh_info",
            "Serving mesh the compiled steps span (1 = this "
            "configuration): tensor-parallel degree and device count.",
            labelnames=("mp_degree", "devices"))
        self._m_mesh.labels(
            mp_degree=str(self.mp_degree),
            devices=str(self.mesh.size if self.mesh is not None
                        else 1)).set(1)
        # the backend label is fixed at construction: resolve the
        # histogram child once, off the per-step path
        self._m_decode_seconds = m.histogram(
            "engine_decode_step_seconds",
            "Wall time of one compiled decode dispatch, labeled by "
            "paged-attention backend.", labelnames=("backend",),
            buckets=LATENCY_BUCKETS).labels(
                backend=self.attention_backend)
        self._decode_traces_seen = 0
        # step-phase decomposition (ISSUE 17 / ROADMAP item 3): the
        # host work between compiled steps, per named phase — the
        # measured baseline the async engine core must beat. Always
        # registered: the phase clock is host bookkeeping, on for
        # every engine (tracing only adds the span stream).
        self._m_host_gap = m.histogram(
            "engine_step_host_gap_seconds",
            "Exclusive wall time one engine.step() spent in each named "
            "host phase (device_wait is the block_until_ready wait — "
            "the only phase that is device time; everything else is "
            "the serial host gap ROADMAP item 3 wants overlapped).",
            labelnames=("phase",), buckets=LATENCY_BUCKETS)
        self._m_device_fraction = m.gauge(
            "engine_step_device_fraction",
            "Fraction of the last step's wall time spent waiting on "
            "the device (device_wait / step wall): 1.0 = device-bound "
            "(host gap hidden), small = host-serial tax dominates.")
        # trace-count series: registered only when tracing is on, so a
        # plain engine's exposition is unchanged (adapter precedent)
        self._m_trace_spans = None
        if self.tracing:
            self._m_trace_spans = m.counter(
                "engine_trace_spans_total",
                "Spans/instants this engine's trace ring recorded "
                "(ring-bounded retention; see "
                "engine_trace_dropped_total).")
            self._m_trace_dropped = m.counter(
                "engine_trace_dropped_total",
                "Trace events evicted by the bounded span ring — "
                "nonzero means the exported timeline is a tail, not "
                "the full history.")
            self._trace_spans_seen = self._trace_dropped_seen = 0
        # multi-tenant adapter serving: per-TENANT latency series plus
        # adapter-pool paging health. Registered only when the
        # subsystem is on, so a plain engine's exposition is unchanged.
        self._m_a_ttft = self._m_a_tpot = None
        if self.adapter_pool is not None:
            self._m_a_ttft = m.histogram(
                "engine_adapter_ttft_seconds",
                "Request arrival to first token, labeled by tenant "
                "adapter id (0 = the null/base adapter) — the "
                "per-tenant SLO view of engine_ttft_seconds.",
                labelnames=("adapter",), buckets=LATENCY_BUCKETS)
            self._m_a_tpot = m.histogram(
                "engine_adapter_tpot_seconds",
                "Per-output-token latency by tenant adapter id — the "
                "per-tenant SLO view of engine_tpot_seconds.",
                labelnames=("adapter",), buckets=LATENCY_BUCKETS)
            self._m_a_pages = m.gauge(
                "engine_adapter_pool_pages",
                "Device-resident adapter pool pages (page 0 is the "
                "permanently-held null adapter).")
            self._m_a_pages.set(self.adapter_pool.num_pages)
            self._m_a_used = m.gauge(
                "engine_adapter_pool_used_pages",
                "Adapter pages referenced by live lanes (warm "
                "refcount-zero pages count as free capacity, like "
                "evictable KV blocks).")
            self._m_a_resident = m.gauge(
                "engine_adapter_pool_resident",
                "Adapters currently materialized on a page (live + "
                "warm LRU).")
            self._m_a_swapins = m.counter(
                "engine_adapter_swapins_total",
                "Host->device adapter page loads (an acquire missed "
                "the pool and copied the registry's stacks in).")
            self._m_a_evictions = m.counter(
                "engine_adapter_evictions_total",
                "Warm adapter pages evicted to make room for another "
                "tenant (LRU, refcount-zero only).")
            self._a_swapins_seen = self._a_evictions_seen = 0
            self._update_adapter_gauges()

    def _obs_ttft(self, req, v):
        """Record one TTFT observation on the priority-labeled series
        and (adapter serving) the tenant-labeled one."""
        self._m_ttft.labels(priority=req.priority).observe(v)
        if self._m_a_ttft is not None:
            self._m_a_ttft.labels(
                adapter=str(req.adapter_id)).observe(v)

    def _obs_tpot(self, req, v):
        self._m_tpot.labels(priority=req.priority).observe(v)
        if self._m_a_tpot is not None:
            self._m_a_tpot.labels(
                adapter=str(req.adapter_id)).observe(v)

    def _update_adapter_gauges(self):
        pool = self.adapter_pool
        if pool is None:
            return
        # re-set the static pages gauge too: a metrics.reset() (bench
        # warmup, per-window scrapes) must not leave it at 0 forever
        self._m_a_pages.set(pool.num_pages)
        self._m_a_used.set(pool.num_pages - 1 - pool.num_free)
        self._m_a_resident.set(pool.num_resident)
        if pool.swapins > self._a_swapins_seen:
            self._m_a_swapins.inc(pool.swapins - self._a_swapins_seen)
            self._a_swapins_seen = pool.swapins
        if pool.evictions > self._a_evictions_seen:
            self._m_a_evictions.inc(
                pool.evictions - self._a_evictions_seen)
            self._a_evictions_seen = pool.evictions

    def _update_pool_gauges(self):
        # "used" = referenced blocks; refcount-zero cached blocks are
        # reclaimable on demand, so they count as free capacity
        used = self.cache.num_blocks - 1 - self.cache.num_free
        self._m_pool_used.set(used)
        self._m_pool_util.set(used / max(self.cache.num_blocks - 1, 1))
        self._m_pool_bytes.set(self.cache.pool_nbytes())
        self._m_pool_hw.set_max(used)
        self._m_cached_blocks.set(self.cache.num_cached_blocks)

    def _sample_traces(self):
        """Mirror the count_traces probes into metrics; a decode trace
        beyond the first is a recompile (the ==0 steady-state SLO)."""
        t = self._decode_pure.traces
        if t > self._decode_traces_seen:
            if self._decode_traces_seen >= 1:
                self._m_recompiles.inc(t - self._decode_traces_seen)
            self._decode_traces_seen = t
        self._m_decode_traces.set(t)
        self._m_prefill_traces.set(self._prefill_pure.traces)

    def metrics_snapshot(self):
        """JSON-able snapshot of this engine's serving metrics."""
        return self.metrics.snapshot()

    # -- request-scoped tracing / step phases ------------------------------
    def _phase(self, name):
        """Enter one named host phase of the current step (exclusive
        accounting — nesting pauses the enclosing phase) and, with
        tracing on, record it as a span."""
        if self.tracer is None:
            return self._phases.phase(name)
        return self._traced_phase(name)

    @contextmanager
    def _traced_phase(self, name):
        t0 = now_us()
        with self._phases.phase(name):
            yield
        self.tracer.add_span("phase." + name, t0, now_us(),
                             cat="phase")

    def _trace_span(self, name, start_us, req=None, tid=0,
                    cat="request", **attrs):
        """Close a request-scoped span started at `start_us` (no-op
        with tracing off or an untraced request)."""
        if self.tracer is None:
            return
        self.tracer.add_span(
            name, start_us, now_us(), tid=tid, cat=cat,
            trace_id=None if req is None else req.trace_id,
            args={"req_id": str(req.req_id), **attrs} if req is not None
            else (attrs or None))

    def _trace_instant(self, name, req=None, **attrs):
        if self.tracer is None:
            return
        self.tracer.add_instant(
            name, cat="request",
            trace_id=None if req is None else req.trace_id,
            args={"req_id": str(req.req_id), **attrs} if req is not None
            else (attrs or None))

    def _flush_step_phases(self, wall):
        """Fold the finished step's phase clock into the host-gap
        histogram and the device-fraction gauge."""
        totals = self._phases.reset()
        if not totals:
            return
        for phase, dt in totals.items():
            self._m_host_gap.labels(phase=phase).observe(dt)
        dev = totals.get("device_wait", 0.0)
        self._m_device_fraction.set(
            min(dev / wall, 1.0) if wall > 0 else 0.0)

    def dump_flight_recorder(self):
        """The bounded ring of recent request-lifecycle events
        (oldest first, JSON-able) — the postmortem `drain()`'s leak
        audit attaches automatically."""
        return self.flight.dump()

    def _audit_error(self, msg):
        """A drain-audit failure with the flight-recorder history
        attached: the bare assertion becomes a postmortem."""
        return RuntimeError(msg + "\n" + self.flight.format(limit=64))

    def export_trace(self, path, include_profiler=True):
        """Write this engine's span ring as one Chrome trace-event /
        Perfetto JSON timeline, merged (same monotonic clock) with any
        spans currently buffered in the profiler's host-event stream.
        Returns the event count written."""
        if self.tracer is None:
            raise RuntimeError(
                "tracing is off — build the engine with tracing=True "
                "(or PADDLE_SERVE_TRACING=1) to record spans")
        groups = [("engine", self.tracer.snapshot())]
        if include_profiler:
            ev = profiler_host_events()
            if ev:
                groups.append(("profiler", ev))
        return export_timeline(path, groups)

    # -- compiled steps ----------------------------------------------------
    def _default_buckets(self):
        b, out = 16, []
        while b < self.max_model_len:
            out.append(b)
            b *= 2
        out.append(self.max_model_len)
        return out

    def _lora_args(self, rest):
        """Unpack a compiled step's OPTIONAL adapter tail: with the
        adapter subsystem on, the pool arrays ride as one tuple arg
        right before the host args and the per-slot page row is the
        LAST host arg. Returns (LoraState-or-None, remaining rest)."""
        if self.adapter_pool is None:
            return None, rest
        from paddle_tpu.ops.lora import LoraState

        return LoraState(rest[0], rest[-1]), rest[1:-1]

    def _build_decode(self):
        model, state = self.model, self._state
        backend = self.attention_backend
        mp_axis = self._mp_axis
        use_q = self.kv_dtype == "int8"
        use_s = self.sampling

        def decode_fn(state_arrays, kpool, vpool, *rest):
            scales = rest[0] if use_q else None
            lora, rest = self._lora_args(rest[1:] if use_q else rest)
            if use_s:
                (tokens, positions, tables,
                 temps, tks, tps, krows) = rest
            else:
                tokens, positions, tables = rest
            arrays = self._materialize_state(state_arrays)
            with bound_state(zip(state, arrays), state):
                r = model.gpt.forward_decode_paged(
                    Tensor._wrap(tokens), Tensor._wrap(positions),
                    Tensor._wrap(kpool), Tensor._wrap(vpool),
                    Tensor._wrap(tables), backend=backend,
                    mp_axis=mp_axis,
                    kv_scales=None if scales is None
                    else Tensor._wrap(scales), lora=lora)
                logits = model._logits_of(r[0], mp_axis=mp_axis)
                if use_s:
                    # per-slot categorical draws on device; greedy
                    # rows take the literal argmax (bit-identical to
                    # the branch below). Draws fold (key row, this
                    # row's absolute position) — replicated at mp>1:
                    # same keys, same all-gathered logits, no
                    # collective.
                    from paddle_tpu.ops.sampling import sample_token

                    nxt = sample_token(logits._array[:, 0], temps,
                                       tks, tps, krows, positions)
                else:
                    nxt = jnp.argmax(logits._array[:, 0], axis=-1) \
                        .astype(jnp.int32)            # logits [slots,1,V]
                return (nxt,) + tuple(t._array for t in r[1:])

        decode_fn.__name__ = "engine_decode_step"
        return self._shard_steps(decode_fn, n_repl=7 if use_s else 3)

    def _build_verify(self):
        """The speculative decode step: one fixed `[slots, K+1]` window
        scores the feed token plus up to K drafts per lane in a single
        target-model pass. Per-row positions and draft lengths are
        traced, so every acceptance outcome reuses ONE program. Under
        sampling the step ALSO runs the rejection-sampling acceptance
        on device (all K+1 logit positions are in hand) and leads with
        the (choices, accepts) pair instead of the argmax row."""
        model, state = self.model, self._state
        backend = self.attention_backend
        mp_axis = self._mp_axis
        use_q = self.kv_dtype == "int8"
        use_s = self.sampling

        def verify_fn(state_arrays, kpool, vpool, *rest):
            scales = rest[0] if use_q else None
            lora, rest = self._lora_args(rest[1:] if use_q else rest)
            if use_s:
                (tokens, positions, dlens, tables,
                 temps, tks, tps, krows) = rest
            else:
                tokens, positions, dlens, tables = rest
            arrays = self._materialize_state(state_arrays)
            with bound_state(zip(state, arrays), state):
                r = model.gpt.forward_verify_paged(
                    Tensor._wrap(tokens), Tensor._wrap(positions),
                    Tensor._wrap(dlens), Tensor._wrap(kpool),
                    Tensor._wrap(vpool), Tensor._wrap(tables),
                    backend=backend, mp_axis=mp_axis,
                    kv_scales=None if scales is None
                    else Tensor._wrap(scales), lora=lora)
                logits = model._logits_of(r[0], mp_axis=mp_axis)
                if use_s:
                    # rejection-sampling acceptance in the same
                    # compiled program: per-row accept coins + the
                    # residual/bonus resamples (greedy rows pin the
                    # argmax / equality contract) — replicated at
                    # mp>1, no collective
                    from paddle_tpu.ops.sampling import verify_window

                    choices, accepts = verify_window(
                        logits._array, tokens, dlens, temps, tks,
                        tps, krows, positions)
                    return (choices, accepts) \
                        + tuple(t._array for t in r[1:])
                nxt = jnp.argmax(logits._array, axis=-1) \
                    .astype(jnp.int32)           # logits [slots,K+1,V]
                return (nxt,) + tuple(t._array for t in r[1:])

        verify_fn.__name__ = "engine_verify_step"
        return self._shard_steps(verify_fn, n_repl=8 if use_s else 4,
                                 n_out=2 if use_s else 1)

    def _build_prefill(self):
        from paddle_tpu.ops.paged_attention import paged_prefill_write

        model, state = self.model, self._state
        mp_axis = self._mp_axis
        use_q = self.kv_dtype == "int8"
        use_s = self.sampling

        def prefill_fn(state_arrays, kpool, vpool, *rest):
            # tokens [1, bucket]; plen traced -> one program per bucket
            scales = rest[0] if use_q else None
            lora, rest = self._lora_args(rest[1:] if use_q else rest)
            if use_s:
                tokens, plen, table_row, temps, tks, tps, krows = rest
            else:
                tokens, plen, table_row = rest
            arrays = self._materialize_state(state_arrays)
            with bound_state(zip(state, arrays), state):
                hidden, ks, vs = model.gpt.forward_prefill(
                    Tensor._wrap(tokens), mp_axis=mp_axis, lora=lora)
                w = paged_prefill_write(
                    Tensor._wrap(kpool), Tensor._wrap(vpool), ks, vs,
                    Tensor._wrap(table_row), Tensor._wrap(plen),
                    scales=None if scales is None
                    else Tensor._wrap(scales), mp_axis=mp_axis)
                # only the last REAL position's logits matter: one-hot
                # reduce to [1,1,H] before the vocab matmul
                sel = (jnp.arange(tokens.shape[1]) == plen - 1) \
                    .astype(hidden._array.dtype)
                h_last = (hidden._array * sel[None, :, None]) \
                    .sum(axis=1, keepdims=True)
                logits = model._logits_of(Tensor._wrap(h_last),
                                          mp_axis=mp_axis)
                if use_s:
                    # the FIRST generated token samples too: it lands
                    # at position plen, so its draw folds plen-1 —
                    # exactly the key a full-prefix-hit decode (or the
                    # final prefill chunk) would fold for it
                    from paddle_tpu.ops.sampling import sample_token

                    nxt = sample_token(
                        logits._array[:, 0], temps, tks, tps, krows,
                        jnp.maximum(plen - 1, 0).reshape(1))[0]
                else:
                    nxt = jnp.argmax(logits._array[0, 0]) \
                        .astype(jnp.int32)
                return (nxt,) + tuple(t._array for t in w)

        prefill_fn.__name__ = "engine_prefill"
        return self._shard_steps(prefill_fn, n_repl=7 if use_s else 3)

    def _build_prefill_chunk(self):
        model, state = self.model, self._state
        C = self.prefill_chunk
        mp_axis = self._mp_axis
        use_q = self.kv_dtype == "int8"
        use_s = self.sampling

        def prefill_chunk_fn(state_arrays, kpool, vpool, *rest):
            # tokens [1, C] FIXED; start/plen traced -> ONE program
            # serves every chunk of every prompt length
            scales = rest[0] if use_q else None
            lora, rest = self._lora_args(rest[1:] if use_q else rest)
            if use_s:
                (tokens, start, plen, table_row,
                 temps, tks, tps, krows) = rest
            else:
                tokens, start, plen, table_row = rest
            arrays = self._materialize_state(state_arrays)
            with bound_state(zip(state, arrays), state):
                r = model.gpt.forward_prefill_chunk(
                    Tensor._wrap(tokens), Tensor._wrap(start),
                    Tensor._wrap(kpool), Tensor._wrap(vpool),
                    Tensor._wrap(table_row), Tensor._wrap(plen),
                    mp_axis=mp_axis,
                    kv_scales=None if scales is None
                    else Tensor._wrap(scales), lora=lora)
                # the LAST REAL prompt position's logits yield the
                # first generated token; it lives in the final chunk —
                # for earlier chunks the one-hot selects nothing and
                # the host ignores the returned token
                sel = (start + jnp.arange(C) == plen - 1) \
                    .astype(r[0]._array.dtype)
                h_last = (r[0]._array * sel[None, :, None]) \
                    .sum(axis=1, keepdims=True)
                logits = model._logits_of(Tensor._wrap(h_last),
                                          mp_axis=mp_axis)
                if use_s:
                    # the first generated token's draw folds plen-1
                    # (it lands at position plen) — identical to the
                    # bucketed prefill's and the full-prefix-hit
                    # decode's key for that token
                    from paddle_tpu.ops.sampling import sample_token

                    nxt = sample_token(
                        logits._array[:, 0], temps, tks, tps, krows,
                        jnp.maximum(plen - 1, 0).reshape(1))[0]
                else:
                    nxt = jnp.argmax(logits._array[0, 0]) \
                        .astype(jnp.int32)
                return (nxt,) + tuple(t._array for t in r[1:])

        prefill_chunk_fn.__name__ = "engine_prefill_chunk"
        return self._shard_steps(prefill_chunk_fn,
                                 n_repl=8 if use_s else 4)

    # -- recompile probes (CI contract) ------------------------------------
    @property
    def decode_traces(self):
        """Times the decode step traced. Steady-state contract: 1,
        regardless of arrivals/evictions."""
        return self._decode_pure.traces

    @property
    def prefill_traces(self):
        """Times prefill traced — bounded by len(prefill_buckets)."""
        return self._prefill_pure.traces

    # -- request intake ----------------------------------------------------
    def _intake_guard(self, prompt, max_new_tokens, priority, req_id):
        """Shared admission validation + id claim for BOTH intake
        paths (`add_request` and the fleet's `adopt_request`), so the
        two can never drift: draining gate, prompt/budget/priority/
        length checks, auto-id allocation with collision detection.
        Returns the normalized (prompt, req_id)."""
        if self._draining:
            raise RuntimeError(
                "engine is draining — admissions are closed (finish "
                "the drain, or route to another replica)")
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        if prompt.size == 0:
            raise ValueError("empty prompt")
        if max_new_tokens < 1:
            raise ValueError("max_new_tokens must be >= 1")
        if priority not in PRIORITY_CLASSES:
            raise ValueError(f"priority must be one of "
                             f"{PRIORITY_CLASSES}, got {priority!r}")
        total = prompt.size + int(max_new_tokens)
        if total > self.max_model_len:
            raise ValueError(
                f"prompt({prompt.size}) + max_new({max_new_tokens}) = "
                f"{total} exceeds max_model_len={self.max_model_len}")
        if req_id is None:
            # skip over any live caller-chosen int ids
            while self._auto_id in self._in_flight():
                self._auto_id += 1
            req_id = self._auto_id
            self._auto_id += 1
        elif req_id in self._in_flight():
            raise ValueError(f"req_id {req_id!r} is already queued, "
                             "decoding, or awaiting collection")
        return prompt, req_id

    def add_request(self, prompt, max_new_tokens, eos_token_id=None,
                    req_id=None, priority="standard",
                    prefill_only=False, adapter_id=0,
                    sampling_params=None, trace_id=None):
        """Queue a request; admitted into a free slot between decode
        iterations (may be called while `run`/`step` is mid-stream).
        `priority` is one of PRIORITY_CLASSES — higher classes admit
        first and survive saturation shedding longer. With `max_queue`
        set and the queue full, the lowest-priority loser is shed: its
        result is recorded as None (the HTTP-429 of this API) and
        `engine_shed_total` counts it; the request kept is whichever
        of (incoming, worst queued) ranks higher.

        `prefill_only=True` is the disaggregated-serving intake: the
        engine prefills the prompt, emits the FIRST token, then parks
        the prompt's KV blocks for `take_handoff` instead of decoding
        further (`max_new_tokens` must be 1 — the fleet's decode
        replica owns the rest of the budget).

        `adapter_id` selects the tenant LoRA adapter the request
        decodes under (needs `GenerationEngine(adapters=...)`; 0 — the
        default — is the null/base adapter and always valid).

        `sampling_params` (a `SamplingParams`; needs
        `GenerationEngine(sampling=True)`) selects per-request
        temperature/top-k/top-p sampling — None (the default) and
        temperature=0 are the greedy contract, bit-identical to a
        no-sampling engine. A None seed is resolved here from the
        engine's deterministic counter, so a fixed trace replays
        token-for-token."""
        if prefill_only and max_new_tokens != 1:
            raise ValueError(
                "prefill_only requests carry max_new_tokens=1 (the "
                "single token the final prefill chunk yields); the "
                "decode replica owns the remaining budget")
        adapter_id = self._check_adapter(adapter_id)
        sampling_params = self._resolve_seed(
            self._check_sampling(sampling_params))
        prompt, req_id = self._intake_guard(prompt, max_new_tokens,
                                            priority, req_id)
        eos = self.eos_token_id if eos_token_id is None else eos_token_id
        if self.tracing and trace_id is None:
            trace_id = new_trace_id()
        req = Request(req_id, prompt, int(max_new_tokens), eos,
                      arrived_at=time.perf_counter(), priority=priority,
                      prefill_only=bool(prefill_only),
                      adapter_id=adapter_id, sampling=sampling_params,
                      trace_id=trace_id)
        self.flight.record("queued", req_id, priority=priority,
                           plen=int(prompt.size),
                           adapter=int(adapter_id))
        self._trace_instant("request.queued", req,
                            priority=priority, plen=int(prompt.size))
        if self.max_queue is not None \
                and self.num_pending >= self.max_queue:
            victim = self._shed_victim(priority)
            if victim is None:         # incoming ranks no better: shed it
                self._shed(req)
                return req_id
            self._shed(victim)
        self._queues[priority].append(req)
        self._m_queue.set(self.num_pending)
        return req_id

    def _shed_victim(self, incoming_priority):
        """Worst queued request STRICTLY below the incoming class
        (newest within it — it has waited least), or None when the
        incoming request is the one to shed."""
        rank = PRIORITY_CLASSES.index(incoming_priority)
        for p in reversed(PRIORITY_CLASSES[rank + 1:]):
            if self._queues[p]:
                return self._queues[p].pop()
        return None

    def _shed(self, req):
        self._results[req.req_id] = None
        self._m_shed.labels(priority=req.priority).inc()
        self._m_queue.set(self.num_pending)
        self.flight.record("shed", req.req_id, priority=req.priority)
        self._trace_instant("request.shed", req, priority=req.priority)

    # -- scheduler ---------------------------------------------------------
    def _bucket_for(self, plen):
        for b in self.prefill_buckets:
            if b >= plen:
                return b
        raise AssertionError("unreachable: last bucket covers "
                             "max_model_len")

    def _state_arrays(self):
        if self._tp_arrays is not None:
            # tensor parallel: the mesh-placed (weight-stationary)
            # snapshot — see refresh_weights()
            return list(self._tp_arrays)
        if self._q_arrays is not None:
            # int8 weights at mp=1: the quantized snapshot (weight-
            # stationary too — refresh_weights() requantizes)
            return list(self._q_arrays)
        return [t._array for t in self._state]

    def _dispatch_step(self, jitted, *host_args, n_out=1):
        """Invoke a compiled step: state + pools (+ the int8 scale
        array) (+ the adapter-pool arrays) threaded in, updated pools
        (+ scales) re-seated on the cache, the `n_out` leading outputs
        returned (token ids; the sampling verify step leads with its
        (choices, accepts) pair). With adapters on, the caller appends
        the per-slot adapter page row as the LAST host arg."""
        c = self.cache
        args = [self._state_arrays(), c.kpool, c.vpool]
        if c.scales is not None:
            args.append(c.scales)
        if self.adapter_pool is not None:
            args.append(self.adapter_pool.arrays())
        out = jitted(*args, *host_args)
        if c.scales is not None:
            c.kpool, c.vpool, c.scales = out[n_out:]
        else:
            c.kpool, c.vpool = out[n_out:]
        return out[0] if n_out == 1 else out[:n_out]

    def _in_flight(self):
        """Ids that would collide with a new request: queued, seated in
        a lane, finished but not yet drained by run()/pop_results(),
        or parked in the handoff buffer (a reused id there would
        overwrite the parked entry and leak its still-referenced
        blocks)."""
        ids = {r.req_id for p in PRIORITY_CLASSES
               for r in self._queues[p]}
        ids.update(s.req.req_id for s in self._slots if s is not None)
        ids.update(self._results)
        ids.update(self._handoffs)
        return ids

    def _peek_request(self):
        for p in PRIORITY_CLASSES:
            if self._queues[p]:
                return self._queues[p][0]
        return None

    def _pop_request(self):
        req = self._peek_request()
        if req is not None:
            self._queues[req.priority].popleft()
        return req

    def _release_adapter(self, slot):
        """Return a vacating lane's adapter-page reference (refcount
        down; the page parks warm in the pool's LRU at zero)."""
        if self.adapter_pool is not None and slot.req.adapter_id:
            self.adapter_pool.release(slot.req.adapter_id)
            self._update_adapter_gauges()

    def _note_tokens(self, req, n=1):
        """Account `n` freshly emitted tokens: the engine counter, the
        tokens-total series, and (probabilistic serving) the
        sampled-token series for temperature>0 lanes."""
        self.tokens_generated += n
        self._m_tokens.inc(n)
        if self._m_sampled_tokens is not None \
                and req.sampling is not None and not req.sampling.greedy:
            self._m_sampled_tokens.inc(n)

    def _finish(self, slot, reason):
        req = slot.req
        self._results[req.req_id] = \
            list(map(int, req.prompt)) + slot.generated
        self.cache.free(slot.blocks)
        self._release_adapter(slot)
        self._m_finished.labels(reason=reason).inc()
        self.flight.record("finish", req.req_id, reason=reason,
                           tokens=len(slot.generated))
        self._trace_instant("request.finish", req, reason=reason,
                            tokens=len(slot.generated))

    def _first_token(self, slot, first, t_step):
        """Seat a request's FIRST generated token (from the final
        prefill chunk or the whole-prompt bucketed prefill): TTFT,
        token accounting, prefix-cache publication, and instant-finish
        retirement. Returns False when the slot finished on the spot
        (its lane has been vacated)."""
        req = slot.req
        now = time.perf_counter()
        slot.generated.append(first)
        slot.last_token_at = now
        self._note_tokens(req)
        self.flight.record("first_token", req.req_id)
        self._trace_instant("request.first_token", req)
        if req.arrived_at is not None:
            self._obs_ttft(req, now - req.arrived_at)
        if self.enable_prefix_cache:
            # the prompt's KV is now fully written: publish its FULL
            # blocks for future admissions to seat read-only (under
            # the request's adapter-salted chain — a tenant's KV can
            # only ever hit the same tenant)
            self.cache.register_prefix(req.prompt, slot.blocks,
                                       adapter_id=req.adapter_id)
        done_eos = (req.eos_token_id is not None
                    and first == req.eos_token_id)
        if done_eos or req.max_new_tokens == 1:
            # instant finisher: its only token would otherwise be
            # invisible to the TPOT histogram while still counting in
            # engine_tokens_generated_total — record the producing
            # step's latency explicitly
            self._obs_tpot(req, now - t_step)
            if req.prefill_only:
                self._handoff_finish(slot)
            else:
                self._finish(slot, "eos" if done_eos else "length")
            self._slots[self._slots.index(slot)] = None
            return False
        return True

    def _handoff_finish(self, slot):
        """Retire a prefill-only lane WITHOUT freeing its blocks: the
        prompt's fully-written KV is this request's product. The blocks
        park in the handoff buffer (still referenced, so neither the
        allocator nor LRU eviction can recycle them) until the fleet
        claims them with `take_handoff`, exports their rows into a
        decode replica's pool, and returns them via
        `release_handoff`."""
        req = slot.req
        self._handoffs[req.req_id] = (list(slot.blocks),
                                      slot.hit_tokens)
        self._results[req.req_id] = \
            list(map(int, req.prompt)) + slot.generated
        # the adapter page is NOT parked with the blocks: its job
        # (prefill under the tenant's projections) is done, and the
        # decode replica acquires from its OWN pool at adoption
        self._release_adapter(slot)
        self._m_finished.labels(reason="handoff").inc()
        self.flight.record("handoff_parked", req.req_id,
                           blocks=len(slot.blocks))
        self._trace_instant("request.handoff", req,
                            blocks=len(slot.blocks))

    # -- admission: chunked (default) --------------------------------------
    def _admit_chunked(self):
        """Seat queued requests (priority order, FIFO within a class)
        into free lanes: match the longest cached block-aligned prefix,
        take read-only references on those blocks, and leave the tail
        for the incremental chunk prefill. No compute happens here —
        a full-prefix hit enters decode directly (feeding the last
        prompt token; copy-on-write keeps its write private)."""
        admitted = 0
        with self._phase("schedule"):
            while None in self._slots:
                req = self._pop_request()
                if req is None:
                    break
                page = self._acquire_adapter(req)
                if page is None:
                    # adapter-pool pressure: every page is referenced
                    # by a live lane. Requeue at the FRONT (strict
                    # order kept) and retry when a lane vacates — the
                    # KV stall/retry contract, page-sized.
                    self._queues[req.priority].appendleft(req)
                    break
                blocks, hit = [], 0
                if self.enable_prefix_cache:
                    with self._phase("prefix_lookup"):
                        blocks, hit = self.cache.match_prefix(
                            req.prompt, adapter_id=req.adapter_id)
                    if hit:
                        self.prefix_hit_tokens += hit
                        self._m_hit_tokens.inc(hit)
                slot = _Slot(req=req, blocks=list(blocks),
                             prefill_pos=hit,
                             hit_tokens=hit,
                             admit_seq=self._admit_counter,
                             adapter_page=page,
                             **self._slot_sampling_fields(req))
                self._admit_counter += 1
                self._slots[self._slots.index(None)] = slot
                self._m_admissions.inc()
                self.flight.record("admitted", req.req_id,
                                   hit_tokens=hit)
                self._trace_instant("request.admitted", req,
                                    hit_tokens=hit)
                self._update_pool_gauges()
                admitted += 1
        self._m_queue.set(self.num_pending)
        return admitted

    def _acquire_adapter(self, req):
        """Take the adapter-page reference a request's lane needs (the
        null adapter is page 0, never paged). Returns the page, or
        None on adapter-pool pressure (stall counted; caller requeues
        and retries — admission's analog of a KV block stall)."""
        if self.adapter_pool is None or not req.adapter_id:
            return 0
        with self._phase("adapter_swap"):
            swapins = self.adapter_pool.swapins
            page = self.adapter_pool.acquire(req.adapter_id)
        if page is None:
            self._m_stalls.labels(path="adapter",
                                  shard=self._shard).inc()
            self.flight.record("stall", req.req_id, path="adapter")
            return None
        if self.adapter_pool.swapins > swapins:
            # cold page: the acquire paid a host->device swap-in
            self.flight.record("adapter_swap_in", req.req_id,
                               adapter=int(req.adapter_id), page=page)
            self._trace_instant("adapter.swap_in", req,
                                adapter=int(req.adapter_id), page=page)
        self._update_adapter_gauges()
        return page

    def _prefill_step(self):
        """Run at most ONE compiled prefill chunk: pick the neediest
        prefilling lane (priority, then admission order), allocate the
        chunk's blocks (evicting cold cache blocks if necessary), and
        push `prefill_chunk` prompt positions through the fixed-shape
        chunk program. The final chunk yields the first generated
        token. A lane that cannot get blocks stalls and the next
        candidate gets the chunk."""
        with self._phase("schedule"):
            cands = [s for s in self._slots
                     if s is not None and s.prefilling]
            cands.sort(key=lambda s: (
                PRIORITY_CLASSES.index(s.req.priority), s.admit_seq))
        C = self.prefill_chunk
        for slot in cands:
            req = slot.req
            plen = int(req.prompt.size)
            start = slot.prefill_pos
            end = min(start + C, plen)
            with self._phase("schedule"):
                need = math.ceil(end / self.block_size) \
                    - len(slot.blocks)
                if need > 0:
                    got = self.cache.allocate(need)
                    if got is None:
                        self._m_stalls.labels(
                            path="prefill", shard=self._shard).inc()
                        self.flight.record("stall", req.req_id,
                                           path="prefill")
                        continue       # pool pressure: next candidate
                    slot.blocks.extend(got)
                    self._update_pool_gauges()
            t_span = now_us()
            with self._phase("dispatch"):
                tokens = np.zeros((1, C), np.int32)
                tokens[0, :end - start] = req.prompt[start:end]
                row = np.zeros(self.max_blocks, np.int32)
                row[:len(slot.blocks)] = slot.blocks
                args = [jnp.asarray(tokens), jnp.int32(start),
                        jnp.int32(plen), jnp.asarray(row)]
                if self.sampling:
                    # the chunk serves ONE slot: its sampling rows, [1]
                    args.extend(self._sampling_host_args_one(slot))
                if self.adapter_pool is not None:
                    # the chunk serves ONE slot: its adapter page,
                    # [1]-row
                    args.append(jnp.asarray(
                        np.asarray([slot.adapter_page], np.int32)))
                with RecordEvent("engine.prefill"):
                    t0 = time.perf_counter()
                    nxt = self._dispatch_step(self._prefill, *args)
                    self._m_prefill_chunks.inc()
                    slot.prefill_pos = end
                    if end < plen:     # mid-prompt: no sync needed
                        self._trace_span("prefill.chunk", t_span,
                                         req=req, start=start, end=end)
                        return 1
                    with self._phase("device_wait"):
                        first = int(nxt)   # sync: first token is out
            self._trace_span("prefill.chunk", t_span, req=req,
                             start=start, end=end, final=True)
            with self._phase("finish"):
                self._first_token(slot, first, t0)
            return 1
        return 0

    # -- admission: legacy whole-prompt bucketed prefill -------------------
    def _admit(self):
        """Fill free lanes from the queue (priority order): allocate
        the prompt's blocks, run the bucketed prefill (writes KV into
        the blocks, yields the first generated token), seat the slot."""
        admitted = 0
        while None in self._slots:
            req = self._peek_request()
            if req is None:
                break
            plen = int(req.prompt.size)
            with self._phase("schedule"):
                need = math.ceil(plen / self.block_size)
                blocks = self.cache.allocate(need)
            if blocks is None:
                self._m_stalls.labels(path="admit", shard=self._shard).inc()
                self.flight.record("stall", req.req_id, path="admit")
                break                      # pool pressure: retry later
            self._update_pool_gauges()     # high-water sees the peak
            # adapter page AFTER the blocks: a block stall must not
            # have burned a swap-in (or evicted another tenant's warm
            # page) for an admission that cannot seat anyway
            page = self._acquire_adapter(req)
            if page is None:
                self.cache.free(blocks)    # fresh, unhashed -> free list
                self._update_pool_gauges()
                break                  # adapter pressure: retry later
            self._pop_request()
            bucket = self._bucket_for(plen)
            slot = _Slot(req=req, blocks=blocks, prefill_pos=plen,
                         admit_seq=self._admit_counter,
                         adapter_page=page,
                         **self._slot_sampling_fields(req))
            self._admit_counter += 1
            self._slots[self._slots.index(None)] = slot
            self._m_admissions.inc()
            self.flight.record("admitted", req.req_id, bucket=bucket)
            self._trace_instant("request.admitted", req, bucket=bucket)
            admitted += 1
            t_span = now_us()
            with self._phase("dispatch"):
                tokens = np.zeros((1, bucket), np.int32)
                tokens[0, :plen] = req.prompt
                row = np.zeros(self.max_blocks, np.int32)
                row[:need] = blocks
                args = [jnp.asarray(tokens), jnp.int32(plen),
                        jnp.asarray(row)]
                if self.sampling:
                    args.extend(self._sampling_host_args_one(slot))
                if self.adapter_pool is not None:
                    args.append(jnp.asarray(
                        np.asarray([slot.adapter_page], np.int32)))
                with RecordEvent("engine.prefill"):
                    t0 = time.perf_counter()
                    first = self._dispatch_step(self._prefill, *args)
                    with self._phase("device_wait"):
                        first = int(first)   # sync: first token is out
            self._trace_span("prefill.bucketed", t_span, req=req,
                             bucket=bucket)
            with self._phase("finish"):
                self._first_token(slot, first, t0)
        self._m_queue.set(self.num_pending)
        return admitted

    # -- decode ------------------------------------------------------------
    def _cow_promote(self, slot, bi, count_stall=True):
        """Give `slot` a private copy of its table entry `bi` via the
        compiled block-copy step (the write is about to land there and
        other owners — slots or the prefix cache — still read it).
        Returns False when the pool cannot serve the copy (caller
        stalls the lane this iteration; `count_stall=False` when the
        caller has a degrade path and the lane may still run)."""
        got = self.cache.allocate(1)
        if got is None:
            if count_stall:
                self._m_stalls.labels(path="decode", shard=self._shard).inc()
                self.flight.record("stall", slot.req.req_id,
                                   path="decode")
            return False
        src, dst = slot.blocks[bi], got[0]
        with self._phase("cow"), RecordEvent("engine.cow"):
            if self.cache.scales is not None:
                # quantized pools: the block's per-layer grid rows
                # ride the copy — a COW'd block must dequantize on
                # the SAME grid its source was written with
                self.cache.kpool, self.cache.vpool, \
                    self.cache.scales = self._cow(
                        self.cache.kpool, self.cache.vpool,
                        jnp.int32(src), jnp.int32(dst),
                        self.cache.scales)
            else:
                self.cache.kpool, self.cache.vpool = self._cow(
                    self.cache.kpool, self.cache.vpool,
                    jnp.int32(src), jnp.int32(dst))
        self.cache.free([src])         # drop our shared reference
        slot.blocks[bi] = dst
        self._m_cow.inc()
        self._update_pool_gauges()
        return True

    def _decode_step(self):
        """One batched decode step over every decode-phase lane that
        holds an exclusively-writable block for its write position.
        Copy-on-write happens here: a lane whose feed position sits in
        a shared or prefix-cached block first gets a private copy via
        the compiled block-copy step.

        SERIAL core: schedule, dispatch, and complete run inline in
        this one call — the same operations in the same order as the
        pre-pipeline engine. The ASYNC core drives the same three
        stages through `_dispatch_ahead`/`_complete_inflight`, with
        the complete of step N and the dispatch of step N+1 split
        across `step()` calls."""
        if self.spec_decode_k:
            runnable, drafts = self._spec_schedule()
            if not runnable:
                return 0
            inflight = self._spec_dispatch(runnable, drafts)
            return self._spec_complete(inflight, synced=False)
        runnable = self._plain_schedule()
        if not runnable:
            return 0
        inflight = self._plain_dispatch(runnable)
        return self._plain_complete(inflight, synced=False)

    def _plain_schedule(self):
        """Schedule stage of a plain decode step: on-demand block
        growth + COW promotion per decode-phase lane; returns the
        runnable lane indices."""
        runnable = []
        with self._phase("schedule"):
            for i, slot in enumerate(self._slots):
                if slot is None or slot.prefilling:
                    continue
                bi = slot.feed_pos // self.block_size
                if bi >= len(slot.blocks):
                    # on-demand growth: the feed position opens a new
                    # block
                    got = self.cache.allocate(1)
                    if got is None:
                        self._m_stalls.labels(
                            path="decode", shard=self._shard).inc()
                        self.flight.record("stall", slot.req.req_id,
                                           path="decode")
                        continue       # stalled this iteration
                    slot.blocks.extend(got)
                    self._update_pool_gauges()
                elif self.cache.needs_cow(slot.blocks[bi]):
                    # the write position sits in a block other owners
                    # (or the prefix cache) still read — promote to a
                    # private copy so the shared KV stays
                    # byte-identical for them
                    if not self._cow_promote(slot, bi):
                        continue       # pool pressure: stalled
                runnable.append(i)
        return runnable

    def _plain_dispatch(self, runnable):
        """Dispatch stage of a plain decode step: build the dynamic
        host rows, move them in one `_put_host_args` batch, and issue
        the compiled step WITHOUT waiting on its output. Returns the
        `_InFlight` record the complete stage consumes."""
        t_span = now_us()
        with self._phase("dispatch"):
            tokens = np.zeros((self.num_slots, 1), np.int32)
            positions = np.zeros(self.num_slots, np.int32)
            tables = np.zeros((self.num_slots, self.max_blocks),
                              np.int32)
            arows = np.zeros(self.num_slots, np.int32)
            for i in runnable:
                slot = self._slots[i]
                tokens[i, 0] = slot.feed_token
                positions[i] = slot.feed_pos
                tables[i, :len(slot.blocks)] = slot.blocks
                arows[i] = slot.adapter_page
            rows = [tokens, positions, tables]
            if self.sampling:
                # per-slot sampling rows (idle/greedy lanes ride temp
                # 0 — the argmax select, like the null block)
                rows.extend(self._sampling_host_rows())
            if self.adapter_pool is not None:
                # per-slot adapter page row (idle/stalled lanes ride
                # the null page 0 — exact-zero delta, like the null
                # block)
                rows.append(arows)
            args = self._put_host_args(rows)
            with RecordEvent("engine.decode"):
                t_dec = time.perf_counter()
                nxt = self._dispatch_step(self._decode, *args)
        self._step_seq += 1
        return _InFlight(out=nxt, runnable=runnable,
                         slots=[self._slots[i] for i in runnable],
                         t_dec=t_dec, t_span=t_span,
                         seq=self._step_seq)

    def _plain_complete(self, inflight, synced):
        """Complete stage of a plain decode step: sync on the device
        output, then the per-lane finish walk. `synced=False` is the
        serial core — the np.asarray IS the device sync, measured as
        `device_wait`; `synced=True` is the async core, where
        `_complete_inflight` already blocked (the true residual) and
        this conversion is only a host copy."""
        if synced:
            nxt = np.asarray(inflight.out)
        else:
            with self._phase("device_wait"):
                nxt = np.asarray(inflight.out)  # sync: tokens are out
        self._m_decode_seconds.observe(
            time.perf_counter() - inflight.t_dec)
        self._trace_span("decode.step", inflight.t_span, cat="engine",
                         lanes=len(inflight.runnable))
        t_dec = inflight.t_dec
        now = time.perf_counter()
        with self._phase("finish"):
            for i, slot in zip(inflight.runnable, inflight.slots):
                tok = int(nxt[i])
                is_first = not slot.generated   # full-prefix-hit lane
                slot.generated.append(tok)
                req = slot.req
                self._note_tokens(req)
                if is_first:
                    # this decode produced the request's FIRST token
                    # (its whole prompt came from the prefix cache)
                    if req.arrived_at is not None:
                        self._obs_ttft(req, now - req.arrived_at)
                    self.flight.record("first_token", req.req_id)
                    self._trace_instant("request.first_token", req)
                elif slot.last_token_at is not None:
                    # inter-token latency per SLOT, not this
                    # iteration's wall time: a lane that sat out N
                    # stalled iterations reports the (N+1)-iteration
                    # gap its user experienced
                    self._obs_tpot(req, now - slot.last_token_at)
                slot.last_token_at = now
                done_eos = req.eos_token_id is not None \
                    and tok == req.eos_token_id
                if done_eos or len(slot.generated) >= req.max_new_tokens:
                    if is_first:
                        # single-token request: its only token still
                        # lands in the TPOT histogram (producing-step
                        # latency)
                        self._obs_tpot(req, now - t_dec)
                    if req.prefill_only:
                        # full-prefix-hit prefill-only lane: its one
                        # decode step produced the first token — park
                        # the blocks for the disaggregated handoff,
                        # don't free them
                        self._handoff_finish(slot)
                    else:
                        self._finish(slot,
                                     "eos" if done_eos else "length")
                    self._slots[i] = None
        return len(inflight.runnable)

    def _spec_schedule(self):
        """Schedule stage of a speculative verify step: draft up to K
        tokens per decode-phase lane (host-side, between compiled
        steps — or joined from the async core's drafter thread via
        `_next_drafts`), then grow and COW-protect every block the
        `[feed_pos, feed_pos+k]` write window touches. Rejection is
        rollback by position: the lane simply does not advance past
        the accepted prefix, so the rejected rows' KV is unreachable
        (attention is position-bounded) until the next window
        overwrites it. A lane that cannot get blocks for its window
        degrades to a draftless (plain-decode) window before it
        stalls. Returns (runnable lane indices, lane -> draft)."""
        K = self.spec_decode_k
        bs = self.block_size
        vocab = self.model.config.vocab_size
        runnable, drafts = [], {}
        with self._phase("schedule"):
            for i, slot in enumerate(self._slots):
                if slot is None or slot.prefilling:
                    continue
                req = slot.req
                # window budget: emitted tokens cap at the request's
                # remaining allowance, and the last write position
                # must stay inside the model's length
                budget = min(
                    K,
                    req.max_new_tokens - len(slot.generated) - 1,
                    self.max_model_len - 1 - slot.feed_pos)
                draft = []
                if budget > 0:
                    with self._phase("draft_propose"):
                        # async core: the drafter thread proposed this
                        # window from the SAME post-walk context while
                        # admissions ran — identical inputs, identical
                        # draft (the serial-vs-async identity gate).
                        # Serial core / fresh lanes: propose inline.
                        draft = self._next_drafts.pop(slot, None)
                        if draft is None:
                            draft = draft_window(
                                self.drafter, req.prompt,
                                slot.generated, budget, vocab)
                # grow the table to cover the window's last write;
                # under pool pressure shed the draft (plain one-token
                # window) before stalling the lane outright
                stalled = False
                while True:
                    need = (slot.feed_pos + len(draft)) // bs + 1 \
                        - len(slot.blocks)
                    if need <= 0:
                        break
                    got = self.cache.allocate(need)
                    if got is not None:
                        slot.blocks.extend(got)
                        self._update_pool_gauges()
                        break
                    if not draft:
                        self._m_stalls.labels(
                            path="decode", shard=self._shard).inc()
                        self.flight.record("stall", req.req_id,
                                           path="decode")
                        stalled = True
                        break
                    draft = []         # degrade: draftless step
                    self._m_stalls.labels(
                        path="spec_degrade", shard=self._shard).inc()
                    self.flight.record("stall", req.req_id,
                                       path="spec_degrade")
                if stalled:
                    continue
                # copy-on-write over EVERY block the window writes
                # into — a speculative write must never land in a
                # block other owners (or the prefix cache) still read
                def cow_window(k_len, count_stall):
                    for bi in range(slot.feed_pos // bs,
                                    (slot.feed_pos + k_len) // bs + 1):
                        if self.cache.needs_cow(slot.blocks[bi]) \
                                and not self._cow_promote(
                                    slot, bi, count_stall=count_stall):
                            return False
                    return True

                if not cow_window(len(draft), count_stall=False):
                    # pool pressure mid-window: shed the draft AND the
                    # surplus tail blocks past the feed block (always
                    # private — they only ever held rejected rows), so
                    # the pool gets them back, then retry the plain
                    # one-token window. Without this a lane could sit
                    # on window blocks while stalling on the COW copy
                    # — deadlocking pools where the K=0 engine
                    # progresses. The degrade is its own stall flavor:
                    # the lane still RUNS, so it must not read as a
                    # skipped iteration.
                    feed_bi = slot.feed_pos // bs
                    surplus = slot.blocks[feed_bi + 1:]
                    if surplus:
                        del slot.blocks[feed_bi + 1:]
                        self.cache.free(surplus)
                        self._update_pool_gauges()
                    if draft:
                        draft = []
                        self._m_stalls.labels(
                            path="spec_degrade", shard=self._shard).inc()
                        self.flight.record("stall", req.req_id,
                                           path="spec_degrade")
                    if not cow_window(0, count_stall=True):
                        continue       # truly stalled this iteration
                drafts[i] = draft
                runnable.append(i)
        return runnable, drafts

    def _spec_dispatch(self, runnable, drafts):
        """Dispatch stage of a speculative verify step: score all K+1
        positions of every runnable lane in ONE compiled pass, issued
        without waiting (one fused `_put_host_args` transfer for the
        dynamic rows). Returns the `_InFlight` record."""
        K = self.spec_decode_k
        W = K + 1
        t_span = now_us()
        with self._phase("dispatch"):
            tokens = np.zeros((self.num_slots, W), np.int32)
            positions = np.zeros(self.num_slots, np.int32)
            dlens = np.zeros(self.num_slots, np.int32)
            tables = np.zeros((self.num_slots, self.max_blocks),
                              np.int32)
            arows = np.zeros(self.num_slots, np.int32)
            for i in runnable:
                slot = self._slots[i]
                d = drafts[i]
                tokens[i, 0] = slot.feed_token
                if d:
                    tokens[i, 1:1 + len(d)] = d
                positions[i] = slot.feed_pos
                dlens[i] = len(d)
                tables[i, :len(slot.blocks)] = slot.blocks
                arows[i] = slot.adapter_page
            rows = [tokens, positions, dlens, tables]
            if self.sampling:
                rows.extend(self._sampling_host_rows())
            if self.adapter_pool is not None:
                rows.append(arows)
            args = self._put_host_args(rows)
            with RecordEvent("engine.decode"):
                t_dec = time.perf_counter()
                out_dev = self._dispatch_step(self._decode, *args,
                                              n_out=self._decode_n_out)
        self._step_seq += 1
        return _InFlight(out=out_dev, runnable=runnable,
                         slots=[self._slots[i] for i in runnable],
                         drafts=drafts, t_dec=t_dec, t_span=t_span,
                         seq=self._step_seq)

    def _spec_complete(self, inflight, synced):
        """Complete stage of a speculative verify step: sync on the
        verify output, then emit the longest draft prefix the target
        confirms plus the target's own next token, per lane. The
        acceptance/sample walks stay on the step thread (their result
        decides the next window's context AND which lanes retire —
        allocator state must not change under an in-flight reader).
        `synced` as in `_plain_complete`."""
        K = self.spec_decode_k
        out_dev = inflight.out
        if synced:
            if self.sampling:
                choices = np.asarray(out_dev[0])
                accepts = np.asarray(out_dev[1])
                nxt = None
            else:
                nxt = np.asarray(out_dev)
        else:
            with self._phase("device_wait"):
                if self.sampling:
                    # sync: per-row stop-choices + accept flags
                    choices = np.asarray(out_dev[0])
                    accepts = np.asarray(out_dev[1])
                    nxt = None
                else:
                    # sync: [slots, K+1] argmaxes
                    nxt = np.asarray(out_dev)
        self._m_decode_seconds.observe(
            time.perf_counter() - inflight.t_dec)
        self._trace_span("decode.verify", inflight.t_span, cat="engine",
                         lanes=len(inflight.runnable), k=K)
        t_dec = inflight.t_dec
        now = time.perf_counter()
        with self._phase("finish"):
            for i, slot in zip(inflight.runnable, inflight.slots):
                req = slot.req
                d = inflight.drafts[i]
                if self.sampling:
                    # rejection-sampling acceptance (computed on
                    # device): accept the longest draft prefix whose
                    # coins passed, then the stop row's choice — the
                    # residual resample on a rejection, the bonus draw
                    # on a full accept. Greedy lanes' flags are exact
                    # argmax equality and their choices the argmax, so
                    # this walk reproduces the exact-acceptance stream
                    # bit-for-bit.
                    with self._phase("sample_walk"):
                        n = 0
                        while n < len(d) and accepts[i, n]:
                            n += 1
                        acc = [int(t) for t in d[:n]] \
                            + [int(choices[i, n])]
                else:
                    # exact greedy acceptance: the target's own next
                    # token, then every draft token that EQUALS the
                    # target's argmax at its position (each match
                    # validates the next column)
                    with self._phase("accept_walk"):
                        out = nxt[i]
                        acc = [int(out[0])]
                        for j, dj in enumerate(d):
                            if dj != int(out[j]):
                                break
                            acc.append(int(out[j + 1]))
                self._m_spec_ok.inc(len(acc) - 1)
                self._m_spec_rej.inc(len(d) - (len(acc) - 1))
                # EOS / length truncation: emit stops AT the first
                # stop token, exactly like the one-token path would
                emit = []
                for t in acc:
                    emit.append(t)
                    if (req.eos_token_id is not None
                            and t == req.eos_token_id) \
                            or len(slot.generated) + len(emit) \
                            >= req.max_new_tokens:
                        break
                m_tok = len(emit)
                is_first = not slot.generated  # full-prefix-hit lane
                slot.generated.extend(emit)
                self._note_tokens(req, m_tok)
                self._m_spec_accepted.observe(m_tok)
                proposed = self._m_spec_ok.value \
                    + self._m_spec_rej.value
                if proposed:
                    self._m_spec_hit_rate.set(
                        self._m_spec_ok.value / proposed)
                if is_first:
                    if req.arrived_at is not None:
                        self._obs_ttft(req, now - req.arrived_at)
                    self.flight.record("first_token", req.req_id)
                    self._trace_instant("request.first_token", req)
                # multi-token latency accounting: every accepted token
                # is recorded against its producing step — the lane's
                # step gap amortized per token, so TPOT sums still
                # integrate to wall time and m_tok=1 degenerates to
                # the plain path
                gap = now - (t_dec
                             if is_first or slot.last_token_at is None
                             else slot.last_token_at)
                n_tpot = m_tok - 1 if is_first else m_tok
                for _ in range(n_tpot):
                    self._obs_tpot(req, gap / m_tok)
                slot.last_token_at = now
                done_eos = req.eos_token_id is not None \
                    and emit[-1] == req.eos_token_id
                if done_eos or len(slot.generated) >= req.max_new_tokens:
                    if is_first and n_tpot == 0:
                        # single-token instant finisher: keep it
                        # visible (the PR-6 TPOT contract)
                        self._obs_tpot(req, now - t_dec)
                    if req.prefill_only:
                        self._handoff_finish(slot)
                    else:
                        self._finish(slot,
                                     "eos" if done_eos else "length")
                    self._slots[i] = None
        return len(inflight.runnable)

    def step(self):
        """One scheduler iteration: admit queued requests into free
        lanes, run AT MOST one prefill chunk (chunked mode — long
        prompts never monopolize an iteration), then one batched decode
        step over every decode-phase lane. Returns the number of
        admissions/chunks/lanes that made progress.

        With the async core on (`async_core=True` / PADDLE_SERVE_ASYNC)
        the same stages run pipelined one step ahead — `_step_async`;
        off (the default) this is the serial loop, op-for-op."""
        if self.async_core:
            return self._step_async()
        with RecordEvent("engine.step"):
            t_wall = time.perf_counter()
            if self.chunked_prefill:
                progressed = self._admit_chunked()
                progressed += self._prefill_step()
            else:
                progressed = self._admit()
            progressed += self._decode_step()
            self._flush_step_phases(time.perf_counter() - t_wall)
            self._end_of_step_gauges()
            return progressed

    # -- async engine core (dispatch-ahead pipeline) -----------------------
    def _step_async(self):
        """One pipelined scheduler iteration — the dispatch-ahead core
        (ROADMAP item 3). Stage order per call:

        1. COMPLETE step N: `jax.block_until_ready` on the in-flight
           output the PREVIOUS call dispatched. `device_wait` here is
           the true residual — every host stage since that dispatch
           (the previous call's adapter prefetch, the caller's
           inter-step work, e.g. the fleet's other replicas) already
           overlapped the device time. The acceptance/sample walks and
           lane retirement stay on the step thread: their results
           decide the NEXT window's context, and a retired lane's
           blocks must not re-enter the allocator while a dispatched
           step could still write to them.
        2. SPAWN the drafter helper: every decode lane's next-window
           proposal runs on a short-lived thread over SNAPSHOTS of the
           post-walk context — identical inputs to the serial
           proposal, so drafts (and therefore sampled lanes'
           acceptance coins) cannot diverge.
        3. ADMIT + one prefill chunk on the step thread, concurrently
           with the helper.
        4. SCHEDULE + DISPATCH step N+1: drafts joined from the
           helper (lanes the helper missed — just admitted or fresh
           out of prefill — propose inline, exactly the serial path),
           dynamic rows ride ONE fused `device_put` tree, and the
           dispatched step stays in the in-flight slot for the next
           call.
        5. PREFETCH the queue head's adapter page: the compiled
           swap-in dispatch is cheap host-side and the page copy
           overlaps step N+1 on device, so the NEXT call's admission
           acquires a resident page.

        `progressed` counts admissions, prefill chunks, and COMPLETED
        decode lanes — a dispatch is credited only when its result is
        consumed, so run totals match the serial core and `run()`'s
        no-progress deadlock check stays sound (an outstanding
        in-flight step always progresses on the next call)."""
        with RecordEvent("engine.step"):
            t_wall = time.perf_counter()
            progressed = self._complete_inflight()
            self._spawn_ahead()
            if self.chunked_prefill:
                progressed += self._admit_chunked()
                progressed += self._prefill_step()
            else:
                progressed += self._admit()
            self._next_drafts = self._collect_ahead()
            self._dispatch_ahead()
            self._next_drafts = {}
            self._prefetch_ahead()
            self._flush_step_phases(time.perf_counter() - t_wall)
            self._end_of_step_gauges()
            return progressed

    def _complete_inflight(self):
        """Retire the dispatched-ahead step, if one is outstanding:
        block for the device residual, then run the normal complete
        stage (walks + finish) on the step thread."""
        inflight = self._inflight
        if inflight is None:
            return 0
        self._inflight = None
        with self._phase("device_wait"):
            # the ONLY wait of the pipeline: everything since the
            # dispatch already ran behind the device step
            jax.block_until_ready(inflight.out)
        self.flight.record("async_complete", seq=inflight.seq,
                           lanes=len(inflight.runnable))
        if self.spec_decode_k:
            return self._spec_complete(inflight, synced=True)
        return self._plain_complete(inflight, synced=True)

    def _dispatch_ahead(self):
        """Schedule + dispatch the next decode/verify step into the
        single in-flight slot — no wait; the next `step()` call (or
        `drain`) completes it."""
        if self.spec_decode_k:
            runnable, drafts = self._spec_schedule()
            if not runnable:
                return
            self._inflight = self._spec_dispatch(runnable, drafts)
        else:
            runnable = self._plain_schedule()
            if not runnable:
                return
            self._inflight = self._plain_dispatch(runnable)
        self.flight.record("async_dispatch", seq=self._inflight.seq,
                           lanes=len(runnable))

    def _spawn_ahead(self):
        """Launch the drafter helper thread: propose every decode
        lane's next verify window off the step thread while admissions
        and the prefill chunk run. Jobs snapshot `generated` (the live
        list mutates when lanes advance) and run the pure
        `draft_window` — see its thread-safety contract. The helper's
        `draft_propose` seconds land on ITS thread-confined PhaseTimer
        clock, never in the step's host-gap partition."""
        if not self.spec_decode_k or self.drafter is None:
            return
        K = self.spec_decode_k
        vocab = self.model.config.vocab_size
        jobs = []
        for slot in self._slots:
            if slot is None or slot.prefilling:
                continue
            budget = min(
                K,
                slot.req.max_new_tokens - len(slot.generated) - 1,
                self.max_model_len - 1 - slot.feed_pos)
            if budget > 0:
                jobs.append((slot, slot.req.prompt,
                             list(slot.generated), budget))
        if not jobs:
            return
        out = {}
        phases = self._phases
        drafter = self.drafter

        def work():
            for slot, prompt, generated, budget in jobs:
                with phases.phase("draft_propose"):
                    out[slot] = draft_window(drafter, prompt,
                                             generated, budget, vocab)

        t = threading.Thread(target=work, name="paddle-draft-ahead",
                             daemon=True)
        t.start()
        self._ahead = (t, out)

    def _collect_ahead(self):
        """Join the drafter helper. Only the step thread's residual
        wait (usually ~zero — admissions ran in between) lands in its
        own `draft_propose` phase; the proposals themselves were
        clocked on the helper's thread."""
        ahead = self._ahead
        if ahead is None:
            return {}
        self._ahead = None
        t, out = ahead
        with self._phase("draft_propose"):
            t.join()
        return out

    def _prefetch_ahead(self):
        """Warm the NEXT admission's adapter page behind the step just
        dispatched: `PagedAdapterPool.prefetch` costs one compiled
        swap-in dispatch on the host while the page copy overlaps the
        in-flight step on device, and it never takes a reference or
        evicts a live page — so the next call's `_acquire_adapter`
        finds the page resident and pays no transfer in the host
        gap."""
        if self.adapter_pool is None:
            return
        req = self._peek_request()
        if req is None or not req.adapter_id \
                or not self.adapter_pool.registry.has(req.adapter_id):
            return
        if self.adapter_pool.page_of(req.adapter_id) is not None:
            return                     # already resident (warm or live)
        page = self.adapter_pool.prefetch(req.adapter_id)
        if page is not None:
            self.flight.record("adapter_prefetch", req.req_id,
                               adapter=int(req.adapter_id), page=page)
            self._update_adapter_gauges()

    def _end_of_step_gauges(self):
        self._m_active.set(self.num_active)
        self._m_queue.set(self.num_pending)
        self._update_pool_gauges()
        self._update_adapter_gauges()
        self._sample_traces()
        if self._m_trace_spans is not None:
            total = self.tracer.total_recorded
            self._m_trace_spans.inc(total - self._trace_spans_seen)
            self._trace_spans_seen = total
            dropped = self.tracer.dropped
            self._m_trace_dropped.inc(dropped - self._trace_dropped_seen)
            self._trace_dropped_seen = dropped

    @property
    def num_active(self):
        return sum(s is not None for s in self._slots)

    @property
    def num_pending(self):
        return sum(len(self._queues[p]) for p in PRIORITY_CLASSES)

    @property
    def free_lanes(self):
        """Decode lanes currently vacant — the fleet's adopt/seat
        headroom signal."""
        return self._slots.count(None)

    def pop_results(self):
        """Drain finished results incrementally: {req_id: tokens} for
        every request that finished since the last pop (None = shed).
        The fleet's collection path — it drives `step()` itself and
        must see finishes as they happen, not at end-of-trace like
        `run()` (which empties the same buffer)."""
        out, self._results = self._results, {}
        return out

    def best_of_n(self, prompt, n, max_new_tokens,
                  sampling_params=None, eos_token_id=None,
                  priority="standard", adapter_id=0):
        """Fan ONE prompt into `n` sampled candidates sharing its
        prefix-cache blocks: candidate 0 is served first (its prefill
        writes and registers the prompt's full blocks ONCE), then
        candidates 1..n-1 admit with a full-prefix hit — the shared
        prompt blocks are seated read-only in each lane's table, never
        re-prefilled and never duplicated (copy-on-write keeps decode
        writes private, the PR 6 contract). Candidate i samples under
        seed `base + i` (base from `sampling_params.seed`, or the
        engine counter when None), so a fixed base replays all n
        candidates token-for-token.

        Drives `run()`; other queued work is served along the way and
        its finishes stay collectable via `pop_results`/`run`. Returns
        the n candidate token lists (prompt + generated), seed
        order."""
        params, base, self._seed_counter = _best_of_n_intake(
            self, sampling_params, n, self._seed_counter)
        out, stash = _best_of_n_fanout(
            lambda p: self.add_request(
                prompt, max_new_tokens, eos_token_id=eos_token_id,
                priority=priority, adapter_id=adapter_id,
                sampling_params=p),
            self.run, params, n, base)
        # bystander finishes collected by the two run()s stay
        # deliverable through the normal channels
        self._results.update(stash)
        return out

    # -- disaggregated prefill/decode (fleet handoff) ----------------------
    def take_handoff(self, req_id):
        """Claim a finished prefill-only request's parked KV footprint:
        returns (block ids, prefix-cache hit tokens). The caller owns
        the blocks' references now — export their rows (the
        `ops.paged_attention.export_pool_block` / `ingest_pool_block`
        pair is the transfer unit), then hand them back with
        `release_handoff`."""
        return self._handoffs.pop(req_id)

    def release_handoff(self, blocks):
        """Return a handed-off request's source blocks to the pool
        once their payload is exported. Prefix-cached blocks park in
        the evictable LRU (still matchable — the warm chain the fleet
        router steers toward survives the handoff); private blocks go
        back to the free list."""
        self.cache.free(blocks)
        self._update_pool_gauges()

    def adopt_request(self, prompt, first_token, blocks,
                      max_new_tokens, eos_token_id=None, req_id=None,
                      priority="standard", arrived_at=None,
                      adapter_id=0, sampling_params=None,
                      trace_id=None):
        """Seat a request whose prompt KV is ALREADY in this engine's
        pool — the decode-side intake of disaggregated serving. The
        fleet allocates `blocks` from this engine's cache, ingests the
        prefill replica's exported rows into them, then adopts:
        `first_token` (the token the remote final prefill chunk
        produced) seeds the lane and decode continues exactly as if
        the prefill had run here — same compiled steps, same pool
        contents, token-identical output. `max_new_tokens` is the
        request's ORIGINAL budget (the first token counts against it).
        Raises when no lane is free (check `free_lanes` first) — the
        fleet, not the engine, owns handoff queueing. The first token
        is not re-counted in `tokens_generated` (its producing replica
        already counted it). `adapter_id` is the tenant adapter the
        request decodes under — the page comes from THIS engine's
        adapter pool (the prefill replica's page never travels); the
        fleet probes `adapter_page_available` before placing, so an
        unavailable page here is a caller bug and raises.
        `sampling_params` must arrive with its seed RESOLVED (the
        prefill replica's seed travels with the handoff): the adopted
        lane re-derives the exact per-slot key row the colocated lane
        would carry, so sampled disaggregated output stays
        token-identical to colocated."""
        adapter_id = self._check_adapter(adapter_id)
        sampling_params = self._check_sampling(sampling_params)
        if sampling_params is not None and not sampling_params.greedy \
                and sampling_params.seed is None:
            raise ValueError(
                "adopted sampled requests need an explicit seed — "
                "resolve it at fleet intake so the prefill replica's "
                "key state travels with the handoff")
        prompt, req_id = self._intake_guard(prompt, max_new_tokens,
                                            priority, req_id)
        need = math.ceil(prompt.size / self.block_size)
        if len(blocks) != need:
            raise ValueError(
                f"adopted prompt of {prompt.size} tokens needs exactly "
                f"{need} block(s), got {len(blocks)}")
        if None not in self._slots:
            raise RuntimeError(
                "no free lane to adopt into — check free_lanes before "
                "handing off")
        eos = self.eos_token_id if eos_token_id is None \
            else eos_token_id
        if self.tracing and trace_id is None:
            trace_id = new_trace_id()
        req = Request(req_id, prompt, int(max_new_tokens), eos,
                      arrived_at=arrived_at, priority=priority,
                      adapter_id=adapter_id, sampling=sampling_params,
                      trace_id=trace_id)
        self.flight.record("adopted", req_id, blocks=len(blocks))
        self._trace_instant("request.adopted", req,
                            blocks=len(blocks))
        page = self._acquire_adapter(req)
        if page is None:
            raise RuntimeError(
                f"no free adapter page for adapter {adapter_id} — "
                "probe adapter_page_available before adopting")
        now = time.perf_counter()
        slot = _Slot(req=req, blocks=[int(b) for b in blocks],
                     generated=[int(first_token)],
                     last_token_at=now, prefill_pos=int(prompt.size),
                     admit_seq=self._admit_counter,
                     adapter_page=page,
                     **self._slot_sampling_fields(req))
        self._admit_counter += 1
        self._slots[self._slots.index(None)] = slot
        self._m_admissions.inc()
        self._update_pool_gauges()
        done_eos = (eos is not None and int(first_token) == eos)
        if done_eos or int(max_new_tokens) <= 1:
            # already complete on arrival (EOS'd or single-token
            # budget): retire immediately, blocks back to the pool
            self._finish(slot, "eos" if done_eos else "length")
            self._slots[self._slots.index(slot)] = None
        self._m_active.set(self.num_active)
        return req_id

    def drain(self):
        """Graceful replica shutdown: close admissions (add_request /
        adopt_request raise from now on), run every queued and
        in-flight request to completion, then AUDIT the pool — every
        non-null block must be back on the free list or parked as a
        refcount-zero prefix-cache block (`PagedKVCache.leak_check`).
        A parked handoff fails the drain loudly: its blocks are
        intentionally held, so the fleet must export-and-release
        before retiring the replica. Returns the drained results
        (run()'s contract). Catches the block-leak class the
        allocator's double-free hardening cannot see — a block freed
        zero times instead of twice."""
        self._draining = True
        out = self.run()
        if self._handoffs:
            raise self._audit_error(
                f"{len(self._handoffs)} handoff(s) still parked — "
                "take_handoff/release_handoff them before draining "
                "the replica")
        leaked = self.cache.leak_check()
        if leaked:
            raise self._audit_error(
                f"drain leak check failed: block(s) {leaked} neither "
                "free nor prefix-cached after all lanes finished — a "
                "scheduler path dropped a reference without freeing")
        if self.adapter_pool is not None:
            leaked = self.adapter_pool.leak_check()
            if leaked:
                raise self._audit_error(
                    f"drain leak check failed: adapter page(s) "
                    f"{leaked} still referenced after all lanes "
                    "finished — a scheduler path vacated a lane "
                    "without releasing its adapter page")
        self._end_of_step_gauges()
        return out

    def run(self):
        """Drive until every queued/admitted request finished; returns
        (and drains) {req_id: prompt + generated tokens; None for a
        request shed at saturation}."""
        while self.num_pending or self.num_active:
            if self.step() == 0:
                req = self._peek_request()
                if req is not None:
                    blocker = ("no admission fits (next request needs "
                               f"{math.ceil(req.prompt.size / self.block_size)}"
                               " blocks)")
                else:
                    stalled = sum(s is not None and s.prefilling
                                  for s in self._slots)
                    blocker = (f"{stalled} lane(s) stalled in prefill "
                               f"and {self.num_active - stalled} in "
                               "decode growth/copy-on-write, all "
                               "waiting on a block")
                raise RuntimeError(
                    "generation engine deadlocked: "
                    f"{blocker} with {self.cache.num_free} free blocks "
                    "— grow num_blocks or shrink "
                    "num_slots/max_model_len")
        out, self._results = self._results, {}
        return out


# -- trace contracts (tpu-verify) ---------------------------------------
# Declared HERE, next to the step builders, so the contract and the
# program evolve in one diff. The harvester
# (analysis/trace/harvest.py) constructs tiny engines over the full
# {dense,pallas} x K x mp matrix and lowers THESE OBJECTS' jitted
# steps; rules TPU101-TPU106 then enforce what is declared below.
# Donation comes from the same introspect table the constructor
# consumes; the collective budget is a lazy reference into models/gpt
# (the module whose _mp_all_gather/_vocab_parallel_embed emit them).
_GPT_SERVING_BUDGET = "paddle_tpu.models.gpt:GPT_SERVING_COLLECTIVES"

for _step in ("engine_prefill", "engine_prefill_chunk",
              "engine_decode_step", "engine_verify_step"):
    register_contract(TraceContract(
        name=_step,
        declared_at="paddle_tpu/inference/engine.py",
        donate_argnums=introspect.ENGINE_STEP_DONATION[_step],
        collective_budget=_GPT_SERVING_BUDGET,
        # decode/verify are the host loop body — one dispatch per
        # generated token, so their collectives sit on the per-token
        # latency path (tpu-shard TPU305 gates these against any
        # future slow/DCN mesh axis); prefills run per admission
        per_token=_step in ("engine_decode_step",
                            "engine_verify_step")))
del _step
