"""Continuous-batching generation engine over a paged KV cache.

The serving tier the north star's "heavy traffic" clause asks for:
instead of one request at a time against a per-request fixed-size cache
(`GPTForCausalLM.generate`), MANY requests decode in ONE compiled step
(Orca-style iteration-level scheduling) against a global block pool
shared by all of them (vLLM-style PagedAttention layout).

Three pieces, each shape-stable so steady-state serving never
recompiles:

- `PagedKVCache`: per-layer `[num_blocks, block_size, heads, head_dim]`
  pool planes stacked on a leading layer axis, plus a host-side free
  list. Requests own `ceil(context/block_size)` blocks, allocated on
  demand as their context grows and returned the moment they finish —
  HBM is shared by live CONTEXT, not reserved per request at max
  sequence length. Block 0 is the null block (idle-slot writes land
  there; never allocated).
- a slot scheduler: `num_slots` decode lanes. Between decode
  iterations, finished requests vacate their lane and queued requests
  are admitted into free lanes via a bucketed prefill (prompts padded
  to a small ladder of lengths, so prefill compiles once per BUCKET,
  not once per prompt length). A lane that cannot get a block this
  iteration simply skips it (masked to the null block) and retries —
  graceful degradation under pool pressure instead of an abort.
- one donated compiled decode step (`jax.jit`, the TrainStep idiom:
  model state threaded as traced args, pools donated so XLA updates
  them in place in HBM): `[slots, 1]` tokens + `[slots]` positions +
  `[slots, max_blocks]` block tables -> next token per slot. Fixed
  shapes regardless of which lanes are live, so arrivals/completions
  never retrace — `jit.count_traces` probes prove it in CI.

Greedy decoding matches `GPTForCausalLM.generate(use_cache=True)`
token-for-token per request (the parity contract CI enforces) — under
either paged-attention backend: `attention_backend` (or the
`PADDLE_PAGED_ATTENTION_BACKEND` env override) picks `auto` / `dense` /
`pallas` per `ops.paged_attention.resolve_backend`, resolved once at
construction so the compiled decode step is fixed; the selection is
published as the `engine_attention_backend_info` gauge and every decode
dispatch lands in the backend-labeled `engine_decode_step_seconds`
histogram.

Serving telemetry (PR 2): every engine carries a metrics registry
(`engine.metrics`, observability tier) — TTFT/TPOT histograms, queue/
slot/pool gauges with a high-water mark, admission/finish/stall
counters, and a decode-recompile counter wired to the count_traces
probes (steady-state contract: 0). Scheduler iterations and compiled
prefill/decode dispatches also emit `engine.*` spans into the profiler
recorder, so a chrome trace shows the scheduler timeline next to the
metrics story.
"""
from __future__ import annotations

import math
import os
import time
from collections import deque
from dataclasses import dataclass, field

import numpy as np

import jax
import jax.numpy as jnp

from paddle_tpu.core.tensor import Tensor
from paddle_tpu.jit.api import bound_state, count_traces, dedup_params, \
    model_buffers
from paddle_tpu.observability.metrics import LATENCY_BUCKETS, \
    MetricsRegistry
from paddle_tpu.profiler import RecordEvent

__all__ = ["PagedKVCache", "GenerationEngine", "Request"]


class PagedKVCache:
    """Global paged KV pool + host-side block allocator.

    kpool/vpool: `[layers, num_blocks, block_size, heads, head_dim]`
    device arrays, functionally updated by the compiled steps (donated,
    so updated in place on device). Block 0 is reserved as the null
    block — `allocate` never returns it."""

    def __init__(self, num_layers, num_blocks, block_size, num_heads,
                 head_dim, dtype=jnp.float32):
        if num_blocks < 2:
            raise ValueError("need >= 2 blocks (block 0 is the null "
                             "block)")
        self.block_size = int(block_size)
        self.num_blocks = int(num_blocks)
        shape = (num_layers, num_blocks, block_size, num_heads, head_dim)
        self.kpool = jnp.zeros(shape, dtype)
        self.vpool = jnp.zeros(shape, dtype)
        # LIFO free list: recently-freed (cache-warm) blocks reused first
        self._free = list(range(num_blocks - 1, 0, -1))

    @property
    def num_free(self):
        return len(self._free)

    def allocate(self, n):
        """n pool blocks, or None (caller stalls/retries) if the pool
        is too fragmented-by-occupancy to serve them."""
        if n > len(self._free):
            return None
        got = self._free[-n:]
        del self._free[-n:]
        return got

    def free(self, blocks):
        self._free.extend(blocks)


@dataclass
class Request:
    """One generation request (prompt in, greedy continuation out)."""

    req_id: object
    prompt: np.ndarray                 # int32 [plen]
    max_new_tokens: int
    eos_token_id: int = None
    arrived_at: float = None           # perf_counter at add_request


@dataclass
class _Slot:
    """A live decode lane: the request plus its paged-cache footprint."""

    req: Request
    blocks: list                       # owned pool block ids, in order
    generated: list = field(default_factory=list)
    last_token_at: float = None        # perf_counter of newest token

    @property
    def feed_pos(self):
        """Absolute position of the token about to be fed (the last
        generated one — prefill already produced generated[0])."""
        return len(self.req.prompt) + len(self.generated) - 1


class GenerationEngine:
    """Iteration-level scheduler + compiled steps over a paged cache.

        engine = GenerationEngine(model, num_slots=8, block_size=16)
        engine.add_request([1, 2, 3], max_new_tokens=32)
        ...                                  # add more any time
        results = engine.run()               # {req_id: full token list}

    `model` is a GPTForCausalLM (or anything exposing
    `gpt.forward_prefill`, `gpt.forward_decode_paged` and `_logits_of`
    with the same contracts). Generation is eval-mode; the engine
    refuses a model left in training mode with active dropout, same as
    `generate(use_cache=True)`.
    """

    def __init__(self, model, num_slots=8, block_size=16,
                 num_blocks=None, prefill_buckets=None,
                 max_model_len=None, eos_token_id=None, donate=None,
                 registry=None, attention_backend=None):
        from paddle_tpu.ops.paged_attention import resolve_backend

        cfg = model.config
        if model.training and cfg.dropout > 0:
            raise ValueError("GenerationEngine decodes deterministically "
                             "(no dropout) — call model.eval() first")
        self.model = model
        self.num_slots = int(num_slots)
        self.block_size = int(block_size)
        self.max_model_len = int(max_model_len or cfg.max_seq_len)
        if self.max_model_len > cfg.max_seq_len:
            raise ValueError(
                f"max_model_len={self.max_model_len} exceeds the "
                f"model's position table ({cfg.max_seq_len})")
        self.max_blocks = math.ceil(self.max_model_len / self.block_size)
        self.eos_token_id = eos_token_id
        # default pool covers every slot at full context (+ null block):
        # correctness-first; serving deployments size it to live-context
        # expectations and lean on the stall/retry path under pressure
        self.cache = PagedKVCache(
            cfg.num_layers,
            int(num_blocks or 1 + self.num_slots * self.max_blocks),
            self.block_size, cfg.num_heads,
            cfg.hidden_size // cfg.num_heads,
            dtype=model.gpt.wte.weight._array.dtype)
        self.prefill_buckets = tuple(sorted(
            prefill_buckets or self._default_buckets()))
        if self.prefill_buckets[-1] < self.max_model_len:
            raise ValueError("largest prefill bucket "
                             f"({self.prefill_buckets[-1]}) must cover "
                             f"max_model_len={self.max_model_len}")
        # paged-attention kernel backend: constructor arg, overridden by
        # the env (deploy-time switch without a code change), resolved
        # ONCE to a concrete backend so the compiled decode step is
        # fixed — `auto` never changes mid-engine (decode traces == 1)
        requested = os.environ.get("PADDLE_PAGED_ATTENTION_BACKEND") \
            or attention_backend or "auto"
        self.attention_backend_requested = requested
        self.attention_backend = resolve_backend(
            requested, head_dim=cfg.hidden_size // cfg.num_heads,
            block_size=self.block_size)
        # the state threading of TrainStep: params+buffers ride as traced
        # args, so weight updates are visible without retracing
        self._state = dedup_params(list(model.parameters())) + \
            model_buffers(model)
        donate = (jax.default_backend() != "cpu") if donate is None \
            else donate
        self._decode_pure = count_traces(self._build_decode())
        self._decode = jax.jit(self._decode_pure,
                               donate_argnums=(1, 2) if donate else ())
        self._prefill_pure = count_traces(self._build_prefill())
        self._prefill = jax.jit(self._prefill_pure,
                                donate_argnums=(1, 2) if donate else ())
        self._queue = deque()
        self._slots = [None] * self.num_slots
        self._results = {}
        self._auto_id = 0
        self.tokens_generated = 0
        # serving telemetry: per-engine registry by default so counter
        # exactness survives multiple engines in one process; pass
        # observability.get_registry() to publish on the process default
        self.metrics = registry if registry is not None \
            else MetricsRegistry()
        self._init_metrics()

    def _init_metrics(self):
        m = self.metrics
        self._m_ttft = m.histogram(
            "engine_ttft_seconds",
            "Request arrival to first generated token (includes queue "
            "wait and prefill).", buckets=LATENCY_BUCKETS)
        self._m_tpot = m.histogram(
            "engine_tpot_seconds",
            "Per-output-token latency: time since the slot's PREVIOUS "
            "token, so block-stall waits show up (not just the "
            "producing iteration's wall time).",
            buckets=LATENCY_BUCKETS)
        self._m_queue = m.gauge(
            "engine_queue_depth", "Requests waiting for a slot.")
        self._m_active = m.gauge(
            "engine_active_slots", "Decode lanes currently occupied.")
        self._m_admissions = m.counter(
            "engine_admissions_total", "Requests admitted into a lane.")
        self._m_finished = m.counter(
            "engine_finished_total",
            "Requests finished (lane vacated).", labelnames=("reason",))
        self._m_stalls = m.counter(
            "engine_block_stalls_total",
            "Iterations a lane/admission skipped for want of a pool "
            "block.", labelnames=("path",))
        self._m_tokens = m.counter(
            "engine_tokens_generated_total", "New tokens emitted.")
        self._m_pool_used = m.gauge(
            "engine_pool_used_blocks", "KV pool blocks in use.")
        self._m_pool_util = m.gauge(
            "engine_pool_utilization",
            "Used fraction of allocatable KV pool blocks.")
        self._m_pool_hw = m.gauge(
            "engine_pool_used_high_water_blocks",
            "High-water mark of KV pool blocks in use.")
        self._m_decode_traces = m.gauge(
            "engine_decode_traces",
            "Times the decode step traced (steady-state contract: 1).")
        self._m_prefill_traces = m.gauge(
            "engine_prefill_traces",
            "Times prefill traced (bounded by len(prefill_buckets)).")
        self._m_recompiles = m.counter(
            "engine_decode_recompiles_total",
            "Decode retraces past the first compile — nonzero means a "
            "shape-stability bug.")
        self._m_backend = m.gauge(
            "engine_attention_backend_info",
            "Paged-attention kernel backend the compiled decode step "
            "dispatches to (1 = selected).", labelnames=("backend",))
        self._m_backend.labels(backend=self.attention_backend).set(1)
        # the backend label is fixed at construction: resolve the
        # histogram child once, off the per-step path
        self._m_decode_seconds = m.histogram(
            "engine_decode_step_seconds",
            "Wall time of one compiled decode dispatch, labeled by "
            "paged-attention backend.", labelnames=("backend",),
            buckets=LATENCY_BUCKETS).labels(
                backend=self.attention_backend)
        self._decode_traces_seen = 0

    def _update_pool_gauges(self):
        used = self.cache.num_blocks - 1 - self.cache.num_free
        self._m_pool_used.set(used)
        self._m_pool_util.set(used / max(self.cache.num_blocks - 1, 1))
        self._m_pool_hw.set_max(used)

    def _sample_traces(self):
        """Mirror the count_traces probes into metrics; a decode trace
        beyond the first is a recompile (the ==0 steady-state SLO)."""
        t = self._decode_pure.traces
        if t > self._decode_traces_seen:
            if self._decode_traces_seen >= 1:
                self._m_recompiles.inc(t - self._decode_traces_seen)
            self._decode_traces_seen = t
        self._m_decode_traces.set(t)
        self._m_prefill_traces.set(self._prefill_pure.traces)

    def metrics_snapshot(self):
        """JSON-able snapshot of this engine's serving metrics."""
        return self.metrics.snapshot()

    # -- compiled steps ----------------------------------------------------
    def _default_buckets(self):
        b, out = 16, []
        while b < self.max_model_len:
            out.append(b)
            b *= 2
        out.append(self.max_model_len)
        return out

    def _build_decode(self):
        model, state = self.model, self._state
        backend = self.attention_backend

        def decode_fn(state_arrays, kpool, vpool, tokens, positions,
                      tables):
            with bound_state(zip(state, state_arrays), state):
                h, kp, vp = model.gpt.forward_decode_paged(
                    Tensor._wrap(tokens), Tensor._wrap(positions),
                    Tensor._wrap(kpool), Tensor._wrap(vpool),
                    Tensor._wrap(tables), backend=backend)
                logits = model._logits_of(h)          # [slots, 1, V]
                nxt = jnp.argmax(logits._array[:, 0], axis=-1) \
                    .astype(jnp.int32)
                return nxt, kp._array, vp._array

        decode_fn.__name__ = "engine_decode_step"
        return decode_fn

    def _build_prefill(self):
        from paddle_tpu.ops.paged_attention import paged_prefill_write

        model, state = self.model, self._state

        def prefill_fn(state_arrays, kpool, vpool, tokens, plen,
                       table_row):
            # tokens [1, bucket]; plen traced -> one program per bucket
            with bound_state(zip(state, state_arrays), state):
                hidden, ks, vs = model.gpt.forward_prefill(
                    Tensor._wrap(tokens))
                kp, vp = paged_prefill_write(
                    Tensor._wrap(kpool), Tensor._wrap(vpool), ks, vs,
                    Tensor._wrap(table_row), Tensor._wrap(plen))
                # only the last REAL position's logits matter: one-hot
                # reduce to [1,1,H] before the vocab matmul
                sel = (jnp.arange(tokens.shape[1]) == plen - 1) \
                    .astype(hidden._array.dtype)
                h_last = (hidden._array * sel[None, :, None]) \
                    .sum(axis=1, keepdims=True)
                logits = model._logits_of(Tensor._wrap(h_last))
                nxt = jnp.argmax(logits._array[0, 0]).astype(jnp.int32)
                return nxt, kp._array, vp._array

        prefill_fn.__name__ = "engine_prefill"
        return prefill_fn

    # -- recompile probes (CI contract) ------------------------------------
    @property
    def decode_traces(self):
        """Times the decode step traced. Steady-state contract: 1,
        regardless of arrivals/evictions."""
        return self._decode_pure.traces

    @property
    def prefill_traces(self):
        """Times prefill traced — bounded by len(prefill_buckets)."""
        return self._prefill_pure.traces

    # -- request intake ----------------------------------------------------
    def add_request(self, prompt, max_new_tokens, eos_token_id=None,
                    req_id=None):
        """Queue a request; admitted into a free slot between decode
        iterations (may be called while `run`/`step` is mid-stream)."""
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        if prompt.size == 0:
            raise ValueError("empty prompt")
        if max_new_tokens < 1:
            raise ValueError("max_new_tokens must be >= 1")
        total = prompt.size + int(max_new_tokens)
        if total > self.max_model_len:
            raise ValueError(
                f"prompt({prompt.size}) + max_new({max_new_tokens}) = "
                f"{total} exceeds max_model_len={self.max_model_len}")
        if req_id is None:
            # skip over any live caller-chosen int ids
            while self._auto_id in self._in_flight():
                self._auto_id += 1
            req_id = self._auto_id
            self._auto_id += 1
        elif req_id in self._in_flight():
            raise ValueError(f"req_id {req_id!r} is already queued, "
                             "decoding, or awaiting collection")
        eos = self.eos_token_id if eos_token_id is None else eos_token_id
        self._queue.append(Request(req_id, prompt, int(max_new_tokens),
                                   eos, arrived_at=time.perf_counter()))
        self._m_queue.set(len(self._queue))
        return req_id

    # -- scheduler ---------------------------------------------------------
    def _bucket_for(self, plen):
        for b in self.prefill_buckets:
            if b >= plen:
                return b
        raise AssertionError("unreachable: last bucket covers "
                             "max_model_len")

    def _state_arrays(self):
        return [t._array for t in self._state]

    def _in_flight(self):
        """Ids that would collide with a new request: queued, seated in
        a lane, or finished but not yet drained by run()."""
        ids = {r.req_id for r in self._queue}
        ids.update(s.req.req_id for s in self._slots if s is not None)
        ids.update(self._results)
        return ids

    def _finish(self, slot, reason):
        req = slot.req
        self._results[req.req_id] = \
            list(map(int, req.prompt)) + slot.generated
        self.cache.free(slot.blocks)
        self._m_finished.labels(reason=reason).inc()

    def _admit(self):
        """Fill free lanes from the queue (FIFO): allocate the prompt's
        blocks, run the bucketed prefill (writes KV into the blocks,
        yields the first generated token), seat the slot."""
        admitted = 0
        while self._queue and None in self._slots:
            req = self._queue[0]
            plen = int(req.prompt.size)
            need = math.ceil(plen / self.block_size)
            blocks = self.cache.allocate(need)
            if blocks is None:
                self._m_stalls.labels(path="admit").inc()
                break                      # pool pressure: retry later
            self._update_pool_gauges()     # high-water sees the peak
            self._queue.popleft()
            bucket = self._bucket_for(plen)
            tokens = np.zeros((1, bucket), np.int32)
            tokens[0, :plen] = req.prompt
            row = np.zeros(self.max_blocks, np.int32)
            row[:need] = blocks
            with RecordEvent("engine.prefill"):
                first, self.cache.kpool, self.cache.vpool = \
                    self._prefill(
                        self._state_arrays(), self.cache.kpool,
                        self.cache.vpool, jnp.asarray(tokens),
                        jnp.int32(plen), jnp.asarray(row))
                first = int(first)         # sync: first token is out
            slot = _Slot(req=req, blocks=blocks, generated=[first],
                         last_token_at=time.perf_counter())
            self.tokens_generated += 1
            self._m_tokens.inc()
            self._m_admissions.inc()
            if req.arrived_at is not None:
                self._m_ttft.observe(time.perf_counter() -
                                     req.arrived_at)
            admitted += 1
            if (req.eos_token_id is not None
                    and slot.generated[-1] == req.eos_token_id):
                self._finish(slot, "eos")  # instant EOS
                continue
            if req.max_new_tokens == 1:
                self._finish(slot, "length")   # one-token request
                continue
            self._slots[self._slots.index(None)] = slot
        self._m_queue.set(len(self._queue))
        return admitted

    def step(self):
        """One scheduler iteration: admit, then one batched decode step
        over every lane that holds a block for its write position.
        Returns the number of lanes+admissions that made progress."""
        with RecordEvent("engine.step"):
            progressed = self._admit()
            runnable = []
            for i, slot in enumerate(self._slots):
                if slot is None:
                    continue
                # on-demand growth: the feed position may open a new
                # block
                bi = slot.feed_pos // self.block_size
                if bi >= len(slot.blocks):
                    got = self.cache.allocate(1)
                    if got is None:
                        self._m_stalls.labels(path="decode").inc()
                        continue           # stalled this iteration
                    slot.blocks.extend(got)
                    self._update_pool_gauges()
                runnable.append(i)
            if not runnable:
                self._end_of_step_gauges()
                return progressed
            tokens = np.zeros((self.num_slots, 1), np.int32)
            positions = np.zeros(self.num_slots, np.int32)
            tables = np.zeros((self.num_slots, self.max_blocks),
                              np.int32)
            for i in runnable:
                slot = self._slots[i]
                tokens[i, 0] = slot.generated[-1]
                positions[i] = slot.feed_pos
                tables[i, :len(slot.blocks)] = slot.blocks
            with RecordEvent("engine.decode"):
                t_dec = time.perf_counter()
                nxt, self.cache.kpool, self.cache.vpool = self._decode(
                    self._state_arrays(), self.cache.kpool,
                    self.cache.vpool, jnp.asarray(tokens),
                    jnp.asarray(positions), jnp.asarray(tables))
                nxt = np.asarray(nxt)      # sync: tokens are out
                self._m_decode_seconds.observe(
                    time.perf_counter() - t_dec)
            now = time.perf_counter()
            for i in runnable:
                slot = self._slots[i]
                tok = int(nxt[i])
                slot.generated.append(tok)
                self.tokens_generated += 1
                self._m_tokens.inc()
                # inter-token latency per SLOT, not this iteration's
                # wall time: a lane that sat out N stalled iterations
                # reports the (N+1)-iteration gap its user experienced
                if slot.last_token_at is not None:
                    self._m_tpot.observe(now - slot.last_token_at)
                slot.last_token_at = now
                req = slot.req
                if req.eos_token_id is not None \
                        and tok == req.eos_token_id:
                    self._finish(slot, "eos")
                    self._slots[i] = None
                elif len(slot.generated) >= req.max_new_tokens:
                    self._finish(slot, "length")
                    self._slots[i] = None
            self._end_of_step_gauges()
            return progressed + len(runnable)

    def _end_of_step_gauges(self):
        self._m_active.set(self.num_active)
        self._m_queue.set(len(self._queue))
        self._update_pool_gauges()
        self._sample_traces()

    @property
    def num_active(self):
        return sum(s is not None for s in self._slots)

    @property
    def num_pending(self):
        return len(self._queue)

    def run(self):
        """Drive until every queued/admitted request finished; returns
        (and drains) {req_id: prompt + generated tokens}."""
        while self._queue or self.num_active:
            if self.step() == 0:
                need = math.ceil(self._queue[0].prompt.size /
                                 self.block_size) if self._queue else 1
                raise RuntimeError(
                    "generation engine deadlocked: no lane could get a "
                    f"block and no admission fits ({self.cache.num_free}"
                    f" free blocks, next request needs {need}) — grow "
                    "num_blocks or shrink num_slots/max_model_len")
        out, self._results = self._results, {}
        return out
