"""Hybrid-parallel GPT benchmark — the BASELINE.md flagship config
(GPT-1.3B, mp=2 pp=2 sharding-stage-2) over a device mesh.

On a real v5e-16 slice this runs the full 1.3B config; on a single chip
or the virtual CPU mesh (BENCH_TINY=1 with
XLA_FLAGS=--xla_force_host_platform_device_count=8) it validates that
the exact same mp2/pp2/sharding2 program compiles and steps.

Prints ONE JSON line like bench.py.
"""
from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

# the host sitecustomize imports jax with JAX_PLATFORMS=axon before this
# script runs; honor a virtual-CPU-mesh request via jax.config
if "xla_force_host_platform_device_count" in os.environ.get("XLA_FLAGS", ""):
    import jax

    try:
        jax.config.update("jax_platforms", "cpu")
    except Exception:
        pass


def main():
    import jax

    import paddle_tpu as paddle
    import paddle_tpu.nn.functional as F
    import paddle_tpu.distributed as dist
    from paddle_tpu.distributed.topology import (
        HybridCommunicateGroup,
        set_hybrid_communicate_group,
    )
    from paddle_tpu.models import GPTConfig
    from paddle_tpu.models.gpt import build_pipeline_gpt

    n_dev = len(jax.devices())
    tiny = os.environ.get("BENCH_TINY") == "1" or n_dev < 8
    mp = 2 if n_dev >= 2 else 1
    pp = 2 if n_dev >= 4 else 1
    sharding = 2 if n_dev >= 8 else 1
    dp = n_dev // (mp * pp * sharding)

    hcg = HybridCommunicateGroup(dp=dp, mp=mp, pp=pp, sharding=sharding)
    set_hybrid_communicate_group(hcg)

    if tiny:
        cfg = GPTConfig.tiny(vocab=512, hidden=64, layers=4, heads=4, seq=64)
        batch, steps, peak = 8, 3, 1e12
    else:
        cfg = GPTConfig.gpt_1p3b()
        cfg.vocab_size = 32768
        batch, steps = int(os.environ.get("BENCH_BATCH", "8")), 5
        peak = 197e12 * n_dev

    paddle.seed(0)
    model = build_pipeline_gpt(cfg, num_stages=pp, num_microbatches=max(pp, 2),
                               recompute_interval=0 if tiny else 1)
    model.eval()
    if not tiny:
        model.to(dtype="bfloat16")
    opt = paddle.optimizer.AdamW(learning_rate=1e-4,
                                 parameters=model.parameters())
    step = dist.DistributedTrainStep(
        model, opt,
        lambda out, lab: F.cross_entropy(
            out.reshape([-1, cfg.vocab_size]), lab.reshape([-1])),
        hcg=hcg, sharding_stage=2, batch_axes=("dp", "sharding"))

    seq = cfg.max_seq_len
    rng = np.random.RandomState(0)
    ids = paddle.to_tensor(rng.randint(0, cfg.vocab_size, (batch, seq),
                                       np.int32))
    t0 = time.time()
    loss = step(ids, ids)
    _ = float(loss)
    compile_s = time.time() - t0

    t1 = time.time()
    for _ in range(steps):
        loss = step(ids, ids)
    val = float(loss)  # readback blocks
    dt = (time.time() - t1) / steps

    n_params = sum(p.size for p in model.parameters())
    flops_tok = 6 * n_params + 12 * cfg.num_layers * cfg.hidden_size * seq
    tok_s = batch * seq / dt
    mfu = tok_s * flops_tok / peak

    if tiny:
        # degenerate config (n_dev<8 collapses the hybrid degrees, or a
        # virtual CPU mesh): this validates compile+step only — emitting
        # a throughput-shaped metric line here would be misleading
        print(json.dumps({
            "metric": "gpt_hybrid_compile_check",
            "value": 1,
            "unit": "ok (NOT a throughput measurement: tiny/collapsed "
                    f"config, devices={n_dev} mp={mp} pp={pp} "
                    f"sharding={sharding})",
            "vs_baseline": None,
        }))
    else:
        print(json.dumps({
            "metric": "gpt_1p3b_hybrid_mp2_pp2_sharding2_tokens_per_sec",
            "value": round(tok_s, 1),
            "unit": "tokens/s",
            "vs_baseline": round(mfu / 0.45, 4),
        }))
    print(f"# devices={n_dev} mesh dp={dp} mp={mp} pp={pp} "
          f"sharding={sharding} params={n_params/1e6:.1f}M batch={batch} "
          f"seq={seq} compile={compile_s:.1f}s step={dt*1000:.1f}ms "
          f"mfu={mfu:.3f} loss={val:.3f}", file=sys.stderr)


if __name__ == "__main__":
    main()
