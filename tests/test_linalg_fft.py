"""paddle.linalg / paddle.fft namespace tests: numerics vs numpy and
gradient flow through the op layer.
"""
import numpy as np

import paddle_tpu as paddle


def test_linalg_namespace():
    rs = np.random.RandomState(0)
    a = rs.randn(4, 4).astype(np.float32)
    m = a @ a.T + 4 * np.eye(4, dtype=np.float32)  # SPD
    t = paddle.to_tensor(m)

    np.testing.assert_allclose(np.asarray(paddle.linalg.inverse(t)._array),
                               np.linalg.inv(m), rtol=1e-4, atol=1e-5)
    L = np.asarray(paddle.linalg.cholesky(t)._array)
    np.testing.assert_allclose(L @ L.T, m, rtol=1e-4, atol=1e-4)
    u, s, vh = paddle.linalg.svd(t)
    np.testing.assert_allclose(np.sort(np.asarray(s._array))[::-1],
                               np.sort(np.linalg.svd(m)[1])[::-1],
                               rtol=1e-4)
    sign, logdet = paddle.linalg.slogdet(t)
    np.testing.assert_allclose(float(sign._array)
                               * np.exp(float(logdet._array)),
                               np.linalg.det(m), rtol=1e-3)


def test_fft_roundtrip_and_reference():
    rs = np.random.RandomState(1)
    x = rs.randn(64).astype(np.float32)
    F = np.asarray(paddle.fft.rfft(paddle.to_tensor(x))._array)
    np.testing.assert_allclose(F, np.fft.rfft(x), rtol=1e-4, atol=1e-4)
    back = np.asarray(paddle.fft.irfft(
        paddle.to_tensor(F), n=64)._array)
    np.testing.assert_allclose(back, x, rtol=1e-4, atol=1e-5)
    # 2-D + shift + freqs
    img = rs.randn(8, 8).astype(np.float32)
    F2 = np.asarray(paddle.fft.fft2(paddle.to_tensor(img))._array)
    np.testing.assert_allclose(F2, np.fft.fft2(img), rtol=1e-4, atol=1e-4)
    np.testing.assert_allclose(
        np.asarray(paddle.fft.fftfreq(8, d=0.5)._array),
        np.fft.fftfreq(8, d=0.5), rtol=1e-6)


def test_fft_gradient_flows():
    x = paddle.to_tensor(np.random.RandomState(2)
                         .randn(32).astype(np.float32))
    x.stop_gradient = False
    spec = paddle.fft.rfft(x)
    power = (spec.abs() ** 2).sum()
    power.backward()
    assert x.grad is not None
    # Parseval: d/dx sum|rfft(x)|^2 relates to x linearly; check nonzero
    assert float(np.abs(np.asarray(x.grad._array)).sum()) > 0


def test_fft_nd_real_and_hermitian_families():
    rs = np.random.RandomState(3)
    x = rs.randn(4, 8).astype(np.float32)
    r = np.asarray(paddle.fft.rfftn(paddle.to_tensor(x))._array)
    np.testing.assert_allclose(r, np.fft.rfftn(x), rtol=1e-4, atol=1e-4)
    back = np.asarray(paddle.fft.irfftn(
        paddle.to_tensor(r), s=(4, 8))._array)
    np.testing.assert_allclose(back, x, rtol=1e-4, atol=1e-5)
    c = (rs.randn(4, 5) + 1j * rs.randn(4, 5)).astype(np.complex64)
    import scipy.fft as sfft

    h = np.asarray(paddle.fft.hfft2(paddle.to_tensor(c))._array)
    np.testing.assert_allclose(h, sfft.hfft2(c), rtol=1e-3, atol=1e-3)
    assert not np.iscomplexobj(h)  # hfft* output is real
    real = rs.randn(4, 8).astype(np.float32)
    ih = np.asarray(paddle.fft.ihfft2(paddle.to_tensor(real))._array)
    np.testing.assert_allclose(ih, sfft.ihfft2(real), rtol=1e-3, atol=1e-4)
    # fftfreq honors dtype aliases through the canonical converter
    assert str(paddle.fft.fftfreq(8, dtype="float32").dtype) \
        .endswith("float32")
