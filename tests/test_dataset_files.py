"""End-to-end dataset FILE parsing (VERDICT r3 weak #8: tests used to
synthesize arrays instead of exercising the parsers). Each test writes
a tiny but format-faithful file (IDX/gz, cifar tar.gz pickles,
aclImdb-layout tar.gz, housing whitespace table), parses it through the
public dataset class, and the MNIST one smoke-trains through hapi.

Reference analogs: python/paddle/vision/datasets/mnist.py, cifar.py,
text/datasets/imdb.py, uci_housing.py.
"""
import gzip
import io
import pickle
import struct
import tarfile

import numpy as np

import paddle_tpu as paddle


def _write_idx_images(path, imgs):
    data = struct.pack(">IIII", 0x803, len(imgs), 28, 28) + \
        np.asarray(imgs, np.uint8).tobytes()
    with gzip.open(path, "wb") as f:
        f.write(data)


def _write_idx_labels(path, labels):
    data = struct.pack(">II", 0x801, len(labels)) + \
        np.asarray(labels, np.uint8).tobytes()
    with gzip.open(path, "wb") as f:
        f.write(data)


def test_mnist_idx_roundtrip_and_hapi_smoke(tmp_path):
    from paddle_tpu.vision.datasets import MNIST

    rs = np.random.RandomState(0)
    imgs = rs.randint(0, 256, (20, 28, 28), dtype=np.uint8)
    labels = rs.randint(0, 10, (20,), dtype=np.uint8)
    ip, lp = str(tmp_path / "im.idx.gz"), str(tmp_path / "lb.idx.gz")
    _write_idx_images(ip, imgs)
    _write_idx_labels(lp, labels)

    ds = MNIST(image_path=ip, label_path=lp)
    assert len(ds) == 20
    x0, y0 = ds[0]
    assert x0.shape == (28, 28, 1) and x0.dtype == np.float32
    np.testing.assert_allclose(x0[..., 0], imgs[0] / 255.0)
    assert y0 == labels[0]

    # raw backend keeps uint8
    raw = MNIST(image_path=ip, label_path=lp, backend="raw")
    assert raw[0][0].dtype == np.uint8

    # smoke-train a real model THROUGH the file-parsed dataset (hapi)
    import paddle_tpu.nn as nn
    from paddle_tpu.hapi import Model

    paddle.seed(0)
    net = nn.Sequential(nn.Flatten(), nn.Linear(784, 10))
    m = Model(net)
    m.prepare(paddle.optimizer.Adam(learning_rate=0.01,
                                    parameters=net.parameters()),
              paddle.nn.CrossEntropyLoss())
    m.fit(ds, epochs=1, batch_size=10, verbose=0)


def test_mnist_rejects_bad_magic(tmp_path):
    from paddle_tpu.vision.datasets import MNIST

    bad = str(tmp_path / "bad.idx.gz")
    with gzip.open(bad, "wb") as f:
        f.write(struct.pack(">IIII", 0x999, 1, 28, 28) + b"\0" * 784)
    lp = str(tmp_path / "lb.idx.gz")
    _write_idx_labels(lp, [0])
    try:
        MNIST(image_path=bad, label_path=lp)
        raise AssertionError("expected bad-magic ValueError")
    except ValueError as e:
        assert "magic" in str(e)


def test_cifar10_targz_roundtrip(tmp_path):
    from paddle_tpu.vision.datasets import Cifar10

    rs = np.random.RandomState(1)
    path = str(tmp_path / "cifar-10-python.tar.gz")
    with tarfile.open(path, "w:gz") as tf:
        for name, n in [("data_batch_1", 6), ("test_batch", 4)]:
            payload = pickle.dumps({
                b"data": rs.randint(0, 256, (n, 3072), dtype=np.uint8),
                b"labels": rs.randint(0, 10, (n,)).tolist()})
            info = tarfile.TarInfo(f"cifar-10-batches-py/{name}")
            info.size = len(payload)
            tf.addfile(info, io.BytesIO(payload))

    tr = Cifar10(data_file=path, mode="train")
    te = Cifar10(data_file=path, mode="test")
    assert len(tr) == 6 and len(te) == 4
    x, y = tr[0]
    assert x.shape == (32, 32, 3) and 0.0 <= x.min() and x.max() <= 1.0
    assert 0 <= int(y) < 10


def test_imdb_targz_vocab_and_encoding(tmp_path):
    from paddle_tpu.text import Imdb

    path = str(tmp_path / "aclImdb_v1.tar.gz")
    reviews = [
        ("train", "pos", "great great movie"),
        ("train", "neg", "bad movie"),
        ("test", "pos", "great film"),
    ]
    with tarfile.open(path, "w:gz") as tf:
        for i, (split, pol, text) in enumerate(reviews):
            payload = text.encode()
            info = tarfile.TarInfo(f"aclImdb/{split}/{pol}/{i}_7.txt")
            info.size = len(payload)
            tf.addfile(info, io.BytesIO(payload))

    tr = Imdb(data_file=path, mode="train", cutoff=0)
    assert len(tr) == 2
    # vocab from the TRAIN split only; ids consistent across docs
    ids = {t: i for t, i in tr.word_idx.items()}
    assert "great" in ids and "film" not in ids
    doc, label = tr[0] if tr.labels[0] == 1 else tr[1]
    te = Imdb(data_file=path, mode="test", cutoff=0, seq_len=4)
    d0, l0 = te[0]
    assert d0.shape == (4,)  # padded to seq_len
    assert d0[1] == ids["<unk>"]  # 'film' unseen in train


def test_uci_housing_file_split_and_normalization(tmp_path):
    from paddle_tpu.text import UCIHousing

    rs = np.random.RandomState(2)
    rows = np.concatenate(
        [rs.randn(10, 13), rs.uniform(10, 50, (10, 1))], axis=1)
    path = str(tmp_path / "housing.data")
    np.savetxt(path, rows)

    tr = UCIHousing(data_file=path, mode="train")
    te = UCIHousing(data_file=path, mode="test")
    assert len(tr) == 8 and len(te) == 2
    allx = np.concatenate([tr.x, te.x])
    np.testing.assert_allclose(allx.mean(axis=0), 0.0, atol=1e-5)
    x0, y0 = tr[0]
    assert x0.shape == (13,) and y0.shape == (1,)
